"""Scenario runner: apply operations step-by-step, schedule, collect.

KEP-140 semantics (reference keps/140-scenario-based-simulation/README.md):
operations carry a step number; all operations of a step are applied,
then the scheduler runs, then results are recorded.  The engine's program
cache (engine/core.py _Program) keeps re-jits bounded to the distinct
padded-shape buckets the churn wanders through.
"""

from __future__ import annotations

import logging
import time
from dataclasses import dataclass, field
from typing import Any, Iterable, Sequence

from ksim_tpu.obs import TRACE
from ksim_tpu.scheduler.service import SchedulerService
from ksim_tpu.state.cluster import ClusterStore
from ksim_tpu.state.resources import JSON, name_of, namespace_of

logger = logging.getLogger(__name__)


@dataclass(frozen=True)
class Operation:
    """One timed mutation (KEP-140 ScenarioOperation: createOperation /
    patchOperation / deleteOperation at a step)."""

    step: int
    op: str  # create | update | patch | delete | done
    kind: str
    obj: JSON | None = None  # create/update payload; merge patch for "patch"
    name: str = ""  # patch/delete target
    namespace: str = ""


@dataclass
class StepResult:
    step: int
    ops_applied: int
    scheduled: int  # pods bound this step
    unschedulable: int  # scheduling attempts with no feasible node
    pending_after: int


@dataclass
class ScenarioResult:
    """The .status.result analogue: per-step aggregates + totals."""

    steps: list[StepResult] = field(default_factory=list)
    events_applied: int = 0
    pods_scheduled: int = 0
    unschedulable_attempts: int = 0
    wall_seconds: float = 0.0
    succeeded: bool = False  # a doneOperation step completed (KEP-140)
    # Per-phase wall-clock split of wall_seconds, sourced from the trace
    # plane (obs.SPAN_NAMES keys): device path = replay.lower /
    # replay.dispatch / replay.reconcile; per-pass host path =
    # runner.step (which NESTS its service.schedule span — the two are
    # reported side by side, not additive).
    phase_seconds: dict[str, float] = field(default_factory=dict)
    phase_counts: dict[str, int] = field(default_factory=dict)
    # Fleet replay (engine/fleet.py): the per-lane ScenarioResults, in
    # lane order.  The top-level counts/steps are then AGGREGATES over
    # the lanes (events/scheduled/unschedulable summed; ``steps`` stays
    # empty — per-trajectory step records live on the lanes) and the
    # phase split covers the whole fleet run (spans are shared across
    # lanes by design — the group dispatch IS one span).
    lanes: "list[ScenarioResult] | None" = None

    @property
    def events_per_second(self) -> float:
        return self.events_applied / self.wall_seconds if self.wall_seconds else 0.0


class _StreamFeeder:
    """Incremental step-grouper over a streaming operation source
    (traces/stream.py ``TraceOperationStream``): the windowed twin of
    ``ScenarioRunner._group_by_step``.

    ``keys``/``by_step`` grow as windows arrive; a step is COMPLETE (and
    appended to ``keys``) only once a later step's first operation — or
    EOF — proves no more operations belong to it.  Batch lists keep
    their object identity for as long as they are resident: the replay
    driver's speculative-prelower match (engine/replay.py ``_take_spec``)
    is identity-based, so ``by_step[s]`` must return the SAME list every
    iteration.  ``release`` evicts batches the run has committed past —
    that eviction is the O(window) half of the memory claim; the step
    keys themselves (small ints) are kept for cursor arithmetic.

    ``ensure`` BLOCKS on the producer queue; ``prefetch`` never blocks
    and is the replay driver's ingest-hook entry (drain while the
    device dispatch is in flight).  Both run on the consumer (main)
    thread only."""

    def __init__(self, stream) -> None:
        self._stream = stream
        self._it = iter(stream)
        self.keys: list[int] = []  # complete steps, ascending
        self.by_step: dict[int, list[Operation]] = {}
        self._open_step: "int | None" = None
        self._open_batch: "list[Operation] | None" = None
        self._eof = False
        self._released = 0  # keys-index cursor: everything below is evicted

    def _accept(self, op: Operation) -> None:
        if self._open_step is None or op.step > self._open_step:
            if self._open_step is not None:
                self._seal()
            elif self.keys and op.step <= self.keys[-1]:
                raise ValueError(
                    f"streaming operations out of step order: step {op.step} "
                    f"after step {self.keys[-1]} was sealed"
                )
            self._open_step = op.step
            self._open_batch = [op]
        elif op.step == self._open_step:
            self._open_batch.append(op)
        else:
            raise ValueError(
                f"streaming operations out of step order: step {op.step} "
                f"after step {self._open_step}"
            )

    def _seal(self) -> None:
        self.by_step[self._open_step] = self._open_batch
        self.keys.append(self._open_step)
        self._open_step = None
        self._open_batch = None

    def ensure(self, n: int) -> None:
        """Block until ``n`` complete steps exist or the stream ends."""
        while len(self.keys) < n and not self._eof:
            try:
                op = next(self._it)
            except StopIteration:
                self._eof = True
                if self._open_step is not None:
                    self._seal()
                return
            self._accept(op)

    def prefetch(self, n: int) -> int:
        """Drain whatever the producer has READY toward ``n`` complete
        steps; never blocks.  Producer-side errors are deferred: they
        re-raise at the next blocking ``ensure``."""
        pulled = 0
        while len(self.keys) < n and not self._eof:
            op = self._stream.next_nowait()
            if op is None:
                break
            self._accept(op)
            pulled += 1
        return pulled

    def release(self, upto: int) -> None:
        """Evict committed step batches (keys indices below ``upto``)."""
        while self._released < min(upto, len(self.keys)):
            self.by_step.pop(self.keys[self._released], None)
            self._released += 1


class ScenarioRunner:
    """Replays an operation stream against a store + scheduler service.

    ``requeue_on_node_delete`` re-marks a deleted node's bound pods as
    pending (the "node preemption" churn of BASELINE config 5 — a drained
    node's pods go back through scheduling, as a controller would recreate
    them).  ``record`` defaults to "selection": full per-node result
    recording multiplies host-side work by O(N) per pod and is opt-in for
    replay (the per-pass results remain available through the service's
    normal watch-driven path)."""

    def __init__(
        self,
        store: ClusterStore | None = None,
        service: SchedulerService | None = None,
        *,
        record: str = "selection",
        preemption: bool = False,
        requeue_on_node_delete: bool = True,
        max_pods_per_pass: int | None = None,
        pod_bucket_min: int | None = None,
        device_replay: bool = False,
        device_segment_steps: int | None = None,
        fleet: int | None = None,
        fleet_faults: str | None = None,
        cancel: "Any | None" = None,
        private_faults: "Any | None" = None,
        checkpoint_hook: "Any | None" = None,
    ) -> None:
        """``device_replay=True`` routes supported step segments through
        the device-resident path (engine/replay.py): K steps of event
        application + scheduling per compiled dispatch, host reconcile at
        segment boundaries, byte-identical scheduling counts.  Steps
        containing ops outside the tensor vocabulary (patch/update/done,
        non-pod/node kinds, pods with host ports or volumes, ...) fall
        back to this per-pass path automatically; DefaultPreemption
        (``preemption=True``) and ``record="full"`` segments stay
        on-device since round 7 (on-device victim search + streamed
        result tensors).

        ``cancel`` (a ``threading.Event``-like object) makes the run
        cooperatively cancellable — the job plane's DELETE surface: the
        flag is checked before every per-pass step AND inside the
        segment reconcile loop, where a set flag raises
        ``errors.RunCancelled`` INSIDE the store transaction, rolling
        the whole in-flight segment back before propagating (the store
        is byte-identical to the segment's start — a cancelled job
        never leaves a half-applied window behind).

        ``private_faults`` (a ``FaultPlane``) is this run's PRIVATE
        fault plane (the job plane's ``KSIM_JOBS_FAULTS``): checked
        next to the process-global ``FAULTS`` at the replay sites
        (``replay.lower`` / ``replay.dispatch`` / ``replay.reconcile``)
        exactly like a fleet lane's plane, so a chaos schedule degrades
        THIS run alone while concurrent runs in the same process stay
        healthy.  Mutually exclusive with ``fleet`` (use
        ``fleet_faults`` there).

        ``checkpoint_hook`` (the job plane's incremental-resume cadence,
        ksim_tpu/jobs/manager.py) is called as ``hook(cursor, driver,
        result)`` after every COMMITTED device segment — cursor is the
        index into the sorted step keys the next iteration starts from,
        i.e. exactly the ``resume_cursor`` a later ``run`` needs to
        replay only the remaining suffix.  The hook runs outside the
        store transaction (the segment is fully committed; a mid-hook
        crash loses at most the not-yet-journaled checkpoint, never
        store integrity) and must not raise for policy reasons — skip
        internally and return.

        ``fleet=S`` (requires ``device_replay=True``) replays S
        INDEPENDENT trajectories — each with its own store, service and
        replay driver — advancing the whole fleet K steps per vmapped
        device dispatch with the shared universe lowered once per window
        (engine/fleet.py).  ``run`` then returns the aggregate result
        with the per-lane results on ``.lanes``; per-lane chaos arms via
        ``fleet_faults`` / ``KSIM_FLEET_FAULTS`` (``lane:site=schedule``
        entries), per-lane streams via ``run(..., lane_ops=...)``.  Lane
        0 reuses this runner's own store/service, so existing evidence
        surfaces (``.store``, ``.replay_driver``) stay meaningful."""
        if fleet is not None:
            if fleet < 2:
                raise ValueError("fleet needs at least 2 lanes")
            if not device_replay:
                raise ValueError("fleet replay requires device_replay=True")
            if store is not None or service is not None:
                raise ValueError(
                    "fleet lanes build their own stores/services; pass the "
                    "service CONFIG (record/preemption/...) instead"
                )
        elif fleet_faults is not None:
            # A lane fault spec with no fleet would be silently dropped —
            # the vacuously-green chaos sweep parse_fleet_faults refuses.
            raise ValueError("fleet_faults requires fleet=S")
        if fleet is not None and private_faults is not None:
            raise ValueError(
                "private_faults is the solo-run chaos surface; fleet lanes "
                "arm per-lane planes via fleet_faults/KSIM_FLEET_FAULTS"
            )
        self.store = store if store is not None else ClusterStore()
        self.service = (
            service
            if service is not None
            else SchedulerService(
                self.store,
                record=record,
                preemption=preemption,
                max_pods_per_pass=max_pods_per_pass,
                pod_bucket_min=pod_bucket_min,
            )
        )
        self._requeue = requeue_on_node_delete
        self._drained_nodes: set[str] = set()
        self._device_replay = device_replay
        self._device_segment_steps = device_segment_steps
        self._fleet = fleet
        self._fleet_faults = fleet_faults
        # Per-lane service construction config (fleet lanes must match
        # lane 0's scheduling semantics exactly).
        self._lane_cfg = dict(
            record=record,
            preemption=preemption,
            max_pods_per_pass=max_pods_per_pass,
            pod_bucket_min=pod_bucket_min,
        )
        # Fleet-lane identity: set on per-lane runners so the reconcile
        # and per-pass spans (and the lane's private fault plane) stay
        # attributable per trajectory.
        self._lane: int | None = None
        # One private-plane slot serves both chaos surfaces: fleet lanes
        # (set per lane in _run_fleet) and solo job runs (private_faults
        # here) — the reconcile/driver checks are identical.
        self._lane_faults = private_faults
        # Cooperative cancellation flag (Event-like; see __init__ doc).
        self._cancel = cancel
        # Post-commit segment callback (job-plane checkpoints; see
        # __init__ doc).  None for fleet lanes — cohort segments commit
        # lane-by-lane and a per-lane cursor is not a resume point.
        self._checkpoint_hook = checkpoint_hook
        # The last run's ReplayDriver (evidence counters: device_steps,
        # fallback_steps, device_round_trips, unsupported reasons).
        self.replay_driver = None
        # Fleet evidence (set by a fleet run): the FleetDriver (stats())
        # and the FleetLane list (per-lane runners/drivers/results).
        self.fleet_driver = None
        self.fleet_lanes = None

    def _check_cancelled(self) -> None:
        """Raise ``RunCancelled`` if the run's cancel flag is set.
        Called between per-pass steps and inside the segment reconcile
        loop — the latter aborts (and rolls back) the in-flight store
        transaction, so cancellation is never store-corrupting."""
        if self._cancel is not None and self._cancel.is_set():
            from ksim_tpu.errors import RunCancelled

            raise RunCancelled("scenario run cancelled")

    # -- one operation ------------------------------------------------------

    @staticmethod
    def _own(obj: JSON) -> JSON:
        """Hand an operation's object to the store without a deepcopy
        (two per create were ~11% of the 50k churn replay).  Only the
        top level and metadata are copied: the store writes rv/uid/
        namespace into metadata, and a same-ops-list replay must not see
        the previous run's values.  Nested structures are shared — safe
        under the store's replace-on-write contract (nothing mutates
        them in place)."""
        out = dict(obj)
        md = out.get("metadata")
        out["metadata"] = dict(md) if isinstance(md, dict) else {}
        return out

    def _apply(self, op: Operation) -> None:
        if op.op == "create":
            self.store.create(op.kind, self._own(op.obj), copy_obj=False)
        elif op.op == "update":
            self.store.update(op.kind, self._own(op.obj), copy_obj=False)
        elif op.op == "patch":
            # KEP-140 PatchOperation: RFC 7386 merge patch (scenario/spec.py).
            # Object identity is immutable under patch, like the apiserver:
            # name/namespace/uid survive whatever the patch does to
            # metadata (a patch can't rename or unkey an object).
            from ksim_tpu.scenario.spec import ScenarioSpecError, merge_patch

            def apply_merge(obj: JSON) -> None:
                merged = merge_patch(obj, op.obj)
                if not isinstance(merged, dict):
                    raise ScenarioSpecError(
                        f"patch for {op.kind}/{op.name} must produce an object"
                    )
                orig_md = obj.get("metadata", {})
                md = merged.get("metadata")
                md = dict(md) if isinstance(md, dict) else {}
                for key in ("name", "namespace", "uid", "resourceVersion"):
                    if orig_md.get(key) is not None:
                        md[key] = orig_md[key]
                merged["metadata"] = md
                obj.clear()
                obj.update(merged)

            self.store.patch(
                op.kind, op.name, op.namespace, apply_merge, copy_ret=False
            )
        elif op.op == "delete":
            if op.kind == "nodes" and self._requeue:
                # Deferred: run() re-queues all drained nodes' pods in ONE
                # pod walk after the step's ops (walking the whole pod
                # list per node delete dominated churn host time).
                self._drained_nodes.add(op.name)
            self.store.delete(op.kind, op.name, op.namespace)
        elif op.op == "done":
            pass  # handled in run(): terminates after this step
        else:
            raise ValueError(f"unknown op {op.op!r}")

    def _requeue_pods_of(self, node_names: set[str]) -> None:
        if not node_names:
            return

        def clear(obj: JSON) -> None:
            obj["spec"].pop("nodeName", None)
            obj.get("status", {}).pop("phase", None)

        # The store's nodeName bucket index bounds the walk to pods ON
        # the drained nodes (the earlier bound-side walk still scanned
        # every bound pod per drain — ~10s of the 50k replay); the
        # matches sort by (name, "ns/name") — exactly list("pods")'s
        # (name, key) order — so patches apply (and consume
        # resourceVersions) in the same order the full walk produced.
        hit = [
            (name_of(p), f"{namespace_of(p) or 'default'}/{name_of(p)}", namespace_of(p))
            for p in self.store.pods_on_nodes(node_names)
        ]
        for name, _key, ns in sorted(hit):
            self.store.patch("pods", name, ns, clear, copy_ret=False)

    # -- replay -------------------------------------------------------------

    def _apply_batch(self, batch: Sequence[Operation]) -> bool:
        """Apply one step's operations to the store (+ deferred requeue).
        Returns whether the step carried a doneOperation."""
        done = False
        self._drained_nodes = set()
        for op in batch:
            self._apply(op)
            done = done or op.op == "done"
        self._requeue_pods_of(self._drained_nodes)
        return done

    def _run_step(self, step: int, batch: list[Operation], result: ScenarioResult) -> bool:
        """The per-pass step body: apply ops, flush, one scheduling pass.
        Returns the done flag."""
        tags = {} if self._lane is None else {"lane": self._lane}
        with TRACE.span("runner.step", step=step, ops=len(batch), **tags):
            return self._run_step_traced(step, batch, result)

    def _run_step_traced(
        self, step: int, batch: list[Operation], result: ScenarioResult
    ) -> bool:
        done = self._apply_batch(batch)
        result.events_applied += len(batch)
        # The runner drives the store directly (no watch loop), so it
        # raises the capacity-freed/topology-changed signal itself:
        # node ops and pod deletions flush the unschedulable backoff.
        if any(
            op.kind in ("nodes", "persistentvolumes",
                        "persistentvolumeclaims", "storageclasses")
            or (op.op == "delete" and op.kind == "pods")
            for op in batch
        ):
            self.service.flush_backoff()
        placements = self.service.schedule_pending()
        scheduled = sum(1 for v in placements.values() if v is not None)
        unsched = len(placements) - scheduled
        result.pods_scheduled += scheduled
        result.unschedulable_attempts += unsched
        result.steps.append(
            StepResult(
                step=step,
                ops_applied=len(batch),
                scheduled=scheduled,
                unschedulable=unsched,
                pending_after=self.service.pending_count(),
            )
        )
        return done

    def _stage_device_step(
        self,
        batch: list[Operation],
        outcome,
        eviction_sink: list[tuple[str, str]],
    ) -> None:
        """Stage one device-computed step's STORE writes: the step's ops
        (+ requeue), then the pass's placements in commit order.  With
        per-attempt detail (preemption / record="full" segments) each
        attempt's write mirrors the per-pass rebuild — result
        annotations, bind or nomination — followed by its preemption
        victims' evictions, in the exact per-pass order.  Runs inside
        the segment transaction: store-only, no service/result effects
        (victim eviction listeners defer into ``eviction_sink`` and fire
        after commit)."""
        self._apply_batch(batch)
        if outcome.attempts is not None:
            from ksim_tpu.engine.annotations import apply_results_to_pod

            for att in outcome.attempts:
                if att.anno or att.node or att.nominated:

                    def mutate(obj: JSON, att=att) -> None:
                        if att.anno:
                            annos = obj.setdefault("metadata", {}).setdefault(
                                "annotations", {}
                            )
                            apply_results_to_pod(annos, att.anno)
                        if att.node:
                            obj.setdefault("spec", {})["nodeName"] = att.node
                            obj.setdefault("status", {})["phase"] = "Running"
                            obj.get("status", {}).pop("nominatedNodeName", None)
                        elif att.nominated:
                            obj.setdefault("status", {})[
                                "nominatedNodeName"
                            ] = att.nominated

                    self.store.patch(
                        "pods", att.name, att.namespace, mutate, copy_ret=False
                    )
                # Victim evictions go through the service so delete
                # semantics match the per-pass path; listener callbacks
                # defer to post-commit (a rolled-back segment must never
                # have announced an eviction that did not happen).
                for vns, vname in att.victims:
                    self.service._evict_victim(
                        {"metadata": {"name": vname, "namespace": vns}},
                        listener_sink=eviction_sink,
                    )
        else:
            for ns, name, node in outcome.binds:

                def bind(obj: JSON) -> None:
                    obj.setdefault("spec", {})["nodeName"] = node
                    obj.setdefault("status", {})["phase"] = "Running"
                    obj.get("status", {}).pop("nominatedNodeName", None)

                self.store.patch("pods", name, ns, bind, copy_ret=False)

    def _record_device_step(
        self, step: int, batch: list[Operation], outcome, result: ScenarioResult
    ) -> None:
        """Post-commit result accounting for one device step."""
        result.events_applied += len(batch)
        result.pods_scheduled += outcome.scheduled
        result.unschedulable_attempts += outcome.unschedulable
        result.steps.append(
            StepResult(
                step=step,
                ops_applied=len(batch),
                scheduled=outcome.scheduled,
                unschedulable=outcome.unschedulable,
                pending_after=outcome.pending_after,
            )
        )

    def _commit_segment(
        self, seg_keys, batches, seg, driver, result: ScenarioResult
    ) -> bool:
        """Reconcile one device-computed segment ALL-OR-NOTHING.

        Every store write of the segment — event ops, requeue patches,
        bind/nomination/annotation patches, victim evictions — stages
        inside one store transaction, the device-vs-store parity check
        runs against the staged state, and only then does the batch
        commit (watch events deliver at commit, in write order).  The
        service-side effects with no rollback story — eviction
        listeners, featurizer slot advances, backoff/pass-count sync,
        result accumulation — run strictly AFTER the commit.

        An INJECTED fault mid-reconcile (the fault plane's
        InjectedFault) rolls the whole segment back and returns False:
        the store is byte-identical to the segment's start and the
        caller proceeds exactly as if the segment had never lowered —
        the window's head step runs per-pass, the remaining steps are
        retried on-device in the next window.  Consecutive rollbacks
        feed the driver's circuit breaker, so a persistently failing
        reconcile stops paying lowering + dispatch + rollback per step.
        Everything else — ReplayParityError, store-integrity errors
        (NotFound/Conflict are device-decode bugs wearing a
        SimulatorError class), programming errors — still propagates
        LOUDLY, but now with the store rolled back rather than
        half-applied: a kernel bug must never be indistinguishable
        from an injected chaos fault."""
        from ksim_tpu.faults import FAULTS, InjectedFault

        evictions: list[tuple[str, str]] = []
        step_nodes: list = []
        tags = {} if self._lane is None else {"lane": self._lane}
        try:
            with TRACE.span(
                "replay.reconcile",
                segment=driver._segment_seq,
                steps=len(seg.steps),
                **tags,
            ), self.store.transaction(epoch_exempt=True):
                # epoch_exempt: the segment's own staged writes are the
                # deltas the driver's lower-cache already tracks; only
                # OUT-OF-BAND writes may move the store mutation epoch
                # (and thereby invalidate the cache).  A rollback takes
                # the explicit invalidation path (note_reconcile_fault).
                for batch, outcome in zip(batches, seg.steps):
                    # A cancel landing mid-segment aborts HERE: the
                    # RunCancelled is not an InjectedFault, so it rolls
                    # the transaction back and propagates to the caller
                    # (the job plane marks the job cancelled; the store
                    # is back at the segment's start state).
                    self._check_cancelled()
                    FAULTS.check("replay.reconcile")
                    if self._lane_faults is not None:
                        # The lane's PRIVATE plane (fleet chaos): an
                        # injected fault here rolls back ONLY this
                        # lane's segment.
                        self._lane_faults.check("replay.reconcile")
                    self._stage_device_step(batch, outcome, evictions)
                    # Captured per step for the deferred slot advance:
                    # live node dicts are frozen (replace-on-write), so
                    # the references stay valid after commit.
                    step_nodes.append(
                        self.store.list("nodes", copy_objs=False)
                        if outcome.eligible > 0
                        else None
                    )
                driver.verify_segment(seg)
        except InjectedFault as e:
            driver.note_reconcile_fault()
            logger.warning(
                "device segment reconcile aborted (%s: %s); store rolled "
                "back — the window's head step re-runs per-pass, the rest "
                "retries on-device",
                type(e).__name__, e,
            )
            return False
        self.service._notify_evictions(evictions)
        driver.advance_service_slots(step_nodes)
        driver.sync_service(seg)
        driver.device_steps += len(seg.steps)
        for step, batch, outcome in zip(seg_keys, batches, seg.steps):
            self._record_device_step(step, batch, outcome, result)
        return True

    def run(
        self,
        ops: Iterable[Operation],
        *,
        lane_ops: "dict[int, Iterable[Operation]] | None" = None,
        resume_cursor: int = 0,
        resume_result: "ScenarioResult | None" = None,
    ) -> ScenarioResult:
        """Apply operations grouped by step; one scheduling pass per step
        (every pending pod is attempted each pass, like the upstream
        queue's flush on cluster events).  With ``device_replay`` on,
        supported K-step segments run as single device dispatches (see
        engine/replay.py); everything else takes this per-pass loop.

        ``resume_cursor``/``resume_result`` are the incremental-resume
        entry (docs/jobs.md): replay starts at sorted-step-key index
        ``resume_cursor`` — the cursor a ``checkpoint_hook`` reported —
        accumulating into ``resume_result`` (the checkpoint's partial
        accounting) instead of a fresh result.  The caller owns restoring
        the matching store/service state first; given that, the suffix
        replay is byte-identical to the uninterrupted run's tail (the
        restored store carries the exact rv counter and mutation epoch,
        the service its pass/backoff/slot-order carries).
        ``wall_seconds`` covers only THIS process's replay.

        With ``fleet=S`` the stream replays on every lane (``lane_ops``
        overrides individual lanes' streams — those lanes run the solo
        device path, outside the shared-universe cohort) and the result
        carries the per-lane results on ``.lanes``.

        A STREAMING source (``ops.streaming_ops`` — traces/stream.py)
        takes the windowed loop: operations are consumed as the
        producer emits them, never materialized whole, with ingest
        overlapping the in-flight device dispatch.  Streaming is the
        solo fresh-run path: fleet replays and incremental resume both
        need the full sorted step-key index up front."""
        if getattr(ops, "streaming_ops", False):
            if self._fleet is not None or lane_ops:
                raise ValueError(
                    "streaming ingest is the solo-run path (fleet replay "
                    "materializes its lanes)"
                )
            if resume_cursor or resume_result is not None:
                raise ValueError(
                    "incremental resume needs materialized operations "
                    "(a resume cursor indexes the full sorted step-key list)"
                )
            if self._checkpoint_hook is not None:
                raise ValueError(
                    "checkpoint_hook needs materialized operations (its "
                    "cursor must stay valid for a later resume)"
                )
            return self._run_streaming(ops)
        if self._fleet is not None:
            if resume_cursor or resume_result is not None:
                raise ValueError(
                    "incremental resume is the solo-run path; fleet runs "
                    "restart from scratch (no per-lane cursor yet)"
                )
            return self._run_fleet(ops, lane_ops)
        if lane_ops:
            raise ValueError("lane_ops requires fleet=S")
        result = resume_result if resume_result is not None else ScenarioResult()
        # Per-phase wall-clock split rides on the trace plane's latency
        # histograms; timing-only mode costs two clock reads per span at
        # segment/pass granularity and never touches scheduling state
        # (the behavior locks hold with it on — tests pin that).
        TRACE.ensure_timing()
        phase0 = TRACE.phase_totals()
        t0 = time.perf_counter()
        by_step, keys = self._group_by_step(ops)
        driver = None
        if self._device_replay:
            from ksim_tpu.engine.replay import SEGMENT_STEPS, ReplayDriver

            driver = ReplayDriver(
                self.store,
                self.service,
                k=self._device_segment_steps or SEGMENT_STEPS,
                requeue_on_node_delete=self._requeue,
                lane_faults=self._lane_faults,
            )
            self.replay_driver = driver
        i = resume_cursor
        while i < len(keys):
            self._check_cancelled()
            if driver is not None:
                # Tails shorter than K no longer fall back: the driver
                # consumes the supported PREFIX of the window (possibly
                # shorter than K for full-record segments or mid-window
                # vocabulary misses) and pads on-device to the compiled
                # shape.  Two windows' worth of batches ride along as
                # LOOKAHEAD: while this window's dispatch runs on the
                # watchdogged worker, the driver pre-lowers the next
                # window's store-independent prefix on this thread (the
                # double-buffered pipeline, engine/replay.py
                # _prelower_next).  The inner batch lists are the same
                # objects every iteration (by_step), so the speculative
                # prefix can be matched to the window that actually runs
                # next by identity alone.
                batches = [by_step[s] for s in keys[i : i + 2 * driver.k]]
                seg = driver.try_segment(batches)
                if seg is not None and self._commit_segment(
                    keys[i : i + len(seg.steps)],
                    batches[: len(seg.steps)],
                    seg,
                    driver,
                    result,
                ):
                    i += len(seg.steps)
                    if self._checkpoint_hook is not None:
                        self._checkpoint_hook(i, driver, result)
                    continue
            step = keys[i]
            if driver is not None:
                driver.fallback_steps += 1
            done = self._run_step(step, by_step[step], result)
            i += 1
            if done:
                # KEP-140 DoneOperation: "when finish the step
                # DoneOperation belongs, this Scenario changes its status
                # to Succeeded" — later steps are not run.
                result.succeeded = True
                break
        result.wall_seconds = time.perf_counter() - t0
        # The trace plane is process-global: diff its totals around this
        # run so concurrent earlier runs don't bleed into the split.
        for name, (total, count) in TRACE.phase_totals().items():
            prev_total, prev_count = phase0.get(name, (0.0, 0))
            if count > prev_count:
                result.phase_seconds[name] = round(total - prev_total, 6)
                result.phase_counts[name] = count - prev_count
        return result

    def _run_streaming(self, stream) -> ScenarioResult:
        """The windowed twin of ``run``'s solo loop: a ``_StreamFeeder``
        stands in for the materialized ``by_step``/``keys`` view, the
        replay driver's ``ingest_hook`` drains ready windows while each
        dispatch is in flight (ingest ∥ prelower ∥ dispatch), and
        committed step batches are evicted as the cursor advances —
        peak host memory is O(window + lookahead), not O(stream).  The
        schedule itself is byte-identical to the materialized run: the
        feeder groups the same operations into the same step batches,
        only their lifetime in memory changes."""
        result = ScenarioResult()
        TRACE.ensure_timing()
        phase0 = TRACE.phase_totals()
        t0 = time.perf_counter()
        feeder = _StreamFeeder(stream)
        driver = None
        try:
            if self._device_replay:
                from ksim_tpu.engine.replay import SEGMENT_STEPS, ReplayDriver

                # The hook's prefetch target is re-aimed every iteration:
                # 4·k steps past the cursor bounds the opportunistic
                # drain, so overlap never turns back into O(stream)
                # buffering on the consumer side.
                target = [0]
                driver = ReplayDriver(
                    self.store,
                    self.service,
                    k=self._device_segment_steps or SEGMENT_STEPS,
                    requeue_on_node_delete=self._requeue,
                    lane_faults=self._lane_faults,
                    ingest_hook=lambda: feeder.prefetch(target[0]),
                )
                self.replay_driver = driver
            i = 0
            while True:
                self._check_cancelled()
                if driver is not None:
                    # The same 2-window lookahead the materialized loop
                    # slices out of ``keys`` — blocking here is the
                    # backpressure point when replay outruns ingest.
                    feeder.ensure(i + 2 * driver.k)
                    target[0] = i + 4 * driver.k
                else:
                    feeder.ensure(i + 1)
                if i >= len(feeder.keys):
                    break
                if driver is not None:
                    batches = [
                        feeder.by_step[s]
                        for s in feeder.keys[i : i + 2 * driver.k]
                    ]
                    seg = driver.try_segment(batches)
                    if seg is not None and self._commit_segment(
                        feeder.keys[i : i + len(seg.steps)],
                        batches[: len(seg.steps)],
                        seg,
                        driver,
                        result,
                    ):
                        i += len(seg.steps)
                        feeder.release(i)
                        continue
                step = feeder.keys[i]
                if driver is not None:
                    driver.fallback_steps += 1
                done = self._run_step(step, feeder.by_step[step], result)
                i += 1
                feeder.release(i)
                if done:
                    result.succeeded = True
                    break
        finally:
            # An abandoned producer blocked on a full queue would leak;
            # close() is idempotent and also covers clean exhaustion.
            stream.close()
        result.wall_seconds = time.perf_counter() - t0
        for name, (total, count) in TRACE.phase_totals().items():
            prev_total, prev_count = phase0.get(name, (0.0, 0))
            if count > prev_count:
                result.phase_seconds[name] = round(total - prev_total, 6)
                result.phase_counts[name] = count - prev_count
        return result

    @staticmethod
    def _group_by_step(ops: Iterable[Operation]) -> tuple[dict, list]:
        by_step: dict[int, list[Operation]] = {}
        for op in ops:
            by_step.setdefault(op.step, []).append(op)
        return by_step, sorted(by_step)

    def _run_fleet(self, ops, lane_ops) -> ScenarioResult:
        """Fleet replay (engine/fleet.py): S independent trajectories,
        the shared universe lowered once per window, one vmapped
        dispatch per cohort window, per-lane reconcile into each lane's
        own store.  Parity contract: every lane's counts/annotations
        are byte-identical to its solo ``device_replay=True`` run."""
        import os

        # The submission-boundary check catches a cancel that landed
        # before the fleet ever built; mid-run cancels thread through to
        # every lane runner below, so a DELETE lands at the next lane
        # dispatch/reconcile boundary (service round 4 (d)) — the
        # in-flight lane segment rolls back exactly like the solo path.
        self._check_cancelled()
        from ksim_tpu.engine.fleet import FleetDriver, FleetLane, parse_fleet_faults
        from ksim_tpu.engine.replay import SEGMENT_STEPS, ReplayDriver

        n = self._fleet
        if lane_ops:
            # Same refusal parse_fleet_faults makes for out-of-range
            # lanes: a typoed index would silently replay the BASE
            # stream on every lane and the sweep would be vacuous.
            bad = sorted(k for k in lane_ops if not 0 <= k < n)
            if bad:
                raise ValueError(
                    f"lane_ops lanes {bad} outside the fleet (0..{n - 1})"
                )
            if any(getattr(v, "streaming_ops", False) for v in lane_ops.values()):
                raise ValueError(
                    "streaming ingest is the solo-run path (lane_ops streams "
                    "must be materialized)"
                )
        spec = self._fleet_faults
        if spec is None:
            spec = os.environ.get("KSIM_FLEET_FAULTS", "")
        planes = parse_fleet_faults(spec, n) if spec else {}
        base_by_step, base_keys = self._group_by_step(ops)
        lanes: list[FleetLane] = []
        for idx in range(n):
            if idx == 0:
                lane_runner = ScenarioRunner(
                    store=self.store,
                    service=self.service,
                    requeue_on_node_delete=self._requeue,
                    device_replay=True,
                    device_segment_steps=self._device_segment_steps,
                    cancel=self._cancel,
                )
            else:
                lane_runner = ScenarioRunner(
                    requeue_on_node_delete=self._requeue,
                    device_replay=True,
                    device_segment_steps=self._device_segment_steps,
                    cancel=self._cancel,
                    **self._lane_cfg,
                )
            lane_runner._lane = idx
            lane_runner._lane_faults = planes.get(idx)
            lane_runner.service._trace_lane = idx
            own = lane_ops.get(idx) if lane_ops else None
            if own is not None:
                # A per-lane stream: this trajectory is divergent from
                # the start and rides the solo device path.
                by_step, keys = self._group_by_step(own)
                shared = False
            else:
                # Cohort lanes share the base dict — the SAME batch list
                # objects, which is what lets the leader's speculative
                # prelower spec match by identity for every lane.
                by_step, keys = base_by_step, base_keys
                shared = True
            driver = ReplayDriver(
                lane_runner.store,
                lane_runner.service,
                k=self._device_segment_steps or SEGMENT_STEPS,
                requeue_on_node_delete=self._requeue,
                lane=idx,
                lane_faults=planes.get(idx),
            )
            lane_runner.replay_driver = driver
            lanes.append(
                FleetLane(
                    idx=idx,
                    runner=lane_runner,
                    driver=driver,
                    keys=keys,
                    by_step=by_step,
                    result=ScenarioResult(),
                    faults=planes.get(idx),
                    shared_stream=shared,
                    convergent=shared,
                )
            )
        fleet = FleetDriver(lanes)
        self.fleet_driver = fleet
        self.fleet_lanes = lanes
        self.replay_driver = lanes[0].driver
        TRACE.ensure_timing()
        phase0 = TRACE.phase_totals()
        t0 = time.perf_counter()
        fleet.run()
        wall = time.perf_counter() - t0
        agg = ScenarioResult(lanes=[ln.result for ln in lanes])
        for ln in lanes:
            ln.result.wall_seconds = wall  # fleet lanes finish together
            agg.events_applied += ln.result.events_applied
            agg.pods_scheduled += ln.result.pods_scheduled
            agg.unschedulable_attempts += ln.result.unschedulable_attempts
        # Solo semantics per lane: succeeded = a doneOperation completed.
        agg.succeeded = all(ln.result.succeeded for ln in lanes)
        agg.wall_seconds = wall
        for name, (total, count) in TRACE.phase_totals().items():
            prev_total, prev_count = phase0.get(name, (0.0, 0))
            if count > prev_count:
                agg.phase_seconds[name] = round(total - prev_total, 6)
                agg.phase_counts[name] = count - prev_count
        return agg
