"""KEP-184 SchedulerSimulation: one-shot scenario runs as documents.

The reference designed (never built) a ``SchedulerSimulation`` CRD whose
controller boots a simulator, runs a Scenario from a mounted file, and
stores the result to a file (reference
keps/184-scheduler-simulation/README.md: SimulatorSpec +
ScenarioTemplateFilePath + ScenarioResultFilePath).  The TPU-native form
is a document -> function call: build the in-memory simulator (store +
scheduler service from the spec's scheduler config and initial
snapshot), replay the referenced Scenario document
(scenario/spec.py), and return/persist the ``status``-shaped result.

Document shape (YAML or JSON)::

    kind: SchedulerSimulation
    spec:
      simulator:                  # SimulatorSpec analogue
        schedulerConfig: {...}    # KubeSchedulerConfiguration (optional)
        initialSnapshotPath: p    # ResourcesForSnap JSON (optional)
        recordMode: selection     # full | final | selection (optional)
      scenarioTemplateFilePath: scenario.yaml   # or inline `scenario:`
      scenarioResultFilePath: out.json          # optional

CLI: ``python -m ksim_tpu.cmd.simulation sim.yaml``.
"""

from __future__ import annotations

import json
from typing import Any

from ksim_tpu.errors import RunCancelled
from ksim_tpu.scenario.runner import ScenarioResult, ScenarioRunner
from ksim_tpu.scenario.spec import ScenarioSpecError, load_scenario
from ksim_tpu.scheduler.service import SchedulerService
from ksim_tpu.state.cluster import ClusterStore
from ksim_tpu.state.resources import JSON as JSONObj


def _result_status(res: ScenarioResult) -> JSONObj:
    """ScenarioResult -> the .status.result analogue (KEP-140 collects
    per-step aggregates in Scenario.status)."""
    return {
        # A replay that ran to completion succeeded (the except branch
        # carries every real failure, with a message); KEP-140's
        # doneOperation marker is surfaced separately.
        "phase": "Succeeded",
        "done": res.succeeded,
        "result": {
            "eventsApplied": res.events_applied,
            "podsScheduled": res.pods_scheduled,
            "unschedulableAttempts": res.unschedulable_attempts,
            "wallSeconds": round(res.wall_seconds, 3),
            "steps": [
                {
                    "step": s.step,
                    "opsApplied": s.ops_applied,
                    "scheduled": s.scheduled,
                    "unschedulable": s.unschedulable,
                    "pendingAfter": s.pending_after,
                }
                for s in res.steps
            ],
        },
    }


def run_scheduler_simulation(doc: "JSONObj | str | bytes") -> JSONObj:
    """Run one SchedulerSimulation document; returns the document with
    ``status`` filled in (and writes ``scenarioResultFilePath`` if set).

    The simulator spec is operator-owned (the KEP mounts it into the
    simulator Pod), so its scheduler config may use plugin imports."""
    if isinstance(doc, (str, bytes)):
        import yaml

        doc = yaml.safe_load(doc)
    if not isinstance(doc, dict):
        raise ScenarioSpecError("SchedulerSimulation document must be a mapping")
    spec = doc.get("spec") or {}
    sim_spec = spec.get("simulator") or {}

    store = ClusterStore()
    if sim_spec.get("initialSnapshotPath"):
        from ksim_tpu.state.snapshot import SnapshotService

        with open(sim_spec["initialSnapshotPath"]) as f:
            SnapshotService(store).load(json.load(f))
    service = SchedulerService(
        store,
        config=sim_spec.get("schedulerConfig"),
        record=sim_spec.get("recordMode", "selection"),
        preemption=bool(sim_spec.get("preemption", False)),
        max_pods_per_pass=sim_spec.get("maxPodsPerPass"),
        allow_plugin_imports=True,  # operator-owned spec (see docstring)
    )

    scenario: Any = spec.get("scenario")
    path = spec.get("scenarioTemplateFilePath")
    if scenario is None and path:
        with open(path) as f:
            scenario = f.read()
    if scenario is None:
        raise ScenarioSpecError(
            "spec needs scenario (inline) or scenarioTemplateFilePath"
        )
    ops = load_scenario(scenario)

    runner = ScenarioRunner(store=store, service=service)
    try:
        res = runner.run(ops)
        status = _result_status(res)
    except RunCancelled:
        # Cancellation is not a Failed phase: it must reach the job
        # worker, which owns the cancelled-state transition.
        raise
    except Exception as e:  # the KEP's Failed phase with a message
        status = {"phase": "Failed", "message": f"{type(e).__name__}: {e}"}

    out = dict(doc, status=status)
    result_path = spec.get("scenarioResultFilePath")
    if result_path:
        tmp = f"{result_path}.tmp"
        with open(tmp, "w") as f:
            json.dump(out, f, indent=1)
        import os

        os.replace(tmp, result_path)
    return out
