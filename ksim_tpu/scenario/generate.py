"""Churn-scenario generator: BASELINE config 5.

"50k scheduling events, rolling arrivals + node preemption over 2k
nodes" — a reproducible operation stream: initial node fleet, then steps
mixing pod arrivals (with the same taint/affinity/spread feature mix as
tests.helpers.random_cluster), pod completions, and rolling node
drain/replace (delete + create, the "node preemption" churn).
"""

from __future__ import annotations

import random
from typing import Iterator

from ksim_tpu.scenario.runner import Operation
from ksim_tpu.state.resources import JSON


def _mk_node(rng: random.Random, name: str) -> JSON:
    from tests.helpers import make_node

    zones = ["zone-a", "zone-b", "zone-c"]
    return make_node(
        name,
        cpu=f"{rng.choice([4, 8, 16, 32])}",
        memory=f"{rng.choice([8, 16, 32, 64])}Gi",
        pods=rng.choice([32, 64, 110]),
        labels={
            "topology.kubernetes.io/zone": rng.choice(zones),
            "kubernetes.io/hostname": name,
            "disktype": rng.choice(["ssd", "hdd"]),
        },
    )


def _mk_pod(rng: random.Random, name: str) -> JSON:
    from tests.helpers import make_pod

    app = rng.choice(["web", "db", "cache", "batch"])
    spread = None
    if rng.random() < 0.2:
        spread = [{
            "maxSkew": rng.choice([1, 2]),
            "topologyKey": rng.choice(
                ["topology.kubernetes.io/zone", "kubernetes.io/hostname"]
            ),
            "whenUnsatisfiable": rng.choice(["DoNotSchedule", "ScheduleAnyway"]),
            "labelSelector": {"matchLabels": {"app": app}},
        }]
    affinity = None
    if rng.random() < 0.1:
        term = {
            "labelSelector": {"matchLabels": {"app": rng.choice(["web", "db"])}},
            "topologyKey": "topology.kubernetes.io/zone",
        }
        if rng.random() < 0.5:
            affinity = {"podAntiAffinity": {
                "requiredDuringSchedulingIgnoredDuringExecution": [term]}}
        else:
            affinity = {"podAffinity": {
                "preferredDuringSchedulingIgnoredDuringExecution": [
                    {"weight": rng.choice([1, 50, 100]), "podAffinityTerm": term}
                ]}}
    return make_pod(
        name,
        cpu=rng.choice(["100m", "250m", "500m", "1", "2"]),
        memory=rng.choice(["128Mi", "512Mi", "1Gi", "2Gi"]),
        labels={"app": app},
        topology_spread_constraints=spread,
        affinity=affinity,
    )


def churn_scenario(
    seed: int,
    *,
    n_nodes: int = 2000,
    n_events: int = 50_000,
    ops_per_step: int = 100,
    pod_create_frac: float = 0.65,
    pod_delete_frac: float = 0.25,
) -> Iterator[Operation]:
    """Yield the node bootstrap (step 0) then churn steps.  Event mix:
    ``pod_create_frac`` arrivals, ``pod_delete_frac`` completions of
    previously-bound pods, remainder node drain/replace pairs."""
    rng = random.Random(seed)
    pod_seq = 0
    node_seq = n_nodes
    live_pods: list[str] = []
    live_nodes = [f"node-{i}" for i in range(n_nodes)]

    for name in live_nodes:
        yield Operation(step=0, op="create", kind="nodes", obj=_mk_node(rng, name))

    emitted = n_nodes
    step = 1
    while emitted < n_events:
        budget = min(ops_per_step, n_events - emitted)
        for _ in range(budget):
            r = rng.random()
            if r < pod_create_frac or not live_pods:
                name = f"pod-{pod_seq}"
                pod_seq += 1
                live_pods.append(name)
                yield Operation(
                    step=step, op="create", kind="pods", obj=_mk_pod(rng, name)
                )
            elif r < pod_create_frac + pod_delete_frac:
                victim = live_pods.pop(rng.randrange(len(live_pods)))
                yield Operation(
                    step=step, op="delete", kind="pods",
                    name=victim, namespace="default",
                )
            else:
                # Rolling node preemption: drain one node, add a fresh one.
                gone = live_nodes.pop(rng.randrange(len(live_nodes)))
                yield Operation(step=step, op="delete", kind="nodes", name=gone)
                fresh = f"node-{node_seq}"
                node_seq += 1
                live_nodes.append(fresh)
                yield Operation(
                    step=step, op="create", kind="nodes", obj=_mk_node(rng, fresh)
                )
        emitted += budget
        step += 1
