"""KEP-140 Scenario documents -> runner operations.

The reference designed (but never built) a Scenario CRD whose
``spec.operations`` drive timed create/patch/delete mutations with a
``doneOperation`` terminator (reference
keps/140-scenario-based-simulation/README.md, ScenarioOperation /
CreateOperation / PatchOperation / DeleteOperation / DoneOperation).
This module accepts that document shape — as a dict, JSON, or YAML —
and lowers it to the library ``Operation`` stream:

- ``createOperation.object``  -> Operation(op="create"), kind from the
  object's ``kind``;
- ``patchOperation``          -> Operation(op="patch") carrying an
  RFC 7386 JSON merge patch (the KEP leaves PatchType open; merge patch
  is the simulator-native choice — strategic merge is an apiserver
  concept);
- ``deleteOperation``         -> Operation(op="delete");
- ``doneOperation``           -> Operation(op="done") — the runner marks
  the scenario succeeded after finishing that step and ignores later
  steps.

Exactly one of the four must be set per operation, like the KEP's
"one of the following four fields must be specified".
"""

from __future__ import annotations

import json
from typing import Any, Sequence

from ksim_tpu.scenario.runner import Operation
from ksim_tpu.state.resources import JSON as JSONObj

# TypeMeta.kind -> store kind (the 7 snapshot kinds).
KIND_MAP = {
    "Pod": "pods",
    "Node": "nodes",
    "PersistentVolume": "persistentvolumes",
    "PersistentVolumeClaim": "persistentvolumeclaims",
    "StorageClass": "storageclasses",
    "PriorityClass": "priorityclasses",
    "Namespace": "namespaces",
}


class ScenarioSpecError(ValueError):
    """Invalid Scenario document (the KEP's 'the scenario will fail')."""


def _store_kind(type_kind: str, op_id: str) -> str:
    kind = KIND_MAP.get(type_kind)
    if kind is None:
        raise ScenarioSpecError(
            f"operation {op_id!r}: unsupported kind {type_kind!r} "
            f"(supported: {sorted(KIND_MAP)})"
        )
    return kind


def merge_patch(target: JSONObj, patch: Any) -> Any:
    """RFC 7386 JSON merge patch: dicts merge recursively, null deletes,
    everything else replaces."""
    if not isinstance(patch, dict):
        return patch
    out = dict(target) if isinstance(target, dict) else {}
    for k, v in patch.items():
        if v is None:
            out.pop(k, None)
        else:
            out[k] = merge_patch(out.get(k, {}), v)
    return out


def operations_from_spec(doc: JSONObj) -> list[Operation]:
    """Lower a Scenario document (or bare ``{"operations": [...]}``) to
    the runner's Operation list, sorted by step (stable within a step,
    like the KEP's per-MajorStep batches)."""
    spec = doc.get("spec") or doc
    raw_ops = spec.get("operations")
    if raw_ops is None:
        raise ScenarioSpecError("document has no spec.operations")
    out: list[Operation] = []
    for i, rop in enumerate(raw_ops):
        op_id = str(rop.get("id") or i)
        step = int(rop.get("step", 0))
        # Key-present counts as set even with a null body: doneOperation
        # is naturally empty ("doneOperation:" in YAML parses to None).
        bodies = {
            k: rop[k] or {}
            for k in ("createOperation", "patchOperation", "deleteOperation", "doneOperation")
            if k in rop
        }
        if len(bodies) != 1:
            raise ScenarioSpecError(
                f"operation {op_id!r}: exactly one of createOperation/"
                f"patchOperation/deleteOperation/doneOperation must be set "
                f"(got {sorted(bodies) or 'none'})"
            )
        key, body = next(iter(bodies.items()))
        if key == "createOperation":
            obj = body.get("object")
            if not isinstance(obj, dict) or not obj.get("kind"):
                raise ScenarioSpecError(
                    f"operation {op_id!r}: createOperation.object needs a kind"
                )
            out.append(
                Operation(step=step, op="create", kind=_store_kind(obj["kind"], op_id), obj=obj)
            )
        elif key == "patchOperation":
            kind = _store_kind((body.get("typeMeta") or {}).get("kind", ""), op_id)
            meta = body.get("objectMeta") or {}
            patch = body.get("patch")
            if isinstance(patch, (str, bytes)):
                patch = json.loads(patch)
            out.append(
                Operation(
                    step=step,
                    op="patch",
                    kind=kind,
                    obj=patch,
                    name=meta.get("name", ""),
                    namespace=meta.get("namespace", ""),
                )
            )
        elif key == "deleteOperation":
            kind = _store_kind((body.get("typeMeta") or {}).get("kind", ""), op_id)
            meta = body.get("objectMeta") or {}
            out.append(
                Operation(
                    step=step,
                    op="delete",
                    kind=kind,
                    name=meta.get("name", ""),
                    namespace=meta.get("namespace", ""),
                )
            )
        else:  # doneOperation
            out.append(Operation(step=step, op="done", kind=""))
    out.sort(key=lambda o: o.step)
    return out


#: store kind -> TypeMeta.kind (the inverse of KIND_MAP, for raising
#: Operation streams back into Scenario documents).
TYPE_META_KIND = {v: k for k, v in KIND_MAP.items()}


def spec_from_operations(ops: "Sequence[Operation]") -> JSONObj:
    """Raise a runner ``Operation`` stream back into the KEP-140
    Scenario document shape — the inverse of ``operations_from_spec``
    (round-trip: ``operations_from_spec(spec_from_operations(ops)) ==
    list(ops)`` for in-vocabulary streams).  This is how library
    streams (``churn_scenario``) are SUBMITTED to the tenant job plane,
    whose wire format is documents, not Operation objects."""
    out: list[JSONObj] = []
    for op in ops:
        entry: JSONObj = {"step": op.step}
        if op.op == "create":
            obj = dict(op.obj or {})
            obj.setdefault("kind", TYPE_META_KIND.get(op.kind, ""))
            entry["createOperation"] = {"object": obj}
        elif op.op == "delete":
            entry["deleteOperation"] = {
                "typeMeta": {"kind": TYPE_META_KIND.get(op.kind, "")},
                "objectMeta": {"name": op.name, "namespace": op.namespace},
            }
        elif op.op == "patch":
            entry["patchOperation"] = {
                "typeMeta": {"kind": TYPE_META_KIND.get(op.kind, "")},
                "objectMeta": {"name": op.name, "namespace": op.namespace},
                "patch": op.obj,
            }
        elif op.op == "done":
            entry["doneOperation"] = {}
        else:
            raise ScenarioSpecError(f"operation {op.op!r} has no document form")
        out.append(entry)
    return {"operations": out}


def load_scenario(text_or_doc: "str | bytes | JSONObj") -> list[Operation]:
    """Parse a Scenario document from YAML/JSON text (or an already-parsed
    dict) into runner operations."""
    if isinstance(text_or_doc, (str, bytes)):
        import yaml

        doc = yaml.safe_load(text_or_doc)
    else:
        doc = text_or_doc
    if not isinstance(doc, dict):
        raise ScenarioSpecError("scenario document must be a mapping")
    return operations_from_spec(doc)
