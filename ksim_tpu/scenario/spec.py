"""KEP-140 Scenario documents -> runner operations.

The reference designed (but never built) a Scenario CRD whose
``spec.operations`` drive timed create/patch/delete mutations with a
``doneOperation`` terminator (reference
keps/140-scenario-based-simulation/README.md, ScenarioOperation /
CreateOperation / PatchOperation / DeleteOperation / DoneOperation).
This module accepts that document shape — as a dict, JSON, or YAML —
and lowers it to the library ``Operation`` stream:

- ``createOperation.object``  -> Operation(op="create"), kind from the
  object's ``kind``;
- ``patchOperation``          -> Operation(op="patch") carrying an
  RFC 7386 JSON merge patch (the KEP leaves PatchType open; merge patch
  is the simulator-native choice — strategic merge is an apiserver
  concept);
- ``deleteOperation``         -> Operation(op="delete");
- ``doneOperation``           -> Operation(op="done") — the runner marks
  the scenario succeeded after finishing that step and ignores later
  steps.

Exactly one of the four must be set per operation, like the KEP's
"one of the following four fields must be specified".

Since round 14 a scenario may also be SOURCED instead of enumerated:
``spec.source.trace`` names a real cluster trace (ksim_tpu/traces/) to
be parsed, resampled and compiled into the operation stream —

    spec:
      source:
        trace:
          name: borg_mini.jsonl     # registered in KSIM_TRACES_DIR
          # path: /data/trace.gz    # library/CLI only; the job plane
          #                           refuses raw paths
          format: borg              # borg | alibaba
          nodes: 64                 # synthesized node universe
          maxEvents: 5000           # resample budget (0 = no cap)
          seed: 0
          opsPerStep: 100
          sourceNodes: 4000         # optional: rescale load to nodes/

and a ``spec.faults`` section arms ``KSIM_FAULTS``-style schedules from
the document itself (the chaos-native half of the same ROADMAP item):
a mapping of injection site to schedule string, canonicalized by
``faults_spec_from_doc`` into the exact grammar ``KSIM_FAULTS`` speaks
and armed by the consumer (the job plane arms it on the job's PRIVATE
plane, sites restricted to the job-plane set — docs/jobs.md).

Exactly one of ``operations`` / ``source`` must be present.
"""

from __future__ import annotations

import json
from typing import Any, Sequence

from ksim_tpu.scenario.runner import Operation
from ksim_tpu.state.resources import JSON as JSONObj

# TypeMeta.kind -> store kind (the 7 snapshot kinds).
KIND_MAP = {
    "Pod": "pods",
    "Node": "nodes",
    "PersistentVolume": "persistentvolumes",
    "PersistentVolumeClaim": "persistentvolumeclaims",
    "StorageClass": "storageclasses",
    "PriorityClass": "priorityclasses",
    "Namespace": "namespaces",
}


class ScenarioSpecError(ValueError):
    """Invalid Scenario document (the KEP's 'the scenario will fail')."""


def _store_kind(type_kind: str, op_id: str) -> str:
    kind = KIND_MAP.get(type_kind)
    if kind is None:
        raise ScenarioSpecError(
            f"operation {op_id!r}: unsupported kind {type_kind!r} "
            f"(supported: {sorted(KIND_MAP)})"
        )
    return kind


def merge_patch(target: JSONObj, patch: Any) -> Any:
    """RFC 7386 JSON merge patch: dicts merge recursively, null deletes,
    everything else replaces."""
    if not isinstance(patch, dict):
        return patch
    out = dict(target) if isinstance(target, dict) else {}
    for k, v in patch.items():
        if v is None:
            out.pop(k, None)
        else:
            out[k] = merge_patch(out.get(k, {}), v)
    return out


def default_trace_resolver(trace_doc: JSONObj) -> str:
    """Resolve a ``source.trace`` reference to a readable path: an
    explicit ``path`` (library/CLI use), else a ``name`` looked up in
    the ``KSIM_TRACES_DIR`` registry.  The job plane substitutes a
    resolver that refuses ``path`` outright (tenants must never make
    the server read arbitrary files)."""
    from ksim_tpu.traces.registry import resolve

    path = trace_doc.get("path")
    if path:
        return str(path)
    name = trace_doc.get("name")
    if not name:
        raise ScenarioSpecError("source.trace needs a name (or path)")
    return resolve(str(name))


def _operations_from_source(
    src: JSONObj, trace_resolver, *, event_bound: int = 0, node_bound: int = 0
) -> list[Operation]:
    from ksim_tpu.traces.compile import TRACE_FORMATS, trace_operations
    from ksim_tpu.traces.schema import TraceBoundExceeded, TraceError

    if not isinstance(src, dict) or set(src) != {"trace"}:
        raise ScenarioSpecError(
            "spec.source supports exactly one key: 'trace'"
        )
    t = src["trace"] or {}
    fmt = t.get("format")
    if fmt not in TRACE_FORMATS:
        raise ScenarioSpecError(
            f"source.trace.format must be one of {list(TRACE_FORMATS)} "
            f"(got {fmt!r})"
        )
    try:
        nodes = int(t.get("nodes", 100))
        max_events = int(t.get("maxEvents", 0))
        seed = int(t.get("seed", 0))
        ops_per_step = int(t.get("opsPerStep", 100))
        source_nodes = t.get("sourceNodes")
        source_nodes = int(source_nodes) if source_nodes is not None else None
    except (TypeError, ValueError):
        raise ScenarioSpecError(
            "source.trace nodes/maxEvents/seed/opsPerStep/sourceNodes "
            "must be integers"
        ) from None
    try:
        path = (trace_resolver or default_trace_resolver)(t)
        return trace_operations(
            path,
            fmt,
            nodes=nodes,
            max_events=max_events,
            seed=seed,
            ops_per_step=ops_per_step,
            source_nodes=source_nodes,
            event_bound=event_bound,
            node_bound=node_bound,
        )
    except TraceBoundExceeded:
        # NOT a bad document: the caller's size limit fired mid-read.
        # The jobs plane owns this vocabulary (JobLimitExceeded, HTTP
        # 413) — folding it into ScenarioSpecError would turn a quota
        # refusal into a 400.
        raise
    except TraceError as e:
        # One failure vocabulary at this surface: a bad trace reference
        # or corrupt file is a bad SCENARIO document (HTTP 400), not a
        # server error.
        raise ScenarioSpecError(str(e)) from e


def faults_spec_from_doc(doc: JSONObj) -> str:
    """Canonicalize ``spec.faults`` — a mapping of injection site to
    ``KSIM_FAULTS`` schedule string (``call:N``/``first:K``/``always``/
    ``p:P[:SEED]``/``hang:T[:K]``, optional ``@error``) — into the
    comma-joined ``site=schedule`` grammar the fault plane's
    ``configure`` speaks.  Returns ``""`` when the document arms
    nothing.  Validation of schedules (and of WHICH sites a consumer
    may arm) stays with the consumer: the job plane restricts sites to
    its own set and lets ``FaultPlane.configure`` reject malformed
    schedules loudly."""
    spec = doc.get("spec") or doc
    faults = spec.get("faults")
    if faults is None:
        return ""
    if not isinstance(faults, dict) or not all(
        isinstance(k, str) and isinstance(v, str) and k and v
        for k, v in faults.items()
    ):
        raise ScenarioSpecError(
            "spec.faults must map injection sites to schedule strings "
            '(e.g. {"replay.dispatch": "call:2@device"})'
        )
    for site, sched in faults.items():
        if "=" in site or "," in site or ";" in site:
            raise ScenarioSpecError(f"spec.faults site {site!r} is malformed")
        # The schedule value must be ONE schedule: an embedded separator
        # would smuggle extra `site=schedule` entries past the caller's
        # site allowlist once FaultPlane.configure re-splits the string.
        if "," in sched or ";" in sched:
            raise ScenarioSpecError(
                f"spec.faults schedule {sched!r} for {site!r} is malformed "
                "(one schedule per site; no ','/';')"
            )
    return ",".join(f"{site}={sched}" for site, sched in sorted(faults.items()))


def operations_from_spec(
    doc: JSONObj, *, trace_resolver=None, event_bound: int = 0, node_bound: int = 0
) -> list[Operation]:
    """Lower a Scenario document (or bare ``{"operations": [...]}``) to
    the runner's Operation list, sorted by step (stable within a step,
    like the KEP's per-MajorStep batches).  A document may instead
    carry ``spec.source.trace`` (exactly one of the two): the named
    trace is ingested through ``trace_resolver`` (default: explicit
    path, else the ``KSIM_TRACES_DIR`` registry).

    ``event_bound``/``node_bound`` (0 = unbounded) arm the trace-ingest
    plane's EARLY size refusal: ingestion raises ``TraceBoundExceeded``
    — deliberately NOT mapped onto ``ScenarioSpecError`` — the moment
    the compiled size provably passes the bound, so the caller (the
    jobs plane) refuses mid-read instead of after full parse+compile.
    Inline ``spec.operations`` documents are unaffected (the caller
    checks their materialized size as before)."""
    spec = doc.get("spec") or doc
    raw_ops = spec.get("operations")
    source = spec.get("source")
    if source is not None:
        if raw_ops is not None:
            raise ScenarioSpecError(
                "document has both spec.operations and spec.source — "
                "exactly one must be present"
            )
        return _operations_from_source(
            source, trace_resolver, event_bound=event_bound, node_bound=node_bound
        )
    if raw_ops is None:
        raise ScenarioSpecError("document has no spec.operations")
    out: list[Operation] = []
    for i, rop in enumerate(raw_ops):
        op_id = str(rop.get("id") or i)
        step = int(rop.get("step", 0))
        # Key-present counts as set even with a null body: doneOperation
        # is naturally empty ("doneOperation:" in YAML parses to None).
        bodies = {
            k: rop[k] or {}
            for k in ("createOperation", "patchOperation", "deleteOperation", "doneOperation")
            if k in rop
        }
        if len(bodies) != 1:
            raise ScenarioSpecError(
                f"operation {op_id!r}: exactly one of createOperation/"
                f"patchOperation/deleteOperation/doneOperation must be set "
                f"(got {sorted(bodies) or 'none'})"
            )
        key, body = next(iter(bodies.items()))
        if key == "createOperation":
            obj = body.get("object")
            if not isinstance(obj, dict) or not obj.get("kind"):
                raise ScenarioSpecError(
                    f"operation {op_id!r}: createOperation.object needs a kind"
                )
            out.append(
                Operation(step=step, op="create", kind=_store_kind(obj["kind"], op_id), obj=obj)
            )
        elif key == "patchOperation":
            kind = _store_kind((body.get("typeMeta") or {}).get("kind", ""), op_id)
            meta = body.get("objectMeta") or {}
            patch = body.get("patch")
            if isinstance(patch, (str, bytes)):
                patch = json.loads(patch)
            out.append(
                Operation(
                    step=step,
                    op="patch",
                    kind=kind,
                    obj=patch,
                    name=meta.get("name", ""),
                    namespace=meta.get("namespace", ""),
                )
            )
        elif key == "deleteOperation":
            kind = _store_kind((body.get("typeMeta") or {}).get("kind", ""), op_id)
            meta = body.get("objectMeta") or {}
            out.append(
                Operation(
                    step=step,
                    op="delete",
                    kind=kind,
                    name=meta.get("name", ""),
                    namespace=meta.get("namespace", ""),
                )
            )
        else:  # doneOperation
            out.append(Operation(step=step, op="done", kind=""))
    out.sort(key=lambda o: o.step)
    return out


#: store kind -> TypeMeta.kind (the inverse of KIND_MAP, for raising
#: Operation streams back into Scenario documents).
TYPE_META_KIND = {v: k for k, v in KIND_MAP.items()}


def spec_from_operations(ops: "Sequence[Operation]") -> JSONObj:
    """Raise a runner ``Operation`` stream back into the KEP-140
    Scenario document shape — the inverse of ``operations_from_spec``
    (round-trip: ``operations_from_spec(spec_from_operations(ops)) ==
    list(ops)`` for in-vocabulary streams).  This is how library
    streams (``churn_scenario``) are SUBMITTED to the tenant job plane,
    whose wire format is documents, not Operation objects."""
    out: list[JSONObj] = []
    for op in ops:
        entry: JSONObj = {"step": op.step}
        if op.op == "create":
            obj = dict(op.obj or {})
            obj.setdefault("kind", TYPE_META_KIND.get(op.kind, ""))
            entry["createOperation"] = {"object": obj}
        elif op.op == "delete":
            entry["deleteOperation"] = {
                "typeMeta": {"kind": TYPE_META_KIND.get(op.kind, "")},
                "objectMeta": {"name": op.name, "namespace": op.namespace},
            }
        elif op.op == "patch":
            entry["patchOperation"] = {
                "typeMeta": {"kind": TYPE_META_KIND.get(op.kind, "")},
                "objectMeta": {"name": op.name, "namespace": op.namespace},
                "patch": op.obj,
            }
        elif op.op == "done":
            entry["doneOperation"] = {}
        else:
            raise ScenarioSpecError(f"operation {op.op!r} has no document form")
        out.append(entry)
    return {"operations": out}


def load_scenario(text_or_doc: "str | bytes | JSONObj") -> list[Operation]:
    """Parse a Scenario document from YAML/JSON text (or an already-parsed
    dict) into runner operations."""
    if isinstance(text_or_doc, (str, bytes)):
        import yaml

        doc = yaml.safe_load(text_or_doc)
    else:
        doc = text_or_doc
    if not isinstance(doc, dict):
        raise ScenarioSpecError("scenario document must be a mapping")
    return operations_from_spec(doc)
