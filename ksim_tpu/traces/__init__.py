"""Trace ingestion plane: real cluster traces -> deterministic churn.

Every perf and parity claim before this subsystem was measured on
synthetic churn (scenario/generate.py); this package compiles the two
standard public workload datasets of the cluster-scheduling literature
— the Google Borg ClusterData instance events and the Alibaba
cluster-trace workload tables — into the same in-vocabulary
``Operation`` streams the replay engine already locks byte-for-byte
(ROADMAP "Scenario diversity: real-trace ingestion").

The pipeline (each stage its own module, each independently testable):

    parse (borg.py / alibaba.py)          format -> TraceRecord stream
      -> resample (resample.py)           seed-deterministic sizing
      -> compile (compile.py)             records -> Operation stream
      -> stream (stream.py)               the same pipeline as a bounded
                                          producer thread: O(window)
                                          windows overlapping the replay
                                          that consumes them

plus ``registry.py``, the allowlisted ``KSIM_TRACES_DIR`` name registry
the tenant job plane resolves trace references through (raw paths are
refused at the job surface), and ``schema.py``, the normalized record.
Selection is order-independent by construction (a keyed-hash rank per
record — resample.py), which is what lets the streaming and batch paths
emit byte-identical operation sequences.

Wired through the scenario spec (``source: {trace: ...}`` —
scenario/spec.py), the job plane (docs/jobs.md), and bench
(``churn_trace`` rung); the whole package is stdlib-only at import
time — machine-checked by the ksimlint import-boundary rule — so the
parsers configure and fail cleanly in jax-free processes.
"""

from ksim_tpu.traces.alibaba import parse_alibaba
from ksim_tpu.traces.borg import parse_borg
from ksim_tpu.traces.compile import (
    PRIORITY_LADDER,
    TRACE_FORMATS,
    compile_trace,
    trace_operations,
)
from ksim_tpu.traces.registry import (
    list_trace_entries,
    list_traces,
    open_trace_lines,
    resolve,
    trace_dir,
)
from ksim_tpu.traces.resample import StreamSelector, estimated_events, resample
from ksim_tpu.traces.schema import (
    TraceBoundExceeded,
    TraceError,
    TraceParseError,
    TraceRecord,
)
from ksim_tpu.traces.stream import TraceOperationStream, stream_trace_operations

__all__ = [
    "PRIORITY_LADDER",
    "TRACE_FORMATS",
    "StreamSelector",
    "TraceBoundExceeded",
    "TraceError",
    "TraceOperationStream",
    "TraceParseError",
    "TraceRecord",
    "compile_trace",
    "estimated_events",
    "list_trace_entries",
    "list_traces",
    "open_trace_lines",
    "parse_alibaba",
    "parse_borg",
    "resample",
    "resolve",
    "stream_trace_operations",
    "trace_dir",
    "trace_operations",
]
