"""Google ClusterData (Borg) instance-events parser — documented subset.

The 2019 "ClusterData v3" collection publishes per-cluster tables as
JSON Lines (one event object per line, gzipped); this parser consumes
the **instance_events** table's documented subset:

========================  ==================================================
field                     use
========================  ==================================================
``time``                  event time in MICROSECONDS since trace start
                          (int, or a numeric string — BigQuery exports
                          stringify int64)
``type``                  event type: the v3 enum number or its name
                          (``SUBMIT``/``QUEUE``/``ENABLE``/``SCHEDULE``/
                          ``EVICT``/``FAIL``/``FINISH``/``KILL``/``LOST``/
                          ``UPDATE_PENDING``/``UPDATE_RUNNING``)
``collection_id``         the owning job/alloc-set id
``instance_index``        the task's index inside its collection
``priority``              Borg priority (0..450; higher preempts lower)
``resource_request``      ``{"cpus": f, "memory": f}`` — fractions of the
                          largest cell machine, both optional
========================  ==================================================

One ``TraceRecord`` is emitted per (collection_id, instance_index)
lifetime: it opens at ``SUBMIT`` and closes at the first terminal event
(``EVICT``/``FAIL``/``FINISH``/``KILL``/``LOST``), whose distance is the
record's ``lifetime_s``; an instance still live at end-of-file yields
``lifetime_s=0`` (the compiler emits no delete).  A ``SUBMIT`` for an
already-closed identity opens a NEW record (Borg resubmits evicted
work); duplicate submits of a live identity and non-terminal lifecycle
events (``QUEUE``/``SCHEDULE``/``UPDATE_*`` — and any type outside the
enum) are ignored.

Normalization (docs/scenario.md "Trace ingestion"):

- resources denormalize against a 16-core / 64-GiB reference machine:
  ``cpu_milli = round(cpus * 16000)``, ``mem_mib = round(memory *
  65536)`` — Kubernetes-exact units by construction;
- the 0..450 priority space maps onto tiers by the published bands:
  <=99 free -> 0, 100..115 best-effort batch -> 1, 116..119 mid -> 2,
  120..359 production -> 3, >=360 monitoring -> 4; tiers >=3 are
  ``kind="service"``, the rest ``"batch"``.

Strict parsing: a line that is not valid JSON, or lacks
``time``/``type``/``collection_id``/``instance_index``, raises
``TraceParseError`` with its line number (see schema.py for why
skip-and-continue is the wrong call here).  Streaming: memory is
bounded by LIVE instances, never by file size.

Stdlib-only at import time (machine-checked).
"""

from __future__ import annotations

import json
import os
from typing import Iterable, Iterator

from ksim_tpu.traces.registry import open_trace_lines
from ksim_tpu.traces.schema import TraceParseError, TraceRecord

__all__ = ["parse_borg"]

#: Reference machine the normalized [0,1] requests denormalize against.
REF_CPU_MILLI = 16_000
REF_MEM_MIB = 65_536

_SUBMIT = 0
#: v3 enum names -> numbers (the documented subset).
EVENT_TYPES = {
    "SUBMIT": 0, "QUEUE": 1, "ENABLE": 2, "SCHEDULE": 3, "EVICT": 4,
    "FAIL": 5, "FINISH": 6, "KILL": 7, "LOST": 8,
    "UPDATE_PENDING": 9, "UPDATE_RUNNING": 10,
}
_TERMINAL = frozenset({4, 5, 6, 7, 8})  # EVICT FAIL FINISH KILL LOST


def _tier(priority: int) -> int:
    if priority <= 99:
        return 0
    if priority <= 115:
        return 1
    if priority <= 119:
        return 2
    if priority <= 359:
        return 3
    return 4


def _int_field(obj: dict, key: str, lineno: int) -> int:
    try:
        return int(obj[key])
    except (KeyError, TypeError, ValueError):
        raise TraceParseError(lineno, f"missing or non-integer {key!r}") from None


class _Open:
    """One live instance: the pending half of its record."""

    __slots__ = ("arrival_s", "cpu_milli", "mem_mib", "tier", "priority", "seq")

    def __init__(self, arrival_s, cpu_milli, mem_mib, tier, priority, seq):
        self.arrival_s = arrival_s
        self.cpu_milli = cpu_milli
        self.mem_mib = mem_mib
        self.tier = tier
        self.priority = priority
        self.seq = seq  # per-identity lifetime ordinal (resubmits)


def parse_borg(
    source: "str | os.PathLike | Iterable[str]",
) -> Iterator[TraceRecord]:
    """Stream ``TraceRecord``s from a ClusterData instance_events table
    (path — gz-transparent — or an iterable of lines).  Yield order is
    NOT arrival order (records close at their terminal event);
    ``resample``/``compile`` sort."""
    live: dict[tuple[int, int], _Open] = {}
    lifetimes: dict[tuple[int, int], int] = {}  # identity -> lifetimes seen

    def _close(key: tuple[int, int], rec: _Open, end_s: float) -> TraceRecord:
        name = f"c{key[0]}-i{key[1]}"
        if rec.seq:
            name = f"{name}-r{rec.seq}"  # resubmit: a distinct workload item
        return TraceRecord(
            name=name,
            arrival_s=rec.arrival_s,
            cpu_milli=rec.cpu_milli,
            mem_mib=rec.mem_mib,
            lifetime_s=max(end_s - rec.arrival_s, 0.0),
            tier=rec.tier,
            priority=rec.priority,
            kind="service" if rec.tier >= 3 else "batch",
        )

    for lineno, line in enumerate(open_trace_lines(source), start=1):
        if not line.strip():
            continue
        try:
            obj = json.loads(line)
        except ValueError:
            raise TraceParseError(lineno, "not valid JSON") from None
        if not isinstance(obj, dict):
            raise TraceParseError(lineno, "event must be a JSON object")
        raw_type = obj.get("type")
        if isinstance(raw_type, str) and not raw_type.isdigit():
            etype = EVENT_TYPES.get(raw_type)
            if etype is None and raw_type == "":
                raise TraceParseError(lineno, "missing or non-integer 'type'")
        else:
            etype = _int_field(obj, "type", lineno)
        time_us = _int_field(obj, "time", lineno)
        key = (
            _int_field(obj, "collection_id", lineno),
            _int_field(obj, "instance_index", lineno),
        )
        t_s = time_us / 1e6
        if etype == _SUBMIT:
            if key in live:
                continue  # duplicate submit of a live instance
            # Strict-with-line-number applies to these fields too: a bare
            # ValueError/AttributeError would escape the TraceError ->
            # ScenarioSpecError (HTTP 400) mapping at the spec surface.
            req = obj.get("resource_request") or {}
            if not isinstance(req, dict):
                raise TraceParseError(lineno, "resource_request must be an object")
            try:
                priority = int(obj.get("priority") or 0)
                cpus = float(req.get("cpus") or 0.0)
                memory = float(req.get("memory") or 0.0)
            except (TypeError, ValueError):
                raise TraceParseError(
                    lineno, "non-numeric priority/resource_request"
                ) from None
            live[key] = _Open(
                arrival_s=t_s,
                cpu_milli=round(cpus * REF_CPU_MILLI),
                mem_mib=round(memory * REF_MEM_MIB),
                tier=_tier(priority),
                priority=priority,
                seq=lifetimes.get(key, 0),
            )
        elif etype in _TERMINAL:
            rec = live.pop(key, None)
            if rec is None:
                continue  # terminal for an identity we never saw open
            lifetimes[key] = rec.seq + 1
            yield _close(key, rec, t_s)
        # else: lifecycle noise (QUEUE/SCHEDULE/UPDATE_* or unknown) — ignored

    # Instances still live at EOF: unknown lifetime, no delete.
    for key, rec in live.items():
        yield _close(key, rec, rec.arrival_s)
