"""Streaming-windowed trace ingest: the materialized pipeline, rebuilt
as a bounded producer/consumer so ingest overlaps replay.

``trace_operations`` (compile.py) is one synchronous call: parse the
whole source, select, materialize EVERY ``Operation``, hand the list to
the runner.  Peak host memory is O(stream) and the replay executor
idles until the last byte is parsed.  This module keeps the byte-exact
output contract and changes the shape of time and memory:

- **A producer thread** (``# ksimlint: thread-role(trace-ingest)``)
  parses the source through the single-pass
  :class:`~ksim_tpu.traces.resample.StreamSelector` (records held:
  O(event budget), exact — resample.py proves it), lays the selected
  records on the :class:`~ksim_tpu.traces.compile._EventLayout` grid,
  and materializes operations ONE WINDOW AT A TIME
  (``KSIM_TRACES_WINDOW`` ops per window) into a bounded queue
  (``KSIM_TRACES_QUEUE`` windows).  A full queue blocks the producer —
  backpressure, not buffering — so in-flight operation objects are
  capped at ``window x (queue + 1)`` regardless of stream length.
- **The consumer** (scenario/runner.py's streaming loop) drains windows
  as the replay engine commits segments, so ingest of window N+1
  overlaps device execution of window N — the third stage of the
  ingest ∥ prelower ∥ dispatch pipeline (engine/replay.py
  ``ingest_hook``).
- **Determinism is free, not re-proven per run**: selection is a pure
  per-record function of ``(seed, record)`` and the layout grid is a
  pure function of the selected set, so the concatenated windows are
  byte-identical to ``trace_operations`` output — golden-tested on the
  bundled fixtures, and the behavior locks (borg_mini 56/19) hold with
  streaming active.
- **Chaos degrades, input errors don't.**  An armed fault at the
  ``traces.stream`` site (or any unexpected SimulatorError) BEFORE the
  first window is emitted falls back to the materialized batch path —
  counted (``traces.ingest_fallback`` event, ``stats()["fallback"]``),
  byte-identical output, only the O(window) memory claim is forfeited.
  ``TraceError`` (bad input) propagates: it would fail both paths
  identically, and "degrading" it would just parse the broken file
  twice.  Errors cross to the consumer through the queue and re-raise
  at the next ``__next__``.

Bound enforcement rides the selector: ``event_bound``/``node_bound``
(the jobs plane's ``KSIM_JOBS_MAX_EVENTS``/``_MAX_NODES``) refuse
mid-read via :class:`~ksim_tpu.traces.schema.TraceBoundExceeded`.

Stdlib-only at import time (machine-checked); the ``Operation``
dataclass arrives lazily through compile.py's function-scope imports.
"""

from __future__ import annotations

import logging
import os
import queue
import threading
from collections import deque
from typing import TYPE_CHECKING, Iterable, Iterator

from ksim_tpu.errors import SimulatorError
from ksim_tpu.faults import FAULTS
from ksim_tpu.obs import TRACE
from ksim_tpu.traces.compile import _EventLayout, _node_ops, _parser, _validate_compile_args
from ksim_tpu.traces.resample import StreamSelector, resample
from ksim_tpu.traces.schema import TraceBoundExceeded, TraceError

if TYPE_CHECKING:  # pragma: no cover - typing only
    from ksim_tpu.scenario.runner import Operation

__all__ = [
    "DEFAULT_WINDOW_OPS",
    "DEFAULT_QUEUE_WINDOWS",
    "TraceOperationStream",
    "stream_trace_operations",
]

logger = logging.getLogger(__name__)

#: Default ``KSIM_TRACES_WINDOW``: operations per emitted window.  2048
#: matches the replay engine's 2K-batch lookahead appetite (a segment
#: consumes ``2 x k`` step batches; one window comfortably covers one
#: segment's worth of average-density steps).
DEFAULT_WINDOW_OPS = 2048

#: Default ``KSIM_TRACES_QUEUE``: windows the bounded queue holds before
#: the producer blocks.  4 windows of slack absorbs replay's bursty
#: consumption (a fast segment commit drains several windows at once)
#: without letting in-flight memory grow past ~5 windows total.
DEFAULT_QUEUE_WINDOWS = 4


def _env_int(name: str, default: int) -> int:
    raw = os.environ.get(name, "")
    try:
        value = int(raw) if raw else default
    except ValueError:
        return default
    return value if value > 0 else default


class _Cancelled(Exception):
    """Producer-internal unwind when the consumer closed the stream —
    never crosses the queue."""


class TraceOperationStream:
    """Iterator of ``Operation`` objects fed by the producer thread.

    Duck-typed by the runner via the ``streaming_ops`` marker; iterate
    to consume (``__next__`` blocks on the queue), ``next_nowait()``
    drains without blocking (the replay engine's ingest_hook overlap
    point), ``close()`` cancels the producer and is idempotent —
    callers wrap consumption in try/finally so an abandoned run never
    leaves a producer blocked on a full queue.

    Thread discipline: ``_buf``/``_done``/``_err`` are touched only on
    the consumer thread; ``_stat_*`` fields are written only by the
    producer (read-after-join or torn-read-tolerated, like every
    evidence snapshot); the queue and the ``_cancelled`` event are the
    only shared edges.
    """

    #: Marker the runner duck-types on (``getattr(ops, "streaming_ops",
    #: False)``) — no import edge from scenario/ back into traces/.
    streaming_ops = True

    def __init__(
        self,
        source: "str | os.PathLike | Iterable[str]",
        fmt: str,
        *,
        nodes: int,
        max_events: int = 0,
        seed: int = 0,
        ops_per_step: int = 100,
        source_nodes: "int | None" = None,
        event_bound: int = 0,
        node_bound: int = 0,
        window: "int | None" = None,
        queue_windows: "int | None" = None,
    ) -> None:
        _parser(fmt)  # unknown-format TraceError raises synchronously
        if nodes <= 0:
            raise TraceError("n_nodes must be positive")
        if ops_per_step <= 0:
            raise TraceError("ops_per_step must be positive")
        if node_bound and nodes > node_bound:
            raise TraceBoundExceeded("nodes", node_bound, nodes)
        self._source = source
        self._fmt = fmt
        self._nodes = nodes
        self._max_events = max_events
        self._seed = seed
        self._ops_per_step = ops_per_step
        self._source_nodes = source_nodes
        self._event_bound = event_bound
        # Synchronous too: rescale node-count validation and the
        # nothing-can-fit event-bound refusal happen at construction.
        self._selector = StreamSelector(
            seed=seed,
            max_events=max_events,
            target_nodes=nodes if source_nodes else None,
            source_nodes=source_nodes,
            event_bound=event_bound,
            base_events=nodes,
        )
        self._window = window if window else _env_int("KSIM_TRACES_WINDOW", DEFAULT_WINDOW_OPS)
        self._qcap = (
            queue_windows
            if queue_windows
            else _env_int("KSIM_TRACES_QUEUE", DEFAULT_QUEUE_WINDOWS)
        )
        self._q: "queue.Queue[tuple[str, object]]" = queue.Queue(maxsize=self._qcap)
        self._cancelled = threading.Event()
        self._thread: "threading.Thread | None" = None
        # Consumer-side state (consumer thread only).
        self._buf: "deque[Operation]" = deque()
        self._done = False
        self._err: "BaseException | None" = None
        # Producer-side evidence (producer thread only; plain ints so
        # torn reads are impossible under the GIL).
        self._stat_windows = 0
        self._stat_ops = 0
        self._stat_records = 0
        self._stat_fallback = 0
        self._stat_queue_peak = 0
        self._parse_started = False

    # -- consumer surface -------------------------------------------------

    def __iter__(self) -> "Iterator[Operation]":
        return self

    def __next__(self) -> "Operation":
        self._ensure_started()
        while True:
            if self._buf:
                return self._buf.popleft()
            if self._err is not None:
                raise self._err
            if self._done:
                raise StopIteration
            self._handle(*self._q.get())

    def next_nowait(self) -> "Operation | None":
        """One buffered operation, or None when nothing is ready (the
        producer is still parsing, or the stream ended) — the replay
        engine's ingest_hook calls this between prelower and the
        watchdog join, so a slow device dispatch is when windows drain."""
        self._ensure_started()
        if self._buf:
            return self._buf.popleft()
        if self._done or self._err is not None:
            return None  # terminal state surfaces at the blocking path
        try:
            item = self._q.get_nowait()
        except queue.Empty:
            return None
        self._handle(*item)
        return self._buf.popleft() if self._buf else None

    def close(self) -> None:
        """Cancel the producer and release its backpressure block; safe
        to call any number of times, including after exhaustion."""
        self._cancelled.set()
        while True:  # unblock a producer waiting on a full queue
            try:
                self._q.get_nowait()
            except queue.Empty:
                break
        t = self._thread
        if t is not None and t.is_alive():
            t.join(timeout=10.0)
        self._done = True
        self._buf.clear()

    def stats(self) -> dict:
        """Producer evidence for bench/tests: window/op/record counts,
        whether the run degraded to the materialized path, and the
        deepest the bounded queue ever got."""
        return {
            "windows": self._stat_windows,
            "ops": self._stat_ops,
            "records": self._stat_records,
            "fallback": self._stat_fallback,
            "queue_peak": self._stat_queue_peak,
            "window_ops": self._window,
            "queue_windows": self._qcap,
        }

    def _ensure_started(self) -> None:
        if self._thread is None:
            t = threading.Thread(
                target=self._produce, name="trace-ingest", daemon=True
            )
            self._thread = t
            t.start()

    def _handle(self, kind: str, payload) -> None:
        if kind == "win":
            self._buf.extend(payload)
        elif kind == "eof":
            self._done = True
        else:  # "err": re-raise the producer's exception where consumed
            self._err = payload
            raise payload

    # -- producer ---------------------------------------------------------

    def _produce(self) -> None:  # ksimlint: thread-role(trace-ingest)
        item: "tuple[str, object]" = ("eof", None)
        try:
            with TRACE.span(
                "traces.stream", format=self._fmt, nodes=self._nodes
            ) as span:
                records = self._ingest()
                self._stat_records = len(records)
                self._emit(records)
                span.set(
                    records=len(records),
                    windows=self._stat_windows,
                    ops=self._stat_ops,
                    fallback=self._stat_fallback,
                )
        except _Cancelled:
            return
        except BaseException as e:  # consumer classifies (incl. re-raise)
            err = e
            item = ("err", err)
        try:
            self._put(item)
        except _Cancelled:
            pass

    def _ingest(self) -> list:
        """Parse + select, bounded memory; armed chaos before the first
        window degrades to the materialized batch selection (counted,
        byte-identical), real input errors propagate."""
        try:
            FAULTS.check("traces.stream")
            self._parse_started = True
            self._selector.feed_all(_parser(self._fmt)(self._source))
            records = self._selector.finish()
            _validate_compile_args(records, self._nodes, self._ops_per_step)
            FAULTS.check("traces.stream")  # last pre-emission fault point
            return records
        except TraceError:
            raise  # fails the batch path identically — nothing to degrade to
        except SimulatorError as e:
            if not self._can_restart():
                raise
            TRACE.event(
                "traces.ingest_fallback", reason=type(e).__name__, format=self._fmt
            )
            self._stat_fallback = 1
            logger.warning(
                "streaming trace ingest degraded to the materialized path: %s", e
            )
            records = resample(
                _parser(self._fmt)(self._source),
                seed=self._seed,
                max_events=self._max_events,
                target_nodes=self._nodes if self._source_nodes else None,
                source_nodes=self._source_nodes,
            )
            _validate_compile_args(records, self._nodes, self._ops_per_step)
            return records

    def _can_restart(self) -> bool:
        """Re-reading the source is safe for paths always, and for raw
        line iterables only while nothing has been consumed."""
        if isinstance(self._source, (str, bytes, os.PathLike)):
            return True
        return not self._parse_started

    def _emit(self, records: list) -> None:
        """The windowed materialization: node bootstrap first, then pod
        events in (step, phase, seq) order — the exact concatenation
        ``compile_trace`` returns, cut into bounded windows."""
        layout = _EventLayout(records, self._ops_per_step)
        keys = layout.keys()
        buf: "list[Operation]" = []

        def flush() -> None:
            if not buf:
                return
            self._put(("win", list(buf)))
            self._stat_windows += 1
            self._stat_ops += len(buf)
            buf.clear()

        for op in _node_ops(self._nodes, self._seed):
            buf.append(op)
            if len(buf) >= self._window:
                flush()
        for key in keys:
            buf.append(layout.materialize(key))
            if len(buf) >= self._window:
                flush()
        flush()

    def _put(self, item: "tuple[str, object]") -> None:
        while True:
            if self._cancelled.is_set():
                raise _Cancelled()
            try:
                self._q.put(item, timeout=0.1)
            except queue.Full:
                continue
            depth = self._q.qsize()
            if depth > self._stat_queue_peak:
                self._stat_queue_peak = depth
            return


def stream_trace_operations(
    source: "str | os.PathLike | Iterable[str]",
    fmt: str,
    *,
    nodes: int,
    max_events: int = 0,
    seed: int = 0,
    ops_per_step: int = 100,
    source_nodes: "int | None" = None,
    event_bound: int = 0,
    node_bound: int = 0,
    window: "int | None" = None,
    queue_windows: "int | None" = None,
) -> TraceOperationStream:
    """The streaming twin of :func:`~ksim_tpu.traces.compile.trace_operations`:
    same arguments, same byte-exact operation sequence, but returned as
    a lazily-started bounded stream the runner replays window-by-window
    while the producer is still parsing."""
    return TraceOperationStream(
        source,
        fmt,
        nodes=nodes,
        max_events=max_events,
        seed=seed,
        ops_per_step=ops_per_step,
        source_nodes=source_nodes,
        event_bound=event_bound,
        node_bound=node_bound,
        window=window,
        queue_windows=queue_windows,
    )
