"""Seed-deterministic trace resampling: node-count rescale + event budget.

Public traces cover thousands of machines and tens of millions of
events; the simulator wants a stream sized to a TARGET node universe
and an event budget, with the empirical arrival / priority / size
distributions intact.  Two independent, composable reductions:

- **Node-count rescale** — with both ``target_nodes`` and
  ``source_nodes`` given (the trace's machine count, per its own
  documentation), every record survives independently with probability
  ``target_nodes / source_nodes``, so the per-node arrival intensity of
  the source cluster carries over to the smaller universe.
- **Event budget** — with ``max_events`` given, a uniform random subset
  of records is kept whose compiled pod-event estimate (one create,
  plus one delete when a lifetime is known) fits the budget.

Both draw from ``random.Random(seed)`` over the records in sorted
``(arrival_s, name)`` order, so the same inputs always select the same
subset — the determinism contract every behavior lock downstream
depends on.  Uniform selection is the whole preservation argument:
every marginal distribution of the records (arrival, priority tier,
request size, lifetime) survives uniform thinning in expectation;
nothing here stratifies, truncates tails, or reweights.

The output is sorted by ``(arrival_s, name)`` — parsers are allowed to
yield out of arrival order (Borg records close at their terminal
event), and ``compile`` requires the sorted view.

Stdlib-only at import time (machine-checked).
"""

from __future__ import annotations

import random
from typing import Iterable

from ksim_tpu.traces.schema import TraceError, TraceRecord

__all__ = ["estimated_events", "resample"]


def estimated_events(rec: TraceRecord) -> int:
    """Pod events this record compiles to: its create, plus its delete
    when the trace knows a lifetime."""
    return 2 if rec.lifetime_s > 0 else 1


def resample(
    records: Iterable[TraceRecord],
    *,
    seed: int = 0,
    max_events: int = 0,
    target_nodes: "int | None" = None,
    source_nodes: "int | None" = None,
) -> list[TraceRecord]:
    """Sorted, deterministically thinned records (see module docstring).
    ``max_events=0`` means no budget; the rescale step needs BOTH node
    counts (a target without a source is a compile-time universe size,
    not a thinning instruction)."""
    out = sorted(records, key=lambda r: (r.arrival_s, r.name))
    rng = random.Random(seed)
    if target_nodes is not None and source_nodes is not None:
        if source_nodes <= 0 or target_nodes <= 0:
            raise TraceError("node counts for rescaling must be positive")
        frac = target_nodes / source_nodes
        if frac < 1.0:
            out = [r for r in out if rng.random() < frac]
    if max_events:
        total = sum(estimated_events(r) for r in out)
        if total > max_events:
            # Uniform subset via a seeded permutation, cut at the budget,
            # then back to arrival order.
            order = list(range(len(out)))
            rng.shuffle(order)
            kept: list[int] = []
            budget = max_events
            for idx in order:
                cost = estimated_events(out[idx])
                if cost <= budget:
                    kept.append(idx)
                    budget -= cost
                if budget <= 0:
                    break
            out = [out[i] for i in sorted(kept)]
    return out
