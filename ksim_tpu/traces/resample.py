"""Seed-deterministic trace resampling: node-count rescale + event budget.

Public traces cover thousands of machines and tens of millions of
events; the simulator wants a stream sized to a TARGET node universe
and an event budget, with the empirical arrival / priority / size
distributions intact.  Two independent, composable reductions:

- **Node-count rescale** — with both ``target_nodes`` and
  ``source_nodes`` given (the trace's machine count, per its own
  documentation), every record survives independently with probability
  ``target_nodes / source_nodes``, so the per-node arrival intensity of
  the source cluster carries over to the smaller universe.
- **Event budget** — with ``max_events`` given, a uniform
  pseudo-random subset of records is kept whose compiled pod-event
  estimate (one create, plus one delete when a lifetime is known) fits
  the budget.

Both decisions are **order-independent**: each record's fate is a pure
function of ``(seed, record)``, via a keyed ``blake2b`` rank (8-byte
digest, domain-separated through the ``person`` parameter, independent
of ``PYTHONHASHSEED``).  The rescale coin is
``rank / 2**64 < target/source`` per record; the budget keeps the
greedy prefix of the records in ascending rank order, stopping at the
first record whose event cost no longer fits.  Because nothing depends
on input order or on a shared RNG stream, a single-pass streaming
selector (`StreamSelector`) can reproduce the exact same subset while
holding only ``O(max_events)`` records — the byte-identity contract
`traces/stream.py` and its golden tests depend on.  Uniform selection
is the whole preservation argument: every marginal distribution of the
records (arrival, priority tier, request size, lifetime) survives
uniform thinning in expectation; nothing here stratifies, truncates
tails, or reweights.

The output is sorted by the full-record `_order_key` — parsers are
allowed to yield out of arrival order (Borg records close at their
terminal event), and ``compile`` requires the sorted view.  The key
includes every field so even duplicate ``(arrival_s, name)`` pairs
(Alibaba task names collide) order deterministically regardless of
input order.

Stdlib-only at import time (machine-checked).
"""

from __future__ import annotations

import hashlib
import heapq
from typing import Iterable

from ksim_tpu.traces.schema import TraceBoundExceeded, TraceError, TraceRecord

__all__ = ["estimated_events", "resample", "StreamSelector"]

#: blake2b domain-separation tags (``person`` is capped at 16 bytes).
_DOMAIN_RESCALE = b"ksim-rescale"
_DOMAIN_BUDGET = b"ksim-budget"


def estimated_events(rec: TraceRecord) -> int:
    """Pod events this record compiles to: its create, plus its delete
    when the trace knows a lifetime."""
    return 2 if rec.lifetime_s > 0 else 1


def _order_key(rec: TraceRecord):
    """Total order over records — every field participates so the sort
    is input-order-independent even under duplicate (arrival, name)."""
    return (
        rec.arrival_s,
        rec.name,
        rec.lifetime_s,
        rec.cpu_milli,
        rec.mem_mib,
        rec.tier,
        rec.priority,
        rec.kind,
    )


def _rank(seed: int, domain: bytes, rec: TraceRecord) -> int:
    """64-bit uniform rank of a record under ``seed`` — a pure function
    of (seed, domain, record), so selection never depends on input
    order, process hash seed, or a shared RNG stream."""
    payload = (
        f"{seed}|{rec.name}|{rec.arrival_s!r}|{rec.lifetime_s!r}|"
        f"{rec.cpu_milli}|{rec.mem_mib}|{rec.tier}|{rec.priority}|{rec.kind}"
    ).encode()
    digest = hashlib.blake2b(payload, digest_size=8, person=domain).digest()
    return int.from_bytes(digest, "big")


def _survives_rescale(seed: int, rec: TraceRecord, frac: float) -> bool:
    return _rank(seed, _DOMAIN_RESCALE, rec) < frac * 2.0**64


def _budget_prefix(
    records: Iterable[TraceRecord], seed: int, budget: int
) -> list[TraceRecord]:
    """The greedy rank-order prefix that fits ``budget`` events: walk
    records in ascending (rank, order-key) order, stop at the FIRST one
    whose cost no longer fits.  Shared verbatim by the batch and
    streaming paths — it IS the byte-identity contract."""
    ranked = sorted(records, key=lambda r: (_rank(seed, _DOMAIN_BUDGET, r), _order_key(r)))
    kept: list[TraceRecord] = []
    for rec in ranked:
        cost = estimated_events(rec)
        if cost > budget:
            break
        kept.append(rec)
        budget -= cost
    return kept


def resample(
    records: Iterable[TraceRecord],
    *,
    seed: int = 0,
    max_events: int = 0,
    target_nodes: "int | None" = None,
    source_nodes: "int | None" = None,
) -> list[TraceRecord]:
    """Sorted, deterministically thinned records (see module docstring).
    ``max_events=0`` means no budget; the rescale step needs BOTH node
    counts (a target without a source is a compile-time universe size,
    not a thinning instruction)."""
    out = sorted(records, key=_order_key)
    if target_nodes is not None and source_nodes is not None:
        if source_nodes <= 0 or target_nodes <= 0:
            raise TraceError("node counts for rescaling must be positive")
        frac = target_nodes / source_nodes
        if frac < 1.0:
            out = [r for r in out if _survives_rescale(seed, r, frac)]
    if max_events:
        total = sum(estimated_events(r) for r in out)
        if total > max_events:
            out = _budget_prefix(out, seed, max_events)
            out.sort(key=_order_key)
    return out


class _HeapItem:
    """Max-heap adapter: ``heapq`` is a min-heap and the (rank, key)
    tuples contain strings, so ordering is reversed here instead of
    negated."""

    __slots__ = ("key", "rec")

    def __init__(self, key, rec: TraceRecord) -> None:
        self.key = key
        self.rec = rec

    def __lt__(self, other: "_HeapItem") -> bool:
        return other.key < self.key  # reversed: heap[0] is the LARGEST key


class StreamSelector:
    """Single-pass, bounded-memory implementation of :func:`resample`.

    Feed records in ANY order; :meth:`finish` returns byte-identically
    what ``resample(all_records, ...)`` would.  Memory is bounded by the
    event budget, not the stream: with ``max_events=B`` every kept
    record costs >= 1 event, so the greedy rank-order prefix holds at
    most ``B`` records and its stop decision only ever examines the
    first ``B + 1`` records in rank order — a capped max-heap of the
    ``B + 1`` smallest-keyed records is therefore *exact*, not
    approximate.  (When the post-rescale total fits the budget, fewer
    than ``B + 1`` records exist, so none were evicted and all are
    kept, again matching the batch path.)  Without a budget, selection
    keeps everything and memory is O(stream) by definition — callers
    wanting O(window) ingest set a budget.

    ``event_bound``/``base_events`` arm *early refusal* (the
    `KSIM_JOBS_MAX_EVENTS` satellite): ``base_events`` is the fixed
    event cost the compiler adds on top of selection (the node
    bootstrap), and the selector raises
    :class:`~ksim_tpu.traces.schema.TraceBoundExceeded` as soon as the
    final selected cost is *provably* above the bound, so oversized
    streams stop mid-read instead of after full parse+compile.  The
    proof obligation: with budget ``B``, the final selected cost ``S``
    is ``total`` when ``total <= B`` and otherwise lands in
    ``[B - 1, B]`` (costs are 1 or 2 and the prefix stops at the first
    overflow), so ``min(running_total, B - 1)`` — ``running_total``
    itself when unbudgeted — is a monotone lower bound on ``S``; the
    precise final gate stays with the caller.
    """

    def __init__(
        self,
        *,
        seed: int = 0,
        max_events: int = 0,
        target_nodes: "int | None" = None,
        source_nodes: "int | None" = None,
        event_bound: int = 0,
        base_events: int = 0,
    ) -> None:
        self._seed = seed
        self._budget = max_events
        self._frac = 1.0
        if target_nodes is not None and source_nodes is not None:
            if source_nodes <= 0 or target_nodes <= 0:
                raise TraceError("node counts for rescaling must be positive")
            self._frac = target_nodes / source_nodes
        self._event_bound = event_bound
        self._base_events = base_events
        self._total = 0  # post-rescale estimated events fed so far
        self._fed = 0  # post-rescale record count fed so far
        self._heap: list[_HeapItem] = []  # budgeted mode: B+1 smallest keys
        self._kept: list[TraceRecord] = []  # unbudgeted mode: everything
        if event_bound and base_events + 1 > event_bound:
            # The compiled stream always holds the bootstrap plus at
            # least one pod event — refusable before reading any bytes.
            raise TraceBoundExceeded("events", event_bound, base_events + 1)

    @property
    def selected_lower_bound(self) -> int:
        """Monotone lower bound on the final selected event cost (see
        class docstring for why it is exact enough to refuse early)."""
        if not self._budget:
            return self._total
        return min(self._total, self._budget - 1)

    def feed(self, rec: TraceRecord) -> None:
        """Account one record; raises ``TraceBoundExceeded`` the moment
        the event bound is provably blown."""
        if self._frac < 1.0 and not _survives_rescale(self._seed, rec, self._frac):
            return
        self._total += estimated_events(rec)
        self._fed += 1
        if self._budget:
            key = (_rank(self._seed, _DOMAIN_BUDGET, rec), _order_key(rec))
            cap = self._budget + 1
            if len(self._heap) < cap:
                heapq.heappush(self._heap, _HeapItem(key, rec))
            elif key < self._heap[0].key:
                heapq.heapreplace(self._heap, _HeapItem(key, rec))
        else:
            self._kept.append(rec)
        if self._event_bound:
            floor = self._base_events + self.selected_lower_bound
            if floor > self._event_bound:
                raise TraceBoundExceeded("events", self._event_bound, floor)

    def feed_all(self, records: Iterable[TraceRecord]) -> None:
        for rec in records:
            self.feed(rec)

    def finish(self) -> list[TraceRecord]:
        """The selected records in `_order_key` order — byte-identical
        to the batch :func:`resample` over the same fed records."""
        if not self._budget:
            out = list(self._kept)
        elif self._total <= self._budget:
            # Nothing was ever evicted (record count <= total <= B < cap).
            out = [item.rec for item in self._heap]
        else:
            out = _budget_prefix(
                (item.rec for item in self._heap), self._seed, self._budget
            )
        out.sort(key=_order_key)
        return out
