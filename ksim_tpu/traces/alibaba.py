"""Alibaba cluster-trace parser (v2018 tables) — documented subset.

The Alibaba cluster-trace-v2018 publishes headerless CSV tables; this
parser consumes the two workload tables, auto-detected by column count
(the files are homogeneous, so the first data row decides):

**batch_task** (9 columns) — one record per task row:

    task_name, instance_num, job_name, task_type, status,
    start_time, end_time, plan_cpu, plan_mem

- ``name`` = ``<job_name>-<task_name>``, ``arrival_s`` = ``start_time``
  (seconds), ``lifetime_s`` = ``end_time - start_time`` when the end is
  known and later, else 0;
- ``plan_cpu`` is in centi-cores (100 = 1 core): ``cpu_milli =
  round(plan_cpu * 10)``; ``plan_mem`` is a percentage of machine
  memory, denormalized against the same 64-GiB reference machine the
  Borg parser uses: ``mem_mib = round(plan_mem / 100 * 65536)``;
- tier 1 (best-effort batch), ``kind="batch"``; ``task_type`` is kept
  as the native ``priority`` when numeric.

**container_meta** (8 columns) — one record per container (the FIRST
row of each ``container_id``; later rows are lifecycle updates):

    container_id, machine_id, time_stamp, app_du, status,
    cpu_request, cpu_limit, mem_size

- ``name`` = ``container_id``, ``arrival_s`` = ``time_stamp``;
  containers are long-running: ``lifetime_s = 0`` (no delete);
- ``cpu_request`` is in centi-cores, ``mem_size`` a percentage of
  machine memory (denormalized as above);
- tier 3 (production), ``kind="service"``.

Strict parsing: a row with the wrong column count or a non-numeric
required field raises ``TraceParseError`` with its line number; empty
``plan_cpu``/``plan_mem``/``cpu_request``/``mem_size`` cells parse as 0
(the traces genuinely carry blanks there).  Streaming: batch rows yield
as read; container dedup keeps one id-set in memory.

Stdlib-only at import time (machine-checked).
"""

from __future__ import annotations

import csv
import os
from typing import Iterable, Iterator

from ksim_tpu.traces.registry import open_trace_lines
from ksim_tpu.traces.schema import TraceParseError, TraceRecord

__all__ = ["parse_alibaba"]

#: Reference machine memory (MiB) the normalized percentages map onto.
REF_MEM_MIB = 65_536

_BATCH_COLS = 9
_CONTAINER_COLS = 8


def _num(row: list[str], idx: int, lineno: int, *, required: bool) -> float:
    cell = row[idx].strip() if idx < len(row) else ""
    if not cell:
        if required:
            raise TraceParseError(lineno, f"empty required column {idx}")
        return 0.0
    try:
        return float(cell)
    except ValueError:
        raise TraceParseError(
            lineno, f"non-numeric value {cell!r} in column {idx}"
        ) from None


def parse_alibaba(
    source: "str | os.PathLike | Iterable[str]",
) -> Iterator[TraceRecord]:
    """Stream ``TraceRecord``s from an Alibaba v2018 workload table
    (path — gz-transparent — or an iterable of CSV lines); the table
    kind is detected from the first data row's column count."""
    reader = csv.reader(open_trace_lines(source))
    ncols: "int | None" = None
    seen_containers: set[str] = set()
    for lineno, row in enumerate(reader, start=1):
        if not row or (len(row) == 1 and not row[0].strip()):
            continue
        if ncols is None:
            if len(row) not in (_BATCH_COLS, _CONTAINER_COLS):
                raise TraceParseError(
                    lineno,
                    f"unrecognized table shape ({len(row)} columns; "
                    f"batch_task has {_BATCH_COLS}, container_meta "
                    f"{_CONTAINER_COLS})",
                )
            ncols = len(row)
        if len(row) != ncols:
            raise TraceParseError(
                lineno, f"expected {ncols} columns, found {len(row)}"
            )
        if ncols == _BATCH_COLS:
            task_name, _inst, job_name, task_type = (
                row[0].strip(), row[1], row[2].strip(), row[3].strip(),
            )
            if not task_name or not job_name:
                raise TraceParseError(lineno, "empty task_name/job_name")
            start = _num(row, 5, lineno, required=True)
            end = _num(row, 6, lineno, required=False)
            yield TraceRecord(
                name=f"{job_name}-{task_name}",
                arrival_s=start,
                cpu_milli=round(_num(row, 7, lineno, required=False) * 10),
                mem_mib=round(_num(row, 8, lineno, required=False) / 100 * REF_MEM_MIB),
                lifetime_s=max(end - start, 0.0) if end else 0.0,
                tier=1,
                priority=int(task_type) if task_type.isdigit() else 0,
                kind="batch",
            )
        else:
            cid = row[0].strip()
            if not cid:
                raise TraceParseError(lineno, "empty container_id")
            if cid in seen_containers:
                continue  # lifecycle update rows for a known container
            seen_containers.add(cid)
            yield TraceRecord(
                name=cid,
                arrival_s=_num(row, 2, lineno, required=True),
                cpu_milli=round(_num(row, 5, lineno, required=False) * 10),
                mem_mib=round(_num(row, 7, lineno, required=False) / 100 * REF_MEM_MIB),
                lifetime_s=0.0,
                tier=3,
                priority=0,
                kind="service",
            )
