"""Where trace bytes come from: the named-trace allowlist + bounded IO.

Two concerns, both security-shaped, live here:

- **The registry.** Tenants submit jobs that reference traces BY NAME;
  the server resolves names inside the operator-allowlisted
  ``KSIM_TRACES_DIR`` and nowhere else.  Raw file paths are refused at
  the job surface (ksim_tpu/jobs/manager.py) for the same reason
  ``initialSnapshotPath`` is: a tenant must never make the server read
  its own filesystem.  Names are bare filenames — no separators, no
  traversal, nothing hidden.
- **Bounded, gz-transparent line streaming.** ``open_trace_lines``
  yields decoded lines from a plain or gzip file (sniffed by magic
  bytes, not extension) while counting DECOMPRESSED bytes against
  ``KSIM_TRACES_MAX_BYTES`` — a tenant naming a pathological file (or a
  gzip bomb) cannot make a job worker chew unbounded input.  Parsers
  stream through this helper and never load a whole file.

Stdlib-only at import time (machine-checked: tools/ksimlint
import-boundary).
"""

from __future__ import annotations

import gzip
import os
from typing import IO, Iterable, Iterator

from ksim_tpu.traces.schema import TraceError

__all__ = [
    "list_trace_entries",
    "list_traces",
    "open_trace_lines",
    "resolve",
    "trace_dir",
]

#: Default ``KSIM_TRACES_MAX_BYTES``: 64 MiB of (decompressed) input.
DEFAULT_MAX_BYTES = 64 * 1024 * 1024


def trace_dir() -> "str | None":
    """The operator's allowlisted trace directory (``KSIM_TRACES_DIR``),
    or None when the registry is not configured."""
    return os.environ.get("KSIM_TRACES_DIR") or None


def _valid_name(name: str) -> bool:
    return bool(name) and not (
        name.startswith(".")
        or "/" in name
        or "\\" in name
        or os.sep in name
        or name != os.path.basename(name)
    )


def resolve(name: str) -> str:
    """Resolve a registered trace name to its path under
    ``KSIM_TRACES_DIR``.  Raises ``TraceError`` when the registry is not
    configured, the name is not a bare filename, or nothing is
    registered under it."""
    base = trace_dir()
    if base is None:
        raise TraceError(
            "no trace registry configured (set KSIM_TRACES_DIR to the "
            "directory of registered traces)"
        )
    if not _valid_name(name):
        raise TraceError(f"invalid trace name {name!r} (bare filenames only)")
    path = os.path.join(base, name)
    if not os.path.isfile(path):
        raise TraceError(f"no registered trace {name!r} (have {list_traces()})")
    return path


def list_traces() -> list[str]:
    """Registered trace names (sorted); empty without a configured or
    readable registry directory."""
    base = trace_dir()
    if base is None:
        return []
    try:
        entries = os.listdir(base)
    except OSError:
        return []
    return sorted(
        e for e in entries if _valid_name(e) and os.path.isfile(os.path.join(base, e))
    )


def _sniff_format(path: str) -> str:
    """Best-effort format detection from the first non-blank line (gz
    transparent, bounded read): a JSON object is the Borg instance-event
    table, an 8/9-column CSV row is an Alibaba workload table, anything
    else — including unreadable or over-cap files — is ``"unknown"``.
    Advisory metadata only: job submission still names the format
    explicitly and the strict parsers remain the authority."""
    import json

    try:
        for line in open_trace_lines(path, max_bytes=1 << 20):
            text = line.strip()
            if not text:
                continue
            if text.startswith("{"):
                try:
                    return "borg" if isinstance(json.loads(text), dict) else "unknown"
                except ValueError:
                    return "unknown"
            if len(text.split(",")) in (8, 9):
                return "alibaba"
            return "unknown"
    except TraceError:
        return "unknown"
    return "unknown"


def list_trace_entries() -> list[dict]:
    """Registered traces with per-entry metadata — the ``GET
    /api/v1/traces`` shape: ``name`` / ``size_bytes`` (on-disk, NOT
    decompressed) / ``gzip`` (magic-byte sniff) / ``format`` (detected,
    advisory — see ``_sniff_format``).  Sorted by name like
    :func:`list_traces`; entries that disappear or turn unreadable
    mid-listing are skipped rather than failing the listing."""
    base = trace_dir()
    out: list[dict] = []
    if base is None:
        return out
    for name in list_traces():
        path = os.path.join(base, name)
        try:
            size = os.stat(path).st_size
            with open(path, "rb") as f:
                gz = f.read(2) == b"\x1f\x8b"
        except OSError:
            continue
        out.append(
            {
                "name": name,
                "size_bytes": size,
                "gzip": gz,
                "format": _sniff_format(path),
            }
        )
    return out


def _max_bytes() -> int:
    raw = os.environ.get("KSIM_TRACES_MAX_BYTES", "")
    try:
        return int(raw) if raw else DEFAULT_MAX_BYTES
    except ValueError:
        return DEFAULT_MAX_BYTES


def open_trace_lines(
    source: "str | os.PathLike | Iterable[str]",
    *,
    max_bytes: "int | None" = None,
) -> Iterator[str]:
    """Yield decoded text lines from ``source``.

    ``source`` may be a path (gzip sniffed by its magic bytes — the
    extension is not trusted) or any iterable of already-decoded lines
    (tests, in-memory snippets).  Streaming: one line in memory at a
    time; cumulative DECOMPRESSED bytes are capped by ``max_bytes``
    (default ``KSIM_TRACES_MAX_BYTES``, 0 = unbounded) and exceeding the
    cap raises ``TraceError`` instead of truncating silently — a
    half-read trace would compile to a stream that LOOKS valid."""
    if not isinstance(source, (str, bytes, os.PathLike)):
        yield from source
        return
    cap = _max_bytes() if max_bytes is None else max_bytes
    try:
        raw: IO[bytes] = open(source, "rb")
    except OSError as e:
        raise TraceError(f"cannot read trace {source!r}: {e}") from None
    with raw:
        magic = raw.read(2)
        raw.seek(0)
        stream: IO[bytes] = gzip.open(raw, "rb") if magic == b"\x1f\x8b" else raw
        seen = 0
        try:
            for line in stream:
                seen += len(line)
                if cap and seen > cap:
                    raise TraceError(
                        f"trace {os.path.basename(str(source))!r} exceeds the "
                        f"{cap}-byte bound (KSIM_TRACES_MAX_BYTES)"
                    )
                yield line.decode("utf-8", errors="strict")
        except (OSError, EOFError, UnicodeDecodeError) as e:
            # A truncated gzip member / undecodable bytes mid-stream:
            # the trace is corrupt, not merely short.
            raise TraceError(f"corrupt trace {source!r}: {e}") from None
