"""Trace records -> in-vocabulary ``Operation`` streams.

The last stage of the ingestion plane: normalized records (schema.py)
become exactly the operation stream the replay engine already speaks —
create/delete of pods and nodes only, so the device-resident segment
path (engine/replay.py) lowers a compiled trace with ZERO new fallback
classes.  The guarantees, each tied to a fallback class it forecloses:

- **Unique pod names** — every pod is ``p<seq>-<sanitized trace id>``;
  a trace that resubmits an identity still never reuses a simulator
  name (``pod_name_reuse`` / ``backoff_name_reuse`` cannot fire).
- **Exact quantities** — requests are emitted as ``<n>m`` / ``<n>Mi``
  strings straight from the record's integer fields
  (``inexact_units`` cannot fire).
- **Plain pods** — no volumes, host ports, scheduling gates, or
  foreign schedulers; priorities ride as resolved ``spec.priority``
  integers (state/priorities.py: explicit priority wins), so no
  PriorityClass objects — an out-of-vocabulary kind — ever enter the
  stream.
- **Static node universe** — the whole fleet is created at step 0 and
  never drained, and deletes only ever name pods the stream created
  (``delete_unknown_*`` cannot fire).

Priority mapping: record tiers (0..4, the normalized Borg/Alibaba
bands) land on ``PRIORITY_LADDER`` as pod priorities.  This makes trace
streams priority-DIVERSE — unlike the synthetic churn, windows are not
priority-flat, which is exactly the workload property the ROADMAP item
wanted on record.  (Trace replay runs with preemption disabled by
default: a preemption-armed trace replay is bounded by
``KSIM_REPLAY_CMAX``/``VMAX`` and may legitimately discard segments —
docs/scenario.md.)

Arrival mapping: the records' arrival span is divided into a fixed
tick chosen so the stream averages ``ops_per_step`` pod events per
step; each record's create lands at its arrival step and its delete at
``arrival + lifetime``'s step.  A fixed tick — not a fixed batch —
preserves the empirical burstiness: a quiet hour is many small steps,
an arrival spike is one huge step.

``trace_operations`` is the one-call surface (parse -> resample ->
compile) and wraps the whole ingestion in the ``scenario.ingest`` trace
span.  Everything here is stdlib at import time; the ``Operation``
dataclass imports lazily (scenario.runner pulls the scheduler stack).
"""

from __future__ import annotations

import os
import re
from typing import TYPE_CHECKING, Iterable, Sequence

from ksim_tpu.obs import TRACE
from ksim_tpu.traces.resample import StreamSelector
from ksim_tpu.traces.schema import TraceBoundExceeded, TraceError, TraceRecord

if TYPE_CHECKING:  # pragma: no cover - typing only
    from ksim_tpu.scenario.runner import Operation

__all__ = ["PRIORITY_LADDER", "TRACE_FORMATS", "compile_trace", "trace_operations"]

#: Pod ``spec.priority`` values per normalized tier (schema.py): free /
#: best-effort batch / mid / production / monitoring.  Far below the
#: system-class range (state/priorities.py) on purpose.
PRIORITY_LADDER: tuple[int, ...] = (0, 1_000, 5_000, 10_000, 100_000)

#: Registered parser entrypoints (the ``format:`` vocabulary of the
#: scenario spec's ``source.trace`` section).  Values are import paths
#: resolved lazily so this module stays import-light.
TRACE_FORMATS: tuple[str, ...] = ("borg", "alibaba")

_NAME_RE = re.compile(r"[^a-z0-9.-]+")

# Node-shape menu for the synthesized universe (the trace tables
# describe workloads, not machines): sizes drawn seed-deterministically,
# zones round-robin so topology plugins have real strata to score.
_NODE_CORES = (8, 16, 32)
_NODE_MEM_GI = (32, 64)
_ZONES = ("zone-a", "zone-b", "zone-c")


def _parser(fmt: str):
    if fmt == "borg":
        from ksim_tpu.traces.borg import parse_borg

        return parse_borg
    if fmt == "alibaba":
        from ksim_tpu.traces.alibaba import parse_alibaba

        return parse_alibaba
    raise TraceError(
        f"unknown trace format {fmt!r} (supported: {list(TRACE_FORMATS)})"
    )


def _mk_node(rng, name: str, zone: str) -> dict:
    alloc = {
        "cpu": str(rng.choice(_NODE_CORES)),
        "memory": f"{rng.choice(_NODE_MEM_GI)}Gi",
        "pods": "110",
        "ephemeral-storage": "100Gi",
    }
    return {
        "apiVersion": "v1",
        "kind": "Node",
        "metadata": {
            "name": name,
            "labels": {
                "kubernetes.io/hostname": name,
                "topology.kubernetes.io/zone": zone,
            },
        },
        "spec": {},
        "status": {"allocatable": dict(alloc), "capacity": dict(alloc)},
    }


def _mk_pod(name: str, rec: TraceRecord) -> dict:
    return {
        "apiVersion": "v1",
        "kind": "Pod",
        "metadata": {
            "name": name,
            "namespace": "default",
            "labels": {"app": rec.kind, "trace-tier": str(rec.tier)},
        },
        "spec": {
            "priority": PRIORITY_LADDER[rec.tier],
            "containers": [
                {
                    "name": "main",
                    "image": "trace",
                    "resources": {
                        "requests": {
                            "cpu": f"{rec.cpu_milli}m",
                            "memory": f"{rec.mem_mib}Mi",
                        }
                    },
                }
            ],
        },
        "status": {},
    }


def _pod_name(seq: int, rec: TraceRecord) -> str:
    san = _NAME_RE.sub("-", rec.name.lower()).strip("-.")[:24] or "task"
    return f"p{seq:05d}-{san}"


def _validate_compile_args(
    records: Sequence[TraceRecord], n_nodes: int, ops_per_step: int
) -> None:
    if n_nodes <= 0:
        raise TraceError("n_nodes must be positive")
    if ops_per_step <= 0:
        raise TraceError("ops_per_step must be positive")
    if not records:
        raise TraceError("trace compiled to zero records")


def _node_ops(n_nodes: int, seed: int) -> "list[Operation]":
    """The step-0 node bootstrap: the whole fleet, sizes drawn
    seed-deterministically in node-index order (the rng draw SEQUENCE
    is part of the byte-identity contract)."""
    import random

    from ksim_tpu.scenario.runner import Operation

    rng = random.Random(seed)
    return [
        Operation(
            step=0,
            op="create",
            kind="nodes",
            obj=_mk_node(rng, f"node-{i}", _ZONES[i % len(_ZONES)]),
        )
        for i in range(n_nodes)
    ]


class _EventLayout:
    """The (step, phase, seq) grid pod events sort on, factored out of
    ``compile_trace`` so the streaming producer (traces/stream.py) can
    materialize the SAME operation list window-by-window: keys are tiny
    tuples computed up front (O(selected events)), operations are built
    one window at a time from the key order.  ``records`` must already
    be in resample's sorted order — ``seq`` indexes into it and names
    the pods."""

    def __init__(self, records: Sequence[TraceRecord], ops_per_step: int) -> None:
        self.records = records
        self.t0 = min(r.arrival_s for r in records)
        span = max(r.arrival_s for r in records) - self.t0
        n_pod_events = sum(2 if r.lifetime_s > 0 else 1 for r in records)
        self.n_steps = max(1, round(n_pod_events / ops_per_step))
        self.tick = (span / self.n_steps) or 1.0

    def _step_of(self, t: float, horizon: int) -> int:
        return 1 + min(int((t - self.t0) / self.tick), horizon)

    def keys(self) -> "list[tuple[int, int, int]]":
        """Sorted (step, phase, seq) keys: creates (phase 0) in arrival
        order, then deletes (phase 1) in end-time order — a same-step
        create+delete stays a well-formed net no-op for the window
        parser."""
        keyed: list[tuple[int, int, int]] = []
        for seq, rec in enumerate(self.records):
            create_step = self._step_of(rec.arrival_s, self.n_steps - 1)
            keyed.append((create_step, 0, seq))
            if rec.lifetime_s > 0:
                # A delete never precedes its create; ends clamp to ONE
                # step past the creation horizon, so a pod born in the
                # last step still lives for a scheduling pass before it
                # leaves.
                del_step = max(
                    self._step_of(rec.arrival_s + rec.lifetime_s, self.n_steps),
                    create_step,
                )
                keyed.append((del_step, 1, seq))
        keyed.sort()
        return keyed

    def materialize(self, key: "tuple[int, int, int]") -> "Operation":
        from ksim_tpu.scenario.runner import Operation

        step, phase, seq = key
        rec = self.records[seq]
        name = _pod_name(seq, rec)
        if phase == 0:
            return Operation(step=step, op="create", kind="pods", obj=_mk_pod(name, rec))
        return Operation(
            step=step, op="delete", kind="pods", name=name, namespace="default"
        )


def compile_trace(
    records: Sequence[TraceRecord],
    *,
    n_nodes: int,
    seed: int = 0,
    ops_per_step: int = 100,
) -> "list[Operation]":
    """Lower sorted records to the runner's ``Operation`` list: the
    step-0 node bootstrap, then each record's create (and delete, when
    its lifetime is known) on the fixed arrival-time grid."""
    _validate_compile_args(records, n_nodes, ops_per_step)
    ops = _node_ops(n_nodes, seed)
    layout = _EventLayout(records, ops_per_step)
    ops.extend(layout.materialize(k) for k in layout.keys())
    return ops


def trace_operations(
    source: "str | os.PathLike | Iterable[str]",
    fmt: str,
    *,
    nodes: int,
    max_events: int = 0,
    seed: int = 0,
    ops_per_step: int = 100,
    source_nodes: "int | None" = None,
    event_bound: int = 0,
    node_bound: int = 0,
) -> "list[Operation]":
    """The one-call ingestion surface: parse ``source`` with the ``fmt``
    parser, resample to the node count / event budget, compile to the
    operation stream — all inside a ``scenario.ingest`` span so the
    ingestion cost shows up on the same timeline as the replay it
    feeds.  ``event_bound``/``node_bound`` (0 = unbounded) arm EARLY
    refusal: the single-pass selector raises
    :class:`~ksim_tpu.traces.schema.TraceBoundExceeded` the moment the
    compiled size provably passes the bound, so an oversized source
    stops costing bytes mid-read instead of after full parse+compile
    (the jobs plane maps it to HTTP 413)."""
    with TRACE.span("scenario.ingest", format=fmt, nodes=nodes) as span:
        if node_bound and nodes > node_bound:
            raise TraceBoundExceeded("nodes", node_bound, nodes)
        selector = StreamSelector(
            seed=seed,
            max_events=max_events,
            target_nodes=nodes if source_nodes else None,
            source_nodes=source_nodes,
            event_bound=event_bound,
            base_events=nodes,
        )
        selector.feed_all(_parser(fmt)(source))
        records = selector.finish()
        ops = compile_trace(
            records, n_nodes=nodes, seed=seed, ops_per_step=ops_per_step
        )
        span.set(records=len(records), ops=len(ops))
        return ops
