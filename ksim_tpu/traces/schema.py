"""Normalized trace records: the one shape every parser lands on.

Public cluster traces disagree about everything — file format (the
Google ClusterData 2019 collection/instance events are JSONL, the
Alibaba cluster-trace-v2018 tables are headerless CSV), time units
(microseconds vs seconds), resource units (fractions of the largest
machine vs centi-cores vs percent of machine memory), and priority
vocabularies (Borg's 0..450 tier bands vs Alibaba's task classes).
The parsers (``borg.py`` / ``alibaba.py``) absorb those differences and
emit this ONE record per workload item; ``resample.py`` and
``compile.py`` never see a format again.

The normalized fields:

- ``name``      — stable identity from the trace (job/task/container
  id).  The compiler never reuses a pod name even when the trace
  resubmits an identity (name reuse is a replay fallback class —
  engine/replay.py ``pod_name_reuse``/``backoff_name_reuse``).
- ``arrival_s`` — seconds since trace start (floats; parsers convert).
- ``cpu_milli`` / ``mem_mib`` — the request, in Kubernetes-exact units
  (millicores / MiB) so quantity lowering stays exact on the device
  path (the ``inexact_units`` fallback class can never fire).
- ``lifetime_s``— seconds until the workload leaves (the compiler emits
  the delete); ``0`` = unknown/forever (no delete is emitted).
- ``tier``      — the normalized priority band ``0..4`` (free /
  best-effort batch / mid / production / monitoring), mapped by each
  parser from its native vocabulary.  ``compile.py`` lands tiers on
  ``PRIORITY_LADDER`` as pod ``spec.priority`` values.
- ``priority``  — the NATIVE priority value, kept for evidence and
  golden tests.
- ``kind``      — workload class: ``"batch"`` or ``"service"`` (becomes
  the pod's ``app`` label, the same label the synthetic churn uses for
  its feature mix).

This module is stdlib-only at import time (machine-checked:
tools/ksimlint import-boundary covers ``ksim_tpu/traces/``).
"""

from __future__ import annotations

from dataclasses import dataclass

__all__ = [
    "TraceRecord",
    "TraceError",
    "TraceParseError",
    "TraceBoundExceeded",
    "TIER_COUNT",
]

#: Normalized priority bands (see ``tier`` above).
TIER_COUNT = 5


class TraceError(ValueError):
    """Any trace-plane failure a caller can act on (bad reference,
    unreadable file, oversized input).  A ``ValueError`` so the spec
    layer can re-raise it as a ``ScenarioSpecError`` (HTTP 400)."""


class TraceParseError(TraceError):
    """A malformed row.  Carries the 1-based line number — parsers are
    strict by construction: a silently-skipped row would make the
    compiled stream depend on which corruption a copy of the trace
    happens to carry, and the whole point of the plane is deterministic
    replay."""

    def __init__(self, line: int, message: str) -> None:
        super().__init__(f"line {line}: {message}")
        self.line = line


class TraceBoundExceeded(TraceError):
    """A tenant ingest bound was provably exceeded MID-READ — raised by
    the streaming selector the moment the compiled-event floor passes
    the caller's limit, so oversized (or gzip-bomb-sized) traces stop
    costing bytes immediately instead of after full parse+compile.
    Carries machine-readable fields; the jobs plane maps it onto its
    own limit vocabulary (``KSIM_JOBS_MAX_EVENTS`` / ``_MAX_NODES``)
    and HTTP 413."""

    def __init__(self, kind: str, limit: int, observed: int) -> None:
        super().__init__(
            f"trace ingest exceeds the {kind} bound: at least {observed} > {limit}"
        )
        self.kind = kind  # "events" | "nodes"
        self.limit = limit
        self.observed = observed


@dataclass(frozen=True)
class TraceRecord:
    """One normalized workload item (see module docstring)."""

    name: str
    arrival_s: float
    cpu_milli: int
    mem_mib: int
    lifetime_s: float = 0.0
    tier: int = 0
    priority: int = 0
    kind: str = "batch"  # "batch" | "service"

    def __post_init__(self) -> None:
        if not self.name:
            raise TraceError("trace record needs a name")
        if not 0 <= self.tier < TIER_COUNT:
            raise TraceError(f"tier {self.tier} outside 0..{TIER_COUNT - 1}")
        if self.kind not in ("batch", "service"):
            raise TraceError(f"unknown workload kind {self.kind!r}")
        if self.cpu_milli < 0 or self.mem_mib < 0:
            raise TraceError("negative resource request")
