"""Runtime registry of device-kernel functions.

``@device_kernel`` marks the functions whose bodies execute under a jax
trace — the segment program (engine/replay.py ``_segment_fn``) and the
sequential-commit / batch programs (engine/core.py).  The decorator is
an identity marker: it records the function (and which of its
parameters are jit-STATIC, mirroring the adjacent ``jax.jit``
``static_argnums``) and returns it unchanged, so it composes under
``@partial(jax.jit, ...)`` with zero runtime cost.

Two consumers:

- ``tools/ksimlint``'s kernel-purity rule finds the decorator in the
  AST and checks the marked bodies for host effects and
  f32-determinism hazards (docs/lint.md "Kernel purity") — the
  decorator is the contract declaration, the analyzer the enforcement;
- tests/test_lint.py cross-checks this runtime registry against the
  analyzer's AST view, so a kernel added without the marker (or marked
  but unregistered) cannot drift silently.

Stdlib-only by design: the registry must be importable (and the
analyzer must be able to reason about it) without touching jax.
"""

from __future__ import annotations

from typing import Callable

#: Every registered kernel function, in import order.
KERNELS: list[Callable] = []


def device_kernel(fn: "Callable | None" = None, *, static: tuple[str, ...] = ()):
    """Mark ``fn`` as a device kernel.  ``static`` names the parameters
    that are jit-static (trace-time Python values — branching on them
    is legal inside the body); it must mirror the ``static_argnums`` of
    the enclosing ``jax.jit``.  Usable bare or with arguments::

        @partial(jax.jit, static_argnums=(0, 1))
        @device_kernel(static=("st", "prog"))
        def _segment_fn(st, prog, const, ev, state0): ...
    """

    def mark(f: Callable) -> Callable:
        f.__ksim_kernel_static__ = tuple(static)
        KERNELS.append(f)
        return f

    return mark(fn) if fn is not None else mark
