"""Process-wide compiled-executable cache evidence + compile-once gate.

jax's jit cache already reuses a compiled executable for identical
(statics, input avals) within one process — but it is silent (no
hit/miss evidence reaches the bench JSON or /api/v1/metrics) and it
does not serialize FIRST calls: two tenant jobs hitting the same shape
rung concurrently can both pay the multi-second XLA trace+compile
before either lands in the cache.  This module adds the missing layer
for the job plane (ksim_tpu/jobs): a process-global registry keyed by
the bucketed shape ladder + profile token that

- counts ``hits``/``misses`` per rung (a miss = the first dispatch of a
  key, i.e. the one that compiles) and records which OWNERS (tenant
  jobs, via the scoped trace plane's ``job`` tag) used each rung — the
  "compile once, serve every tenant on that rung" claim becomes
  machine-checkable straight from the bench record
  (``shared_rungs``/``shared_single_compile_rungs``);
- serializes the first call per key: one leader runs the compiling
  dispatch, concurrent same-rung callers WAIT (bounded) for it, then
  dispatch against jax's now-warm jit cache.  A leader that dies
  removes its entry (``aborts``) so the next caller retries as leader
  rather than deadlocking behind a tombstone.

Round 15 adds the ON-DISK layer (ISSUE 11 "persistent executables"):
``run`` takes an optional ``disk`` spec — a duck-typed handle the
CALLER builds (engine/replay.py ``_aot_disk_spec``) carrying the entry
``path``, a stable identity ``token`` (shape-ladder rung + profile
token + jaxlib version + backend), and ``load``/``invoke``/
``serialize`` callables.  A leader first tries load-from-disk (a
deserialized ``jax.export`` executable skips XLA compilation
entirely); corrupt, version-mismatched or un-invokable entries are
unlinked and counted with a ``compilecache.evict`` trace event, then
the leader falls back to compiling and best-effort persists the fresh
executable (atomic tmp+rename).  Followers reuse the leader's
deserialized executable — after a disk hit jax's jit cache was never
warmed, so dispatching ``fn`` again would re-pay the compile the disk
hit just skipped.

The module stays stdlib-only (json/os/zlib): all jax calls live inside
the caller's ``disk`` callables, so nothing here ever imports jax.
"""

from __future__ import annotations

import json
import logging
import os
import threading
import zlib
from typing import Any, Callable

from ksim_tpu.obs import TRACE, register_provider

logger = logging.getLogger(__name__)

__all__ = ["CompileCache", "COMPILE_CACHE"]

#: Bound on the follower wait for a leader's in-flight compile.  The
#: replay watchdog (KSIM_REPLAY_WATCHDOG_S, default 300 s — "generous:
#: first dispatch includes XLA compile") covers the same window from
#: the dispatch side, so a stuck leader degrades through the existing
#: device_error ladder instead of wedging followers forever.
_WAIT_DEFAULT_S = 300.0


class _Entry:
    """One shape rung's state: the leader-compiled gate + per-key
    evidence.  Mutated only under the owning cache's lock (the ready
    Event is the one cross-thread signal and is safe bare)."""

    __slots__ = ("ready", "hits", "owners", "exec_obj")

    def __init__(self) -> None:
        self.ready = threading.Event()
        self.hits = 0
        self.owners: set = set()
        # The leader's disk-loaded executable (None when the leader
        # compiled via fn — jax's jit cache is warm then and followers
        # dispatch fn directly).
        self.exec_obj: Any = None


class CompileCache:
    """Counting, compile-once-serializing front of the jit cache."""

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._entries: dict[Any, _Entry] = {}  # guarded-by: _lock
        self.hits = 0  # guarded-by: _lock
        self.misses = 0  # guarded-by: _lock
        self.waits = 0  # guarded-by: _lock (followers that blocked on a leader)
        self.aborts = 0  # guarded-by: _lock (leader dispatches that raised)
        self.disk_hits = 0  # guarded-by: _lock (leaders warm-started from disk)
        self.disk_misses = 0  # guarded-by: _lock (leaders that found no entry)
        self.disk_stores = 0  # guarded-by: _lock (fresh executables persisted)
        self.disk_evictions = 0  # guarded-by: _lock (corrupt/mismatched unlinks)
        self.disk_prewarmed = 0  # guarded-by: _lock (startup-deserialized entries)
        self.disk_speculative = 0  # guarded-by: _lock (rescan-loaded peers' entries)

    def run(
        self,
        key: Any,
        fn: Callable[[], Any],
        *,
        owner: "str | None" = None,
        wait_s: float = _WAIT_DEFAULT_S,
        disk: Any = None,
    ) -> Any:
        """Run ``fn`` (the jitted dispatch) under the compile-once gate.

        The first caller of ``key`` is the LEADER: it counts a miss and
        runs ``fn`` directly — jax traces+compiles, then caches.  Every
        later caller counts a hit; if the leader's first call is still
        in flight it waits (up to ``wait_s``) before dispatching, so a
        rung is compiled once no matter how many tenants race onto it.
        A leader that raises removes the entry and re-raises — the next
        caller becomes the new leader (counted in ``aborts``).

        ``disk`` (optional) is the persistent layer's handle for this
        key: ``.path`` (entry file), ``.token`` (the stable identity
        string the header must match), ``.load(blob) -> exec_obj``,
        ``.invoke(exec_obj) -> result`` and ``.serialize() -> bytes |
        None``.  A leader tries disk first (warm restart: no compile);
        any corruption, token mismatch, failed deserialize or failed
        invoke evicts the entry (``compilecache.evict``) and degrades
        to the compile path, after which the fresh executable is
        persisted best-effort.  Followers behind a disk-hit leader
        reuse its deserialized executable — ``fn`` would re-compile,
        jax's jit cache was never warmed on that path."""
        with self._lock:
            ent = self._entries.get(key)
            if ent is None:
                ent = self._entries[key] = _Entry()
                if owner is not None:
                    ent.owners.add(owner)
                self.misses += 1
                leader = True
            else:
                ent.hits += 1
                if owner is not None:
                    ent.owners.add(owner)
                self.hits += 1
                leader = False
            ready = ent.ready
        if leader:
            if disk is not None:
                exec_obj = self._disk_load(disk)
                if exec_obj is not None:
                    try:
                        out = disk.invoke(exec_obj)
                    except Exception:
                        # Deserialized fine but will not run (e.g. a
                        # platform the blob was not exported for):
                        # evict and fall through to the compile path.
                        self._evict(disk, "exec_failed")
                    else:
                        with self._lock:
                            ent.exec_obj = exec_obj
                        ready.set()
                        return out
            try:
                out = fn()
            except BaseException:
                with self._lock:
                    self.aborts += 1
                    self._entries.pop(key, None)
                # Wake any followers parked on this generation; they
                # dispatch themselves (jax may still have cached a
                # partial trace — correctness is jax's, we only lose
                # one dedupe opportunity).
                ready.set()
                raise
            ready.set()
            if disk is not None:
                self._disk_store(disk)
            return out
        if not ready.is_set():
            with self._lock:
                self.waits += 1
            ready.wait(wait_s)
        if disk is not None:
            with self._lock:
                live = self._entries.get(key)
                exec_obj = live.exec_obj if live is not None else None
            if exec_obj is not None:
                return disk.invoke(exec_obj)
        return fn()

    def note_prewarmed(self, n: int) -> None:
        """Count ``n`` entries deserialized by the startup prewarm pass
        (engine/replay.py ``prewarm_aot_cache``, ``KSIM_AOT_PREWARM``)
        — evidence only; the entries themselves live with the caller."""
        with self._lock:
            self.disk_prewarmed += n

    def note_speculative(self, n: int) -> None:
        """Count ``n`` entries the ``KSIM_AOT_PREWARM=2`` rescan loop
        loaded AFTER startup — executables another fleet worker stored
        (possibly for rungs this process never dispatched), now warm
        here too.  Same evidence-only contract as ``note_prewarmed``."""
        with self._lock:
            self.disk_speculative += n

    @staticmethod
    def read_disk_entry(path: str) -> "tuple[str, bytes] | None":
        """Non-destructively parse one on-disk entry: validate the
        header shape and blob CRC, return ``(stored token, blob)`` —
        or None for unreadable/corrupt files.  Unlike ``_disk_load``
        this NEVER evicts and does no token comparison: it serves scans
        (the prewarm pass) that do not know which rung identity the
        entry belongs to; eviction authority stays with the dispatch
        path, where the expected token is known."""
        try:
            with open(path, "rb") as f:
                header, sep, blob = f.read().partition(b"\n")
        except OSError:
            return None
        try:
            meta = json.loads(header)
            crc = int(meta.get("crc", -1))
            token = meta.get("key")
            ok_shape = bool(sep) and meta.get("v") == 1
        except (ValueError, TypeError):
            return None
        if (
            not ok_shape
            or not isinstance(token, str)
            or (zlib.crc32(blob) & 0xFFFFFFFF) != crc
        ):
            return None
        return token, blob

    # -- the persistent layer (leader-only helpers) ----------------------

    def _disk_load(self, disk: Any) -> Any:
        """entry file -> deserialized executable, or None (miss /
        evicted).  Validates the one-line JSON header (version, the
        caller's identity token, blob CRC) before handing bytes to
        ``disk.load`` — a stale jaxlib or a hash-colliding path must
        never reach the deserializer."""
        try:
            with open(disk.path, "rb") as f:
                header, sep, blob = f.read().partition(b"\n")
        except OSError:
            with self._lock:
                self.disk_misses += 1
            return None
        try:
            meta = json.loads(header)
            crc = int(meta.get("crc", -1))
            ok_shape = bool(sep) and meta.get("v") == 1
        except (ValueError, TypeError):
            self._evict(disk, "corrupt")
            return None
        if not ok_shape or (zlib.crc32(blob) & 0xFFFFFFFF) != crc:
            self._evict(disk, "corrupt")
            return None
        if meta.get("key") != disk.token:
            self._evict(disk, "key_mismatch")
            return None
        try:
            exec_obj = disk.load(blob)
        except Exception:
            self._evict(disk, "deserialize_failed")
            return None
        with self._lock:
            self.disk_hits += 1
        return exec_obj

    def _disk_store(self, disk: Any) -> None:
        """Best-effort persist of the leader's fresh executable —
        serialization or I/O failure costs only the NEXT process's
        warm start, never this dispatch."""
        try:
            blob = disk.serialize()
            if blob is None:
                return  # the caller deemed this plan non-exportable
            header = json.dumps({
                "v": 1, "key": disk.token,
                "crc": zlib.crc32(blob) & 0xFFFFFFFF,
            }).encode()
            os.makedirs(os.path.dirname(disk.path) or ".", exist_ok=True)
            tmp = f"{disk.path}.tmp{os.getpid()}"
            with open(tmp, "wb") as f:
                f.write(header + b"\n" + blob)
                f.flush()
                os.fsync(f.fileno())
            os.replace(tmp, disk.path)
        except Exception:
            logger.debug("compile cache: could not persist %s", disk.path,
                         exc_info=True)
            return
        with self._lock:
            self.disk_stores += 1

    def _evict(self, disk: Any, reason: str) -> None:
        """Unlink an unusable entry and count it — the evidence trail
        behind the "discarded gracefully" contract."""
        try:
            os.unlink(disk.path)
        except OSError:
            pass
        with self._lock:
            self.disk_evictions += 1
        TRACE.event("compilecache.evict", reason=reason, path=disk.path)

    def snapshot(self) -> dict:
        """JSON-ready evidence (the ``compile_cache`` section of
        /api/v1/metrics and the bench JSON): aggregate counters plus
        the cross-tenant sharing proof — ``shared_rungs`` = keys used
        by >= 2 distinct owners, ``shared_single_compile_rungs`` = the
        subset that also compiled exactly once (present entries never
        re-miss; an aborted leader removes its key, so every LIVE
        entry's compile count is exactly 1)."""
        with self._lock:
            rungs = len(self._entries)
            shared = sum(1 for e in self._entries.values() if len(e.owners) >= 2)
            shared_hot = sum(
                1
                for e in self._entries.values()
                if len(e.owners) >= 2 and e.hits > 0
            )
            max_owners = max(
                (len(e.owners) for e in self._entries.values()), default=0
            )
            return {
                "hits": self.hits,
                "misses": self.misses,
                "waits": self.waits,
                "aborts": self.aborts,
                "disk_hits": self.disk_hits,
                "disk_misses": self.disk_misses,
                "disk_stores": self.disk_stores,
                "disk_evictions": self.disk_evictions,
                "disk_prewarmed": self.disk_prewarmed,
                "disk_speculative": self.disk_speculative,
                "rungs": rungs,
                "shared_rungs": shared,
                "shared_single_compile_rungs": shared_hot,
                "max_owners_per_rung": max_owners,
            }

    def reset(self) -> None:
        """Drop entries and counters (tests; bench children start cold
        by construction — fresh process — so production never calls
        this)."""
        with self._lock:
            self._entries.clear()
            self.hits = 0
            self.misses = 0
            self.waits = 0
            self.aborts = 0
            self.disk_hits = 0
            self.disk_misses = 0
            self.disk_stores = 0
            self.disk_evictions = 0
            self.disk_prewarmed = 0
            self.disk_speculative = 0


#: The process-wide cache every segment dispatch consults — one compile
#: per shape rung regardless of how many runners/tenants share the
#: process.  engine/replay.py owns the key construction.
COMPILE_CACHE = CompileCache()

# Self-register as a /api/v1/metrics evidence provider: any process
# that imports this module (the replay executor, the HTTP server)
# serves the rung counters live.  obs is stdlib-only like this module,
# and never imports back — no cycle.
register_provider("compile_cache", COMPILE_CACHE.snapshot)
