"""Process-wide compiled-executable cache evidence + compile-once gate.

jax's jit cache already reuses a compiled executable for identical
(statics, input avals) within one process — but it is silent (no
hit/miss evidence reaches the bench JSON or /api/v1/metrics) and it
does not serialize FIRST calls: two tenant jobs hitting the same shape
rung concurrently can both pay the multi-second XLA trace+compile
before either lands in the cache.  This module adds the missing layer
for the job plane (ksim_tpu/jobs): a process-global registry keyed by
the bucketed shape ladder + profile token that

- counts ``hits``/``misses`` per rung (a miss = the first dispatch of a
  key, i.e. the one that compiles) and records which OWNERS (tenant
  jobs, via the scoped trace plane's ``job`` tag) used each rung — the
  "compile once, serve every tenant on that rung" claim becomes
  machine-checkable straight from the bench record
  (``shared_rungs``/``shared_single_compile_rungs``);
- serializes the first call per key: one leader runs the compiling
  dispatch, concurrent same-rung callers WAIT (bounded) for it, then
  dispatch against jax's now-warm jit cache.  A leader that dies
  removes its entry (``aborts``) so the next caller retries as leader
  rather than deadlocking behind a tombstone.

The module is stdlib-only: callers (engine/replay.py ``_device_exec``)
build the key from hashable statics + the input trees' dtype/shape
signature, so nothing here ever imports jax.
"""

from __future__ import annotations

import threading
from typing import Any, Callable

__all__ = ["CompileCache", "COMPILE_CACHE"]

#: Bound on the follower wait for a leader's in-flight compile.  The
#: replay watchdog (KSIM_REPLAY_WATCHDOG_S, default 300 s — "generous:
#: first dispatch includes XLA compile") covers the same window from
#: the dispatch side, so a stuck leader degrades through the existing
#: device_error ladder instead of wedging followers forever.
_WAIT_DEFAULT_S = 300.0


class _Entry:
    """One shape rung's state: the leader-compiled gate + per-key
    evidence.  Mutated only under the owning cache's lock (the ready
    Event is the one cross-thread signal and is safe bare)."""

    __slots__ = ("ready", "hits", "owners")

    def __init__(self) -> None:
        self.ready = threading.Event()
        self.hits = 0
        self.owners: set = set()


class CompileCache:
    """Counting, compile-once-serializing front of the jit cache."""

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._entries: dict[Any, _Entry] = {}  # guarded-by: _lock
        self.hits = 0  # guarded-by: _lock
        self.misses = 0  # guarded-by: _lock
        self.waits = 0  # guarded-by: _lock (followers that blocked on a leader)
        self.aborts = 0  # guarded-by: _lock (leader dispatches that raised)

    def run(
        self,
        key: Any,
        fn: Callable[[], Any],
        *,
        owner: "str | None" = None,
        wait_s: float = _WAIT_DEFAULT_S,
    ) -> Any:
        """Run ``fn`` (the jitted dispatch) under the compile-once gate.

        The first caller of ``key`` is the LEADER: it counts a miss and
        runs ``fn`` directly — jax traces+compiles, then caches.  Every
        later caller counts a hit; if the leader's first call is still
        in flight it waits (up to ``wait_s``) before dispatching, so a
        rung is compiled once no matter how many tenants race onto it.
        A leader that raises removes the entry and re-raises — the next
        caller becomes the new leader (counted in ``aborts``)."""
        with self._lock:
            ent = self._entries.get(key)
            if ent is None:
                ent = self._entries[key] = _Entry()
                if owner is not None:
                    ent.owners.add(owner)
                self.misses += 1
                leader = True
            else:
                ent.hits += 1
                if owner is not None:
                    ent.owners.add(owner)
                self.hits += 1
                leader = False
            ready = ent.ready
        if leader:
            try:
                out = fn()
            except BaseException:
                with self._lock:
                    self.aborts += 1
                    self._entries.pop(key, None)
                # Wake any followers parked on this generation; they
                # dispatch themselves (jax may still have cached a
                # partial trace — correctness is jax's, we only lose
                # one dedupe opportunity).
                ready.set()
                raise
            ready.set()
            return out
        if not ready.is_set():
            with self._lock:
                self.waits += 1
            ready.wait(wait_s)
        return fn()

    def snapshot(self) -> dict:
        """JSON-ready evidence (the ``compile_cache`` section of
        /api/v1/metrics and the bench JSON): aggregate counters plus
        the cross-tenant sharing proof — ``shared_rungs`` = keys used
        by >= 2 distinct owners, ``shared_single_compile_rungs`` = the
        subset that also compiled exactly once (present entries never
        re-miss; an aborted leader removes its key, so every LIVE
        entry's compile count is exactly 1)."""
        with self._lock:
            rungs = len(self._entries)
            shared = sum(1 for e in self._entries.values() if len(e.owners) >= 2)
            shared_hot = sum(
                1
                for e in self._entries.values()
                if len(e.owners) >= 2 and e.hits > 0
            )
            max_owners = max(
                (len(e.owners) for e in self._entries.values()), default=0
            )
            return {
                "hits": self.hits,
                "misses": self.misses,
                "waits": self.waits,
                "aborts": self.aborts,
                "rungs": rungs,
                "shared_rungs": shared,
                "shared_single_compile_rungs": shared_hot,
                "max_owners_per_rung": max_owners,
            }

    def reset(self) -> None:
        """Drop entries and counters (tests; bench children start cold
        by construction — fresh process — so production never calls
        this)."""
        with self._lock:
            self._entries.clear()
            self.hits = 0
            self.misses = 0
            self.waits = 0
            self.aborts = 0


#: The process-wide cache every segment dispatch consults — one compile
#: per shape rung regardless of how many runners/tenants share the
#: process.  engine/replay.py owns the key construction.
COMPILE_CACHE = CompileCache()

# Self-register as a /api/v1/metrics evidence provider: any process
# that imports this module (the replay executor, the HTTP server)
# serves the rung counters live.  obs is stdlib-only like this module,
# and never imports back — no cycle.
from ksim_tpu.obs import register_provider  # noqa: E402  (after the global)

register_provider("compile_cache", COMPILE_CACHE.snapshot)
