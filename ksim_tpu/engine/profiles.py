"""Default plugin set for the engine.

The subset of the upstream default profile implemented so far, with the
upstream default score weights (upstream pkg/scheduler/apis/config/v1/
default_plugins.go getDefaultPlugins).  Grows as plugins land; the full
KubeSchedulerConfiguration-driven profile compiler lives in sched/config.
"""

from __future__ import annotations

from ksim_tpu.engine.core import ScoredPlugin
from ksim_tpu.plugins.imagelocality import ImageLocality
from ksim_tpu.plugins.interpodaffinity import InterPodAffinity
from ksim_tpu.plugins.nodeaffinity import NodeAffinity
from ksim_tpu.plugins.nodename import NodeName
from ksim_tpu.plugins.nodeports import NodePorts
from ksim_tpu.plugins.nodeunschedulable import NodeUnschedulable
from ksim_tpu.plugins.noderesources import (
    NodeResourcesBalancedAllocation,
    NodeResourcesFit,
)
from ksim_tpu.plugins.podtopologyspread import PodTopologySpread
from ksim_tpu.plugins.tainttoleration import TaintToleration
from ksim_tpu.plugins.volumes import (
    NodeVolumeLimits,
    VolumeBinding,
    VolumeRestrictions,
    VolumeZone,
)
from ksim_tpu.state.featurizer import FeaturizedSnapshot


def default_plugins(feats: FeaturizedSnapshot) -> tuple[ScoredPlugin, ...]:
    """Upstream default-profile weights: BalancedAllocation 1, Fit 1,
    ImageLocality 1, NodeAffinity 2, PodTopologySpread 2,
    InterPodAffinity 2, TaintToleration 3 (default_plugins.go)."""
    # Filter order follows upstream MultiPoint registration order
    # (default_plugins.go): NodeUnschedulable, NodeName, TaintToleration,
    # NodeAffinity, NodePorts, NodeResourcesFit, VolumeRestrictions,
    # NodeVolumeLimits, VolumeBinding, VolumeZone, PodTopologySpread,
    # InterPodAffinity — early-exit filter-result recording depends on it.
    vols = feats.aux["volumes"]
    return (
        ScoredPlugin(NodeUnschedulable(), score_enabled=False),
        ScoredPlugin(NodeName(), score_enabled=False),
        ScoredPlugin(TaintToleration(feats.aux["taints"]), weight=3),
        ScoredPlugin(NodeAffinity(), weight=2),
        ScoredPlugin(NodePorts(), score_enabled=False),
        ScoredPlugin(NodeResourcesFit(feats.resources), weight=1),
        ScoredPlugin(
            NodeResourcesBalancedAllocation(feats.resources),
            weight=1,
            filter_enabled=False,
        ),
        ScoredPlugin(VolumeRestrictions(vols), score_enabled=False),
        ScoredPlugin(NodeVolumeLimits(vols), score_enabled=False),
        ScoredPlugin(VolumeBinding(vols), score_enabled=False),
        ScoredPlugin(VolumeZone(vols), score_enabled=False),
        ScoredPlugin(PodTopologySpread(feats.aux["spread"]), weight=2),
        ScoredPlugin(InterPodAffinity(feats.aux["interpod"]), weight=2),
        ScoredPlugin(
            ImageLocality(feats.aux["imagelocality"]),
            weight=1,
            filter_enabled=False,
        ),
    )
