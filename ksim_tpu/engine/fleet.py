"""Fleet replay: S independent what-if trajectories, one vmapped dispatch.

The ROADMAP's "millions of users" shape is thousands of INDEPENDENT
scenario variants — policy sweeps, Monte-Carlo chaos, autoscaler tuning
— each a full churn trajectory.  Running them solo pays S times the
segment lowering and S times the dispatch latency for work that shares
one pod/node universe.  This module multiplexes them:

- Every lane is a COMPLETE solo stack — its own ClusterStore, its own
  SchedulerService, its own ReplayDriver (cache, breaker, counters) —
  so per-lane reconcile, per-lane fallback and per-lane evidence are
  the solo code paths verbatim (scenario/runner.py drives them).
- Lanes replaying the SAME base stream form the CONVERGENT COHORT: the
  cohort leader lowers each window ONCE (``ReplayDriver.prepare_segment``
  — the shared-universe, O(delta)-cached lowering), and one
  ``jax.vmap``-batched dispatch (``replay._fleet_exec`` →
  ``_fleet_segment_fn``) advances every cohort lane K steps.  Each
  lane's slice of the stacked outputs decodes and reconciles against
  that lane's own store, byte-identical to its solo run — the fleet
  parity lock.
- Per-lane deltas degrade per lane, never fleet-wide: a lane whose
  private fault plane (``KSIM_FLEET_FAULTS``) fires, whose reconcile
  rolls back, or whose stream diverges (per-lane op streams) leaves the
  cohort and continues on the ordinary SOLO device path — its own
  lowering, its own dispatch — while the cohort keeps amortizing.
  Divergence is detected by cursor drift: the byte-identical parity
  contract means equal cursors over the shared stream imply equal
  stores, so any lane that stops advancing in lockstep is split off
  (and a ``replay.fleet_lane_fallback`` event marks the timeline).

``KSIM_FLEET_DP=n`` lays the stacked lane axis over a ``dp``-mesh
(engine/sharding.py ``fleet_mesh``) so lanes spread across devices;
constants replicate.  The mesh is built lazily ON the dispatch worker
thread — never an unguarded main-thread backend init (the wedged-tunnel
containment, repo CLAUDE.md).
"""

from __future__ import annotations

import logging
import os
import threading
import time
from dataclasses import dataclass, field
from typing import Any

import jax

from ksim_tpu.errors import (
    DeviceUnavailableError,
    ReplayFallback,
    SimulatorError,
)
from ksim_tpu.faults import FaultPlane
from ksim_tpu.obs import TRACE
from ksim_tpu.engine.replay import ReplayParityError, _fleet_exec

logger = logging.getLogger(__name__)


def parse_fleet_faults(spec: str, n_lanes: int) -> dict[int, FaultPlane]:
    """Parse a ``KSIM_FLEET_FAULTS`` spec into per-lane fault planes.

    Syntax (docs/env.md): comma/semicolon-separated
    ``<lane>:<site>=<schedule>[@error]`` entries, the right-hand side
    exactly the ``KSIM_FAULTS`` grammar, e.g.
    ``"2:replay.dispatch=call:1;2:replay.lower=first:1"`` arms lane 2
    only.  Each listed lane gets its OWN ``FaultPlane`` instance checked
    next to the process-global ``FAULTS`` at the replay sites, so chaos
    lands on one trajectory while the rest of the fleet stays healthy.
    Malformed entries raise (a silently dropped lane spec would make a
    chaos sweep vacuously green, like ``KSIM_FAULTS`` itself)."""
    planes: dict[int, FaultPlane] = {}
    for part in spec.replace(";", ",").split(","):
        part = part.strip()
        if not part:
            continue
        lane_s, sep, rest = part.partition(":")
        if not sep or not lane_s.strip().isdigit():
            raise ValueError(
                f"KSIM_FLEET_FAULTS entry {part!r}: expected "
                f"<lane>:<site>=<schedule>"
            )
        lane = int(lane_s)
        if not 0 <= lane < n_lanes:
            raise ValueError(
                f"KSIM_FLEET_FAULTS entry {part!r}: lane {lane} outside "
                f"the fleet (0..{n_lanes - 1})"
            )
        planes.setdefault(lane, FaultPlane()).configure(rest)
    return planes


@dataclass
class FleetLane:
    """One trajectory's full solo stack plus its fleet bookkeeping."""

    idx: int
    runner: Any  # per-lane ScenarioRunner (store+service owner)
    driver: Any  # per-lane ReplayDriver
    keys: list  # sorted step keys of THIS lane's stream
    by_step: dict  # step -> list[Operation] (cohort lanes share the base dict)
    result: Any  # per-lane ScenarioResult
    faults: "FaultPlane | None" = None
    shared_stream: bool = True  # replays the base stream (cohort-eligible)
    i: int = 0  # cursor into keys
    done: bool = False  # a doneOperation step completed
    convergent: bool = True
    # The reason this lane degraded in the CURRENT round (fleet-lane
    # fallback evidence; cleared each round).
    round_reason: "str | None" = field(default=None, repr=False)

    @property
    def finished(self) -> bool:
        return self.done or self.i >= len(self.keys)


class FleetDriver:
    """Drives every lane to completion, multiplexing the convergent
    cohort through shared lowerings and vmapped group dispatches."""

    def __init__(self, lanes: list[FleetLane]) -> None:
        self.lanes = lanes
        _dp = os.environ.get("KSIM_FLEET_DP")
        self.dp: "int | None" = int(_dp) if _dp else None
        # Cohort dispatch mode.  The convergence invariant makes every
        # cohort lane's carry BYTE-IDENTICAL, so the default dispatches
        # the leader's segment program ONCE and fans the pulled outputs
        # out to every lane's decode + reconcile (each lane's own
        # verify_segment still independently proves its store against
        # the device view) — computing S identical trajectories would
        # be pure redundancy, and on CPU the vmapped program's batched
        # scatters make it MORE than S times slower (docs/scaling.md
        # "Fleet replay", the measured vmap tax).  KSIM_FLEET_VMAP=1
        # forces the genuinely lane-stacked vmapped program
        # (_fleet_segment_fn) — the parity lock runs it to prove the
        # kernels are lane-independent, and it is the path per-lane
        # deltas will ride (ROADMAP "fleet round 2").  A KSIM_FLEET_DP
        # mesh implies it (the dedupe program has no lane axis to lay
        # over dp).
        self.vmap_cohort = (
            os.environ.get("KSIM_FLEET_VMAP") == "1" or self.dp is not None
        )
        # Mesh state is touched from the dispatch worker (the build must
        # run behind the watchdog — jax.devices() on a wedged tunnel
        # hangs) and read by later workers, so it takes a real lock.
        self._mesh_lock = threading.Lock()
        # (dp, tp) -> Mesh: one entry per node-shard width the cohort's
        # plans have dispatched with (tp follows plan.statics.tp, round
        # 19 — the 2-D fleet lays lanes over dp AND node shards over tp).
        self._mesh: dict = {}  # guarded-by: _mesh_lock
        self._mesh_failed = False  # guarded-by: _mesh_lock
        # Fleet evidence counters (the churn_fleet bench rung and the
        # lock-check's lowered-once guard read them).  All fleet
        # orchestration runs on the main thread; the dispatch worker
        # below is side-effect-free on this object.
        self.shared_lowerings = 0  # guarded-by: main-thread
        self.group_dispatches = 0  # guarded-by: main-thread
        self.lane_fallbacks = 0  # guarded-by: main-thread
        self.divergences = 0  # guarded-by: main-thread

    # -- evidence ------------------------------------------------------------

    def stats(self) -> dict:
        total = sum(d.device_steps + d.fallback_steps for d in self._drivers())
        on_dev = sum(d.device_steps for d in self._drivers())
        return {
            "lanes": len(self.lanes),
            "cohort_mode": "vmap" if self.vmap_cohort else "dedupe",
            "shared_lowerings": self.shared_lowerings,
            "group_dispatches": self.group_dispatches,
            "lane_fallbacks": self.lane_fallbacks,
            "divergences": self.divergences,
            "convergent_lanes": sum(1 for ln in self.lanes if ln.convergent),
            # The lanes-on-device fraction: device-committed lane-steps
            # over all lane-steps (1.0 = every step of every trajectory
            # rode a device segment).
            "lanes_on_device": round(on_dev / total, 4) if total else None,
            "lane_device_steps": [d.device_steps for d in self._drivers()],
            "lane_fallback_steps": [d.fallback_steps for d in self._drivers()],
            "lane_lowerings": [len(d.lower_log) for d in self._drivers()],
        }

    def _drivers(self):
        return [ln.driver for ln in self.lanes]

    # -- the fleet loop ------------------------------------------------------

    def run(self) -> None:
        while True:
            active = [ln for ln in self.lanes if not ln.finished]
            if not active:
                return
            # Cooperative cancel (service round 4 (d)): every lane
            # runner carries the PARENT run's cancel flag, so one check
            # per round — the lane dispatch boundary — raises
            # RunCancelled before the next shared lowering; a cancel
            # landing later, mid-segment, aborts inside that lane's
            # reconcile transaction instead (per-lane rollback, the
            # solo semantics), and the exception ladders below
            # deliberately do not catch it.
            active[0].runner._check_cancelled()
            for ln in active:
                ln.round_reason = None
            cohort = [ln for ln in active if ln.convergent]
            solos = [ln for ln in active if not ln.convergent]
            if len(cohort) == 1:
                # A cohort of one gains nothing from the group path;
                # hand the lane the richer solo pipeline (prelower
                # overlap, dev-const reuse) for the rest of the run.
                cohort[0].convergent = False
                solos.append(cohort[0])
                cohort = []
            if cohort:
                self._advance_cohort(cohort)
            for ln in solos:
                if not ln.finished:
                    self._advance_solo(ln)

    def _advance_solo(self, ln: FleetLane) -> None:
        """One solo advance: exactly the ScenarioRunner.run loop body."""
        drv = ln.driver
        batches = [ln.by_step[s] for s in ln.keys[ln.i : ln.i + 2 * drv.k]]
        seg = drv.try_segment(batches)
        if seg is not None and ln.runner._commit_segment(
            ln.keys[ln.i : ln.i + len(seg.steps)],
            batches[: len(seg.steps)],
            seg,
            drv,
            ln.result,
        ):
            ln.i += len(seg.steps)
            return
        self._per_pass_head(ln)

    def _per_pass_head(self, ln: FleetLane) -> None:
        """Run the lane's head step on the per-pass host path (the
        window fallback).  The lane's incremental lowering state is
        strictly flushed first — the per-pass pass mutates store and
        service state the lowered-universe cache cannot track (the
        try_segment wrapper does this on the solo path; fleet
        degradations must too)."""
        ln.driver._flush_incremental("fallback")
        ln.driver.fallback_steps += 1
        step = ln.keys[ln.i]
        done = ln.runner._run_step(step, ln.by_step[step], ln.result)
        ln.i += 1
        if done:
            ln.result.succeeded = True
            ln.done = True

    # -- per-lane degradation ------------------------------------------------

    def _lane_gate(self, ln: FleetLane, site: str) -> "BaseException | None":
        """Check the lane's PRIVATE fault plane at a replay site.
        Returns the containable exception (the lane degrades alone) or
        None; programming errors (``@type`` faults) propagate — the
        classified-taxonomy contract, same as the solo handlers."""
        if ln.faults is None:
            return None
        try:
            ln.faults.check(site)
            return None
        except (
            ReplayFallback,
            DeviceUnavailableError,
            SimulatorError,
            RuntimeError,
            OSError,
        ) as e:
            return e

    def _degrade_lane(self, ln: FleetLane, reason: str) -> None:
        """One lane leaves this round's shared path (reason recorded for
        the round-end divergence bookkeeping) and runs its head step
        per-pass."""
        ln.round_reason = reason
        self.lane_fallbacks += 1
        self._per_pass_head(ln)

    def _note_divergence(self, ln: FleetLane) -> None:
        ln.convergent = False
        self.divergences += 1
        TRACE.event(
            "replay.fleet_lane_fallback",
            lane=ln.idx,
            reason=ln.round_reason or "cursor_drift",
        )
        logger.info(
            "fleet lane %d left the convergent cohort (%s); it continues "
            "on the solo device path",
            ln.idx, ln.round_reason or "cursor_drift",
        )

    # -- the shared window ---------------------------------------------------

    def _advance_cohort(self, cohort: list[FleetLane]) -> None:
        """Advance every convergent lane by one window: one shared
        lowering (the cohort leader's driver — its lowered-universe
        cache makes steady-state windows O(delta)), one vmapped group
        dispatch, one per-lane decode + reconcile.  Any lane that fails
        a per-lane gate degrades ALONE; a shared failure (lowering
        vocabulary miss, device error, post-dispatch discard) degrades
        every lane IDENTICALLY, which keeps the cohort convergent — all
        lanes run the head step per-pass and retry the rest on-device
        next round, exactly like a solo run would."""
        start_i = cohort[0].i
        # 1. Per-lane gates.  First the service-support screen — the
        #    same check a solo prepare_segment opens with, run per lane
        #    because it also caches the lane driver's resolved profile
        #    config (_sched_name/record/preemption) that decode and slot
        #    advancement read.  Then the lane's private replay.lower
        #    fault plane: a firing lane degrades as its solo lowering
        #    would have.
        stay: list[FleetLane] = []
        for ln in cohort:
            if not ln.driver.service_supported():
                self._degrade_lane(ln, ln.driver._last_reject or "unsupported")
                continue
            e = self._lane_gate(ln, "replay.lower")
            if e is None:
                stay.append(ln)
            else:
                reason = str(e) if isinstance(e, ReplayFallback) else "lowering_fault"
                ln.driver._reject(reason)
                self._degrade_lane(ln, reason)
        if stay:
            self._dispatch_cohort(stay)
        # 2. Divergence bookkeeping: the parity contract makes equal
        #    cursors over the shared stream imply equal stores, so any
        #    lane off the common cursor leaves the cohort.  If EVERY
        #    lane took the same path (all committed, or all degraded
        #    identically) the cohort survives intact.
        cursors = {ln.i for ln in cohort}
        if len(cursors) > 1:
            lead_i = max(cursors)  # the device-committed lanes
            for ln in cohort:
                if ln.i != lead_i:
                    self._note_divergence(ln)
        else:
            # Lanes that degraded through a PRIVATE fault this round
            # diverge even at a common cursor unless everyone did: a
            # lane-local device_error fed only that lane's breaker, so
            # its future degradation ladder no longer matches the
            # cohort's.
            reasons = {ln.round_reason for ln in cohort}
            if len(reasons) > 1:
                for ln in cohort:
                    if ln.round_reason is not None:
                        self._note_divergence(ln)
        assert all(ln.i > start_i for ln in cohort), "fleet round made no progress"

    def _dispatch_cohort(self, stay: list[FleetLane]) -> None:
        lead = stay[0]
        drv = lead.driver
        keys, by_step = lead.keys, lead.by_step
        i = lead.i
        batches = [by_step[s] for s in keys[i : i + 2 * drv.k]]
        # Reset before the shared lowering so a None return's reason can
        # only be what THIS window just recorded — prepare_segment's
        # pre-span head screen returns None without a _reject, and
        # mirroring a stale reason from an earlier window would
        # fabricate per-lane fallback evidence no solo run records.
        drv._last_reject = None
        plan = drv.prepare_segment(batches, check_lane_faults=False)
        self.shared_lowerings += 1
        if plan is None:
            # Shared rejection (vocabulary miss, breaker, lowering
            # fault): mirror the leader's recorded reason onto every
            # follower's histogram — each solo run would have recorded
            # it — and degrade the whole cohort identically.
            reason = drv._last_reject
            for ln in stay:
                if ln is not lead and reason is not None:
                    ln.driver._reject(reason)
                self._per_pass_head(ln)
            return
        # 2. Per-lane dispatch gate: a lane whose private plane fires at
        #    replay.dispatch is excluded from the group program and
        #    degrades through the device_error ladder (its own breaker).
        ready: list[FleetLane] = []
        for ln in stay:
            e = self._lane_gate(ln, "replay.dispatch")
            if e is None:
                ready.append(ln)
            else:
                ln.driver._note_device_error(e)
                self._degrade_lane(ln, "device_error")
        if not ready:
            return
        outcome = self._group_dispatch(ready, lead, plan, batches)
        if outcome is None:
            return  # every ready lane already degraded identically
        pulled_state, pulled = outcome
        # 3. Per-lane decode + reconcile against each lane's own store.
        #    Vmapped outputs slice per lane; dedupe outputs are shared
        #    (read-only) — either way each lane decodes against its OWN
        #    service backoff table and reconciles into its OWN store.
        lead.driver._last_plan = plan  # the cache-advance anchor (leader only)
        stacked = self.vmap_cohort
        for j, ln in enumerate(ready):
            if stacked:
                lane_state = jax.tree_util.tree_map(lambda a, j=j: a[j], pulled_state)
                lane_pulled = jax.tree_util.tree_map(lambda a, j=j: a[j], pulled)
            else:
                lane_state, lane_pulled = pulled_state, pulled
            res = ln.driver._decode_outputs(plan, lane_state, lane_pulled)
            if isinstance(res, str):
                # Post-dispatch validation discard — deterministic over
                # identical inputs, so every lane lands here together
                # and the cohort degrades convergently.
                ln.driver._reject(res)
                self._per_pass_head(ln)
                continue
            if ln.runner._commit_segment(
                keys[i : i + len(res.steps)],
                batches[: len(res.steps)],
                res,
                ln.driver,
                ln.result,
            ):
                ln.i += len(res.steps)
            else:
                # Per-lane reconcile rollback (the lane's store is
                # byte-identical to the window start).
                self._degrade_lane(ln, "reconcile_fault")

    def _group_dispatch(self, ready, lead, plan, batches):
        """The vmapped dispatch on a watchdogged worker, overlapped with
        the leader's speculative prelower of the next window (the solo
        pipeline's overlap, kept for the cohort).  Returns the stacked
        ``(pulled_state, pulled)`` or None after degrading every ready
        lane identically."""
        drv = lead.driver
        stacked = self.vmap_cohort
        # Vmapped mode: one scan-carry tree per lane.  The cohort's
        # lanes are byte-identical by the convergence invariant, so the
        # stacked carry is S references to the leader plan's state0;
        # per-lane carries become real when heterogeneous grouping
        # lands (ROADMAP "fleet round 2").
        lanes_state0 = [plan.state0] * len(ready)
        lane_ids = ",".join(str(ln.idx) for ln in ready)
        box: dict[str, Any] = {}

        def work() -> None:  # ksimlint: worker-thread
            try:
                if stacked:
                    box["out"] = _fleet_exec(
                        plan, lanes_state0, self._worker_mesh(plan.statics.tp)
                    )
                else:
                    # Dedupe: the leader's solo segment program (same
                    # compile, same dev-const reuse); its outputs ARE
                    # every cohort lane's outputs.
                    box["out"] = lead.driver._device_exec(plan)
            except BaseException as e:  # classified below, on the main thread
                box["err"] = e

        err: "BaseException | None" = None
        try:
            with TRACE.span(
                "replay.dispatch",
                segment=drv._segment_seq,
                steps=plan.n_steps,
                lanes=len(ready),
                lane=lane_ids,
            ):
                if drv.watchdog_s <= 0:
                    work()
                    drv._prelower_next(plan, batches)
                else:
                    t = threading.Thread(
                        target=work, name="fleet-dispatch", daemon=True
                    )
                    t.start()
                    t0 = time.monotonic()
                    drv._prelower_next(plan, batches)
                    t.join(max(drv.watchdog_s - (time.monotonic() - t0), 0.001))
                    if t.is_alive():
                        # EVERY ready lane counts the timeout: solo
                        # semantics give each lane's breaker a
                        # cumulative-timeout leg, and the cohort's
                        # breakers must stay in lockstep (one abandoned
                        # worker per GROUP timeout, so the leaked-worker
                        # bound stays breaker_threshold — the lanes trip
                        # together).
                        for ln in ready:
                            ln.driver.watchdog_timeouts += 1
                        TRACE.event(
                            "replay.watchdog_timeout",
                            segment=drv._segment_seq,
                            watchdog_s=drv.watchdog_s,
                            lanes=len(ready),
                        )
                        raise DeviceUnavailableError(
                            f"fleet dispatch ({len(ready)} lanes) exceeded "
                            f"the {drv.watchdog_s:.0f}s watchdog"
                        )
                if "err" in box:
                    raise box["err"]
        except ReplayParityError:
            raise  # a kernel bug, not a degradable condition
        except ReplayFallback as e:
            for ln in ready:
                ln.driver._reject(str(e))
                self._per_pass_head(ln)
            return None
        except (DeviceUnavailableError, SimulatorError, RuntimeError, OSError) as e:
            err = e
        if err is not None:
            # A shared device failure: every lane's driver walks the
            # same device_error ladder its solo run would — breakers
            # stay in lockstep, so the cohort survives convergent.
            for ln in ready:
                ln.driver._note_device_error(err)
                self._per_pass_head(ln)
            return None
        for ln in ready:
            ln.driver.note_dispatch_healthy(plan, adopt=(ln is lead))
        self.group_dispatches += 1
        return box["out"]

    def _worker_mesh(self, tp: int = 1):
        """The KSIM_FLEET_DP (dp, tp) fleet mesh, built lazily on the
        DISPATCH WORKER thread (jax.devices() initializes the backend;
        a wedged tunnel must hang the watchdogged worker, never the
        main thread).  ``tp`` follows the dispatching plan's node-shard
        width (plan.statics.tp, round 19) — a cohort whose plans narrow
        tp across windows gets one memoized mesh per width.  A mesh
        build failure degrades to single-device fleet dispatch — once,
        loudly."""
        if self.dp is None:
            return None
        from ksim_tpu.engine.sharding import fleet_mesh

        with self._mesh_lock:
            if self._mesh_failed:
                return None
            mesh = self._mesh.get((self.dp, tp))
            if mesh is None:
                try:
                    # Deliberate worker-side store: the mesh is built
                    # lazily ON the dispatch worker so a wedged chip
                    # tunnel hangs the watchdogged worker, never the
                    # main thread; _mesh_lock makes both writes safe.
                    mesh = fleet_mesh(self.dp, tp)  # ksimlint: disable=thread-role
                    self._mesh[(self.dp, tp)] = mesh  # ksimlint: disable=thread-role
                except Exception as e:
                    self._mesh_failed = True  # ksimlint: disable=thread-role
                    logger.warning(
                        "KSIM_FLEET_DP=%d x tp=%d mesh unavailable (%s: %s); "
                        "fleet dispatch stays single-device",
                        self.dp, tp, type(e).__name__, e,
                    )
                    return None
            return mesh
