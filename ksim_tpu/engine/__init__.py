"""Batched TPU scheduling engine."""

from ksim_tpu.engine.core import Engine, EngineResult, ScoredPlugin

__all__ = ["Engine", "EngineResult", "ScoredPlugin"]
