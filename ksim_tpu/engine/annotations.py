"""Render engine results to the reference's Pod result annotations.

The recorded results ARE the product (SURVEY.md hard part 7): the reference
wraps every plugin, records per-node per-plugin outcomes into a result
store, and reflects them onto the scheduled Pod's annotations (reference
simulator/scheduler/plugin/resultstore/store.go:133-198 GetStoredResult,
simulator/scheduler/plugin/annotation/annotation.go:3-31 keys,
simulator/scheduler/storereflector/storereflector.go:148-167 history).

This module reconstructs the exact same annotation contract from the
batched EngineResult tensors:

- ``filter-result``: node -> plugin -> "passed" | reason message, with the
  upstream framework's early-exit semantics (a node rejected by filter k
  has no entries for filters > k — upstream RunFilterPlugins stops at the
  first failure).
- ``score-result``: node -> plugin -> raw score (feasible nodes only —
  upstream only scores nodes that passed all filters).
- ``finalscore-result``: node -> plugin -> normalized x weight
  (resultstore/store.go:461-507: AddScoreResult seeds final with
  raw x weight; NormalizeScore overwrites with normalized x weight).
- ``prefilter-result`` / ``prefilter-result-status`` / ``prescore-result``:
  per-plugin "success" for plugins whose upstream counterpart implements
  the extension point (our kernels fold Pre* work into the fused kernels,
  so the recorded status is always success; PreFilterResult node lists are
  always nil upstream for the default plugins -> "{}" here).
- ``reserve-result`` / ``prebind-result``: {"VolumeBinding": "success"}
  for scheduled pods when VolumeBinding is enabled at that point (the
  default profile's only Reserve/PreBind plugin; wrappedplugin.go:616-645
  Reserve, :670-697 PreBind); per-point profile disables drop it.
- ``permit-result`` / ``permit-result-timeout``: "{}" — the default
  profile has no Permit plugins.
- ``bind-result``: {"DefaultBinder": "success"} for scheduled pods.
- ``selected-node``: set only when the pod was scheduled (reference
  store.go AddSelectedNode is called at Reserve).

JSON is serialized with sorted keys and compact separators to byte-match
Go's json.Marshal of map[string]string.
"""

from __future__ import annotations

import json
from typing import Sequence

from ksim_tpu.engine.core import EngineResult, ScoredPlugin
from ksim_tpu.state.featurizer import FeaturizedSnapshot

PREFIX = "kube-scheduler-simulator.sigs.k8s.io/"

PRE_FILTER_STATUS_KEY = PREFIX + "prefilter-result-status"
PRE_FILTER_RESULT_KEY = PREFIX + "prefilter-result"
FILTER_RESULT_KEY = PREFIX + "filter-result"
POST_FILTER_RESULT_KEY = PREFIX + "postfilter-result"
PRE_SCORE_RESULT_KEY = PREFIX + "prescore-result"
SCORE_RESULT_KEY = PREFIX + "score-result"
FINAL_SCORE_RESULT_KEY = PREFIX + "finalscore-result"
RESERVE_RESULT_KEY = PREFIX + "reserve-result"
PERMIT_RESULT_KEY = PREFIX + "permit-result"
PERMIT_TIMEOUT_RESULT_KEY = PREFIX + "permit-result-timeout"
PRE_BIND_RESULT_KEY = PREFIX + "prebind-result"
BIND_RESULT_KEY = PREFIX + "bind-result"
SELECTED_NODE_KEY = PREFIX + "selected-node"
RESULT_HISTORY_KEY = PREFIX + "result-history"

ALL_RESULT_KEYS = (
    PRE_FILTER_STATUS_KEY,
    PRE_FILTER_RESULT_KEY,
    FILTER_RESULT_KEY,
    POST_FILTER_RESULT_KEY,
    PRE_SCORE_RESULT_KEY,
    SCORE_RESULT_KEY,
    FINAL_SCORE_RESULT_KEY,
    RESERVE_RESULT_KEY,
    PERMIT_RESULT_KEY,
    PERMIT_TIMEOUT_RESULT_KEY,
    PRE_BIND_RESULT_KEY,
    BIND_RESULT_KEY,
    SELECTED_NODE_KEY,
)

PASSED_FILTER_MESSAGE = "passed"  # resultstore PassedFilterMessage
SUCCESS_MESSAGE = "success"  # resultstore SuccessMessage
POST_FILTER_NOMINATED_MESSAGE = "preemption victim"

# Upstream extension points implemented by each kernel's Go counterpart
# (v1.30 plugin sources); used to emit the per-plugin "success" statuses
# the wrapped plugins would have recorded.
UPSTREAM_PRE_FILTER = {
    "NodeResourcesFit",
    "NodeAffinity",
    "PodTopologySpread",
    "InterPodAffinity",
    "NodePorts",
    "VolumeBinding",
    "VolumeRestrictions",
    "NodeVolumeLimits",
}
UPSTREAM_PRE_SCORE = {
    "TaintToleration",
    "NodeAffinity",
    "PodTopologySpread",
    "InterPodAffinity",
    "NodeResourcesFit",
    "NodeResourcesBalancedAllocation",
    "VolumeBinding",
}


def _marshal(obj) -> str:
    """Byte-compatible with Go json.Marshal for string maps."""
    return json.dumps(obj, sort_keys=True, separators=(",", ":"))


class RenderCtx:
    """Per-pass shared state for rendering many pods' results: sorted
    node-name order, pre-JSON'd node/plugin names, the all-pass filter
    row, and a cross-pod reason-bit decode memo.  Build once per
    scheduling pass (the maps are assembled as JSON text directly — at
    10k pods x 5k nodes the per-entry dict building + json.dumps of the
    nested maps dominated the product path)."""

    def __init__(self, feats, plugins: Sequence[ScoredPlugin]) -> None:
        """``feats`` is a FeaturizedSnapshot, or a plain sequence of
        node names — the device-replay decode (engine/replay.py) renders
        per-step annotations over a step's live-node subset without a
        featurized snapshot in hand."""
        import numpy as np

        self.node_names = (
            list(feats) if isinstance(feats, (list, tuple)) else feats.nodes.names
        )
        self.filter_plugins = [sp for sp in plugins if sp.filter_enabled]
        self.score_plugins = [sp for sp in plugins if sp.score_enabled]
        names = self.node_names
        # json.dumps per atom keeps byte-compatibility with _marshal
        # (escaping, ensure_ascii) while the maps are joined by hand.
        self.node_json = [json.dumps(nm) for nm in names]
        order = sorted(range(len(names)), key=lambda i: names[i])
        self.rank = np.empty(len(names), dtype=np.int64)
        for r, i in enumerate(order):
            self.rank[i] = r
        fnames = [sp.plugin.name for sp in self.filter_plugins]
        self.fname_json = [json.dumps(n) for n in fnames]
        passed = json.dumps(PASSED_FILTER_MESSAGE)
        self.passed_row = "{" + ",".join(
            f"{k}:{passed}" for k in sorted(self.fname_json)
        ) + "}"
        # Inner score rows list plugin names sorted (Go map marshal order).
        sorder = sorted(range(len(self.score_plugins)),
                        key=lambda s: self.score_plugins[s].plugin.name)
        self.score_order = sorder
        self.sname_json = [json.dumps(self.score_plugins[s].plugin.name) for s in sorder]
        # Vectorized-assembly pieces: '"node":' prefixes (full and in
        # key-sorted node order) and the per-plugin score-row separators
        # ('{"p1":"', '","p2":"', ...).
        self.sorted_order_arr = np.asarray(order, dtype=np.int64)
        self.node_json_prefix_arr = np.asarray([nj + ":" for nj in self.node_json])
        self.node_json_sorted_prefix = [self.node_json[i] + ":" for i in order]
        self.score_prefix = [
            ("{" if s == 0 else '",') + self.sname_json[s] + ':"'
            for s in range(len(sorder))
        ]
        # (fi, bits) -> rendered filter row JSON, shared across pods.
        self.fail_row_memo: dict[tuple[int, int], str] = {}

    def fail_row(self, fi: int, bits: int) -> str:
        """Row for a node whose first filter failure is plugin ``fi``
        with ``bits``: upstream RunFilterPlugins stops at the first
        failure, so plugins after ``fi`` are absent from the row."""
        key = (fi, bits)
        row = self.fail_row_memo.get(key)
        if row is None:
            msg = ", ".join(self.filter_plugins[fi].plugin.decode_reasons(bits))
            entries = {self.fname_json[i]: json.dumps(PASSED_FILTER_MESSAGE) for i in range(fi)}
            entries[self.fname_json[fi]] = json.dumps(msg)
            row = "{" + ",".join(f"{k}:{v}" for k, v in sorted(entries.items())) + "}"
            self.fail_row_memo[key] = row
        return row


def render_pod_results(
    feats: FeaturizedSnapshot,
    plugins: Sequence[ScoredPlugin],
    res: EngineResult,
    pi: int,
    *,
    postfilter: dict | None = None,
    permit: tuple[dict, dict] | None = None,
    bound: bool = True,
    reserve_extra: dict | None = None,
    prebind_extra: dict | None = None,
    bind_map: dict | None = None,
    ctx: "RenderCtx | None" = None,
    visited: "np.ndarray | None" = None,
) -> dict[str, str]:
    """The 13 result annotations for queue pod ``pi`` (all keys present,
    empty maps as "{}", mirroring GetStoredResult's unconditional adds).
    ``postfilter`` is the {node: {plugin: msg}} map recorded by the
    PostFilter wrapper when preemption ran (wrappedplugin.go:550-577);
    ``permit`` is ({plugin: status}, {plugin: timeout_str}) recorded by
    the Permit wrapper (wrappedplugin.go:582-611, store.go:549-560);
    ``bound=False`` marks a cycle that selected a node but never reached
    Bind (a Permit rejection): selected-node and reserve-result stay
    recorded — upstream wrote them at Reserve — while prebind/bind maps
    stay empty because those wrappers never ran.
    ``reserve_extra``/``prebind_extra`` merge out-of-tree Reserve and
    PreBind hook results into their maps; ``bind_map`` overrides the
    bind-result map when a custom binder handled (or failed) the bind
    (wrappedplugin.go:699-726 AddBindResult records under the actual
    binder's name).
    ``visited`` (percentageOfNodesToScore emulation, res.visited[pi]):
    only visited nodes appear in the recorded maps — upstream's
    NodeToStatusMap and score lists cover the nodes its sampled filter
    iteration actually touched.
    Pass a shared ``ctx`` when rendering many pods of one pass."""
    if res.reason_bits is None:
        raise ValueError("render_pod_results needs record='full' results")
    import numpy as np

    if ctx is None:
        ctx = RenderCtx(feats, plugins)
    node_names = ctx.node_names
    filter_plugins = ctx.filter_plugins
    score_plugins = ctx.score_plugins
    N = len(node_names)

    bits_pi = np.asarray(res.reason_bits[pi])[:, :N]  # [F, N]
    failed = bits_pi != 0
    any_fail = failed.any(axis=0)
    # First failing plugin per node (argmax finds the first True); with
    # no filter plugins every node is feasible and argmax is undefined.
    if bits_pi.shape[0]:
        first_fail = np.argmax(failed, axis=0)
    else:
        first_fail = np.zeros(N, dtype=np.int64)
    vis = None if visited is None else np.asarray(visited)[:N].astype(bool)
    if vis is None:
        feasible_nodes = np.nonzero(~any_fail)[0]
    else:
        feasible_nodes = np.nonzero(~any_fail & vis)[0]

    # filter-result: every (visited) node gets a row; rows are shared
    # strings.  Nodes share a handful of distinct rows (the all-pass row
    # or one per (first failing plugin, bits) pattern): classify every
    # node to a pattern code in bulk, render each distinct row once,
    # then join.
    so = ctx.sorted_order_arr
    ff_s = first_fail[so].astype(np.int64)
    bits_at_ff = bits_pi[ff_s, so].astype(np.int64)
    codes = np.where(any_fail[so], (ff_s << 32) | (bits_at_ff & 0xFFFFFFFF), -1)
    uniq, inv = np.unique(codes, return_inverse=True)
    row_strs = []
    for code in uniq:
        if code < 0:
            row_strs.append(ctx.passed_row)
        else:
            row_strs.append(ctx.fail_row(int(code >> 32), int(code & 0xFFFFFFFF)))
    prefixes = ctx.node_json_sorted_prefix
    if vis is None:
        parts = [prefixes[k] + row_strs[i] for k, i in enumerate(inv)]
    else:
        vis_s = vis[so]
        parts = [
            prefixes[k] + row_strs[i]
            for k, i in enumerate(inv)
            if vis_s[k]
        ]
    filter_json = "{" + ",".join(parts) + "}"

    # Upstream schedulePod returns right after filtering when exactly one
    # node is feasible (schedule_one.go findNodesThatFitPod early return):
    # PreScore/Score/NormalizeScore never run, so the reference records
    # empty score maps.  Zero feasible nodes goes to PostFilter, likewise
    # without scoring.
    ran_scoring = len(feasible_nodes) > 1
    score_json = "{}"
    final_json = "{}"
    if res.scores is not None and score_plugins and ran_scoring:
        # Feasible nodes in key-sorted order; values stringified in bulk.
        feas = feasible_nodes[np.argsort(ctx.rank[feasible_nodes], kind="stable")]
        raw = np.char.mod("%d", np.asarray(res.scores[pi])[:, feas][ctx.score_order])
        fin = np.char.mod("%d", np.asarray(res.final_scores[pi])[:, feas][ctx.score_order])

        def rows_json(vals: np.ndarray) -> np.ndarray:
            # '"p1":"V1","p2":"V2",...' assembled as S vectorized string
            # concatenations over the feasible axis (python-level per-cell
            # loops dominated the product path at 10k x 5k).
            row = np.char.add(ctx.score_prefix[0], vals[0])
            for s in range(1, vals.shape[0]):
                row = np.char.add(row, ctx.score_prefix[s])
                row = np.char.add(row, vals[s])
            return np.char.add(row, '"}')

        node_pre = ctx.node_json_prefix_arr[feas]
        score_json = "{" + ",".join(np.char.add(node_pre, rows_json(raw)).tolist()) + "}"
        final_json = "{" + ",".join(np.char.add(node_pre, rows_json(fin)).tolist()) + "}"

    prefilter_status = {
        sp.plugin.name: SUCCESS_MESSAGE
        for sp in filter_plugins
        if sp.plugin.name in UPSTREAM_PRE_FILTER
    }
    prescore = (
        {
            sp.plugin.name: SUCCESS_MESSAGE
            for sp in score_plugins
            if sp.plugin.name in UPSTREAM_PRE_SCORE
        }
        if ran_scoring
        else {}
    )

    selected = int(res.selected[pi])
    # VolumeBinding is the default profile's only Reserve/PreBind plugin;
    # on a successful cycle upstream's wrappers record "success" for it
    # (wrappedplugin.go:616-645 Reserve, :670-697 PreBind).  Profiles can
    # disable it at a single point (ScoredPlugin.reserve/prebind_enabled).
    def _point_map(flag: str, ran: bool = True) -> dict:
        if selected < 0 or not ran:
            return {}
        return {
            sp.plugin.name: SUCCESS_MESSAGE
            for sp in plugins
            if sp.plugin.name == "VolumeBinding" and getattr(sp, flag, True)
        }

    reserve_map = _point_map("reserve_enabled")
    if reserve_extra and selected >= 0:
        reserve_map = {**reserve_map, **reserve_extra}
    prebind_map = _point_map("prebind_enabled", ran=bound)
    if prebind_extra and selected >= 0:
        prebind_map = {**prebind_map, **prebind_extra}
    if bind_map is None:
        bind_map = {"DefaultBinder": SUCCESS_MESSAGE} if selected >= 0 and bound else {}
    elif selected < 0:
        bind_map = {}
    out = {
        PRE_FILTER_RESULT_KEY: _marshal({}),
        PRE_FILTER_STATUS_KEY: _marshal(prefilter_status),
        FILTER_RESULT_KEY: filter_json,
        POST_FILTER_RESULT_KEY: _marshal(postfilter or {}),
        PRE_SCORE_RESULT_KEY: _marshal(prescore),
        SCORE_RESULT_KEY: score_json,
        FINAL_SCORE_RESULT_KEY: final_json,
        RESERVE_RESULT_KEY: _marshal(reserve_map),
        PERMIT_RESULT_KEY: _marshal(permit[0] if permit else {}),
        PERMIT_TIMEOUT_RESULT_KEY: _marshal(permit[1] if permit else {}),
        PRE_BIND_RESULT_KEY: _marshal(prebind_map),
        BIND_RESULT_KEY: _marshal(bind_map),
    }
    if selected >= 0:
        out[SELECTED_NODE_KEY] = node_names[selected]
    return out


def update_result_history(annotations: dict[str, str], result: dict[str, str]) -> None:
    """Append ``result`` to the result-history annotation in place
    (reference storereflector.go:148-167 updateResultHistory)."""
    history = json.loads(annotations.get(RESULT_HISTORY_KEY, "[]"))
    history.append(result)
    annotations[RESULT_HISTORY_KEY] = _marshal(history)


def apply_results_to_pod(
    pod_annotations: dict[str, str], result: dict[str, str]
) -> dict[str, str]:
    """What storeAllResultToPodFunc does to one Pod's annotations: merge
    the result keys, then append the same set to the history."""
    pod_annotations.update(result)
    update_result_history(pod_annotations, result)
    return pod_annotations
