"""Render engine results to the reference's Pod result annotations.

The recorded results ARE the product (SURVEY.md hard part 7): the reference
wraps every plugin, records per-node per-plugin outcomes into a result
store, and reflects them onto the scheduled Pod's annotations (reference
simulator/scheduler/plugin/resultstore/store.go:133-198 GetStoredResult,
simulator/scheduler/plugin/annotation/annotation.go:3-31 keys,
simulator/scheduler/storereflector/storereflector.go:148-167 history).

This module reconstructs the exact same annotation contract from the
batched EngineResult tensors:

- ``filter-result``: node -> plugin -> "passed" | reason message, with the
  upstream framework's early-exit semantics (a node rejected by filter k
  has no entries for filters > k — upstream RunFilterPlugins stops at the
  first failure).
- ``score-result``: node -> plugin -> raw score (feasible nodes only —
  upstream only scores nodes that passed all filters).
- ``finalscore-result``: node -> plugin -> normalized x weight
  (resultstore/store.go:461-507: AddScoreResult seeds final with
  raw x weight; NormalizeScore overwrites with normalized x weight).
- ``prefilter-result`` / ``prefilter-result-status`` / ``prescore-result``:
  per-plugin "success" for plugins whose upstream counterpart implements
  the extension point (our kernels fold Pre* work into the fused kernels,
  so the recorded status is always success; PreFilterResult node lists are
  always nil upstream for the default plugins -> "{}" here).
- ``reserve-result`` / ``prebind-result``: {"VolumeBinding": "success"}
  for scheduled pods when VolumeBinding is enabled at that point (the
  default profile's only Reserve/PreBind plugin; wrappedplugin.go:616-645
  Reserve, :670-697 PreBind); per-point profile disables drop it.
- ``permit-result`` / ``permit-result-timeout``: "{}" — the default
  profile has no Permit plugins.
- ``bind-result``: {"DefaultBinder": "success"} for scheduled pods.
- ``selected-node``: set only when the pod was scheduled (reference
  store.go AddSelectedNode is called at Reserve).

JSON is serialized with sorted keys and compact separators to byte-match
Go's json.Marshal of map[string]string.
"""

from __future__ import annotations

import json
from typing import Sequence

from ksim_tpu.engine.core import EngineResult, ScoredPlugin
from ksim_tpu.state.featurizer import FeaturizedSnapshot

PREFIX = "kube-scheduler-simulator.sigs.k8s.io/"

PRE_FILTER_STATUS_KEY = PREFIX + "prefilter-result-status"
PRE_FILTER_RESULT_KEY = PREFIX + "prefilter-result"
FILTER_RESULT_KEY = PREFIX + "filter-result"
POST_FILTER_RESULT_KEY = PREFIX + "postfilter-result"
PRE_SCORE_RESULT_KEY = PREFIX + "prescore-result"
SCORE_RESULT_KEY = PREFIX + "score-result"
FINAL_SCORE_RESULT_KEY = PREFIX + "finalscore-result"
RESERVE_RESULT_KEY = PREFIX + "reserve-result"
PERMIT_RESULT_KEY = PREFIX + "permit-result"
PERMIT_TIMEOUT_RESULT_KEY = PREFIX + "permit-result-timeout"
PRE_BIND_RESULT_KEY = PREFIX + "prebind-result"
BIND_RESULT_KEY = PREFIX + "bind-result"
SELECTED_NODE_KEY = PREFIX + "selected-node"
RESULT_HISTORY_KEY = PREFIX + "result-history"

ALL_RESULT_KEYS = (
    PRE_FILTER_STATUS_KEY,
    PRE_FILTER_RESULT_KEY,
    FILTER_RESULT_KEY,
    POST_FILTER_RESULT_KEY,
    PRE_SCORE_RESULT_KEY,
    SCORE_RESULT_KEY,
    FINAL_SCORE_RESULT_KEY,
    RESERVE_RESULT_KEY,
    PERMIT_RESULT_KEY,
    PERMIT_TIMEOUT_RESULT_KEY,
    PRE_BIND_RESULT_KEY,
    BIND_RESULT_KEY,
    SELECTED_NODE_KEY,
)

PASSED_FILTER_MESSAGE = "passed"  # resultstore PassedFilterMessage
SUCCESS_MESSAGE = "success"  # resultstore SuccessMessage
POST_FILTER_NOMINATED_MESSAGE = "preemption victim"

# Upstream extension points implemented by each kernel's Go counterpart
# (v1.30 plugin sources); used to emit the per-plugin "success" statuses
# the wrapped plugins would have recorded.
UPSTREAM_PRE_FILTER = {
    "NodeResourcesFit",
    "NodeAffinity",
    "PodTopologySpread",
    "InterPodAffinity",
    "NodePorts",
    "VolumeBinding",
    "VolumeRestrictions",
    "NodeVolumeLimits",
}
UPSTREAM_PRE_SCORE = {
    "TaintToleration",
    "NodeAffinity",
    "PodTopologySpread",
    "InterPodAffinity",
    "NodeResourcesFit",
    "NodeResourcesBalancedAllocation",
    "VolumeBinding",
}


def _marshal(obj) -> str:
    """Byte-compatible with Go json.Marshal for string maps."""
    return json.dumps(obj, sort_keys=True, separators=(",", ":"))


def render_pod_results(
    feats: FeaturizedSnapshot,
    plugins: Sequence[ScoredPlugin],
    res: EngineResult,
    pi: int,
    *,
    postfilter: dict | None = None,
) -> dict[str, str]:
    """The 13 result annotations for queue pod ``pi`` (all keys present,
    empty maps as "{}", mirroring GetStoredResult's unconditional adds).
    ``postfilter`` is the {node: {plugin: msg}} map recorded by the
    PostFilter wrapper when preemption ran (wrappedplugin.go:550-577)."""
    if res.reason_bits is None:
        raise ValueError("render_pod_results needs record='full' results")
    import numpy as np

    node_names = feats.nodes.names
    filter_plugins = [sp for sp in plugins if sp.filter_enabled]
    score_plugins = [sp for sp in plugins if sp.score_enabled]

    # Decode reason bits through a per-plugin memo: clusters repeat a
    # handful of distinct bit patterns across thousands of nodes, and the
    # rendered results are the product's hot output path at 10k x 5k
    # (SURVEY hard part 7).
    bits_pi = np.asarray(res.reason_bits[pi])  # [F, N]
    decode_memo: list[dict[int, str]] = []
    for fi, sp in enumerate(filter_plugins):
        memo: dict[int, str] = {0: PASSED_FILTER_MESSAGE}
        for b in np.unique(bits_pi[fi, : len(node_names)]):
            if int(b) != 0:
                memo[int(b)] = ", ".join(sp.plugin.decode_reasons(int(b)))
        decode_memo.append(memo)

    filter_map: dict[str, dict[str, str]] = {}
    feasible_nodes: list[int] = []
    plugin_names_f = [sp.plugin.name for sp in filter_plugins]
    for ni, node in enumerate(node_names):
        row: dict[str, str] = {}
        ok = True
        for fi in range(len(filter_plugins)):
            bits = int(bits_pi[fi, ni])
            row[plugin_names_f[fi]] = decode_memo[fi][bits]
            if bits != 0:
                ok = False
                break  # upstream RunFilterPlugins early exit
        filter_map[node] = row
        if ok:
            feasible_nodes.append(ni)

    # Upstream schedulePod returns right after filtering when exactly one
    # node is feasible (schedule_one.go findNodesThatFitPod early return):
    # PreScore/Score/NormalizeScore never run, so the reference records
    # empty score maps.  Zero feasible nodes goes to PostFilter, likewise
    # without scoring.
    ran_scoring = len(feasible_nodes) > 1
    score_map: dict[str, dict[str, str]] = {}
    final_map: dict[str, dict[str, str]] = {}
    if res.scores is not None and score_plugins and ran_scoring:
        for ni in feasible_nodes:
            node = node_names[ni]
            score_map[node] = {
                sp.plugin.name: str(int(res.scores[pi, si, ni]))
                for si, sp in enumerate(score_plugins)
            }
            final_map[node] = {
                sp.plugin.name: str(int(res.final_scores[pi, si, ni]))
                for si, sp in enumerate(score_plugins)
            }

    prefilter_status = {
        sp.plugin.name: SUCCESS_MESSAGE
        for sp in filter_plugins
        if sp.plugin.name in UPSTREAM_PRE_FILTER
    }
    prescore = (
        {
            sp.plugin.name: SUCCESS_MESSAGE
            for sp in score_plugins
            if sp.plugin.name in UPSTREAM_PRE_SCORE
        }
        if ran_scoring
        else {}
    )

    selected = int(res.selected[pi])
    # VolumeBinding is the default profile's only Reserve/PreBind plugin;
    # on a successful cycle upstream's wrappers record "success" for it
    # (wrappedplugin.go:616-645 Reserve, :670-697 PreBind).  Profiles can
    # disable it at a single point (ScoredPlugin.reserve/prebind_enabled).
    def _point_map(flag: str) -> dict:
        if selected < 0:
            return {}
        return {
            sp.plugin.name: SUCCESS_MESSAGE
            for sp in plugins
            if sp.plugin.name == "VolumeBinding" and getattr(sp, flag, True)
        }

    reserve_map = _point_map("reserve_enabled")
    prebind_map = _point_map("prebind_enabled")
    out = {
        PRE_FILTER_RESULT_KEY: _marshal({}),
        PRE_FILTER_STATUS_KEY: _marshal(prefilter_status),
        FILTER_RESULT_KEY: _marshal(filter_map),
        POST_FILTER_RESULT_KEY: _marshal(postfilter or {}),
        PRE_SCORE_RESULT_KEY: _marshal(prescore),
        SCORE_RESULT_KEY: _marshal(score_map),
        FINAL_SCORE_RESULT_KEY: _marshal(final_map),
        RESERVE_RESULT_KEY: _marshal(reserve_map),
        PERMIT_RESULT_KEY: _marshal({}),
        PERMIT_TIMEOUT_RESULT_KEY: _marshal({}),
        PRE_BIND_RESULT_KEY: _marshal(prebind_map),
        BIND_RESULT_KEY: _marshal(
            {"DefaultBinder": SUCCESS_MESSAGE} if selected >= 0 else {}
        ),
    }
    if selected >= 0:
        out[SELECTED_NODE_KEY] = node_names[selected]
    return out


def update_result_history(annotations: dict[str, str], result: dict[str, str]) -> None:
    """Append ``result`` to the result-history annotation in place
    (reference storereflector.go:148-167 updateResultHistory)."""
    history = json.loads(annotations.get(RESULT_HISTORY_KEY, "[]"))
    history.append(result)
    annotations[RESULT_HISTORY_KEY] = _marshal(history)


def apply_results_to_pod(
    pod_annotations: dict[str, str], result: dict[str, str]
) -> dict[str, str]:
    """What storeAllResultToPodFunc does to one Pod's annotations: merge
    the result keys, then append the same set to the history."""
    pod_annotations.update(result)
    update_result_history(pod_annotations, result)
    return pod_annotations
