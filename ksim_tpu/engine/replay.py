"""Device-resident churn replay: K scheduling passes per device dispatch.

The per-pass replay path (scenario/runner.py + scheduler/service.py) pays
one axon-tunnel round trip per scheduling pass — ~80-100 ms of pure
dispatch latency on the v5e, ~480 times over the 50k churn replay — because
each pass's placements mutate the host ClusterStore before the next step's
events can apply (docs/churn_floor.md, "Where the remaining time goes").

This module removes that serialization for the common churn op vocabulary
(pod create / pod delete / node drain / node replace): a SEGMENT of K
scenario steps is pre-lowered on the host into padded tensor event
streams over a pod/node UNIVERSE (every object alive during the segment,
including ones created mid-segment), and a single compiled program runs
all K steps — event application, backoff bookkeeping, queue compaction,
and the sequential-commit scheduling scan — inside one ``lax.scan`` whose
carry holds the full cluster tensor state.  The host store remains the
JSON-speaking source of truth: placements stream back once per segment
and are reconciled into the store step by step (scenario/runner.py).

Parity contract (the behavior locks, repo CLAUDE.md): the device path
must reproduce the per-pass path's scheduled/unschedulable counts
byte-identically.  The design choices that guarantee it:

- **Universe row order is queue order.**  Pod rows are pre-sorted by the
  exact ``queue_sort_key`` (priority desc, creationTimestamp, namespace,
  name) — static per pod — so per-step queue compaction preserves the
  per-pass scheduling order without a device sort.
- **Rank-based selectHost.**  The per-pass path's tie-break is "lowest
  node index" in the persistent featurizer's slot order, which evolves by
  NodeSlots swap-remove under churn.  The lowering simulates that exact
  slot history step by step (``_SlotSim``) and ships a per-step rank
  tensor; the device selects the max-score feasible node with minimal
  rank — the same node the per-pass argmax picks.
- **Integer-space deltas.**  Event application mutates only additive
  integer state (requested/nonzero/pod-count aggregates, spread selector
  counts, inter-pod term accumulators, backoff counters), so the
  f32-fast-mode cross-platform determinism argument of round 5
  (docs/churn_floor.md "Cross-platform count determinism") carries over
  unchanged: the scoring kernels see bit-identical inputs.
- **Local accumulators for InterPodAffinity.**  The per-pass carry is a
  domain-AGGREGATED view that cannot absorb deletes; the segment carry
  keeps per-node local term sums and re-derives the domain view each
  step with fixed segment reductions (``_derive_interpod``) — verified
  at lowering time against the featurizer's own aggregation.

Two former fallback classes are now lowered instead (round 7):

- **DefaultPreemption** runs ON-DEVICE: the per-candidate fit re-check
  and the MoreImportantPod reprieve loop are masked tensor ops over the
  universe (bounded candidate scan + ``lax.fori_loop`` reprieve; the
  pickOneNode narrowing cascade is one lexicographic argmin), against a
  LIVE mid-pass state that tracks this pass's binds plus earlier
  victims — exactly the store view the per-pass dry-run reads.  Bounds
  exceeded -> per-step overflow flag -> segment discarded before any
  store effect.
- **record="full"** streams the per-attempt reason-bit / raw / final
  score tensors out of the scan as stacked segment outputs (shorter
  fixed K to bound device memory); the host decodes them into the exact
  per-pass result annotations at segment boundaries.

Segments shorter than the compiled K (stream tails, mid-window
vocabulary misses) are tail-padded with inactive no-op steps and reuse
the existing compile.  Anything outside the remaining vocabulary
(patch/update ops, pods with host ports / volumes / scheduling gates,
extenders, multiple profiles, node images, inexact unit scaling, ...)
makes ``lower()`` return None and the segment falls back to the
per-pass path, so coverage can grow incrementally without risking the
locks.
"""

from __future__ import annotations

import bisect
import hashlib
import logging
import math
import os
import threading
import time
import zlib
from dataclasses import dataclass, field, fields, is_dataclass
from functools import partial
from typing import Any, Sequence

import jax
import numpy as np

from ksim_tpu.errors import (
    DeviceUnavailableError,
    ReplayFallback,
    RunCancelled,
    SimulatorError,
)
from ksim_tpu.engine.compilecache import COMPILE_CACHE
from ksim_tpu.engine.kernelreg import device_kernel
from ksim_tpu.faults import FAULTS
from ksim_tpu.obs import TRACE, register_provider
from ksim_tpu.state.resources import JSON, name_of, namespace_of

logger = logging.getLogger(__name__)

#: Every STATIC fallback/discard reason ``ReplayDriver._reject`` can
#: record — the machine-readable half of the taxonomy prose in
#: docs/churn_floor.md.  Each rejection also lands a ``replay.fallback``
#: trace event carrying the reason, so a timeline shows WHICH segment
#: degraded and why; tests/test_obs.py's registry-sync test scans this
#: module's source for reason literals and asserts this set matches
#: (drift = a reason that silently never reaches the trace taxonomy).
FALLBACK_REASONS: frozenset[str] = frozenset(
    {
        # service/profile configuration outside the vocabulary
        "record_mode", "extenders", "pnts_emulation", "shard_mesh",
        "featurizer_override", "multi_profile", "no_profile",
        "queue_hooks", "permit_waiters", "plugin_extender",
        # object vocabulary misses
        "scheduling_gates", "foreign_scheduler", "terminal_phase",
        "host_ports", "volumes", "volume_objects", "node_images",
        "create_bound_pod", "bound_to_unknown_node", "inexact_units",
        # stream-shape misses
        "pod_name_reuse", "backoff_name_reuse", "node_name_reuse",
        "delete_unknown_pod", "delete_unknown_node",
        "drain_without_requeue", "duplicate_pod_keys",
        # lowering-time guards
        "interpod_local_mismatch", "preemption_filter_set",
        "preemption_bits_width", "full_record_bytes",
        # post-dispatch validation discards
        "featurize_prediction", "preemption_overflow",
        # degradation ladder (docs/churn_floor.md round 8)
        "lowering_fault", "device_error", "reconcile_fault",
        "breaker_open",
    }
)

#: Dynamic reason families (``op:<op>/<kind>``, ``host_hook:<attr>``) —
#: prefix-matched by the registry-sync test.
FALLBACK_REASON_PREFIXES: tuple[str, ...] = ("op:", "host_hook:")

# Steps batched per device dispatch.  The dispatch-latency win scales
# with K; lowering/reconcile host work amortizes over it.  8-32 is the
# useful range (beyond that the universe grows stale and the first
# fallback forces a re-lower anyway).
SEGMENT_STEPS = int(os.environ.get("KSIM_REPLAY_K", "16"))

# record="full" segments stack per-step [Q, F|S, N] result tensors on
# device, so they run at a SHORTER fixed K (one extra compiled shape)
# and are rejected outright when even that would exceed the byte bound
# below ("full_record_bytes" fallback).
FULL_SEGMENT_STEPS = int(os.environ.get("KSIM_REPLAY_FULL_K", "4"))
FULL_RECORD_BYTES = int(os.environ.get("KSIM_REPLAY_FULL_BYTES", str(1 << 30)))

# On-device preemption victim-search bounds (static shapes for the
# candidate scan and the unrolled reprieve loop).  A step whose search
# would exceed either bound sets an overflow flag and the whole segment
# is DISCARDED before any store effect ("preemption_overflow" fallback)
# — bounded-exact, never approximate.
PREEMPT_CANDIDATES = int(os.environ.get("KSIM_REPLAY_CMAX", "16"))
PREEMPT_VICTIMS = int(os.environ.get("KSIM_REPLAY_VMAX", "8"))

# Failure containment (docs/churn_floor.md "Failure containment"):
# each segment dispatch runs on a worker thread bounded by the watchdog
# (a wedged chip tunnel blocks block_until_ready FOREVER — the exact
# condition that has repeatedly stalled TPU measurement on this image,
# repo CLAUDE.md); N CONSECUTIVE device failures trip a sticky
# circuit breaker that disables the device path for the rest of the
# run, so a dead backend costs N watchdog timeouts total rather than
# one per remaining segment.  Read at ReplayDriver construction so
# tests (and bench children) tune them through the environment.
WATCHDOG_DEFAULT_S = 300.0  # generous: first dispatch includes XLA compile
BREAKER_DEFAULT_N = 3


def _watchdog_seconds() -> float:
    return float(os.environ.get("KSIM_REPLAY_WATCHDOG_S", str(WATCHDOG_DEFAULT_S)))


def _breaker_threshold() -> int:
    return int(os.environ.get("KSIM_REPLAY_BREAKER_N", str(BREAKER_DEFAULT_N)))


def _replay_tp() -> int:
    """``KSIM_REPLAY_TP``: lay every node-axis tensor of the segment
    program over a ``make_mesh(tp, dp=1)`` node mesh (round 17).  1 (the
    default) keeps the single-device layout.  The byte bound
    (``KSIM_REPLAY_FULL_BYTES``) and the preemption search bounds
    (``KSIM_REPLAY_CMAX``/``KSIM_REPLAY_VMAX``) are PER-SHARD budgets —
    record="full" and bounded-exact preemption scale with the mesh.
    Read at ReplayDriver construction; an explicit service ``shard_mesh``
    takes precedence over the env knob."""
    return max(int(os.environ.get("KSIM_REPLAY_TP", "1")), 1)


#: Minimum node rows per shard before _lower narrows the mesh width.
#: Empirical partitioner-hazard floor, NOT tunable: below it the SPMD
#: preemption scan silently doubled sel/nom values (see the narrowing
#: comment in _lower, docs/churn_floor.md, and the standalone
#: jax-only repro in tools/shard_repro.py).
_MIN_SHARD_NODES = 4


#: ``KSIM_REPLAY_DONATE`` (default on): donate the scan-carried cluster
#: state (``state0``) to the segment programs.  The carry is transferred
#: fresh every dispatch and never enters the id-keyed dev-const reuse
#: map, so XLA may alias its input buffer into the output instead of
#: holding TWO copies of cluster state per chip for the dispatch's
#: lifetime — on a fleet mesh that halves the per-chip carry footprint
#: (docs/scaling.md "2-D mesh (round 19)").  ``0`` is the escape hatch
#: for backends whose runtime mishandles input-output aliasing.  Read
#: at import (the jit wrappers are built once, at module load).
_REPLAY_DONATE = os.environ.get("KSIM_REPLAY_DONATE", "1") != "0"


#: Half-open cooldown doubling is bounded here: a backend that stays
#: dead costs one probe per hour at worst, never less often.
_BREAKER_COOLDOWN_CAP_S = 3600.0


def _breaker_cooldown_s() -> float:
    """``KSIM_REPLAY_BREAKER_COOLDOWN_S``: 0 (the default) keeps the
    round-8 STICKY breaker — openings only, the behavior every breaker
    test pins; > 0 arms half-open recovery (ISSUE 11): after the
    cooldown an open breaker admits ONE probe segment, a healthy probe
    closes it (re-promoting the driver to the device path), a failed
    probe re-opens with the cooldown doubled (bounded above)."""
    return float(os.environ.get("KSIM_REPLAY_BREAKER_COOLDOWN_S", "0"))

_I32_MIN = np.iinfo(np.int32).min
_I32_MAX = np.iinfo(np.int32).max


def _backoff_constants() -> tuple[int, int]:
    """(MAX_BACKOFF_PASSES, FLUSH_CAP_PASSES) from the ONE source of
    truth — the per-pass scheduler (lazy import: ksim_tpu.scheduler
    imports this package).  Tuning the service constants must retune the
    device kernel's mirror or the byte-identical count contract breaks."""
    from ksim_tpu.scheduler.service import SchedulerService

    return SchedulerService.MAX_BACKOFF_PASSES, SchedulerService.FLUSH_CAP_PASSES


class ReplayParityError(RuntimeError):
    """Device-resident replay state diverged from the host store — a bug
    in the delta application.  Deliberately NOT a SimulatorError: the
    classified fault handlers must re-raise it, never absorb it into a
    silent per-pass fallback (that would mask a kernel bug behind
    correct-looking counts).  Since the atomic segment reconcile
    (round 8) the store it fired against has been ROLLED BACK — the
    error is loud but no longer leaves device-computed placements
    behind."""


def _pod_key(pod: JSON) -> str:
    """The SERVICE's pod key scheme (`namespace/name`, namespace
    defaulted): op-created objects may lack metadata.namespace until the
    store defaults it, so every universe/event/backoff key must go
    through this one normalization."""
    return f"{namespace_of(pod) or 'default'}/{name_of(pod)}"


# ---------------------------------------------------------------------------
# Canonical slot simulation (the per-pass featurizer's NodeSlots history)
# ---------------------------------------------------------------------------


class _SlotSim:
    """Name-only replica of boundagg.NodeSlots' swap-remove assignment.

    The per-pass path's node tie-break order is the persistent
    featurizer's slot order, which depends on the entire churn history
    (a delete moves the LAST slot's node into the freed slot).  The
    lowering replays that exact evolution one step ahead of the store to
    produce the per-step rank tensors."""

    def __init__(self, slot_of: dict[str, int] | None = None, names: list[str] | None = None) -> None:
        self.slot_of: dict[str, int] = dict(slot_of or {})
        self.names: list[str] = list(names or [])

    def sync(
        self, current_names: Sequence[str]
    ) -> tuple[list[str], list[tuple[str, int]]]:
        """Mirror NodeSlots.sync for a post-step node-name set, in the
        store's name-sorted list order (what featurize receives).

        Returns ``(removed_names, changed_assignments)`` — the per-step
        DELTA, so the lowering maintains its rank row incrementally
        instead of re-walking the whole slot map every step (the old
        O(K*N) python loop).  Entries in ``changed_assignments`` apply
        in order (a name moved twice within one sync keeps its last
        slot)."""
        present = set(current_names)
        removed: list[str] = []
        changed: list[tuple[str, int]] = []
        gone = [s for nm, s in self.slot_of.items() if nm not in present]
        for s in sorted(gone, reverse=True):
            nm = self.names[s]
            last = len(self.names) - 1
            del self.slot_of[nm]
            removed.append(nm)
            if s != last:
                moved = self.names[last]
                self.names[s] = moved
                self.slot_of[moved] = s
                changed.append((moved, s))
            self.names.pop()
        for nm in current_names:
            if nm not in self.slot_of:
                self.slot_of[nm] = len(self.names)
                self.names.append(nm)
                changed.append((nm, len(self.names) - 1))
        return removed, changed


# ---------------------------------------------------------------------------
# Window parse (the store-independent prefix of segment lowering)
# ---------------------------------------------------------------------------


@dataclass
class _StepParse:
    """One step's net object events, window-locally validated."""

    pc: list[str] = field(default_factory=list)  # created pod keys
    pd: list[str] = field(default_factory=list)  # deleted pod keys
    nc: list[str] = field(default_factory=list)  # created node names
    nd: list[str] = field(default_factory=list)  # deleted node names
    flush: bool = False


@dataclass
class _WindowSpec:
    """The STORE-INDEPENDENT prefix of one window's lowering: event
    parsing, op-vocabulary screening, window-local name bookkeeping and
    created-object support checks — everything ``_lower`` needs that
    does not read the ClusterStore or the service's mutable state.

    Built either synchronously (inside the ``replay.lower`` span) or
    SPECULATIVELY for segment N+1 on the main thread while segment N's
    dispatch runs on the watchdogged worker (``replay.prelower`` span /
    fault site) — the double-buffered executor's overlap.  A speculative
    spec is keyed by the identity of its batch lists and discarded
    whenever the window it predicted is not the window that actually
    runs next (mid-window fallback, rollback, shorter consumed prefix,
    service reconfiguration).

    Store-membership validation (delete-of-unknown, name reuse against
    live objects, backoff-entry reuse) cannot run here; those checks are
    recorded in op order in ``checks`` and replayed against the live
    store/service sets by ``_lower``.  A window-local vocabulary miss
    stops the parse and lands in ``err_step``/``err_reason``: the
    consumer lowers only the supported prefix, and the erroring step
    heads the next window, which head-rejects it (prefix-granular
    fallback)."""

    wlen: int  # window length this spec was parsed for
    sched_names: tuple[str, ...]  # service config the support checks used
    n: int = 0  # op-screen prefix length (steps fully parsed)
    head_reason: str | None = None  # op-vocabulary reject of step 0
    err_step: int = _I32_MAX  # step where a window-local miss stopped parse
    err_reason: str | None = None
    steps: list[_StepParse] = field(default_factory=list)
    # (step, kind, key) store-membership checks, in op order; kind in
    # {"create_pod", "delete_pod", "create_node", "delete_node"}.
    checks: list[tuple[int, str, str]] = field(default_factory=list)
    created_pods: list[tuple[int, str, JSON]] = field(default_factory=list)
    created_nodes: list[tuple[int, JSON]] = field(default_factory=list)


# ---------------------------------------------------------------------------
# Persistent lowered-universe cache
# ---------------------------------------------------------------------------


class _LowerCache:
    """Lowered-universe state reused across CONSECUTIVE committed
    segments, making per-segment host lowering O(delta) instead of
    O(universe): the queue-sorted universe (cleaned pod objects + their
    static ``queue_sort_key`` tuples), the priority resolution, and —
    by keeping the surviving objects' IDENTITY stable — every per-pod
    featurizer/encoder memo row behind them.  Only objects created
    inside the new window are featurized fresh.

    Validity contract (docs/churn_floor.md "Incremental lowering +
    pipelined executor"): the cache is trustworthy exactly when nothing
    touched the store except committed device segments, which is what
    ``ClusterStore.mutation_epoch`` certifies — segment reconciles run
    in an epoch-exempt transaction, every other write moves the epoch.
    Invalidation is STRICT: any per-pass fallback, a segment rollback, a
    breaker trip, or an epoch mismatch (out-of-band store write) flushes
    the whole cache; the next lower rebuilds from the store and
    re-screens every object.  ``verify_segment``'s store-vs-device
    parity check (which runs inside every segment transaction) is what
    anchors the cached survivor view to the real store contents."""

    def __init__(self) -> None:
        self.valid = False
        self.epoch = -1
        self.keys: list[str] = []  # queue-sort order
        self.sort_keys: list[tuple] = []  # parallel queue_sort_key tuples
        self.clean_pods: list[JSON] = []  # parallel cleaned pending objects
        self.priority_of = None
        self.prio_gen = 0  # memo token for resolver-dependent per-pod keys
        self.sched_names = None  # profile set the survivors were screened against
        self.hits = 0
        self.misses = 0
        self.invalidations = 0

    def invalidate(self, reason: str) -> None:
        if not self.valid:
            return
        self.valid = False
        self.invalidations += 1
        self.keys = []
        self.sort_keys = []
        self.clean_pods = []
        self.priority_of = None
        self.sched_names = None
        TRACE.event("replay.cache_invalidate", reason=reason)

    def stats(self) -> dict:
        return {
            "hits": self.hits,
            "misses": self.misses,
            "invalidations": self.invalidations,
        }


# ---------------------------------------------------------------------------
# Static program configuration (jit cache key material)
# ---------------------------------------------------------------------------


@dataclass(frozen=True)
class _SegmentStatics:
    """Hashable statics of one compiled segment program."""

    k: int  # steps per dispatch
    q: int  # compacted queue width
    cap: int  # max_pods_per_pass (large sentinel when uncapped)
    n_tk: int  # inter-pod topology-key vocab width
    n_dom: int  # inter-pod padded domain count (segment id space)
    record: str = "selection"  # "selection" | "full" (streamed results)
    preempt: bool = False  # on-device DefaultPreemption victim search
    c_max: int = PREEMPT_CANDIDATES  # candidate-node scan bound (per shard)
    v_max: int = PREEMPT_VICTIMS  # victims-per-candidate bound (per shard)
    tp: int = 1  # node-axis mesh width (round 17 sharded replay)
    # Round 19: the vmap axis name the fleet program maps lanes over, or
    # None for a solo program.  With it set, the preemption-search gate
    # reduces its predicate over the lane axis (lax.psum) so the
    # lax.cond predicate stays UNBATCHED under vmap — the gate lowers
    # to a real XLA conditional instead of a both-branches select (the
    # select bomb, docs/scaling.md "2-D mesh (round 19)").
    lane_axis: "str | None" = None


# ---------------------------------------------------------------------------
# The compiled K-step program
# ---------------------------------------------------------------------------


@device_kernel(static=("st",))
def _derive_interpod(loc: dict, ipa: dict, st: _SegmentStatics) -> dict:
    """Local per-node term accumulators -> the domain-aggregated carry
    view the InterPodAffinity kernels consume (state/interpod.py
    cnt_node/ecnt_node/ew_node/total semantics):

    ``cnt[n, t] = sum over n' in n's term_tk[t]-domain of loc_cnt[n', t]``

    computed per topology key with one segment reduction (the key vocab
    is tiny and static, so the per-key results select together), and
    ``total[t]`` summed over key-carrying nodes only — exactly the
    encoder's "no topologyPair exists on a keyless node" rule."""
    import jax
    import jax.numpy as jnp

    node_dom = ipa["node_dom"]  # i32 [N, TK]
    term_tk = ipa["term_tk"]  # i32 [T]
    dom_t = ipa["dom_t"]  # i32 [N, T]
    out = {}
    for name, key in (("cnt", "cnt"), ("ecnt", "eat"), ("ew", "vw")):
        arr = loc[key]  # i32 [N, T]
        acc = jnp.zeros_like(arr)
        for k in range(st.n_tk):
            ids = node_dom[:, k]  # [N], -1 = key absent
            safe = jnp.where(ids >= 0, ids, st.n_dom)  # junk segment
            seg = jax.ops.segment_sum(arr, safe, num_segments=st.n_dom + 1)
            derived = jnp.where(ids[:, None] >= 0, seg[jnp.minimum(safe, st.n_dom)], 0)
            acc = jnp.where((term_tk == k)[None, :], derived, acc)
        out[name] = acc
    out["total"] = jnp.sum(jnp.where(dom_t >= 0, loc["cnt"], 0), axis=0)
    return out


@device_kernel(static=("st", "prog"))
def _segment_body(st: _SegmentStatics, prog, const: dict, ev: dict, state0: dict):
    """Run K scenario steps on-device.

    const: universe-static arrays — node statics (allocatable /
        allowed_pods / unschedulable), pod rows (requests / nonzero /
        tolerates / has_requests / spread-selector and inter-pod term
        rows; with preemption also priority / importance / start-time
        ranks), the full plugin aux pytree, and (full-record preemption)
        the per-plugin reason-bit resolvability table.
    ev: per-step event streams, leading axis K — pod/node create/delete
        index lists (-1 padded), the flush flag, the canonical rank
        tensor, the per-step active flag (False = tail padding: the step
        is a pure no-op), and (preemption) the per-step name-order node
        ranks + upstream candidate count.
    state0: the carried cluster tensor state at segment start.

    Returns (final_state, outputs) where outputs stack per-step selected
    node rows + attempted pod rows and the step aggregates, plus (full
    record) the per-attempt result tensors and (preemption) nominated
    nodes / victim rows / the bound-overflow flag."""
    import jax
    import jax.numpy as jnp

    from ksim_tpu.plugins.base import NodeStateView, PodBatch
    from ksim_tpu.engine.core import SCAN_UNROLL

    max_backoff, flush_cap = _backoff_constants()
    # _record_attempts' delay is min(2^(attempts_new-1), MAX) — computed
    # as a shift with the exponent clamped where the cap saturates.
    shift_cap = max(max_backoff.bit_length() - 1, 0)
    aux = const["aux"]
    nstat = const["node"]
    prow = const["pods"]
    ipa = aux["interpod"]
    P = prow["requests"].shape[0]
    N = nstat["allocatable"].shape[0]
    sel_rows = aux["spread"]["pod_sel_match"]  # bool [P, S]
    qm_rows = ipa["pod_term_match"]  # bool [P, T]
    eat_rows = ipa["pod_eat"]  # i32 [P, T]
    vw_rows = ipa["pod_vw"]  # i32 [P, T]
    n_filters = sum(1 for sp in prog.plugins if sp.filter_enabled)
    n_scores = sum(1 for sp in prog.plugins if sp.score_enabled)
    bits_dtype, final_dtype = prog._result_dtypes()
    # Effective search bounds: the configured statics are PER-SHARD
    # budgets (round 17) — multiplied by the mesh width, then clamped to
    # the padded axes (top_k needs k <= axis; small universes can't
    # overflow a bound wider than themselves anyway).  At tp=1 this is
    # the historical global bound; bounded-exact semantics are unchanged
    # (overflow still discards the segment before any store effect).
    c_eff = min(st.c_max * st.tp, N)
    v_eff = min(st.v_max * st.tp, P)

    def _victim_deltas(rows, act):
        """Summed universe-row contributions of ``rows`` where ``act``
        — the aggregate a victim set adds to (or removal subtracts
        from) one node's carried state (mirrors apply_pod_deletes)."""
        w = act[:, None]
        safe = jnp.clip(rows, 0, P - 1)
        return dict(
            req=jnp.sum(jnp.where(w, prow["requests"][safe], 0), axis=0),
            nz=jnp.sum(jnp.where(w, prow["nonzero_requests"][safe], 0), axis=0),
            cnt=jnp.sum(act.astype(jnp.int32)),
            sel=jnp.sum(jnp.where(w, sel_rows[safe].astype(jnp.int32), 0), axis=0),
            qm=jnp.sum(jnp.where(w, qm_rows[safe].astype(jnp.int32), 0), axis=0),
            eat=jnp.sum(jnp.where(w, eat_rows[safe], 0), axis=0),
            vw=jnp.sum(jnp.where(w, vw_rows[safe], 0), axis=0),
        )

    def _sub_victims(live: dict, node_t, d: dict) -> dict:
        """live minus a victim-delta dict at node index ``node_t`` (OOB
        index drops — pass N to no-op)."""
        live = dict(live)
        live["requested"] = live["requested"].at[node_t].add(-d["req"], mode="drop")
        live["nonzero_requested"] = live["nonzero_requested"].at[node_t].add(
            -d["nz"], mode="drop"
        )
        live["pod_count"] = live["pod_count"].at[node_t].add(-d["cnt"], mode="drop")
        live["spread"] = live["spread"].at[node_t].add(
            -d["sel"].astype(live["spread"].dtype), mode="drop"
        )
        live["ip_cnt"] = live["ip_cnt"].at[node_t].add(-d["qm"], mode="drop")
        live["ip_eat"] = live["ip_eat"].at[node_t].add(-d["eat"], mode="drop")
        live["ip_vw"] = live["ip_vw"].at[node_t].add(-d["vw"], mode="drop")
        return live

    def apply_pod_deletes(s: dict, pdel: jnp.ndarray) -> dict:
        v = pdel >= 0
        safe = jnp.clip(pdel, 0, P - 1)
        bnode = jnp.where(v, s["bound"][safe], -1)  # [Ed]
        tgt = jnp.where(bnode >= 0, bnode, N)  # OOB rows drop
        s = dict(s)
        s["requested"] = s["requested"].at[tgt].add(
            -prow["requests"][safe], mode="drop"
        )
        s["nonzero_requested"] = s["nonzero_requested"].at[tgt].add(
            -prow["nonzero_requests"][safe], mode="drop"
        )
        s["pod_count"] = s["pod_count"].at[tgt].add(-1, mode="drop")
        s["spread"] = s["spread"].at[tgt].add(
            -sel_rows[safe].astype(s["spread"].dtype), mode="drop"
        )
        s["ip_cnt"] = s["ip_cnt"].at[tgt].add(
            -qm_rows[safe].astype(s["ip_cnt"].dtype), mode="drop"
        )
        s["ip_eat"] = s["ip_eat"].at[tgt].add(-eat_rows[safe], mode="drop")
        s["ip_vw"] = s["ip_vw"].at[tgt].add(-vw_rows[safe], mode="drop")
        gone = jnp.where(v, pdel, P)
        s["alive"] = s["alive"].at[gone].set(False, mode="drop")
        s["bound"] = s["bound"].at[gone].set(-1, mode="drop")
        return s

    def apply_node_events(s: dict, ndel, ncre) -> dict:
        s = dict(s)
        dmask = (
            jnp.zeros(N, bool).at[jnp.where(ndel >= 0, ndel, N)].set(True, mode="drop")
        )
        s["valid"] = s["valid"] & ~dmask
        keep = ~dmask
        s["requested"] = jnp.where(keep[:, None], s["requested"], 0)
        s["nonzero_requested"] = jnp.where(keep[:, None], s["nonzero_requested"], 0)
        s["pod_count"] = jnp.where(keep, s["pod_count"], 0)
        s["spread"] = jnp.where(keep[:, None], s["spread"], 0)
        s["ip_cnt"] = jnp.where(keep[:, None], s["ip_cnt"], 0)
        s["ip_eat"] = jnp.where(keep[:, None], s["ip_eat"], 0)
        s["ip_vw"] = jnp.where(keep[:, None], s["ip_vw"], 0)
        # Drained nodes' pods re-enter the pending queue (the runner's
        # requeue_on_node_delete — their backoff state is untouched, the
        # per-pass entry was popped when they scheduled).
        requeued = s["alive"] & (s["bound"] >= 0) & dmask[jnp.clip(s["bound"], 0, N - 1)]
        s["bound"] = jnp.where(requeued, -1, s["bound"])
        s["valid"] = (
            s["valid"].at[jnp.where(ncre >= 0, ncre, N)].set(True, mode="drop")
        )
        return s

    def _preempt_search(s, live, pod, bits_mat, rank_names, want_k, lower):
        """DefaultPreemption's victim search for one unschedulable pod,
        against the LIVE mid-pass state (earlier binds + earlier
        victims), as bounded tensor ops:

        - candidate nodes = nodes holding >= 1 lower-priority victim,
          resolvable per the reason-bit table (full-record mode only —
          the per-pass path has no bits in selection mode), examined in
          live name order like upstream's node loop (first c_max;
          overflow discards the segment);
        - per candidate, the fit re-check runs the profile's compiled
          filter chain over a hypothetical state with the victims'
          aggregates subtracted (the lowering gates on the filter set
          matching the oracle fit chain, preemption.py
          ORACLE_FIT_FILTER_NAMES);
        - the reprieve loop re-adds victims in MoreImportantPod order
          (the pre-lowered imp_rank) as a bounded fori_loop (first
          v_max; overflow discards);
        - pickOneNodeForPreemption is the lexicographic min over
          (max victim prio, prio sum, count, -latest earliest-top-start,
          discovery order) — exactly the host's narrowing cascade.

        Returns (live', nominated_slot, victim_rows, overflow)."""
        valid_now = s["valid"]
        if st.record == "full":
            fail = bits_mat != 0  # [F, N]
            fail_any = jnp.any(fail, axis=0)
            first = jnp.argmax(fail, axis=0)
            bval = jnp.take_along_axis(bits_mat, first[None, :], axis=0)[0]
            tw = const["resolv"].shape[1]
            bval = jnp.clip(bval, 0, tw - 1)
            resolvable = const["resolv"][first, bval] & fail_any
        else:
            resolvable = jnp.ones(N, bool)
        tgtn = jnp.where(lower, live["bound"], N)
        vcnt = jnp.zeros(N, jnp.int32).at[tgtn].add(1, mode="drop")
        examine = (vcnt > 0) & valid_now & resolvable
        over_c = jnp.sum(examine.astype(jnp.int32)) > c_eff
        keyed = jnp.where(examine, rank_names, _I32_MAX)
        negk, cand_nodes = jax.lax.top_k(-keyed, c_eff)
        cand_act = negk > -_I32_MAX

        def eval_fit(node_i, rows, act):
            """Does the preemptor pass every filter at node_i with the
            ``act`` rows' aggregates removed?  Full kernel-chain eval
            (spread/inter-pod are global: their carries re-derive from
            the modified locals)."""
            d = _victim_deltas(rows, act)
            view = NodeStateView(
                allocatable=nstat["allocatable"],
                allowed_pods=nstat["allowed_pods"],
                valid=valid_now,
                unschedulable=nstat["unschedulable"],
                requested=live["requested"].at[node_i].add(-d["req"]),
                nonzero_requested=live["nonzero_requested"].at[node_i].add(-d["nz"]),
                pod_count=live["pod_count"].at[node_i].add(-d["cnt"]),
            )
            carr = prog.init_carries(aux)
            carr["PodTopologySpread"] = live["spread"].at[node_i].add(
                -d["sel"].astype(live["spread"].dtype)
            )
            carr["InterPodAffinity"] = _derive_interpod(
                {
                    "cnt": live["ip_cnt"].at[node_i].add(-d["qm"]),
                    "eat": live["ip_eat"].at[node_i].add(-d["eat"]),
                    "vw": live["ip_vw"].at[node_i].add(-d["vw"]),
                },
                ipa,
                st,
            )
            okf, _bits = prog._eval_filters(view, pod, aux, carr)
            return okf[node_i]

        def cand_body(i, acc):
            is_c, maxp_a, sump_a, cnt_a, est_a, nrank_a, node_a, vic_a, over = acc
            n_i = cand_nodes[i]
            act = cand_act[i]
            on_n = lower & (live["bound"] == n_i)
            kv = jnp.where(on_n, prow["imp_rank"], _I32_MAX)
            negv, vrows = jax.lax.top_k(-kv, v_eff)
            vact = negv > -_I32_MAX
            over = over | (act & (jnp.sum(on_n.astype(jnp.int32)) > v_eff))
            fit0 = eval_fit(n_i, vrows, vact)

            def rep_body(v, rc):
                removed, vic = rc
                test = removed.at[v].set(False)
                okv = eval_fit(n_i, vrows, vact & test)
                back = vact[v] & okv  # reprieved: stays re-added
                removed = jnp.where(back, test, removed)
                vic = vic.at[v].set(vact[v] & ~okv)
                return removed, vic

            _removed, vic = jax.lax.fori_loop(
                0, v_eff, rep_body, (vact, jnp.zeros(v_eff, bool))
            )
            vprio = prow["priority"][vrows]
            have = jnp.any(vic)
            maxp = jnp.max(jnp.where(vic, vprio, _I32_MIN))
            est = jnp.min(
                jnp.where(vic & (vprio == maxp), prow["start_rank"][vrows], _I32_MAX)
            )
            return (
                is_c.at[i].set(act & fit0),
                maxp_a.at[i].set(maxp),
                sump_a.at[i].set(jnp.sum(jnp.where(vic, vprio, 0))),
                cnt_a.at[i].set(jnp.sum(vic.astype(jnp.int32))),
                est_a.at[i].set(
                    jnp.where(have, est, jnp.reshape(const["empty_start_rank"], ()))
                ),
                nrank_a.at[i].set(rank_names[n_i]),
                node_a.at[i].set(n_i),
                vic_a.at[i].set(jnp.where(vic, vrows, -1)),
                over,
            )

        C = c_eff
        acc0 = (
            jnp.zeros(C, bool),
            jnp.zeros(C, jnp.int32),
            jnp.zeros(C, jnp.int32),
            jnp.zeros(C, jnp.int32),
            jnp.zeros(C, jnp.int32),
            jnp.zeros(C, jnp.int32),
            jnp.zeros(C, jnp.int32),
            jnp.full((C, v_eff), -1, jnp.int32),
            over_c,
        )
        is_c, maxp_a, sump_a, cnt_a, est_a, nrank_a, node_a, vic_a, over = (
            jax.lax.fori_loop(0, C, cand_body, acc0)
        )
        # Upstream stops after `want` successful candidates (discovery =
        # name order); narrowing criteria 1-4 then "first" compose into
        # one lexicographic argmin.
        pos = jnp.cumsum(is_c.astype(jnp.int32)) - 1
        keep = is_c & (pos < want_k)
        any_c = jnp.any(keep)
        m = keep
        for arr, take_min in (
            (maxp_a, True),
            (sump_a, True),
            (cnt_a, True),
            (est_a, False),
            (nrank_a, True),
        ):
            kv = jnp.where(m, arr, _I32_MAX if take_min else _I32_MIN)
            tgt = jnp.min(kv) if take_min else jnp.max(kv)
            m = m & (arr == tgt)
        chosen = jnp.argmax(m)
        nom = jnp.where(any_c, node_a[chosen], -1).astype(jnp.int32)
        vic_rows = jnp.where(any_c, vic_a[chosen], -1)
        vact2 = vic_rows >= 0
        d = _victim_deltas(vic_rows, vact2)
        live = _sub_victims(live, jnp.where(any_c, nom, N), d)
        gone = jnp.where(vact2, vic_rows, P)
        live["alive"] = live["alive"].at[gone].set(False, mode="drop")
        live["bound"] = live["bound"].at[gone].set(-1, mode="drop")
        live["nominated"] = (
            live["nominated"].at[jnp.where(any_c, pod.index, P)].set(True, mode="drop")
        )
        return live, nom, vic_rows, over

    def step(carry, ev_k):
        def run_step(s):
            return _run_step(s, ev_k)

        def skip_step(s):
            z = {
                "sel": jnp.full(st.q, -1, jnp.int32),
                "idx": jnp.full(st.q, P, jnp.int32),
                "scheduled": jnp.zeros((), jnp.int32),
                "unschedulable": jnp.zeros((), jnp.int32),
                "eligible": jnp.zeros((), jnp.int32),
                "pass_count": s["pass_count"],
                "pending_after": jnp.zeros((), jnp.int32),
            }
            if st.preempt:
                z["nom"] = jnp.full(st.q, -1, jnp.int32)
                z["vic"] = jnp.full((st.q, v_eff), -1, jnp.int32)
                z["overflow"] = jnp.zeros((), bool)
            if st.record == "full":
                z["bits"] = jnp.zeros((st.q, n_filters, N), bits_dtype)
                z["raw"] = jnp.zeros((st.q, n_scores, N), jnp.int32)
                z["final"] = jnp.zeros((st.q, n_scores, N), final_dtype)
            return s, z

        # Tail-padded (inactive) steps are pure no-ops: same compiled K
        # shape, zero semantic effect.  Scalar-pred cond in a scan is a
        # real XLA conditional, so padding costs nothing at runtime.
        return jax.lax.cond(ev_k["active"], run_step, skip_step, dict(carry))

    def _run_step(s, ev_k):
        s = dict(s)
        s = apply_pod_deletes(s, ev_k["pod_delete"])
        s = apply_node_events(s, ev_k["node_delete"], ev_k["node_create"])
        s["alive"] = (
            s["alive"]
            .at[jnp.where(ev_k["pod_create"] >= 0, ev_k["pod_create"], P)]
            .set(True, mode="drop")
        )
        # flush_backoff (service semantics): existing entries' remaining
        # wait capped at min(attempts-1, FLUSH_CAP) from the pre-pass
        # count.
        has_entry = s["attempts"] > 0
        flushed = jnp.minimum(
            s["retry_at"],
            s["pass_count"] + jnp.minimum(s["attempts"] - 1, flush_cap),
        )
        s["retry_at"] = jnp.where(
            ev_k["flush"] & has_entry, flushed, s["retry_at"]
        )
        any_valid = jnp.any(s["valid"])
        pc = s["pass_count"] + any_valid.astype(jnp.int32)
        s["pass_count"] = pc

        # Queue build: pending, not backed off, in universe (= queue
        # sort) order, first min(eligible, cap) attempted.
        in_backoff = has_entry & (s["retry_at"] >= pc)
        elig = s["alive"] & (s["bound"] < 0) & ~in_backoff
        pos = jnp.cumsum(elig.astype(jnp.int32)) - 1
        att = elig & (pos < min(st.cap, st.q)) & any_valid
        idx_q = (
            jnp.full(st.q, P, jnp.int32)
            .at[jnp.where(att, pos, st.q)]
            .set(jnp.arange(P, dtype=jnp.int32), mode="drop")
        )
        clamped = jnp.minimum(idx_q, P - 1)
        pods_q = PodBatch(
            requests=prow["requests"][clamped],
            nonzero_requests=prow["nonzero_requests"][clamped],
            valid=idx_q < P,
            tolerates_unschedulable=prow["tolerates_unschedulable"][clamped],
            has_requests=prow["has_requests"][clamped],
            index=clamped,
        )

        node_state = NodeStateView(
            allocatable=nstat["allocatable"],
            allowed_pods=nstat["allowed_pods"],
            valid=s["valid"],
            unschedulable=nstat["unschedulable"],
            requested=s["requested"],
            nonzero_requested=s["nonzero_requested"],
            pod_count=s["pod_count"],
        )
        carries = prog.init_carries(aux)
        carries["PodTopologySpread"] = s["spread"]
        carries["InterPodAffinity"] = _derive_interpod(
            {"cnt": s["ip_cnt"], "eat": s["ip_eat"], "vw": s["ip_vw"]}, ipa, st
        )
        rank = ev_k["rank"]  # i32 [N], canonical slot, big when dead
        if st.preempt:
            # The mid-pass LIVE view (what the store holds while
            # _bind_results iterates): this pass's binds so far PLUS
            # preemption victims removed so far.  The scan's filter/
            # score state (nstate + pcarries) stays binds-only — the
            # per-pass engine ran on the pre-pass snapshot.
            live0 = {
                k: s[k]
                for k in (
                    "alive", "bound", "requested", "nonzero_requested",
                    "pod_count", "spread", "ip_cnt", "ip_eat", "ip_vw",
                    "nominated",
                )
            }
        else:
            live0 = {}

        def _bind_live(live: dict, pb, best) -> dict:
            """Apply one pod attempt's bind to the live view (a failed
            attempt — best < 0 — drops every scatter, so this is a
            no-op for it).  Shared VERBATIM by the bind scan and the
            gated search scan below: the search phase re-derives the
            exact live sequence by replaying these binds, so the op
            order (and with it f32/i32 bit-exactness) must be the one
            sequence both phases execute."""
            j = pb.index
            tgtb = jnp.where(best >= 0, best, N)
            bj = jnp.where(best >= 0, j, P)
            live = dict(live)
            live["requested"] = live["requested"].at[tgtb].add(
                pb.requests, mode="drop"
            )
            live["nonzero_requested"] = live["nonzero_requested"].at[tgtb].add(
                pb.nonzero_requests, mode="drop"
            )
            live["pod_count"] = live["pod_count"].at[tgtb].add(1, mode="drop")
            live["spread"] = live["spread"].at[tgtb].add(
                sel_rows[j].astype(live["spread"].dtype), mode="drop"
            )
            live["ip_cnt"] = live["ip_cnt"].at[tgtb].add(
                qm_rows[j].astype(live["ip_cnt"].dtype), mode="drop"
            )
            live["ip_eat"] = live["ip_eat"].at[tgtb].add(eat_rows[j], mode="drop")
            live["ip_vw"] = live["ip_vw"].at[tgtb].add(vw_rows[j], mode="drop")
            live["bound"] = live["bound"].at[bj].set(best, mode="drop")
            # The apiserver clears nominations on bind.
            live["nominated"] = live["nominated"].at[bj].set(False, mode="drop")
            return live

        def pod_body(pcarry, pb):
            nstate, pcarries, live = pcarry
            from ksim_tpu.plugins.base import PodView

            pod = PodView(
                requests=pb.requests,
                nonzero_requests=pb.nonzero_requests,
                tolerates_unschedulable=pb.tolerates_unschedulable,
                has_requests=pb.has_requests,
                index=pb.index,
            )
            ok, _bits, _raw, _final, total = prog._eval_one(
                nstate, pod, aux, pcarries
            )
            # selectHost with the canonical-slot tie-break: max summed
            # score, minimal rank — the node the per-pass argmax (lowest
            # slot index) picks.
            feasible = jnp.any(ok)
            masked = jnp.where(ok, total, _I32_MIN)
            cand = ok & (masked == jnp.max(masked))
            best = jnp.argmin(jnp.where(cand, rank, _I32_MAX)).astype(jnp.int32)
            best = jnp.where(feasible & pb.valid, best, -1)
            nstate = nstate.commit(best, pb.requests, pb.nonzero_requests)
            pcarries = prog._commit_carries(pcarries, pod, best, aux)
            out_pod = {"best": best}
            if st.record == "full":
                out_pod["bits"] = (
                    jnp.stack(_bits) if _bits else jnp.zeros((0, N), jnp.int32)
                ).astype(bits_dtype)
                out_pod["raw"] = (
                    jnp.stack(_raw) if _raw else jnp.zeros((0, N), jnp.int32)
                )
                out_pod["final"] = (
                    jnp.stack(_final) if _final else jnp.zeros((0, N), jnp.int32)
                ).astype(final_dtype)
            if st.preempt:
                # Phase A (round 19): apply the bind and emit only the
                # search TRIGGER — the victim search itself moved to a
                # second, step-level `lax.cond`-gated scan below.  The
                # trigger is computed against the binds-only live view,
                # which can only OVER-approximate the exact one: a
                # search removes victims (alive <- False, bound <- -1),
                # shrinking `lower`, and never touches anything `best`
                # depends on (nstate / pcarries are binds-only — see
                # the live0 comment above).  So exact-pred true implies
                # pred_hat true, and a step whose every pred_hat is
                # false provably ran no search — its binds-only live IS
                # the exact post-step live.
                live = _bind_live(live, pb, best)
                j = pb.index
                prio_p = prow["priority"][j]
                lower = (
                    live["alive"] & (live["bound"] >= 0) & (prow["priority"] < prio_p)
                )
                out_pod["pred_hat"] = (
                    pb.valid
                    & (best < 0)
                    & prow["preempt_ok"][j]
                    & jnp.any(lower)
                )
            return (nstate, pcarries, live), out_pod

        (node_state, carries, live), pod_outs = jax.lax.scan(
            pod_body, (node_state, carries, live0), pods_q, unroll=SCAN_UNROLL
        )
        sel = pod_outs["best"]
        bound_mask = (idx_q < P) & (sel >= 0)
        fail_mask = (idx_q < P) & (sel < 0)
        if st.preempt:
            # Phase B (round 19): the victim search, behind ONE
            # step-level conditional.  `go` is the disjunction of the
            # phase-A triggers; in the fleet program (st.lane_axis set)
            # it is additionally psum-reduced over the vmap lane axis,
            # which makes the predicate UNBATCHED — the cond lowers to
            # a real XLA conditional instead of the both-branches
            # select a batched predicate forces (the select bomb,
            # docs/scaling.md "2-D mesh (round 19)").  Lane semantics:
            # if ANY lane wants a search this step, EVERY lane replays
            # the search scan (lanes without triggers recompute their
            # binds-only live, byte-identically); steps where no lane
            # triggers skip the ~c_eff*(v_eff+1) search machinery
            # entirely.
            go = jnp.any(pod_outs["pred_hat"])
            if st.lane_axis is not None:
                go = jax.lax.psum(go.astype(jnp.int32), st.lane_axis) > 0

            with_bits = st.record == "full" and n_filters > 0
            search_xs = (pods_q, sel) + (
                (pod_outs["bits"],) if with_bits else ()
            )

            def search_pods(_):
                # Exact replay: rescan the queue from the pre-pass live
                # snapshot, re-applying each bind via the SAME
                # _bind_live the bind scan used and running the
                # original per-pod search cond — the one interleaved
                # bind/search sequence round 12 shipped, byte for byte.
                # (`best` comes in from phase A: searches never feed
                # back into it.)  Stored bits are value-identical to
                # the raw i32 stack the old in-scan search consumed:
                # _result_dtypes picks bits_dtype wide enough for every
                # declared reason bit.
                def search_body(live, xs):
                    if with_bits:
                        pb, best, bits_mat = xs
                    else:
                        (pb, best), bits_mat = xs, None
                    from ksim_tpu.plugins.base import PodView

                    pod = PodView(
                        requests=pb.requests,
                        nonzero_requests=pb.nonzero_requests,
                        tolerates_unschedulable=pb.tolerates_unschedulable,
                        has_requests=pb.has_requests,
                        index=pb.index,
                    )
                    live = _bind_live(live, pb, best)
                    j = pb.index
                    prio_p = prow["priority"][j]
                    lower = (
                        live["alive"]
                        & (live["bound"] >= 0)
                        & (prow["priority"] < prio_p)
                    )
                    pred = (
                        pb.valid
                        & (best < 0)
                        & prow["preempt_ok"][j]
                        & jnp.any(lower)
                    )

                    def do_search(op):
                        lv, lw = op
                        return _preempt_search(
                            s, lv, pod, bits_mat, ev_k["name_rank"],
                            ev_k["want"], lw,
                        )

                    def no_search(op):
                        lv, _lw = op
                        return (
                            lv,
                            jnp.int32(-1),
                            jnp.full(v_eff, -1, jnp.int32),
                            jnp.zeros((), bool),
                        )

                    live, nom, vicr, over = jax.lax.cond(
                        pred, do_search, no_search, (live, lower)
                    )
                    return live, {"nom": nom, "vic": vicr, "over": over}

                return jax.lax.scan(
                    search_body, dict(live0), search_xs, unroll=SCAN_UNROLL
                )

            def skip_search(_):
                return dict(live), {
                    "nom": jnp.full(st.q, -1, jnp.int32),
                    "vic": jnp.full((st.q, v_eff), -1, jnp.int32),
                    "over": jnp.zeros(st.q, bool),
                }

            live, souts = jax.lax.cond(go, search_pods, skip_search, 0)
        if st.preempt:
            # live already holds binds + victim removals: it IS the
            # post-step state.
            for k in (
                "alive", "bound", "requested", "nonzero_requested",
                "pod_count", "spread", "ip_cnt", "ip_eat", "ip_vw",
                "nominated",
            ):
                s[k] = live[k]
        else:
            s["requested"] = node_state.requested
            s["nonzero_requested"] = node_state.nonzero_requested
            s["pod_count"] = node_state.pod_count
            # The committed spread carry is node-local — carry it forward.
            s["spread"] = carries["PodTopologySpread"]
            bind_node = jnp.where(bound_mask, sel, N)
            s["ip_cnt"] = s["ip_cnt"].at[bind_node].add(
                qm_rows[clamped].astype(s["ip_cnt"].dtype), mode="drop"
            )
            s["ip_eat"] = s["ip_eat"].at[bind_node].add(eat_rows[clamped], mode="drop")
            s["ip_vw"] = s["ip_vw"].at[bind_node].add(vw_rows[clamped], mode="drop")
            s["bound"] = s["bound"].at[jnp.where(bound_mask, idx_q, P)].set(
                sel, mode="drop"
            )
            s["nominated"] = (
                s["nominated"]
                .at[jnp.where(bound_mask, idx_q, P)]
                .set(False, mode="drop")
            )
        # Backoff bookkeeping (_record_attempts): success pops the entry,
        # failure doubles the delay (capped) — UNLESS the pod holds a
        # nomination (from this pass or an earlier one): a nominated pod
        # expects to schedule as soon as its victims are gone, so the
        # per-pass path pops its entry instead of backing it off.
        a_prev = s["attempts"][clamped]
        nomd = s["nominated"][clamped]
        delay = jnp.minimum(1 << jnp.minimum(a_prev, shift_cap), max_backoff)
        succ_idx = jnp.where(bound_mask, idx_q, P)
        pop_idx = jnp.where(fail_mask & nomd, idx_q, P)
        inc_idx = jnp.where(fail_mask & ~nomd, idx_q, P)
        s["attempts"] = (
            s["attempts"]
            .at[succ_idx].set(0, mode="drop")
            .at[pop_idx].set(0, mode="drop")
            .at[inc_idx].set(a_prev + 1, mode="drop")
        )
        s["retry_at"] = (
            s["retry_at"]
            .at[succ_idx].set(0, mode="drop")
            .at[pop_idx].set(0, mode="drop")
            .at[inc_idx].set(pc + delay, mode="drop")
        )
        out = {
            "sel": sel,
            "idx": idx_q,
            # astype pins the cond-branch dtype (x64 mode promotes sums
            # of i32 to i64, and the inactive skip branch emits i32).
            "scheduled": jnp.sum(bound_mask.astype(jnp.int32)).astype(jnp.int32),
            "unschedulable": jnp.sum(fail_mask.astype(jnp.int32)).astype(jnp.int32),
            # Zero when the pass never ran (no valid nodes: the per-pass
            # path returns before even building the queue) — this is what
            # the featurize-schedule validation and slot advancing key on.
            "eligible": jnp.where(
                any_valid, jnp.sum(elig.astype(jnp.int32)), 0
            ).astype(jnp.int32),
            "pass_count": pc,
            "pending_after": jnp.sum(
                (s["alive"] & (s["bound"] < 0)).astype(jnp.int32)
            ).astype(jnp.int32),
        }
        if st.preempt:
            out["nom"] = souts["nom"]
            out["vic"] = souts["vic"]
            out["overflow"] = jnp.any(souts["over"])
        if st.record == "full":
            out["bits"] = pod_outs["bits"]
            out["raw"] = pod_outs["raw"]
            out["final"] = pod_outs["final"]
        return s, out

    final_state, outs = jax.lax.scan(step, dict(state0), ev)
    return final_state, outs


#: Donation (round 19): argument 4 is the carried cluster state.  Both
#: executors transfer it FRESH every dispatch — the id-keyed device
#: reuse map covers the CONST leaves only (_pack_plan_buffers /
#: _shard_plan_buffers put ``(ev, state0)`` in the per-dispatch batch
#: unconditionally) — so donating it can never hand XLA a buffer a
#: later dispatch still needs, and the output carry reuses the input's
#: allocation instead of holding two copies of ``[N]``/``[N, R]``
#: cluster state per chip (SNIPPETS.md scan-carry donation idiom; the
#: fleet's dominant per-lane footprint).  ``KSIM_REPLAY_DONATE=0``
#: restores the copying program.
#:
#: MESH dispatches never donate (the ``_nodonate`` twins below): on the
#: forced-8-virtual-device CPU backend, donating the carry of a
#: multi-device (dp, tp) program made replay diverge from the store
#: NONDETERMINISTICALLY at 1200-event fleet scale (ReplayParityError
#: with the device view AHEAD of the store, or silently wrong counts)
#: while any host-sync instrumentation made it pass — a timing race in
#: input-output aliasing across the virtual devices, not a logic bug:
#: the same program is byte-stable donation-off (repeated-trial
#: bisection, round 19) and single-device donation is locked by
#: tests/test_replay_device.py.  Virtual CPU devices share one host
#: allocator, so per-device "exclusive" donated buffers can alias in
#: ways real per-chip HBM cannot; re-evaluate on silicon before
#: donating mesh carries.
_DONATE_ARGNUMS = (4,) if _REPLAY_DONATE else ()


@partial(jax.jit, static_argnums=(0, 1), donate_argnums=_DONATE_ARGNUMS)
@device_kernel(static=("st", "prog"))
def _segment_fn(st: _SegmentStatics, prog, const: dict, ev: dict, state0: dict):
    """Solo replay program: ``_segment_body`` jitted with the carry
    donated (see ``_DONATE_ARGNUMS``).  The jit boundary lives on this
    thin wrapper — not on the body — so the fleet program can vmap the
    UNJITTED body: donation must be declared on the outermost jit, and
    a jit-inside-vmap would re-trace per lane."""
    return _segment_body(st, prog, const, ev, state0)


@partial(jax.jit, static_argnums=(0, 1))
@device_kernel(static=("st", "prog"))
def _segment_fn_nodonate(
    st: _SegmentStatics, prog, const: dict, ev: dict, state0: dict
):
    """``_segment_fn`` without carry donation — the MESH twin.  Sharded
    (tp > 1) dispatches route here: see the ``_DONATE_ARGNUMS`` note for
    the virtual-device aliasing race that forbids donating multi-device
    carries on this backend."""
    return _segment_body(st, prog, const, ev, state0)


def _fleet_segment_impl(
    st: _SegmentStatics, prog, const: dict, ev: dict, state0: dict
):
    """Shared traced body of the fleet program (see ``_fleet_segment_fn``
    for the semantics); the donating and non-donating jit twins both
    wrap this so the vmap/lane-axis structure is written once."""
    import dataclasses

    import jax

    lane_st = dataclasses.replace(st, lane_axis="lane")
    return jax.vmap(
        lambda s: _segment_body(lane_st, prog, const, ev, s),
        axis_name="lane",
    )(state0)


@partial(jax.jit, static_argnums=(0, 1), donate_argnums=_DONATE_ARGNUMS)
@device_kernel(static=("st", "prog"))
def _fleet_segment_fn(st: _SegmentStatics, prog, const: dict, ev: dict, state0: dict):
    """Fleet replay: advance S INDEPENDENT trajectories by K steps in one
    dispatch — ``_segment_body`` vmapped over a leading lane axis on the
    carried cluster state (``state0``), with the lane axis NAMED: the
    statics gain ``lane_axis="lane"`` so the preemption-search gate can
    psum its trigger over lanes and keep a lane-uniform (unbatched)
    ``lax.cond`` predicate — the round-19 select-bomb fix.

    ``const`` AND ``ev`` are closed over, i.e. broadcast across lanes:
    the fleet's contract is that every grouped lane shares ONE lowered
    plan (engine/fleet.py lowers it once via the cohort leader), so the
    universe tables and the per-step event streams are lane-identical by
    construction.  Keeping ``ev`` unbatched is load-bearing, not just a
    transfer saving: the per-step inactive-tail ``lax.cond`` predicates
    on ``ev['active']``, and under vmap a cond with a BATCHED predicate
    lowers to select — both branches execute — while an unbatched
    predicate keeps the real conditional, so tail padding stays free in
    the batched program exactly as it is solo.  (The same select
    semantics are why priority-flat windows lower preempt-free —
    ``_lower``'s ``preempt_plan`` screen.)  Per-lane event DELTAS are
    the ROADMAP's fleet round 2; they will stack ``ev`` and re-split
    this axis handling.

    The kernels are RNG-free and every per-lane reduction runs over the
    same axes in the same order as the solo program, so each lane's
    slice of the outputs is byte-identical to its solo ``_segment_fn``
    dispatch — the fleet parity lock (tests/test_replay_device.py,
    `make lock-check`)."""
    return _fleet_segment_impl(st, prog, const, ev, state0)


@partial(jax.jit, static_argnums=(0, 1))
@device_kernel(static=("st", "prog"))
def _fleet_segment_fn_nodonate(
    st: _SegmentStatics, prog, const: dict, ev: dict, state0: dict
):
    """``_fleet_segment_fn`` without carry donation — the MESH twin.
    Fleet dispatches on a (dp, tp) mesh route here: donating a
    multi-device carry raced on the virtual CPU backend (see the
    ``_DONATE_ARGNUMS`` note); single-device fleet packs keep the
    donating twin."""
    return _fleet_segment_impl(st, prog, const, ev, state0)


# ---------------------------------------------------------------------------
# Host driver: segment lowering, dispatch, reconcile
# ---------------------------------------------------------------------------


@dataclass
class AttemptOutcome:
    """One scheduling attempt within a device step, in commit order —
    everything the reconcile needs to mirror the per-pass path's store
    writes for that pod: the bind (or nomination), the preemption
    victims to evict right after the pod's own write, and the fully
    rendered record="full" result annotations."""

    namespace: str
    name: str
    node: str | None  # bound node (None = unschedulable this pass)
    nominated: str | None  # newly nominated node (preemption)
    victims: list[tuple[str, str]]  # (namespace, name) in reprieve order
    anno: dict | None  # record="full" annotations (None in selection)


@dataclass
class StepOutcome:
    """One device-computed scheduling pass, ready for store reconcile."""

    scheduled: int
    unschedulable: int
    pending_after: int
    eligible: int  # queue size before the cap (0 = the pass never featurized)
    # (namespace, name, node_name) in queue (commit) order.
    binds: list[tuple[str, str, str]] = field(default_factory=list)
    # Per-attempt detail (preemption / full-record segments); None means
    # the binds list is the whole story (pure selection mode).
    attempts: "list[AttemptOutcome] | None" = None


@dataclass
class SegmentOutcome:
    steps: list[StepOutcome]
    pass_count: int
    # namespace/name -> (attempts, retry_at) for the service backoff sync.
    backoff: dict[str, tuple[int, int]]
    # Device end-of-segment views for the store parity check.
    bound_view: dict[str, str]  # pod key -> node name
    pending_view: set[str]  # pod keys


def _cleaned_pending(pod: JSON) -> JSON:
    """The pod as the per-pass path would featurize it when PENDING
    (node-drain requeue shape: spec.nodeName and status.phase cleared) —
    identity-cached per source object so the featurizer's per-pod memo
    rows survive across segments."""
    from ksim_tpu.state import objcache

    def build() -> JSON:
        spec = dict(pod.get("spec") or {})
        spec.pop("nodeName", None)
        status = dict(pod.get("status") or {})
        status.pop("phase", None)
        return dict(pod, spec=spec, status=status)

    if not pod.get("spec", {}).get("nodeName") and not pod.get("status", {}).get(
        "phase"
    ):
        return pod
    return objcache.cached("replay_clean", pod, build)


class ReplayDriver:
    """Segment-batched device replay over a ClusterStore + SchedulerService.

    One instance per ScenarioRunner run.  ``try_segment`` lowers K steps
    against the CURRENT store state and runs them in a single dispatch;
    ``None`` means the segment is outside the supported vocabulary and
    the caller must fall back to the per-pass path for those steps."""

    def __init__(
        self,
        store,
        service,
        *,
        k: int = SEGMENT_STEPS,
        requeue_on_node_delete: bool = True,
        lane: "int | None" = None,
        lane_faults=None,
        ingest_hook=None,
    ) -> None:
        self.store = store
        self.service = service
        self.k = max(int(k), 1)
        # Fleet-lane identity (engine/fleet.py): stamped on every span
        # and fallback event this driver emits so a Chrome trace from an
        # S-lane run stays attributable, and the lane's PRIVATE fault
        # plane (parsed from the per-lane KSIM_FLEET_FAULTS spec) checked
        # next to the process-global FAULTS at the replay sites — a lane
        # fault degrades only this lane.
        self.lane = lane
        self._lane_faults = lane_faults
        self._span_tags = {} if lane is None else {"lane": lane}
        # The segment program bakes the runner's drain-requeue semantics
        # in; a no-requeue runner must take the per-pass path for any
        # segment containing node deletes.
        self._requeue = requeue_on_node_delete
        self._featurizer = None  # persistent device-side featurizer
        self._sched_name: str | None = None
        self._record_mode = "selection"  # set by service_supported
        self._preempt_active = False  # set by service_supported
        # record="full" segments run at a shorter fixed K (their stacked
        # result tensors multiply device memory by K).
        self._full_k = max(1, min(self.k, FULL_SEGMENT_STEPS))
        # Evidence counters (the bench rung reports them).  guarded-by:
        # main-thread — the driver's mutable state is thread-confined:
        # only the main thread writes it; the watchdogged dispatch
        # worker (``_run``, annotated worker-thread below) must stay
        # side-effect-free on the driver so an abandoned late-finishing
        # worker can never corrupt the degraded run's accounting.
        # tools/ksimlint's lock-discipline rule enforces the write side.
        self.device_steps = 0  # guarded-by: main-thread
        self.fallback_steps = 0  # guarded-by: main-thread
        self.device_round_trips = 0  # guarded-by: main-thread
        # Streaming ingest overlap (round 22, traces/stream.py): a
        # runner-provided NONBLOCKING drain of the trace-ingest queue,
        # called on the main thread while the dispatch worker owns the
        # device — the third stage of the ingest ∥ prelower ∥ dispatch
        # pipeline.  None for materialized runs.
        self._ingest_hook = ingest_hook
        self.ingest_prefetches = 0  # guarded-by: main-thread
        self.unsupported: dict[str, int] = {}  # guarded-by: main-thread
        # Failure-containment state — PER DRIVER, never process-global
        # (two runners in one process must not trip each other's
        # breaker).  The bench rung and runner stats surface all of it.
        self.watchdog_s = _watchdog_seconds()
        self.breaker_threshold = max(_breaker_threshold(), 1)
        self.device_errors = 0  # guarded-by: main-thread (degraded dispatches)
        self.watchdog_timeouts = 0  # guarded-by: main-thread (subset of above)
        # Sticky with the default cooldown of 0; with
        # KSIM_REPLAY_BREAKER_COOLDOWN_S > 0 the half-open machinery
        # below may close it again after a healthy probe segment.
        self.breaker_tripped = False  # guarded-by: main-thread
        self._consecutive_device_errors = 0  # guarded-by: main-thread
        self._consecutive_reconcile_faults = 0  # guarded-by: main-thread
        # Half-open recovery state (round 15, _breaker_cooldown_s).
        self.breaker_cooldown_s = max(_breaker_cooldown_s(), 0.0)
        self._breaker_cooldown_cur = self.breaker_cooldown_s  # guarded-by: main-thread
        self._breaker_retry_at: "float | None" = None  # guarded-by: main-thread
        self._breaker_probe = False  # guarded-by: main-thread
        self.breaker_probes = 0  # guarded-by: main-thread
        self.breaker_closes = 0  # guarded-by: main-thread
        self.breaker_reopens = 0  # guarded-by: main-thread
        # Segment sequence number (trace-span correlation id: every
        # lower/dispatch/reconcile span of one window shares it).
        self._segment_seq = 0
        # Incremental-lowering state (docs/churn_floor.md round 10): the
        # persistent lowered-universe cache, the speculative next-window
        # spec from the double-buffered executor, the committed plan the
        # cache advances from, and the device-resident constant-buffer
        # reuse map ({id(host array): (host ref, device array)} from the
        # previous dispatch; the host ref pins the id).
        self._cache = _LowerCache()  # guarded-by: main-thread
        self._spec: "tuple[tuple[int, ...], _WindowSpec] | None" = None  # guarded-by: main-thread
        self._last_plan: "_SegmentPlan | None" = None  # guarded-by: main-thread
        self._dev_consts: dict[int, tuple[Any, Any]] = {}  # guarded-by: main-thread
        self._dev_consts_x64: "bool | None" = None  # guarded-by: main-thread
        # Layout token the adopted buffers were committed under (round
        # 19): ("pack",) or ("mesh", dp, tp) — see _SegmentPlan.
        self._dev_consts_layout: Any = None  # guarded-by: main-thread
        # Sharded replay (round 17): the requested node-mesh width.  An
        # explicit service shard_mesh (validated in service_supported)
        # wins over the env knob.  Fleet lanes honor the knob too since
        # round 19: the group dispatch lays the lane axis over dp and
        # the node axis over tp of its own (dp, tp) fleet mesh, so a
        # lane's tp declaration composes with KSIM_FLEET_DP instead of
        # being forced to 1 (the round-17 whole-lane-per-device rule).
        self._tp_env = _replay_tp()
        self._tp_req = self._tp_env  # guarded-by: main-thread
        self._shard_mesh_obj: Any = None  # guarded-by: main-thread
        # Default: ON where re-transfer is the only cost (cpu backend),
        # OFF on the axon remote-tunnel runtime — pinning extra live
        # device buffers there slows every subsequent execution/transfer
        # 3-4x (the measured KSIM_H2D_CACHE pathology, engine/core.py);
        # KSIM_REPLAY_DEV_CACHE=1/0 overrides either way.  Unset env ->
        # None: the backend probe is DEFERRED to after the first healthy
        # dispatch — jax.default_backend() initializes the XLA client,
        # which must only ever happen on the watchdogged worker (a
        # wedged tunnel would hang an unguarded main-thread init here).
        _dc = os.environ.get("KSIM_REPLAY_DEV_CACHE")
        self._dev_cache_on: "bool | None" = _dc != "0" if _dc is not None else None
        self._prio_gen = 0
        # Pipeline / O(delta) evidence counters (bench JSON, lock-check
        # guard).  ``lower_log`` records one entry per successful lower:
        # the window's event count vs the fresh per-pod featurize rows it
        # actually built — the counter-based O(delta) guard's input.
        self.prelower_windows = 0  # guarded-by: main-thread
        self.prelower_consumed = 0  # guarded-by: main-thread
        self.prelower_discarded = 0  # guarded-by: main-thread
        self.prelower_faults = 0  # guarded-by: main-thread
        self.dev_const_hits = 0  # guarded-by: main-thread
        self.dev_const_misses = 0  # guarded-by: main-thread
        self.lower_log: list[dict] = []  # guarded-by: main-thread
        # Last _reject reason — the fleet coordinator mirrors a shared
        # (cohort-leader) rejection onto every follower lane's histogram
        # so per-lane evidence matches what each solo run would record.
        self._last_reject: "str | None" = None  # guarded-by: main-thread
        # The live driver's degradation evidence rides in the merged
        # /api/v1/metrics document (latest driver wins — one per
        # ScenarioRunner run).  Weakly referenced: the module-global
        # provider registry must not root a finished run's driver (and
        # its store/service graph) for the rest of the process.
        import weakref

        ref = weakref.ref(self)

        def _stats() -> dict:
            drv = ref()
            return drv.stats() if drv is not None else {"collected": True}

        register_provider("replay", _stats)

    @property
    def segment_seq(self) -> int:
        """Segments lowered so far (the trace-correlation counter).  The
        job plane's checkpoint cadence keys off this — a restored run's
        driver restarts at 0, which only re-bases span tags, never the
        schedule (docs/jobs.md "Incremental resume")."""
        return self._segment_seq

    def stats(self) -> dict:
        """Degradation evidence for runner stats / the bench JSON."""
        feat = self._featurizer
        return {
            "device_steps": self.device_steps,
            "fallback_steps": self.fallback_steps,
            "device_round_trips": self.device_round_trips,
            "ingest_prefetches": self.ingest_prefetches,
            "device_errors": self.device_errors,
            "watchdog_timeouts": self.watchdog_timeouts,
            "breaker_tripped": self.breaker_tripped,
            # Half-open recovery evidence: zeros (and cooldown_s 0)
            # under the default sticky configuration.
            "breaker": {
                "cooldown_s": self.breaker_cooldown_s,
                "cooldown_current_s": self._breaker_cooldown_cur,
                "probes": self.breaker_probes,
                "closes": self.breaker_closes,
                "reopens": self.breaker_reopens,
            },
            "unsupported": dict(self.unsupported),
            # Incremental-lowering evidence (round 10): the cache's
            # hit/miss/invalidation counters and the driver featurizer's
            # fresh per-pod row builds make the O(delta) lowering claim
            # machine-checkable straight from the bench JSON line.
            "lower_cache": self._cache.stats(),
            "featurize_calls": feat.pod_rows_built if feat is not None else 0,
            "featurize_reused": feat.pod_rows_reused if feat is not None else 0,
            "featurize_passes": feat.featurize_passes if feat is not None else 0,
            "prelower": {
                "windows": self.prelower_windows,
                "consumed": self.prelower_consumed,
                "discarded": self.prelower_discarded,
                "faults": self.prelower_faults,
            },
            "dev_const": {
                "hits": self.dev_const_hits,
                "misses": self.dev_const_misses,
            },
            # PROCESS-WIDE (shared by every driver/tenant in the
            # process): the compiled-executable cache's rung counters —
            # misses = actual compiles, shared_rungs = rungs serving
            # more than one tenant (engine/compilecache.py).
            "compile_cache": COMPILE_CACHE.snapshot(),
        }

    # -- support checks ------------------------------------------------------

    def _reject(self, reason: str) -> None:
        self.unsupported[reason] = self.unsupported.get(reason, 0) + 1
        self._last_reject = reason
        # Every degradation is a timeline event: reason + which window
        # (the lower/dispatch spans of the same segment share the seq).
        TRACE.event(
            "replay.fallback",
            reason=reason,
            segment=self._segment_seq,
            **self._span_tags,
        )

    def service_supported(self) -> bool:
        svc = self.service
        if svc._record not in ("selection", "full"):
            self._reject("record_mode")
            return False
        if getattr(svc, "_extenders", None):
            self._reject("extenders")
            return False
        if svc._pnts_emulation:
            self._reject("pnts_emulation")
            return False
        if svc._shard_mesh is not None:
            # Round 17: a node-axis (tp) mesh is SUPPORTED — the segment
            # program lays every [N]/[N, R] tensor over it and GSPMD
            # inserts the per-step collectives.  Only genuinely
            # unsupported shapes still reject: a dp>1 mesh would split
            # the pod axis under the sequential-commit scan (order is
            # the parity contract), and a mesh without a tp axis has
            # nothing to lay the node axis over.  Axis sizes come off
            # the mesh object itself — no backend init on this thread.
            # A FLEET lane (round 19) takes the mesh as its tp-width
            # declaration only: the group dispatch lays lanes over its
            # own (dp, tp) fleet mesh of the same node-shard width
            # (engine/fleet.py _worker_mesh), while the lane's solo
            # fallback dispatches honor the declared (1, tp) layout.
            from ksim_tpu.engine.sharding import DP, TP

            mesh = svc._shard_mesh
            axes = dict(zip(mesh.axis_names, mesh.devices.shape))
            if axes.get(DP, 1) != 1 or TP not in axes:
                self._reject("shard_mesh")
                return False
            self._shard_mesh_obj = mesh
            self._tp_req = int(axes[TP])
        else:
            self._shard_mesh_obj = None
            self._tp_req = self._tp_env
        if svc._featurizer_override is not None:
            self._reject("featurizer_override")
            return False
        names = svc._scheduler_names
        if len(names) != 1:
            self._reject("multi_profile")
            return False
        prof = None
        if svc._plugins_factory is None:
            prof = svc._profiles.get(names[0])
            if prof is None:
                self._reject("no_profile")
                return False
            if prof.pre_enqueue_hooks or prof.queue_sort_plugin is not None:
                self._reject("queue_hooks")
                return False
        if svc._waiting:
            self._reject("permit_waiters")
            return False
        self._sched_name = names[0]
        self._record_mode = svc._record
        # Preemption lowers into the segment scan unless the profile
        # disabled DefaultPreemption (then PostFilter is inert for the
        # modeled vocabulary — custom post_filter hooks reject below).
        preempt = bool(svc._preemption)
        if preempt and prof is not None and "DefaultPreemption" in prof.postfilter_disabled:
            preempt = False
        self._preempt_active = preempt
        return True

    _OP_KINDS = frozenset({"pods", "nodes"})

    def _window_len(self) -> int:
        """Steps one lowered window may consume (record mode dependent;
        valid after ``service_supported``)."""
        return self._full_k if self._record_mode == "full" else self.k

    def _parse_window(self, batches: list[list[Any]]) -> _WindowSpec:
        """The store-independent lowering prefix for up to one window of
        batches: op-vocabulary screening, per-step net object events
        (same-step create+delete cancels), window-local name
        bookkeeping, and support checks on CREATED objects.  Never reads
        the store or mutable service state, so it can run speculatively
        while the previous segment's dispatch is in flight.  Vocabulary
        misses never propagate: they stop the parse and land in the
        spec's ``head_reason`` / ``err_step``+``err_reason`` fields for
        the consumer to raise (or ignore, when its clamped window ends
        before the erroring step)."""
        spec = _WindowSpec(
            wlen=self._window_len(), sched_names=self.service._scheduler_names
        )
        # (The op screen below is also run — head batch only, pre-span —
        # by _batch_ops_ok, so a head-rejected window never opens the
        # replay.lower span; keep the two in sync.)
        win_pod_seen: set[str] = set()  # keys ever used by window creates
        win_pod_live: set[str] = set()  # window-created keys still alive
        ext_del_pods: set[str] = set()  # pre-window keys deleted in-window
        win_node_seen: set[str] = set()
        win_node_live: set[str] = set()
        ext_del_nodes: set[str] = set()
        try:
            for k, batch in enumerate(batches):
                for op in batch:
                    if op.kind not in self._OP_KINDS or op.op not in (
                        "create",
                        "delete",
                    ):
                        if k == 0:
                            spec.head_reason = f"op:{op.op}/{op.kind}"
                        return spec  # op-screen prefix ends here
                st = _StepParse(
                    flush=any(
                        op.kind == "nodes"
                        or (op.op == "delete" and op.kind == "pods")
                        for op in batch
                    )
                )
                for op in batch:
                    if op.kind == "pods":
                        if op.op == "create":
                            key = _pod_key(op.obj)
                            if key in win_pod_seen or key in ext_del_pods:
                                raise _Unsupported("pod_name_reuse")
                            # Against the live store + the service's
                            # backoff table: deferred (_lower).
                            spec.checks.append((k, "create_pod", key))
                            if op.obj.get("spec", {}).get("nodeName") or op.obj.get(
                                "status", {}
                            ).get("phase"):
                                raise _Unsupported("create_bound_pod")
                            reason = self._pod_supported(op.obj, spec.sched_names)
                            if reason is not None:
                                raise _Unsupported(reason)
                            win_pod_seen.add(key)
                            win_pod_live.add(key)
                            st.pc.append(key)
                            spec.created_pods.append((k, key, op.obj))
                        else:
                            key = f"{op.namespace or 'default'}/{op.name}"
                            if key in win_pod_live:
                                if key in st.pc:
                                    st.pc.remove(key)  # same-step net no-op
                                else:
                                    st.pd.append(key)
                                win_pod_live.discard(key)
                            elif key in win_pod_seen or key in ext_del_pods:
                                # Window-locally provable double delete.
                                raise _Unsupported("delete_unknown_pod")
                            else:
                                # Must exist in the store: deferred.
                                spec.checks.append((k, "delete_pod", key))
                                ext_del_pods.add(key)
                                st.pd.append(key)
                    else:  # nodes
                        if op.op == "create":
                            nm = name_of(op.obj)
                            if nm in win_node_seen or nm in ext_del_nodes:
                                raise _Unsupported("node_name_reuse")
                            spec.checks.append((k, "create_node", nm))
                            if op.obj.get("status", {}).get("images"):
                                raise _Unsupported("node_images")
                            win_node_seen.add(nm)
                            win_node_live.add(nm)
                            st.nc.append(nm)
                            spec.created_nodes.append((k, op.obj))
                        else:
                            if not self._requeue:
                                raise _Unsupported("drain_without_requeue")
                            nm = op.name
                            if nm in win_node_live:
                                if nm in st.nc:
                                    st.nc.remove(nm)
                                else:
                                    st.nd.append(nm)
                                win_node_live.discard(nm)
                            elif nm in win_node_seen or nm in ext_del_nodes:
                                raise _Unsupported("delete_unknown_node")
                            else:
                                spec.checks.append((k, "delete_node", nm))
                                ext_del_nodes.add(nm)
                                st.nd.append(nm)
                spec.steps.append(st)
                spec.n = len(spec.steps)
        except _Unsupported as e:
            spec.err_step = len(spec.steps)
            spec.err_reason = str(e)
        return spec

    # -- the double-buffered executor's speculative prefix -------------------

    def _discard_spec(self) -> None:
        if self._spec is not None:
            self._spec = None
            self.prelower_discarded += 1

    def _flush_incremental(self, reason: str) -> None:
        """Strictly drop ALL incremental lowering state — the cache, the
        speculative prefix, the retained plan, and the device-resident
        constant buffers — ahead of a path the incremental bookkeeping
        cannot track.  One helper so no future invalidation site can
        flush the cache but leave a stale plan/buffer map behind it."""
        self._cache.invalidate(reason)
        self._discard_spec()
        self._last_plan = None
        self._dev_consts = {}

    def _take_spec(self, batches: list[list[Any]]) -> "_WindowSpec | None":
        """Consume the speculative prefix if it predicted exactly this
        window (same batch-list identities, same window length, same
        profile config); discard it otherwise."""
        held = self._spec
        self._spec = None
        if held is None:
            return None
        lists, spec = held
        if (
            len(batches) < len(lists)
            or any(a is not b for a, b in zip(lists, batches))
            or spec.wlen != self._window_len()
            or spec.sched_names != self.service._scheduler_names
        ):
            self.prelower_discarded += 1
            return None
        self.prelower_consumed += 1
        return spec

    def _prelower_next(self, plan: "_SegmentPlan", future: list[list[Any]]) -> None:
        """Speculatively parse + memo-warm the NEXT window while the
        current segment's dispatch runs on the worker thread.  The
        prefix is store-independent by construction, so it cannot race
        the (not-yet-known) outcome of segment N; the store-dependent
        remainder runs in ``_lower`` only after N's reconcile commits.
        Containment: any classified failure here — including an armed
        ``replay.prelower`` fault — degrades THIS window's overlap only
        (the window parses synchronously instead); it never touches the
        in-flight dispatch or the locks."""
        self._discard_spec()  # a stale prediction can never be consumed
        nxt = future[plan.n_steps : plan.n_steps + self._window_len()]
        if not nxt:
            return
        self.prelower_windows += 1
        try:
            with TRACE.span(
                "replay.prelower", segment=self._segment_seq, steps=len(nxt)
            ):
                FAULTS.check("replay.prelower")
                spec = self._parse_window(nxt)
                self._warm_spec(spec)
        except Exception as e:
            # Catch EVERYTHING, not just SimulatorError: this runs while
            # the dispatch worker is in flight, and a propagating
            # programming error would be misclassified by the dispatch
            # handlers as a device_error (feeding the breaker) or crash
            # past the un-joined worker.  A real bug is not masked — the
            # window re-parses synchronously inside replay.lower, where
            # the taxonomy re-raises non-SimulatorErrors with the worker
            # safely joined.
            self.prelower_faults += 1
            logger.warning(
                "speculative prelower failed (%s: %s); the next window "
                "lowers synchronously",
                type(e).__name__, e,
            )
            return
        # Hold the batch lists themselves, not bare id()s: the pinned
        # references keep CPython from recycling an id onto a different
        # list, so _take_spec's identity match can never false-positive.
        self._spec = (tuple(nxt), spec)

    def _warm_spec(self, spec: _WindowSpec) -> None:
        """Populate the per-object parse memos for the window's CREATED
        objects (the only ones the next featurize will miss on) off the
        critical path.  Every warmed function is a pure parse of a
        frozen object memoized on its identity (state/objcache.py), so
        warming is semantically invisible — the completion path would
        compute the identical entries, just inside the replay.lower
        span."""
        from ksim_tpu.state.encoding import _parsed_node_affinity
        from ksim_tpu.state.interpod import parsed_terms
        from ksim_tpu.state.resources import node_allocatable, pod_tolerations
        from ksim_tpu.state.resources import pod_requests as _preqs

        for _step, _key, obj in spec.created_pods:
            _preqs(obj)
            _preqs(obj, non_zero=True)
            pod_tolerations(obj)
            _parsed_node_affinity(obj)
            parsed_terms(obj)
        for _step, obj in spec.created_nodes:
            node_allocatable(obj)

    def _batch_ops_ok(self, batch: Sequence[Any], record: bool) -> bool:
        """Cheap op-vocabulary screen for ONE step's batch (no store
        access).  ``record`` counts the reject reason — only the batch
        that actually forces a fallback (the segment head) should."""
        for op in batch:
            if op.kind not in self._OP_KINDS or op.op not in ("create", "delete"):
                if record:
                    self._reject(f"op:{op.op}/{op.kind}")
                return False
        return True

    @staticmethod
    def _pod_supported(pod: JSON, sched_names: tuple[str, ...]) -> str | None:
        """None when the pod fits the tensor vocabulary, else the reason."""
        from ksim_tpu.scheduler.profile import DEFAULT_SCHEDULER_NAME
        from ksim_tpu.state.extras import _host_ports
        from ksim_tpu.state.volumes import _pod_has_volumes

        spec = pod.get("spec", {})
        if spec.get("schedulingGates"):
            return "scheduling_gates"
        name = spec.get("schedulerName") or DEFAULT_SCHEDULER_NAME
        if name not in sched_names:
            return "foreign_scheduler"
        if pod.get("status", {}).get("phase") in ("Succeeded", "Failed"):
            return "terminal_phase"
        if _host_ports(pod):
            return "host_ports"
        if _pod_has_volumes(pod):
            return "volumes"
        return None

    # -- lowering ------------------------------------------------------------

    def try_segment(self, batches: list[list[Any]]):
        """Lower + run up to one window of steps (``batches`` may carry
        LOOKAHEAD beyond the window — the double-buffered executor
        pre-lowers the following window's store-independent prefix while
        this one's dispatch is in flight); returns SegmentOutcome (whose
        ``steps`` may be SHORTER than the window: the supported prefix,
        tail-padded on-device to the compiled K) or None (the FIRST step
        is unsupported — the caller falls back for it).  Must be called
        BEFORE the steps' ops touch the store.

        Failure taxonomy (classified, never a bare catch-all):

        - ``ReplayFallback`` (lowering vocabulary misses, validation
          discards) -> per-pass fallback under its stable reason;
        - any other ``SimulatorError`` during LOWERING -> fallback as
          ``lowering_fault`` (an expected, containable failure);
        - device/runtime errors or a watchdog timeout during DISPATCH ->
          ``device_error`` fallback, counted toward the circuit breaker;
        - everything else (TypeError & friends) is a programming error
          and RE-RAISES — silent fallback must never mask a bug.

        Any None return STRICTLY invalidates the lowered-universe cache,
        discards the speculative prefix, and drops the device-resident
        constant buffers: the per-pass path is about to mutate store and
        service state the incremental bookkeeping cannot track.
        """
        out = self._try_segment_impl(batches)
        if out is None:
            # A probe admitted in prepare_segment that never reached a
            # dispatch verdict (lowering fault / vocabulary miss) must
            # not leave the half-open gate ajar: re-open, cooldown
            # doubled — unbounded free re-probing would defeat the
            # backoff.  (A probe that failed IN dispatch was already
            # resolved by _note_device_error, which clears the flag.)
            if self._breaker_probe:
                self._breaker_reopen("probe lost before dispatch")
            self._flush_incremental("fallback")
        return out

    def _try_segment_impl(self, batches: list[list[Any]]):
        plan = self.prepare_segment(batches)
        if plan is None:
            return None
        return self.dispatch_segment(plan, batches)

    def prepare_segment(
        self, batches: list[list[Any]], *, check_lane_faults: bool = True
    ) -> "_SegmentPlan | None":
        """The lowering half of ``try_segment``: breaker / support / op
        screens plus the classified lowering taxonomy, ending in a
        dispatch-ready ``_SegmentPlan`` (device-const reuse attached) or
        None with the reason recorded.  Split from the dispatch half so
        the fleet coordinator (engine/fleet.py) can lower a shared
        window ONCE on the cohort leader and dispatch all lanes in one
        program.  The fleet passes ``check_lane_faults=False``: it gates
        EVERY cohort lane's private plane itself (including the
        leader's) so a lane-armed replay.lower fault degrades exactly
        one lane — a check here too would both double-count the
        leader's schedule and land the injected fault inside the SHARED
        lowering, degrading the whole cohort."""
        if self.breaker_tripped and not self._breaker_admit_probe():
            # Open: every window falls back immediately — no lowering
            # work, no watchdog tax.  Sticky under the default cooldown
            # of 0; otherwise ONE probe segment per elapsed cooldown
            # gets through the gate above.
            self._reject("breaker_open")
            return None
        if not self.service_supported():
            return None
        # Pre-span head screen: a window whose FIRST step is outside the
        # op vocabulary never lowers — no replay.lower span, no fault
        # slot, no segment seq — so phase counts and armed call:N fault
        # schedules keep tracking REAL lowerings (the pre-round-10
        # semantics).  try_segment's None wrapper discards any held
        # speculative spec and flushes the cache, as for any fallback.
        if not batches or not self._batch_ops_ok(batches[0], record=True):
            return None
        wlen = self._window_len()
        spec = self._take_spec(batches)
        self._segment_seq += 1
        try:
            with TRACE.span(
                "replay.lower",
                segment=self._segment_seq,
                steps=min(len(batches), wlen),
                **self._span_tags,
            ) as sp:
                FAULTS.check("replay.lower")
                if check_lane_faults and self._lane_faults is not None:
                    self._lane_faults.check("replay.lower")
                if spec is None:
                    spec = self._parse_window(batches[:wlen])
                m = min(spec.n, wlen)
                if m == 0:
                    raise _Unsupported(spec.head_reason or spec.err_reason)
                # The opening value is window CAPACITY; refine to the
                # actually-lowered count so lower spans line up with
                # dispatch spans on short (vocabulary-miss) segments.
                sp.set(steps=m)
                plan = self._lower(list(batches[:m]), spec)
        except ReplayFallback as e:
            self._reject(str(e))
            return None
        except SimulatorError as e:
            logger.warning(
                "segment lowering failed (%s: %s); falling back per-pass",
                type(e).__name__, e,
            )
            self._reject("lowering_fault")
            return None
        if plan is None:
            return None
        if (
            self._dev_cache_on
            and self._dev_consts_x64 == bool(jax.config.jax_enable_x64)
        ):
            # Round 17/19: the reuse map holds buffers committed to ONE
            # device layout.  The map rides with its layout TOKEN and
            # the executor compares at use-site (a solo-vs-fleet or
            # mesh-shape change silently misses and re-transfers;
            # changed host arrays still miss by id individually) — the
            # driver can't predict here whether the fleet will dispatch
            # this plan on its (dp, tp) mesh.
            plan.dev_reuse = self._dev_consts
            plan.dev_reuse_layout = self._dev_consts_layout
        return plan

    def dispatch_segment(self, plan: "_SegmentPlan", batches: list[list[Any]]):
        """The dispatch half of ``try_segment``: the watchdogged device
        run plus post-dispatch accounting.  Returns the SegmentOutcome
        or None (reason recorded, breaker fed)."""
        try:
            with TRACE.span(
                "replay.dispatch",
                segment=self._segment_seq,
                steps=plan.n_steps,
                **self._span_tags,
            ):
                res = self._run_watchdogged(plan, batches)
        except ReplayParityError:
            raise  # a kernel bug, not a degradable condition
        except ReplayFallback as e:
            self._reject(str(e))
            return None
        except (DeviceUnavailableError, SimulatorError, RuntimeError, OSError) as e:
            return self._note_device_error(e)
        # The dispatch came back healthy (even if validation discarded
        # the segment): the backend is alive — reset the breaker window.
        self.note_dispatch_healthy(plan)
        if isinstance(res, str):
            # Post-dispatch validation discard (featurize_prediction /
            # preemption_overflow): store untouched, fall back.
            self._reject(res)
            return None
        # device_steps is counted by the caller once the segment COMMITS
        # (a rolled-back reconcile re-runs its steps per-pass — counting
        # here would double-book them).
        self._last_plan = plan
        return res

    def note_dispatch_healthy(self, plan: "_SegmentPlan", *, adopt: bool = True) -> None:
        """Main-thread accounting for one healthy dispatch join: breaker
        window reset, round-trip count, device-const buffer adoption.
        Shared by the solo path above and the fleet's group dispatch
        (where every lane's driver gets the reset but only the plan
        OWNER — the cohort leader — adopts the buffers, ``adopt``)."""
        self._consecutive_device_errors = 0
        if self._breaker_probe:
            # The half-open probe segment came back healthy: the
            # backend recovered — close the breaker and re-promote the
            # driver to the device path.
            self._breaker_close()
        self.device_round_trips += 1
        if self._dev_cache_on is None:
            # Safe to probe now: the dispatch initialized the backend on
            # the watchdogged worker, so this is an instant lookup.
            self._dev_cache_on = jax.default_backend() == "cpu"
        if adopt and self._dev_cache_on and plan.dev_map_out is not None:
            # Adopt this dispatch's device buffers for id-keyed reuse by
            # the next one (main thread: _run never mutates the driver).
            self._dev_consts = plan.dev_map_out
            self._dev_consts_x64 = bool(jax.config.jax_enable_x64)
            self._dev_consts_layout = plan.dev_layout
            self.dev_const_hits += plan.dev_hits
            self.dev_const_misses += plan.dev_misses

    def _run_watchdogged(self, plan: "_SegmentPlan", future: list[list[Any]]):
        """Run ``_run`` on a worker thread bounded by the watchdog, and
        OVERLAP the wait with the next window's speculative prelower on
        this (the main) thread — the double-buffered pipeline.  The
        watchdog budget still covers the dispatch from ITS start: the
        join timeout is reduced by however long the prelower took.

        ``block_until_ready`` against a wedged backend never returns;
        the join timeout turns that hang into DeviceUnavailableError so
        the run DEGRADES instead of stalling.  The abandoned worker is a
        daemon — it cannot be killed, but the breaker counts CUMULATIVE
        watchdog timeouts (see ``_note_device_error``), so at most
        ``breaker_threshold`` of them ever exist.  ``_run`` is
        side-effect-free on the driver (counters are applied by the
        caller on the MAIN thread), so a late-finishing stray worker
        cannot corrupt the accounting of the degraded run."""
        if self.watchdog_s <= 0:
            out = self._run(plan)
            # No worker to overlap with; the parse/memo warm still moves
            # off the next window's replay.lower span.
            self._prelower_next(plan, future)
            self._drain_ingest()
            return out
        box: dict[str, Any] = {}
        # A job-scoped caller's trace override is thread-local; carry it
        # onto the worker so dispatch-side spans/events (fault.fired,
        # the lane plane's checks) stay attributed to the owning job.
        scope = TRACE.scope()

        def work() -> None:  # ksimlint: worker-thread
            try:
                with TRACE.scoped(scope):
                    box["out"] = self._run(plan)
            except BaseException as e:  # classified by the caller
                box["err"] = e

        t = threading.Thread(target=work, name="replay-dispatch", daemon=True)
        t.start()
        t0 = time.monotonic()
        self._prelower_next(plan, future)
        self._drain_ingest()
        t.join(max(self.watchdog_s - (time.monotonic() - t0), 0.001))
        if t.is_alive():
            self.watchdog_timeouts += 1
            TRACE.event(
                "replay.watchdog_timeout",
                segment=self._segment_seq,
                watchdog_s=self.watchdog_s,
            )
            raise DeviceUnavailableError(
                f"segment dispatch exceeded the {self.watchdog_s:.0f}s watchdog"
            )
        if "err" in box:
            raise box["err"]
        return box["out"]

    def _drain_ingest(self) -> None:
        """Pull whatever the trace-ingest producer has ready (a bounded,
        nonblocking window drain) while the dispatch worker owns the
        device.  Errors other than cancellation are swallowed HERE on
        purpose: a mid-dispatch raise would be misclassified by the
        device-error ladder (or strand the un-joined worker), and the
        same error re-raises deterministically at the runner's next
        blocking ensure."""
        if self._ingest_hook is None:
            return
        try:
            self._ingest_hook()
            self.ingest_prefetches += 1
        except RunCancelled:
            raise
        except Exception:
            logger.debug(
                "ingest prefetch hook failed; deferring to the blocking "
                "ingest path",
                exc_info=True,
            )

    def _note_device_error(self, e: BaseException) -> None:
        """Account one degraded dispatch; trip the breaker on the Nth
        CONSECUTIVE failure — or the Nth watchdog timeout over the whole
        run: every timeout abandons a worker thread pinned on its
        segment plan forever, so cumulative timeouts must trip even when
        healthy dispatches reset the consecutive window in between
        (bounding leaked workers at breaker_threshold).  Always returns
        None (the fallback)."""
        self.device_errors += 1
        self._consecutive_device_errors += 1
        self._reject("device_error")
        if self._breaker_probe:
            # This WAS the half-open probe: the backend is still dead.
            # Re-open with a doubled (bounded) cooldown; none of the
            # trip logic below applies — the breaker never closed.
            self._breaker_reopen(f"{type(e).__name__}: {e}")
            return None
        if (
            not self.breaker_tripped
            and (
                self._consecutive_device_errors >= self.breaker_threshold
                or self.watchdog_timeouts >= self.breaker_threshold
            )
        ):
            self.breaker_tripped = True
            self._breaker_schedule_retry()
            TRACE.event(
                "replay.breaker_open",
                cause="device_error",
                consecutive=self._consecutive_device_errors,
                watchdog_timeouts=self.watchdog_timeouts,
            )
            logger.error(
                "device replay circuit breaker TRIPPED (%d consecutive "
                "device failures, %d watchdog timeouts total, threshold %d; "
                "last: %s: %s); remaining steps run on the per-pass host "
                "path",
                self._consecutive_device_errors, self.watchdog_timeouts,
                self.breaker_threshold, type(e).__name__, e,
            )
        else:
            logger.warning(
                "segment dispatch failed (%s: %s); the window's head step "
                "re-runs per-pass, the rest retries on-device "
                "(%d/%d consecutive failures before the circuit breaker "
                "opens)",
                type(e).__name__, e,
                self._consecutive_device_errors, self.breaker_threshold,
            )
        return None

    # -- breaker half-open recovery (round 15) ---------------------------
    # All main-thread, like every other breaker field: probes are
    # admitted in prepare_segment and resolved on the main thread after
    # the dispatch joins — the worker never touches the gate.

    def _breaker_schedule_retry(self) -> None:
        """Arm the next probe window (no-op under the sticky default)."""
        if self.breaker_cooldown_s > 0:
            self._breaker_retry_at = time.monotonic() + self._breaker_cooldown_cur

    def _breaker_admit_probe(self) -> bool:
        """One probe segment per elapsed cooldown: True admits THIS
        window through the open breaker as the probe.  False while the
        cooldown runs, while a probe is already in flight, or under the
        sticky default (cooldown 0)."""
        if self.breaker_cooldown_s <= 0 or self._breaker_probe:
            return False
        if self._breaker_retry_at is None or time.monotonic() < self._breaker_retry_at:
            return False
        self._breaker_probe = True
        self.breaker_probes += 1
        TRACE.event(
            "replay.breaker_probe",
            cooldown_s=self._breaker_cooldown_cur,
            probes=self.breaker_probes,
            **self._span_tags,
        )
        logger.info(
            "circuit breaker half-open: admitting one probe segment "
            "(cooldown %.1fs elapsed)", self._breaker_cooldown_cur,
        )
        return True

    def _breaker_close(self) -> None:
        """A healthy probe: close the breaker, reset both consecutive
        windows and the cooldown ladder — the driver is back on the
        device path as if it never tripped."""
        self._breaker_probe = False
        self.breaker_tripped = False
        self.breaker_closes += 1
        self._consecutive_device_errors = 0
        self._consecutive_reconcile_faults = 0
        self._breaker_cooldown_cur = self.breaker_cooldown_s
        self._breaker_retry_at = None
        TRACE.event(
            "replay.breaker_close",
            closes=self.breaker_closes,
            **self._span_tags,
        )
        logger.info(
            "device replay circuit breaker CLOSED after a healthy probe "
            "segment; device path re-promoted"
        )

    def _breaker_reopen(self, why: str) -> None:
        """A failed (or lost) probe: stay open, double the cooldown
        (bounded by _BREAKER_COOLDOWN_CAP_S) before the next probe."""
        self._breaker_probe = False
        self.breaker_reopens += 1
        self._breaker_cooldown_cur = min(
            self._breaker_cooldown_cur * 2.0, _BREAKER_COOLDOWN_CAP_S
        )
        self._breaker_retry_at = time.monotonic() + self._breaker_cooldown_cur
        TRACE.event(
            "replay.breaker_open",
            cause="probe_failed",
            cooldown_s=self._breaker_cooldown_cur,
            **self._span_tags,
        )
        logger.warning(
            "circuit breaker probe failed (%s); re-opened, next probe in "
            "%.1fs", why, self._breaker_cooldown_cur,
        )

    def _service_featurizer(self):
        """The canonical per-pass featurizer (created exactly as the
        service would, so a later fallback pass sees the same instance
        and — critically — the same NodeSlots history)."""
        svc = self.service
        name = self._sched_name
        feat = svc._featurizers.get(name)
        if feat is None:
            from ksim_tpu.state.featurizer import Featurizer

            if svc._plugins_factory is not None:
                feat = Featurizer(pod_bucket_min=svc._pod_bucket_min)
            else:
                feat = svc._profiles[name].featurizer(
                    pod_bucket_min=svc._pod_bucket_min
                )
            svc._featurizers[name] = feat
        return feat

    def _lower(self, batches: list[list[Any]], spec: _WindowSpec):
        from ksim_tpu.engine.core import _Program
        from ksim_tpu.scheduler.service import queue_sort_key
        from ksim_tpu.state.featurizer import bucket_size
        from ksim_tpu.state.priorities import build_priority_resolver

        svc = self.service
        store = self.store
        for kind in ("persistentvolumes", "persistentvolumeclaims", "storageclasses"):
            if store.list(kind, copy_objs=False):
                raise _Unsupported("volume_objects")

        m_steps = len(batches)
        lower_epoch = store.mutation_epoch
        cur_pods = store.list("pods", copy_objs=False)
        cur_nodes = store.list("nodes", copy_objs=False)
        node_names = {name_of(n) for n in cur_nodes}
        sched_names = svc._scheduler_names
        cache = self._cache
        use_cache = (
            cache.valid
            and cache.epoch == lower_epoch
            and cache.sched_names == sched_names
        )
        if cache.valid and not use_cache:
            # An out-of-band store write moved the mutation epoch — or a
            # scheduler reconfiguration changed the profile set the cached
            # survivors' support screen ran against (config changes never
            # write the store, so the epoch alone cannot see them) —
            # since the cache's segment committed: strict flush, rebuild.
            cache.invalidate(
                "epoch_mismatch"
                if cache.epoch != lower_epoch
                else "sched_config"
            )

        # Store-dependent half of the window validation: replay the
        # parse's deferred membership checks against the live store and
        # the service backoff table, in recorded op order, then raise
        # any window-local miss that sits inside this window.  (The
        # window-LOCAL half — op vocabulary, same-window name reuse,
        # created-object support — already ran in _parse_window,
        # possibly speculatively while the previous dispatch flew.)
        with svc._backoff_lock:
            backoff_keys = set(svc._backoff)
        # O(checks), not O(universe): each deferred pod-membership check
        # is one keyed store probe — building a key set over every store
        # pod here would reintroduce the per-segment O(U) host walk on
        # exactly the common cache-hit path this cache exists to avoid.
        for stp, check, key in spec.checks:
            if stp >= m_steps:
                break
            if check == "create_pod":
                ns, _, nm = key.partition("/")
                if store.contains("pods", nm, ns):
                    raise _Unsupported("pod_name_reuse")
                if key in backoff_keys:
                    # A stale backoff entry for a DEAD same-name pod: the
                    # per-pass path would let the new pod inherit it
                    # (_in_backoff is key-based), which the fresh
                    # universe row cannot model.
                    raise _Unsupported("backoff_name_reuse")
            elif check == "delete_pod":
                ns, _, nm = key.partition("/")
                if not store.contains("pods", nm, ns):
                    raise _Unsupported("delete_unknown_pod")
            elif check == "create_node":
                if key in node_names:
                    raise _Unsupported("node_name_reuse")
            else:  # delete_node
                if key not in node_names:
                    raise _Unsupported("delete_unknown_node")
        # A parse error can only sit AT or PAST the lowered prefix's end
        # (err_step == spec.n by construction and m_steps <= spec.n):
        # the erroring step heads the NEXT window, which head-rejects it
        # — the prefix-granular fallback.
        assert spec.err_step >= m_steps, spec.err_reason

        # Net per-step object events from the (possibly speculative)
        # window parse; copies, because tail padding appends below.
        steps = spec.steps[:m_steps]
        step_pod_creates = [list(s.pc) for s in steps]
        step_pod_deletes = [list(s.pd) for s in steps]
        step_node_creates = [list(s.nc) for s in steps]
        step_node_deletes = [list(s.nd) for s in steps]
        step_flush = [s.flush for s in steps]
        created_pod_entries = [e for e in spec.created_pods if e[0] < m_steps]
        created_nodes = [obj for stp, obj in spec.created_nodes if stp < m_steps]

        # Tail padding: segments shorter than the compiled K (the stream
        # tail, a mid-window vocabulary miss, or full-record's shorter
        # K) extend with inactive no-op steps so they reuse the existing
        # compile instead of falling back (ROADMAP open item).
        k_pad = self._window_len()
        step_active = [True] * m_steps + [False] * (k_pad - m_steps)
        for _ in range(k_pad - m_steps):
            step_pod_creates.append([])
            step_pod_deletes.append([])
            step_node_creates.append([])
            step_node_deletes.append([])
            step_flush.append(False)

        # Universe pods, globally sorted by the exact per-pass queue key
        # (static per pod), so slot order IS queue order every step.
        # O(delta) on a cache hit: survivors keep their cached order and
        # sort keys (``queue_sort_key`` is total over distinct pod keys
        # — priority desc, creationTimestamp, namespace, name — so a
        # bisect merge of the window's creates reproduces exactly what a
        # full stable sort would); only created objects compute keys.
        # The UNIVERSE LIST HOLDS THE CLEANED PENDING OBJECTS: identical
        # to the live store objects in every lowered field (sort key,
        # requests, labels, tolerations, affinity, preemption statics —
        # binds/annotations only touch nodeName/phase/annotations/rv),
        # and identity-stable across segments, which is what keeps every
        # per-pod featurizer memo row alive (the O(delta) claim).
        if use_cache:
            cache.hits += 1
            priority_of = cache.priority_of
            prio_gen = cache.prio_gen
            uni_keys = list(cache.keys)
            uni_sort = list(cache.sort_keys)
            uni_clean = list(cache.clean_pods)
        else:
            cache.misses += 1
            priority_of = build_priority_resolver(
                store.list("priorityclasses", copy_objs=False)
            )
            self._prio_gen += 1
            prio_gen = self._prio_gen
            # Full support screen + node-image screen (survivors on the
            # cache-hit path were screened when they entered the
            # universe and cannot have changed: only segment-exempt
            # writes happened since, and those never touch the screened
            # fields).
            for p in cur_pods:
                reason = self._pod_supported(p, sched_names)
                if reason is not None:
                    raise _Unsupported(reason)
            for n in cur_nodes:
                if n.get("status", {}).get("images"):
                    raise _Unsupported("node_images")
            decorated = sorted(
                (queue_sort_key(p, priority_of), _pod_key(p), _cleaned_pending(p))
                for p in cur_pods
            )
            uni_sort = [d[0] for d in decorated]
            uni_keys = [d[1] for d in decorated]
            uni_clean = [d[2] for d in decorated]
        for _stp, key, obj in created_pod_entries:
            sk = queue_sort_key(obj, priority_of)
            j = bisect.bisect_left(uni_sort, sk)
            uni_sort.insert(j, sk)
            uni_keys.insert(j, key)
            uni_clean.insert(j, obj)

        universe_pods = uni_clean
        universe_keys = uni_keys
        row_of = {k: j for j, k in enumerate(universe_keys)}
        if len(row_of) != len(universe_pods):
            raise _Unsupported("duplicate_pod_keys")

        # On-device preemption statics, window-scoped: a PRIORITY-FLAT
        # window can never enter DefaultPreemption's search — a
        # candidate node needs a bound pod of strictly LOWER priority
        # than the preemptor (`prow["priority"] < prio_p`), and no pod
        # carries a prior nomination — so it lowers preempt-free: the
        # bounded victim search is neither compiled nor traced.  Besides
        # the solo compile win, this is what keeps FLEET dispatch honest
        # (round 12): under jax.vmap a lax.cond lowers to select — BOTH
        # branches execute for every pod attempt — so for the search's
        # no-candidate case to stay free in a batched program it must be
        # absent from the statics, not merely predicated off.
        # record="full" keeps the search statics regardless: with
        # preemption enabled the host path writes a postfilter-result
        # annotation for every failed attempt, which only the preempt
        # decode path reproduces.
        preempt_plan = self._preempt_active
        prios = None
        if preempt_plan:
            prios = [priority_of(p) for p in universe_pods]
            if (
                self._record_mode == "selection"
                and not any(
                    p.get("status", {}).get("nominatedNodeName")
                    for p in cur_pods
                )
                and (not prios or prios.count(prios[0]) == len(prios))
            ):
                preempt_plan = False

        # Featurize the universe once (persistent device featurizer:
        # per-pod rows memoize, bound aggregates update by delta; with
        # the identity-stable cached universe, fresh row builds are
        # O(window creates) — tracked in pod_rows_built and logged per
        # segment in lower_log for the counter-based O(delta) guard).
        if self._featurizer is None:
            if svc._plugins_factory is not None:
                from ksim_tpu.state.featurizer import Featurizer

                self._featurizer = Featurizer()
            else:
                self._featurizer = svc._profiles[self._sched_name].featurizer()
        rows_built0 = self._featurizer.pod_rows_built
        universe_nodes = list(cur_nodes) + created_nodes
        bound_pods = store.pods_with_node()
        feats = self._featurizer.featurize(
            universe_nodes,
            (),
            queue_pods=universe_pods,
            bound_pods=bound_pods,
            namespaces=store.list("namespaces", copy_objs=False),
        )
        if not feats.exact:
            raise _Unsupported("inexact_units")
        slot_of = dict(self._featurizer._slots.slot_of)

        factory = (
            svc._plugins_factory
            if svc._plugins_factory is not None
            else svc._profiles[self._sched_name].plugins
        )
        plugins = tuple(factory(feats))
        for sp in plugins:
            if sp.extender is not None:
                raise _Unsupported("plugin_extender")
            for attr in (
                "reserve",
                "unreserve",
                "permit",
                "pre_bind",
                "bind",
                "post_bind",
                "post_filter",
            ):
                if hasattr(sp.plugin, attr):
                    raise _Unsupported(f"host_hook:{attr}")
        prog = _Program(plugins, self._record_mode)

        if preempt_plan:
            from ksim_tpu.scheduler.preemption import (
                ORACLE_FIT_FILTER_NAMES,
                VOLUME_FIT_FILTER_NAMES,
            )

            # The device victim search re-checks fits through the
            # PROFILE's filter kernels, but the host oracle's fit chain
            # is FIXED — exactness requires the profile's filter set to
            # match it (volume filters optional: trivially passing for
            # this vocabulary, which has no volume objects or pod
            # volumes).
            fnames = {sp.plugin.name for sp in plugins if sp.filter_enabled}
            if not (
                ORACLE_FIT_FILTER_NAMES
                <= fnames
                <= (ORACLE_FIT_FILTER_NAMES | VOLUME_FIT_FILTER_NAMES)
            ):
                raise _Unsupported("preemption_filter_set")

        N = feats.nodes.padded
        P = feats.pods.requests.shape[0]
        K = k_pad
        # Round 17: the mesh width for THIS universe.  N is a power-of-
        # two bucket, so gcd against the requested width finds the
        # largest divisor both agree on — the node axis always splits
        # evenly, and a universe narrower than the requested mesh just
        # runs at a narrower tp instead of rejecting.  An EXPLICIT
        # service shard_mesh is a layout contract, not a hint: a
        # universe its tp axis cannot divide is a genuinely unsupported
        # mesh shape (the narrowed "shard_mesh" reason).
        #
        # The per-shard width floor is a partitioner-hazard guard, not a
        # perf heuristic: at N=8 with tp>=4 the SPMD-partitioned
        # preemption scan returned sel/nom tensors with every value
        # DOUBLED (-1 came back -2, node 2 came back 4 — a partial sum
        # an all-reduce never folded), byte-identical at every width
        # with >= _MIN_SHARD_NODES rows per shard.  Silent corruption,
        # caught only because the doubled slot overran node_names — so
        # narrow below the floor rather than trust the compiler there.
        # A universe this small has nothing to gain from sharding
        # anyway; see docs/churn_floor.md.
        tp = math.gcd(self._tp_req, N)
        while tp > 1 and N // tp < _MIN_SHARD_NODES:
            tp //= 2
        if self._shard_mesh_obj is not None and tp != self._tp_req:
            raise _Unsupported("shard_mesh")
        ipa = feats.aux["interpod"]
        spread = feats.aux["spread"]

        # Initial dynamic state.
        valid0 = np.zeros(N, bool)
        for n in cur_nodes:
            valid0[slot_of[name_of(n)]] = True
        alive0 = np.zeros(P, bool)
        bound0 = np.full(P, -1, np.int32)
        cur_keys = {_pod_key(p) for p in cur_pods}
        for p in cur_pods:
            j = row_of[_pod_key(p)]
            alive0[j] = True
            nn = p.get("spec", {}).get("nodeName")
            if nn:
                ns = slot_of.get(nn)
                if ns is None:
                    raise _Unsupported("bound_to_unknown_node")
                bound0[j] = ns
        attempts0 = np.zeros(P, np.int32)
        retry0 = np.zeros(P, np.int32)
        for key, (a, r) in svc._backoff.items():
            j = row_of.get(key)
            if j is not None and key in cur_keys:
                attempts0[j] = a
                retry0[j] = r

        # Inter-pod local per-node accumulators from the bound population
        # (the linear pre-aggregation the segment re-derives each step).
        T = ipa.pod_term_match.shape[1]
        ip_cnt0 = np.zeros((N, T), np.int32)
        ip_eat0 = np.zeros((N, T), np.int32)
        ip_vw0 = np.zeros((N, T), np.int32)
        b_rows = [row_of[_pod_key(p)] for p in bound_pods]
        b_slots = [int(bound0[j]) for j in b_rows]
        if b_rows:
            rows = np.asarray(b_rows)
            slots = np.asarray(b_slots)
            np.add.at(ip_cnt0, slots, ipa.pod_term_match[rows].astype(np.int32))
            np.add.at(ip_eat0, slots, ipa.pod_eat[rows])
            np.add.at(ip_vw0, slots, ipa.pod_vw[rows])
        n_dom = int(ipa.n_domains)
        from ksim_tpu.state.featurizer import vocab_pad

        n_dom_pad = vocab_pad(n_dom + 1)
        if not self._check_interpod_locals(
            ipa, ip_cnt0, ip_eat0, ip_vw0, n_dom_pad
        ):
            self._reject("interpod_local_mismatch")
            return None

        # Per-step event index tensors (-1 padded) + canonical ranks.
        # Widths bucket like every other axis: an exact-max width would
        # hand the jit cache a fresh shape (= a multi-second compile)
        # nearly every segment.
        def pad(lists: list[list[int]]) -> np.ndarray:
            width = vocab_pad(max((len(x) for x in lists), default=1))
            out = np.full((K, width), -1, np.int32)
            for k, xs in enumerate(lists):
                out[k, : len(xs)] = xs
            return out

        pod_create = pad([[row_of[k] for k in xs] for xs in step_pod_creates])
        pod_delete = pad([[row_of[k] for k in xs] for xs in step_pod_deletes])
        node_create = pad([[slot_of[n] for n in xs] for xs in step_node_creates])
        node_delete = pad([[slot_of[n] for n in xs] for xs in step_node_deletes])

        # The canonical featurizer advances its slot assignment ONLY on
        # passes that featurize — an empty eligible queue skips the sync
        # entirely (_schedule_pending_locked's `if not queue: continue`).
        # Queue emptiness depends on scheduling outcomes, so the lowering
        # PREDICTS it (a step with pod creates always has an eligible
        # queue: fresh pods carry no backoff) and the run validates the
        # prediction against the device-computed eligible counts,
        # discarding the segment on any mismatch (store untouched).
        pred_featurizes = [len(xs) > 0 for xs in step_pod_creates]
        sim_feat = self._service_featurizer()
        # No getattr default: if NodeSlots' internals ever change shape,
        # this must fail loudly — a silently empty seed would produce
        # wrong rank tensors and break the count locks undetected.
        sim = _SlotSim(sim_feat._slots.slot_of, sim_feat._slots._names)
        ranks = np.full((K, N), _I32_MAX, np.int32)
        # Per-step live-node views: name-order ranks + upstream's
        # candidate count for the preemption search; the live slot/name
        # lists (store list order = name order) for full-record decode.
        name_ranks = np.full((K, N), _I32_MAX, np.int32)
        want = np.zeros(K, np.int32)
        step_live_slots: list[np.ndarray] = []
        step_live_names: list[list[str]] = []
        step_node_event = [
            bool(step_node_creates[k] or step_node_deletes[k]) for k in range(K)
        ]
        from ksim_tpu.scheduler.preemption import candidate_count

        # Rank rows are maintained INCREMENTALLY: ``rank_row`` applies
        # only the slots each sync actually changed (the per-step delta
        # _SlotSim.sync now returns), and the sorted live-name list
        # evolves by bisect insert/remove — per-step cost is O(events +
        # one vectorized row copy), not the old O(N) python walk per
        # step over the whole slot map.
        rank_row = np.full(N, _I32_MAX, np.int32)
        for nm, slot in sim.slot_of.items():
            # .get: a dead node's name can linger in the service
            # featurizer's slot map (an empty-queue pass skips the
            # sync entirely); it has no universe slot and the kernels
            # never read its rank.
            j = slot_of.get(nm)
            if j is not None:
                rank_row[j] = slot
        need_names = preempt_plan or self._record_mode == "full"
        live_sorted: list[str] = sorted(node_names)
        live_slots = (
            np.asarray([slot_of[nm] for nm in live_sorted], np.int64)
            if need_names
            else None
        )
        for k in range(K):
            for nm in step_node_deletes[k]:
                j = bisect.bisect_left(live_sorted, nm)
                live_sorted.pop(j)
                if need_names:
                    live_slots = np.delete(live_slots, j)
            for nm in step_node_creates[k]:
                j = bisect.bisect_left(live_sorted, nm)
                live_sorted.insert(j, nm)
                if need_names:
                    live_slots = np.insert(live_slots, j, slot_of[nm])
            if pred_featurizes[k]:
                removed, changed = sim.sync(live_sorted)
                for nm in removed:
                    # .get: the sync may drop a name that predates the
                    # universe (see the seed loop above).
                    j = slot_of.get(nm)
                    if j is not None:
                        rank_row[j] = _I32_MAX
                for nm, slot in changed:
                    rank_row[slot_of[nm]] = slot
            ranks[k] = rank_row
            if need_names:
                want[k] = candidate_count(len(live_sorted))
                name_ranks[k, live_slots] = np.arange(
                    len(live_sorted), dtype=np.int32
                )
                if self._record_mode == "full":
                    # Only the full-record decode consumes the slot/name
                    # views — don't build them on the selection hot path.
                    step_live_slots.append(live_slots)
                    step_live_names.append(list(live_sorted))

        # Queue width: pending(now) + creates + requeue-able is an exact
        # upper bound on the pending population at any step, so eligible
        # can never exceed it (overflow-free by construction).
        pending_now = int(np.sum(alive0 & (bound0 < 0)))
        drained = set().union(*step_node_deletes) if step_node_deletes else set()
        drained_bound = sum(
            1
            for p in bound_pods
            if p.get("spec", {}).get("nodeName") in drained
        )
        hard_bound = pending_now + sum(len(x) for x in step_pod_creates) + drained_bound
        cap = svc._max_pods_per_pass or (1 << 30)
        q = bucket_size(max(min(cap, hard_bound), 1))

        statics = _SegmentStatics(
            k=K,
            q=q,
            cap=cap,
            n_tk=ipa.node_dom.shape[1],
            n_dom=n_dom_pad,
            record=self._record_mode,
            preempt=preempt_plan,
            c_max=PREEMPT_CANDIDATES,
            v_max=PREEMPT_VICTIMS,
            tp=tp,
        )
        const = {
            "node": dict(
                allocatable=feats.nodes.allocatable,
                allowed_pods=feats.nodes.allowed_pods,
                unschedulable=feats.nodes.unschedulable,
            ),
            "pods": dict(
                requests=feats.pods.requests,
                nonzero_requests=feats.pods.nonzero_requests,
                tolerates_unschedulable=feats.pods.tolerates_unschedulable,
                has_requests=feats.pods.has_requests,
            ),
            "aux": None,  # filled with the packed aux pytree below
        }
        ev = {
            "rank": ranks,
            "flush": np.asarray(step_flush, bool),
            "active": np.asarray(step_active, bool),
            "pod_create": pod_create,
            "pod_delete": pod_delete,
            "node_create": node_create,
            "node_delete": node_delete,
        }
        U = len(universe_pods)
        nominated0 = np.zeros(P, bool)
        for p in cur_pods:
            if p.get("status", {}).get("nominatedNodeName"):
                nominated0[row_of[_pod_key(p)]] = True
        # Stacked result tensors multiply one pass's [Q, F|S, N]
        # footprint by K on-device — bound it before dispatch.  The
        # budget is PER SHARD (round 17): each chip holds N/tp node
        # columns of every stacked tensor, so record="full" headroom
        # scales with the mesh.  Computed in every record mode (the
        # lower_log / bench rung report it as sizing evidence); only
        # record="full" actually allocates, so only it rejects.
        bits_dt, final_dt = prog._result_dtypes()
        n_f = sum(1 for sp in plugins if sp.filter_enabled)
        n_s = sum(1 for sp in plugins if sp.score_enabled)
        per_cell = (
            n_f * np.dtype(bits_dt).itemsize
            + n_s * 4
            + n_s * np.dtype(final_dt).itemsize
        )
        full_bytes_shard = K * q * (N // tp) * per_cell
        if self._record_mode == "full" and full_bytes_shard > FULL_RECORD_BYTES:
            raise _Unsupported("full_record_bytes")
        if preempt_plan:
            from ksim_tpu.scheduler.preemption import (
                more_important_key,
                pod_eligible_to_preempt,
                start_time,
            )
            from ksim_tpu.state import objcache

            # Per-pod statics memoized on object identity (the cached
            # universe keeps survivors' objects alive across segments,
            # so the JSON walks behind these keys run once per pod, not
            # once per segment).  ``more_important_key`` depends on the
            # priority resolver, so its memo carries the resolver
            # generation — a rebuilt resolver (cache miss) mints fresh
            # entries instead of trusting stale priorities.
            def mik(p: JSON):
                return objcache.cached(
                    "replay_mik",
                    p,
                    lambda: more_important_key(p, priority_of),
                    prio_gen,
                )

            def stime(p: JSON) -> str:
                return objcache.cached("replay_stime", p, lambda: start_time(p))

            priority = np.zeros(P, np.int32)
            imp_rank = np.full(P, _I32_MAX, np.int32)
            start_rank = np.zeros(P, np.int32)
            preempt_ok = np.zeros(P, bool)
            priority[:U] = prios  # computed with the preempt_plan screen above
            for r, j in enumerate(
                sorted(range(U), key=lambda j: mik(universe_pods[j]))
            ):
                imp_rank[j] = r
            starts = sorted({stime(p) for p in universe_pods} | {""})
            srank = {sv: i for i, sv in enumerate(starts)}
            for j, p in enumerate(universe_pods):
                start_rank[j] = srank[stime(p)]
                preempt_ok[j] = objcache.cached(
                    "replay_pel", p, lambda p=p: pod_eligible_to_preempt(p)
                )
            const["pods"].update(
                priority=priority,
                imp_rank=imp_rank,
                start_rank=start_rank,
                preempt_ok=preempt_ok,
            )
            const["empty_start_rank"] = np.asarray(srank[""], np.int32)
            ev["name_rank"] = name_ranks
            ev["want"] = want
            if self._record_mode == "full":
                # Per-plugin reason-bit -> "resolvable by preemption"
                # tables (the traceable form of service._resolvable_mask:
                # a missing failure_unresolvable rule is conservatively
                # unresolvable, exactly like the host path).
                tables = []
                for sp in plugins:
                    if not sp.filter_enabled:
                        continue
                    w = int(getattr(sp.plugin, "reason_bit_width", 31))
                    if w > 10:
                        raise _Unsupported("preemption_bits_width")
                    rule = getattr(sp.plugin, "failure_unresolvable", None)
                    t = np.zeros(1 << w, bool)
                    if rule is not None:
                        for b in range(1, 1 << w):
                            t[b] = not rule(b)
                    tables.append(t)
                tw = max((len(t) for t in tables), default=1)
                resolv = np.zeros((max(len(tables), 1), tw), bool)
                for fi, t in enumerate(tables):
                    resolv[fi, : len(t)] = t
                const["resolv"] = resolv
        state0 = {
            "valid": valid0,
            "requested": feats.nodes.requested,
            "nonzero_requested": feats.nodes.nonzero_requested,
            "pod_count": feats.nodes.pod_count,
            "alive": alive0,
            "bound": bound0,
            "attempts": attempts0,
            "retry_at": retry0,
            "nominated": nominated0,
            "spread": spread.init_counts,
            "ip_cnt": ip_cnt0,
            "ip_eat": ip_eat0,
            "ip_vw": ip_vw0,
            "pass_count": np.asarray(svc._pass_count, np.int32),
        }
        # O(delta) evidence: fresh per-pod featurize rows this lower
        # actually built vs the window's event count (the lock-check
        # guard asserts steady-state proportionality; counters, not
        # timings, so it is CI-stable).
        self.lower_log.append(
            {
                "events": sum(len(b) for b in batches),
                "steps": m_steps,
                "universe": U,
                "rows_built": self._featurizer.pod_rows_built - rows_built0,
                "cache_hit": use_cache,
                "tp": tp,
                "full_bytes_per_shard": int(full_bytes_shard),
            }
        )
        return _SegmentPlan(
            statics=statics,
            prog=prog,
            const=const,
            aux=feats.aux,
            ev=ev,
            state0=state0,
            universe_keys=universe_keys,
            universe_row_of=row_of,
            node_names=list(feats.nodes.names),
            n_steps=m_steps,
            pred_featurizes=pred_featurizes,
            initial_pass_count=int(svc._pass_count),
            step_live_slots=step_live_slots,
            step_live_names=step_live_names,
            step_node_event=step_node_event,
            lower_epoch=lower_epoch,
            sort_keys=uni_sort,
            clean_pods=uni_clean,
            priority_of=priority_of,
            prio_gen=prio_gen,
            sched_names=sched_names,
            dev_collect=bool(self._dev_cache_on),
            mesh=self._shard_mesh_obj,
        )

    @staticmethod
    def _check_interpod_locals(ipa, cnt, eat, vw, n_dom_pad: int) -> bool:
        """Verify the local accumulators re-derive the featurizer's own
        domain-aggregated carry init (numpy mirror of _derive_interpod) —
        the lowering-time guard against delta/aggregation skew."""
        node_dom = ipa.node_dom  # [N, TK]
        term_tk = ipa.term_tk  # [T]
        dom_t = ipa.dom_t
        expect = {"cnt": ipa.cnt_node, "ecnt": ipa.ecnt_node, "ew": ipa.ew_node}
        got = {}
        for name, arr in (("cnt", cnt), ("ecnt", eat), ("ew", vw)):
            acc = np.zeros_like(arr)
            for k in range(node_dom.shape[1]):
                ids = node_dom[:, k]
                safe = np.where(ids >= 0, ids, n_dom_pad)
                seg = np.zeros((n_dom_pad + 1, arr.shape[1]), arr.dtype)
                np.add.at(seg, safe, arr)
                derived = np.where(ids[:, None] >= 0, seg[safe], 0)
                acc = np.where((term_tk == k)[None, :], derived, acc)
            got[name] = acc
        total = np.sum(np.where(dom_t >= 0, cnt, 0), axis=0, dtype=np.int64)
        ok = all(np.array_equal(got[k], expect[k]) for k in expect) and np.array_equal(
            total.astype(np.int32), ipa.total
        )
        if not ok:
            logger.warning(
                "device replay: inter-pod local accumulators disagree with "
                "the featurizer's aggregation; falling back to per-pass"
            )
        return ok

    # -- dispatch + decode ---------------------------------------------------

    def _step_render_ctx(self, plan: "_SegmentPlan", k: int):
        """RenderCtx over step k's live node set (rebuilt only when a
        node event changed the set — the common segment reuses one)."""
        from ksim_tpu.engine.annotations import RenderCtx

        return RenderCtx(plan.step_live_names[k], plan.prog.plugins)

    def _render_step_annotations(
        self, plan: "_SegmentPlan", k: int, att, pulled, noms, ctx
    ) -> list[dict]:
        """record="full": the 13 result annotations for every attempt of
        step k, decoded from the streamed result tensors exactly as the
        per-pass path renders them — same renderer, node axis restricted
        to the step's live set (dead universe slots never existed for
        that pass), postfilter map from the on-device preemption
        outcome."""
        from ksim_tpu.engine.annotations import render_pod_results
        from ksim_tpu.engine.core import EngineResult
        from ksim_tpu.scheduler.preemption import DEFAULT_PREEMPTION, NOMINATED_MESSAGE

        slots = plan.step_live_slots[k]
        names = plan.step_live_names[k]
        pos_of = {int(s): i for i, s in enumerate(slots)}
        sel_k = np.asarray(pulled["sel"][k])[att]
        bits = np.asarray(pulled["bits"][k])[att][:, :, slots]
        raw = np.asarray(pulled["raw"][k])[att][:, :, slots]
        fin = np.asarray(pulled["final"][k])[att][:, :, slots]
        sel_sub = np.asarray(
            [pos_of.get(int(s), -1) if s >= 0 else -1 for s in sel_k], np.int64
        )
        plugins = plan.prog.plugins
        res = EngineResult(
            plugin_names=[sp.plugin.name for sp in plugins if sp.score_enabled],
            filter_plugin_names=[
                sp.plugin.name for sp in plugins if sp.filter_enabled
            ],
            reason_bits=bits,
            scores=raw,
            final_scores=fin,
            total=None,
            feasible=sel_sub >= 0,
            selected=sel_sub,
        )
        preempt = plan.statics.preempt
        out = []
        for i, qq in enumerate(att):
            postfilter = None
            if preempt and sel_sub[i] < 0:
                # _attempt_preemption's render_postfilter_result: every
                # live node gets an entry; the nominated one (if any)
                # names the plugin.
                postfilter = {nm: {} for nm in names}
                nsl = int(noms[k, qq])
                if nsl >= 0:
                    postfilter[plan.node_names[nsl]] = {
                        DEFAULT_PREEMPTION: NOMINATED_MESSAGE
                    }
            out.append(
                render_pod_results(
                    None, plugins, res, i, postfilter=postfilter, ctx=ctx
                )
            )
        return out

    def _run(self, plan: "_SegmentPlan") -> "SegmentOutcome | str":  # ksimlint: worker-thread
        """Dispatch one lowered segment and decode its outputs.

        Returns the SegmentOutcome, or a DISCARD REASON string when
        post-dispatch validation rejects the results (store untouched
        either way).  Runs on the watchdog worker thread: it must not
        mutate driver state — ``try_segment`` applies all accounting on
        the main thread after a successful join."""
        if self._lane_faults is not None:
            # The lane's private plane fires here — inside the
            # watchdogged worker like the global plane — so a
            # lane-armed hang schedule is watchdog-bounded on the solo
            # path.  The check lives in _run, NOT _device_exec: the
            # fleet's group dispatch calls _device_exec directly after
            # gating every lane's plane on the coordinator thread, and
            # a second check here would double-count the leader's
            # schedule.
            self._lane_faults.check("replay.dispatch")
        pulled_state, pulled = self._device_exec(plan)
        return self._decode_outputs(plan, pulled_state, pulled)

    def _device_exec(self, plan: "_SegmentPlan"):  # ksimlint: worker-thread
        """The device half of a dispatch: pack constants (id-keyed
        buffer reuse), execute the compiled segment program, pull the
        carried state + per-step outputs back to host numpy.  Worker
        thread; side-effect-free on the driver (packing evidence rides
        on the plan).  The dispatch goes through the process-wide
        compile-once gate (engine/compilecache.py): the first caller of
        a shape rung compiles, concurrent same-rung callers — other
        tenant jobs on the same bucketed shapes — wait and reuse."""
        from ksim_tpu.engine.core import _pull_tree_to_host

        FAULTS.check("replay.dispatch")
        if plan.statics.tp > 1:
            # Round 17: committed NamedShardings on every input leaf —
            # GSPMD lays the node axis over the tp mesh and inserts the
            # per-step collectives; the scan carry stays sharded on
            # device end to end.  An explicit service mesh rides on the
            # plan; the env-knob mesh is built lazily HERE (this is the
            # watchdogged worker — jax.devices() may initialize the
            # backend, which must never happen on the main thread).
            mesh = plan.mesh if plan.mesh is not None else _tp_mesh(plan.statics.tp)
            const_dev, (ev_dev, state_dev) = _shard_plan_buffers(
                plan, (plan.ev, plan.state0), mesh
            )
        else:
            mesh = None
            const_dev, (ev_dev, state_dev) = _pack_plan_buffers(
                plan, (plan.ev, plan.state0)
            )
        # Mesh dispatches take the non-donating twin — donated
        # multi-device carries race on the virtual-device CPU backend
        # (the _DONATE_ARGNUMS note); the cache key's mesh component
        # keeps the two executables distinct.
        seg_fn = _segment_fn if mesh is None else _segment_fn_nodonate
        final_state, outs = COMPILE_CACHE.run(
            _compile_cache_key("solo", plan, (const_dev, ev_dev, state_dev), mesh=mesh),
            lambda: seg_fn(
                plan.statics, plan.prog, const_dev, ev_dev, state_dev
            ),
            owner=TRACE.scope_tags().get("job"),
            wait_s=self.watchdog_s if self.watchdog_s > 0 else 300.0,
            # The persistent layer (round 15): a warm restart loads the
            # serialized executable instead of re-compiling; None when
            # KSIM_AOT_CACHE is off/unset (and no KSIM_JOBS_DIR) or the
            # plan's identity is process-local.
            disk=_aot_disk_spec("solo", plan, (const_dev, ev_dev, state_dev)),
        )
        pulled_state, pulled = _pull_tree_to_host(
            (
                {
                    k: final_state[k]
                    for k in ("alive", "bound", "attempts", "retry_at", "pass_count")
                },
                outs,
            )
        )
        return pulled_state, pulled

    def _decode_outputs(  # ksimlint: worker-thread
        self, plan: "_SegmentPlan", pulled_state, pulled
    ) -> "SegmentOutcome | str":
        """The host half of a dispatch: validate the featurize/overflow
        predictions and decode the pulled tensors into a SegmentOutcome
        (or a discard-reason string).  Runs on the watchdog worker for
        solo dispatches; the fleet calls it once per LANE on the main
        thread with that lane's slice of the stacked outputs — the
        decode only reads the (shared) plan, the lane's pulled arrays,
        and the lane's own service backoff table."""
        st = plan.statics
        eligible = np.asarray(pulled["eligible"])
        for k in range(plan.n_steps):
            if bool(eligible[k] > 0) != plan.pred_featurizes[k]:
                # The sync-schedule prediction missed (a create-free step
                # still had eligible pods, or every eligible pod vanished).
                # That only matters when the divergent sync schedules can
                # see DIFFERENT node sets: the slot sim is a pure function
                # of the live-node sequence, and a sync over an unchanged
                # set is a no-op.  Both schedules agree (and synced the
                # same steps) before this first mismatch; if no node event
                # happened after the last predicted sync, the node set is
                # frozen from there on, every later sync in EITHER
                # schedule is a no-op, and the shipped rank tensors are
                # provably identical — the window stays on-device.  (This
                # is what keeps static-universe trace streams, whose
                # create-free steps routinely carry eligible pods, at
                # zero fallbacks — docs/churn_floor.md.)  With a node
                # event past that sync the divergence is real: the
                # shipped rank tensors may assume the wrong slot history.
                # The store is untouched: discard and fall back.
                last_sync = max(
                    (j for j in range(k) if plan.pred_featurizes[j]), default=-1
                )
                if any(plan.step_node_event[last_sync + 1 : plan.n_steps]):
                    return "featurize_prediction"
                break
        if st.preempt and bool(
            np.any(np.asarray(pulled["overflow"])[: plan.n_steps])
        ):
            # A victim search exceeded the static candidate/victim
            # bounds: the computed outcomes past that point assumed a
            # truncated search.  Store untouched — discard, fall back.
            return "preemption_overflow"

        sel = np.asarray(pulled["sel"])  # [K, Q]
        idx = np.asarray(pulled["idx"])  # [K, Q]
        P = len(plan.universe_keys)
        detailed = st.preempt or st.record == "full"
        noms = np.asarray(pulled["nom"]) if st.preempt else None
        vics = np.asarray(pulled["vic"]) if st.preempt else None
        steps: list[StepOutcome] = []
        render_ctx = None
        for k in range(plan.n_steps):
            att = np.nonzero(idx[k] < P)[0]
            binds = []
            attempts = None
            if detailed:
                annos = [None] * len(att)
                if st.record == "full":
                    if render_ctx is None or plan.step_node_event[k]:
                        render_ctx = self._step_render_ctx(plan, k)
                    annos = self._render_step_annotations(
                        plan, k, att, pulled, noms, render_ctx
                    )
                attempts = []
                for i, qq in enumerate(att):
                    key = plan.universe_keys[int(idx[k, qq])]
                    ns, _, nm = key.partition("/")
                    sl = int(sel[k, qq])
                    node = plan.node_names[sl] if sl >= 0 else None
                    nominated = None
                    victims: list[tuple[str, str]] = []
                    if st.preempt:
                        nsl = int(noms[k, qq])
                        nominated = plan.node_names[nsl] if nsl >= 0 else None
                        for vr in vics[k, qq]:
                            if vr >= 0:
                                vkey = plan.universe_keys[int(vr)]
                                vns, _, vnm = vkey.partition("/")
                                victims.append((vns, vnm))
                    attempts.append(
                        AttemptOutcome(
                            namespace=ns,
                            name=nm,
                            node=node,
                            nominated=nominated,
                            victims=victims,
                            anno=annos[i],
                        )
                    )
                    if node is not None:
                        binds.append((ns, nm, node))
            else:
                for qq in np.nonzero((idx[k] < P) & (sel[k] >= 0))[0]:
                    key = plan.universe_keys[int(idx[k, qq])]
                    ns, _, nm = key.partition("/")
                    binds.append((ns, nm, plan.node_names[int(sel[k, qq])]))
            steps.append(
                StepOutcome(
                    scheduled=int(pulled["scheduled"][k]),
                    unschedulable=int(pulled["unschedulable"][k]),
                    pending_after=int(pulled["pending_after"][k]),
                    eligible=int(eligible[k]),
                    binds=binds,
                    attempts=attempts,
                )
            )
        alive = np.asarray(pulled_state["alive"])[:P]
        bound = np.asarray(pulled_state["bound"])[:P]
        attempts = np.asarray(pulled_state["attempts"])[:P]
        retry = np.asarray(pulled_state["retry_at"])[:P]
        # Per-pass keeps DEAD pods' backoff entries too (until its
        # shedding valve prunes them), so export every universe row's
        # entry — device flushes already updated the dead ones — and
        # fold in pre-segment entries for keys outside the universe,
        # applying the same flush cap the per-pass path would have
        # (one min against the FIRST flush step's pre-pass count is
        # exactly the running minimum over all of them).
        backoff = {
            plan.universe_keys[j]: (int(attempts[j]), int(retry[j]))
            for j in np.nonzero(attempts > 0)[0]
        }
        pcs = np.asarray(pulled["pass_count"]).reshape(-1)
        _max_backoff, flush_cap = _backoff_constants()
        flush = np.asarray(plan.ev["flush"])
        first_flush_pc = None
        for k in range(plan.n_steps):
            if bool(flush[k]):
                first_flush_pc = int(pcs[k - 1]) if k else plan.initial_pass_count
                break
        # Snapshot under the service's lock: _run executes on the
        # watchdog worker thread, and an ABANDONED worker (timeout)
        # races the main thread's per-pass fallback mutating _backoff —
        # an unlocked iteration could die mid-dict-resize.
        with self.service._backoff_lock:
            svc_backoff = dict(self.service._backoff)
        for key, (a, r) in svc_backoff.items():
            if key in backoff or key in plan.universe_row_of:
                continue
            if first_flush_pc is not None:
                r = min(r, first_flush_pc + min(a - 1, flush_cap))
            backoff[key] = (a, r)
        bound_view = {
            plan.universe_keys[j]: plan.node_names[int(bound[j])]
            for j in np.nonzero(alive & (bound >= 0))[0]
        }
        pending_view = {
            plan.universe_keys[j] for j in np.nonzero(alive & (bound < 0))[0]
        }
        return SegmentOutcome(
            steps=steps,
            pass_count=int(np.asarray(pulled_state["pass_count"]).ravel()[0]),
            backoff=backoff,
            bound_view=bound_view,
            pending_view=pending_view,
        )

    # -- reconcile -----------------------------------------------------------

    def advance_service_slots(self, step_nodes: "Sequence[Any]") -> None:
        """Roll the canonical featurizer's slot history forward one
        entry per reconciled step (``None`` = the pass never featurized:
        empty eligible queue — the per-pass path skips the sync too), so
        any LATER fallback pass sees exactly the node order the pure
        per-pass history would have produced.  Called AFTER the segment
        transaction commits: the featurizer has no rollback, so staging
        must never touch it."""
        feat = self._service_featurizer()
        for nodes in step_nodes:
            if nodes is not None:
                feat.advance_slots(nodes)

    def verify_segment(self, seg: SegmentOutcome) -> None:
        """Verify the staged store converged to the device's view of the
        cluster.  Runs INSIDE the segment transaction: a mismatch raises
        ReplayParityError and the transaction rolls every staged write
        back — loud, but no longer store-poisoning."""
        store_bound = {
            _pod_key(p): p["spec"]["nodeName"]
            for p in self.store.pods_with_node()
        }
        store_pending = {
            _pod_key(p) for p in self.store.pods_without_node()
        }
        if store_bound != seg.bound_view or store_pending != seg.pending_view:
            extra = set(store_bound) ^ set(seg.bound_view)
            raise ReplayParityError(
                "device-resident replay diverged from the store after "
                f"reconcile: {len(extra)} pod(s) differ (e.g. "
                f"{sorted(extra)[:3]}); bound {len(store_bound)} vs "
                f"{len(seg.bound_view)}, pending {len(store_pending)} vs "
                f"{len(seg.pending_view)}"
            )

    def sync_service(self, seg: SegmentOutcome) -> None:
        """Sync service bookkeeping (pass counter, backoff table) to the
        committed device outcome — post-commit only, like every other
        non-store effect of a segment."""
        svc = self.service
        svc._pass_count = seg.pass_count
        with svc._backoff_lock:
            svc._backoff = dict(seg.backoff)
        # A committed segment proves the whole device->store pipeline is
        # healthy: reset the reconcile side of the breaker window.
        self._consecutive_reconcile_faults = 0
        self._advance_cache(seg)

    def _advance_cache(self, seg: SegmentOutcome) -> None:
        """Roll the lowered-universe cache forward to the committed
        segment's end state: the lowered universe filtered to the pods
        the device left alive (``verify_segment`` — which ran inside the
        just-committed transaction — proved that view byte-identical to
        the store).  Refuses and invalidates if the store epoch moved
        since the lowering read it: an out-of-band write interleaved
        with the dispatch, and the cache must not paper over it."""
        plan = self._last_plan
        cache = self._cache
        if plan is None:
            cache.invalidate("no_plan")
            return
        if self.store.mutation_epoch != plan.lower_epoch:
            cache.invalidate("epoch_raced")
            return
        surv = set(seg.bound_view) | set(seg.pending_view)
        keep = [j for j, k in enumerate(plan.universe_keys) if k in surv]
        cache.keys = [plan.universe_keys[j] for j in keep]
        cache.sort_keys = [plan.sort_keys[j] for j in keep]
        cache.clean_pods = [plan.clean_pods[j] for j in keep]
        cache.priority_of = plan.priority_of
        cache.prio_gen = plan.prio_gen
        cache.sched_names = plan.sched_names
        cache.epoch = plan.lower_epoch
        cache.valid = True

    def note_reconcile_fault(self) -> None:
        """Account one rolled-back segment reconcile (the runner's
        atomic-commit fallback).  Consecutive rollbacks trip the same
        sticky breaker as device failures: a persistently failing
        reconcile would otherwise pay a full lowering + dispatch +
        rollback for every remaining step with no containment.  The
        lowered-universe cache and the speculative prefix are STRICTLY
        flushed: the rolled-back window's head step is about to re-run
        per-pass, mutating state the incremental bookkeeping does not
        track."""
        self._reject("reconcile_fault")
        self._flush_incremental("rollback")
        self._consecutive_reconcile_faults += 1
        if (
            not self.breaker_tripped
            and self._consecutive_reconcile_faults >= self.breaker_threshold
        ):
            self.breaker_tripped = True
            self._breaker_schedule_retry()
            TRACE.event(
                "replay.breaker_open",
                cause="reconcile_fault",
                consecutive=self._consecutive_reconcile_faults,
            )
            logger.error(
                "device replay circuit breaker TRIPPED after %d consecutive "
                "segment-reconcile rollbacks (threshold %d); remaining steps "
                "run on the per-pass host path",
                self._consecutive_reconcile_faults, self.breaker_threshold,
            )


def _compile_cache_key(kind: str, plan: "_SegmentPlan", dev_tree, mesh=None) -> tuple:
    """The shape-rung identity of one dispatch, for the process-wide
    compile-once gate (engine/compilecache.py): the hashable program
    statics, the profile token (``_Program`` hashes on its plugin
    signature, so two tenants with equal scheduler configs share), the
    x64 mode, and the dtype/shape signature of every input leaf — the
    bucketed shape ladder makes these collide across same-rung tenants
    by construction.  ``kind`` separates the solo and lane-stacked
    (fleet) programs, which compile differently for identical inputs;
    ``mesh`` (round 19) adds the (dp, tp) device-grid shape — a fleet
    dispatch on a 2-D mesh commits different input shardings than a
    single-device one of identical avals, so they must not share a
    rung."""
    leaves = jax.tree_util.tree_leaves(dev_tree)
    sig = tuple((str(a.dtype), tuple(a.shape)) for a in leaves)
    grid = tuple(int(d) for d in mesh.devices.shape) if mesh is not None else None
    return (
        kind, plan.statics, plan.prog, bool(jax.config.jax_enable_x64), grid, sig,
    )


# ---------------------------------------------------------------------------
# Persistent executables (round 15): the compile cache's on-disk layer
# ---------------------------------------------------------------------------


def _aot_cache_dir() -> "str | None":
    """Where serialized executables live: ``KSIM_AOT_CACHE`` (a path, or
    ``off`` to disable), defaulting to ``$KSIM_JOBS_DIR/aot`` when the
    durable job plane is on — a restarted server then warms from the
    same directory its journal lives in.  None disables persistence
    (the jax compilation cache wired in ksim_tpu/util.py:15 still
    soft-warms XLA compiles underneath either way)."""
    raw = os.environ.get("KSIM_AOT_CACHE", "")
    if raw == "off":
        return None
    if raw:
        return raw
    jobs_dir = os.environ.get("KSIM_JOBS_DIR", "")
    return os.path.join(jobs_dir, "aot") if jobs_dir else None


def _aot_stable_token(obj) -> "str | None":
    """A CROSS-PROCESS-deterministic rendering of jit-cache key
    material, or None when the object's identity is process-local and
    must not be persisted.  The in-memory key (``_compile_cache_key``)
    leans on ``hash``/``repr`` semantics that do not survive a restart:
    frozenset iteration order moves with hash randomization, and
    ``_plugin_sig``'s ``("@id", id(plugin))`` fallback (engine/core.py)
    is a memory address.  This canonicalizer sorts unordered
    collections, recurses dataclasses field-by-field, admits only
    scalar leaves — and refuses (None) anything else, so a plan whose
    identity cannot be pinned simply skips the disk layer instead of
    colliding in it."""
    if obj is None or isinstance(obj, (bool, int, float, str, bytes)):
        return repr(obj)
    if isinstance(obj, tuple) and len(obj) == 2 and obj[0] == "@id":
        return None  # process-local plugin identity
    if isinstance(obj, (tuple, list)):
        parts = []
        for item in obj:
            t = _aot_stable_token(item)
            if t is None:
                return None
            parts.append(t)
        return "(" + ",".join(parts) + ")"
    if isinstance(obj, (frozenset, set)):
        parts = []
        for item in obj:
            t = _aot_stable_token(item)
            if t is None:
                return None
            parts.append(t)
        return "{" + ",".join(sorted(parts)) + "}"
    if is_dataclass(obj) and not isinstance(obj, type):
        parts = [type(obj).__name__]
        for f in fields(obj):
            t = _aot_stable_token(getattr(obj, f.name))
            if t is None:
                return None
            parts.append(f"{f.name}={t}")
        return "<" + ";".join(parts) + ">"
    return None


class _AotDiskSpec:
    """The compile cache's duck-typed disk handle for one solo segment
    dispatch (engine/compilecache.py ``run(disk=...)``): entry path +
    identity token + the three jax-touching callables.  Lives entirely
    on the watchdogged worker thread and holds no driver reference —
    kernel purity and the worker-thread write ban stay intact."""

    __slots__ = ("path", "token", "_plan", "_args")

    def __init__(self, path: str, token: str, plan, args) -> None:
        self.path = path
        self.token = token
        self._plan = plan
        self._args = args

    def load(self, blob: bytes):
        """Serialized entry -> a dispatchable callable.  ``jax.jit``
        over the exported call keeps repeat dispatches on the fast
        C++ path.  A matching startup-prewarmed executable
        (``prewarm_aot_cache``) is served instead of deserializing
        again — the crc re-check means a rewritten entry can never be
        handed a stale program."""
        from jax import export as jax_export

        with _PREWARM_LOCK:
            ent = _PREWARMED.get(self.path)
        if ent is not None and ent[0] == (zlib.crc32(blob) & 0xFFFFFFFF):
            return ent[1]
        return jax.jit(jax_export.deserialize(blob).call)

    def invoke(self, exec_obj):
        return exec_obj(*self._args)

    def serialize(self) -> "bytes | None":
        """Export the freshly compiled program for the next process.
        ``jax.export`` bakes the static argnums in at export time, so
        the deserialized call takes only the dynamic operands."""
        from jax import export as jax_export

        ex = jax_export.export(_segment_fn)(
            self._plan.statics, self._plan.prog, *self._args
        )
        return ex.serialize()


def _aot_disk_spec(kind: str, plan: "_SegmentPlan", args) -> "_AotDiskSpec | None":
    """Build the disk handle for one dispatch, or None when persistence
    is off or the plan's identity is not stable across processes
    (custom plugin objects without a static signature).  The token pins
    everything a stale entry could differ in: jax/jaxlib version,
    backend, program kind, statics, the profile signature, x64 mode and
    the full dtype/shape ladder rung."""
    base = _aot_cache_dir()
    if base is None:
        return None
    body = _aot_stable_token((
        kind,
        plan.statics,
        plan.prog._sig,
        bool(jax.config.jax_enable_x64),
        tuple(
            (str(a.dtype), tuple(a.shape))
            for a in jax.tree_util.tree_leaves(args)
        ),
    ))
    if body is None:
        return None
    # The device count joins the version/backend prefix (round 17): a
    # serialized executable bakes its input shardings in, so a warm
    # restart on a DIFFERENT topology (tp=8 entry, single-device host)
    # must be a counted miss/eviction, never a wrong load.  The mesh
    # width itself already rides in the statics (``tp``) inside body.
    token = f"{jax.__version__}|{jax.default_backend()}|d{jax.device_count()}|{body}"
    name = hashlib.sha256(token.encode()).hexdigest()[:32] + ".aot"
    return _AotDiskSpec(os.path.join(base, name), token, plan, args)


#: Executables deserialized at server startup (``prewarm_aot_cache``):
#: path -> (crc32 of the stored blob, jitted call).  Consulted by
#: ``_AotDiskSpec.load`` so the first tenant dispatch of an
#: already-learned shape rung skips the deserialize round.
_PREWARM_LOCK = threading.Lock()
_PREWARMED: dict = {}  # guarded-by: _PREWARM_LOCK


def prewarm_aot_cache(*, speculative: bool = False) -> int:  # ksimlint: thread-role(service-loop)
    """``KSIM_AOT_PREWARM=1`` (cmd/simulator.py): walk the on-disk AOT
    directory at server startup and deserialize every entry whose token
    matches THIS process's jax version / backend / device count —
    load-only, never cold-compiles.  A corrupt, foreign-version or
    foreign-topology entry is SKIPPED, not evicted: eviction authority
    stays with the dispatch path's token check, where the exact rung
    identity is known.  Returns the number prewarmed; the process-wide
    ``compile_cache`` counters carry it as ``disk_prewarmed``.

    ``speculative=True`` is the rescan-loop variant (AOT cache round 2,
    ``prewarm_rescan_loop``): only entries NOT already in the prewarm
    registry load — on-disk executables that appeared after startup are
    another fleet worker's compiles, including ladder rungs this
    process never dispatched, and loading them makes one worker's
    compile every worker's warm start.  Counted separately as
    ``disk_speculative``."""
    base = _aot_cache_dir()
    if base is None or not os.path.isdir(base):
        return 0
    from jax import export as jax_export

    prefix = f"{jax.__version__}|{jax.default_backend()}|d{jax.device_count()}|"
    n = 0
    for fname in sorted(os.listdir(base)):
        if not fname.endswith(".aot"):
            continue
        path = os.path.join(base, fname)
        if speculative:
            with _PREWARM_LOCK:
                if path in _PREWARMED:
                    continue
        ent = COMPILE_CACHE.read_disk_entry(path)
        if ent is None:
            continue
        token, blob = ent
        if not token.startswith(prefix):
            continue
        try:
            call = jax.jit(jax_export.deserialize(blob).call)
        except Exception:
            logger.warning("aot prewarm: skipping undeserializable %s", fname)
            continue
        with _PREWARM_LOCK:
            _PREWARMED[path] = (zlib.crc32(blob) & 0xFFFFFFFF, call)
        n += 1
    if n:
        if speculative:
            COMPILE_CACHE.note_speculative(n)
        else:
            COMPILE_CACHE.note_prewarmed(n)
    return n


def prewarm_rescan_loop(
    stop: "threading.Event | None" = None,
    interval_s: "float | None" = None,
) -> None:  # ksimlint: thread-role(service-loop)
    """``KSIM_AOT_PREWARM=2`` (cmd/simulator.py): the startup prewarm
    pass, then a speculative rescan every ``KSIM_AOT_PREWARM_RESCAN_S``
    seconds (default 30) picking up executables OTHER fleet workers
    stored since the last scan.  Runs forever on its daemon thread;
    ``stop`` is the tests' exit handle."""
    if interval_s is None:
        interval_s = float(os.environ.get("KSIM_AOT_PREWARM_RESCAN_S", "30"))
    interval_s = max(float(interval_s), 0.05)
    if stop is None:
        stop = threading.Event()
    try:
        prewarm_aot_cache()
    except RunCancelled:
        raise
    except Exception:
        logger.exception("aot prewarm startup pass failed")
    while not stop.wait(interval_s):
        try:
            prewarm_aot_cache(speculative=True)
        except RunCancelled:
            raise
        except Exception:
            # One failed rescan (e.g. the cache dir vanished mid-walk)
            # must not kill the loop — the next tick retries.
            logger.exception("aot speculative rescan failed")


def _plan_const_parts(plan: "_SegmentPlan"):
    """The plan's universe-constant trees in canonical order (node
    statics, pod rows, the optional preemption extras, the packed aux
    host tree) — the id-keyed-reuse "cacheable" half of a dispatch's
    inputs, shared by the solo and fleet executors."""
    from ksim_tpu.engine.core import _aux_host

    aux_host, _axes = _aux_host(plan.aux)
    const = dict(plan.const)
    extra = {k: const[k] for k in ("resolv", "empty_start_rank") if k in const}
    return (const["node"], const["pods"], extra, aux_host)


def _const_dev_dict(cacheable_dev) -> dict:
    node_dev, pods_dev, extra_dev, aux_dev = cacheable_dev
    return {"node": node_dev, "pods": pods_dev, "aux": aux_dev, **extra_dev}


def _reuse_scan(reuse, c_leaves):
    """Split const leaves into device-buffer reuse hits and transfer
    misses — the shared first half of both executors' packers.  Two
    rungs: the id-keyed fast path (the featurizer kept the host array
    OBJECT alive since last window), then a positional VALUE rung —
    ``_plan_const_parts`` flattens in canonical order and the reuse
    map preserves insertion order, so leaf ``i`` aligns with last
    window's leaf ``i``.  The value rung is what makes steady-state
    reuse real on churn streams: the featurizer restacks its tensors
    every lower (fresh array ids even on a lowered-universe cache
    hit) while the steady-state VALUES are unchanged, so an id-only
    map misses wholesale forever.  Byte-equality is the full safety
    condition (the cached device buffer holds exactly the bytes the
    transfer would produce); positional alignment only affects the
    hit rate, never correctness.  A changed leaf pays one short-
    circuiting memcmp before it transfers — cheap against the H2D
    round trip it replaces."""
    prev = list(reuse.values()) if reuse else None
    dev_c: "list[Any]" = [None] * len(c_leaves)
    miss_idx: "list[int]" = []
    for i, a in enumerate(c_leaves):
        ent = reuse.get(id(a)) if reuse else None
        if ent is not None and ent[0] is a:
            dev_c[i] = ent[1]
            continue
        if prev is not None and i < len(prev):
            pa, pd = prev[i]
            if (
                isinstance(a, np.ndarray)
                and isinstance(pa, np.ndarray)
                and pa.shape == a.shape
                and pa.dtype == a.dtype
                and np.array_equal(pa, a)
            ):
                dev_c[i] = pd
                continue
        miss_idx.append(i)
    return dev_c, miss_idx


def _pack_plan_buffers(plan: "_SegmentPlan", transient):
    """ONE transfer protocol for both executors: constant buffers (node
    statics, pod rows, aux tables) that are the SAME host arrays as the
    previous dispatch — the featurizer family caches and the
    lowered-universe cache keep them identity-stable when the
    underlying objects survived — reuse their device buffers instead of
    re-transferring; everything else (the caller's per-segment
    ``transient`` tree: event streams + the solo or lane-stacked carry)
    packs into the usual single byte-buffer transfer.  The id-keyed map
    pins its host arrays, so a recycled id can never alias a fresh
    array; identity is the fast path and positional byte-equality the
    second rung (``_reuse_scan`` — the featurizer restacks tensors
    every lower, so steady-state reuse is a VALUE property, not an id
    one).  Reuse evidence (dev_hits/dev_misses) and the next window's
    reuse map (dev_map_out, only when the driver will adopt it — with
    the cache off, retaining it would pin a full segment's constant
    buffers across the next window: the KSIM_H2D_CACHE pinning
    pathology, engine/core.py) ride on the plan.

    Returns ``(const_dev, transient_dev)``."""
    from ksim_tpu.engine.core import _pack_tree_to_device

    cacheable = _plan_const_parts(plan)
    c_leaves, c_def = jax.tree_util.tree_flatten(cacheable)
    t_leaves, t_def = jax.tree_util.tree_flatten(transient)
    plan.dev_layout = ("pack",)
    reuse = plan.dev_reuse if plan.dev_reuse_layout == ("pack",) else None
    dev_c, miss_idx = _reuse_scan(reuse, c_leaves)
    packed = _pack_tree_to_device([c_leaves[i] for i in miss_idx] + t_leaves)
    for pos, i in enumerate(miss_idx):
        dev_c[i] = packed[pos]
    plan.dev_hits = len(c_leaves) - len(miss_idx)
    plan.dev_misses = len(miss_idx)
    plan.dev_map_out = (
        {id(a): (a, d) for a, d in zip(c_leaves, dev_c)}
        if plan.dev_collect
        else None
    )
    const_dev = _const_dev_dict(jax.tree_util.tree_unflatten(c_def, dev_c))
    transient_dev = jax.tree_util.tree_unflatten(t_def, packed[len(miss_idx):])
    return const_dev, transient_dev


#: Lazily built (1, tp) node meshes for env-requested sharded dispatch,
#: memoized per width (mesh construction touches jax.devices()).
_TP_MESH_LOCK = threading.Lock()
_TP_MESHES: dict = {}  # guarded-by: _TP_MESH_LOCK


def _tp_mesh(tp: int):
    """The ``make_mesh(tp, dp=1)`` node mesh for ``KSIM_REPLAY_TP``
    dispatches.  Built on the watchdogged worker only (``jax.devices``
    initializes the backend — a wedged tunnel becomes a watchdog
    timeout, never a main-thread hang); a host with fewer devices than
    the requested width raises DeviceUnavailableError, which feeds the
    ordinary device-error ladder and breaker instead of crashing the
    run — dead-device containment is identical to tp=1."""
    from ksim_tpu.engine import sharding

    with _TP_MESH_LOCK:
        mesh = _TP_MESHES.get(tp)
        if mesh is None:
            n = len(jax.devices())
            if n < tp:
                raise DeviceUnavailableError(
                    f"KSIM_REPLAY_TP={tp} but only {n} device(s) present"
                )
            mesh = sharding.make_mesh(tp, dp=1)
            _TP_MESHES[tp] = mesh
        return mesh


#: Carried cluster-state keys whose LEADING axis is the node axis [N] /
#: [N, R] — sharded over tp.  Everything else in state0 (the pod-axis
#: queue state and the pass counter) replicates: every chip needs the
#: whole pod table to score its node shard, and the pod rows are tiny
#: next to the node tensors (docs/scaling.md memory budgets).
_NODE_STATE_KEYS = frozenset(
    {"valid", "requested", "nonzero_requested", "pod_count",
     "spread", "ip_cnt", "ip_eat", "ip_vw"}
)


def _plan_shard_specs(plan: "_SegmentPlan", transient, mesh):
    """NamedSharding spec trees mirroring ``_plan_const_parts(plan)``
    and the ``(ev, state0)`` transient tree, structure-identical so the
    flattened leaves zip with the data leaves:

    - node statics and node-leading aux tables ("node" in the AXES map,
      state/encoding.py) lay their leading axis over tp;
    - the per-step rank tensors (``rank``/``name_rank``, [K, N]) shard
      axis 1 — their leading axis is the step;
    - pod rows, event index lists, scalars and everything else
      replicate (the pod axis must stay whole: the sequential-commit
      scan's queue order is the parity contract).

    The aux specs iterate the dict pairs manually: ``_aux_host``'s axes
    tree carries ``None`` at leaf positions, which jax's tree_map would
    read as an empty subtree and raise on."""
    from ksim_tpu.engine import sharding

    def node_lead(a):
        return sharding.node_leading_sharding(mesh, np.ndim(a))

    def repl(a):
        return sharding.replicated_sharding(mesh, np.ndim(a))

    node_spec = {k: node_lead(v) for k, v in plan.const["node"].items()}
    pods_spec = {k: repl(v) for k, v in plan.const["pods"].items()}
    extra_spec = {
        k: repl(plan.const[k])
        for k in ("resolv", "empty_start_rank")
        if k in plan.const
    }
    from ksim_tpu.engine.core import _aux_host

    aux_host, aux_axes = _aux_host(plan.aux)
    aux_spec: dict = {}
    for k, v in aux_host.items():
        ax = aux_axes[k]
        if isinstance(v, dict):
            aux_spec[k] = {
                name: node_lead(arr)
                if ax.get(name) == "node" and np.ndim(arr)
                else repl(arr)
                for name, arr in v.items()
            }
        else:
            aux_spec[k] = jax.tree_util.tree_map(repl, v)
    ev, state0 = transient
    ev_spec = {
        k: sharding.node_axis_sharding(mesh, np.ndim(v), 1)
        if k in ("rank", "name_rank")
        else repl(v)
        for k, v in ev.items()
    }
    state_spec = {
        k: node_lead(v) if k in _NODE_STATE_KEYS else repl(v)
        for k, v in state0.items()
    }
    return (node_spec, pods_spec, extra_spec, aux_spec), (ev_spec, state_spec)


def _fleet_shard_specs(plan: "_SegmentPlan", transient, mesh):
    """Spec trees for a FLEET dispatch on a (dp, tp) mesh (round 19):
    constants and event streams take the solo tp specs — on a 2-D mesh
    a ``P(TP, ...)`` spec replicates over dp automatically, so every
    lane's row of chips reads the same node-sharded tables — while the
    lane-STACKED carry (``transient[1]``, leading axis S) lays lanes
    over dp and, for the ``_NODE_STATE_KEYS`` tensors, the node axis
    (axis 1) over tp.  Structure-identical to the transient tree so the
    flattened leaves zip, like ``_plan_shard_specs``."""
    from ksim_tpu.engine import sharding

    ev, st_s = transient
    c_spec, (ev_spec, _solo_state_spec) = _plan_shard_specs(
        plan, (ev, plan.state0), mesh
    )
    state_spec = {
        k: sharding.lane_node_sharding(mesh, np.ndim(v))
        if k in _NODE_STATE_KEYS
        else sharding.lane_sharding(mesh, np.ndim(v))
        for k, v in st_s.items()
    }
    return c_spec, (ev_spec, state_spec)


def _shard_plan_buffers(plan: "_SegmentPlan", transient, mesh, *, specs=None):
    """The mesh mirror of ``_pack_plan_buffers``: the same id-keyed
    constant-buffer reuse protocol, but every transferred leaf goes up
    COMMITTED to its NamedSharding (one batched ``jax.device_put`` over
    the miss + transient leaves — jit then respects the input layouts
    without in_shardings and GSPMD propagates them through the scan).
    Reuse hits return buffers already laid out for THIS mesh: the
    layout token (``("mesh", dp, tp)``) rides with the reuse map and a
    mismatch misses wholesale (a mesh change re-shards everything)
    while an unchanged-universe redispatch re-shards only changed host
    arrays.  ``specs`` overrides the solo spec trees — the fleet passes
    ``_fleet_shard_specs`` so its lane-stacked carry lays lanes over dp
    and node axes over tp.

    Returns ``(const_dev, transient_dev)`` exactly like the packed
    path."""
    c_spec, t_spec = (
        specs if specs is not None else _plan_shard_specs(plan, transient, mesh)
    )
    cacheable = _plan_const_parts(plan)
    c_leaves, c_def = jax.tree_util.tree_flatten(cacheable)
    cs_leaves = jax.tree_util.tree_leaves(c_spec)
    t_leaves, t_def = jax.tree_util.tree_flatten(transient)
    ts_leaves = jax.tree_util.tree_leaves(t_spec)

    # Mirror _pack_tree_to_device's host canonicalization EXACTLY, so a
    # sharded dispatch sees the same avals as a packed one and shares
    # its compiled shape rung: np.ascontiguousarray promotes 0-d leaves
    # to (1,) (pass_count, scalar aux), and with x64 off 64-bit leaves
    # downcast by value.  A () -vs- (1,) skew here is not cosmetic — it
    # compiles a DIFFERENT program whose broadcasting silently corrupts
    # the scan (selected slots past N were observed under tp=4).
    x64 = bool(jax.config.jax_enable_x64)

    def _canon(a):
        if isinstance(a, np.ndarray):
            a = np.ascontiguousarray(a)
            if not x64 and a.dtype.itemsize == 8 and a.dtype.kind in "iuf":
                a = a.astype(np.dtype(f"{a.dtype.kind}4"))
        return a
    plan.dev_layout = ("mesh",) + tuple(int(d) for d in mesh.devices.shape)
    reuse = plan.dev_reuse if plan.dev_reuse_layout == plan.dev_layout else None
    dev_c, miss_idx = _reuse_scan(reuse, c_leaves)
    put = jax.device_put(
        [_canon(c_leaves[i]) for i in miss_idx] + [_canon(a) for a in t_leaves],
        [cs_leaves[i] for i in miss_idx] + ts_leaves,
    )
    for pos, i in enumerate(miss_idx):
        dev_c[i] = put[pos]
    plan.dev_hits = len(c_leaves) - len(miss_idx)
    plan.dev_misses = len(miss_idx)
    plan.dev_map_out = (
        {id(a): (a, d) for a, d in zip(c_leaves, dev_c)}
        if plan.dev_collect
        else None
    )
    const_dev = _const_dev_dict(jax.tree_util.tree_unflatten(c_def, dev_c))
    transient_dev = jax.tree_util.tree_unflatten(t_def, put[len(miss_idx):])
    return const_dev, transient_dev


def _fleet_exec(plan: "_SegmentPlan", lanes_state0, mesh=None):
    """One vmapped dispatch advancing S independent trajectories by the
    plan's K steps (engine/fleet.py's group dispatch; runs on the fleet
    watchdog worker thread).

    ``lanes_state0`` is one carried-state tree per lane, all
    shape-identical to the plan's own: the scan carry stacks along a
    new leading lane axis while the universe constants AND the per-step
    event streams transfer once and broadcast across lanes
    (``_fleet_segment_fn`` closes over them — see its docstring for why
    broadcasting ``ev`` is load-bearing under vmap).  With ``mesh`` (a
    ``(dp, tp)`` fleet mesh), every leaf goes up COMMITTED to its
    NamedSharding via the sharded packer: lanes lay over ``dp``, node
    tensors over ``tp`` (round 19 — ``_fleet_shard_specs``), and the
    id-keyed device-buffer reuse map applies exactly as on the solo
    path (layout-token gated), so steady-state segments re-transfer
    only the event streams and the carry.

    Returns ``(pulled_state, pulled)`` exactly as a solo dispatch would,
    with a leading lane axis on every leaf; the caller decodes each
    lane's slice through ``ReplayDriver._decode_outputs``.  Module
    function, side-effect-free on every driver (packing evidence rides
    on the plan, applied by the fleet on the main thread)."""
    from ksim_tpu.engine.core import _pull_tree_to_host

    FAULTS.check("replay.dispatch")
    st_s = jax.tree_util.tree_map(lambda *xs: np.stack(xs), *lanes_state0)
    if mesh is not None:
        const_dev, (ev_dev, state_dev) = _shard_plan_buffers(
            plan,
            (plan.ev, st_s),
            mesh,
            specs=_fleet_shard_specs(plan, (plan.ev, st_s), mesh),
        )
    else:
        const_dev, (ev_dev, state_dev) = _pack_plan_buffers(plan, (plan.ev, st_s))
    # Mesh cohorts take the non-donating twin (_DONATE_ARGNUMS note:
    # donated multi-device carries race on virtual CPU devices).
    fleet_fn = _fleet_segment_fn if mesh is None else _fleet_segment_fn_nodonate
    final_state, outs = COMPILE_CACHE.run(
        _compile_cache_key("fleet", plan, (const_dev, ev_dev, state_dev), mesh=mesh),
        lambda: fleet_fn(
            plan.statics, plan.prog, const_dev, ev_dev, state_dev
        ),
        owner=TRACE.scope_tags().get("job"),
    )
    return _pull_tree_to_host(
        (
            {
                k: final_state[k]
                for k in ("alive", "bound", "attempts", "retry_at", "pass_count")
            },
            outs,
        )
    )


@dataclass
class _SegmentPlan:
    statics: _SegmentStatics
    prog: Any
    const: dict
    aux: dict
    ev: dict
    state0: dict
    universe_keys: list[str]
    universe_row_of: dict[str, int]
    node_names: list[str]
    n_steps: int  # REAL steps (the compiled K may be tail-padded longer)
    pred_featurizes: list[bool]
    initial_pass_count: int
    # Per-step live-node decode views (preemption / full-record only).
    step_live_slots: list = field(default_factory=list)
    step_live_names: list = field(default_factory=list)
    step_node_event: list = field(default_factory=list)
    # Lower-cache seed (ReplayDriver._advance_cache filters it to the
    # committed segment's survivors) + the store epoch the lowering read.
    lower_epoch: int = -1
    sort_keys: list = field(default_factory=list)
    clean_pods: list = field(default_factory=list)
    priority_of: Any = None
    prio_gen: int = 0
    sched_names: Any = None  # profile set the lowering screened against
    # Device-resident constant-buffer reuse: ``dev_reuse`` is consumed by
    # _run (id(host array) -> (host ref, device array) from the previous
    # dispatch); ``dev_map_out``/hits/misses are produced by _run and
    # adopted by the driver on the MAIN thread after a healthy join
    # (_run itself stays side-effect-free on the driver).
    dev_reuse: dict = field(default_factory=dict)
    dev_collect: bool = False  # build dev_map_out (driver cache enabled)
    dev_map_out: "dict | None" = None
    dev_hits: int = 0
    dev_misses: int = 0
    # Round 19: device-buffer LAYOUT tokens — ``dev_reuse_layout`` is
    # the token the attached reuse map's buffers were committed under
    # (("pack",) for the single-device packed transfer, ("mesh", dp, tp)
    # for a sharded one); ``dev_layout`` is the token this dispatch's
    # executor actually used (adopted by note_dispatch_healthy).  The
    # executors compare tokens at USE-SITE and silently miss on a
    # mismatch: prepare_segment cannot know whether the plan will be
    # dispatched solo or on the fleet's (dp, tp) mesh, and reusing a
    # buffer laid out for a different device set corrupts the program.
    dev_reuse_layout: Any = None
    dev_layout: Any = None
    # Round 17: the EXPLICIT service shard_mesh this plan was lowered
    # for (None for env-knob sharding — _device_exec builds that mesh
    # lazily on the worker — and for tp=1 plans).
    mesh: Any = None


class _Unsupported(ReplayFallback):
    """Lowering found an op/object outside the tensor vocabulary — the
    replay-local spelling of errors.ReplayFallback (str(e) is the
    histogram reason, as before)."""

    def __init__(self, reason: str) -> None:
        super().__init__(reason)
