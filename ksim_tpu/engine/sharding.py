"""Device-mesh sharding of the scheduling engine.

The pod-by-node evaluation has two natural parallel axes (SURVEY.md
section 2.4): the pod batch (data parallel, "dp") and the node axis
(tensor parallel, "tp") — the reference has neither (its loop is
sequential Go, simulator/scheduler/plugin/wrappedplugin.go:523-548).

We annotate input shardings with jax.sharding.NamedSharding and let
GSPMD insert the collectives: node-axis reductions (any/argmax over
sharded N) lower to psum/all-gather over ICI.  No hand-written
collectives — the idiomatic JAX approach (scaling-book recipe).
"""

from __future__ import annotations

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ksim_tpu.plugins.base import NodeStateView

DP, TP = "dp", "tp"


def make_mesh(n_devices: int | None = None, *, dp: int | None = None) -> Mesh:
    """(dp, tp) mesh over the first n devices; tp gets the larger factor
    since the node axis dominates memory."""
    devices = jax.devices()
    n = n_devices or len(devices)
    devices = devices[:n]
    if dp is None:
        dp = 2 if n % 2 == 0 and n > 2 else 1
    tp = n // dp
    if dp * tp != n:
        raise ValueError(f"cannot factor {n} devices into dp={dp} x tp={tp}")
    return Mesh(np.asarray(devices).reshape(dp, tp), (DP, TP))


def node_state_shardings(mesh: Mesh) -> NodeStateView:
    """Shard every node-axis array over TP; replicate over DP."""
    s1 = NamedSharding(mesh, P(TP))
    s2 = NamedSharding(mesh, P(TP, None))
    return NodeStateView(
        allocatable=s2,
        allowed_pods=s1,
        valid=s1,
        unschedulable=s1,
        requested=s2,
        nonzero_requested=s2,
        pod_count=s1,
    )


def shard_pod_batch(pods, mesh: Mesh):
    """Shard every pod-batch leaf over DP (leading axis)."""
    def put(a):
        spec = P(DP, *([None] * (a.ndim - 1)))
        return jax.device_put(a, NamedSharding(mesh, spec))

    return jax.tree_util.tree_map(put, pods)


def shard_node_state(state: NodeStateView, mesh: Mesh) -> NodeStateView:
    shardings = node_state_shardings(mesh)
    return NodeStateView(
        *(jax.device_put(a, s) for a, s in zip(state, shardings))
    )


def fleet_mesh(dp: int, tp: int = 1) -> Mesh:
    """The fleet replay mesh (``KSIM_FLEET_DP``): the stacked trajectory
    (lane) axis lays over ``dp`` devices.  With ``tp == 1`` (the round-12
    fleet) each lane's segment scan runs whole on one device and GSPMD
    only splits the lane axis; with ``tp > 1`` (the round-19 2-D fleet)
    each lane's ``[N]``/``[N, R]`` node tensors additionally shard over
    ``tp`` chips — ``dp * tp`` devices total, lanes on mesh rows, node
    shards on mesh columns.  Raises if the host has too few devices."""
    devices = jax.devices()
    if len(devices) < dp * tp:
        raise ValueError(
            f"fleet mesh dp={dp} x tp={tp} needs {dp * tp} device(s) "
            f"but only {len(devices)} present"
        )
    return Mesh(np.asarray(devices[: dp * tp]).reshape(dp, tp), (DP, TP))


def shard_lane_axis(tree, mesh: Mesh):
    """Lay every leaf's LEADING (lane) axis over the mesh's dp axis;
    later axes stay unsharded (a lane's cluster state lives whole on its
    device — the fleet's dp parallelism is across trajectories, not
    inside one)."""

    def put(a):
        spec = P(DP, *([None] * (a.ndim - 1))) if a.ndim else P()
        return jax.device_put(a, NamedSharding(mesh, spec))

    return jax.tree_util.tree_map(put, tree)


def replicate_tree(tree, mesh: Mesh):
    """Replicate every leaf across the whole mesh (the fleet's shared
    universe constants: every lane reads the same tables)."""

    def put(a):
        return jax.device_put(a, NamedSharding(mesh, P(*([None] * a.ndim))))

    return jax.tree_util.tree_map(put, tree)


def lane_sharding(mesh: Mesh, ndim: int) -> NamedSharding:
    """Sharding for a lane-stacked fleet leaf whose trailing axes stay
    whole per lane (pod-axis queue state, scalars): leading (lane) axis
    over ``dp``, the rest replicated."""
    if ndim == 0:
        return NamedSharding(mesh, P())
    return NamedSharding(mesh, P(DP, *([None] * (ndim - 1))))


def lane_node_sharding(mesh: Mesh, ndim: int) -> NamedSharding:
    """Sharding for a lane-stacked ``[S, N, ...]`` node tensor on a 2-D
    fleet mesh: lanes over ``dp``, the node axis (axis 1) over ``tp`` —
    the round-19 composition of the fleet lane split with the round-17
    node split."""
    if ndim < 2:
        return lane_sharding(mesh, ndim)
    return NamedSharding(mesh, P(DP, TP, *([None] * (ndim - 2))))


def node_leading_sharding(mesh: Mesh, ndim: int) -> NamedSharding:
    """Sharding laying a tensor's LEADING axis over ``tp`` (the node
    axis of the device replay's ``[N]``/``[N, R]`` state); later axes
    replicated.  Scalars replicate."""
    if ndim == 0:
        return NamedSharding(mesh, P())
    return NamedSharding(mesh, P(TP, *([None] * (ndim - 1))))


def node_axis_sharding(mesh: Mesh, ndim: int, axis: int) -> NamedSharding:
    """Sharding laying one interior axis over ``tp`` (the replay's
    per-step ``[K, N]`` event rank tables shard axis 1, not 0)."""
    spec = [None] * ndim
    spec[axis] = TP
    return NamedSharding(mesh, P(*spec))


def replicated_sharding(mesh: Mesh, ndim: int) -> NamedSharding:
    """Fully replicated sharding (pod-axis and scalar replay state:
    every chip needs the whole pod table to score its node shard)."""
    return NamedSharding(mesh, P(*([None] * ndim)))


def shard_aux(aux: dict, axes: dict, mesh: Mesh) -> dict:
    """Shard encoding arrays by their declared leading-axis kind
    ("node" -> TP, "pod" -> DP, None -> replicated) — see the AXES
    classvars in state/encoding.py."""

    def put(a, kind):
        name = {"node": TP, "pod": DP}.get(kind)
        if name is None or a.ndim == 0:
            spec = P(*([None] * a.ndim))
        else:
            spec = P(name, *([None] * (a.ndim - 1)))
        return jax.device_put(a, NamedSharding(mesh, spec))

    return jax.tree_util.tree_map(put, aux, axes)
