"""The batched scheduling cycle.

Replaces the reference's per-(pod, node, plugin) hot loop (SURVEY.md
section 3.3; reference simulator/scheduler/plugin/wrappedplugin.go:420-548)
with two compiled programs:

- ``evaluate_batch`` — all pods x all nodes x all plugins against a FIXED
  snapshot: filter reason-bit matrices, raw score matrices, final
  (normalized x weight) score matrices, in one vmap'ed pass.  This is the
  "batch evaluating" product capability and the throughput benchmark.
- ``schedule`` — the sequential-commit loop: ``lax.scan`` over the pod
  queue carrying node state (requested/pod-count tensors), so each pod
  sees earlier pods' placements exactly like the upstream scheduler's
  Reserve-phase cache commit (SURVEY.md section 7 hard part 2).

Selection follows upstream selectHost (max summed final score) except ties
are broken by lowest node index instead of randomly (upstream
schedule_one.go selectHost picks uniformly among the max scorers; a
deterministic choice keeps replays reproducible).  Unschedulable pods
(no feasible node) get selected index -1.

Every pod x node result the reference records is preserved (the recorded
results ARE the product — SURVEY.md hard part 7); ``record`` modes bound
result-tensor memory for the 10k x 5k configs.

Compiled-program reuse: the jitted programs live on ``_Program``, a small
static object keyed by (record mode, plugin static signatures).  Engines
built for re-featurized snapshots share programs whenever the signatures
and array shapes match — the analogue of NOT restarting the reference's
scheduler container when nothing about the profile changed
(scheduler.go:58-111).  The jit cache pins only the ``_Program`` (plugins
hold vocab-sized statics, never snapshot tensors), so dropping an Engine
frees its device arrays.
"""

from __future__ import annotations

import dataclasses
import hashlib
import os
from collections import OrderedDict
from dataclasses import dataclass
from functools import partial
from typing import Any, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from ksim_tpu.engine.kernelreg import device_kernel
from ksim_tpu.plugins.base import (
    FilterOutput,
    NodeStateView,
    PodBatch,
    PodView,
)
from ksim_tpu.state.featurizer import FeaturizedSnapshot


@dataclass(frozen=True)
class PluginExtender:
    """Before/After hooks around one plugin's extension points — the
    TPU-native form of the reference's PluginExtender surface
    (simulator/scheduler/plugin/wrappedplugin.go:47-171): hooks are
    jax-traceable callables over the BATCHED tensors, compiled into the
    engine programs rather than wrapped around per-(pod,node) calls.

    Device-side hooks (jax-traceable, compiled into the engine):

    - before_filter(state, pod, aux) -> (state, pod): rewrite inputs;
    - after_filter(state, pod, aux, out: FilterOutput) -> FilterOutput;
    - before_score(state, pod, aux) -> (state, pod);
    - after_score(state, pod, aux, scores) -> scores (pre-normalize);
    - before_normalize(state, pod, aux, raw, ok) -> raw;
      after_normalize(state, pod, aux, normalized, ok) -> normalized
      (the NormalizeScore extender pair, wrappedplugin.go:388-418;
      weight applies after).

    The reference's PreFilter/PreScore extenders have no separate hooks
    here by design: those upstream points precompute per-cycle state
    that this architecture folds into the featurizer and the fused
    filter/score kernels, so before_filter/before_score are their
    extension seams (they see the same batched inputs the kernels do).

    Host-side hooks (plain Python over pod JSON, run by the scheduler
    service around the corresponding host extension points — the
    reference's Permit/PreBind/Bind/PostBind/PostFilter extender
    interfaces, wrappedplugin.go:47-171).  ``before_*`` returning a
    non-None string is a non-success status: the original plugin hook is
    skipped and the message becomes the point's result (for post_bind the
    original is skipped silently, matching wrappedplugin.go:728-738).
    ``after_*`` receives the point's outcome and may replace it:

    - before_post_filter(pod) -> str | None;
      after_post_filter(pod, nominated, msg) -> (nominated, msg);
    - before_reserve(pod, node) -> str | None;
      after_reserve(pod, node, msg) -> str | None;
    - before_unreserve(pod, node) -> str | None (non-None skips the
      original unreserve, like BeforePostBind);
      after_unreserve(pod, node) -> None;
    - before_permit(pod, node) -> str | None;
      after_permit(pod, node, result) -> result (a PermitResult);
    - before_pre_bind(pod, node) -> str | None;
      after_pre_bind(pod, node, msg) -> str | None;
    - before_bind(pod, node) -> str | None;
      after_bind(pod, node, outcome) -> outcome;
    - before_post_bind(pod, node) -> str | None;
      after_post_bind(pod, node) -> None.

    Implement ``static_sig()`` for cross-instance program reuse; without
    it the engine keys the jit cache by extender identity (always safe).
    """

    before_filter: Any = None
    after_filter: Any = None
    before_score: Any = None
    after_score: Any = None
    before_normalize: Any = None
    after_normalize: Any = None
    before_post_filter: Any = None
    after_post_filter: Any = None
    before_reserve: Any = None
    after_reserve: Any = None
    before_unreserve: Any = None
    after_unreserve: Any = None
    before_permit: Any = None
    after_permit: Any = None
    before_pre_bind: Any = None
    after_pre_bind: Any = None
    before_bind: Any = None
    after_bind: Any = None
    before_post_bind: Any = None
    after_post_bind: Any = None

    def static_sig(self) -> tuple | None:
        return None


@dataclass(frozen=True)
class ScoredPlugin:
    """A plugin enabled in a profile, with its score weight."""

    plugin: Any
    weight: int = 1
    filter_enabled: bool = True
    score_enabled: bool = True
    extender: PluginExtender | None = None
    # Host-side recording hints (not part of the traced computation): is
    # the plugin active at the Reserve/Permit/PreBind/PostFilter/Bind/
    # PostBind points (profiles can disable single extension points; the
    # annotation renderer consults these for reserve-result/prebind-
    # result, and the scheduler service consults them before calling a
    # plugin's host-side ``permit(pod, node_name)`` / ``post_filter`` /
    # ``pre_bind`` / ``bind`` / ``post_bind`` hooks).
    reserve_enabled: bool = True
    prebind_enabled: bool = True
    permit_enabled: bool = True
    postfilter_enabled: bool = True
    bind_enabled: bool = True
    postbind_enabled: bool = True


@dataclass
class EngineResult:
    """Host-side results for a pod batch.

    Shapes: P pods (padded), N nodes (padded); slices [:num_pods,:num_nodes]
    are valid.  ``selected`` is -1 for unschedulable (or padding) pods.
    """

    plugin_names: list[str]
    filter_plugin_names: list[str]
    reason_bits: np.ndarray | None  # i32 [P, F, N], 0 == passed
    scores: np.ndarray | None  # i32 [P, S, N] raw plugin scores
    final_scores: np.ndarray | None  # i32 [P, S, N] normalized x weight
    total: np.ndarray | None  # i32 [P, N] summed final scores
    feasible: np.ndarray  # bool [P]
    selected: np.ndarray  # i32 [P]
    # percentageOfNodesToScore emulation (Engine(sampling_k=...)):
    # per-pod visited-node mask (upstream iterates nodes from a rotating
    # start index and stops after finding K feasible — only visited
    # nodes appear in recorded results) and the rotating index's value
    # after this batch (feeds the next pass).
    visited: np.ndarray | None = None  # bool [P, N]
    sampling_next_start: int | None = None


# Content-addressed host->device transfer cache.  Engines rebuilt for an
# UNCHANGED snapshot skip re-transferring byte-identical arrays (1.8x on
# a rebuild+schedule cycle).  Keyed on content + dtype/shape + the x64
# flag (jnp.asarray downcasts int64/float64 when x64 is off).  No jitted
# path donates its inputs, so cached buffers stay alive.
#
# DISABLED by default (limit 0): on this chip's remote-tunnel runtime
# (axon), keeping even a few hundred extra live device buffers slows
# every subsequent execution/transfer 3-4x in churn replay (measured
# 36s -> 90-133s for a 6k-event run at any limit >= 256), far outweighing
# the transfer savings.  Set KSIM_H2D_CACHE to a positive entry count to
# enable on hardware without that pathology.
_H2D: "OrderedDict[tuple, jnp.ndarray]" = OrderedDict()
_H2D_LIMIT = int(os.environ.get("KSIM_H2D_CACHE", "0"))

# lax.scan unroll factor for the sequential-commit loop (see
# _Program._schedule_fn).
SCAN_UNROLL = int(os.environ.get("KSIM_SCAN_UNROLL", "4"))


def _to_device(a) -> jnp.ndarray:
    if not _H2D_LIMIT or not isinstance(a, np.ndarray) or a.nbytes > (64 << 20):
        return jnp.asarray(a)
    digest = hashlib.blake2b(a.tobytes(), digest_size=16).digest()
    key = (a.dtype.str, a.shape, digest, bool(jax.config.jax_enable_x64))
    hit = _H2D.get(key)
    if hit is not None:
        _H2D.move_to_end(key)
        return hit
    v = jnp.asarray(a)
    _H2D[key] = v
    if len(_H2D) > _H2D_LIMIT:
        _H2D.popitem(last=False)
    return v


def _aux_host(aux: dict) -> tuple[dict, dict]:
    """FeaturizedSnapshot.aux -> (pytree of HOST arrays, leading-axis map).

    Dataclasses become dicts of their ndarray fields; host-only fields
    stay behind.  The axis map mirrors the array tree with "node"/"pod"/
    None leading-axis kinds (from each dataclass's AXES classvar) for
    sharding."""
    out = {}
    axes = {}
    for k, v in (aux or {}).items():
        if dataclasses.is_dataclass(v):
            declared = getattr(v, "AXES", {})
            out[k] = {
                f.name: getattr(v, f.name)
                for f in dataclasses.fields(v)
                if isinstance(getattr(v, f.name), np.ndarray)
            }
            axes[k] = {name: declared.get(name) for name in out[k]}
        else:
            out[k] = v
            axes[k] = jax.tree_util.tree_map(lambda _: None, v)
    return out, axes


# One jitted unpack program per packing signature (grouped dtypes/shapes
# are bucketed upstream, so churn replay sees only a handful).
_UNPACK_CACHE: dict[tuple, Any] = {}

# One jitted byte-pack program per output signature (the device->host
# mirror of _pack_tree_to_device).
_OUTPACK_CACHE: dict[tuple, Any] = {}


def _multi_device(a) -> bool:
    """True for a jax.Array laid out over more than one device.  The
    jitted byte-pack below must never see one: GSPMD partitions the
    bitcast+concatenate and inserts a cross-replica reduction, so every
    output byte comes back SUMMED over the mesh replicas (observed on
    the 8-device CPU mesh: selected values 4x on a dp=2 x tp=4 layout,
    -1 bytes wrapping to 0xFC).  Sharded results gather per-leaf."""
    s = getattr(a, "sharding", None)
    try:
        return s is not None and len(s.device_set) > 1
    except Exception:
        return False


def _owned_host(a) -> np.ndarray:
    """Pull ONE device array to host as an OWNED numpy array.

    ``np.asarray`` on a jax.Array is ZERO-COPY on the CPU backend where
    the layout allows it — single-device outputs view a ``memoryview``
    of the result buffer, and a replicated multi-device output views
    shard 0's buffer directly (sharded leaves gather, which copies).  A
    retained view is a time bomb once the producing buffer's memory can
    be recycled: with the segment carry DONATED (round 19) XLA reuses
    execution memory aggressively, and the fleet tp*dp replay was
    observed to decode garbage through exactly such views — committed
    counts diverged nondeterministically at 1200-event scale, and any
    host-sync instrumentation made the race vanish.  One explicit copy
    per leaf pins the decode to host-owned memory; host numpy inputs
    pass through untouched."""
    if isinstance(a, np.ndarray):
        return a
    h = np.asarray(a)
    if isinstance(h, np.ndarray) and not h.flags["OWNDATA"]:
        h = np.array(h)
    return h


def _pull_tree_to_host(tree):
    """Transfer a pytree of device arrays to host numpy with ONE
    device->host transfer: a jitted program bitcasts every leaf to bytes
    and concatenates them into a single uint8 buffer; the host splits and
    re-views.  The record="full" product path pulls 5 result tensors per
    pod chunk — on the remote-tunnel runtime each pull is a blocking
    round-trip, so collapsing them is the mirror of the input packing.
    Every returned leaf is host-OWNED (``_owned_host``): zero-copy
    views of device buffers must never escape the pull boundary."""
    leaves, treedef = jax.tree_util.tree_flatten(tree)
    if len(leaves) < 2 or not all(
        hasattr(a, "dtype") and np.dtype(a.dtype) != object for a in leaves
    ) or any(_multi_device(a) for a in leaves):
        # Mirror _pack_tree_to_device's non-array fallback.
        return jax.tree_util.tree_unflatten(
            treedef, [_owned_host(a) for a in leaves]
        )
    sig = tuple((np.dtype(a.dtype).str, a.shape) for a in leaves)
    fn = _OUTPACK_CACHE.get(sig)
    if fn is None:

        def pack(*xs):
            chunks = []
            for x in xs:
                if x.dtype == jnp.bool_:
                    x = x.astype(jnp.uint8)
                if x.dtype != jnp.uint8:
                    # Every chunk must be uint8: concatenate would PROMOTE
                    # a stray int8 chunk and silently double the buffer.
                    x = jax.lax.bitcast_convert_type(x, jnp.uint8)
                chunks.append(x.reshape(-1))
            return jnp.concatenate(chunks) if len(chunks) > 1 else chunks[0]

        fn = jax.jit(pack)
        _OUTPACK_CACHE[sig] = fn
    # _owned_host: the split below RE-VIEWS buf, so buf itself must own
    # its memory or every decoded leaf aliases the device result buffer.
    buf = _owned_host(fn(*leaves))
    out = []
    off = 0
    for dtype_str, shape in sig:
        dt = np.dtype(dtype_str)
        n = int(np.prod(shape, dtype=np.int64))
        nbytes = n * dt.itemsize
        seg = buf[off : off + nbytes]
        if dt == np.bool_:
            arr = seg.astype(np.bool_)
        else:
            arr = seg.view(dt)
        out.append(arr.reshape(shape))
        off += nbytes
    return jax.tree_util.tree_unflatten(treedef, out)


def _pack_tree_to_device(tree):
    """Move a pytree of host arrays to device with ONE byte-buffer
    transfer plus one jitted unpack dispatch, instead of one device_put
    per leaf.

    The featurized snapshot is ~83 small arrays; on a remote-tunnel
    runtime every transfer costs milliseconds of round-trip latency, so
    per-leaf device_put dominated churn-replay profiles (~0.3s/pass),
    and even one-transfer-per-dtype left 4-6 round-trips per pass.  All
    ndarray leaves are viewed as bytes, concatenated into a single uint8
    buffer, transferred once, and sliced + bitcast back to their dtypes
    on device (little-endian on both host and TPU).  Non-ndarray leaves
    fall back to jnp.asarray."""
    leaves, treedef = jax.tree_util.tree_flatten(tree)
    pack_idx = [
        i
        for i, a in enumerate(leaves)
        if isinstance(a, np.ndarray) and a.dtype != object
    ]
    if len(pack_idx) < 4:
        return jax.tree_util.tree_unflatten(
            treedef, [_to_device(a) for a in leaves]
        )
    x64 = bool(jax.config.jax_enable_x64)
    chunks = []
    sig = []
    for i in pack_idx:
        a = np.ascontiguousarray(leaves[i])
        if not x64 and a.dtype.itemsize == 8 and a.dtype.kind in "iuf":
            # Mirror jnp.asarray's canonicalization: with x64 off, 64-bit
            # leaves downcast by VALUE (the f32 fast mode relies on it).
            a = a.astype(np.dtype(f"{a.dtype.kind}4"))
        chunks.append(a.view(np.uint8).ravel())
        sig.append((a.dtype.str, a.shape))
    buf = jnp.asarray(np.concatenate(chunks))
    sig = tuple(sig)
    fn = _UNPACK_CACHE.get(sig)
    if fn is None:

        def unpack(b):
            outs = []
            off = 0
            for dtype_str, shape in sig:
                dt = np.dtype(dtype_str)
                nbytes = int(np.prod(shape, dtype=np.int64)) * dt.itemsize
                seg = jax.lax.dynamic_slice_in_dim(b, off, nbytes)
                if dt == np.bool_:
                    arr = seg.astype(jnp.bool_)
                elif dt.itemsize == 1:
                    arr = jax.lax.bitcast_convert_type(seg, dt)
                else:
                    arr = jax.lax.bitcast_convert_type(
                        seg.reshape(-1, dt.itemsize), dt
                    )
                outs.append(arr.reshape(shape))
                off += nbytes
            return outs

        fn = jax.jit(unpack)
        _UNPACK_CACHE[sig] = fn
    unpacked = fn(buf)
    out = list(leaves)
    for pos, i in enumerate(pack_idx):
        out[i] = unpacked[pos]
    for i, a in enumerate(out):
        if i not in pack_idx and not isinstance(a, jnp.ndarray):
            out[i] = _to_device(a)
    return jax.tree_util.tree_unflatten(treedef, out)


def _final_from_raw(
    plugin: Any,
    raw: jnp.ndarray,
    ok: jnp.ndarray,
    weight: int,
    state=None,
    pod=None,
    aux=None,
    kw=None,
    ext=None,
) -> jnp.ndarray:
    """normalize (if the plugin defines it) then apply weight — the
    reference's applyWeightOnScore (resultstore/store.go:504-507).
    Plugins declaring ``normalize_needs_ctx = True`` get the evaluation
    context (PodTopologySpread's normalize depends on the pod).  The
    extender's before/after_normalize hooks wrap the plugin's normalize
    (the reference's NormalizeScorePluginExtender,
    wrappedplugin.go:388-418): before may rewrite the raw scores, after
    the normalized ones — both jax-traceable, pre-weight."""
    if ext is not None and ext.before_normalize is not None:
        raw = ext.before_normalize(state, pod, aux, raw, ok)
    if hasattr(plugin, "normalize"):
        if getattr(plugin, "normalize_needs_ctx", False):
            raw = plugin.normalize(raw, ok, state=state, pod=pod, aux=aux, **(kw or {}))
        else:
            raw = plugin.normalize(raw, ok)
    if ext is not None and ext.after_normalize is not None:
        raw = ext.after_normalize(state, pod, aux, raw, ok)
    return raw * weight


def _plugin_sig(plugin: Any) -> tuple:
    """Hashable jit-cache key component for one plugin: its declared
    static_sig, or object identity for plugins that don't implement one
    (no cross-instance program reuse, but always safe)."""
    try:
        sig = plugin.static_sig()
    except (AttributeError, NotImplementedError):
        sig = None
    if sig is None:
        return ("@id", id(plugin))
    return tuple(sig)


class _Program:
    """The static half of an Engine: plugin set + record mode, hashable by
    signature.  jax.jit keys its cache on this object (static argnum 0),
    so equal-signature programs share compiled code while the cache entry
    retains only vocab-sized plugin statics — never snapshot tensors."""

    def __init__(
        self,
        plugins: tuple[ScoredPlugin, ...],
        record: str,
        assume_skip: frozenset[str] = frozenset(),
        sampling_k: int | None = None,
    ) -> None:
        self.plugins = plugins
        self.record = record
        # Plugin names whose per-pod Skip condition is STATICALLY true for
        # every pod this program will see (host-side classification,
        # Engine._light_mask): their filter contributes no failures and
        # their score is zero, so the heavy bodies are never traced —
        # unlike lax.cond, which vmap lowers to select (both branches
        # execute for every pod in the batch program).
        self.assume_skip = assume_skip
        # percentageOfNodesToScore emulation: find-K-feasible sampling in
        # the sequential scan (upstream numFeasibleNodesToFind,
        # schedule_one.go).  Static so lax.top_k can use it.
        self.sampling_k = sampling_k
        self._sig = (
            record,
            assume_skip,
            sampling_k,
            tuple(
                (
                    _plugin_sig(sp.plugin),
                    sp.weight,
                    sp.filter_enabled,
                    sp.score_enabled,
                    _plugin_sig(sp.extender) if sp.extender is not None else None,
                )
                for sp in plugins
            ),
        )

    def __hash__(self) -> int:
        return hash(self._sig)

    def __eq__(self, other) -> bool:
        return isinstance(other, _Program) and self._sig == other._sig

    # -- shared per-pod evaluation -----------------------------------------

    def _eval_one(self, state: NodeStateView, pod: PodView, aux: dict, carries: dict):
        """One pod vs all nodes through every plugin.

        ``carries`` maps plugin name -> that plugin's scan-carried state
        (e.g. PodTopologySpread's per-selector per-node match counts);
        plugins without carry state never see the dict.
        """
        filter_ok, reason_bits = self._eval_filters(state, pod, aux, carries)
        raw_scores, final_scores, total = self._eval_scores(
            state, pod, aux, carries, filter_ok
        )
        return filter_ok, reason_bits, raw_scores, final_scores, total

    def _eval_filters(self, state: NodeStateView, pod: PodView, aux: dict, carries: dict):
        n = state.valid.shape[0]
        reason_bits = []
        filter_ok = state.valid
        for sp in self.plugins:
            if not sp.filter_enabled:
                continue
            if sp.plugin.name in self.assume_skip:
                # Statically-skipped plugin: its Skip branch yields code 0
                # for every pod in this program's batch (the caller's
                # classification guarantees the cond predicate is false).
                reason_bits.append(jnp.zeros(n, jnp.int32))
                continue
            kw = {"carry": carries[sp.plugin.name]} if sp.plugin.name in carries else {}
            ext = sp.extender
            f_state, f_pod = state, pod
            if ext is not None and ext.before_filter is not None:
                f_state, f_pod = ext.before_filter(f_state, f_pod, aux)
            out: FilterOutput = sp.plugin.filter(f_state, f_pod, aux, **kw)
            if ext is not None and ext.after_filter is not None:
                out = ext.after_filter(f_state, f_pod, aux, out)
            reason_bits.append(out.reason_bits)
            filter_ok = filter_ok & out.ok
        return filter_ok, reason_bits

    def _eval_scores(
        self, state: NodeStateView, pod: PodView, aux: dict, carries: dict, filter_ok
    ):
        """``filter_ok`` is the mask scoring/normalizing runs over — the
        full feasible set normally, the SAMPLED feasible set under
        percentageOfNodesToScore emulation (upstream normalizes over the
        nodes it actually scored)."""
        n = state.valid.shape[0]
        raw_scores = []
        final_scores = []
        total = jnp.zeros(n, dtype=jnp.int32)
        for sp in self.plugins:
            if not sp.score_enabled:
                continue
            if sp.plugin.name in self.assume_skip:
                # Skip branch: raw 0 -> normalize of all-zeros -> final 0.
                raw_scores.append(jnp.zeros(n, jnp.int32))
                final_scores.append(jnp.zeros(n, jnp.int32))
                continue
            kw = {"carry": carries[sp.plugin.name]} if sp.plugin.name in carries else {}
            ext = sp.extender
            s_state, s_pod = state, pod
            if ext is not None and ext.before_score is not None:
                s_state, s_pod = ext.before_score(s_state, s_pod, aux)
            raw = sp.plugin.score(s_state, s_pod, aux, ok=filter_ok, **kw)
            if ext is not None and ext.after_score is not None:
                raw = ext.after_score(s_state, s_pod, aux, raw)
            final = _final_from_raw(
                sp.plugin, raw, filter_ok, sp.weight, s_state, s_pod, aux, kw,
                ext=ext,
            )
            raw_scores.append(raw)
            final_scores.append(final)
            total = total + final.astype(jnp.int32)
        return raw_scores, final_scores, total

    def init_carries(self, aux: dict) -> dict:
        return {
            sp.plugin.name: sp.plugin.carry_init(aux)
            for sp in self.plugins
            if hasattr(sp.plugin, "carry_init")
        }

    def _commit_carries(self, carries: dict, pod: PodView, best, aux: dict) -> dict:
        out = dict(carries)
        for sp in self.plugins:
            if sp.plugin.name in carries and hasattr(sp.plugin, "carry_commit"):
                out[sp.plugin.name] = sp.plugin.carry_commit(
                    carries[sp.plugin.name], aux, pod, best
                )
        return out

    def _select(self, filter_ok: jnp.ndarray, total: jnp.ndarray) -> jnp.ndarray:
        """selectHost: index of the max-scoring feasible node, -1 when
        none is feasible (feasibility is fully encoded in the sign)."""
        feasible = jnp.any(filter_ok)
        masked = jnp.where(filter_ok, total, jnp.iinfo(jnp.int32).min)
        best = jnp.argmax(masked).astype(jnp.int32)
        return jnp.where(feasible, best, -1)

    def _result_dtypes(self):
        """Smallest SAFE dtypes for the recorded result tensors, decided
        statically from plugin declarations — the device->host transfer
        of [P,F,N]/[P,S,N] tensors is the record="full" bottleneck on a
        bandwidth-limited link, and bytes scale with dtype width.

        - reason bits: each filter plugin declares ``reason_bit_width``
          (low bits it can set); missing declaration means int32.
        - final scores: each score plugin declares ``final_score_bound``
          (max post-normalize value); final = bound x weight per plugin.
          Raw scores stay int32 (data-dependent magnitudes)."""
        widths = [
            getattr(sp.plugin, "reason_bit_width", 31)
            for sp in self.plugins
            if sp.filter_enabled
        ]
        maxw = max(widths, default=0)
        bits_dtype = (
            jnp.int8 if maxw <= 7 else jnp.int16 if maxw <= 15 else jnp.int32
        )
        fmax = 0
        for sp in self.plugins:
            if not sp.score_enabled:
                continue
            bound = getattr(sp.plugin, "final_score_bound", None)
            if bound is None:
                fmax = None
                break
            fmax = max(fmax, bound * max(sp.weight, 1))
        final_dtype = (
            jnp.int16 if fmax is not None and fmax < 2**15 else jnp.int32
        )
        return bits_dtype, final_dtype

    def _pod_outputs(self, pv, best, bits, raw, final, total) -> dict:
        # No separate feasible output: selected >= 0 iff (valid & any node
        # passed), so _to_result derives it — one fewer device->host pull
        # per chunk (each costs ~150ms over a high-latency link).
        out = dict(selected=jnp.where(pv, best, -1))
        n = total.shape[0]
        bits_dtype, final_dtype = self._result_dtypes()
        if self.record in ("full", "final"):
            out["total"] = total
            out["final"] = (
                jnp.stack(final).astype(final_dtype)
                if final
                else jnp.zeros((0, n), final_dtype)
            )
        if self.record == "full":
            out["bits"] = (
                jnp.stack(bits).astype(bits_dtype)
                if bits
                else jnp.zeros((0, n), bits_dtype)
            )
            out["raw"] = jnp.stack(raw) if raw else jnp.zeros((0, n), jnp.int32)
        return out

    # -- compiled entry points ----------------------------------------------

    @device_kernel(static=("self",))
    def _batch_eval(self, state, pods: PodBatch, aux: dict, carries: dict):
        """Traceable body shared by the chunked and fused batch entries."""

        def per_pod(pb: PodBatch):
            pod = PodView(
                requests=pb.requests,
                nonzero_requests=pb.nonzero_requests,
                tolerates_unschedulable=pb.tolerates_unschedulable,
                has_requests=pb.has_requests,
                index=pb.index,
            )
            ok, bits, raw, final, total = self._eval_one(state, pod, aux, carries)
            best = self._select(ok, total)
            return self._pod_outputs(pb.valid, best, bits, raw, final, total)

        return jax.vmap(per_pod)(pods)

    @partial(jax.jit, static_argnums=0)
    @device_kernel(static=("self",))
    def _batch_fn(self, state, pods: PodBatch, aux: dict, carries: dict):
        return self._batch_eval(state, pods, aux, carries)

    @partial(jax.jit, static_argnums=(0, 5))
    @device_kernel(static=("self", "block"))
    def _batch_fused_fn(
        self, state, pods: PodBatch, aux: dict, carries: dict, block: int
    ):
        """The whole pod axis in ONE device program: lax.map over
        block-sized vmap segments.  Two wins over the host chunk loop:
        every [block, N] plugin intermediate stays on-chip (the chunked
        path round-trips [chunk, N] tensors through HBM between plugin
        stages — it is bandwidth-bound, which is why the sequential scan
        was beating it), and the per-chunk dispatch latency (~100-700ms
        each over the axon tunnel) collapses into a single launch.
        Measured at 10k x 5k exact selection on a v5e: 2092ms chunked ->
        976ms fused (23.9 -> 51.2M pairs/s), vs 1957ms for the scan."""
        P = pods.valid.shape[0]
        blocks = jax.tree_util.tree_map(
            lambda x: x.reshape((P // block, block) + x.shape[1:]), pods
        )
        out = jax.lax.map(
            lambda pb: self._batch_eval(state, pb, aux, carries), blocks
        )
        return jax.tree_util.tree_map(
            lambda x: x.reshape((P,) + x.shape[2:]), out
        )

    def _sample_visited(self, filter_ok, start, n_real):
        """Upstream's find-K-feasible iteration as tensor ops
        (schedule_one.go findNodesThatPassFilters + numFeasibleNodesToFind,
        idealized as the sequential visit order — upstream's parallel
        workers make the exact visited set racy; the deterministic
        sequential semantics is the reproducible contract).

        Nodes are visited in index order from the rotating ``start``;
        iteration stops once ``sampling_k`` feasible nodes are found.
        Returns (visited [N] bool, sample = feasible&visited,
        new_start)."""
        k = self.sampling_k
        n = filter_ok.shape[0]
        big = jnp.iinfo(jnp.int32).max
        i = jnp.arange(n, dtype=jnp.int32)
        nr = jnp.maximum(n_real, 1)
        in_real = i < n_real
        p = (i - start) % nr  # visit position of node i
        p = jnp.where(in_real, p, big)
        feas_pos = jnp.where(filter_ok & in_real, p, big)
        # K-th smallest feasible visit position (big when < K feasible).
        kth = -jax.lax.top_k(-feas_pos, k)[0][k - 1]
        n_feas = jnp.sum((filter_ok & in_real).astype(jnp.int32))
        threshold = jnp.where(n_feas >= k, kth, n_real - 1)
        visited = in_real & (p <= threshold)
        sample = filter_ok & visited
        # nextStartNodeIndex advances by the nodes processed this cycle
        # (feasible found + filtered-out visited = every visited node).
        new_start = (start + threshold + 1) % nr
        return visited, sample, new_start

    @partial(jax.jit, static_argnums=0)
    @device_kernel(static=("self",))
    def _schedule_sampled_fn(
        self, state, pods: PodBatch, aux: dict, carries: dict, start, n_real
    ):
        """The sequential-commit scan with percentageOfNodesToScore
        emulation: filter everywhere (the mask is needed to FIND the
        K feasible), then score/normalize/select over the sampled
        feasible set only, with the rotating start index carried across
        pods exactly like upstream's sched.nextStartNodeIndex."""

        def body(carry, pb: PodBatch):
            node_state, plugin_carries, start = carry
            pod = PodView(
                requests=pb.requests,
                nonzero_requests=pb.nonzero_requests,
                tolerates_unschedulable=pb.tolerates_unschedulable,
                has_requests=pb.has_requests,
                index=pb.index,
            )
            ok, bits = self._eval_filters(node_state, pod, aux, plugin_carries)
            visited, sample, new_start = self._sample_visited(ok, start, n_real)
            # Padding pods never ran a cycle upstream: no rotation.
            new_start = jnp.where(pb.valid, new_start, start)
            raw, final, total = self._eval_scores(
                node_state, pod, aux, plugin_carries, sample
            )
            best = jnp.where(pb.valid, self._select(sample, total), -1)
            node_state = node_state.commit(best, pb.requests, pb.nonzero_requests)
            plugin_carries = self._commit_carries(plugin_carries, pod, best, aux)
            out = self._pod_outputs(pb.valid, best, bits, raw, final, total)
            if self.record == "full":
                out["visited"] = visited
            return (node_state, plugin_carries, new_start), out

        (final_state, final_carries, final_start), out = jax.lax.scan(
            body, (state, carries, start), pods, unroll=SCAN_UNROLL
        )
        return final_state, final_carries, final_start, out

    @partial(jax.jit, static_argnums=0)
    @device_kernel(static=("self",))
    def _schedule_fn(self, state, pods: PodBatch, aux: dict, carries: dict):
        def body(carry, pb: PodBatch):
            node_state, plugin_carries = carry
            pod = PodView(
                requests=pb.requests,
                nonzero_requests=pb.nonzero_requests,
                tolerates_unschedulable=pb.tolerates_unschedulable,
                has_requests=pb.has_requests,
                index=pb.index,
            )
            ok, bits, raw, final, total = self._eval_one(node_state, pod, aux, plugin_carries)
            best = jnp.where(pb.valid, self._select(ok, total), -1)
            node_state = node_state.commit(best, pb.requests, pb.nonzero_requests)
            plugin_carries = self._commit_carries(plugin_carries, pod, best, aux)
            return (node_state, plugin_carries), self._pod_outputs(
                pb.valid, best, bits, raw, final, total
            )

        # Unrolling amortizes per-iteration loop overhead: each step's
        # compute is tiny ([N]-wide elementwise + small matmuls), so the
        # while-loop bookkeeping is a measurable fraction of scan time
        # (415ms -> 348ms at padded 8192x1024, unroll=4).  Compile time
        # grows with the factor; the persistent compile cache absorbs it.
        (final_state, final_carries), out = jax.lax.scan(
            body, (state, carries), pods, unroll=SCAN_UNROLL
        )
        return final_state, final_carries, out


class Engine:
    """Compiled filter/score programs for one profile + featurized snapshot.

    Building an Engine binds a snapshot's device arrays to a ``_Program``
    (the static plugin set + record mode); the heavy compilation caches on
    the program signature and array shapes, so rebuilding an Engine for a
    fresh same-shaped snapshot costs only the host->device transfer.
    """

    def __init__(
        self,
        feats: FeaturizedSnapshot,
        plugins: Sequence[ScoredPlugin],
        *,
        record: str = "full",  # full | final | selection
        sampling_k: int | None = None,
    ) -> None:
        """``sampling_k`` enables percentageOfNodesToScore emulation on
        the ``schedule`` path: each pod's cycle visits nodes from a
        rotating start index and stops after finding K feasible — only
        visited nodes are scored/recorded, exactly upstream's adaptive
        sampling (scan-only; batch evaluation has no visit order)."""
        if record not in ("full", "final", "selection"):
            raise ValueError(f"unknown record mode {record!r}")
        # Validate against the REAL node count, not the padded axis: a K
        # between count and padding would "find" padding rows that never
        # pass filters, silently scoring fewer nodes than asked.
        if sampling_k is not None and not (
            0 < sampling_k <= int(feats.nodes.count)
        ):
            raise ValueError(
                f"sampling_k {sampling_k} out of range: must be in "
                f"[1, {int(feats.nodes.count)}] (real node count; the "
                f"padded axis is {int(feats.nodes.valid.shape[0])})"
            )
        self._feats = feats
        self._prog = _Program(tuple(plugins), record, sampling_k=sampling_k)
        n = feats.nodes
        p = feats.pods
        node_host = dict(
            allocatable=n.allocatable,
            allowed_pods=n.allowed_pods,
            valid=n.valid,
            unschedulable=n.unschedulable,
            requested=n.requested,
            nonzero_requested=n.nonzero_requested,
            pod_count=n.pod_count,
        )
        pod_host = dict(
            requests=p.requests,
            nonzero_requests=p.nonzero_requests,
            valid=p.valid,
            tolerates_unschedulable=p.tolerates_unschedulable,
            has_requests=p.has_requests,
            index=p.index,
        )
        aux_host, self._aux_axes = _aux_host(feats.aux)
        node_dev, pod_dev, self._aux = _pack_tree_to_device(
            (node_host, pod_host, aux_host)
        )
        self._node_state = NodeStateView(**node_dev)
        self._pods = PodBatch(**pod_dev)
        self._sharded = False

    @property
    def _plugins(self) -> tuple[ScoredPlugin, ...]:
        return self._prog.plugins

    @property
    def _record(self) -> str:
        return self._prog.record

    def shard(self, mesh) -> "Engine":
        """Lay the engine's arrays out over a device mesh: node axis over
        "tp", pod batch over "dp" (see engine/sharding.py).  GSPMD inserts
        the node-axis collectives (any/argmax reductions) over ICI.

        Note: the sequential ``schedule`` path wants replicated pod arrays
        (lax.scan consumes one row per step); ``evaluate_batch`` benefits
        from the dp sharding.  Shard for the path you will run.
        """
        from ksim_tpu.engine import sharding as shlib

        self._node_state = shlib.shard_node_state(self._node_state, mesh)
        self._pods = shlib.shard_pod_batch(self._pods, mesh)
        self._aux = shlib.shard_aux(self._aux, self._aux_axes, mesh)
        self._sharded = True
        return self

    def batch_step(self, state, pods: PodBatch, aux: dict, carries: dict):
        """Pure jittable batch-evaluation step (un-jitted public form)."""
        return _Program._batch_fn.__wrapped__(self._prog, state, pods, aux, carries)

    @property
    def example_args(self):
        return (self._node_state, self._pods, self._aux, self._prog.init_carries(self._aux))

    # Plugins whose per-pod Skip condition the Engine can evaluate
    # host-side from the featurized snapshot (see _light_mask) — the
    # candidates for a statically-skipping batch program.
    _PARTITION_PLUGINS = ("PodTopologySpread", "InterPodAffinity")

    def _partition_assume(self) -> frozenset[str]:
        """Names from _PARTITION_PLUGINS present in this profile without
        extenders (a Before/After hook may observe the heavy branch, so
        hooked plugins are never statically skipped)."""
        return frozenset(
            sp.plugin.name
            for sp in self._plugins
            if sp.plugin.name in self._PARTITION_PLUGINS and sp.extender is None
        )

    def _light_mask(self, assume: frozenset[str]) -> np.ndarray | None:
        """bool [P]: pods for which every plugin in ``assume`` provably
        takes its Skip branch — the HOST-side mirror of the kernels' cond
        predicates (conservative: any doubt classifies heavy).

        - PodTopologySpread: no valid constraints at all (implies the
          filter's ``any(active)`` and score/normalize's ``has_score_con``
          are both false).
        - InterPodAffinity: no required (anti-)affinity terms, no
          preferred weights, and no existing pod's term selector matches
          (filter pred: sum(raff)+sum(ranti)+sum(qm) > 0; score pred:
          any(pref_w) | any(qm > 0)).
        """
        aux = self._feats.aux or {}
        P = int(self._pods.valid.shape[0])
        light = np.ones(P, dtype=bool)
        try:
            if "PodTopologySpread" in assume:
                spread = aux["spread"]
                light &= ~np.asarray(spread.con_valid).any(axis=1)
            if "InterPodAffinity" in assume:
                ipa = aux["interpod"]
                terms = (
                    np.asarray(ipa.req_aff).astype(np.int64)
                    + np.asarray(ipa.req_anti)
                    + np.asarray(ipa.pod_term_match)
                )
                light &= terms.sum(axis=1) == 0
                light &= (np.asarray(ipa.pref_w) == 0).all(axis=1)
        except (KeyError, AttributeError):
            return None  # unfamiliar aux layout: never partition
        return light

    def _gather_pods(self, idx: np.ndarray, chunk: int) -> tuple[PodBatch, np.ndarray]:
        """Pod rows for ``idx`` padded to ``chunk`` (pad rows read pod 0
        but are marked invalid, so their outputs decode to selected=-1
        and are dropped at reassembly).  Returns (PodBatch, index array
        with -1 at pad positions)."""
        pad = chunk - len(idx)
        padded = np.concatenate([idx, np.zeros(pad, dtype=idx.dtype)]) if pad else idx
        idx_dev = jnp.asarray(padded)
        pods_c = jax.tree_util.tree_map(lambda x: x[idx_dev], self._pods)
        if pad:
            keep = jnp.asarray(np.arange(chunk) < len(idx))
            pods_c = pods_c._replace(valid=pods_c.valid & keep)
        out_idx = padded.astype(np.int64)
        if pad:
            out_idx = out_idx.copy()
            out_idx[len(idx):] = -1
        return pods_c, out_idx

    def evaluate_batch_chunks(self, *, chunk: int | None = None, partition: bool = False):
        """Yield per-chunk device results — the streaming form of
        ``evaluate_batch``.  Each ``device_out`` is the device-resident
        result pytree for one pod chunk; callers decode or transfer it
        before the next iteration if they want bounded device memory
        (record="full" at 16k x 8k is ~9GB of result tensors — far more
        than it costs to recompute, so nothing is retained).

        ``partition=False`` (default): yields ``(start, device_out)`` for
        contiguous chunks, exactly the historical contract.

        ``partition=True``: pods are CLASSED host-side by whether the
        heavy constraint plugins' Skip conditions provably hold
        (``_light_mask``), and light pods run through a program variant
        that never traces those plugin bodies — under vmap, lax.cond
        lowers to select, so the default batch program pays the heavy
        branches for EVERY pod while the sequential scan skips them;
        this restores the skip for the batch path.  Yields
        ``(indices, device_out)`` where ``indices`` is an int64 array of
        original pod positions per output row (-1 = padding row of a
        ragged class tail).  Results are bit-identical to the
        unpartitioned evaluation, in a different row order."""
        if self._prog.sampling_k is not None:
            raise ValueError(
                "percentageOfNodesToScore emulation is scan-only "
                "(batch evaluation has no sequential visit order)"
            )
        P = int(self._pods.valid.shape[0])
        if chunk is None:
            chunk = min(P, self._default_batch_chunk())
        carries = self._prog.init_carries(self._aux)
        # dp-sharded pod arrays would turn the class gathers into
        # cross-device collectives — partitioning is a single-chip
        # optimization (the mesh path keeps the contiguous contract).
        partition = partition and not self._sharded
        assume = self._partition_assume() if partition else frozenset()
        light = self._light_mask(assume) if assume else None
        if partition and light is not None and light.any() and not light.all():
            light_prog = _Program(self._plugins, self._record, assume_skip=assume)
            for mask, prog in ((~light, self._prog), (light, light_prog)):
                idx_all = np.nonzero(mask)[0]
                for s in range(0, len(idx_all), chunk):
                    pods_c, out_idx = self._gather_pods(
                        idx_all[s : s + chunk], chunk
                    )
                    yield out_idx, prog._batch_fn(
                        self._node_state, pods_c, self._aux, carries
                    )
            return
        for s in range(0, P, chunk):
            pods_c = jax.tree_util.tree_map(
                lambda x: x[s : s + chunk], self._pods
            )
            yield s, self._prog._batch_fn(self._node_state, pods_c, self._aux, carries)

    def evaluate_batch_fused(self, *, block: int = 256) -> EngineResult:
        """One-dispatch batch evaluation for bounded-size record modes
        (``selection``/``final``): see _Program._batch_fused_fn for why
        this beats both the chunked batch AND the sequential scan on
        TPU.  record="full" must stream — its result tensors exceed
        device memory at large shapes — so it stays on
        ``evaluate_batch``; dp-sharded engines likewise (the reshape
        would fight the pod-axis sharding)."""
        if self._record == "full":
            raise ValueError(
                "record='full' results must stream: use evaluate_batch"
            )
        if self._prog.sampling_k is not None:
            raise ValueError(
                "percentageOfNodesToScore emulation is scan-only "
                "(batch evaluation has no sequential visit order)"
            )
        if self._sharded:
            return self.evaluate_batch()
        P = int(self._pods.valid.shape[0])
        block = max(1, min(block, P))
        while P % block:
            block //= 2
        out = self._prog._batch_fused_fn(
            self._node_state,
            self._pods,
            self._aux,
            self._prog.init_carries(self._aux),
            block,
        )
        return self._to_result(_pull_tree_to_host(out))

    def evaluate_batch(
        self, *, chunk: int | None = None, partition: bool = False
    ) -> EngineResult:
        """All pods x nodes against the fixed snapshot (no state commit).

        Pod-chunked like ``schedule`` so the recorded result tensors
        ([P, plugins, N] in record="full") never exceed one chunk's worth
        of device memory; chunks stream to host and concatenate.
        ``partition=True`` runs the classed-pod fast path (see
        ``evaluate_batch_chunks``) and reassembles original pod order."""
        chunks = [
            (key, _pull_tree_to_host(out))
            for key, out in self.evaluate_batch_chunks(chunk=chunk, partition=partition)
        ]
        if chunks and isinstance(chunks[0][0], np.ndarray):
            P = int(self._pods.valid.shape[0])
            merged = jax.tree_util.tree_map(
                lambda x: np.zeros((P,) + x.shape[1:], x.dtype), chunks[0][1]
            )
            for idx, out in chunks:
                keep = idx >= 0
                rows = idx[keep]

                def scatter(dst, src):
                    dst[rows] = src[keep]
                    return dst

                merged = jax.tree_util.tree_map(
                    lambda d, s: scatter(d, np.asarray(s)), merged, out
                )
            return self._to_result(merged)
        merged = jax.tree_util.tree_map(
            lambda *xs: np.concatenate(xs, axis=0), *(out for _s, out in chunks)
        )
        return self._to_result(merged)

    # Default pod-axis chunk for the sequential scan.  One device program
    # per chunk bounds both the compiled scan length and the live result
    # buffers (full [P,*,N] stacks at 16k x 8k exceed a v5e chip); the
    # carries thread through unchanged so chunking is semantically
    # invisible.
    SCHEDULE_CHUNK = 2048
    # Batch-evaluation chunk on CPU: the vmapped batch program
    # materializes [chunk, plugins, N] intermediates, and on CPU the pass
    # is memory-bandwidth-bound — chunks small enough to stay cache-warm
    # measure fastest (256: 15.9s vs 2048: 28.3s at 5000x1000 full-record;
    # docs/scaling.md "batch-vs-scan platform asymmetry").  TPU keeps the
    # large chunk: HBM bandwidth prefers big tiles and per-dispatch
    # overhead is the scarce resource over the remote tunnel.
    BATCH_CHUNK_CPU = 256

    def _default_batch_chunk(self) -> int:
        if jax.default_backend() == "cpu":
            return self.BATCH_CHUNK_CPU
        return self.SCHEDULE_CHUNK

    def _default_schedule_chunk(self) -> int:
        if self._record == "selection" and jax.default_backend() != "cpu":
            # One dispatch for the whole pod axis: at 2048-pod chunks the
            # TPU scan pays six dispatch round-trips at the 10kx5k shape
            # (measured 2051ms -> 1405ms, 24.4 -> 35.6M pairs/s exact,
            # going single-dispatch).  Selection-mode outputs are
            # [P]-sized, so the per-chunk result-buffer bound that forces
            # chunking in the recording modes does not apply.  CPU keeps
            # the smaller chunk — its cache-resident working set wins
            # there (1056ms vs 1235ms at 5000x1000).
            return 1 << 30
        return self.SCHEDULE_CHUNK

    def schedule(
        self,
        *,
        chunk: int | None = None,
        pull_state: bool = True,
        sampling_start: int = 0,
    ) -> tuple[EngineResult, NodeStateView | None]:
        """Greedy sequential scheduling of the pod queue with capacity
        commit; pod order is queue order (upstream pops by priority —
        callers sort the queue before featurizing).

        The scan runs in ``chunk``-sized pod segments (host loop, one
        compiled program reused across segments); results are concatenated
        host-side.  ``pull_state=False`` skips the device->host transfer
        of the final node state (callers that only consume the per-pod
        results — the scheduler service — save ~7 blocking pulls per
        pass, which dominate wall-clock over a high-latency link).

        ``sampling_start`` (sampling_k engines only) is the rotating
        node index carried over from the previous pass (upstream's
        sched.nextStartNodeIndex); the result's ``sampling_next_start``
        feeds the next pass."""
        P = int(self._pods.valid.shape[0])
        if chunk is None:
            chunk = min(P, self._default_schedule_chunk())
        state, carries = self._node_state, self._prog.init_carries(self._aux)
        outs = []
        sampled = self._prog.sampling_k is not None
        start = jnp.asarray(sampling_start, dtype=jnp.int32)
        n_real = jnp.asarray(int(self._feats.nodes.count), dtype=jnp.int32)
        for s in range(0, P, chunk):
            pods_c = jax.tree_util.tree_map(
                lambda x: x[s : s + chunk], self._pods
            )
            if sampled:
                state, carries, start, out = self._prog._schedule_sampled_fn(
                    state, pods_c, self._aux, carries, start, n_real
                )
            else:
                state, carries, out = self._prog._schedule_fn(
                    state, pods_c, self._aux, carries
                )
            outs.append(_pull_tree_to_host(out))
        merged = jax.tree_util.tree_map(
            lambda *xs: np.concatenate(xs, axis=0), *outs
        )
        final_state = _pull_tree_to_host(state) if pull_state else None
        result = self._to_result(merged)
        if sampled:
            result.sampling_next_start = int(start)
        return result, final_state

    # -- decode -------------------------------------------------------------

    def _to_result(self, out: dict) -> EngineResult:
        filter_names = [
            sp.plugin.name for sp in self._plugins if sp.filter_enabled
        ]
        score_names = [sp.plugin.name for sp in self._plugins if sp.score_enabled]
        get = lambda k: np.asarray(out[k]) if k in out else None
        selected = np.asarray(out["selected"])
        return EngineResult(
            plugin_names=score_names,
            filter_plugin_names=filter_names,
            reason_bits=get("bits"),
            scores=get("raw"),
            final_scores=get("final"),
            total=get("total"),
            feasible=selected >= 0,
            selected=selected,
            visited=get("visited"),
        )
