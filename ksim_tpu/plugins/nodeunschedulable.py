"""NodeUnschedulable filter plugin.

Upstream kube-scheduler v1.30 ``plugins/nodeunschedulable/node_unschedulable.go``:
a node with ``spec.unschedulable`` fails the filter unless the pod tolerates
the ``node.kubernetes.io/unschedulable:NoSchedule`` taint.  The toleration
check is host-side boolean per pod (featurizer), so the kernel is a pure
mask op.  Reason message matches upstream ``ErrReasonUnschedulable``.
"""

from __future__ import annotations

import jax.numpy as jnp

from ksim_tpu.plugins.base import FilterOutput, NodeStateView, PodView
from ksim_tpu.state.resources import UNSCHEDULABLE_TAINT  # noqa: F401 (re-export)

NAME = "NodeUnschedulable"
ERR_REASON_UNSCHEDULABLE = "node(s) were unschedulable"


class NodeUnschedulable:
    # Static reason-bit width: result tensors downcast when every
    # filter plugin's bits fit a narrower dtype (engine/core.py).
    reason_bit_width = 2
    name = NAME

    def filter(self, state: NodeStateView, pod: PodView, aux=None) -> FilterOutput:
        blocked = state.unschedulable & ~pod.tolerates_unschedulable
        return FilterOutput(
            ok=~blocked, reason_bits=jnp.where(blocked, 1, 0).astype(jnp.int32)
        )

    def decode_reasons(self, bits: int) -> list[str]:
        return [ERR_REASON_UNSCHEDULABLE] if bits else []

    def static_sig(self) -> tuple:
        return (NAME,)

    def failure_unresolvable(self, bits: int) -> bool:
        # Upstream returns UnschedulableAndUnresolvable: removing pods
        # cannot un-cordon a node.
        return True
