"""Pure-Python parity oracle for every plugin.

Direct, slow, obviously-correct re-derivations of upstream kube-scheduler
v1.30 plugin code paths over Python ints (int64 semantics) and floats (IEEE
double, same as Go float64).  The batched JAX kernels are tested
golden-style against these (SURVEY.md section 4: "golden-file parity tests
... against a pure-Python reference implementation of each plugin").

The oracle operates on NodeInfo dicts built by ``build_node_infos`` —
the analogue of the upstream scheduler cache NodeInfo.

The oracle is also the parity source of truth for PREEMPTION's fit
re-checks: scheduler/preemption.py's host victim search runs this
module's filters directly (``_FitState.fits``), and the device-resident
victim search (engine/replay.py) re-checks fits through the compiled
kernels — exactness there rests on the kernel<->oracle parity tests
plus a lowering gate that the profile's filter set matches the fit
chain (preemption.ORACLE_FIT_FILTER_NAMES).  Changing any filter's
semantics here must change the kernel AND the hand-derived fixtures
under tests/fixtures/ together.
"""

from __future__ import annotations

import math
from typing import Any, Sequence

from ksim_tpu.state.resources import (
    CPU,
    EPHEMERAL_STORAGE,
    JSON,
    MEMORY,
    PODS,
    name_of,
    pod_is_scheduled,
    pod_node_name,
    pod_requests,
)

from ksim_tpu.plugins.base import MAX_NODE_SCORE
from ksim_tpu.state.resources import BASE_RESOURCES

NodeInfo = dict[str, Any]


def build_node_infos(nodes: Sequence[JSON], pods: Sequence[JSON]) -> list[NodeInfo]:
    """NodeInfo accumulation: bound, non-terminal pods charge their node."""
    from ksim_tpu.state.resources import node_allocatable

    infos: list[NodeInfo] = []
    by_name: dict[str, NodeInfo] = {}
    for n in nodes:
        alloc = node_allocatable(n)
        info: NodeInfo = {
            "node": n,
            "name": name_of(n),
            "allocatable": {r: v for r, v in alloc.items() if r != PODS},
            "allowed_pods": alloc.get(PODS, 0),
            "requested": {},
            "nonzero_requested": {},
            "pod_count": 0,
        }
        infos.append(info)
        by_name[info["name"]] = info
    for p in pods:
        if not pod_is_scheduled(p):
            continue
        if p.get("status", {}).get("phase") in ("Succeeded", "Failed"):
            continue
        info = by_name.get(pod_node_name(p))
        if info is None:
            continue
        for r, v in pod_requests(p).items():
            info["requested"][r] = info["requested"].get(r, 0) + v
        for r, v in pod_requests(p, non_zero=True).items():
            info["nonzero_requested"][r] = info["nonzero_requested"].get(r, 0) + v
        info["pod_count"] += 1
    return infos


def commit_pod(info: NodeInfo, pod: JSON) -> None:
    """Charge a newly scheduled pod to a NodeInfo (Reserve-phase commit)."""
    for r, v in pod_requests(pod).items():
        info["requested"][r] = info["requested"].get(r, 0) + v
    for r, v in pod_requests(pod, non_zero=True).items():
        info["nonzero_requested"][r] = info["nonzero_requested"].get(r, 0) + v
    info["pod_count"] += 1


# -- NodeUnschedulable ------------------------------------------------------


def node_unschedulable_filter(pod: JSON, info: NodeInfo) -> list[str]:
    """Upstream node_unschedulable.go Filter."""
    from ksim_tpu.state.resources import (
        node_unschedulable,
        pod_tolerations,
        tolerations_tolerate_taint,
    )
    from ksim_tpu.plugins.nodeunschedulable import UNSCHEDULABLE_TAINT

    if not node_unschedulable(info["node"]):
        return []
    if tolerations_tolerate_taint(pod_tolerations(pod), UNSCHEDULABLE_TAINT):
        return []
    return ["node(s) were unschedulable"]


# -- TaintToleration --------------------------------------------------------


def taint_toleration_filter(pod: JSON, info: NodeInfo) -> list[str]:
    """Upstream taint_toleration.go Filter (FindMatchingUntoleratedTaint
    over NoSchedule/NoExecute taints, node order)."""
    from ksim_tpu.state.resources import node_taints, pod_tolerations, untolerated_taint

    taint = untolerated_taint(node_taints(info["node"]), pod_tolerations(pod))
    if taint is None:
        return []
    return [
        f"node(s) had untolerated taint {{{taint.get('key', '')}: {taint.get('value', '')}}}"
    ]


def taint_toleration_score(pod: JSON, info: NodeInfo) -> int:
    """Upstream countIntolerableTaintsPreferNoSchedule: PreferNoSchedule
    taints not tolerated by the pod's ""/PreferNoSchedule tolerations."""
    from ksim_tpu.state.resources import (
        node_taints,
        pod_tolerations,
        tolerations_tolerate_taint,
    )

    tols = [
        t
        for t in pod_tolerations(pod)
        if (t.get("effect") or "") in ("", "PreferNoSchedule")
    ]
    count = 0
    for taint in node_taints(info["node"]):
        if taint.get("effect") != "PreferNoSchedule":
            continue
        if not tolerations_tolerate_taint(tols, taint):
            count += 1
    return count


# -- NodeAffinity ------------------------------------------------------------


def node_affinity_filter(
    pod: JSON, info: NodeInfo, added_affinity: JSON | None = None
) -> list[str]:
    """Upstream node_affinity.go Filter: the profile's enforced
    addedAffinity first (early return, errReasonEnforced), then
    nodeSelector AND required terms."""
    from ksim_tpu.state.selectors import match_node_selector_terms

    node = info["node"]
    labels = dict(node.get("metadata", {}).get("labels") or {})
    if added_affinity:
        added_req = added_affinity.get(
            "requiredDuringSchedulingIgnoredDuringExecution"
        )
        if added_req is not None and not match_node_selector_terms(
            added_req.get("nodeSelectorTerms") or [], labels, info["name"]
        ):
            return ["node(s) didn't match scheduler-enforced node affinity"]
    spec = pod.get("spec", {})
    ns = spec.get("nodeSelector")
    if ns:
        for k, v in ns.items():
            if labels.get(k) != v:
                return ["node(s) didn't match Pod's node affinity/selector"]
    aff = (spec.get("affinity") or {}).get("nodeAffinity") or {}
    required = aff.get("requiredDuringSchedulingIgnoredDuringExecution")
    if required is not None:
        if not match_node_selector_terms(
            required.get("nodeSelectorTerms") or [], labels, info["name"]
        ):
            return ["node(s) didn't match Pod's node affinity/selector"]
    return []


def node_affinity_score(
    pod: JSON, info: NodeInfo, added_affinity: JSON | None = None
) -> int:
    """Upstream node_affinity.go Score: sum of matching preferred weights
    (pod terms plus the profile's addedAffinity preferred terms)."""
    from ksim_tpu.state.selectors import match_node_selector_term

    node = info["node"]
    labels = dict(node.get("metadata", {}).get("labels") or {})
    aff = (pod.get("spec", {}).get("affinity") or {}).get("nodeAffinity") or {}
    score = 0
    pref = list(aff.get("preferredDuringSchedulingIgnoredDuringExecution") or [])
    if added_affinity:
        pref += list(
            added_affinity.get("preferredDuringSchedulingIgnoredDuringExecution")
            or []
        )
    for pt in pref:
        w = int(pt.get("weight", 0))
        if w == 0:
            continue
        if match_node_selector_term(pt.get("preference") or {}, labels, info["name"]):
            score += w
    return score


# -- PodTopologySpread -------------------------------------------------------


def _spread_constraints(pod: JSON, mode: str) -> list[JSON]:
    want = "DoNotSchedule" if mode == "filter" else "ScheduleAnyway"
    out = []
    for con in pod.get("spec", {}).get("topologySpreadConstraints") or []:
        if con.get("whenUnsatisfiable", "DoNotSchedule") == want:
            out.append(con)
    return out


def _spread_selector(con: JSON, pod: JSON) -> JSON:
    from ksim_tpu.state.encoding import _effective_selector

    return _effective_selector(con, pod)


def _spread_node_eligible(pod: JSON, info: NodeInfo, con: JSON) -> bool:
    """Per-constraint inclusion policies (NodeInclusionPolicy on,
    defaults Honor affinity / Ignore taints)."""
    from ksim_tpu.state.resources import node_taints, pod_tolerations, untolerated_taint

    if (con.get("nodeAffinityPolicy") or "Honor") == "Honor":
        if node_affinity_filter(pod, info):
            return False
    if (con.get("nodeTaintsPolicy") or "Ignore") == "Honor":
        if untolerated_taint(node_taints(info["node"]), pod_tolerations(pod)) is not None:
            return False
    return True


def _count_matching(info: NodeInfo, all_pods_by_node, ns: str, sel: JSON) -> int:
    from ksim_tpu.state.selectors import match_label_selector
    from ksim_tpu.state.resources import labels_of, namespace_of

    count = 0
    for p in all_pods_by_node.get(info["name"], []):
        if (namespace_of(p) or "default") != ns:
            continue
        if match_label_selector(sel, labels_of(p)):
            count += 1
    return count


def _node_has_keys(info: NodeInfo, cons: list[JSON]) -> bool:
    from ksim_tpu.state.resources import labels_of

    lbls = labels_of(info["node"])
    return all(c.get("topologyKey", "") in lbls for c in cons)


def topology_spread_filter_all(
    pod: JSON, infos: list[NodeInfo], all_pods_by_node: dict
) -> list[list[str]]:
    """Upstream filtering.go: per-node failure reasons (empty = pass)."""
    from ksim_tpu.state.resources import labels_of, namespace_of
    from ksim_tpu.state.selectors import match_label_selector

    cons = _spread_constraints(pod, "filter")
    if not cons:
        return [[] for _ in infos]
    ns = namespace_of(pod) or "default"
    out: list[list[str]] = []
    # Domain stats per constraint over eligible nodes with all filter keys.
    per_con: list[dict] = []
    for con in cons:
        sel = _spread_selector(con, pod)
        counts: dict[str, int] = {}
        for info in infos:
            if not _node_has_keys(info, cons):
                continue
            if not _spread_node_eligible(pod, info, con):
                continue
            v = labels_of(info["node"]).get(con.get("topologyKey", ""))
            counts[v] = counts.get(v, 0) + _count_matching(info, all_pods_by_node, ns, sel)
        min_match = min(counts.values()) if counts else 0
        min_domains = int(con.get("minDomains") or 0)
        if min_domains > 0 and len(counts) < min_domains:
            min_match = 0
        per_con.append(
            {
                "con": con,
                "sel": sel,
                "counts": counts,
                "min_match": min_match,
                "self": match_label_selector(sel, labels_of(pod)),
            }
        )
    for info in infos:
        reasons: list[str] = []
        lbls = labels_of(info["node"])
        for pc in per_con:
            tk = pc["con"].get("topologyKey", "")
            if tk not in lbls:
                reasons = [
                    "node(s) didn't match pod topology spread constraints (missing required label)"
                ]
                break
            match_num = pc["counts"].get(lbls[tk], 0)
            skew = match_num + (1 if pc["self"] else 0) - pc["min_match"]
            if skew > int(pc["con"].get("maxSkew", 1)):
                reasons = ["node(s) didn't match pod topology spread constraints"]
                break
        out.append(reasons)
    return out


def topology_spread_score_all(
    pod: JSON,
    infos: list[NodeInfo],
    all_pods_by_node: dict,
    feasible: list[bool],
) -> tuple[list[int], list[int]]:
    """Upstream scoring.go: (raw, normalized) per node.  ``feasible`` marks
    nodes that passed the whole framework filter (PreScore's
    filteredNodes)."""
    import math as _math

    from ksim_tpu.state.resources import labels_of, namespace_of
    from ksim_tpu.state.selectors import match_label_selector

    n = len(infos)
    cons = _spread_constraints(pod, "score")
    if not cons:
        # PreScore returns Skip: the plugin contributes nothing.
        return [0] * n, [0] * n
    ns = namespace_of(pod) or "default"
    ignored = [not _node_has_keys(info, cons) for info in infos]
    per_con = []
    for con in cons:
        sel = _spread_selector(con, pod)
        registered: set[str] = set()
        for i, info in enumerate(infos):
            if feasible[i] and not ignored[i]:
                v = labels_of(info["node"]).get(con.get("topologyKey", ""))
                if v is not None:
                    registered.add(v)
        counts: dict[str, int] = {v: 0 for v in registered}
        for info in infos:
            if not _spread_node_eligible(pod, info, con):
                continue
            v = labels_of(info["node"]).get(con.get("topologyKey", ""))
            if v in counts:
                counts[v] += _count_matching(info, all_pods_by_node, ns, sel)
        per_con.append(
            {
                "con": con,
                "counts": counts,
                "tp_weight": _math.log(len(registered) + 2),
            }
        )
    raw = []
    for i, info in enumerate(infos):
        if not feasible[i] or ignored[i]:
            raw.append(0)
            continue
        lbls = labels_of(info["node"])
        total = 0.0
        for pc in per_con:
            v = lbls.get(pc["con"].get("topologyKey", ""))
            if v in pc["counts"]:
                total += pc["counts"][v] * pc["tp_weight"] + (
                    int(pc["con"].get("maxSkew", 1)) - 1
                )
        raw.append(int(round(total)))
    scoreable = [raw[i] for i in range(n) if feasible[i] and not ignored[i]]
    mx = max(scoreable, default=0)
    mn = min(scoreable, default=0)
    norm = []
    for i in range(n):
        if ignored[i] or not feasible[i]:
            norm.append(0)
            continue
        if mx == 0:
            norm.append(MAX_NODE_SCORE)
        else:
            norm.append(MAX_NODE_SCORE * (mx + mn - raw[i]) // mx)
    return raw, norm


# -- InterPodAffinity --------------------------------------------------------


def _ipa_required(pod: JSON, kind: str) -> list[JSON]:
    aff = (pod.get("spec", {}).get("affinity") or {}).get(kind) or {}
    return list(aff.get("requiredDuringSchedulingIgnoredDuringExecution") or [])


def _ipa_preferred(pod: JSON, kind: str) -> list[JSON]:
    aff = (pod.get("spec", {}).get("affinity") or {}).get(kind) or {}
    return list(aff.get("preferredDuringSchedulingIgnoredDuringExecution") or [])


def _ipa_term_matches(term: JSON, owner: JSON, other: JSON, ns_labels: dict) -> bool:
    from ksim_tpu.state.interpod import context_matches, term_context
    from ksim_tpu.state.resources import namespace_of

    ctx = term_context(term, namespace_of(owner) or "default")
    return context_matches(ctx, other, ns_labels)


def _ipa_has_affinity(pod: JSON) -> bool:
    from ksim_tpu.state.interpod import has_any_affinity

    return has_any_affinity(pod)


def inter_pod_affinity_filter_all(
    pod: JSON,
    infos: list[NodeInfo],
    all_pods_by_node: dict,
    namespaces: Sequence[JSON] = (),
) -> list[list[str]]:
    """Upstream filtering.go: per-node failure reasons (empty = pass),
    first failing check only (Filter returns on first violation)."""
    from ksim_tpu.state.resources import labels_of

    ns_labels = {name_of(ns): dict(labels_of(ns)) for ns in namespaces}
    aff_terms = _ipa_required(pod, "podAffinity")
    anti_terms = _ipa_required(pod, "podAntiAffinity")

    # PreFilter count maps: topologyPair -> matched term count.
    affinity_counts: dict[tuple[str, str], int] = {}
    anti_counts: dict[tuple[str, str], int] = {}
    existing_anti_counts: dict[tuple[str, str], int] = {}
    for info in infos:
        node_lbls = labels_of(info["node"])
        for ep in all_pods_by_node.get(info["name"], []):
            for t in aff_terms:
                tk = t.get("topologyKey", "")
                if tk in node_lbls and _ipa_term_matches(t, pod, ep, ns_labels):
                    key = (tk, node_lbls[tk])
                    affinity_counts[key] = affinity_counts.get(key, 0) + 1
            for t in anti_terms:
                tk = t.get("topologyKey", "")
                if tk in node_lbls and _ipa_term_matches(t, pod, ep, ns_labels):
                    key = (tk, node_lbls[tk])
                    anti_counts[key] = anti_counts.get(key, 0) + 1
            for t in _ipa_required(ep, "podAntiAffinity"):
                tk = t.get("topologyKey", "")
                if tk in node_lbls and _ipa_term_matches(t, ep, pod, ns_labels):
                    key = (tk, node_lbls[tk])
                    existing_anti_counts[key] = existing_anti_counts.get(key, 0) + 1

    self_match = bool(aff_terms) and all(
        _ipa_term_matches(t, pod, pod, ns_labels) for t in aff_terms
    )

    out: list[list[str]] = []
    for info in infos:
        node_lbls = labels_of(info["node"])
        # (1) satisfyPodAffinity.
        pods_exist = True
        missing_key = False
        for t in aff_terms:
            tk = t.get("topologyKey", "")
            if tk in node_lbls:
                if affinity_counts.get((tk, node_lbls[tk]), 0) <= 0:
                    pods_exist = False
            else:
                missing_key = True
                break
        ok_aff = not missing_key and (
            pods_exist or (len(affinity_counts) == 0 and self_match)
        )
        if not ok_aff:
            out.append(["node(s) didn't match pod affinity rules"])
            continue
        # (2) satisfyPodAntiAffinity.
        viol = any(
            t.get("topologyKey", "") in node_lbls
            and anti_counts.get(
                (t.get("topologyKey", ""), node_lbls[t.get("topologyKey", "")]), 0
            )
            > 0
            for t in anti_terms
        )
        if viol:
            out.append(["node(s) didn't match pod anti-affinity rules"])
            continue
        # (3) satisfyExistingPodsAntiAffinity.
        viol = any(
            node_lbls.get(tk) == val and cnt > 0
            for (tk, val), cnt in existing_anti_counts.items()
        )
        if viol:
            out.append(["node(s) didn't satisfy existing pods' anti-affinity rules"])
            continue
        out.append([])
    return out


def inter_pod_affinity_score_all(
    pod: JSON,
    infos: list[NodeInfo],
    all_pods_by_node: dict,
    feasible: list[bool],
    namespaces: Sequence[JSON] = (),
    hard_weight: int = 1,
) -> tuple[list[int], list[int]]:
    """Upstream scoring.go: (raw, normalized) per node; non-feasible nodes
    (absent from the upstream score list) get 0."""
    from ksim_tpu.state.resources import labels_of

    ns_labels = {name_of(ns): dict(labels_of(ns)) for ns in namespaces}
    pref_aff = _ipa_preferred(pod, "podAffinity")
    pref_anti = _ipa_preferred(pod, "podAntiAffinity")
    has_constraints = bool(pref_aff) or bool(pref_anti)

    topo: dict[tuple[str, str], int] = {}

    def add(term: JSON, owner: JSON, to_check: JSON, node_lbls: dict, w: int) -> None:
        tk = term.get("topologyKey", "")
        if tk in node_lbls and _ipa_term_matches(term, owner, to_check, ns_labels):
            key = (tk, node_lbls[tk])
            topo[key] = topo.get(key, 0) + w

    for info in infos:
        node_lbls = labels_of(info["node"])
        for ep in all_pods_by_node.get(info["name"], []):
            if not has_constraints and not _ipa_has_affinity(ep):
                continue  # podsToProcess = PodsWithAffinity
            for wt in pref_aff:
                add(wt.get("podAffinityTerm") or {}, pod, ep, node_lbls, int(wt.get("weight", 0)))
            for wt in pref_anti:
                add(wt.get("podAffinityTerm") or {}, pod, ep, node_lbls, -int(wt.get("weight", 0)))
            if hard_weight > 0:
                for t in _ipa_required(ep, "podAffinity"):
                    add(t, ep, pod, node_lbls, hard_weight)
            for wt in _ipa_preferred(ep, "podAffinity"):
                add(wt.get("podAffinityTerm") or {}, ep, pod, node_lbls, int(wt.get("weight", 0)))
            for wt in _ipa_preferred(ep, "podAntiAffinity"):
                add(wt.get("podAffinityTerm") or {}, ep, pod, node_lbls, -int(wt.get("weight", 0)))

    raw = []
    for i, info in enumerate(infos):
        if not feasible[i]:
            raw.append(0)
            continue
        node_lbls = labels_of(info["node"])
        raw.append(
            sum(cnt for (tk, val), cnt in topo.items() if node_lbls.get(tk) == val)
        )
    feas_scores = [raw[i] for i in range(len(infos)) if feasible[i]]
    norm = [0] * len(infos)
    if feas_scores:
        mn, mx = min(feas_scores), max(feas_scores)
        diff = mx - mn
        for i in range(len(infos)):
            if feasible[i] and diff > 0:
                norm[i] = int(float(MAX_NODE_SCORE) * (float(raw[i] - mn) / float(diff)))
    return raw, norm


# -- normalization helper ----------------------------------------------------


def default_normalize_score(
    scores: list[int], *, reverse: bool, max_priority: int = MAX_NODE_SCORE
) -> list[int]:
    """Upstream helper.DefaultNormalizeScore over a scored-node list."""
    max_count = max(scores, default=0)
    if max_count == 0:
        if reverse:
            return [max_priority] * len(scores)
        return list(scores)
    out = []
    for s in scores:
        s = max_priority * s // max_count
        if reverse:
            s = max_priority - s
        out.append(s)
    return out


# -- NodeResourcesFit -------------------------------------------------------


def fit_filter(pod: JSON, info: NodeInfo) -> list[str]:
    """Upstream fit.go fitsRequest: returns insufficient-resource reasons
    (empty == fits)."""
    reasons: list[str] = []
    if info["pod_count"] + 1 > info["allowed_pods"]:
        reasons.append("Too many pods")
    req = pod_requests(pod)
    # Early exit iff base requests are zero AND no scalar-resource key is
    # present — a zero-valued extended-resource key still populates
    # ScalarResources upstream and defeats the early return.
    if all(req.get(r, 0) == 0 for r in BASE_RESOURCES) and not any(
        k not in BASE_RESOURCES for k in req
    ):
        return reasons
    alloc = info["allocatable"]
    used = info["requested"]
    for r in BASE_RESOURCES:
        if req.get(r, 0) > alloc.get(r, 0) - used.get(r, 0):
            reasons.append(f"Insufficient {r}")
    # Extended resources in sorted order — upstream iterates a Go map
    # (random order); we canonicalize to the featurizer's sorted resource
    # axis so kernel and oracle agree on reason ordering.
    for r in sorted(req):
        v = req[r]
        if r in BASE_RESOURCES or v == 0:
            continue
        if v > alloc.get(r, 0) - used.get(r, 0):
            reasons.append(f"Insufficient {r}")
    return reasons


def least_allocated_score(
    pod: JSON,
    info: NodeInfo,
    resources: tuple[tuple[str, int], ...] = ((CPU, 1), (MEMORY, 1)),
) -> int:
    """Upstream least_allocated.go leastResourceScorer."""
    pod_nz = pod_requests(pod, non_zero=True)
    node_score = 0
    weight_sum = 0
    for r, weight in resources:
        allocatable = info["allocatable"].get(r, 0)
        if allocatable == 0:
            continue
        requested = info["nonzero_requested"].get(r, 0) + pod_nz.get(r, 0)
        if requested > allocatable:
            s = 0
        else:
            s = ((allocatable - requested) * MAX_NODE_SCORE) // allocatable
        node_score += s * weight
        weight_sum += weight
    if weight_sum == 0:
        return 0
    return node_score // weight_sum


def most_allocated_score(
    pod: JSON,
    info: NodeInfo,
    resources: tuple[tuple[str, int], ...] = ((CPU, 1), (MEMORY, 1)),
) -> int:
    """Upstream most_allocated.go mostResourceScorer."""
    pod_nz = pod_requests(pod, non_zero=True)
    node_score = 0
    weight_sum = 0
    for r, weight in resources:
        allocatable = info["allocatable"].get(r, 0)
        if allocatable == 0:
            continue
        requested = info["nonzero_requested"].get(r, 0) + pod_nz.get(r, 0)
        # Requests above capacity clamp (pods with no requests get minimums).
        s = (min(requested, allocatable) * MAX_NODE_SCORE) // allocatable
        node_score += s * weight
        weight_sum += weight
    if weight_sum == 0:
        return 0
    return node_score // weight_sum


def _broken_linear(shape: tuple[tuple[int, int], ...], p: int) -> int:
    """Upstream helper/shape_score.go BuildBrokenLinearFunction (scores
    already scaled x10).  Go integer division truncates toward zero."""
    for i, (u, s) in enumerate(shape):
        if p <= u:
            if i == 0:
                return s
            u_p, s_p = shape[i - 1]
            num = (s - s_p) * (p - u_p)
            den = u - u_p
            q = num // den if num >= 0 else -((-num) // den)
            return s_p + q
    return shape[-1][1]


def requested_to_capacity_ratio_score(
    pod: JSON,
    info: NodeInfo,
    shape: tuple[tuple[int, int], ...],
    resources: tuple[tuple[str, int], ...] = ((CPU, 1), (MEMORY, 1)),
) -> int:
    """Upstream requested_to_capacity_ratio.go
    buildRequestedToCapacityRatioScorerFunction: shape scores pre-scaled
    x10; zero-capacity/overcommit evaluate the shape at maxUtilization;
    only positive resource scores enter the weight sum; the final average
    is math.Round of a float division (exact for our int magnitudes)."""
    pod_nz = pod_requests(pod, non_zero=True)
    scaled = tuple((u, s * 10) for u, s in shape)
    node_score = 0
    weight_sum = 0
    for r, weight in resources:
        allocatable = info["allocatable"].get(r, 0)
        if allocatable == 0:
            continue
        requested = info["nonzero_requested"].get(r, 0) + pod_nz.get(r, 0)
        if requested > allocatable:
            util = MAX_NODE_SCORE
        else:
            util = (requested * MAX_NODE_SCORE) // allocatable
        s = _broken_linear(scaled, util)
        if s > 0:
            node_score += s * weight
            weight_sum += weight
    if weight_sum == 0:
        return 0
    # math.Round(n / d) for n >= 0 == (2n + d) // (2d).
    return (2 * node_score + weight_sum) // (2 * weight_sum)


def balanced_allocation_score(
    pod: JSON,
    info: NodeInfo,
    resources: tuple[str, ...] = (CPU, MEMORY),
) -> int:
    """Upstream balanced_allocation.go balancedResourceScorer (float64)."""
    pod_nz = pod_requests(pod, non_zero=True)
    fractions: list[float] = []
    total = 0.0
    for r in resources:
        allocatable = info["allocatable"].get(r, 0)
        if allocatable == 0:
            continue
        requested = info["nonzero_requested"].get(r, 0) + pod_nz.get(r, 0)
        fraction = float(requested) / float(allocatable)
        if fraction > 1:
            fraction = 1.0
        total += fraction
        fractions.append(fraction)
    std = 0.0
    if len(fractions) == 2:
        std = abs((fractions[0] - fractions[1]) / 2)
    elif len(fractions) > 2:
        mean = total / len(fractions)
        std = math.sqrt(sum((f - mean) ** 2 for f in fractions) / len(fractions))
    return int((1 - std) * float(MAX_NODE_SCORE))


# -- NodeName ---------------------------------------------------------------


def node_name_filter(pod: JSON, info: NodeInfo) -> list[str]:
    """Upstream nodename/node_name.go Fits."""
    from ksim_tpu.plugins.nodename import ERR_REASON

    want = pod.get("spec", {}).get("nodeName") or ""
    if not want or want == info["name"]:
        return []
    return [ERR_REASON]


# -- NodePorts --------------------------------------------------------------


def node_ports_filter(pod: JSON, pods_on_node: Sequence[JSON]) -> list[str]:
    """Upstream nodeports/node_ports.go Fits over the node's existing
    pods' (hostIP, protocol, hostPort) triples."""
    from ksim_tpu.plugins.nodeports import ERR_REASON
    from ksim_tpu.state.extras import _host_ports, ports_conflict

    wants = _host_ports(pod)
    if not wants:
        return []
    existing = [t for p in pods_on_node for t in _host_ports(p)]
    for w in wants:
        for e in existing:
            if ports_conflict(w, e):
                return [ERR_REASON]
    return []


# -- ImageLocality ----------------------------------------------------------


def build_image_states(nodes: Sequence[JSON]) -> dict[str, tuple[int, int]]:
    """normalized image name -> (sizeBytes, numNodes) — the scheduler
    cache's ImageStateSummary."""
    from ksim_tpu.state.extras import normalized_image_name

    sizes: dict[str, int] = {}
    num: dict[str, int] = {}
    for node in nodes:
        seen: set[str] = set()
        for img in node.get("status", {}).get("images") or []:
            sz = int(img.get("sizeBytes") or 0)
            for nm in img.get("names") or []:
                key = normalized_image_name(nm)
                if key in seen:
                    continue
                seen.add(key)
                sizes[key] = max(sizes.get(key, 0), sz)
                num[key] = num.get(key, 0) + 1
    return {k: (sizes[k], num[k]) for k in sizes}


def image_locality_score(
    pod: JSON,
    node: JSON,
    image_states: dict[str, tuple[int, int]],
    total_nodes: int,
) -> int:
    """Upstream imagelocality/image_locality.go Score (sumImageScores +
    calculatePriority), float64 exact."""
    from ksim_tpu.state.extras import normalized_image_name

    node_images = {
        normalized_image_name(nm)
        for img in node.get("status", {}).get("images") or []
        for nm in img.get("names") or []
    }
    containers = pod.get("spec", {}).get("containers") or []
    sum_scores = 0
    for c in containers:
        name = normalized_image_name(c.get("image") or "")
        if name in node_images and name in image_states:
            size, nn = image_states[name]
            # Go evaluates size * (nn/total): the spread ratio FIRST, so
            # the float64 rounding point matches (int(size*nn/total) can
            # differ by 1 at ~1-in-4000 triples).
            sum_scores += int(float(size) * (float(nn) / float(total_nodes)))
    from ksim_tpu.plugins.imagelocality import MAX_CONTAINER_THRESHOLD, MIN_THRESHOLD

    max_threshold = MAX_CONTAINER_THRESHOLD * len(containers)
    clamped = min(max(sum_scores, MIN_THRESHOLD), max(max_threshold, MIN_THRESHOLD))
    denom = max_threshold - MIN_THRESHOLD
    if denom <= 0:
        return 0
    return int(MAX_NODE_SCORE * (clamped - MIN_THRESHOLD) / denom)


# -- Volume family ----------------------------------------------------------
# Pure-Python counterparts of plugins/volumes.py (same scoped semantics,
# state/volumes.py docstring documents the simplifications).


def _volume_claims(pod: JSON, pvcs_by_key: dict) -> tuple[list[JSON], int]:
    """(resolved PVC objects, pod_fail code 0|1 unbound-immediate|2 missing)
    — ignores storage-class context; callers refine."""
    from ksim_tpu.state.volumes import _pvc_name, _pod_volumes
    from ksim_tpu.state.resources import namespace_of

    ns = namespace_of(pod) or "default"
    out, fail = [], 0
    for vol in _pod_volumes(pod):
        claim = _pvc_name(pod, vol)
        if claim is None:
            continue
        pvc = pvcs_by_key.get(f"{ns}/{claim}")
        if pvc is None:
            fail = fail or 2
            continue
        out.append(pvc)
    return out, fail


def volume_binding_filter(
    pod: JSON, node: JSON, pvcs: Sequence[JSON], pvs: Sequence[JSON],
    storage_classes: Sequence[JSON],
) -> list[str]:
    from ksim_tpu.plugins.volumes import (
        ERR_BIND_CONFLICT,
        ERR_NODE_CONFLICT,
        ERR_PVC_NOT_FOUND,
        ERR_UNBOUND_IMMEDIATE,
    )
    from ksim_tpu.state.volumes import (
        NO_PROVISIONER,
        _pv_affinity_admits,
        _pv_matches_claim,
    )
    from ksim_tpu.state.resources import namespace_of

    pvcs_by_key = {f"{namespace_of(c)}/{name_of(c)}": c for c in pvcs}
    pv_by_name = {name_of(v): v for v in pvs}
    sc_by_name = {name_of(s): s for s in storage_classes}
    claims, fail = _volume_claims(pod, pvcs_by_key)
    reasons = []
    if fail == 2:
        reasons.append(ERR_PVC_NOT_FOUND)
    node_conf = bind_conf = unbound = False
    for pvc in claims:
        spec = pvc.get("spec") or {}
        bound = spec.get("volumeName") or ""
        sc = sc_by_name.get(spec.get("storageClassName") or "")
        mode = (sc or {}).get("volumeBindingMode") or "Immediate"
        if bound:
            pv = pv_by_name.get(bound)
            if pv is None:
                if ERR_PVC_NOT_FOUND not in reasons:
                    reasons.append(ERR_PVC_NOT_FOUND)
            elif not _pv_affinity_admits(pv, node):
                node_conf = True
        elif mode == "Immediate":
            unbound = True
        else:
            provisionable = bool(
                sc and (sc.get("provisioner") or "") not in ("", NO_PROVISIONER)
            )
            has_cand = any(
                _pv_matches_claim(pv, pvc) and _pv_affinity_admits(pv, node)
                for pv in pvs
            )
            if not (provisionable or has_cand):
                bind_conf = True
    if unbound:
        reasons.insert(0, ERR_UNBOUND_IMMEDIATE)
    if node_conf:
        reasons.append(ERR_NODE_CONFLICT)
    if bind_conf:
        reasons.append(ERR_BIND_CONFLICT)
    return reasons


def volume_zone_filter(
    pod: JSON, node: JSON, pvcs: Sequence[JSON], pvs: Sequence[JSON]
) -> list[str]:
    from ksim_tpu.plugins.volumes import ERR_ZONE_CONFLICT
    from ksim_tpu.state.volumes import _pv_zone_admits
    from ksim_tpu.state.resources import labels_of, namespace_of

    pvcs_by_key = {f"{namespace_of(c)}/{name_of(c)}": c for c in pvcs}
    pv_by_name = {name_of(v): v for v in pvs}
    claims, _fail = _volume_claims(pod, pvcs_by_key)
    node_labels = dict(labels_of(node))
    for pvc in claims:
        bound = (pvc.get("spec") or {}).get("volumeName") or ""
        pv = pv_by_name.get(bound)
        if pv is not None and not _pv_zone_admits(pv, node_labels):
            return [ERR_ZONE_CONFLICT]
    return []


def volume_restrictions_filter(
    pod: JSON, pods_on_node: Sequence[JSON], pvcs: Sequence[JSON]
) -> list[str]:
    from ksim_tpu.plugins.volumes import ERR_DISK_CONFLICT, ERR_RWOP_CONFLICT
    from ksim_tpu.state.volumes import DISK_SOURCES, _pod_volumes, _pvc_name
    from ksim_tpu.state.resources import namespace_of

    pvcs_by_key = {f"{namespace_of(c)}/{name_of(c)}": c for c in pvcs}

    def rwop_claims(p):
        ns = namespace_of(p) or "default"
        out = set()
        for vol in _pod_volumes(p):
            claim = _pvc_name(p, vol)
            if claim is None:
                continue
            pvc = pvcs_by_key.get(f"{ns}/{claim}")
            modes = set(((pvc or {}).get("spec") or {}).get("accessModes") or [])
            if "ReadWriteOncePod" in modes:
                out.add(f"{ns}/{claim}")
        return out

    def disks(p):
        out = []
        for vol in _pod_volumes(p):
            for src, id_field, ro_share in DISK_SOURCES:
                s = vol.get(src)
                if s and s.get(id_field):
                    out.append((src, str(s[id_field]), not s.get("readOnly"), ro_share))
        return out

    reasons = []
    mine = rwop_claims(pod)
    existing = set()
    for p in pods_on_node:
        existing |= rwop_claims(p)
    my_disks = disks(pod)
    node_disks = [d for p in pods_on_node for d in disks(p)]
    disk_conf = False
    for src, vid, rw, ro_share in my_disks:
        for esrc, evid, erw, _ in node_disks:
            if (src, vid) != (esrc, evid):
                continue
            if not ro_share or rw or erw:
                disk_conf = True
    if disk_conf:
        reasons.append(ERR_DISK_CONFLICT)
    if mine & existing:
        reasons.append(ERR_RWOP_CONFLICT)
    return reasons


def node_volume_limits_filter(
    pod: JSON,
    node: JSON,
    pods_on_node: Sequence[JSON],
    pvcs: Sequence[JSON],
    pvs: Sequence[JSON],
    storage_classes: Sequence[JSON],
    pools: tuple[str, ...] | None = None,
) -> list[str]:
    """``pools`` restricts the check to the named attachable-volumes-*
    suffixes — the legacy one-type plugins (EBSLimits, GCEPDLimits,
    AzureDiskLimits, CinderLimits; upstream nodevolumelimits/non_csi.go);
    None is the all-pool NodeVolumeLimits behavior."""
    from ksim_tpu.plugins.volumes import ERR_MAX_VOLUME_COUNT
    from ksim_tpu.state.volumes import (
        DISK_SOURCES,
        LIMIT_ONLY_SOURCES,
        SOURCE_POOL,
        _csi_pool,
        _pod_volumes,
        _pvc_name,
        _pv_source_id,
    )
    from ksim_tpu.state.resources import namespace_of

    pvcs_by_key = {f"{namespace_of(c)}/{name_of(c)}": c for c in pvcs}
    pv_by_name = {name_of(v): v for v in pvs}
    sc_by_name = {name_of(s): s for s in storage_classes}

    def pooled_volumes(p):
        """set of (pool, volume-id) the pod attaches."""
        ns = namespace_of(p) or "default"
        out = set()
        for vol in _pod_volumes(p):
            claim = _pvc_name(p, vol)
            if claim is not None:
                pvc = pvcs_by_key.get(f"{ns}/{claim}")
                if not pvc:
                    continue
                pv = pv_by_name.get((pvc.get("spec") or {}).get("volumeName") or "")
                if not pv:
                    continue
                src, _vid = _pv_source_id(pv)
                sc = sc_by_name.get((pvc.get("spec") or {}).get("storageClassName") or "")
                pool = SOURCE_POOL.get(src) if src else None
                pool = pool or _csi_pool(pv, sc)
                if pool:
                    out.add((pool, f"pv:{name_of(pv)}"))
                continue
            for src, id_field, _ro in DISK_SOURCES:
                s = vol.get(src)
                if s and s.get(id_field) and SOURCE_POOL.get(src):
                    out.add((SOURCE_POOL[src], f"{src}:{s[id_field]}"))
            for src, id_field in LIMIT_ONLY_SOURCES:
                s = vol.get(src)
                if s and s.get(id_field) and SOURCE_POOL.get(src):
                    out.add((SOURCE_POOL[src], f"{src}:{s[id_field]}"))
        return out

    alloc = node.get("status", {}).get("allocatable") or {}
    limits = {
        k.removeprefix("attachable-volumes-"): int(v)
        for k, v in alloc.items()
        if k.startswith("attachable-volumes-")
    }
    attached: dict[str, set] = {}
    for p in pods_on_node:
        for pool, vid in pooled_volumes(p):
            attached.setdefault(pool, set()).add(vid)
    # Accumulate the pod's volumes per pool BEFORE comparing: a pod
    # attaching several new volumes must fit as a whole (the kernel sums
    # used + new the same way).
    want: dict[str, set] = {}
    for pool, vid in pooled_volumes(pod):
        want.setdefault(pool, set()).add(vid)
    for pool, vids in want.items():
        if pools is not None and pool not in pools:
            continue
        if pool in limits and len(attached.get(pool, set()) | vids) > limits[pool]:
            return [ERR_MAX_VOLUME_COUNT]
    return []
