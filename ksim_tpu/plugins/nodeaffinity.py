"""NodeAffinity filter + score kernels.

Upstream kube-scheduler v1.30 ``plugins/nodeaffinity/node_affinity.go``:

- Filter: pod.spec.nodeSelector (all pairs must match) AND
  requiredDuringSchedulingIgnoredDuringExecution (OR over
  nodeSelectorTerms; a present-but-unmatchable required clause fails).
  Failure message: ``node(s) didn't match Pod's node affinity/selector``.
- Score: sum of weights of matching preferred terms; normalized with
  DefaultNormalizeScore(MaxNodeScore, reverse=false).

Device algebra over the term vocabulary (state/encoding.py): a node
matches term t iff its satisfied-requirement count over t's requirement
set equals |t| — one integer matmul ``node_req_match @ term_req.T`` per
evaluation, shared by filter and score.  Empty terms have size -1 and can
never match (upstream: empty term matches nothing).
"""

from __future__ import annotations

import jax.numpy as jnp

from ksim_tpu.plugins.base import MAX_NODE_SCORE, FilterOutput, NodeStateView, PodView

NAME = "NodeAffinity"
ERR_REASON_POD = "node(s) didn't match Pod's node affinity/selector"
ERR_REASON_ENFORCED = "node(s) didn't match scheduler-enforced node affinity"
POD_MISMATCH_BIT = 1
ENFORCED_MISMATCH_BIT = 2


def _term_matches(aux) -> jnp.ndarray:
    """bool [N, T]: node matches term."""
    a = aux["affinity"]
    counts = a["node_req_match"].astype(jnp.int32) @ a["term_req"].astype(jnp.int32).T
    return counts == a["term_size"][None, :]


def required_affinity_match(aux, pod: PodView) -> jnp.ndarray:
    """bool [N]: node passes the pod's nodeSelector AND required node
    affinity — upstream nodeaffinity.GetRequiredNodeAffinity(pod).Match,
    which PodTopologySpread's Honor nodeAffinityPolicy also consults."""
    a = aux["affinity"]
    term_ok = _term_matches(aux)  # [N, T]
    sel = a["selector_term"][pod.index]  # scalar
    sel_ok = jnp.where(sel >= 0, term_ok[:, jnp.maximum(sel, 0)], True)
    req_set = a["required_terms"][pod.index]  # [T]
    req_ok = jnp.where(
        a["has_required"][pod.index],
        jnp.any(term_ok & req_set[None, :], axis=1),
        True,
    )
    return sel_ok & req_ok


class NodeAffinity:
    # Static reason-bit width: result tensors downcast when every
    # filter plugin's bits fit a narrower dtype (engine/core.py).
    reason_bit_width = 2
    final_score_bound = 100  # post-normalize max (MaxNodeScore)
    name = NAME

    def filter(self, state: NodeStateView, pod: PodView, aux) -> FilterOutput:
        a = aux["affinity"]
        pod_ok = required_affinity_match(aux, pod)
        # Profile-level addedAffinity (NodeAffinityArgs): checked FIRST
        # upstream (node_affinity.go Filter, errReasonEnforced), ANDed for
        # every pod of the profile.
        term_ok = _term_matches(aux)
        added_ok = jnp.where(
            a["has_added"][0],
            jnp.any(term_ok & a["added_terms"][None, :], axis=1),
            True,
        )
        bits = jnp.where(added_ok, 0, ENFORCED_MISMATCH_BIT) | jnp.where(
            pod_ok, 0, POD_MISMATCH_BIT
        )
        return FilterOutput(ok=bits == 0, reason_bits=bits.astype(jnp.int32))

    def decode_reasons(self, bits: int) -> list[str]:
        # Upstream early-returns on the enforced mismatch, so the pod
        # reason never co-occurs with it in a recorded status.
        if bits & ENFORCED_MISMATCH_BIT:
            return [ERR_REASON_ENFORCED]
        return [ERR_REASON_POD] if bits else []

    def static_sig(self) -> tuple:
        return (NAME,)

    def failure_unresolvable(self, bits: int) -> bool:
        # Upstream returns UnschedulableAndUnresolvable: labels don't
        # change when pods are preempted.
        return True

    def score(self, state: NodeStateView, pod: PodView, aux, ok=None) -> jnp.ndarray:
        a = aux["affinity"]
        term_ok = _term_matches(aux)
        # addedAffinity preferred terms score for every pod (upstream
        # node_affinity.go Score: addedPrefSchedTerms).
        weights = a["preferred_weights"][pod.index] + a["added_pref"]  # [T] i32
        return (term_ok.astype(jnp.int32) * weights[None, :]).sum(axis=1)

    def normalize(self, scores: jnp.ndarray, ok: jnp.ndarray) -> jnp.ndarray:
        """DefaultNormalizeScore(MaxNodeScore, reverse=False) over feasible
        nodes."""
        mx = jnp.max(jnp.where(ok, scores, 0))
        return jnp.where(
            mx > 0, (MAX_NODE_SCORE * scores) // jnp.maximum(mx, 1), scores
        ).astype(jnp.int32)
