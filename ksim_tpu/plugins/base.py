"""Common types for batched plugin kernels.

A batch plugin evaluates ONE pod against ALL nodes at once (the node axis is
vectorized, and may be sharded over the TPU mesh); the engine vmaps over the
pod axis for one-shot batch evaluation, or lax.scan's over pods for the
sequential commit loop.  This replaces the reference's per-(pod, node,
plugin) wrapped calls (reference simulator/scheduler/plugin/
wrappedplugin.go:420-445 Score, :523-548 Filter).

Reason codes: filters return an int32 bitmask per node instead of a status
string; bit meanings are plugin-specific and decoded host-side into the
exact upstream status messages for the result annotations ("Insufficient
cpu", "Too many pods", ... — upstream noderesources/fit.go).
"""

from __future__ import annotations

from typing import NamedTuple, Protocol

import jax.numpy as jnp

# framework.MaxNodeScore — the single definition for the package.
MAX_NODE_SCORE = 100


class NodeStateView(NamedTuple):
    """Dynamic + static per-node arrays visible to kernels.

    Static across a scheduling run: allocatable, allowed_pods, valid,
    unschedulable.  Dynamic (the lax.scan carry): requested,
    nonzero_requested, pod_count.
    """

    allocatable: jnp.ndarray  # i32 [N, R]
    allowed_pods: jnp.ndarray  # i32 [N]
    valid: jnp.ndarray  # bool [N]
    unschedulable: jnp.ndarray  # bool [N]
    requested: jnp.ndarray  # i32 [N, R]
    nonzero_requested: jnp.ndarray  # i32 [N, R]
    pod_count: jnp.ndarray  # i32 [N]

    def commit(self, node_idx: jnp.ndarray, pod_req: jnp.ndarray, pod_nz: jnp.ndarray) -> "NodeStateView":
        """Charge a pod to node ``node_idx`` (no-op when node_idx < 0)."""
        onehot = (jnp.arange(self.pod_count.shape[0]) == node_idx) & (node_idx >= 0)
        return self._replace(
            requested=self.requested + jnp.where(onehot[:, None], pod_req[None, :], 0),
            nonzero_requested=self.nonzero_requested
            + jnp.where(onehot[:, None], pod_nz[None, :], 0),
            pod_count=self.pod_count + onehot.astype(jnp.int32),
        )


class PodView(NamedTuple):
    """One pod's arrays as seen by kernels (a row of PodBatch)."""

    requests: jnp.ndarray  # i32 [R]
    nonzero_requests: jnp.ndarray  # i32 [R]
    tolerates_unschedulable: jnp.ndarray  # bool scalar
    has_requests: jnp.ndarray  # bool scalar (upstream fitsRequest early-exit)
    index: jnp.ndarray  # i32 scalar — row into per-pod aux arrays


class PodBatch(NamedTuple):
    """The pod axis as device arrays (leading dim P on every leaf)."""

    requests: jnp.ndarray  # i32 [P, R]
    nonzero_requests: jnp.ndarray  # i32 [P, R]
    valid: jnp.ndarray  # bool [P]
    tolerates_unschedulable: jnp.ndarray  # bool [P]
    has_requests: jnp.ndarray  # bool [P]
    index: jnp.ndarray  # i32 [P] == arange(P)

    def row(self, i) -> tuple["PodView", jnp.ndarray]:
        return (
            PodView(
                requests=self.requests[i],
                nonzero_requests=self.nonzero_requests[i],
                tolerates_unschedulable=self.tolerates_unschedulable[i],
                has_requests=self.has_requests[i],
                index=self.index[i],
            ),
            self.valid[i],
        )


class FilterOutput(NamedTuple):
    ok: jnp.ndarray  # bool [N]
    reason_bits: jnp.ndarray  # i32 [N], 0 == passed


class BatchPlugin(Protocol):
    """Static interface of a batched plugin module.

    ``aux`` is the device-side encoding dict (Engine converts
    FeaturizedSnapshot.aux dataclasses to pytrees of jnp arrays); plugins
    that need none ignore it.  ``ok`` is the combined post-filter
    feasibility mask passed to score.  Plugins with scan-carried state
    additionally define carry_init(aux) / carry_commit(carry, aux, pod,
    best) and receive ``carry=`` in filter/score.
    """

    name: str

    def filter(
        self, state: NodeStateView, pod: PodView, aux: dict, **kw
    ) -> FilterOutput: ...

    def score(
        self, state: NodeStateView, pod: PodView, aux: dict, ok=None, **kw
    ) -> jnp.ndarray: ...

    def static_sig(self) -> tuple:
        """Hashable signature of everything that shapes the TRACED
        computation (not host-side decode tables).  Two plugin instances
        with equal signatures must trace identically; the Engine keys its
        jit cache on these so re-featurizing a same-shaped snapshot reuses
        compiled programs.  Plugins that don't implement it are keyed by
        object identity (no cross-instance cache reuse)."""
        ...
