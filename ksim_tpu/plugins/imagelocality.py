"""ImageLocality score plugin.

Upstream kube-scheduler v1.30 ``plugins/imagelocality/image_locality.go``:

- per container image present on the node, ``scaledImageScore`` =
  ``int64(size * numNodes / totalNodes)`` (image-spread discount);
- ``calculatePriority``: clamp the sum to [23MB, 1000MB * containers] and
  map linearly onto [0, MaxNodeScore] with int64 truncation.

No NormalizeScore (upstream registers Score only).  float64 under x64
matches Go exactly; float32 on TPU carries a documented ±1 rounding
tolerance at truncation boundaries (same caveat as the other float-path
scores).  Encoding: state/extras.py.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from ksim_tpu.plugins.base import MAX_NODE_SCORE, NodeStateView, PodView
from ksim_tpu.state.extras import ImageTensors

NAME = "ImageLocality"

MB = 1024 * 1024
MIN_THRESHOLD = 23 * MB
MAX_CONTAINER_THRESHOLD = 1000 * MB


class ImageLocality:
    # Static reason-bit width: result tensors downcast when every
    # filter plugin's bits fit a narrower dtype (engine/core.py).
    reason_bit_width = 1
    final_score_bound = 100  # post-normalize max (MaxNodeScore)
    name = NAME

    def __init__(self, img: ImageTensors) -> None:
        del img  # all state flows through aux

    def static_sig(self) -> tuple:
        return (NAME,)

    # Score-only plugin: every registration site disables the filter
    # point, so no filter method exists.

    def score(self, state: NodeStateView, pod: PodView, aux, ok=None) -> jnp.ndarray:
        a = aux["imagelocality"]
        ft = jnp.float64 if jax.config.jax_enable_x64 else jnp.float32
        j = pod.index
        # scaledImageScore per vocab image (int64 truncation per image).
        spread = a["image_num_nodes"].astype(ft) / a["total_nodes_f"].astype(ft)
        scaled = jnp.trunc(a["image_size"].astype(ft) * spread)  # [I]
        counts = a["pod_image_count"][j].astype(ft)  # [I]
        sum_scores = jnp.dot(a["node_has_image"].astype(ft), scaled * counts)  # [N]
        n_cont = a["pod_num_containers"][j].astype(ft)
        max_threshold = ft(MAX_CONTAINER_THRESHOLD) * n_cont
        clamped = jnp.clip(
            sum_scores,
            ft(MIN_THRESHOLD),
            jnp.maximum(max_threshold, ft(MIN_THRESHOLD)),
        )
        val = (
            ft(MAX_NODE_SCORE)
            * (clamped - ft(MIN_THRESHOLD))
            / jnp.maximum(max_threshold - ft(MIN_THRESHOLD), 1.0)
        )
        return jnp.trunc(val).astype(jnp.int32)
