"""Scheduling-plugin kernel library.

Each upstream kube-scheduler plugin the reference wraps (reference
simulator/scheduler/plugin/wrappedplugin.go) is re-implemented twice here:

1. a **batched JAX kernel pair** ``filter``/``score`` producing whole
   node-axis vectors (vmapped over the pod axis by the engine), and
2. a **pure-Python oracle** (`plugins/oracle.py`) that mirrors the upstream
   Go code path exactly — the parity reference every kernel is tested
   against (SURVEY.md section 4 test-plan implication).
"""

from ksim_tpu.plugins.base import BatchPlugin, FilterOutput, NodeStateView

__all__ = ["BatchPlugin", "FilterOutput", "NodeStateView"]
