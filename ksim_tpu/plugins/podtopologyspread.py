"""PodTopologySpread filter + score kernels.

Upstream kube-scheduler v1.30 ``plugins/podtopologyspread/{filtering,scoring}.go``
with NodeInclusionPolicy and MatchLabelKeys on (their v1.30 defaults),
MinDomains honored for DoNotSchedule constraints:

- Filter: for each DoNotSchedule constraint, nodes eligible for domain
  statistics are those passing the constraint's inclusion policies
  (nodeAffinityPolicy Honor -> pod's nodeSelector+required affinity;
  nodeTaintsPolicy default Ignore) and carrying ALL the pod's
  DoNotSchedule topology keys.  skew = matchNum + selfMatch - minMatchNum
  must not exceed maxSkew; a candidate missing the topology key fails with
  the upstream "(missing required label)" message.  minMatchNum is 0 when
  the observed domain count is below minDomains.
- Score: for each ScheduleAnyway constraint, counts accumulate over
  policy-passing nodes whose domain is registered (i.e. present among
  framework-feasible nodes with all score keys); per-node score is
  ``count * log(domains + 2) + (maxSkew - 1)`` summed over constraints and
  rounded; NormalizeScore is the integer ``100 * (max + min - s) // max``
  with ignored nodes (missing a score key) pinned to 0, and everything
  100 when max == 0.  Pods with no ScheduleAnyway constraints take
  upstream's PreScore-Skip path: final contribution 0.

The scan-carried state is the per-node matching-pod count per selector
context (``[N, S]``); committing a pod is an elementwise outer-product
add.  Per-pod domain statistics are computed with a compile-time dispatch
over the (tiny, host-known) topology-key vocabulary:

- **singleton keys** (every domain holds exactly one node — hostname):
  the domain sum IS the per-node value, the domain count IS the eligible-
  node count, the domain min IS a plain axis reduce — all elementwise;
- **small dense keys** (zone-like): one [N,Dk] one-hot, built elementwise
  per step, carries all constraints at once through two narrow matmuls
  ``[Dk,N] x [N,MC]`` and back;
- **large many-node keys** (rare): per-key segment_sum/segment_max
  fallback.

This keeps the sequential scan step free of gathers and scatters (each
costs ~50us inside a compiled TPU loop) for the common key shapes.

Known divergence (documented): upstream's *system default* constraints
derive selectors from owning Services/ReplicaSets via DefaultSelector;
the snapshot model (like the reference's 7-kind snapshot,
simulator/snapshot/snapshot.go:33-42) carries no Services, so default
constraints are not synthesized — only pod-defined constraints apply.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from ksim_tpu.plugins.base import MAX_NODE_SCORE, FilterOutput, NodeStateView, PodView
from ksim_tpu.plugins.nodeaffinity import required_affinity_match
from ksim_tpu.plugins.tainttoleration import forbidding_taints_tolerated
from ksim_tpu.state.encoding import SpreadTensors

NAME = "PodTopologySpread"
ERR_REASON_CONSTRAINTS_NOT_MATCH = "node(s) didn't match pod topology spread constraints"
ERR_REASON_NODE_LABEL_NOT_MATCH = (
    ERR_REASON_CONSTRAINTS_NOT_MATCH + " (missing required label)"
)
_BIG = jnp.iinfo(jnp.int32).max

SKEW_BIT = 1
MISSING_LABEL_BIT = 2

# Largest per-key domain count that still uses the dense one-hot matmul
# path; beyond this the [N, Dk] one-hot outweighs a segment reduction.
DENSE_MAX = 256


def _ftype():
    """Dtype for GO-PARITY float math (the log-weighted score): float64
    under x64 to match the oracle bit-for-bit."""
    return jnp.float64 if jax.config.jax_enable_x64 else jnp.float32


# The one-hot/selector matmul machinery moves integer COUNTS (< 2^24,
# bounded by the pod count), which float32 represents exactly — always
# use f32 there: f64 matmuls are software-emulated on TPU and dominated
# the exact-mode scan (~4s of 7.5s at 16k x 8k).  The matmuls need
# HIGHEST precision: TPU f32 matmuls default to bf16 passes whose 8-bit
# mantissa silently truncates counts above 256.
_COUNT_FT = jnp.float32
_EXACT = jax.lax.Precision.HIGHEST


def _mm(a, b):
    return jnp.matmul(a, b, precision=_EXACT)


class PodTopologySpread:
    # Static reason-bit width: result tensors downcast when every
    # filter plugin's bits fit a narrower dtype (engine/core.py).
    reason_bit_width = 2
    final_score_bound = 100  # post-normalize max (MaxNodeScore)
    name = NAME
    normalize_needs_ctx = True

    def __init__(self, spread: SpreadTensors) -> None:
        from ksim_tpu.state.featurizer import vocab_pad

        self._mc = spread.con_valid.shape[1]
        self._n_tk = spread.node_ldom.shape[1]
        self._singleton = spread.tk_singleton
        # Per-key domain counts only bound aranges / num_segments, so pad
        # them to power-of-two buckets (padded local ids never occur ->
        # all-zero one-hot columns, never "present"); singleton keys don't
        # use their size at all.  Unbucketed sizes would recompile on
        # every node add/remove under churn.
        self._sizes = tuple(
            1 if singleton else vocab_pad(size)
            for size, singleton in zip(spread.tk_sizes, spread.tk_singleton)
        )

    def static_sig(self) -> tuple:
        return (NAME, self._mc, self._n_tk, self._sizes, self._singleton)

    def failure_unresolvable(self, bits: int) -> bool:
        # Upstream: missing topology label is UnschedulableAndUnresolvable;
        # a skew violation is plain Unschedulable (victims can fix it).
        return bits == MISSING_LABEL_BIT

    # -- carried state ------------------------------------------------------

    def carry_init(self, aux) -> jnp.ndarray:
        return aux["spread"]["init_counts"]  # i32 [N, S]

    def carry_commit(self, carry, aux, pod: PodView, best) -> jnp.ndarray:
        match = aux["spread"]["pod_sel_match"][pod.index]  # [S]
        onehot = (jnp.arange(carry.shape[0]) == best) & (best >= 0)
        return carry + (onehot[:, None] & match[None, :]).astype(carry.dtype)

    # -- helpers ------------------------------------------------------------

    def _constraint_arrays(self, aux, pod: PodView):
        a = aux["spread"]
        j = pod.index
        return {
            "valid": a["con_valid"][j],
            "mode": a["con_mode"][j],
            "sel": a["con_sel"][j],
            "tk": a["con_tk"][j],
            "max_skew": a["con_max_skew"][j],
            "min_domains": a["con_min_domains"][j],
            "self": a["con_self"][j],
            "honor_aff": a["con_honor_aff"][j],
            "honor_taints": a["con_honor_taints"][j],
        }

    def _ldom_mc(self, aux, con) -> jnp.ndarray:
        """[N, MC] each constraint's local domain id per node (-1 = key
        missing), assembled with a static unroll over the key vocab."""
        ldom = aux["spread"]["node_ldom"]  # [N, TK]
        out = jnp.full((ldom.shape[0], self._mc), -1, dtype=jnp.int32)
        for k in range(self._n_tk):
            out = jnp.where((con["tk"] == k)[None, :], ldom[:, k : k + 1], out)
        return out

    def _policy_elig(self, state, con, aff, tnt) -> jnp.ndarray:
        """[N, MC] inclusion-policy eligibility per constraint.

        ``aff``/``tnt`` are computed by the CALLER outside the skip
        cond: the same expressions the NodeAffinity / TaintToleration
        kernels evaluate, so XLA CSE makes them free whenever those
        plugins are enabled — inside the cond branch they would be
        recomputed per extension point instead."""
        e = state.valid[:, None]
        e = e & jnp.where(con["honor_aff"][None, :], aff[:, None], True)
        e = e & jnp.where(con["honor_taints"][None, :], tnt[:, None], True)
        return e

    def _sel_counts(self, carry, con) -> jnp.ndarray:
        """[N, MC] the carried matching-pod count for each constraint's
        selector context (one narrow matmul instead of per-ci gathers)."""
        s = carry.shape[1]
        sel_oh = (con["sel"][None, :] == jnp.arange(s)[:, None]).astype(_COUNT_FT)
        return _mm(carry.astype(_COUNT_FT), sel_oh).astype(jnp.int32)

    def _per_key_stats(self, aux, con, pres_mask, cnt_for):
        """Domain statistics for every constraint at once, via the static
        per-key dispatch (singleton / dense one-hot / segment fallback).

        pres_mask: bool [N, MC] — nodes whose domain counts as present
        (filter: stat-eligible; score: registered = filtered & keyed).
        cnt_for(reg_at): -> i32 [N, MC] per-node contributions given
        reg_at (bool [N, MC]: node's domain is present) — score gates
        contributors on registration, filter ignores the argument.

        Returns (seg_at [N,MC] domain sum at each node (0 where the node
        misses the key), dom_num [MC] present-domain count, min_match [MC]
        min present-domain sum, _BIG when none present).
        """
        ldom = aux["spread"]["node_ldom"]
        ft = _COUNT_FT  # integer counts: f32-exact, no emulated f64
        n = ldom.shape[0]
        seg_at = jnp.zeros((n, self._mc), jnp.int32)
        dom_num = jnp.zeros((self._mc,), jnp.int32)
        minm = jnp.full((self._mc,), _BIG)
        for k in range(self._n_tk):  # static unroll over the key vocab
            g = con["tk"] == k  # [MC]
            if self._singleton[k]:
                contrib = cnt_for(pres_mask)  # own domain == the node
                seg_k = contrib
                dn_k = jnp.sum(pres_mask, axis=0).astype(jnp.int32)
                mm_k = jnp.min(jnp.where(pres_mask, contrib, _BIG), axis=0)
            elif self._sizes[k] <= DENSE_MAX:
                oh = (
                    ldom[:, k][:, None] == jnp.arange(self._sizes[k])[None, :]
                ).astype(ft)  # [N, Dk]
                pres = _mm(oh.T, pres_mask.astype(ft)) > 0  # [Dk, MC]
                reg_at = _mm(oh, pres.astype(ft)) > 0  # [N, MC]
                seg_d = _mm(oh.T, cnt_for(reg_at).astype(ft))  # [Dk, MC]
                seg_k = _mm(oh, seg_d).astype(jnp.int32)
                dn_k = jnp.sum(pres, axis=0).astype(jnp.int32)
                mm_k = jnp.min(
                    jnp.where(pres, seg_d, _BIG), axis=0
                ).astype(jnp.int32)
            else:
                ids = jnp.maximum(ldom[:, k], 0)
                haskey = (ldom[:, k] >= 0)[:, None]
                pres = (
                    jax.ops.segment_max(
                        jnp.where(haskey & pres_mask, 1, 0), ids,
                        num_segments=self._sizes[k],
                    )
                    > 0
                )  # [Dk, MC]
                reg_at = haskey & pres[ids]
                seg_d = jax.ops.segment_sum(
                    jnp.where(haskey, cnt_for(reg_at), 0), ids,
                    num_segments=self._sizes[k],
                )
                seg_k = jnp.where(haskey, seg_d[ids], 0)
                dn_k = jnp.sum(pres, axis=0).astype(jnp.int32)
                mm_k = jnp.min(jnp.where(pres, seg_d, _BIG), axis=0)
            seg_at = jnp.where(g[None, :], seg_k, seg_at)
            dom_num = jnp.where(g, dn_k, dom_num)
            minm = jnp.where(g, mm_k, minm)
        return seg_at, dom_num, minm

    # -- filter -------------------------------------------------------------

    def filter(self, state: NodeStateView, pod: PodView, aux, carry) -> FilterOutput:
        con = self._constraint_arrays(aux, pod)
        active = con["valid"] & (con["mode"] == 0)  # [MC]
        n = state.valid.shape[0]
        aff = required_affinity_match(aux, pod)
        tnt = forbidding_taints_tolerated(aux, pod)

        def heavy(_):
            l_mc = self._ldom_mc(aux, con)  # [N, MC]
            haskey = l_mc >= 0
            allkeys = jnp.all(haskey | ~active[None, :], axis=1)  # [N]
            elig = self._policy_elig(state, con, aff, tnt) & allkeys[:, None]
            stat = elig & haskey  # [N, MC]
            cnt_mc = self._sel_counts(carry, con)
            x = jnp.where(stat, cnt_mc, 0)
            seg_at, dom_num, min_match = self._per_key_stats(
                aux, con, stat, lambda _reg_at: x
            )
            min_match = jnp.where(dom_num > 0, min_match, 0)
            min_match = jnp.where(
                (con["min_domains"] > 0) & (dom_num < con["min_domains"]),
                0,
                min_match,
            )
            match_num = jnp.where(haskey, seg_at, 0)
            skew = (
                match_num
                + con["self"].astype(jnp.int32)[None, :]
                - min_match[None, :]
            )
            viol = skew > con["max_skew"][None, :]
            code_mc = jnp.where(
                ~haskey, MISSING_LABEL_BIT, jnp.where(viol, SKEW_BIT, 0)
            ).astype(jnp.int32)
            # First failing active constraint wins (upstream constraint
            # order).
            code = jnp.zeros(n, dtype=jnp.int32)
            for ci in range(self._mc):
                code = jnp.where(active[ci] & (code == 0), code_mc[:, ci], code)
            return code

        # Upstream's PreFilter Skip (filtering.go): a pod with no
        # DoNotSchedule constraints passes everywhere with nothing
        # recorded.  Inside the sequential scan lax.cond executes only
        # the taken branch, so the ~majority of pods without constraints
        # skip the whole domain-statistics machinery; the heavy branch
        # yields exactly zeros for such pods (active gates every code
        # write), so the split is bit-exact.  Under vmap (batch path)
        # cond lowers to select — same cost as before, same results.
        code = jax.lax.cond(
            jnp.any(active), heavy, lambda _: jnp.zeros(n, jnp.int32), None
        )
        return FilterOutput(ok=code == 0, reason_bits=code)

    def decode_reasons(self, bits: int) -> list[str]:
        if bits == MISSING_LABEL_BIT:
            return [ERR_REASON_NODE_LABEL_NOT_MATCH]
        if bits == SKEW_BIT:
            return [ERR_REASON_CONSTRAINTS_NOT_MATCH]
        return []

    # -- score --------------------------------------------------------------

    def _score_parts(self, aux, con, pod: PodView):
        """(active [MC], l_mc [N,MC], ignored [N]) for ScheduleAnyway."""
        active = con["valid"] & (con["mode"] == 1)
        l_mc = self._ldom_mc(aux, con)
        haskey = l_mc >= 0
        allkeys = jnp.all(haskey | ~active[None, :], axis=1)
        has_con = aux["spread"]["has_score_con"][pod.index]
        ignored = has_con & ~allkeys
        return active, l_mc, haskey, ignored

    def score(self, state: NodeStateView, pod: PodView, aux, ok=None, carry=None) -> jnp.ndarray:
        n = state.valid.shape[0]
        aff = required_affinity_match(aux, pod)
        tnt = forbidding_taints_tolerated(aux, pod)

        def heavy(_):
            con = self._constraint_arrays(aux, pod)
            active, l_mc, haskey, ignored = self._score_parts(aux, con, pod)
            filtered = ok & ~ignored  # [N]

            # Registered domains: present among framework-feasible,
            # non-ignored nodes (upstream calPreScoreState filteredNodes);
            # contributors are policy-passing nodes whose domain is
            # registered.
            fd = filtered[:, None] & haskey  # [N, MC]
            elig0 = self._policy_elig(state, con, aff, tnt) & haskey
            cnt_mc = self._sel_counts(carry, con)
            seg_at, dom_num, _min_unused = self._per_key_stats(
                aux, con, fd, lambda reg_at: jnp.where(elig0 & reg_at, cnt_mc, 0)
            )

            ft = _ftype()
            if jax.config.jax_enable_x64:
                # Exact mode: f64 log, bit-exact vs the oracle (verified
                # on real TPU by tests/tpu_parity_main.py).
                tp_weight = jnp.log(dom_num.astype(ft) + 2.0)  # [MC]
            else:
                # f32 fast mode: platform-deterministic by construction.
                # Backend log implementations differ in ulps (an f32
                # log on TPU vs CPU flipped this round() for raw 1244
                # vs 1243 — the 50k churn drift after the IPA fix), so
                # the weight comes from a trace-time table of
                # float32(log(k+2)) over the integer domain counts
                # (dom_num <= padded N), computed in f64 on the host —
                # a compiled constant, identical on every backend.
                n_nodes = aux["spread"]["node_ldom"].shape[0]
                table = jnp.asarray(
                    np.log(np.arange(n_nodes + 1, dtype=np.float64) + 2.0).astype(
                        np.float32
                    )
                )
                tp_weight = table[jnp.clip(dom_num, 0, n_nodes)]  # [MC]
            contrib = seg_at.astype(ft) * tp_weight[None, :] + (
                con["max_skew"].astype(ft)[None, :] - 1.0
            )
            gate = active[None, :] & filtered[:, None]
            vals = jnp.where(gate, contrib, 0.0)
            if jax.config.jax_enable_x64:
                total = jnp.sum(vals, axis=1)
            else:
                # Fixed-order unrolled MC reduce: IEEE f32 multiply/add
                # are correctly rounded everywhere, but reduce
                # association order is backend-chosen.
                total = vals[:, 0]
                for k in range(1, vals.shape[1]):
                    total = total + vals[:, k]
            return jnp.round(total).astype(jnp.int32)

        # Upstream's PreScore Skip: no ScheduleAnyway constraints ->
        # raw score 0 (normalize pins the final contribution to 0 too).
        # The heavy branch's `gate` zeroes every contribution for such
        # pods, so skipping it is bit-exact; lax.cond makes the skip free
        # in the sequential scan.
        has_con = aux["spread"]["has_score_con"][pod.index]
        return jax.lax.cond(
            has_con, heavy, lambda _: jnp.zeros(n, jnp.int32), None
        )

    def normalize(self, scores, ok, *, state=None, pod=None, aux=None, carry=None):
        def heavy(_):
            con = self._constraint_arrays(aux, pod)
            _active, _l_mc, _haskey, ignored = self._score_parts(aux, con, pod)
            scoreable = ok & ~ignored
            mx = jnp.max(jnp.where(scoreable, scores, jnp.iinfo(jnp.int32).min))
            mn = jnp.min(jnp.where(scoreable, scores, _BIG))
            any_scoreable = jnp.any(scoreable)
            mx = jnp.where(any_scoreable, mx, 0)
            mn = jnp.where(any_scoreable, mn, 0)
            norm = jnp.where(
                mx == 0,
                MAX_NODE_SCORE,
                (MAX_NODE_SCORE * (mx + mn - scores)) // jnp.maximum(mx, 1),
            )
            return jnp.where(ignored, 0, norm).astype(jnp.int32)

        # PreScore Skip: no ScheduleAnyway constraints -> no contribution
        # (the old unconditional `where(has_con, out, 0)` tail, now a
        # cond so skipped pods pay nothing in the scan).
        has_con = aux["spread"]["has_score_con"][pod.index]
        return jax.lax.cond(
            has_con,
            heavy,
            lambda _: jnp.zeros(scores.shape[0], jnp.int32),
            None,
        )
