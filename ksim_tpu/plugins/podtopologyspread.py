"""PodTopologySpread filter + score kernels.

Upstream kube-scheduler v1.30 ``plugins/podtopologyspread/{filtering,scoring}.go``
with NodeInclusionPolicy and MatchLabelKeys on (their v1.30 defaults),
MinDomains honored for DoNotSchedule constraints:

- Filter: for each DoNotSchedule constraint, nodes eligible for domain
  statistics are those passing the constraint's inclusion policies
  (nodeAffinityPolicy Honor -> pod's nodeSelector+required affinity;
  nodeTaintsPolicy default Ignore) and carrying ALL the pod's
  DoNotSchedule topology keys.  skew = matchNum + selfMatch - minMatchNum
  must not exceed maxSkew; a candidate missing the topology key fails with
  the upstream "(missing required label)" message.  minMatchNum is 0 when
  the observed domain count is below minDomains.
- Score: for each ScheduleAnyway constraint, counts accumulate over
  policy-passing nodes whose domain is registered (i.e. present among
  framework-feasible nodes with all score keys); per-node score is
  ``count * log(domains + 2) + (maxSkew - 1)`` summed over constraints and
  rounded; NormalizeScore is the integer ``100 * (max + min - s) // max``
  with ignored nodes (missing a score key) pinned to 0, and everything
  100 when max == 0.  Pods with no ScheduleAnyway constraints take
  upstream's PreScore-Skip path: final contribution 0.

The scan-carried state is the per-node matching-pod count per selector
context (``[N, S]``); per-pod, per-constraint domain statistics are
segment reductions over the global domain vocabulary (Dom axis).

Known divergence (documented): upstream's *system default* constraints
derive selectors from owning Services/ReplicaSets via DefaultSelector;
the snapshot model (like the reference's 7-kind snapshot,
simulator/snapshot/snapshot.go:33-42) carries no Services, so default
constraints are not synthesized — only pod-defined constraints apply.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from ksim_tpu.plugins.base import MAX_NODE_SCORE, FilterOutput, NodeStateView, PodView
from ksim_tpu.plugins.nodeaffinity import required_affinity_match
from ksim_tpu.plugins.tainttoleration import forbidding_taints_tolerated
from ksim_tpu.state.encoding import SpreadTensors

NAME = "PodTopologySpread"
ERR_REASON_CONSTRAINTS_NOT_MATCH = "node(s) didn't match pod topology spread constraints"
ERR_REASON_NODE_LABEL_NOT_MATCH = (
    ERR_REASON_CONSTRAINTS_NOT_MATCH + " (missing required label)"
)
_BIG = jnp.iinfo(jnp.int32).max

SKEW_BIT = 1
MISSING_LABEL_BIT = 2


class PodTopologySpread:
    name = NAME
    normalize_needs_ctx = True

    def __init__(self, spread: SpreadTensors) -> None:
        self._dom = spread.n_domains  # static for segment ops
        self._mc = spread.con_valid.shape[1]

    # -- carried state ------------------------------------------------------

    def carry_init(self, aux) -> jnp.ndarray:
        return aux["spread"]["init_counts"]  # i32 [N, S]

    def carry_commit(self, carry, aux, pod: PodView, best) -> jnp.ndarray:
        match = aux["spread"]["pod_sel_match"][pod.index]  # [S]
        onehot = (jnp.arange(carry.shape[0]) == best) & (best >= 0)
        return carry + (onehot[:, None] & match[None, :]).astype(carry.dtype)

    # -- helpers ------------------------------------------------------------

    def _constraint_arrays(self, aux, pod: PodView):
        a = aux["spread"]
        j = pod.index
        return {
            "valid": a["con_valid"][j],
            "mode": a["con_mode"][j],
            "sel": a["con_sel"][j],
            "tk": a["con_tk"][j],
            "max_skew": a["con_max_skew"][j],
            "min_domains": a["con_min_domains"][j],
            "self": a["con_self"][j],
            "honor_aff": a["con_honor_aff"][j],
            "honor_taints": a["con_honor_taints"][j],
        }

    def _eligibility(self, state, pod, aux, honor_aff, honor_taints):
        aff = required_affinity_match(aux, pod)
        tnt = forbidding_taints_tolerated(aux, pod)
        e = state.valid
        e = e & jnp.where(honor_aff, aff, True)
        e = e & jnp.where(honor_taints, tnt, True)
        return e

    def _has_all_keys(self, aux, con, mode_val) -> jnp.ndarray:
        """bool [N]: node has every topology key of the pod's constraints
        with the given mode."""
        a = aux["spread"]
        node_dom = a["node_dom"]  # [N, TK]
        ok = jnp.ones(node_dom.shape[0], dtype=bool)
        for ci in range(self._mc):
            active = con["valid"][ci] & (con["mode"][ci] == mode_val)
            has = jnp.take(node_dom, con["tk"][ci], axis=1) >= 0
            ok = ok & jnp.where(active, has, True)
        return ok

    # -- filter -------------------------------------------------------------

    def filter(self, state: NodeStateView, pod: PodView, aux, carry) -> FilterOutput:
        a = aux["spread"]
        con = self._constraint_arrays(aux, pod)
        node_dom = a["node_dom"]
        n = node_dom.shape[0]
        allkeys = self._has_all_keys(aux, con, 0)

        code = jnp.zeros(n, dtype=jnp.int32)
        for ci in range(self._mc):
            active = con["valid"][ci] & (con["mode"][ci] == 0)
            d = jnp.take(node_dom, con["tk"][ci], axis=1)  # [N]
            elig = (
                self._eligibility(state, pod, aux, con["honor_aff"][ci], con["honor_taints"][ci])
                & allkeys
            )
            cnt_node = jnp.take(carry, con["sel"][ci], axis=1)  # [N]
            d_safe = jnp.maximum(d, 0)
            stat = elig & (d >= 0)
            seg = jax.ops.segment_sum(
                jnp.where(stat, cnt_node, 0), d_safe, num_segments=self._dom
            )
            present = (
                jax.ops.segment_max(
                    jnp.where(stat, 1, 0), d_safe, num_segments=self._dom
                )
                > 0
            )
            domains_num = present.sum()
            min_match = jnp.min(jnp.where(present, seg, _BIG))
            min_match = jnp.where(domains_num > 0, min_match, 0)
            min_match = jnp.where(
                (con["min_domains"][ci] > 0) & (domains_num < con["min_domains"][ci]),
                0,
                min_match,
            )
            match_num = jnp.where(d >= 0, seg[d_safe], 0)
            skew = match_num + con["self"][ci].astype(jnp.int32) - min_match
            viol = skew > con["max_skew"][ci]
            missing = d < 0
            this_code = jnp.where(missing, MISSING_LABEL_BIT, jnp.where(viol, SKEW_BIT, 0))
            code = jnp.where(active & (code == 0), this_code, code)
        return FilterOutput(ok=code == 0, reason_bits=code)

    def decode_reasons(self, bits: int) -> list[str]:
        if bits == MISSING_LABEL_BIT:
            return [ERR_REASON_NODE_LABEL_NOT_MATCH]
        if bits == SKEW_BIT:
            return [ERR_REASON_CONSTRAINTS_NOT_MATCH]
        return []

    # -- score --------------------------------------------------------------

    def _ignored(self, aux, con, pod: PodView) -> jnp.ndarray:
        """Nodes missing any ScheduleAnyway key while the pod has
        constraints (requireAllTopologies -> IgnoredNodes)."""
        a = aux["spread"]
        has_con = a["has_score_con"][pod.index]
        return has_con & ~self._has_all_keys(aux, con, 1)

    def score(self, state: NodeStateView, pod: PodView, aux, ok=None, carry=None) -> jnp.ndarray:
        a = aux["spread"]
        con = self._constraint_arrays(aux, pod)
        node_dom = a["node_dom"]
        n = node_dom.shape[0]
        ignored = self._ignored(aux, con, pod)
        filtered = ok & ~ignored

        # float64 under x64 (exact vs the float64 oracle/upstream);
        # float32 on TPU (documented rounding tolerance at .5 boundaries).
        ftype = jnp.float64 if jax.config.jax_enable_x64 else jnp.float32
        total = jnp.zeros(n, dtype=ftype)
        for ci in range(self._mc):
            active = con["valid"][ci] & (con["mode"][ci] == 1)
            d = jnp.take(node_dom, con["tk"][ci], axis=1)
            d_safe = jnp.maximum(d, 0)
            # Registered domains: present among framework-feasible,
            # non-ignored nodes (upstream calPreScoreState filteredNodes).
            reg = (
                jax.ops.segment_max(
                    jnp.where(filtered & (d >= 0), 1, 0), d_safe, num_segments=self._dom
                )
                > 0
            )
            elig = (
                self._eligibility(state, pod, aux, con["honor_aff"][ci], con["honor_taints"][ci])
                & (d >= 0)
                & reg[d_safe]
            )
            cnt_node = jnp.take(carry, con["sel"][ci], axis=1)
            seg = jax.ops.segment_sum(
                jnp.where(elig, cnt_node, 0), d_safe, num_segments=self._dom
            )
            domains_num = reg.sum()
            tp_weight = jnp.log(domains_num.astype(ftype) + 2.0)
            contrib = seg[d_safe].astype(ftype) * tp_weight + (
                con["max_skew"][ci].astype(ftype) - 1.0
            )
            total = total + jnp.where(active & filtered, contrib, 0.0)
        return jnp.round(total).astype(jnp.int32)

    def normalize(self, scores, ok, *, state=None, pod=None, aux=None, carry=None):
        con = self._constraint_arrays(aux, pod)
        ignored = self._ignored(aux, con, pod)
        scoreable = ok & ~ignored
        has_con = aux["spread"]["has_score_con"][pod.index]
        mx = jnp.max(jnp.where(scoreable, scores, jnp.iinfo(jnp.int32).min))
        mn = jnp.min(jnp.where(scoreable, scores, _BIG))
        any_scoreable = jnp.any(scoreable)
        mx = jnp.where(any_scoreable, mx, 0)
        mn = jnp.where(any_scoreable, mn, 0)
        norm = jnp.where(
            mx == 0,
            MAX_NODE_SCORE,
            (MAX_NODE_SCORE * (mx + mn - scores)) // jnp.maximum(mx, 1),
        )
        out = jnp.where(ignored, 0, norm)
        # PreScore Skip: no ScheduleAnyway constraints -> no contribution.
        return jnp.where(has_con, out, 0).astype(jnp.int32)
