"""NodeName filter plugin.

Upstream kube-scheduler v1.30 ``plugins/nodename/node_name.go``: a pod
naming a specific node in ``spec.nodeName`` fails every other node with
``node(s) didn't match the requested node name``; pods without a request
pass everywhere.  Encoding: state/extras.py (requested node index, -2 for
a name not in the snapshot).
"""

from __future__ import annotations

import jax.numpy as jnp

from ksim_tpu.plugins.base import FilterOutput, NodeStateView, PodView

NAME = "NodeName"
ERR_REASON = "node(s) didn't match the requested node name"


class NodeName:
    # Static reason-bit width: result tensors downcast when every
    # filter plugin's bits fit a narrower dtype (engine/core.py).
    reason_bit_width = 1
    name = NAME

    def static_sig(self) -> tuple:
        return (NAME,)

    def failure_unresolvable(self, bits: int) -> bool:
        # Upstream returns UnschedulableAndUnresolvable.
        return True

    def filter(self, state: NodeStateView, pod: PodView, aux) -> FilterOutput:
        req = aux["nodename"]["pod_req_node"][pod.index]  # scalar
        n = state.valid.shape[0]
        ok = (req == -1) | (jnp.arange(n) == req)
        return FilterOutput(ok=ok, reason_bits=jnp.where(ok, 0, 1).astype(jnp.int32))

    def decode_reasons(self, bits: int) -> list[str]:
        return [ERR_REASON] if bits else []
