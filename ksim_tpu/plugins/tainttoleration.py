"""TaintToleration filter + score kernels.

Upstream kube-scheduler v1.30 ``plugins/tainttoleration/taint_toleration.go``:

- Filter: the first taint with effect NoSchedule/NoExecute (in node taint
  order) not tolerated by the pod fails the node with
  ``node(s) had untolerated taint {<key>: <value>}``.
- Score: count of PreferNoSchedule taints not tolerated by the pod's
  tolerations with effect ""/PreferNoSchedule; normalized with
  DefaultNormalizeScore(MaxNodeScore, reverse=true).

Toleration matching runs host-side (state/encoding.py encode_taints);
the kernel works on the distinct-taint vocabulary: ``reason_bits`` holds
``w + 1`` of the first untolerated taint (0 == passed) so the exact
upstream message is reconstructable.
"""

from __future__ import annotations

import jax.numpy as jnp

from ksim_tpu.plugins.base import MAX_NODE_SCORE, FilterOutput, NodeStateView, PodView
from ksim_tpu.state.encoding import TaintTensors

NAME = "TaintToleration"
_BIG = jnp.iinfo(jnp.int32).max


def forbidding_taints_tolerated(aux, pod: PodView) -> jnp.ndarray:
    """bool [N]: no untolerated NoSchedule/NoExecute taint — the predicate
    PodTopologySpread's Honor nodeTaintsPolicy consults."""
    a = aux["taints"]
    order = a["node_taint_order"]
    tolerated = a["pod_tolerated"][pod.index]
    bad = (order > 0) & a["forbidding"][None, :] & ~tolerated[None, :]
    return ~jnp.any(bad, axis=1)


class TaintToleration:
    final_score_bound = 100  # post-normalize max (MaxNodeScore)
    name = NAME

    def __init__(self, taints: TaintTensors) -> None:
        self._taints = taints  # host-side vocab for decode
        # The reason is a 1-based INDEX into the taint vocabulary (not a
        # bit mask), so the width the engine's dtype downcast may rely on
        # is the vocabulary size's bit length (engine/core.py).
        self.reason_bit_width = (taints.n_taints + 1).bit_length()

    def static_sig(self) -> tuple:
        return (NAME,)  # the vocab only feeds host-side decode

    def failure_unresolvable(self, bits: int) -> bool:
        # Upstream returns UnschedulableAndUnresolvable for untolerated
        # NoSchedule/NoExecute taints.
        return True

    def filter(self, state: NodeStateView, pod: PodView, aux) -> FilterOutput:
        a = aux["taints"]
        order = a["node_taint_order"]  # [N, W]
        tolerated = a["pod_tolerated"][pod.index]  # [W]
        bad = (order > 0) & a["forbidding"][None, :] & ~tolerated[None, :]
        first = jnp.min(jnp.where(bad, order, _BIG), axis=1)  # [N]
        blocked = first != _BIG
        # Recover which taint vocab index sits at that position.
        w_idx = jnp.argmax(
            (order == first[:, None]) & bad, axis=1
        ).astype(jnp.int32)
        reason = jnp.where(blocked, w_idx + 1, 0).astype(jnp.int32)
        return FilterOutput(ok=~blocked, reason_bits=reason)

    def decode_reasons(self, bits: int) -> list[str]:
        if bits == 0:
            return []
        t = self._taints.taints[bits - 1]
        return [f"node(s) had untolerated taint {{{t['key']}: {t['value']}}}"]

    def score(self, state: NodeStateView, pod: PodView, aux, ok=None) -> jnp.ndarray:
        a = aux["taints"]
        order = a["node_taint_order"]
        tolerated = a["pod_tolerated_prefer"][pod.index]
        intolerable = (order > 0) & a["prefer"][None, :] & ~tolerated[None, :]
        return intolerable.sum(axis=1).astype(jnp.int32)

    def normalize(self, scores: jnp.ndarray, ok: jnp.ndarray) -> jnp.ndarray:
        """DefaultNormalizeScore(MaxNodeScore, reverse=True) over feasible
        nodes (upstream normalizes the scored-node list only)."""
        mx = jnp.max(jnp.where(ok, scores, 0))
        scaled = (MAX_NODE_SCORE * scores) // jnp.maximum(mx, 1)
        return jnp.where(mx > 0, MAX_NODE_SCORE - scaled, MAX_NODE_SCORE).astype(
            jnp.int32
        )
