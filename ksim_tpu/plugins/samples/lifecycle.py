"""Sample out-of-tree lifecycle plugins: PostBind export, custom
QueueSort, and a PreEnqueue gate.

The reference fork's own sample is a PostBind plugin that POSTs every
placement to hardcoded third-party URLs from inside the scheduling path
(reference simulator/pkg/nodenumber/plugin.go:98-114 — SURVEY.md flags
the URLs as fork-specific cruft).  ``PlacementExport`` keeps the
*capability* — observe every (pod, node) bind from an out-of-tree
plugin — with a pluggable sink instead: a callable, or an append-JSONL
path from plugin args (ship it wherever you like OUTSIDE the hot path).

``FifoSort`` demonstrates a custom QueueSort replacing PrioritySort
(the reference wraps custom QueueSort plugins, wrappedplugin.go:750-765)
and ``NamePrefixGate`` a PreEnqueue gate (wrappedplugin.go:376).  All
three register through ``builderImport`` / the Builder registry like
any out-of-tree plugin (scheduler/profile.py load_plugin_import).
"""

from __future__ import annotations

import json
import threading
from typing import Callable

from ksim_tpu.engine.core import ScoredPlugin
from ksim_tpu.state.resources import JSON, name_of, namespace_of


class PlacementExport:
    """PostBind observer: ``sink`` receives {"pod": ns/name, "node": n}
    per successful bind.  With ``sink_path`` the records append to a
    JSONL file (one bind per line) under a lock."""

    name = "PlacementExport"

    def __init__(
        self,
        sink: Callable[[dict], None] | None = None,
        sink_path: str | None = None,
    ) -> None:
        self._sink = sink
        self._path = sink_path
        self._lock = threading.Lock()

    def post_bind(self, pod: JSON, node_name: str) -> None:
        rec = {
            "pod": f"{namespace_of(pod)}/{name_of(pod)}",
            "node": node_name,
        }
        if self._sink is not None:
            self._sink(rec)
        if self._path:
            with self._lock, open(self._path, "a") as f:
                f.write(json.dumps(rec, sort_keys=True) + "\n")


def _build_placement_export(feats, args):
    plug = PlacementExport(
        sink=args.get("sink") if callable(args.get("sink")) else None,
        sink_path=args.get("sinkPath"),
    )
    return ScoredPlugin(plug, filter_enabled=False, score_enabled=False)


PLACEMENT_EXPORT_PLUGIN = {"builder": _build_placement_export}


# -- custom QueueSort --------------------------------------------------------


def _fifo_key(pod: JSON, priority_of=None):
    """Strict FIFO: creation time, then name — priority ignored (the
    point: observably different from PrioritySort)."""
    return (
        pod.get("metadata", {}).get("creationTimestamp") or "",
        namespace_of(pod),
        name_of(pod),
    )


def _build_fifo(feats, args):
    class _FifoMarker:
        name = "FifoSort"

    return ScoredPlugin(_FifoMarker(), filter_enabled=False, score_enabled=False)


FIFO_SORT_PLUGIN = {"builder": _build_fifo, "queue_sort_key": _fifo_key}


# -- PreEnqueue gate ---------------------------------------------------------


GATE_PREFIX = "hold-"


def _name_prefix_gate(pod: JSON) -> str | None:
    """Pods named ``hold-*`` never enter the queue (stand-in for a real
    readiness/dependency gate)."""
    if name_of(pod).startswith(GATE_PREFIX):
        return f"pod name carries the {GATE_PREFIX!r} hold prefix"
    return None


def _build_gate(feats, args):
    class _GateMarker:
        name = "NamePrefixGate"

    return ScoredPlugin(_GateMarker(), filter_enabled=False, score_enabled=False)


NAME_PREFIX_GATE_PLUGIN = {"builder": _build_gate, "pre_enqueue": _name_prefix_gate}
