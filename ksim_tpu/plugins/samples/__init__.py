"""Sample out-of-tree plugins (the reference's pkg/nodenumber analogue)."""

from ksim_tpu.plugins.samples.nodenumber import (
    DataProviderScore,
    NodeNumber,
    data_provider_builder,
    encode_node_number,
    node_number_builder,
    provider_encoder,
)

__all__ = [
    "DataProviderScore",
    "NodeNumber",
    "data_provider_builder",
    "encode_node_number",
    "node_number_builder",
    "provider_encoder",
]
