"""NodeNumber sample plugin + the external-data-provider capability.

Two demonstrations of out-of-tree score plugins (reference
simulator/pkg/nodenumber — the UPSTREAM-ORIGINAL semantics kept at
simulator/docs/sample/nodenumber/plugin.go:1-149: score 10 when the pod
name's trailing digit equals the node name's trailing digit, optional
``reverse``; pods/nodes without a digit suffix score 0):

- ``NodeNumber``: the suffix-digit scorer as a batched kernel — suffix
  extraction happens host-side at featurize time (encode_node_number),
  the kernel is one equality compare.
- ``DataProviderScore``: the fork's "renewable-energy-aware" idea done
  right — a *capability*, not hardcoded third-party URLs (SURVEY.md
  fork-specific caution: the fork performs live HTTP calls inside the
  scoring hot path, simulator/pkg/nodenumber/plugin.go:98-138).  The
  provider is any callable ``nodes -> per-node score array``; it runs
  ONCE per featurization on the host (fetch your API there if you like),
  and the kernel just reads the resulting tensor.

Both register through the out-of-tree Builder registry
(scheduler/profile.py) — the WithPlugin analogue."""

from __future__ import annotations

from dataclasses import dataclass
from typing import Callable, Sequence

import jax.numpy as jnp
import numpy as np

from ksim_tpu.engine.core import ScoredPlugin
from ksim_tpu.plugins.base import NodeStateView, PodView
from ksim_tpu.state.resources import JSON, name_of

NAME = "NodeNumber"


def _suffix_digit(name: str) -> int:
    return int(name[-1]) if name and name[-1].isdigit() else -1


@dataclass
class NodeNumberTensors:
    """Trailing-digit codes (-1 = no digit suffix)."""

    AXES = {"node_digit": "node", "pod_digit": "pod"}

    node_digit: np.ndarray  # i32 [N]
    pod_digit: np.ndarray  # i32 [P]


def encode_node_number(
    nodes: Sequence[JSON], pods: Sequence[JSON], n_padded: int, p_padded: int
) -> NodeNumberTensors:
    nd = np.full(n_padded, -1, dtype=np.int32)
    pd = np.full(p_padded, -1, dtype=np.int32)
    for i, n in enumerate(nodes):
        nd[i] = _suffix_digit(name_of(n))
    for j, p in enumerate(pods):
        pd[j] = _suffix_digit(name_of(p))
    return NodeNumberTensors(node_digit=nd, pod_digit=pd)


class NodeNumber:
    """Score 10 on suffix-digit match (0 otherwise; reversed if asked)."""

    name = NAME

    def __init__(self, tensors: NodeNumberTensors, *, reverse: bool = False) -> None:
        del tensors  # flows through aux
        self._reverse = reverse

    def static_sig(self) -> tuple:
        return (NAME, self._reverse)

    def score(self, state: NodeStateView, pod: PodView, aux, ok=None) -> jnp.ndarray:
        a = aux["nodenumber"]
        pod_digit = a["pod_digit"][pod.index]
        match = (a["node_digit"] == pod_digit) & (pod_digit >= 0) & (
            a["node_digit"] >= 0
        )
        hit, miss = (0, 10) if self._reverse else (10, 0)
        return jnp.where(match, hit, miss).astype(jnp.int32)


# nodes -> float/int array of per-node scores (any external data source;
# called host-side, once per featurization).
DataProvider = Callable[[Sequence[JSON]], np.ndarray]


@dataclass
class ProvidedTensors:
    AXES = {"provided_score": "node"}

    provided_score: np.ndarray  # i32 [N]


class DataProviderScore:
    """Score nodes by an externally-provided per-node value."""

    def __init__(self, name: str, tensors: ProvidedTensors) -> None:
        self.name = name
        del tensors  # flows through aux

    def static_sig(self) -> tuple:
        return ("DataProviderScore", self.name)

    def score(self, state: NodeStateView, pod: PodView, aux, ok=None) -> jnp.ndarray:
        return aux[f"provider:{self.name}"]["provided_score"].astype(jnp.int32)


def node_number_builder(*, reverse: bool = False, weight: int = 1):
    """Out-of-tree Builder for the profile registry:
    ``registry={"NodeNumber": node_number_builder()}`` — the reference's
    ``debuggablescheduler.WithPlugin`` analogue.  Registers its encoder
    through the featurizer's extra-encoder hook."""

    def build(feats, args):
        return ScoredPlugin(
            NodeNumber(feats.aux["nodenumber"], reverse=bool(
                (args or {}).get("reverse", reverse))),
            weight=weight,
            filter_enabled=False,
        )

    return build


def provider_encoder(provider: DataProvider):
    """Featurizer extra-encoder wrapping a data provider: the provider
    runs here, host-side, once per featurization."""

    def encode(nodes, pods, n_padded, p_padded) -> ProvidedTensors:
        values = np.asarray(provider(nodes))
        out = np.zeros(n_padded, dtype=np.int32)
        out[: len(values)] = values.astype(np.int32)
        return ProvidedTensors(provided_score=out)

    return encode


def data_provider_builder(name: str, provider: DataProvider, *, weight: int = 1):
    """Out-of-tree Builder wiring an external data source into a score
    plugin (the capability the fork's renewable-energy scorer needed)."""

    def build(feats, args):
        return ScoredPlugin(
            DataProviderScore(name, feats.aux[f"provider:{name}"]),
            weight=weight,
            filter_enabled=False,
        )

    return build


# Ready-made config-plugin import target: enable NodeNumber purely from a
# KubeSchedulerConfiguration (no code changes to the scheduler binary),
# the reference's wasm-plugin capability (scheduler/config/wasm.go:14-58):
#
#   pluginConfig:
#     - name: NodeNumber
#       args:
#         builderImport: "ksim_tpu.plugins.samples.nodenumber:NODE_NUMBER_PLUGIN"
NODE_NUMBER_PLUGIN = {
    "builder": node_number_builder(),
    "extra_encoders": {"nodenumber": encode_node_number},
}
