"""NodeResourcesFit and NodeResourcesBalancedAllocation batched kernels.

Semantics mirror upstream kube-scheduler v1.30 (the version the reference
pins, simulator/go.mod):

- Fit filter: ``pkg/scheduler/framework/plugins/noderesources/fit.go``
  fitsRequest — "Too many pods" first, then per-resource
  ``podRequest > allocatable - requested`` checks; base resources
  (cpu/memory/ephemeral-storage) are always checked once the pod requests
  anything at all, extended resources only when the pod requests them.
- LeastAllocated score: ``noderesources/least_allocated.go``
  leastResourceScorer — per-resource ``(c - r) * 100 // c`` (0 when the
  resource is overcommitted), weight-averaged with integer division,
  skipping zero-allocatable resources; ``r`` uses the *non-zero* request
  accumulation (resource_allocation.go calculatePodResourceRequest).
- MostAllocated score: ``noderesources/most_allocated.go``
  mostResourceScorer — per-resource ``min(r, c) * 100 // c`` (requests
  above capacity clamp to capacity), same weighted integer average.
- RequestedToCapacityRatio score:
  ``noderesources/requested_to_capacity_ratio.go`` — utilization
  ``r * 100 // c`` (overcommit or zero capacity evaluate at 100) fed
  through the broken-linear shape function (helper/shape_score.go
  BuildBrokenLinearFunction, Go truncating division), shape scores
  pre-scaled x10 (MaxNodeScore/MaxCustomPriorityScore); only resources
  with a POSITIVE score contribute to the weight sum, and the final
  weighted average uses math.Round (exact here via (2n + d) // (2d)).
  The simulator accepts all three strategies because the reference
  decodes any upstream config (simulator/config/config.go:275-291) and
  its tests exercise MostAllocated (config_test.go:30-56).
- BalancedAllocation score: ``noderesources/balanced_allocation.go``
  balancedResourceScorer — fractions clamped to 1, two-resource case is
  ``std = |f1 - f2| / 2``, score ``int64((1 - std) * 100)``.

Integer exactness: with x64 enabled the balanced score is computed as an
exact rational floor in int64 (``100 - ceil(50*|r1*c2 - r2*c1| / (c1*c2))``),
which equals Go's float64 result except within ~1e-13 of integer
boundaries; without x64 a float32 path with a +1e-4 floor nudge is used
(documented tolerance, not bit-exact).  Fit/LeastAllocated are pure int32
and bit-exact given the featurizer's gcd unit scaling.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from ksim_tpu.plugins.base import MAX_NODE_SCORE, FilterOutput, NodeStateView, PodView
from ksim_tpu.state.resources import BASE_RESOURCES

# Reason-bit layout for Fit: bit 0 = "Too many pods", bit 1+r = resource r.
TOO_MANY_PODS_BIT = 0
RESOURCE_BIT_BASE = 1
MAX_RESOURCE_BITS = 30

FIT_NAME = "NodeResourcesFit"
BALANCED_NAME = "NodeResourcesBalancedAllocation"


def _x64() -> bool:
    return jax.config.jax_enable_x64


class NodeResourcesFit:
    """Filter + scoring strategy (upstream defaults: LeastAllocated over
    cpu=1, memory=1; MostAllocated and RequestedToCapacityRatio are the
    other two upstream strategies)."""

    name = FIT_NAME

    def __init__(
        self,
        resources: tuple[str, ...],
        *,
        score_resources: tuple[tuple[str, int], ...] = (("cpu", 1), ("memory", 1)),
        base_resource_count: int = len(BASE_RESOURCES),
        strategy: str = "LeastAllocated",
        shape: tuple[tuple[int, int], ...] = (),
    ) -> None:
        if strategy not in ("LeastAllocated", "MostAllocated", "RequestedToCapacityRatio"):
            raise ValueError(f"unknown NodeResourcesFit scoring strategy {strategy!r}")
        if strategy == "RequestedToCapacityRatio":
            if not shape:
                raise ValueError(
                    "RequestedToCapacityRatio requires a non-empty shape "
                    "(upstream validation: at least one UtilizationShapePoint)"
                )
            utils = [u for u, _ in shape]
            if utils != sorted(set(utils)):
                raise ValueError(
                    "RequestedToCapacityRatio shape utilization must be "
                    "strictly increasing (upstream validation)"
                )
        self._resources = resources
        self._base_count = min(base_resource_count, len(resources))
        self._strategy = strategy
        # Shape scores arrive 0..10 and scale x10 to MaxNodeScore
        # (upstream requestedToCapacityRatioScorer).
        self._shape = tuple((int(u), int(s) * 10) for u, s in shape)
        idx = {r: i for i, r in enumerate(resources)}
        self._score_spec = tuple(
            (idx[r], w) for r, w in score_resources if r in idx
        )
        # Bit 0 = "Too many pods", bit 1+r per resource (capped): the
        # engine downcasts result tensors when all widths fit (core.py).
        self.reason_bit_width = 1 + min(len(resources), MAX_RESOURCE_BITS)
        self.final_score_bound = 100  # all strategies are 0..MaxNodeScore

    def static_sig(self) -> tuple:
        return (FIT_NAME, self._base_count, self._score_spec, self._strategy, self._shape)

    def failure_unresolvable(self, bits: int) -> bool:
        # Upstream returns Unschedulable: preempting pods frees resources.
        return False

    # -- filter -------------------------------------------------------------

    def filter(self, state: NodeStateView, pod: PodView, aux=None) -> FilterOutput:
        free = state.allocatable - state.requested  # [N, R]
        podr = pod.requests  # [R]
        r_axis = jnp.arange(podr.shape[0])
        checked = (r_axis < self._base_count) | (podr > 0)  # [R]
        # Upstream fitsRequest early-exits only when cpu/memory/ephemeral
        # are all zero AND no scalar-resource key exists (even zero-valued
        # keys defeat the early return) — the featurizer computes that
        # predicate host-side (PodView.has_requests).
        insufficient = checked[None, :] & (podr[None, :] > free) & pod.has_requests
        too_many = state.pod_count + 1 > state.allowed_pods  # [N]

        shift = jnp.minimum(r_axis + RESOURCE_BIT_BASE, MAX_RESOURCE_BITS)
        # Bits are disjoint per resource, so sum == bitwise-or.  Resources
        # past MAX_RESOURCE_BITS share a saturated bit; or them first.
        res_bits = jnp.where(insufficient, (1 << shift)[None, :], 0).astype(jnp.int32)
        or_reduced = jax.lax.reduce(
            res_bits, jnp.zeros((), res_bits.dtype), jax.lax.bitwise_or, (1,)
        )
        bits = or_reduced | jnp.where(
            too_many, 1 << TOO_MANY_PODS_BIT, 0
        ).astype(or_reduced.dtype)
        bits = bits.astype(jnp.int32)
        return FilterOutput(ok=bits == 0, reason_bits=bits)

    def decode_reasons(self, bits: int) -> list[str]:
        """Reason bitmask -> upstream status reasons, in upstream order."""
        out = []
        if bits & (1 << TOO_MANY_PODS_BIT):
            out.append("Too many pods")
        for i, r in enumerate(self._resources):
            if bits & (1 << min(i + RESOURCE_BIT_BASE, MAX_RESOURCE_BITS)):
                out.append(f"Insufficient {r}")
        return out

    # -- score (strategy dispatch) -------------------------------------------

    def score(self, state: NodeStateView, pod: PodView, aux=None, ok=None) -> jnp.ndarray:
        req = state.nonzero_requested + pod.nonzero_requests[None, :]  # [N, R]
        if self._strategy == "RequestedToCapacityRatio":
            return self._score_rtcr(state, req)
        node_score = jnp.zeros(state.pod_count.shape[0], dtype=jnp.int32)
        weight_sum = jnp.zeros_like(node_score)
        most = self._strategy == "MostAllocated"
        for ri, w in self._score_spec:
            c = state.allocatable[:, ri]
            r = req[:, ri]
            has = c > 0
            if most:
                # mostRequestedScore: min(r, c) * 100 // c.
                s = jnp.where(
                    has, (jnp.minimum(r, c) * MAX_NODE_SCORE) // jnp.maximum(c, 1), 0
                )
            else:
                # leastRequestedScore: (c - r) * 100 // c, 0 when overcommitted.
                s = jnp.where(
                    has & (r <= c), ((c - r) * MAX_NODE_SCORE) // jnp.maximum(c, 1), 0
                )
            node_score = node_score + s.astype(jnp.int32) * w
            weight_sum = weight_sum + jnp.where(has, w, 0)
        return jnp.where(weight_sum > 0, node_score // jnp.maximum(weight_sum, 1), 0)

    def _score_rtcr(self, state: NodeStateView, req: jnp.ndarray) -> jnp.ndarray:
        """requested_to_capacity_ratio.go: broken-linear over integer
        utilization; zero-capacity/overcommit evaluate at maxUtilization;
        only positive per-resource scores count toward the weight sum;
        final average is math.Round (exact integer (2n + d) // (2d))."""
        node_score = jnp.zeros(state.pod_count.shape[0], dtype=jnp.int32)
        weight_sum = jnp.zeros_like(node_score)
        for ri, w in self._score_spec:
            c = state.allocatable[:, ri]
            r = req[:, ri]
            has = c > 0
            util = jnp.where(
                has & (r <= c),
                (r * MAX_NODE_SCORE) // jnp.maximum(c, 1),
                MAX_NODE_SCORE,
            )
            s = self._broken_linear(util)
            # allocable==0 resources are skipped entirely; zero scores are
            # computed but excluded from the weight sum (upstream quirk).
            counts = has & (s > 0)
            node_score = node_score + jnp.where(counts, s, 0).astype(jnp.int32) * w
            weight_sum = weight_sum + jnp.where(counts, w, 0)
        d = jnp.maximum(weight_sum, 1)
        rounded = (2 * node_score + d) // (2 * d)
        return jnp.where(weight_sum > 0, rounded, 0)

    def _broken_linear(self, p: jnp.ndarray) -> jnp.ndarray:
        """helper/shape_score.go BuildBrokenLinearFunction with Go's
        truncating integer division (segment slopes may be negative, where
        floor and trunc differ), unrolled over the static shape."""
        shape = self._shape
        res = jnp.full_like(p, shape[-1][1])
        for i in range(len(shape) - 1, -1, -1):
            u_i, s_i = shape[i]
            if i == 0:
                expr = jnp.full_like(p, s_i)
            else:
                u_p, s_p = shape[i - 1]
                num = (s_i - s_p) * (p - u_p)
                den = u_i - u_p
                q = jnp.where(num >= 0, num // den, -((-num) // den))
                expr = s_p + q
            res = jnp.where(p <= u_i, expr, res)
        return res


class NodeResourcesBalancedAllocation:
    """Balanced-allocation score (upstream defaults: cpu, memory)."""
    final_score_bound = 100  # post-normalize max (MaxNodeScore)

    name = BALANCED_NAME

    def __init__(
        self,
        resources: tuple[str, ...],
        *,
        score_resources: tuple[str, ...] = ("cpu", "memory"),
    ) -> None:
        idx = {r: i for i, r in enumerate(resources)}
        self._spec = tuple(idx[r] for r in score_resources if r in idx)

    def static_sig(self) -> tuple:
        return (BALANCED_NAME, self._spec)

    def filter(self, state: NodeStateView, pod: PodView, aux=None) -> FilterOutput:
        n = state.pod_count.shape[0]
        ok = jnp.ones(n, dtype=bool)
        return FilterOutput(ok=ok, reason_bits=jnp.zeros(n, dtype=jnp.int32))

    def score(self, state: NodeStateView, pod: PodView, aux=None, ok=None) -> jnp.ndarray:
        req = state.nonzero_requested + pod.nonzero_requests[None, :]
        if len(self._spec) == 2 and _x64():
            return self._score_exact2(state, req)
        return self._score_float(state, req)

    def _score_exact2(self, state: NodeStateView, req: jnp.ndarray) -> jnp.ndarray:
        """Exact rational floor for the two-resource case, int64."""
        i1, i2 = self._spec
        c1 = state.allocatable[:, i1].astype(jnp.int64)
        c2 = state.allocatable[:, i2].astype(jnp.int64)
        r1 = jnp.minimum(req[:, i1].astype(jnp.int64), c1)
        r2 = jnp.minimum(req[:, i2].astype(jnp.int64), c2)
        both = (c1 > 0) & (c2 > 0)
        # Skip zero-allocatable resources (upstream `continue`): with fewer
        # than two fractions std == 0 and the score is exactly 100.
        n = jnp.abs(r1 * c2 - r2 * c1) * 50
        d = jnp.maximum(c1 * c2, 1)
        score = MAX_NODE_SCORE - (n + d - 1) // d
        return jnp.where(both, score, MAX_NODE_SCORE).astype(jnp.int32)

    def _score_float(self, state: NodeStateView, req: jnp.ndarray) -> jnp.ndarray:
        fracs = []
        present = []
        for ri in self._spec:
            c = state.allocatable[:, ri].astype(jnp.float32)
            r = req[:, ri].astype(jnp.float32)
            f = jnp.minimum(jnp.where(c > 0, r / jnp.maximum(c, 1.0), 0.0), 1.0)
            fracs.append(f)
            present.append(c > 0)
        f_mat = jnp.stack(fracs, axis=0)  # [S, N]
        p_mat = jnp.stack(present, axis=0)
        count = p_mat.sum(axis=0).astype(jnp.float32)  # [N]
        safe_count = jnp.maximum(count, 1.0)
        mean = jnp.where(p_mat, f_mat, 0.0).sum(axis=0) / safe_count
        var = (jnp.where(p_mat, (f_mat - mean[None, :]) ** 2, 0.0)).sum(axis=0) / safe_count
        # Upstream's two-fraction special case |f1 - f2| / 2 equals
        # sqrt(variance) for two points, so sqrt(var) covers all counts.
        std = jnp.where(count >= 2, jnp.sqrt(var), 0.0)
        # +1e-4 nudge: floor() of a float32 value that is exactly integral
        # in exact arithmetic can otherwise land one below.
        score = jnp.floor((1.0 - std) * MAX_NODE_SCORE + 1e-4)
        return score.astype(jnp.int32)
