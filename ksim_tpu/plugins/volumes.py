"""Volume-family filter kernels: VolumeBinding, VolumeZone,
NodeVolumeLimits, VolumeRestrictions.

Upstream kube-scheduler v1.30 semantics over the snapshot model's
pvs/pvcs/storageClasses (encoding + documented simplifications in
state/volumes.py).  All four are filter-only in the default profile
(VolumeBinding's capacity score is gated behind an alpha feature).
Every per-pod check is a ``[N, X] x [X]`` matvec over the factored
volume tensors; the attach/usage state mutated by scheduling rides the
scan carries with the same elementwise outer-product commit as the other
carried plugins.
"""

from __future__ import annotations

import jax.numpy as jnp

from ksim_tpu.plugins.base import FilterOutput, NodeStateView, PodView
from ksim_tpu.state.volumes import VolumeTensors

VOLUME_BINDING = "VolumeBinding"
VOLUME_ZONE = "VolumeZone"
NODE_VOLUME_LIMITS = "NodeVolumeLimits"
VOLUME_RESTRICTIONS = "VolumeRestrictions"

# VolumeBinding (volume_binding.go / binder.go)
ERR_UNBOUND_IMMEDIATE = "pod has unbound immediate PersistentVolumeClaims"
ERR_PVC_NOT_FOUND = "persistentvolumeclaim not found"
ERR_NODE_CONFLICT = "node(s) had volume node affinity conflict"
ERR_BIND_CONFLICT = "node(s) didn't find available persistent volumes to bind"
UNBOUND_IMMEDIATE_BIT = 1
PVC_MISSING_BIT = 2
NODE_CONFLICT_BIT = 4
BIND_CONFLICT_BIT = 8

# VolumeZone (volume_zone.go)
ERR_ZONE_CONFLICT = "node(s) had no available volume zone"

# NodeVolumeLimits (nodevolumelimits csi.go/non_csi.go)
ERR_MAX_VOLUME_COUNT = "node(s) exceed max volume count"

# VolumeRestrictions (volume_restrictions.go)
ERR_DISK_CONFLICT = "node(s) had no available disk"
ERR_RWOP_CONFLICT = (
    "node has pod using PersistentVolumeClaim with the same name and "
    "ReadWriteOncePod access mode"
)
DISK_CONFLICT_BIT = 1
RWOP_CONFLICT_BIT = 2


def _dot_bool(mat: jnp.ndarray, vec: jnp.ndarray) -> jnp.ndarray:
    """(mat[X, N] or [N, X]) boolean hit-count against vec[X] -> i32."""
    return jnp.dot(mat.astype(jnp.int32), vec.astype(jnp.int32))


class VolumeBinding:
    # Static reason-bit width: result tensors downcast when every
    # filter plugin's bits fit a narrower dtype (engine/core.py).
    reason_bit_width = 4
    name = VOLUME_BINDING

    def __init__(self, vt: VolumeTensors) -> None:
        del vt

    def static_sig(self) -> tuple:
        return (VOLUME_BINDING,)

    def failure_unresolvable(self, bits: int) -> bool:
        return True  # upstream: all UnschedulableAndUnresolvable

    def filter(self, state: NodeStateView, pod: PodView, aux) -> FilterOutput:
        a = aux["volumes"]
        j = pod.index
        n = state.valid.shape[0]
        i32 = jnp.int32
        pod_level = a["pod_fail"][j]  # bitmask: 1 unbound-immediate | 2 missing
        # Bound PVs whose node affinity rejects the node.
        node_conf = _dot_bool(~a["pv_node_ok"].T, a["pod_pv"][j]) > 0  # [N]
        # WFFC claims with neither a candidate PV on the node nor dynamic
        # provisioning.
        unsat = ~(a["pvc_cand_ok"] | a["pvc_provisionable"][:, None])  # [C, N]
        bind_conf = _dot_bool(unsat.T, a["pod_wffc"][j]) > 0  # [N]
        # pod_fail's bit layout matches UNBOUND_IMMEDIATE_BIT/PVC_MISSING_BIT.
        pod_bits = pod_level
        code = (
            jnp.broadcast_to(pod_bits, (n,))
            + jnp.where(node_conf, NODE_CONFLICT_BIT, 0)
            + jnp.where(bind_conf, BIND_CONFLICT_BIT, 0)
        ).astype(i32)
        return FilterOutput(ok=code == 0, reason_bits=code)

    def decode_reasons(self, bits: int) -> list[str]:
        out = []
        if bits & UNBOUND_IMMEDIATE_BIT:
            out.append(ERR_UNBOUND_IMMEDIATE)
        if bits & PVC_MISSING_BIT:
            out.append(ERR_PVC_NOT_FOUND)
        if bits & NODE_CONFLICT_BIT:
            out.append(ERR_NODE_CONFLICT)
        if bits & BIND_CONFLICT_BIT:
            out.append(ERR_BIND_CONFLICT)
        return out


class VolumeZone:
    # Static reason-bit width: result tensors downcast when every
    # filter plugin's bits fit a narrower dtype (engine/core.py).
    reason_bit_width = 1
    name = VOLUME_ZONE

    def __init__(self, vt: VolumeTensors) -> None:
        del vt

    def static_sig(self) -> tuple:
        return (VOLUME_ZONE,)

    def failure_unresolvable(self, bits: int) -> bool:
        return True  # upstream: UnschedulableAndUnresolvable

    def filter(self, state: NodeStateView, pod: PodView, aux) -> FilterOutput:
        a = aux["volumes"]
        j = pod.index
        conflict = _dot_bool(~a["pv_zone_ok"].T, a["pod_pv"][j]) > 0
        return FilterOutput(
            ok=~conflict, reason_bits=jnp.where(conflict, 1, 0).astype(jnp.int32)
        )

    def decode_reasons(self, bits: int) -> list[str]:
        return [ERR_ZONE_CONFLICT] if bits else []


class NodeVolumeLimits:
    """Attach-limit filter over one or all attachable-volumes-* pools.

    ``NodeVolumeLimits`` covers every pool (upstream v1.30's CSI plugin
    counts migrated in-tree volumes too); the legacy registry names —
    EBSLimits, GCEPDLimits, AzureDiskLimits, CinderLimits (upstream
    nodevolumelimits/non_csi.go, carried by the reference's exported
    default config, simulator/snapshot/snapshot_test.go:1415) — are
    instances restricted to their one pool via ``pools``."""

    # Static reason-bit width: result tensors downcast when every
    # filter plugin's bits fit a narrower dtype (engine/core.py).
    reason_bit_width = 1

    def __init__(
        self,
        vt: VolumeTensors,
        *,
        name: str = NODE_VOLUME_LIMITS,
        pools: tuple[str, ...] | None = None,
    ) -> None:
        self.name = name
        self._pool_ids = tuple(
            k
            for k, pool in enumerate(vt.pool_names[: int(vt.n_pools)])
            if pools is None or pool in pools
        )

    def static_sig(self) -> tuple:
        return (NODE_VOLUME_LIMITS, self.name, self._pool_ids)

    def failure_unresolvable(self, bits: int) -> bool:
        return False  # evicting pods detaches volumes

    def carry_init(self, aux) -> jnp.ndarray:
        return aux["volumes"]["attached_init"]  # i32 [N, V]

    def carry_commit(self, carry, aux, pod: PodView, best) -> jnp.ndarray:
        uses = aux["volumes"]["pod_vol"][pod.index].astype(carry.dtype)  # [V]
        onehot = ((jnp.arange(carry.shape[0]) == best) & (best >= 0)).astype(
            carry.dtype
        )
        # Attachment is unique per (volume, node): saturate at 1.
        return jnp.maximum(carry, onehot[:, None] * uses[None, :])

    def filter(self, state: NodeStateView, pod: PodView, aux, carry) -> FilterOutput:
        a = aux["volumes"]
        j = pod.index
        attached = carry > 0  # [N, V]
        pod_vol = a["pod_vol"][j]  # [V]
        over = jnp.zeros(state.valid.shape[0], dtype=bool)
        for k in self._pool_ids:  # static unroll over this plugin's pools
            in_pool = a["vol_key"] == k  # [V]
            used = _dot_bool(attached, in_pool)  # [N]
            new = _dot_bool(~attached, pod_vol & in_pool)  # [N] dedup'd
            limit = a["limits"][:, k]
            over = over | ((limit >= 0) & (used + new > limit))
        return FilterOutput(
            ok=~over, reason_bits=jnp.where(over, 1, 0).astype(jnp.int32)
        )

    def decode_reasons(self, bits: int) -> list[str]:
        return [ERR_MAX_VOLUME_COUNT] if bits else []


class VolumeRestrictions:
    # Static reason-bit width: result tensors downcast when every
    # filter plugin's bits fit a narrower dtype (engine/core.py).
    reason_bit_width = 2
    name = VOLUME_RESTRICTIONS

    def __init__(self, vt: VolumeTensors) -> None:
        del vt

    def static_sig(self) -> tuple:
        return (VOLUME_RESTRICTIONS,)

    def failure_unresolvable(self, bits: int) -> bool:
        return False  # upstream: Unschedulable (preemptable)

    def carry_init(self, aux) -> dict:
        a = aux["volumes"]
        return {
            "rwop": a["rwop_init"],
            "disk_any": a["disk_any_init"],
            "disk_rw": a["disk_rw_init"],
        }

    def carry_commit(self, carry, aux, pod: PodView, best) -> dict:
        a = aux["volumes"]
        j = pod.index
        onehot = ((jnp.arange(carry["rwop"].shape[0]) == best) & (best >= 0)).astype(
            jnp.int32
        )

        def add(c, uses):
            return c + onehot[:, None] * uses.astype(jnp.int32)[None, :]

        return {
            "rwop": add(carry["rwop"], a["pod_rwop"][j]),
            "disk_any": add(carry["disk_any"], a["pod_disk_any"][j]),
            "disk_rw": add(carry["disk_rw"], a["pod_disk_rw"][j]),
        }

    def filter(self, state: NodeStateView, pod: PodView, aux, carry) -> FilterOutput:
        a = aux["volumes"]
        j = pod.index
        # ReadWriteOncePod: any other user of the claim on the node.
        rwop = _dot_bool(carry["rwop"] > 0, a["pod_rwop"][j]) > 0  # [N]
        # Disk conflicts (isVolumeConflict): EBS never shares; GCE/ISCSI/
        # RBD share only when BOTH uses are read-only.
        share = a["disk_ro_shareable"]
        pod_any = a["pod_disk_any"][j]
        pod_rw = a["pod_disk_rw"][j]
        any_used = carry["disk_any"] > 0
        rw_used = carry["disk_rw"] > 0
        disk = (
            (_dot_bool(any_used, pod_any & ~share) > 0)
            | (_dot_bool(any_used, pod_rw & share) > 0)
            | (_dot_bool(rw_used, pod_any & ~pod_rw & share) > 0)
        )
        code = jnp.where(disk, DISK_CONFLICT_BIT, 0) + jnp.where(
            rwop, RWOP_CONFLICT_BIT, 0
        )
        return FilterOutput(ok=code == 0, reason_bits=code.astype(jnp.int32))

    def decode_reasons(self, bits: int) -> list[str]:
        out = []
        if bits & DISK_CONFLICT_BIT:
            out.append(ERR_DISK_CONFLICT)
        if bits & RWOP_CONFLICT_BIT:
            out.append(ERR_RWOP_CONFLICT)
        return out
