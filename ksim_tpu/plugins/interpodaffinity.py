"""InterPodAffinity filter + score kernels.

Upstream kube-scheduler v1.30 ``plugins/interpodaffinity/{filtering,
scoring}.go`` (the reference records this plugin's per-node outcomes via its
wrapped-plugin layer, reference simulator/scheduler/plugin/
wrappedplugin.go:420-548):

- Filter: (1) every required affinity term must have a matching existing
  pod in the candidate node's topology domain — unless NO pod in the
  cluster matches any term and the pod matches its own terms (the
  first-pod-of-a-series escape); a node missing any term's topology key
  fails.  (2) No required anti-affinity term may have a matching pod in
  the domain.  (3) No existing pod's required anti-affinity term that
  matches the incoming pod may have presence in the domain.  First failing
  check wins (upstream Filter order).
- Score: topology-pair weights accumulated from (a) the incoming pod's
  preferred (anti-)affinity terms over matching existing pods (+w / -w)
  and (b) existing pods' terms matched against the incoming pod —
  required-affinity terms at HardPodAffinityWeight, preferred at +-w
  (scoring.go processExistingPod).  NormalizeScore is
  ``int(100 * (s - min) / (max - min))`` over feasible nodes, all zeros
  when max == min.

Tensorization: domain match counts are segment sums over the node axis
(one per (context, topologyKey) term, batched via a flattened segment id
space); each per-pod check is then a ``[N,T] x [T]`` matvec, which vmap
turns into ``[P,T] x [T,N]`` MXU matmuls.  The [N,T] count tensors depend
only on the scan carry, so XLA hoists them out of the vmapped pod batch.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from ksim_tpu.plugins.base import MAX_NODE_SCORE, FilterOutput, NodeStateView, PodView
from ksim_tpu.state.interpod import InterPodTensors

NAME = "InterPodAffinity"

ERR_REASON_AFFINITY_RULES_NOT_MATCH = "node(s) didn't match pod affinity rules"
ERR_REASON_ANTI_AFFINITY_RULES_NOT_MATCH = "node(s) didn't match pod anti-affinity rules"
ERR_REASON_EXISTING_ANTI_AFFINITY_RULES_NOT_MATCH = (
    "node(s) didn't satisfy existing pods' anti-affinity rules"
)

AFFINITY_BIT = 1
ANTI_BIT = 2
EXISTING_ANTI_BIT = 4


def _domain_counts(cols: jnp.ndarray, dom_t: jnp.ndarray, n_dom: int) -> jnp.ndarray:
    """Per-(node, term) domain totals: out[n,t] = sum over nodes n' in the
    same t-domain as n of cols[n',t]; 0 where the node lacks the key.

    One flattened segment_sum covers all T terms (term t's ids live in
    [t*(Dom+1), (t+1)*(Dom+1)); slot Dom collects the key-missing rows)."""
    t = cols.shape[1]
    ids = jnp.where(dom_t >= 0, dom_t, n_dom) + jnp.arange(t, dtype=dom_t.dtype)[None, :] * (
        n_dom + 1
    )
    flat = jax.ops.segment_sum(
        cols.reshape(-1), ids.reshape(-1), num_segments=t * (n_dom + 1)
    )
    out = flat[ids.reshape(-1)].reshape(cols.shape)
    return jnp.where(dom_t >= 0, out, 0)


class InterPodAffinity:
    name = NAME

    def __init__(self, ipa: InterPodTensors) -> None:
        self._dom = ipa.n_domains  # static for segment ops

    # -- carried state ------------------------------------------------------

    def carry_init(self, aux) -> dict:
        a = aux["interpod"]
        return {
            "match": a["match_counts"],
            "ranti": a["ranti_counts"],
            "ew": a["ew_counts"],
        }

    def carry_commit(self, carry, aux, pod: PodView, best) -> dict:
        a = aux["interpod"]
        j = pod.index
        n = carry["match"].shape[0]
        onehot = ((jnp.arange(n) == best) & (best >= 0)).astype(jnp.int32)
        return {
            "match": carry["match"] + onehot[:, None] * a["pod_ctx_match"][j].astype(jnp.int32),
            "ranti": carry["ranti"] + onehot[:, None] * a["pod_eat"][j],
            "ew": carry["ew"] + onehot[:, None] * a["pod_vw"][j],
        }

    # -- shared pod-independent tensors -------------------------------------

    def _shared(self, aux, carry):
        a = aux["interpod"]
        dom_t = jnp.take(a["node_dom"], a["term_tk"], axis=1)  # [N, T]
        mc_t = jnp.take(carry["match"], a["term_u"], axis=1)  # [N, T]
        cnt = _domain_counts(mc_t, dom_t, self._dom)  # [N, T]
        return a, dom_t, mc_t, cnt

    # -- filter -------------------------------------------------------------

    def filter(self, state: NodeStateView, pod: PodView, aux, carry) -> FilterOutput:
        a, dom_t, mc_t, cnt = self._shared(aux, carry)
        j = pod.index
        i32 = jnp.int32
        raff = a["req_aff"][j].astype(i32)  # [T]
        ranti = a["req_anti"][j].astype(i32)
        qm_t = jnp.take(a["pod_ctx_match"][j], a["term_u"]).astype(i32)  # [T]

        # (1) required affinity: all topology keys present AND every term's
        # domain count > 0 — or the global-empty + self-match escape.
        # Upstream keys affinityCounts by topologyPair (key, value) SHARED
        # across all of the pod's required terms (filtering.go
        # topologyToMatchedTermCount.update): two required terms with the
        # same topologyKey read one combined count, so a domain satisfying
        # either term satisfies both.  Aggregate this pod's per-term counts
        # over terms sharing a topology key before the <=0 check.
        missing_any = jnp.dot((dom_t < 0).astype(i32), raff) > 0  # [N]
        n_tk = a["node_dom"].shape[1]
        tk_onehot = (
            a["term_tk"][:, None] == jnp.arange(n_tk, dtype=a["term_tk"].dtype)[None, :]
        ).astype(i32)  # [T, TK]
        cnt_req = cnt * raff[None, :]  # this pod's required terms only
        key_cnt = cnt_req @ tk_onehot  # [N, TK] per-key totals
        need_key = (raff @ tk_onehot) > 0  # [TK] keys with required terms
        no_pods_any = jnp.any((key_cnt <= 0) & need_key[None, :], axis=1)
        total_t = jnp.sum(jnp.where(dom_t >= 0, mc_t, 0), axis=0)  # [T]
        escape = (jnp.dot(total_t, raff) == 0) & a["self_aff"][j]
        pass_aff = ~missing_any & (~no_pods_any | escape)
        # (2) incoming required anti-affinity (missing key = satisfied).
        viol_anti = jnp.dot((cnt > 0).astype(i32), ranti) > 0
        # (3) existing pods' required anti-affinity vs this pod.
        ecnt = _domain_counts(carry["ranti"], dom_t, self._dom)
        viol_existing = jnp.dot((ecnt > 0).astype(i32), qm_t) > 0

        code = jnp.where(
            ~pass_aff,
            AFFINITY_BIT,
            jnp.where(viol_anti, ANTI_BIT, jnp.where(viol_existing, EXISTING_ANTI_BIT, 0)),
        ).astype(i32)
        return FilterOutput(ok=code == 0, reason_bits=code)

    def decode_reasons(self, bits: int) -> list[str]:
        if bits & AFFINITY_BIT:
            return [ERR_REASON_AFFINITY_RULES_NOT_MATCH]
        if bits & ANTI_BIT:
            return [ERR_REASON_ANTI_AFFINITY_RULES_NOT_MATCH]
        if bits & EXISTING_ANTI_BIT:
            return [ERR_REASON_EXISTING_ANTI_AFFINITY_RULES_NOT_MATCH]
        return []

    # -- score --------------------------------------------------------------

    def score(self, state: NodeStateView, pod: PodView, aux, ok=None, carry=None) -> jnp.ndarray:
        a, dom_t, _mc_t, cnt = self._shared(aux, carry)
        j = pod.index
        ew_c = _domain_counts(carry["ew"], dom_t, self._dom)
        qm_t = jnp.take(a["pod_ctx_match"][j], a["term_u"]).astype(jnp.int32)
        return (jnp.dot(cnt, a["pref_w"][j]) + jnp.dot(ew_c, qm_t)).astype(jnp.int32)

    def normalize(self, scores: jnp.ndarray, ok: jnp.ndarray) -> jnp.ndarray:
        big = jnp.iinfo(jnp.int32).max
        any_ok = jnp.any(ok)
        mn = jnp.where(any_ok, jnp.min(jnp.where(ok, scores, big)), 0)
        mx = jnp.where(any_ok, jnp.max(jnp.where(ok, scores, -big - 1)), 0)
        diff = mx - mn
        # Go: fScore = float64(MaxNodeScore) * (float64(s-min)/float64(diff));
        # int64(fScore) truncates (values >= 0 -> floor).  Division first.
        # float64 under x64 (exact vs the float64 oracle/upstream); float32
        # on TPU (documented +-1 rounding tolerance at exact-integer ratio
        # boundaries, same caveat as PodTopologySpread.score).
        ftype = jnp.float64 if jax.config.jax_enable_x64 else jnp.float32
        ratio = (scores - mn).astype(ftype) / jnp.maximum(diff, 1).astype(ftype)
        val = jnp.floor(ftype(MAX_NODE_SCORE) * ratio)
        out = jnp.where(diff > 0, val, 0.0)
        return jnp.where(ok, out, 0).astype(jnp.int32)
