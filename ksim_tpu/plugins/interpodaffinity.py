"""InterPodAffinity filter + score kernels.

Upstream kube-scheduler v1.30 ``plugins/interpodaffinity/{filtering,
scoring}.go`` (the reference records this plugin's per-node outcomes via its
wrapped-plugin layer, reference simulator/scheduler/plugin/
wrappedplugin.go:420-548):

- Filter: (1) every required affinity term must have a matching existing
  pod in the candidate node's topology domain — unless NO pod in the
  cluster matches any term and the pod matches its own terms (the
  first-pod-of-a-series escape); a node missing any term's topology key
  fails.  (2) No required anti-affinity term may have a matching pod in
  the domain.  (3) No existing pod's required anti-affinity term that
  matches the incoming pod may have presence in the domain.  First failing
  check wins (upstream Filter order).
- Score: topology-pair weights accumulated from (a) the incoming pod's
  preferred (anti-)affinity terms over matching existing pods (+w / -w)
  and (b) existing pods' terms matched against the incoming pod —
  required-affinity terms at HardPodAffinityWeight, preferred at +-w
  (scoring.go processExistingPod).  NormalizeScore is
  ``int(100 * (s - min) / (max - min))`` over feasible nodes, all zeros
  when max == min.

Tensorization: the scan carry IS the per-node domain-count view
(state/interpod.py ``cnt_node``/``ecnt_node``/``ew_node`` [N,T] plus the
cluster-wide ``total`` [T]), so filter and score read it directly and
every per-pod check is a ``[N,T] x [T]`` matvec — vmapped over pods these
become ``[P,T] x [T,N]`` MXU matmuls.  Committing a pod is an elementwise
same-domain-mask outer-product add: the entire scan step contains no
gather, scatter, or segment reduction (each of those costs ~50us inside a
compiled TPU loop; elementwise [N,T] ops are effectively free).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from ksim_tpu.plugins.base import MAX_NODE_SCORE, FilterOutput, NodeStateView, PodView
from ksim_tpu.state.interpod import InterPodTensors

NAME = "InterPodAffinity"

ERR_REASON_AFFINITY_RULES_NOT_MATCH = "node(s) didn't match pod affinity rules"
ERR_REASON_ANTI_AFFINITY_RULES_NOT_MATCH = "node(s) didn't match pod anti-affinity rules"
ERR_REASON_EXISTING_ANTI_AFFINITY_RULES_NOT_MATCH = (
    "node(s) didn't satisfy existing pods' anti-affinity rules"
)

AFFINITY_BIT = 1
ANTI_BIT = 2
EXISTING_ANTI_BIT = 4


class InterPodAffinity:
    # Static reason-bit width: result tensors downcast when every
    # filter plugin's bits fit a narrower dtype (engine/core.py).
    reason_bit_width = 3
    final_score_bound = 100  # post-normalize max (MaxNodeScore)
    name = NAME

    def __init__(self, ipa: InterPodTensors) -> None:
        del ipa  # all state flows through aux/carry

    def static_sig(self) -> tuple:
        return (NAME,)

    def failure_unresolvable(self, bits: int) -> bool:
        # Upstream: unmatched required affinity is UnschedulableAndUnresolvable
        # (removing pods can't create matches); anti-affinity violations are
        # Unschedulable (victims can clear them).
        return bool(bits & AFFINITY_BIT)

    # -- carried state ------------------------------------------------------

    def carry_init(self, aux) -> dict:
        a = aux["interpod"]
        return {
            "cnt": a["cnt_node"],
            "ecnt": a["ecnt_node"],
            "ew": a["ew_node"],
            "total": a["total"],
        }

    def carry_commit(self, carry, aux, pod: PodView, best) -> dict:
        a = aux["interpod"]
        j = pod.index
        placed = best >= 0
        b = jnp.maximum(best, 0)
        # Per-term same-domain mask [N, T]: node n is in the placed node's
        # domain for term t's topology key — one elementwise compare
        # against the placed node's row of the precomputed per-term domain
        # view (no gather/scatter in the scan step).
        doms_t = a["dom_t"][b]  # [T] the placed node's domain per term
        key_present = (doms_t >= 0) & placed  # [T]
        mask_t = (
            (a["dom_t"] == doms_t[None, :]) & key_present[None, :]
        ).astype(jnp.int32)  # [N, T] 0/1
        qm_t = a["pod_term_match"][j].astype(jnp.int32)  # [T]
        return {
            "cnt": carry["cnt"] + mask_t * qm_t[None, :],
            "ecnt": carry["ecnt"] + mask_t * a["pod_eat"][j][None, :],
            "ew": carry["ew"] + mask_t * a["pod_vw"][j][None, :],
            "total": carry["total"] + jnp.where(key_present, qm_t, 0),
        }

    # -- filter -------------------------------------------------------------

    def filter(self, state: NodeStateView, pod: PodView, aux, carry) -> FilterOutput:
        a = aux["interpod"]
        j = pod.index
        i32 = jnp.int32
        raff = a["req_aff"][j].astype(i32)  # [T]
        ranti = a["req_anti"][j].astype(i32)
        qm_t = a["pod_term_match"][j].astype(i32)  # [T]
        n = a["dom_t"].shape[0]

        def heavy(_):
            return self._filter_code(a, carry, raff, ranti, qm_t, j)

        # Upstream's PreFilter Skip (filtering.go): a pod with no required
        # (anti-)affinity terms of its own that also matches no existing
        # pod's term selectors cannot fail any of the three checks — the
        # heavy branch provably yields code 0 for it (every check dots
        # against raff/ranti/qm_t).  lax.cond skips the matvec work in the
        # sequential scan; under vmap it lowers to select (both branches,
        # as before).
        pred = (jnp.sum(raff) + jnp.sum(ranti) + jnp.sum(qm_t)) > 0
        code = jax.lax.cond(
            pred, heavy, lambda _: jnp.zeros(n, jnp.int32), None
        )
        return FilterOutput(ok=code == 0, reason_bits=code)

    def _filter_code(self, a, carry, raff, ranti, qm_t, j):
        i32 = jnp.int32
        dom_t = a["dom_t"]  # [N, T] constant
        cnt = carry["cnt"]  # [N, T]
        # (1) required affinity: all topology keys present AND every term's
        # domain count > 0 — or the global-empty + self-match escape.
        # Upstream keys affinityCounts by topologyPair (key, value) SHARED
        # across all of the pod's required terms (filtering.go
        # topologyToMatchedTermCount.update): two required terms with the
        # same topologyKey read one combined count, so a domain satisfying
        # either term satisfies both.  Aggregate this pod's per-term counts
        # over terms sharing a topology key before the <=0 check.
        missing_any = jnp.dot((dom_t < 0).astype(i32), raff) > 0  # [N]
        n_tk = a["node_dom"].shape[1]
        tk_onehot = (
            a["term_tk"][:, None] == jnp.arange(n_tk, dtype=a["term_tk"].dtype)[None, :]
        ).astype(i32)  # [T, TK]
        cnt_req = cnt * raff[None, :]  # this pod's required terms only
        key_cnt = cnt_req @ tk_onehot  # [N, TK] per-key totals
        need_key = (raff @ tk_onehot) > 0  # [TK] keys with required terms
        no_pods_any = jnp.any((key_cnt <= 0) & need_key[None, :], axis=1)
        escape = (jnp.dot(carry["total"], raff) == 0) & a["self_aff"][j]
        pass_aff = ~missing_any & (~no_pods_any | escape)
        # (2) incoming required anti-affinity (missing key = satisfied).
        viol_anti = jnp.dot((cnt > 0).astype(i32), ranti) > 0
        # (3) existing pods' required anti-affinity vs this pod.
        viol_existing = jnp.dot((carry["ecnt"] > 0).astype(i32), qm_t) > 0

        return jnp.where(
            ~pass_aff,
            AFFINITY_BIT,
            jnp.where(viol_anti, ANTI_BIT, jnp.where(viol_existing, EXISTING_ANTI_BIT, 0)),
        ).astype(i32)

    def decode_reasons(self, bits: int) -> list[str]:
        if bits & AFFINITY_BIT:
            return [ERR_REASON_AFFINITY_RULES_NOT_MATCH]
        if bits & ANTI_BIT:
            return [ERR_REASON_ANTI_AFFINITY_RULES_NOT_MATCH]
        if bits & EXISTING_ANTI_BIT:
            return [ERR_REASON_EXISTING_ANTI_AFFINITY_RULES_NOT_MATCH]
        return []

    # -- score --------------------------------------------------------------

    def score(self, state: NodeStateView, pod: PodView, aux, ok=None, carry=None) -> jnp.ndarray:
        a = aux["interpod"]
        j = pod.index
        qm_t = a["pod_term_match"][j].astype(jnp.int32)
        n = a["dom_t"].shape[0]

        def heavy(_):
            return (
                jnp.dot(carry["cnt"], a["pref_w"][j]) + jnp.dot(carry["ew"], qm_t)
            ).astype(jnp.int32)

        # Scoring Skip: no preferred weights of its own and no term
        # selector matching this pod -> both dot products are provably 0.
        pred = jnp.any(a["pref_w"][j] != 0) | jnp.any(qm_t > 0)
        return jax.lax.cond(pred, heavy, lambda _: jnp.zeros(n, jnp.int32), None)

    def normalize(self, scores: jnp.ndarray, ok: jnp.ndarray) -> jnp.ndarray:
        def heavy(_):
            big = jnp.iinfo(jnp.int32).max
            any_ok = jnp.any(ok)
            mn = jnp.where(any_ok, jnp.min(jnp.where(ok, scores, big)), 0)
            mx = jnp.where(any_ok, jnp.max(jnp.where(ok, scores, -big - 1)), 0)
            diff = mx - mn
            # Go: fScore = float64(MaxNodeScore) * (float64(s-min) /
            # float64(diff)); int64(fScore) truncates (values >= 0 ->
            # floor).  The ratio is of int32s, so the floor is computed in
            # INTEGER space: (100*(s-mn)) // diff is bit-identical to the
            # float64 result whenever 100*(s-mn) fits int32 (a raw score
            # span > ~21M — far beyond real clusters) and, unlike a float
            # division, identical on every XLA backend.  TPU's approximate
            # float32 divide truncated exact integer ratios one ulp low
            # (100*3166/3166 -> 99), the root cause of BENCH_r04's 199-pod
            # f32 churn drift vs CPU.  Out-of-range spans fall back to the
            # old float path (f64 under x64 — exact; f32 otherwise, with
            # the documented +-1 boundary tolerance).
            shifted = scores - mn  # >= 0 on ok nodes (mn is their min)
            in_range = shifted < big // MAX_NODE_SCORE
            val_int = (
                jnp.where(in_range, shifted, 0) * MAX_NODE_SCORE
            ) // jnp.maximum(diff, 1)
            ftype = jnp.float64 if jax.config.jax_enable_x64 else jnp.float32
            ratio = shifted.astype(ftype) / jnp.maximum(diff, 1).astype(ftype)
            val_f = jnp.floor(ftype(MAX_NODE_SCORE) * ratio).astype(jnp.int32)
            val = jnp.where(in_range, val_int, val_f)
            out = jnp.where(diff > 0, val, 0)
            return jnp.where(ok, out, 0).astype(jnp.int32)

        # All-zero raw scores normalize to all zeros (diff == 0 branch);
        # skip the float work for the majority of pods the score cond
        # already zeroed.
        return jax.lax.cond(
            jnp.any(scores != 0),
            heavy,
            lambda _: jnp.zeros(scores.shape[0], jnp.int32),
            None,
        )
