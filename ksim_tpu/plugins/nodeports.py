"""NodePorts filter plugin.

Upstream kube-scheduler v1.30 ``plugins/nodeports/node_ports.go``: each of
the pod's requested host ports must be free on the node; conflicts follow
(protocol, port, hostIP-with-0.0.0.0-wildcard) semantics.  Failure reason:
``node(s) didn't have free ports for the requested pod ports``.

Encoding: state/extras.py builds a vocabulary of the queue pods' wanted
(ip, proto, port) triples; the scan carry is the per-node conflict count
per vocab entry, committed with an elementwise outer-product add (same
no-gather/no-scatter scheme as the other carried plugins).
"""

from __future__ import annotations

import jax.numpy as jnp

from ksim_tpu.plugins.base import FilterOutput, NodeStateView, PodView

NAME = "NodePorts"
ERR_REASON = "node(s) didn't have free ports for the requested pod ports"


class NodePorts:
    # Static reason-bit width: result tensors downcast when every
    # filter plugin's bits fit a narrower dtype (engine/core.py).
    reason_bit_width = 1
    name = NAME

    def static_sig(self) -> tuple:
        return (NAME,)

    def failure_unresolvable(self, bits: int) -> bool:
        # Upstream returns Unschedulable: evicting the conflicting pod
        # frees the port.
        return False

    def carry_init(self, aux) -> jnp.ndarray:
        return aux["nodeports"]["conflict_counts"]  # i32 [N, V]

    def carry_commit(self, carry, aux, pod: PodView, best) -> jnp.ndarray:
        adds = aux["nodeports"]["pod_adds"][pod.index]  # [V]
        onehot = (jnp.arange(carry.shape[0]) == best) & (best >= 0)
        return carry + onehot.astype(carry.dtype)[:, None] * adds[None, :]

    def filter(self, state: NodeStateView, pod: PodView, aux, carry) -> FilterOutput:
        wants = aux["nodeports"]["pod_wants"][pod.index]  # bool [V]
        conflict = jnp.dot(
            (carry > 0).astype(jnp.int32), wants.astype(jnp.int32)
        )  # [N]
        ok = conflict == 0
        return FilterOutput(ok=ok, reason_bits=jnp.where(ok, 0, 1).astype(jnp.int32))

    def decode_reasons(self, bits: int) -> list[str]:
        return [ERR_REASON] if bits else []
