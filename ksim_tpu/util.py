"""Small utilities (the reference's simulator/util package analogue)."""

from __future__ import annotations

import os
import threading
import time
from typing import Callable, TypeVar

from ksim_tpu.obs import LatencyHistogram

T = TypeVar("T")


def enable_compilation_cache(cache_dir: str | None = None) -> None:
    """Turn on JAX's persistent (on-disk) compilation cache.

    XLA compiles of the scheduling scan at large shapes cost seconds to
    tens of seconds each; the disk cache makes them one-time per machine
    instead of per process (measured: a 5k-event churn replay drops
    46s -> 18s on its second cold-process run).  Called by the product
    entrypoints (simulator/scheduler CLIs, bench) — NOT on library
    import, so embedding applications keep control of jax.config.

    ``KSIM_COMPILE_CACHE`` overrides the location; set it to ``off`` to
    disable.

    The default location is fingerprinted by the HOST CPU's feature set:
    XLA:CPU caches AOT-compiled code, and an artifact produced on a
    machine with different vector extensions can SIGILL when loaded on
    this one (cpu_aot_loader warns exactly that; images here migrate
    across heterogeneous hosts between rounds, and a round-4 suite run
    crashed on a stale cross-host artifact).  One subdirectory per
    feature set makes the cache per-machine-model instead of
    per-filesystem."""
    env = os.environ.get("KSIM_COMPILE_CACHE")
    if env == "off":
        return
    cache_dir = env or cache_dir
    if cache_dir is None:
        cache_dir = os.path.join(
            os.path.expanduser("~/.cache/ksim_tpu/jax"), _host_fingerprint()
        )
    import jax

    try:
        os.makedirs(cache_dir, exist_ok=True)
    except OSError:
        # Read-only HOME (containers): run without the persistent cache.
        return
    jax.config.update("jax_compilation_cache_dir", cache_dir)
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.5)


def raise_map_count_limit(target: int = 1_000_000) -> None:
    """Best-effort raise of vm.max_map_count: every XLA:CPU executable
    mmaps code pages, and a long single process (the full test suite, a
    50k-event churn replay) can hit the kernel's 65530 default —
    observed as SIGSEGV/SIGABRT inside LLVM at ~63k maps (round 4).
    No-op without root/procfs."""
    try:
        with open("/proc/sys/vm/max_map_count") as f:
            if int(f.read()) >= target:
                return
        with open("/proc/sys/vm/max_map_count", "w") as f:
            f.write(str(target))
    except (OSError, ValueError):
        pass


def _host_fingerprint() -> str:
    """Short stable hash of this host's CPU feature flags (falls back to
    the platform string when /proc/cpuinfo is unavailable)."""
    import hashlib
    import platform

    flags = ""
    try:
        with open("/proc/cpuinfo") as f:
            for line in f:
                if line.startswith(("flags", "Features")):
                    flags = " ".join(sorted(line.split(":", 1)[1].split()))
                    break
    except OSError:
        pass
    basis = flags or platform.processor() or platform.machine() or "unknown"
    return "host-" + hashlib.sha256(basis.encode()).hexdigest()[:12]


def retry_with_exponential_backoff(
    fn: Callable[[], T],
    *,
    initial: float = 0.1,
    factor: float = 2.0,
    steps: int = 6,
    retriable: tuple[type[BaseException], ...] = (Exception,),
) -> T:
    """Run ``fn`` until it succeeds, backing off exponentially — the
    reference's RetryWithExponentialBackOff (util/retry.go:9-26: 100ms
    initial, 6 steps).  Raises the last error when steps are exhausted."""
    delay = initial
    for attempt in range(steps):
        try:
            return fn()
        except retriable:
            if attempt == steps - 1:
                raise
            time.sleep(delay)
            delay *= factor
    raise AssertionError("unreachable")


class Metrics:
    """Thread-safe counters + latency histograms.

    The reference's observability is the upstream scheduler's Prometheus
    metrics plus klog (SURVEY section 5); this is the in-process
    analogue, exposed as JSON at /api/v1/metrics.  Timers record into
    fixed-bucket log-spaced histograms (ksim_tpu.obs.LatencyHistogram)
    — the former mean-only [total, count] pairs hid multimodal
    latencies (a 5 s cold XLA compile averaged into thousands of 10 ms
    warm passes reads as "15 ms mean"); the snapshot keeps the legacy
    total/count/mean keys and adds buckets + estimated quantiles."""

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._counters: dict[str, int] = {}
        self._timers: dict[str, LatencyHistogram] = {}

    def inc(self, name: str, value: int = 1) -> None:
        with self._lock:
            self._counters[name] = self._counters.get(name, 0) + value

    def observe(self, name: str, seconds: float) -> None:
        with self._lock:
            hist = self._timers.get(name)
            if hist is None:
                hist = self._timers[name] = LatencyHistogram()
            hist.observe(seconds)

    class _Timer:
        def __init__(self, metrics: "Metrics", name: str) -> None:
            self._m, self._name = metrics, name

        def __enter__(self):
            self._t0 = time.perf_counter()
            return self

        def __exit__(self, *exc):
            self._m.observe(self._name, time.perf_counter() - self._t0)
            return False

    def timer(self, name: str) -> "_Timer":
        return self._Timer(self, name)

    def snapshot(self) -> dict:
        with self._lock:
            return {
                "counters": dict(self._counters),
                "timings": {
                    name: hist.snapshot() for name, hist in self._timers.items()
                },
            }
