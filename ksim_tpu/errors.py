"""Sentinel errors (analogue of reference simulator/errors/errors.go)."""


class SimulatorError(Exception):
    """Base class for simulator errors."""


class NotFoundError(SimulatorError):
    """Requested resource does not exist (reference: errors.ErrNotFound)."""


class ConflictError(SimulatorError):
    """Optimistic-concurrency conflict on a resource update."""


class InvalidConfigError(SimulatorError):
    """Configuration failed validation."""


class ExpiredError(SimulatorError):
    """A watch resume point fell out of the event history — the "410
    Gone" etcd compaction analogue; the client must relist."""
