"""Sentinel errors (analogue of reference simulator/errors/errors.go)."""


class SimulatorError(Exception):
    """Base class for simulator errors."""


class NotFoundError(SimulatorError):
    """Requested resource does not exist (reference: errors.ErrNotFound)."""


class ConflictError(SimulatorError):
    """Optimistic-concurrency conflict on a resource update."""


class InvalidConfigError(SimulatorError):
    """Configuration failed validation."""


class ExpiredError(SimulatorError):
    """A watch resume point fell out of the event history — the "410
    Gone" etcd compaction analogue; the client must relist."""


class DeviceUnavailableError(SimulatorError):
    """The accelerator backend failed or stopped answering — an XLA
    runtime error, a wedged chip tunnel, or a dispatch that outlived its
    watchdog.  Consumers must DEGRADE (host path, circuit breaker)
    rather than crash: the condition is environmental, not a bug."""


class RunCancelled(Exception):
    """A scenario run was cancelled cooperatively (the job plane's
    DELETE /api/v1/jobs/<id>).  Deliberately NOT a SimulatorError: the
    replay's classified fault handlers absorb SimulatorErrors into
    per-pass fallbacks, and a cancellation must propagate out of the
    run — after the in-flight segment transaction rolled back — rather
    than be retried on the host path."""


class ReplayFallback(SimulatorError):
    """A replay segment cannot (or must not) run on-device and should
    take the per-pass host path instead.  ``reason`` is the stable
    string the fallback histogram buckets on (engine/replay.py
    ``ReplayDriver.unsupported``)."""

    def __init__(self, reason: str = "replay_fallback") -> None:
        super().__init__(reason)
        self.reason = reason
