"""InterPodAffinity: kernel-vs-oracle parity and behavioral tests."""

import numpy as np
import pytest

from ksim_tpu.engine import Engine
from ksim_tpu.engine.profiles import default_plugins
from ksim_tpu.plugins import oracle
from ksim_tpu.plugins.interpodaffinity import InterPodAffinity
from ksim_tpu.state.featurizer import Featurizer
from tests.helpers import make_node, make_pod, pods_by_node, random_cluster


def run_batch(nodes, pods, queue, namespaces=()):
    feats = Featurizer().featurize(nodes, pods, queue_pods=queue, namespaces=namespaces)
    eng = Engine(feats, default_plugins(feats), record="full")
    return feats, eng.evaluate_batch()


@pytest.mark.parametrize("seed", [21, 22, 23])
def test_batch_parity_interpod_random(seed):
    nodes, pods = random_cluster(
        seed, n_nodes=9, n_pods=33, bound_fraction=0.5, pod_affinity_fraction=0.45
    )
    queue = [p for p in pods if not p["spec"].get("nodeName")]
    feats, res = run_batch(nodes, pods, queue)
    infos = oracle.build_node_infos(nodes, pods)
    by_node = pods_by_node(pods)
    ipa = InterPodAffinity(feats.aux["interpod"])
    f_i = res.filter_plugin_names.index("InterPodAffinity")
    s_i = res.plugin_names.index("InterPodAffinity")
    for pi, pod in enumerate(queue):
        want_rows = oracle.inter_pod_affinity_filter_all(pod, infos, by_node)
        for ni in range(len(infos)):
            got = ipa.decode_reasons(int(res.reason_bits[pi, f_i, ni]))
            assert got == want_rows[ni], (seed, pod["metadata"]["name"], ni)
        feasible = [
            bool(np.all(res.reason_bits[pi, :, ni] == 0)) for ni in range(len(infos))
        ]
        raw, norm = oracle.inter_pod_affinity_score_all(pod, infos, by_node, feasible)
        for ni in range(len(infos)):
            if feasible[ni]:
                assert int(res.scores[pi, s_i, ni]) == raw[ni], (seed, pi, ni)
                # final = normalized x weight (2).
                assert int(res.final_scores[pi, s_i, ni]) == 2 * norm[ni], (seed, pi, ni)


def test_required_affinity_missing_everywhere_blocks():
    nodes = [make_node("n0", labels={"topology.kubernetes.io/zone": "za"})]
    aff = {"podAffinity": {"requiredDuringSchedulingIgnoredDuringExecution": [{
        "labelSelector": {"matchLabels": {"app": "db"}},
        "topologyKey": "topology.kubernetes.io/zone",
    }]}}
    q = make_pod("q", labels={"app": "web"}, affinity=aff)  # doesn't match itself
    feats, res = run_batch(nodes, [], [q])
    ipa = InterPodAffinity(feats.aux["interpod"])
    f_i = res.filter_plugin_names.index("InterPodAffinity")
    assert ipa.decode_reasons(int(res.reason_bits[0, f_i, 0])) == [
        "node(s) didn't match pod affinity rules"
    ]
    assert int(res.selected[0]) == -1


def test_self_affinity_escape_first_pod_of_series():
    # No matching pods exist anywhere, but the pod matches its own term:
    # upstream lets the first pod of a self-affine series through.
    nodes = [make_node("n0", labels={"topology.kubernetes.io/zone": "za"})]
    aff = {"podAffinity": {"requiredDuringSchedulingIgnoredDuringExecution": [{
        "labelSelector": {"matchLabels": {"app": "web"}},
        "topologyKey": "topology.kubernetes.io/zone",
    }]}}
    q = make_pod("q", labels={"app": "web"}, affinity=aff)
    feats, res = run_batch(nodes, [], [q])
    f_i = res.filter_plugin_names.index("InterPodAffinity")
    assert int(res.reason_bits[0, f_i, 0]) == 0
    assert int(res.selected[0]) == 0


def test_affinity_requires_topology_key_on_node():
    nodes = [
        make_node("keyed", labels={"topology.kubernetes.io/zone": "za"}),
        make_node("plain", labels={}),
    ]
    aff = {"podAffinity": {"requiredDuringSchedulingIgnoredDuringExecution": [{
        "labelSelector": {"matchLabels": {"app": "web"}},
        "topologyKey": "topology.kubernetes.io/zone",
    }]}}
    q = make_pod("q", labels={"app": "web"}, affinity=aff)
    feats, res = run_batch(nodes, [], [q])
    f_i = res.filter_plugin_names.index("InterPodAffinity")
    assert int(res.reason_bits[0, f_i, 0]) == 0  # escape applies, key present
    assert int(res.reason_bits[0, f_i, 1]) != 0  # missing key always fails


def test_required_anti_affinity_blocks_domain():
    nodes = [
        make_node("a1", labels={"topology.kubernetes.io/zone": "za"}),
        make_node("b1", labels={"topology.kubernetes.io/zone": "zb"}),
    ]
    bound = [make_pod("w1", labels={"app": "web"}, node_name="a1")]
    aff = {"podAntiAffinity": {"requiredDuringSchedulingIgnoredDuringExecution": [{
        "labelSelector": {"matchLabels": {"app": "web"}},
        "topologyKey": "topology.kubernetes.io/zone",
    }]}}
    q = make_pod("q", labels={"app": "other"}, affinity=aff)
    feats, res = run_batch(nodes, bound, [q])
    ipa = InterPodAffinity(feats.aux["interpod"])
    f_i = res.filter_plugin_names.index("InterPodAffinity")
    assert ipa.decode_reasons(int(res.reason_bits[0, f_i, 0])) == [
        "node(s) didn't match pod anti-affinity rules"
    ]
    assert feats.nodes.names[int(res.selected[0])] == "b1"


def test_existing_pods_anti_affinity_blocks_incoming():
    # Bound pod has anti-affinity against app=web; incoming web pod must
    # avoid the bound pod's zone.
    nodes = [
        make_node("a1", labels={"topology.kubernetes.io/zone": "za"}),
        make_node("b1", labels={"topology.kubernetes.io/zone": "zb"}),
    ]
    anti = {"podAntiAffinity": {"requiredDuringSchedulingIgnoredDuringExecution": [{
        "labelSelector": {"matchLabels": {"app": "web"}},
        "topologyKey": "topology.kubernetes.io/zone",
    }]}}
    bound = [make_pod("guard", labels={"app": "db"}, node_name="a1", affinity=anti)]
    q = make_pod("q", labels={"app": "web"})
    feats, res = run_batch(nodes, bound, [q])
    ipa = InterPodAffinity(feats.aux["interpod"])
    f_i = res.filter_plugin_names.index("InterPodAffinity")
    assert ipa.decode_reasons(int(res.reason_bits[0, f_i, 0])) == [
        "node(s) didn't satisfy existing pods' anti-affinity rules"
    ]
    assert feats.nodes.names[int(res.selected[0])] == "b1"


def test_preferred_affinity_scores_colocated_domain():
    nodes = [
        make_node("a1", labels={"topology.kubernetes.io/zone": "za"}),
        make_node("a2", labels={"topology.kubernetes.io/zone": "za"}),
        make_node("b1", labels={"topology.kubernetes.io/zone": "zb"}),
    ]
    bound = [make_pod("w1", labels={"app": "web"}, node_name="a1")]
    aff = {"podAffinity": {"preferredDuringSchedulingIgnoredDuringExecution": [{
        "weight": 50,
        "podAffinityTerm": {
            "labelSelector": {"matchLabels": {"app": "web"}},
            "topologyKey": "topology.kubernetes.io/zone",
        },
    }]}}
    q = make_pod("q", labels={"app": "cache"}, affinity=aff)
    feats, res = run_batch(nodes, bound, [q])
    s_i = res.plugin_names.index("InterPodAffinity")
    # Both za nodes get raw 50, zb gets 0.
    assert int(res.scores[0, s_i, 0]) == 50
    assert int(res.scores[0, s_i, 1]) == 50
    assert int(res.scores[0, s_i, 2]) == 0
    assert feats.nodes.names[int(res.selected[0])] in ("a1", "a2")


def test_hard_affinity_weight_symmetry():
    # Existing pod REQUIRES affinity to app=web; an incoming web pod is
    # drawn to its domain with HardPodAffinityWeight (=1).
    nodes = [
        make_node("a1", labels={"topology.kubernetes.io/zone": "za"}),
        make_node("b1", labels={"topology.kubernetes.io/zone": "zb"}),
    ]
    need_web = {"podAffinity": {"requiredDuringSchedulingIgnoredDuringExecution": [{
        "labelSelector": {"matchLabels": {"app": "web"}},
        "topologyKey": "topology.kubernetes.io/zone",
    }]}}
    bound = [make_pod("seed", labels={"app": "web"}, node_name="a1", affinity=need_web)]
    q = make_pod("q", labels={"app": "web"})
    feats, res = run_batch(nodes, bound, [q])
    s_i = res.plugin_names.index("InterPodAffinity")
    assert int(res.scores[0, s_i, 0]) == 1  # hard weight
    assert int(res.scores[0, s_i, 1]) == 0


def test_namespace_selector_matching():
    nodes = [make_node("n0", labels={"kubernetes.io/hostname": "n0"})]
    namespaces = [
        {"metadata": {"name": "team-a", "labels": {"team": "a"}}},
        {"metadata": {"name": "team-b", "labels": {"team": "b"}}},
    ]
    bound = [make_pod("w1", namespace="team-a", labels={"app": "web"}, node_name="n0")]
    # Anti-affinity with namespaceSelector team=a: sees the team-a pod.
    aff = {"podAntiAffinity": {"requiredDuringSchedulingIgnoredDuringExecution": [{
        "labelSelector": {"matchLabels": {"app": "web"}},
        "namespaceSelector": {"matchLabels": {"team": "a"}},
        "topologyKey": "kubernetes.io/hostname",
    }]}}
    q = make_pod("q", namespace="team-b", labels={"app": "x"}, affinity=aff)
    feats, res = run_batch(nodes, bound, [q], namespaces=namespaces)
    f_i = res.filter_plugin_names.index("InterPodAffinity")
    assert int(res.reason_bits[0, f_i, 0]) != 0
    # Without the selector the term defaults to the pod's own namespace
    # (team-b) and the team-a pod is invisible.
    aff2 = {"podAntiAffinity": {"requiredDuringSchedulingIgnoredDuringExecution": [{
        "labelSelector": {"matchLabels": {"app": "web"}},
        "topologyKey": "kubernetes.io/hostname",
    }]}}
    q2 = make_pod("q2", namespace="team-b", labels={"app": "x"}, affinity=aff2)
    feats2, res2 = run_batch(nodes, bound, [q2], namespaces=namespaces)
    f_i2 = res2.filter_plugin_names.index("InterPodAffinity")
    assert int(res2.reason_bits[0, f_i2, 0]) == 0


def test_sequential_anti_affinity_spreads_one_per_host():
    # 3 pods with required hostname anti-affinity to their own app: the
    # scan must place one per node (each placement updates the carry).
    nodes = [make_node(f"n{i}", labels={"kubernetes.io/hostname": f"n{i}"}) for i in range(3)]
    aff = {"podAntiAffinity": {"requiredDuringSchedulingIgnoredDuringExecution": [{
        "labelSelector": {"matchLabels": {"app": "web"}},
        "topologyKey": "kubernetes.io/hostname",
    }]}}
    queue = [make_pod(f"w{i}", labels={"app": "web"}, affinity=aff) for i in range(3)]
    feats = Featurizer().featurize(nodes, [], queue_pods=queue)
    eng = Engine(feats, default_plugins(feats), record="selection")
    res, _ = eng.schedule()
    chosen = sorted(int(s) for s in res.selected[:3])
    assert chosen == [0, 1, 2]


def test_sequential_affinity_follows_first_placement():
    # First pod self-escapes into some zone; followers must join it.
    nodes = [
        make_node("a1", labels={"topology.kubernetes.io/zone": "za"}),
        make_node("b1", labels={"topology.kubernetes.io/zone": "zb"}),
        make_node("a2", labels={"topology.kubernetes.io/zone": "za"}),
    ]
    aff = {"podAffinity": {"requiredDuringSchedulingIgnoredDuringExecution": [{
        "labelSelector": {"matchLabels": {"app": "web"}},
        "topologyKey": "topology.kubernetes.io/zone",
    }]}}
    queue = [make_pod(f"w{i}", labels={"app": "web"}, affinity=aff) for i in range(3)]
    feats = Featurizer().featurize(nodes, [], queue_pods=queue)
    eng = Engine(feats, default_plugins(feats), record="selection")
    res, _ = eng.schedule()
    zones = {feats.nodes.names[int(s)][0] for s in res.selected[:3]}
    assert len(zones) == 1  # all in one zone
    assert all(int(s) >= 0 for s in res.selected[:3])


def test_required_terms_sharing_topology_key_share_counts():
    # Upstream keys affinityCounts by topologyPair shared across ALL of the
    # pod's required terms (filtering.go topologyToMatchedTermCount): with
    # two required terms on the same topologyKey, a domain with pods
    # matching only ONE term still satisfies both checks (the shared
    # (key, value) count is > 0).  Advisor round-1 high finding.
    zone = "topology.kubernetes.io/zone"
    nodes = [make_node("n0", labels={zone: "za"})]
    existing = make_pod("db0", labels={"app": "db"}, node_name="n0")
    aff = {"podAffinity": {"requiredDuringSchedulingIgnoredDuringExecution": [
        {"labelSelector": {"matchLabels": {"app": "db"}}, "topologyKey": zone},
        {"labelSelector": {"matchLabels": {"tier": "cache"}}, "topologyKey": zone},
    ]}}
    q = make_pod("q", labels={"app": "web"}, affinity=aff)
    feats, res = run_batch(nodes, [existing], [q])
    ipa = InterPodAffinity(feats.aux["interpod"])
    f_i = res.filter_plugin_names.index("InterPodAffinity")
    got = ipa.decode_reasons(int(res.reason_bits[0, f_i, 0]))
    infos = oracle.build_node_infos(nodes, [existing])
    want = oracle.inter_pod_affinity_filter_all(q, infos, pods_by_node([existing]))
    assert want[0] == []  # oracle (upstream) accepts
    assert got == want[0]


def test_required_terms_distinct_topology_keys_stay_independent():
    # Terms on DIFFERENT topology keys must still be checked independently:
    # a domain satisfying the zone term does not satisfy a hostname term
    # with no matching pods.
    zone = "topology.kubernetes.io/zone"
    host = "kubernetes.io/hostname"
    nodes = [make_node("n0", labels={zone: "za", host: "n0"})]
    existing = make_pod("db0", labels={"app": "db"}, node_name="n0")
    aff = {"podAffinity": {"requiredDuringSchedulingIgnoredDuringExecution": [
        {"labelSelector": {"matchLabels": {"app": "db"}}, "topologyKey": zone},
        {"labelSelector": {"matchLabels": {"tier": "cache"}}, "topologyKey": host},
    ]}}
    q = make_pod("q", labels={"app": "web"}, affinity=aff)
    feats, res = run_batch(nodes, [existing], [q])
    ipa = InterPodAffinity(feats.aux["interpod"])
    f_i = res.filter_plugin_names.index("InterPodAffinity")
    got = ipa.decode_reasons(int(res.reason_bits[0, f_i, 0]))
    infos = oracle.build_node_infos(nodes, [existing])
    want = oracle.inter_pod_affinity_filter_all(q, infos, pods_by_node([existing]))
    assert want[0] == ["node(s) didn't match pod affinity rules"]
    assert got == want[0]
