"""percentageOfNodesToScore emulation (opt-in replay-fidelity mode).

Upstream kube-scheduler v1.30 samples which feasible nodes get scored
once a cluster exceeds 100 nodes: it visits nodes in index order from a
rotating start (sched.nextStartNodeIndex), stops filtering after finding
numFeasibleNodesToFind feasible ones, scores/normalizes only those, and
advances the start by the number of nodes processed
(pkg/scheduler/schedule_one.go findNodesThatPassFilters +
numFeasibleNodesToFind).  The reference simulator inherits this
behavior; its exported default config carries the field
(simulator/snapshot/snapshot_test.go:1415).

The emulation is deliberately the DETERMINISTIC sequential idealization
(upstream's parallel filter workers make the exact visited set racy);
docs/migration.md states the contract.  Expectations below are
hand-derived from the upstream formulas, never from running the engine.
"""

import numpy as np
import pytest

from ksim_tpu.engine import Engine
from ksim_tpu.engine.profiles import default_plugins
from ksim_tpu.state.featurizer import Featurizer
from tests.helpers import make_node, make_pod


def _engine(n_nodes, pods, k, record="full"):
    nodes = [make_node(f"n{i:03d}") for i in range(n_nodes)]
    feats = Featurizer().featurize(nodes, [], queue_pods=pods)
    return Engine(feats, default_plugins(feats), record=record, sampling_k=k), feats


def test_sampling_visits_first_k_feasible_from_start():
    """12 feasible nodes, K=4, start=0: exactly nodes 0..3 are visited
    and scored; selection comes from that sample; the start index
    advances by 4 (all visited nodes were feasible)."""
    eng, feats = _engine(12, [make_pod("p0")], 4)
    res, _ = eng.schedule(sampling_start=0)
    N = feats.nodes.count
    vis = res.visited[0][:N]
    assert vis.tolist() == [True] * 4 + [False] * 8
    assert int(res.selected[0]) in range(4)
    assert res.sampling_next_start == 4


def test_sampling_rotates_across_pods():
    """Two pods in one pass: the second pod's window starts where the
    first stopped (hand-derived: K=4 from start 0 -> visits 0-3, next
    start 4 -> second pod visits 4-7)."""
    eng, feats = _engine(12, [make_pod("p0"), make_pod("p1")], 4)
    res, _ = eng.schedule(sampling_start=0)
    N = feats.nodes.count
    assert res.visited[0][:N].tolist() == [True] * 4 + [False] * 8
    assert res.visited[1][:N].tolist() == [False] * 4 + [True] * 4 + [False] * 4
    assert res.sampling_next_start == 8


def test_sampling_wraps_modulo_node_count():
    """start=10 with 12 nodes and K=4 wraps: visits 10, 11, 0, 1."""
    eng, feats = _engine(12, [make_pod("p0")], 4)
    res, _ = eng.schedule(sampling_start=10)
    N = feats.nodes.count
    want = [False] * N
    for i in (10, 11, 0, 1):
        want[i] = True
    assert res.visited[0][:N].tolist() == want
    assert res.sampling_next_start == 2


def test_sampling_skips_infeasible_until_k_found():
    """Nodes 1 and 2 infeasible (cordoned): from start 0 with K=3 the
    visit order is 0(feasible), 1(x), 2(x), 3, 4 — five nodes processed,
    visited mask covers all five, and the infeasible ones carry their
    filter failure in the recorded results."""
    nodes = [make_node(f"n{i:03d}", unschedulable=i in (1, 2)) for i in range(10)]
    feats = Featurizer().featurize(nodes, [], queue_pods=[make_pod("p0")])
    eng = Engine(feats, default_plugins(feats), record="full", sampling_k=3)
    res, _ = eng.schedule(sampling_start=0)
    N = feats.nodes.count
    assert res.visited[0][:N].tolist() == [True] * 5 + [False] * 5
    assert res.sampling_next_start == 5
    # Selection comes from the 3 feasible visited nodes (final-score
    # values for nodes OUTSIDE the sample are dead weight the selection
    # and the renderer both mask, exactly like infeasible nodes in the
    # unsampled path).
    assert int(res.selected[0]) in (0, 3, 4)


def test_sampling_fewer_feasible_than_k_visits_everything():
    """With every node infeasible but 2 and K=3, the whole list is
    processed (upstream iterates to the end) and the start wraps to 0."""
    nodes = [make_node(f"n{i:03d}", unschedulable=i not in (5, 6)) for i in range(8)]
    feats = Featurizer().featurize(nodes, [], queue_pods=[make_pod("p0")])
    eng = Engine(feats, default_plugins(feats), record="full", sampling_k=3)
    res, _ = eng.schedule(sampling_start=0)
    N = feats.nodes.count
    assert res.visited[0][:N].tolist() == [True] * 8
    assert res.sampling_next_start == 0
    assert int(res.selected[0]) in (5, 6)


def test_sampling_normalizes_over_sample_only():
    """Normalization (e.g. NodeAffinity's DefaultNormalizeScore) runs
    over the sampled nodes, not the full feasible set — a high-scoring
    node OUTSIDE the window must not depress the sampled nodes'
    normalized scores.  Node 9 has the preferred label; window 0..3
    doesn't include it, so the sampled max is over equal scores and
    normalize sees only them."""
    labels = {"zone": "hot"}
    aff = {
        "nodeAffinity": {
            "preferredDuringSchedulingIgnoredDuringExecution": [
                {
                    "weight": 100,
                    "preference": {
                        "matchExpressions": [
                            {"key": "zone", "operator": "In", "values": ["hot"]}
                        ]
                    },
                }
            ]
        }
    }
    nodes = [
        make_node(f"n{i:03d}", labels=labels if i == 9 else None) for i in range(10)
    ]
    pod = make_pod("p0", affinity=aff)
    feats = Featurizer().featurize(nodes, [], queue_pods=[pod])
    eng = Engine(feats, default_plugins(feats), record="full", sampling_k=4)
    res, _ = eng.schedule(sampling_start=0)
    # All sampled nodes have raw NodeAffinity 0: upstream's
    # DefaultNormalizeScore with max 0 leaves them 0 — node 9's raw 100
    # must NOT have entered the normalize max.
    na = res.plugin_names.index("NodeAffinity")
    N = feats.nodes.count
    assert (res.final_scores[0][na][:4] == 0).all()
    # Unsampled nodes contribute nothing.
    assert int(res.selected[0]) in range(4)


def test_sampling_scan_only():
    eng, _ = _engine(8, [make_pod("p0")], 3)
    with pytest.raises(ValueError):
        eng.evaluate_batch()
    with pytest.raises(ValueError):
        eng.evaluate_batch_fused()


def test_recorded_maps_cover_visited_nodes_only():
    """filter-result lists exactly the visited nodes (upstream's
    NodeToStatusMap covers nodes the sampled iteration touched); score
    maps cover the sampled feasible set."""
    import json

    from ksim_tpu.engine.annotations import FILTER_RESULT_KEY, SCORE_RESULT_KEY, render_pod_results

    nodes = [make_node(f"n{i:03d}", unschedulable=i == 1) for i in range(10)]
    feats = Featurizer().featurize(nodes, [], queue_pods=[make_pod("p0")])
    plugins = default_plugins(feats)
    eng = Engine(feats, plugins, record="full", sampling_k=3)
    res, _ = eng.schedule(sampling_start=0)
    anno = render_pod_results(
        feats, plugins, res, 0, visited=res.visited[0]
    )
    filt = json.loads(anno[FILTER_RESULT_KEY])
    # Visit order 0(ok), 1(x), 2(ok), 3(ok): four visited nodes.
    assert sorted(filt) == ["n000", "n001", "n002", "n003"]
    assert "NodeUnschedulable" in str(filt["n001"])
    score = json.loads(anno[SCORE_RESULT_KEY])
    assert sorted(score) == ["n000", "n002", "n003"]


def test_service_sampling_k_resolution():
    """numFeasibleNodesToFind hand-derivations (schedule_one.go):
    <100 nodes -> no sampling; adaptive percentage 50 - n/125 floored at
    5; explicit percentage respected; floor of 100 feasible nodes."""
    from ksim_tpu.scheduler.service import SchedulerService
    from ksim_tpu.state.cluster import ClusterStore

    svc = SchedulerService(ClusterStore(), record="selection", preemption=False)
    svc._pnts_emulation = True
    # 99 nodes: below minFeasibleNodesToFind -> score all.
    assert svc._sampling_k_for(None, 99) is None
    # 5000 nodes, adaptive: 50 - 40 = 10% -> 500.
    assert svc._sampling_k_for(None, 5000) == 500
    # 125000 nodes: adaptive hits the 5% floor -> 6250.
    assert svc._sampling_k_for(None, 125_000) == 6250
    # 200 nodes, adaptive: 50 - 1 = 49% -> 98 -> floored to 100.
    assert svc._sampling_k_for(None, 200) == 100
    # 110 nodes adaptive: 50% -> 55 -> floored to 100 (< 110): upstream
    # really does sample 100 of 110 here.
    assert svc._sampling_k_for(None, 110) == 100
    # Explicit global percentage.
    svc._config = {"percentageOfNodesToScore": 20}
    assert svc._sampling_k_for(None, 5000) == 1000
    # >= 100 percent -> everything.
    svc._config = {"percentageOfNodesToScore": 100}
    assert svc._sampling_k_for(None, 5000) is None
    # Emulation off -> always None.
    svc._pnts_emulation = False
    assert svc._sampling_k_for(None, 5000) is None


def test_service_end_to_end_sampling(monkeypatch):
    """KSIM_PNTS_EMULATION=1 + 120 nodes: the service schedules through
    the sampled scan (adaptive K=100 of 120), records visited-restricted
    maps, and persists the rotating start across passes."""
    import json

    from ksim_tpu.engine.annotations import FILTER_RESULT_KEY
    from ksim_tpu.scheduler.service import SchedulerService
    from ksim_tpu.state.cluster import ClusterStore

    monkeypatch.setenv("KSIM_PNTS_EMULATION", "1")
    store = ClusterStore()
    for i in range(120):
        store.create("nodes", make_node(f"n{i:03d}"))
    store.create("pods", make_pod("p0", cpu="100m", memory="64Mi"))
    svc = SchedulerService(store, record="full", preemption=False)
    assert svc._pnts_emulation
    placements = svc.schedule_pending()
    assert placements["default/p0"] is not None
    # K=100 of 120 from start 0: nodes 0..99 visited; start advanced.
    pod = store.get("pods", "p0", "default")
    filt = json.loads(pod["metadata"]["annotations"][FILTER_RESULT_KEY])
    assert len(filt) == 100
    assert "n000" in filt and "n099" in filt and "n100" not in filt
    assert svc._pnts_start["default-scheduler"] == 100
    # Second pass starts at 100 and wraps.
    store.create("pods", make_pod("p1", cpu="100m", memory="64Mi"))
    svc.schedule_pending()
    pod1 = store.get("pods", "p1", "default")
    filt1 = json.loads(pod1["metadata"]["annotations"][FILTER_RESULT_KEY])
    assert "n100" in filt1 and "n119" in filt1 and "n099" not in filt1
    assert svc._pnts_start["default-scheduler"] == 80


def test_sampled_schedule_sharded_equals_single_device():
    """The sampling emulation composes with the tp mesh: the rotating
    start/n_real scalars replicate and the visited/top_k machinery runs
    under GSPMD identically to single-device."""
    from ksim_tpu.engine.sharding import make_mesh

    nodes = [make_node(f"n{i:03d}", unschedulable=i % 7 == 3) for i in range(24)]
    pods = [make_pod(f"p{i}") for i in range(6)]
    feats = Featurizer().featurize(nodes, [], queue_pods=pods)
    plain = Engine(feats, default_plugins(feats), record="full", sampling_k=5)
    res_plain, _ = plain.schedule(sampling_start=2)
    sharded = Engine(feats, default_plugins(feats), record="full", sampling_k=5)
    sharded.shard(make_mesh(8, dp=1))
    res_shard, _ = sharded.schedule(sampling_start=2)
    assert np.array_equal(res_plain.selected, res_shard.selected)
    assert np.array_equal(res_plain.visited, res_shard.visited)
    assert res_plain.sampling_next_start == res_shard.sampling_next_start
