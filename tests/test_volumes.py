"""Volume-family plugins (VolumeBinding, VolumeZone, NodeVolumeLimits,
VolumeRestrictions) — kernel vs oracle on hand-built scenarios covering
every failure branch, plus the service end-to-end flow."""

from __future__ import annotations

import json

from ksim_tpu.engine import Engine
from ksim_tpu.engine.annotations import FILTER_RESULT_KEY
from ksim_tpu.engine.profiles import default_plugins
from ksim_tpu.plugins import oracle
from ksim_tpu.plugins.volumes import (
    ERR_BIND_CONFLICT,
    ERR_MAX_VOLUME_COUNT,
    ERR_NODE_CONFLICT,
    ERR_RWOP_CONFLICT,
    ERR_UNBOUND_IMMEDIATE,
    ERR_ZONE_CONFLICT,
    ERR_DISK_CONFLICT,
)
from ksim_tpu.scheduler.service import SchedulerService
from ksim_tpu.state.cluster import ClusterStore
from ksim_tpu.state.featurizer import Featurizer
from tests.helpers import make_node, make_pod


def _pvc(name, *, volume_name="", sc="", modes=("ReadWriteOnce",), storage="1Gi"):
    return {
        "apiVersion": "v1", "kind": "PersistentVolumeClaim",
        "metadata": {"name": name, "namespace": "default"},
        "spec": {
            "accessModes": list(modes),
            "storageClassName": sc,
            "volumeName": volume_name,
            "resources": {"requests": {"storage": storage}},
        },
    }


def _pv(name, *, zone=None, affinity_zone=None, capacity="10Gi", sc="",
        phase="Available", claim_ref=None, source=None):
    pv = {
        "apiVersion": "v1", "kind": "PersistentVolume",
        "metadata": {"name": name, "labels": {}},
        "spec": {
            "capacity": {"storage": capacity},
            "accessModes": ["ReadWriteOnce"],
            "storageClassName": sc,
        },
        "status": {"phase": phase},
    }
    if zone:
        pv["metadata"]["labels"]["topology.kubernetes.io/zone"] = zone
    if affinity_zone:
        pv["spec"]["nodeAffinity"] = {"required": {"nodeSelectorTerms": [
            {"matchExpressions": [{"key": "topology.kubernetes.io/zone",
                                   "operator": "In", "values": [affinity_zone]}]}
        ]}}
    if claim_ref:
        pv["spec"]["claimRef"] = claim_ref
    if source:
        pv["spec"].update(source)
    return pv


def _sc(name, *, provisioner="pd.csi.storage.gke.io", mode="WaitForFirstConsumer"):
    return {
        "apiVersion": "storage.k8s.io/v1", "kind": "StorageClass",
        "metadata": {"name": name},
        "provisioner": provisioner,
        "volumeBindingMode": mode,
    }


def _pod_with_claim(name, claim, **kw):
    p = make_pod(name, **kw)
    p["spec"]["volumes"] = [
        {"name": "data", "persistentVolumeClaim": {"claimName": claim}}
    ]
    return p


def _run(nodes, queue, pvs=(), pvcs=(), scs=(), pods=()):
    feats = Featurizer().featurize(
        nodes, list(pods), queue_pods=queue, pvs=list(pvs), pvcs=list(pvcs),
        storage_classes=list(scs),
    )
    eng = Engine(feats, default_plugins(feats), record="full")
    return feats, eng.schedule()[0]


def _reasons(feats, res, plugins_name, pi, ni):
    fi = res.filter_plugin_names.index(plugins_name)
    import ksim_tpu.plugins.volumes as vol

    cls = {
        "VolumeBinding": vol.VolumeBinding,
        "VolumeZone": vol.VolumeZone,
        "NodeVolumeLimits": vol.NodeVolumeLimits,
        "VolumeRestrictions": vol.VolumeRestrictions,
    }[plugins_name]
    inst = cls.__new__(cls)
    return inst.decode_reasons(int(res.reason_bits[pi, fi, ni]))


def test_volume_binding_node_affinity_conflict():
    nodes = [
        make_node("na", labels={"topology.kubernetes.io/zone": "a"}),
        make_node("nb", labels={"topology.kubernetes.io/zone": "b"}),
    ]
    pvs = [_pv("pv1", affinity_zone="a")]
    pvcs = [_pvc("claim", volume_name="pv1")]
    queue = [_pod_with_claim("p", "claim")]
    feats, res = _run(nodes, queue, pvs=pvs, pvcs=pvcs)
    assert feats.nodes.names[int(res.selected[0])] == "na"
    assert _reasons(feats, res, "VolumeBinding", 0, 1) == [ERR_NODE_CONFLICT]
    # Oracle agreement on both nodes.
    for ni, node in enumerate(nodes):
        want = oracle.volume_binding_filter(queue[0], node, pvcs, pvs, [])
        assert _reasons(feats, res, "VolumeBinding", 0, ni) == want


def test_volume_binding_unbound_immediate_and_missing():
    nodes = [make_node("n0")]
    pvcs = [_pvc("immediate", sc="")]  # no SC -> Immediate, unbound
    q1 = _pod_with_claim("p1", "immediate")
    q2 = _pod_with_claim("p2", "nosuch")
    feats, res = _run(nodes, [q1, q2], pvcs=pvcs)
    assert int(res.selected[0]) == -1 and int(res.selected[1]) == -1
    assert _reasons(feats, res, "VolumeBinding", 0, 0) == [ERR_UNBOUND_IMMEDIATE]
    assert "not found" in _reasons(feats, res, "VolumeBinding", 1, 0)[0]


def test_volume_binding_wffc_candidates_and_provisioning():
    nodes = [
        make_node("na", labels={"topology.kubernetes.io/zone": "a"}),
        make_node("nb", labels={"topology.kubernetes.io/zone": "b"}),
    ]
    # WFFC claim with a static candidate PV only in zone a, no provisioner.
    scs = [_sc("local", provisioner="kubernetes.io/no-provisioner")]
    pvs = [_pv("pv-a", affinity_zone="a", sc="local")]
    pvcs = [_pvc("claim", sc="local")]
    queue = [_pod_with_claim("p", "claim")]
    feats, res = _run(nodes, queue, pvs=pvs, pvcs=pvcs, scs=scs)
    assert feats.nodes.names[int(res.selected[0])] == "na"
    assert _reasons(feats, res, "VolumeBinding", 0, 1) == [ERR_BIND_CONFLICT]
    # With a dynamic provisioner the claim binds anywhere.
    scs2 = [_sc("dyn")]
    pvcs2 = [_pvc("claim", sc="dyn")]
    feats2, res2 = _run(nodes, [_pod_with_claim("p", "claim")], pvcs=pvcs2, scs=scs2)
    assert int(res2.selected[0]) >= 0
    assert _reasons(feats2, res2, "VolumeBinding", 0, 0) == []


def test_volume_zone_conflict():
    nodes = [
        make_node("na", labels={"topology.kubernetes.io/zone": "a"}),
        make_node("nb", labels={"topology.kubernetes.io/zone": "b"}),
    ]
    pvs = [_pv("pv1", zone="a")]
    pvcs = [_pvc("claim", volume_name="pv1")]
    queue = [_pod_with_claim("p", "claim")]
    feats, res = _run(nodes, queue, pvs=pvs, pvcs=pvcs)
    assert feats.nodes.names[int(res.selected[0])] == "na"
    assert _reasons(feats, res, "VolumeZone", 0, 1) == [ERR_ZONE_CONFLICT]
    for ni, node in enumerate(nodes):
        assert _reasons(feats, res, "VolumeZone", 0, ni) == oracle.volume_zone_filter(
            queue[0], node, pvcs, pvs
        )


def test_node_volume_limits_and_commit():
    nodes = [make_node("n0", extra_alloc={"attachable-volumes-csi-d": "1"}),
             make_node("n1", extra_alloc={"attachable-volumes-csi-d": "2"})]
    scs = [_sc("fast", provisioner="d")]
    pvs = [
        _pv("pv1", sc="fast", phase="Bound"),
        _pv("pv2", sc="fast", phase="Bound"),
        _pv("pv3", sc="fast", phase="Bound"),
    ]
    for pv in pvs:
        pv["spec"]["csi"] = {"driver": "d", "volumeHandle": pv["metadata"]["name"]}
    pvcs = [_pvc(f"c{i}", volume_name=f"pv{i+1}", sc="fast") for i in range(3)]
    queue = [_pod_with_claim(f"p{i}", f"c{i}") for i in range(3)]
    feats, res = _run(nodes, queue, pvs=pvs, pvcs=pvcs, scs=scs)
    placed = [feats.nodes.names[int(res.selected[i])] if res.selected[i] >= 0 else None
              for i in range(3)]
    # Capacity 1+2: all three fit, the scan carry enforcing per-node limits.
    assert sorted(p for p in placed if p) == ["n0", "n1", "n1"]
    # A fourth claim cannot fit anywhere.
    pv4 = _pv("pv4", sc="fast", phase="Bound")
    pv4["spec"]["csi"] = {"driver": "d", "volumeHandle": "pv4"}
    pvcs4 = pvcs + [_pvc("c3", volume_name="pv4", sc="fast")]
    bound = []
    for i, p in enumerate(queue):
        b = _pod_with_claim(f"b{i}", f"c{i}", node_name=placed[i])
        bound.append(b)
    feats2, res2 = _run(nodes, [_pod_with_claim("p3", "c3")], pvs=pvs + [pv4],
                        pvcs=pvcs4, scs=scs, pods=bound)
    assert int(res2.selected[0]) == -1
    assert _reasons(feats2, res2, "NodeVolumeLimits", 0, 0) == [ERR_MAX_VOLUME_COUNT]
    want = oracle.node_volume_limits_filter(
        _pod_with_claim("p3", "c3"), nodes[0], [bound[0]], pvcs4, pvs + [pv4], scs
    )
    assert want == [ERR_MAX_VOLUME_COUNT]


def test_volume_restrictions_rwop_and_disk():
    nodes = [make_node("n0"), make_node("n1")]
    pvcs = [_pvc("shared", volume_name="", modes=("ReadWriteOncePod",))]
    bound = _pod_with_claim("holder", "shared", node_name="n0")
    q = _pod_with_claim("p", "shared")
    feats, res = _run(nodes, [q], pvcs=pvcs, pods=[bound])
    # RWOP claim in use on n0 -> lands on n1.
    assert feats.nodes.names[int(res.selected[0])] == "n1"
    assert _reasons(feats, res, "VolumeRestrictions", 0, 0) == [ERR_RWOP_CONFLICT]
    assert oracle.volume_restrictions_filter(q, [bound], pvcs) == [ERR_RWOP_CONFLICT]

    # GCE PD: rw conflicts with any use; both-read-only shares.
    def gce(name, node_name, ro):
        p = make_pod(name, node_name=node_name)
        p["spec"]["volumes"] = [{
            "name": "d", "gcePersistentDisk": {"pdName": "disk-1", "readOnly": ro}
        }]
        return p

    q_rw = gce("q-rw", "", False)
    q_ro = gce("q-ro", "", True)
    holder_ro = gce("h", "n0", True)
    feats2, res2 = _run(nodes, [q_rw], pods=[holder_ro])
    assert feats2.nodes.names[int(res2.selected[0])] == "n1"
    assert _reasons(feats2, res2, "VolumeRestrictions", 0, 0) == [ERR_DISK_CONFLICT]
    feats3, res3 = _run(nodes, [q_ro], pods=[holder_ro])
    assert _reasons(feats3, res3, "VolumeRestrictions", 0, 0) == []  # ro+ro shares
    assert oracle.volume_restrictions_filter(q_ro, [holder_ro], []) == []
    assert oracle.volume_restrictions_filter(q_rw, [holder_ro], []) == [ERR_DISK_CONFLICT]


def test_service_end_to_end_with_pvc_pods():
    """The VERDICT gap: a snapshot with PVC-backed pods must schedule
    CORRECTLY (zone-affine PV pins the pod) instead of silently ignoring
    volumes."""
    store = ClusterStore()
    store.create("nodes", make_node("na", labels={"topology.kubernetes.io/zone": "a"}))
    store.create("nodes", make_node("nb", cpu="64", memory="128Gi",
                                    labels={"topology.kubernetes.io/zone": "b"}))
    store.create("persistentvolumes", _pv("pv1", affinity_zone="a"))
    store.create("persistentvolumeclaims", _pvc("claim", volume_name="pv1"))
    store.create("pods", _pod_with_claim("p", "claim", cpu="100m"))
    svc = SchedulerService(store)
    # nb is far bigger (better LeastAllocated score) but the PV pins to na.
    assert svc.schedule_pending() == {"default/p": "na"}
    anno = store.get("pods", "p")["metadata"]["annotations"]
    fr = json.loads(anno[FILTER_RESULT_KEY])
    assert fr["nb"]["VolumeBinding"] == ERR_NODE_CONFLICT


def test_bound_volume_count_tracks_across_passes():
    """The persistent featurizer's incremental bound-volumes count must
    engage/disengage the trivial fast path correctly as volume-using
    pods come and go — and never produce different tensors than a fresh
    featurizer."""
    import numpy as np

    from ksim_tpu.state.featurizer import Featurizer
    from tests.helpers import make_node, make_pod

    node = make_node("n0")
    voluser = make_pod("voluser", node_name="n0")
    voluser["spec"]["volumes"] = [
        {"name": "d", "gcePersistentDisk": {"pdName": "disk-1"}}
    ]
    plain_bound = make_pod("plain", node_name="n0")
    queue = [make_pod("q0")]

    f = Featurizer()
    # Pass 1: a bound volume user -> full encode (disk counts non-zero).
    feats1 = f.featurize([node], [voluser, plain_bound], queue_pods=queue)
    assert feats1.aux["volumes"].disk_any_init.sum() > 0
    # Pass 2: the volume user is gone -> trivial path, zero tensors.
    feats2 = f.featurize([node], [plain_bound], queue_pods=queue)
    assert feats2.aux["volumes"].disk_any_init.sum() == 0
    # Fresh featurizer agrees with the persistent one, field by field.
    fresh = Featurizer().featurize([node], [plain_bound], queue_pods=queue)
    for name in ("disk_any_init", "attached_init", "pod_vol", "pod_fail"):
        np.testing.assert_array_equal(
            getattr(feats2.aux["volumes"], name),
            getattr(fresh.aux["volumes"], name),
        )
    # Pass 3: a QUEUE pod with volumes still forces the full encode even
    # though no bound pod uses any.
    volq = make_pod("volq")
    volq["spec"]["volumes"] = [{"name": "d", "gcePersistentDisk": {"pdName": "disk-2"}}]
    feats3 = f.featurize([node], [plain_bound], queue_pods=[volq])
    assert feats3.aux["volumes"].pod_vol.sum() > 0
