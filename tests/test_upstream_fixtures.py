"""Independently-derived upstream-v1.30 fixtures vs BOTH oracle and kernels.

tests/fixtures/upstream_v130.py holds expected values hand-computed from
the upstream formulas (arithmetic documented there).  Every assertion here
runs twice conceptually: once against the pure-Python oracle and once
against the compiled JAX kernels through the engine — so an oracle
mis-derivation can no longer hide behind kernel-oracle agreement
(round-1's InterPodAffinity shared-topology-key bug was exactly that).
"""

from __future__ import annotations

import numpy as np
import pytest

from ksim_tpu.engine import Engine
from ksim_tpu.engine.profiles import default_plugins
from ksim_tpu.plugins import oracle
from ksim_tpu.state.featurizer import Featurizer
from tests.fixtures import upstream_v130 as fx
from tests.helpers import make_node, make_pod, pods_by_node

ZONE_KEY = "topology.kubernetes.io/zone"
HOST_KEY = "kubernetes.io/hostname"


def _mem_str(n: int) -> str:
    return str(n)  # raw bytes quantity


def _score_case_cluster(case):
    node = make_node(
        "n0", cpu=f"{case['node_cpu_milli']}m", memory=_mem_str(case["node_mem"])
    )
    if case["pod_cpu_milli"] is None:
        pod = make_pod("p0", cpu=None, memory=None)
    else:
        pod = make_pod(
            "p0", cpu=f"{case['pod_cpu_milli']}m", memory=_mem_str(case["pod_mem"])
        )
    return [node], pod


def _engine_result(nodes, bound_pods, queue):
    feats = Featurizer().featurize(nodes, bound_pods, queue_pods=queue)
    eng = Engine(feats, default_plugins(feats), record="full")
    return feats, eng.evaluate_batch()


@pytest.mark.parametrize("case", fx.BALANCED_ALLOCATION_CASES, ids=lambda c: c["name"])
def test_balanced_allocation_fixture(case):
    nodes, pod = _score_case_cluster(case)
    # Oracle side.
    infos = oracle.build_node_infos(nodes, [])
    assert oracle.balanced_allocation_score(pod, infos[0]) == case["want"]
    # Kernel side.
    _feats, res = _engine_result(nodes, [], [pod])
    si = res.plugin_names.index("NodeResourcesBalancedAllocation")
    assert int(res.scores[0, si, 0]) == case["want"]


@pytest.mark.parametrize("case", fx.LEAST_ALLOCATED_CASES, ids=lambda c: c["name"])
def test_least_allocated_fixture(case):
    nodes, pod = _score_case_cluster(case)
    infos = oracle.build_node_infos(nodes, [])
    assert oracle.least_allocated_score(pod, infos[0]) == case["want"]
    _feats, res = _engine_result(nodes, [], [pod])
    si = res.plugin_names.index("NodeResourcesFit")
    assert int(res.scores[0, si, 0]) == case["want"]


def test_taint_toleration_fixture():
    nodes = [
        make_node(
            f"n{i}",
            taints=[
                {"key": f"k{j}", "value": "v", "effect": "PreferNoSchedule"}
                for j in range(count)
            ],
        )
        for i, count in enumerate(fx.TAINT_PREFER_COUNTS)
    ]
    pod = make_pod("p0")
    infos = oracle.build_node_infos(nodes, [])
    raw = [oracle.taint_toleration_score(pod, info) for info in infos]
    assert raw == fx.TAINT_EXPECT_RAW
    assert oracle.default_normalize_score(raw, reverse=True) == fx.TAINT_EXPECT_NORMALIZED

    _feats, res = _engine_result(nodes, [], [pod])
    si = res.plugin_names.index("TaintToleration")
    weight = 3  # upstream default-profile weight (default_plugins.go)
    got_raw = [int(res.scores[0, si, ni]) for ni in range(3)]
    got_final = [int(res.final_scores[0, si, ni]) for ni in range(3)]
    assert got_raw == fx.TAINT_EXPECT_RAW
    assert got_final == [s * weight for s in fx.TAINT_EXPECT_NORMALIZED]


@pytest.mark.parametrize("case", fx.IMAGE_LOCALITY_CASES, ids=lambda c: c["name"])
def test_image_locality_fixture(case):
    nodes = []
    for name in ("node-a", "node-b"):
        node = make_node(name)
        node["status"]["images"] = [
            {"names": [img], "sizeBytes": meta["size"]}
            for img, meta in case["images"].items()
            if name in meta["on"]
        ]
        nodes.append(node)
    pod = make_pod("p0")
    pod["spec"]["containers"] = [
        {"name": f"c{i}", "image": img, "resources": {"requests": {"cpu": "100m"}}}
        for i, img in enumerate(case["pod_images"])
    ]

    states = oracle.build_image_states(nodes)
    for ni, node in enumerate(nodes):
        want = case["want"][node["metadata"]["name"]]
        assert oracle.image_locality_score(pod, node, states, len(nodes)) == want

    _feats, res = _engine_result(nodes, [], [pod])
    si = res.plugin_names.index("ImageLocality")
    for ni, node in enumerate(nodes):
        assert int(res.scores[0, si, ni]) == case["want"][node["metadata"]["name"]]


# -- PodTopologySpread -------------------------------------------------------


def _spread_cluster(existing_counts):
    zones = {"node-a": "z1", "node-b": "z1", "node-x": "z2", "node-y": "z2"}
    nodes = [
        make_node(n, labels={ZONE_KEY: z, HOST_KEY: n}) for n, z in zones.items()
    ]
    bound = []
    for node_name, count in existing_counts.items():
        for i in range(count):
            bound.append(
                make_pod(f"e-{node_name}-{i}", labels={"foo": "bar"}, node_name=node_name)
            )
    return nodes, bound


def _spread_con(key):
    return {
        "maxSkew": 1,
        "topologyKey": key,
        "whenUnsatisfiable": "DoNotSchedule",
        "labelSelector": {"matchLabels": {"foo": "bar"}},
    }


@pytest.mark.parametrize(
    "keys,expect",
    [
        ((ZONE_KEY,), fx.SPREAD_ZONE_ONLY_EXPECT),
        ((HOST_KEY,), fx.SPREAD_HOSTNAME_ONLY_EXPECT),
        ((ZONE_KEY, HOST_KEY), fx.SPREAD_BOTH_EXPECT),
    ],
    ids=["zone-only", "hostname-only", "both"],
)
def test_topology_spread_filter_fixture(keys, expect):
    nodes, bound = _spread_cluster(fx.SPREAD_EXISTING)
    pod = make_pod(
        "incoming",
        labels={"foo": "bar"},
        topology_spread_constraints=[_spread_con(k) for k in keys],
    )
    infos = oracle.build_node_infos(nodes, bound)
    rows = oracle.topology_spread_filter_all(pod, infos, pods_by_node(bound))
    for info, reasons in zip(infos, rows):
        assert bool(reasons) == expect[info["name"]], info["name"]

    _feats, res = _engine_result(nodes, bound, [pod])
    fi = res.filter_plugin_names.index("PodTopologySpread")
    for ni, info in enumerate(infos):
        got_violates = int(res.reason_bits[0, fi, ni]) != 0
        assert got_violates == expect[info["name"]], info["name"]


def test_topology_spread_score_ordering_fixture():
    nodes, bound = _spread_cluster(fx.SPREAD_SCORE_EXISTING)
    con = dict(_spread_con(HOST_KEY), whenUnsatisfiable="ScheduleAnyway")
    pod = make_pod("incoming", labels={"foo": "bar"}, topology_spread_constraints=[con])

    _feats, res = _engine_result(nodes, bound, [pod])
    si = res.plugin_names.index("PodTopologySpread")
    by_name = {
        info["name"]: int(res.final_scores[0, si, ni])
        for ni, info in enumerate(oracle.build_node_infos(nodes, bound))
    }
    # Fewer matching pods in the candidate's host domain => higher score.
    assert by_name["node-x"] == by_name["node-y"]
    assert by_name["node-x"] > by_name["node-b"] > by_name["node-a"]


# -- InterPodAffinity --------------------------------------------------------


def _ipa_cluster():
    zones = {"node-a": "z1", "node-b": "z1", "node-x": "z2", "node-y": "z2"}
    return [make_node(n, labels={ZONE_KEY: z, HOST_KEY: n}) for n, z in zones.items()]


def _ipa_term(key, match_labels, weight=None):
    term = {
        "labelSelector": {"matchLabels": match_labels},
        "topologyKey": key,
    }
    if weight is not None:
        return {"weight": weight, "podAffinityTerm": term}
    return term


def _assert_ipa_filter(nodes, bound, pod, expect):
    infos = oracle.build_node_infos(nodes, bound)
    rows = oracle.inter_pod_affinity_filter_all(pod, infos, pods_by_node(bound))
    for info, reasons in zip(infos, rows):
        assert (not reasons) == expect[info["name"]], ("oracle", info["name"])

    _feats, res = _engine_result(nodes, bound, [pod])
    fi = res.filter_plugin_names.index("InterPodAffinity")
    for ni, info in enumerate(infos):
        passes = int(res.reason_bits[0, fi, ni]) == 0
        assert passes == expect[info["name"]], ("kernel", info["name"])


def test_interpod_required_affinity_fixture():
    nodes = _ipa_cluster()
    bound = [make_pod("db0", labels={"app": "db"}, node_name="node-a")]
    pod = make_pod("incoming")
    pod["spec"]["affinity"] = {
        "podAffinity": {
            "requiredDuringSchedulingIgnoredDuringExecution": [
                _ipa_term(ZONE_KEY, {"app": "db"})
            ]
        }
    }
    _assert_ipa_filter(nodes, bound, pod, fx.IPA_REQUIRED_AFFINITY_EXPECT)


def test_interpod_required_anti_affinity_fixture():
    nodes = _ipa_cluster()
    bound = [make_pod("web0", labels={"app": "web"}, node_name="node-x")]
    pod = make_pod("incoming")
    pod["spec"]["affinity"] = {
        "podAntiAffinity": {
            "requiredDuringSchedulingIgnoredDuringExecution": [
                _ipa_term(ZONE_KEY, {"app": "web"})
            ]
        }
    }
    _assert_ipa_filter(nodes, bound, pod, fx.IPA_REQUIRED_ANTI_EXPECT)


def test_interpod_existing_anti_affinity_fixture():
    nodes = _ipa_cluster()
    guard = make_pod("guard", node_name="node-b")
    guard["spec"]["affinity"] = {
        "podAntiAffinity": {
            "requiredDuringSchedulingIgnoredDuringExecution": [
                _ipa_term(HOST_KEY, {"team": "t1"})
            ]
        }
    }
    pod = make_pod("incoming", labels={"team": "t1"})
    _assert_ipa_filter(nodes, [guard], pod, fx.IPA_EXISTING_ANTI_EXPECT)


def test_interpod_preferred_affinity_normalized_fixture():
    nodes = _ipa_cluster()
    bound = [make_pod("db0", labels={"app": "db"}, node_name="node-a")]
    pod = make_pod("incoming")
    pod["spec"]["affinity"] = {
        "podAffinity": {
            "preferredDuringSchedulingIgnoredDuringExecution": [
                _ipa_term(ZONE_KEY, {"app": "db"}, weight=fx.IPA_PREFERRED_WEIGHT)
            ]
        }
    }
    infos = oracle.build_node_infos(nodes, bound)
    raw, normalized = oracle.inter_pod_affinity_score_all(
        pod, infos, pods_by_node(bound), [True] * len(infos)
    )
    for info, n in zip(infos, normalized):
        assert n == fx.IPA_PREFERRED_EXPECT_NORMALIZED[info["name"]], ("oracle", info["name"])

    _feats, res = _engine_result(nodes, bound, [pod])
    si = res.plugin_names.index("InterPodAffinity")
    plugin_weight = 2  # upstream default-profile weight
    for ni, info in enumerate(infos):
        want = fx.IPA_PREFERRED_EXPECT_NORMALIZED[info["name"]] * plugin_weight
        assert int(res.final_scores[0, si, ni]) == want, ("kernel", info["name"])
