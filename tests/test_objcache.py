"""Per-object memo contracts: store writes invalidate by object identity,
events share frozen objects without being corrupted by later writes."""

import numpy as np

from ksim_tpu.state import objcache
from ksim_tpu.state.cluster import ClusterStore
from ksim_tpu.state.featurizer import Featurizer
from ksim_tpu.state.resources import pod_requests
from tests.helpers import make_node, make_pod


def test_store_write_yields_fresh_object_and_fresh_parse():
    store = ClusterStore()
    store.create("pods", make_pod("p1", cpu="1"))
    before = store.list("pods", copy_objs=False)[0]
    req1 = pod_requests(before)
    assert req1["cpu"] == 1000
    assert pod_requests(before) is req1  # memo hit on the same object

    def bump(obj):
        obj["spec"]["containers"][0]["resources"]["requests"]["cpu"] = "2"

    store.patch("pods", "p1", "default", bump)
    after = store.list("pods", copy_objs=False)[0]
    assert after is not before  # writes replace, never mutate
    assert pod_requests(after)["cpu"] == 2000
    assert pod_requests(before)["cpu"] == 1000  # old object's parse intact


def test_delete_event_does_not_mutate_shared_object():
    store = ClusterStore()
    store.create("nodes", make_node("n1"))
    stored = store.list("nodes", copy_objs=False)[0]
    rv_before = stored["metadata"]["resourceVersion"]
    stream = store.watch(("nodes",))
    store.delete("nodes", "n1")
    # The DELETED event carries a bumped rv on a re-wrapped object; the
    # previously shared dict keeps its original rv (frozen contract).
    assert stored["metadata"]["resourceVersion"] == rv_before
    ev = stream.next(timeout=1)
    stream.close()
    assert ev is not None and ev.event_type == "DELETED"
    assert ev.obj["metadata"]["resourceVersion"] != rv_before


def test_featurize_consistent_across_memo_flush():
    nodes = [make_node(f"n{i}", cpu="4") for i in range(4)]
    pods = [make_pod(f"p{i}", cpu="1") for i in range(6)]
    f = Featurizer()
    a = f.featurize(nodes, pods)
    objcache.clear()
    b = f.featurize(nodes, pods)
    np.testing.assert_array_equal(a.nodes.allocatable, b.nodes.allocatable)
    np.testing.assert_array_equal(a.pods.requests, b.pods.requests)


def test_maybe_flush_sweeps_only_stale_entries(monkeypatch):
    objcache.clear()
    monkeypatch.setattr(objcache, "LIMIT", 4)
    objs = [{"i": i} for i in range(6)]
    for i, o in enumerate(objs):
        objcache.cached("slot", o, lambda i=i: i)
    assert objcache.stats()["entries"] == 6  # put never evicts inline
    # A sweep while everything is fresh reclaims nothing and doubles the
    # working limit instead of rescanning every pass.
    objcache.maybe_flush()
    assert objcache.stats()["entries"] == 6
    # Keep the first two warm; age the rest past STALE_GENERATIONS, then
    # grow the table over the doubled limit to trigger the next sweep.
    for _ in range(objcache.STALE_GENERATIONS + 1):
        objcache.maybe_flush()
        for o in objs[:2]:
            objcache.cached("slot", o, lambda: None)
    fresh = [{"j": j} for j in range(3)]
    for j, o in enumerate(fresh):
        objcache.cached("slot", o, lambda j=j: j)
    objcache.maybe_flush()
    st = objcache.stats()
    assert st["entries"] == 5  # 2 warm + 3 fresh; 4 stale swept
    assert st["refs"] == 5
    # Warm entries still serve their original values.
    assert objcache.cached("slot", objs[0], lambda: "recomputed") == 0
    objcache.clear()
