"""Incremental segment lowering: lower-cache validity + pipelined
executor containment (docs/churn_floor.md "Incremental lowering +
pipelined executor (round 10)").

The lowered-universe cache makes per-segment host lowering O(delta); its
entire correctness story is STRICT invalidation — any path the
incremental bookkeeping cannot track (a per-pass fallback step, a
rolled-back segment reconcile, an out-of-band store write, a breaker
trip) must flush it, and the behavior locks must hold byte-identically
with the cache and the double-buffered prelower fully on.  Small-stream
probes (tier-1) pin the mechanics against per-pass ground truth; the
slow-marked 6k runs pin the locked counts (repo CLAUDE.md) under each
invalidation class and run via ``make faults`` / the full suite.
"""

from __future__ import annotations

import jax
import pytest

from ksim_tpu.faults import FAULTS
from ksim_tpu.scenario import ScenarioRunner, churn_scenario
from ksim_tpu.scenario.runner import Operation
from ksim_tpu.state.cluster import ClusterStore

LOCK = (2524, 471)  # scheduled/unschedulable, seed 0 / 2000 nodes / 6k events


@pytest.fixture(autouse=True)
def _clean_fault_plane():
    FAULTS.reset()
    yield
    FAULTS.reset()


@pytest.fixture(autouse=True)
def _f32_fast_mode():
    jax.config.update("jax_enable_x64", False)
    yield
    jax.config.update("jax_enable_x64", True)


# ---------------------------------------------------------------------------
# Store mutation epoch (the cache's validity anchor)
# ---------------------------------------------------------------------------


def test_store_mutation_epoch_semantics():
    """Every write bumps the epoch EXCEPT writes staged in an
    epoch-exempt transaction (the segment reconcile); a rollback never
    delivers events and exempt writes never move the epoch either way."""
    store = ClusterStore()
    e0 = store.mutation_epoch
    store.create("nodes", {"metadata": {"name": "n1"}})
    assert store.mutation_epoch == e0 + 1
    store.patch("nodes", "n1", "", lambda o: o["metadata"].setdefault("labels", {}))
    assert store.mutation_epoch == e0 + 2
    store.delete("nodes", "n1")
    assert store.mutation_epoch == e0 + 3

    # Exempt transaction: commit moves the store, not the epoch.
    e1 = store.mutation_epoch
    with store.transaction(epoch_exempt=True):
        store.create("nodes", {"metadata": {"name": "n2"}})
    assert store.mutation_epoch == e1
    # Non-exempt transaction: its writes count.
    with store.transaction():
        store.create("nodes", {"metadata": {"name": "n3"}})
    assert store.mutation_epoch == e1 + 1
    # Exempt rollback: store restored, epoch still untouched.
    with pytest.raises(RuntimeError):
        with store.transaction(epoch_exempt=True):
            store.create("nodes", {"metadata": {"name": "n4"}})
            raise RuntimeError("abort")
    assert store.mutation_epoch == e1 + 1
    assert len(store.list("nodes")) == 2  # n2, n3


# ---------------------------------------------------------------------------
# Small-stream probes (tier-1): mechanics against per-pass ground truth
# ---------------------------------------------------------------------------


def _small_ops(extra=()):
    ops = list(churn_scenario(7, n_nodes=24, n_events=600, ops_per_step=40))
    ops.extend(extra)
    return ops


def _signature(res, store):
    return (
        res.pods_scheduled,
        res.unschedulable_attempts,
        [(s.step, s.scheduled, s.unschedulable, s.pending_after) for s in res.steps],
        {
            f"{p['metadata']['namespace']}/{p['metadata']['name']}": p["spec"].get(
                "nodeName"
            )
            for p in store.list("pods")
        },
    )


def _run(ops, device, runner_cls=ScenarioRunner, k=8):
    runner = runner_cls(
        max_pods_per_pass=64, device_replay=device, device_segment_steps=k
    )
    res = runner.run(list(ops))
    return runner, _signature(res, runner.store)


def test_cache_and_pipeline_match_per_pass_small():
    """The steady-state happy path: cache hits + consumed speculative
    prefixes, zero invalidations, and stepwise equality with the
    per-pass ground truth."""
    ops = _small_ops()
    _base, sig_base = _run(ops, device=False)
    dev, sig_dev = _run(ops, device=True)
    assert sig_dev == sig_base
    d = dev.replay_driver
    cache = d.stats()["lower_cache"]
    assert cache["hits"] >= 1
    assert cache["invalidations"] == 0
    assert d.prelower_consumed >= 1
    assert d.prelower_discarded == 0
    # O(delta): every cache-hit lower built at most O(window events)
    # fresh featurize rows, never the whole universe.
    for entry in d.lower_log:
        if entry["cache_hit"]:
            assert entry["rows_built"] <= entry["events"] + 32


def test_mid_stream_fallback_discards_prefix_and_invalidates():
    """An op outside the tensor vocabulary (a patch) forces a per-pass
    fallback mid-stream: the speculative prefix for the shifted window
    is discarded, the cache strictly invalidates, and — because the
    per-pass path is the ground truth being fallen back to — the
    outcomes still match the pure per-pass replay exactly."""
    # An inert node-annotation patch: the per-pass path applies it (no
    # scheduling effect), the device path rejects the step (op:patch).
    # The target must exist at the patch step — replay the node events
    # up to it to pick one that does.
    base = _small_ops()
    live: set[str] = set()
    for op in sorted(base, key=lambda o: o.step):
        if op.step > 8:
            break
        if op.kind == "nodes":
            if op.op == "create":
                live.add(op.obj["metadata"]["name"])
            elif op.op == "delete":
                live.discard(op.name)
    patch = Operation(
        step=8,
        op="patch",
        kind="nodes",
        obj={"metadata": {"annotations": {"oob": "1"}}},
        name=sorted(live)[0],
    )
    ops = base + [patch]
    # K=4 so enough windows run on BOTH sides of the fallback to observe
    # the cache warming, flushing, and warming again.
    _base, sig_base = _run(ops, device=False, k=4)
    dev, sig_dev = _run(ops, device=True, k=4)
    assert sig_dev == sig_base
    d = dev.replay_driver
    assert d.fallback_steps >= 1
    assert d.unsupported.get("op:patch/nodes", 0) >= 1
    cache = d.stats()["lower_cache"]
    assert cache["invalidations"] >= 1
    # The head-rejected window never reaches _take_spec (the pre-span
    # op screen rejects first), so its speculative prefix is discarded
    # by the fallback wrapper; untouched windows still consume theirs.
    assert d.prelower_consumed >= 1
    assert d.prelower_discarded >= 1
    # The cache recovers after the fallback: at least one pre-fallback
    # hit and at least one post-rebuild hit.
    assert cache["hits"] >= 2


def test_unpredicted_window_shift_discards_speculative_prefix():
    """A device error mid-stream shifts the next window by ONE step
    instead of the speculated n_steps: the held prefix can no longer
    match and must be discarded, never consumed against the wrong
    window."""
    # call:1 — the FIRST dispatch fails, while a speculative prefix for
    # the window after it is already held (a later fault could land on
    # the stream tail, where there is nothing left to speculate about).
    FAULTS.arm("replay.dispatch", "call:1")
    ops = _small_ops()
    _base, sig_base = _run(ops, device=False)
    dev, sig_dev = _run(ops, device=True)
    assert sig_dev == sig_base
    d = dev.replay_driver
    assert FAULTS.fired("replay.dispatch") == 1
    assert d.device_errors == 1
    # The prefix speculated during the failed dispatch was discarded
    # (the window it predicted never ran).  No invalidation: the fault
    # hit before the cache ever became valid — invalidate() counts only
    # flushes of real state (the 6k rollback test covers the warm case).
    assert d.prelower_discarded >= 1
    assert d.stats()["lower_cache"]["invalidations"] == 0


class _OutOfBandRunner(ScenarioRunner):
    """Writes an inert object to the store after each committed segment
    — the out-of-band mutation class the epoch counter exists to catch.
    A PriorityClass no pod references cannot change any outcome."""

    def __init__(self, *a, **kw):
        super().__init__(*a, **kw)
        self._oob = 0

    def _commit_segment(self, *a, **kw):
        out = super()._commit_segment(*a, **kw)
        if out:
            self._oob += 1
            self.store.create(
                "priorityclasses",
                {"metadata": {"name": f"oob-{self._oob}"}, "value": 7},
            )
        return out


def test_out_of_band_store_write_invalidates_cache_small():
    ops = _small_ops()
    _base, sig_base = _run(ops, device=False)
    dev, sig_dev = _run(ops, device=True, runner_cls=_OutOfBandRunner)
    assert sig_dev == sig_base
    cache = dev.replay_driver.stats()["lower_cache"]
    # Every post-commit write moved the epoch, so every subsequent
    # lower rebuilt from the store instead of trusting the cache.
    assert cache["invalidations"] >= 1
    assert cache["hits"] == 0


def test_stale_featurizer_slot_name_survives_lowering():
    """A node deleted on a per-pass step whose scheduling pass has an
    EMPTY queue lingers in the service featurizer's slot map (the
    canonical path skips the sync entirely).  The next lowered window's
    incremental rank seed iterates that map and must SKIP the stale
    name — it has no universe slot — instead of raising KeyError."""
    from tests.helpers import make_node, make_pod

    def ops():
        out = [
            Operation(step=0, op="create", kind="nodes", obj=make_node(f"n{i}"))
            for i in range(3)
        ]
        out.append(Operation(step=0, op="create", kind="pods", obj=make_pod("p0")))
        # Step 1 runs per-pass (the patch is an op-vocabulary head miss)
        # and its pass sees an empty queue (p0 bound at step 0), so the
        # featurizer never syncs away the deleted n2.
        out.append(
            Operation(
                step=1,
                op="patch",
                kind="nodes",
                obj={"metadata": {"annotations": {"x": "1"}}},
                name="n0",
            )
        )
        out.append(Operation(step=1, op="delete", kind="nodes", name="n2"))
        # Step 2 lowers on-device again, with n2 still in the slot map.
        out.append(Operation(step=2, op="create", kind="pods", obj=make_pod("p1")))
        return out

    _base, sig_base = _run(ops(), device=False)
    dev, sig_dev = _run(ops(), device=True)
    assert sig_dev == sig_base
    # Both the pre-patch window and the post-delete window ran on-device
    # (the KeyError class would have crashed the second lowering).
    assert dev.replay_driver.device_steps >= 2


class _SchedReconfigRunner(ScenarioRunner):
    """Swaps the scheduler profile set after the FIRST committed segment
    — the epoch-BLIND out-of-band mutation class: apply_scheduler_config
    writes no store object, so only the cache's sched_names token can
    see that the cached survivors' support screen is stale."""

    def __init__(self, *a, **kw):
        super().__init__(*a, **kw)
        self._reconfigured = False

    def _commit_segment(self, *a, **kw):
        out = super()._commit_segment(*a, **kw)
        if out and not self._reconfigured:
            self._reconfigured = True
            self.service.apply_scheduler_config(
                {"profiles": [{"schedulerName": "other-sched"}]}, trusted=True
            )
        return out


def test_scheduler_reconfig_invalidates_cache_small():
    """After the swap every pending pod is foreign to the new profile.
    The rebuilt (NOT cached) screen must reject the next window to the
    per-pass path — whose queue skips foreign pods too — so scheduling
    stops at the swap instead of the stale cached universe smuggling
    default-profile pods onto the device."""
    ops = _small_ops()
    _clean, sig_clean = _run(ops, device=True)
    dev = _SchedReconfigRunner(
        max_pods_per_pass=64, device_replay=True, device_segment_steps=8
    )
    res = dev.run(list(ops))
    d = dev.replay_driver
    cache = d.stats()["lower_cache"]
    assert cache["invalidations"] >= 1
    assert d.unsupported.get("foreign_scheduler", 0) >= 1
    assert d.fallback_steps >= 1
    # Strictly fewer binds than the un-reconfigured run: nothing
    # schedules after the first (K=8) segment commits.
    assert res.pods_scheduled < sig_clean[0]
    assert all(s.scheduled == 0 for s in res.steps if s.step >= 8)


def test_prelower_fault_degrades_window_only_small():
    """An armed fault in the SPECULATIVE prefix loses that window's
    overlap and nothing else: no fallback step, no cache flush, same
    outcomes."""
    FAULTS.arm("replay.prelower", "call:1")
    ops = _small_ops()
    _base, sig_base = _run(ops, device=False)
    dev, sig_dev = _run(ops, device=True)
    assert sig_dev == sig_base
    d = dev.replay_driver
    assert FAULTS.fired("replay.prelower") == 1
    assert d.prelower_faults == 1
    assert d.fallback_steps == 0
    assert d.stats()["lower_cache"]["invalidations"] == 0


# ---------------------------------------------------------------------------
# The locked 6k prefix under each invalidation class (slow; make faults)
# ---------------------------------------------------------------------------


def _run_6k(runner_cls=ScenarioRunner):
    runner = runner_cls(
        max_pods_per_pass=1024,
        pod_bucket_min=128,
        device_replay=True,
        device_segment_steps=16,
    )
    res = runner.run(
        churn_scenario(0, n_nodes=2000, n_events=6000, ops_per_step=100)
    )
    return runner, res


def _assert_lock(res, driver):
    assert (res.pods_scheduled, res.unschedulable_attempts) == LOCK
    assert driver.device_steps + driver.fallback_steps == len(res.steps)


@pytest.mark.slow
def test_lock_holds_with_midstream_fallback_invalidation_6k():
    """A mid-stream lowering fault forces one window per-pass: the
    speculative prefix is discarded, the cache flushes and then
    recovers, and the locked counts hold byte-identically."""
    FAULTS.arm("replay.lower", "call:2")
    runner, res = _run_6k()
    d = runner.replay_driver
    _assert_lock(res, d)
    assert FAULTS.fired("replay.lower") == 1
    cache = d.stats()["lower_cache"]
    assert cache["invalidations"] >= 1
    assert cache["hits"] >= 1  # recovered after the fallback


@pytest.mark.slow
def test_lock_holds_with_rollback_invalidation_6k():
    """A mid-reconcile injected fault rolls the segment back
    (ClusterStore.transaction abort): the cache flushes, the head step
    re-runs per-pass, and the locked counts hold.  call:17 = the FIRST
    step of the SECOND segment's reconcile (the site fires per step,
    K=16), so the cache is warm when the rollback flushes it."""
    FAULTS.arm("replay.reconcile", "call:17")
    runner, res = _run_6k()
    d = runner.replay_driver
    _assert_lock(res, d)
    assert FAULTS.fired("replay.reconcile") == 1
    assert d.unsupported.get("reconcile_fault") == 1
    assert d.stats()["lower_cache"]["invalidations"] >= 1
    # The prefix speculated during the rolled-back segment's dispatch
    # predicted a window that never ran: discarded, not consumed.
    assert d.prelower_discarded >= 1


@pytest.mark.slow
def test_lock_holds_with_out_of_band_writes_6k():
    runner, res = _run_6k(runner_cls=_OutOfBandRunner)
    d = runner.replay_driver
    _assert_lock(res, d)
    cache = d.stats()["lower_cache"]
    assert cache["invalidations"] >= 1
    assert cache["hits"] == 0


@pytest.mark.slow
def test_lock_holds_with_prelower_fault_6k():
    """The replay.prelower fault site (faults.SITES): an armed fault in
    the speculative prefix degrades that window's overlap only — every
    step still runs on-device and the locked counts hold."""
    FAULTS.arm("replay.prelower", "call:1")
    runner, res = _run_6k()
    d = runner.replay_driver
    _assert_lock(res, d)
    assert FAULTS.fired("replay.prelower") == 1
    assert d.prelower_faults == 1
    assert d.fallback_steps == 0


# ---------------------------------------------------------------------------
# Round 17: startup AOT prewarm (KSIM_AOT_PREWARM — load-only warm start)
# ---------------------------------------------------------------------------


@pytest.fixture()
def _clean_aot_plane():
    """Process-wide prewarm registry + compile cache, restored after."""
    import ksim_tpu.engine.replay as R
    from ksim_tpu.engine.compilecache import COMPILE_CACHE

    with R._PREWARM_LOCK:
        R._PREWARMED.clear()
    COMPILE_CACHE.reset()
    yield
    with R._PREWARM_LOCK:
        R._PREWARMED.clear()
    COMPILE_CACHE.reset()


def _prewarm_stream():
    from tests.helpers import make_node, make_pod

    for i in range(4):
        yield Operation(
            step=0, op="create", kind="nodes",
            obj=make_node(f"n-{i}", cpu="8", memory="16Gi"),
        )
    for step in (1, 2, 3):
        yield Operation(
            step=step, op="create", kind="pods",
            obj=make_pod(f"p-{step}", cpu="500m", memory="512Mi"),
        )


def test_aot_prewarm_serves_without_deserializing(
    tmp_path, monkeypatch, _clean_aot_plane
):
    """The startup pass (prewarm_aot_cache) deserializes every on-disk
    rung ONCE; the first tenant dispatch of each rung is then served
    from the prewarm registry.  Proof: with jax.export.deserialize
    broken after the prewarm, a cold-cache run still lands every disk
    load as a hit with ZERO evictions — the dispatch path never needed
    the deserializer."""
    import os

    import ksim_tpu.engine.replay as R
    from ksim_tpu.engine.compilecache import COMPILE_CACHE

    monkeypatch.setenv("KSIM_AOT_CACHE", str(tmp_path))
    runner = ScenarioRunner(device_replay=True, device_segment_steps=4)
    runner.run(_prewarm_stream())
    assert runner.replay_driver.device_steps >= 1
    stored = [f for f in os.listdir(tmp_path) if f.endswith(".aot")]
    assert stored, "seeding run persisted no AOT entries"
    assert COMPILE_CACHE.snapshot()["disk_stores"] >= 1

    # "Restarted server": cold in-memory cache, same disk.
    COMPILE_CACHE.reset()
    n = R.prewarm_aot_cache()
    assert n == len(stored)
    snap = COMPILE_CACHE.snapshot()
    assert snap["disk_prewarmed"] == n
    with R._PREWARM_LOCK:
        assert len(R._PREWARMED) == n

    def boom(_blob):
        raise AssertionError("dispatch path deserialized despite prewarm")

    monkeypatch.setattr("jax.export.deserialize", boom)
    runner2 = ScenarioRunner(device_replay=True, device_segment_steps=4)
    runner2.run(_prewarm_stream())
    assert runner2.replay_driver.device_steps >= 1
    snap2 = COMPILE_CACHE.snapshot()
    assert snap2["disk_hits"] >= 1
    assert snap2["disk_evictions"] == 0, snap2


def test_aot_prewarm_skips_foreign_entries_without_evicting(
    tmp_path, monkeypatch, _clean_aot_plane
):
    """Load-only means load-only: a foreign-version token, a corrupt
    blob and a garbage header are all SKIPPED — counted nowhere,
    deleted never (eviction authority stays with the dispatch path's
    token check)."""
    import json
    import os
    import zlib

    import ksim_tpu.engine.replay as R
    from ksim_tpu.engine.compilecache import COMPILE_CACHE

    monkeypatch.setenv("KSIM_AOT_CACHE", str(tmp_path))
    blob = b"not-an-executable"

    def entry(token, payload, crc=None):
        header = json.dumps(
            {"v": 1, "key": token, "crc": crc if crc is not None else (zlib.crc32(payload) & 0xFFFFFFFF)}
        ).encode()
        return header + b"\n" + payload

    foreign = f"jax-9.9.9|cpu|d{jax.device_count()}|rest"
    native_prefix = f"{jax.__version__}|{jax.default_backend()}|d{jax.device_count()}|rest"
    (tmp_path / "foreign.aot").write_bytes(entry(foreign, blob))
    # Native prefix but the blob is not a serialized executable: the
    # deserialize attempt fails and the entry is skipped in place.
    (tmp_path / "undeser.aot").write_bytes(entry(native_prefix, blob))
    (tmp_path / "corrupt.aot").write_bytes(entry(native_prefix, blob, crc=1))
    (tmp_path / "garbage.aot").write_bytes(b"\x00 no header here")

    assert R.prewarm_aot_cache() == 0
    assert COMPILE_CACHE.snapshot()["disk_prewarmed"] == 0
    assert COMPILE_CACHE.snapshot()["disk_evictions"] == 0
    with R._PREWARM_LOCK:
        assert not R._PREWARMED
    assert sorted(os.listdir(tmp_path)) == [
        "corrupt.aot", "foreign.aot", "garbage.aot", "undeser.aot",
    ]


def test_aot_speculative_rescan_picks_up_new_entries(
    tmp_path, monkeypatch, _clean_aot_plane
):
    """Round 20 (fleet prewarm): a speculative pass re-reads the shared
    disk plane and warms ONLY entries it has never seen — the mechanism
    that turns one fleet worker's compile into every peer's warm start.
    Counted under disk_speculative (not disk_prewarmed), idempotent
    when nothing new landed, and the background rescan loop drives the
    same pass on its interval."""
    import os
    import shutil
    import threading
    import time

    import ksim_tpu.engine.replay as R
    from ksim_tpu.engine.compilecache import COMPILE_CACHE

    monkeypatch.setenv("KSIM_AOT_CACHE", str(tmp_path))
    runner = ScenarioRunner(device_replay=True, device_segment_steps=4)
    runner.run(_prewarm_stream())
    stored = sorted(f for f in os.listdir(tmp_path) if f.endswith(".aot"))
    assert stored, "seeding run persisted no AOT entries"

    COMPILE_CACHE.reset()
    n = R.prewarm_aot_cache()
    assert n == len(stored)
    base = COMPILE_CACHE.snapshot()
    assert base["disk_prewarmed"] == n
    assert base["disk_speculative"] == 0

    # A peer worker lands a new entry in the shared plane (stand-in: a
    # copy of an existing entry under a fresh name — the registry is
    # keyed by path, so this is "a file we have never deserialized").
    shutil.copyfile(tmp_path / stored[0], tmp_path / "peer-0.aot")
    assert R.prewarm_aot_cache(speculative=True) == 1
    snap = COMPILE_CACHE.snapshot()
    assert snap["disk_speculative"] == 1
    assert snap["disk_prewarmed"] == n  # startup counter untouched
    # Nothing new on disk: the speculative pass is a no-op, not a
    # re-count.
    assert R.prewarm_aot_cache(speculative=True) == 0
    assert COMPILE_CACHE.snapshot()["disk_speculative"] == 1

    # The background loop: a full startup pass, then speculative
    # rescans on the interval.  Wait for the startup pass (it bumps
    # disk_prewarmed), THEN land a new peer entry and watch the rescan
    # pick it up as speculative.
    prewarmed_before = COMPILE_CACHE.snapshot()["disk_prewarmed"]
    stop = threading.Event()
    t = threading.Thread(
        target=R.prewarm_rescan_loop,
        kwargs={"stop": stop, "interval_s": 0.05},
        daemon=True,
    )
    t.start()
    try:
        deadline = time.monotonic() + 10.0
        while time.monotonic() < deadline:
            if COMPILE_CACHE.snapshot()["disk_prewarmed"] > prewarmed_before:
                break
            time.sleep(0.02)
        shutil.copyfile(tmp_path / stored[0], tmp_path / "peer-1.aot")
        while time.monotonic() < deadline:
            if COMPILE_CACHE.snapshot()["disk_speculative"] >= 2:
                break
            time.sleep(0.02)
    finally:
        stop.set()
        t.join(timeout=5)
    assert not t.is_alive()
    assert COMPILE_CACHE.snapshot()["disk_speculative"] == 2
