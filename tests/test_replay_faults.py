"""Fault-plane schedules over the device-resident replay executor.

The reference simulator has no fault injection (SURVEY.md §5); round 8
adds a first-class fault plane (ksim_tpu/faults.py) and a crash-safe
replay executor: watchdogged dispatch, a sticky circuit breaker, and an
all-or-nothing segment reconcile (store transaction).  The invariant
under EVERY injected schedule is the behavior lock (repo CLAUDE.md):
seed 0, 2000 nodes, 6k events -> 2524/471, byte-identical — plus a
nonzero exercised-fault counter (a green run whose fault never fired
would be vacuous) and the degradation evidence the schedule promises.

The schedules here are the SHIPPED ones the acceptance criteria name:
dispatch error, dispatch hang (watchdog), mid-reconcile fault (rollback),
lowering fault, and permanent device failure (breaker trip).

Tier-1 budget: the canonical dispatch-error schedule and the breaker
trip run in the default suite; the other three 6k schedules are
slow-marked (each is a full 6k replay, ~30-45 s) and run via
``make faults``, which overrides the repo's default ``-m 'not slow'``
deselection.  Every small-stream probe stays tier-1.
"""

from __future__ import annotations

import jax
import pytest

from ksim_tpu.faults import FAULTS, InjectedFault
from ksim_tpu.scenario import ScenarioRunner, churn_scenario
from ksim_tpu.scenario.runner import Operation
from tests.helpers import make_node, make_pod

LOCK = (2524, 471)  # scheduled/unschedulable, seed 0 / 2000 nodes / 6k events


@pytest.fixture(autouse=True)
def _clean_fault_plane():
    FAULTS.reset()
    yield
    FAULTS.reset()


@pytest.fixture(autouse=True)
def _f32_fast_mode():
    # The locked counts hold in both modes; f32 is how the bench runs it.
    jax.config.update("jax_enable_x64", False)
    yield
    jax.config.update("jax_enable_x64", True)


def _run_6k():
    runner = ScenarioRunner(
        max_pods_per_pass=1024,
        pod_bucket_min=128,
        device_replay=True,
        device_segment_steps=16,
    )
    res = runner.run(
        churn_scenario(0, n_nodes=2000, n_events=6000, ops_per_step=100)
    )
    return runner, res


def _assert_lock(res, driver):
    assert (res.pods_scheduled, res.unschedulable_attempts) == LOCK
    # Step accounting stays exact under degradation: every step landed
    # through exactly one path (a rolled-back segment must not
    # double-book its steps as device AND fallback).
    assert driver.device_steps + driver.fallback_steps == len(res.steps)


# ---------------------------------------------------------------------------
# Shipped 6k schedules
# ---------------------------------------------------------------------------


def test_dispatch_error_degrades_to_host_path():
    """One injected dispatch failure: that segment re-runs per-pass
    under the ``device_error`` reason, the next dispatch succeeds (the
    breaker window resets), and the locked counts hold."""
    FAULTS.arm("replay.dispatch", "call:2")
    runner, res = _run_6k()
    driver = runner.replay_driver
    _assert_lock(res, driver)
    assert FAULTS.fired("replay.dispatch") == 1
    assert driver.device_errors == 1
    assert driver.unsupported.get("device_error") == 1
    assert not driver.breaker_tripped
    assert driver.device_steps >= 32  # the device path carried the run


@pytest.mark.slow
def test_dispatch_hang_watchdog_degrades(monkeypatch):
    """A hung dispatch (the wedged-chip-tunnel shape: block_until_ready
    never returns) is bounded by the watchdog and degrades instead of
    stalling the trajectory.  Deliberately loose on HOW FAR it degrades:
    the hung call 1 never reached the segment program's first
    trace/compile, so later dispatches pay it under the shortened test
    watchdog and may time out too (even trip the breaker) — the
    contract is that the run completes, bounded, with the locked
    counts, never that a hang is free."""
    monkeypatch.setenv("KSIM_REPLAY_WATCHDOG_S", "10")
    FAULTS.arm("replay.dispatch", "hang:15:1")  # first dispatch hangs 15s
    runner, res = _run_6k()
    driver = runner.replay_driver
    _assert_lock(res, driver)
    assert FAULTS.fired("replay.dispatch") == 1
    assert driver.watchdog_timeouts >= 1
    assert driver.device_errors >= driver.watchdog_timeouts


@pytest.mark.slow
def test_mid_reconcile_fault_rolls_back_atomically():
    """A fault in the middle of a segment's store reconcile rolls the
    WHOLE segment back (the store never observes a partially applied
    segment) and the segment re-runs per-pass — counts byte-identical."""
    FAULTS.arm("replay.reconcile", "call:2")  # second staged step faults
    runner, res = _run_6k()
    driver = runner.replay_driver
    _assert_lock(res, driver)
    assert FAULTS.fired("replay.reconcile") == 1
    assert driver.unsupported.get("reconcile_fault") == 1
    assert driver.device_steps >= 32


@pytest.mark.slow
def test_lowering_fault_classified_fallback():
    """An expected (SimulatorError) lowering failure falls back under
    the stable ``lowering_fault`` reason instead of crashing or being
    silently swallowed."""
    FAULTS.arm("replay.lower", "first:2")
    runner, res = _run_6k()
    driver = runner.replay_driver
    _assert_lock(res, driver)
    assert FAULTS.fired("replay.lower") == 2
    assert driver.unsupported.get("lowering_fault") == 2
    assert driver.device_steps >= 32


def test_permanent_device_failure_trips_breaker(monkeypatch):
    """A permanently failing backend costs exactly breaker-threshold
    failed dispatches, then the sticky breaker disables the device path
    and the whole run completes per-pass — no per-segment timeout tax,
    locked counts intact."""
    monkeypatch.setenv("KSIM_REPLAY_BREAKER_N", "2")
    FAULTS.arm("replay.dispatch", "always")
    runner, res = _run_6k()
    driver = runner.replay_driver
    _assert_lock(res, driver)
    assert FAULTS.fired("replay.dispatch") == 2  # breaker stops the bleeding
    assert driver.breaker_tripped
    assert driver.device_errors == 2
    assert driver.unsupported.get("device_error") == 2
    assert driver.unsupported.get("breaker_open", 0) > 0
    assert driver.device_steps == 0
    assert driver.fallback_steps == len(res.steps)


@pytest.mark.slow
def test_breaker_half_open_probe_closes(monkeypatch):
    """Half-open recovery (round 15): with KSIM_REPLAY_BREAKER_COOLDOWN_S
    set, a tripped breaker admits ONE probe segment after the cooldown;
    the injected fault is transient (first:1), so the probe dispatch
    succeeds, the breaker closes and the rest of the run is back on the
    device path."""
    monkeypatch.setenv("KSIM_REPLAY_BREAKER_N", "1")
    monkeypatch.setenv("KSIM_REPLAY_BREAKER_COOLDOWN_S", "0.05")
    FAULTS.arm("replay.dispatch", "first:1@device")
    runner = ScenarioRunner(
        max_pods_per_pass=1024, pod_bucket_min=128,
        device_replay=True, device_segment_steps=8,
    )
    runner.run(churn_scenario(0, n_nodes=100, n_events=1200, ops_per_step=40))
    d = runner.replay_driver
    assert d.breaker_probes >= 1
    assert d.breaker_closes >= 1
    assert d.breaker_reopens == 0
    assert d.breaker_tripped is False
    assert d.device_steps > 0  # post-close segments dispatched on-device
    b = d.stats()["breaker"]
    assert b["closes"] == d.breaker_closes
    assert b["cooldown_current_s"] == 0.05  # close resets the ladder


@pytest.mark.slow
def test_breaker_failed_probes_double_cooldown(monkeypatch):
    """A permanently dead backend: every probe fails, each failure
    re-opens with a DOUBLED cooldown (bounded), and the run still
    completes on the host path — recovery attempts never compromise
    the fallback guarantee."""
    monkeypatch.setenv("KSIM_REPLAY_BREAKER_N", "1")
    monkeypatch.setenv("KSIM_REPLAY_BREAKER_COOLDOWN_S", "0.05")
    FAULTS.arm("replay.dispatch", "always@device")
    runner = ScenarioRunner(
        max_pods_per_pass=1024, pod_bucket_min=128,
        device_replay=True, device_segment_steps=8,
    )
    res = runner.run(
        churn_scenario(0, n_nodes=100, n_events=1200, ops_per_step=40)
    )
    d = runner.replay_driver
    assert d.breaker_tripped is True
    assert d.breaker_reopens >= 1
    assert d.breaker_closes == 0
    assert d.device_steps == 0
    assert d.fallback_steps == len(res.steps)
    b = d.stats()["breaker"]
    # Doubled at least once, never past the base * 2**reopens ladder.
    assert b["cooldown_current_s"] >= 0.1
    assert b["cooldown_current_s"] == pytest.approx(
        min(0.05 * 2 ** d.breaker_reopens, 3600.0)
    )


def test_breaker_sticky_by_default(monkeypatch):
    """Without KSIM_REPLAY_BREAKER_COOLDOWN_S the breaker stays sticky:
    no probes, no closes — exactly the pre-round-15 contract."""
    monkeypatch.setenv("KSIM_REPLAY_BREAKER_N", "1")
    monkeypatch.delenv("KSIM_REPLAY_BREAKER_COOLDOWN_S", raising=False)
    FAULTS.arm("replay.dispatch", "always")
    runner = _small_runner()
    runner.run(_small_stream())
    d = runner.replay_driver
    assert d.breaker_tripped is True
    assert d.breaker_probes == 0
    assert d.breaker_closes == 0
    assert d.stats()["breaker"]["cooldown_s"] == 0.0


# ---------------------------------------------------------------------------
# Classification: programming errors must surface, not become fallbacks
# ---------------------------------------------------------------------------


def _small_stream():
    for i in range(4):
        yield Operation(
            step=0, op="create", kind="nodes",
            obj=make_node(f"n-{i}", cpu="8", memory="16Gi"),
        )
    for step in range(1, 5):
        yield Operation(
            step=step, op="create", kind="pods",
            obj=make_pod(f"p-{step}", cpu="500m", memory="512Mi"),
        )


def _small_runner():
    return ScenarioRunner(device_replay=True, device_segment_steps=4)


def test_planted_type_error_in_lowering_surfaces():
    """The taxonomy is classified, not a catch-all: a TypeError planted
    in lowering RE-RAISES instead of becoming a silent fallback."""
    FAULTS.arm("replay.lower", "call:1", exc=TypeError)
    with pytest.raises(TypeError, match="injected fault"):
        _small_runner().run(_small_stream())


def test_planted_type_error_in_dispatch_surfaces():
    FAULTS.arm("replay.dispatch", "call:1", exc=TypeError)
    with pytest.raises(TypeError, match="injected fault"):
        _small_runner().run(_small_stream())


def test_injected_lowering_fault_is_contained_on_small_stream():
    """The same site armed with the default (SimulatorError) class is
    contained — the run completes and matches the per-pass baseline."""
    base = ScenarioRunner().run(_small_stream())
    FAULTS.arm("replay.lower", "call:1")
    runner = _small_runner()
    dev = runner.run(_small_stream())
    assert [
        (s.step, s.scheduled, s.unschedulable) for s in dev.steps
    ] == [(s.step, s.scheduled, s.unschedulable) for s in base.steps]
    assert runner.replay_driver.unsupported.get("lowering_fault") == 1


# ---------------------------------------------------------------------------
# Atomicity probe: rolled-back segments leave byte-identical store state
# ---------------------------------------------------------------------------


def test_reconcile_rollback_store_matches_per_pass_baseline():
    """Small-stream end-to-end probe of reconcile atomicity: with a
    mid-reconcile fault forcing a rollback, the final store (every pod's
    node, phase, annotations) is byte-identical to the pure per-pass
    run, and no watcher ever saw an event from the rolled-back staging."""

    def state(runner):
        return sorted(
            (
                p["metadata"]["name"],
                p.get("spec", {}).get("nodeName"),
                p.get("status", {}).get("phase"),
            )
            for p in runner.store.list("pods")
        )

    base_r = ScenarioRunner()
    base = base_r.run(_small_stream())

    runner = _small_runner()
    stream = runner.store.watch(("pods",))
    FAULTS.arm("replay.reconcile", "call:1")
    dev = runner.run(_small_stream())
    assert FAULTS.fired("replay.reconcile") == 1
    assert runner.replay_driver.unsupported.get("reconcile_fault") == 1
    assert state(runner) == state(base_r)
    assert (dev.pods_scheduled, dev.unschedulable_attempts) == (
        base.pods_scheduled, base.unschedulable_attempts,
    )
    # Drain the watch queue: every MODIFIED bind event must name a pod
    # whose FINAL state carries that bind — a delivered event from a
    # rolled-back staging would have no matching final state.
    final = {name: node for name, node, _ph in state(runner)}
    while True:
        ev = stream.next(timeout=0)
        if ev is None:
            break
        node = ev.obj.get("spec", {}).get("nodeName")
        if ev.event_type == "MODIFIED" and node:
            assert final.get(ev.obj["metadata"]["name"]) == node
    stream.close()


def test_store_integrity_error_in_reconcile_surfaces():
    """Reconcile containment is scoped to InjectedFault: a NotFoundError
    raised mid-reconcile is a device-decode bug wearing a store-error
    class — it must roll back and then RE-RAISE, never be absorbed as a
    chaos fallback."""
    from ksim_tpu.errors import NotFoundError

    FAULTS.arm("replay.reconcile", "call:1", exc=NotFoundError)
    with pytest.raises(NotFoundError, match="injected fault"):
        _small_runner().run(_small_stream())


def test_persistent_reconcile_fault_trips_breaker(monkeypatch):
    """A reconcile that fails every time must not pay lowering +
    dispatch + rollback for every remaining step: consecutive rollbacks
    trip the same sticky breaker and the run completes per-pass with
    baseline-identical results."""
    monkeypatch.setenv("KSIM_REPLAY_BREAKER_N", "2")
    base = ScenarioRunner().run(_small_stream())
    FAULTS.arm("replay.reconcile", "always")
    runner = _small_runner()
    dev = runner.run(_small_stream())
    driver = runner.replay_driver
    assert driver.breaker_tripped
    assert driver.unsupported.get("reconcile_fault") == 2
    assert driver.device_steps == 0  # no segment ever committed
    assert [
        (s.step, s.scheduled, s.unschedulable) for s in dev.steps
    ] == [(s.step, s.scheduled, s.unschedulable) for s in base.steps]


def test_breaker_state_is_per_driver(monkeypatch):
    """Two runners in one process must not share breaker state: a run
    whose breaker tripped leaves the next run's device path intact."""
    FAULTS.arm("replay.dispatch", "always")
    monkeypatch.setenv("KSIM_REPLAY_BREAKER_N", "1")
    r1 = _small_runner()
    r1.run(_small_stream())
    assert r1.replay_driver.breaker_tripped
    monkeypatch.delenv("KSIM_REPLAY_BREAKER_N")
    FAULTS.reset()
    r2 = _small_runner()
    r2.run(_small_stream())
    assert not r2.replay_driver.breaker_tripped
    assert r2.replay_driver.device_steps > 0


# ---------------------------------------------------------------------------
# Fleet replay per-lane chaos (round 12, engine/fleet.py): a lane's
# PRIVATE fault plane (KSIM_FLEET_FAULTS) degrades that lane alone.
# Slow-marked for the tier-1 budget; `make faults` runs them (-m '').
# ---------------------------------------------------------------------------


def _fleet_sig(res):
    return [
        (s.step, s.scheduled, s.unschedulable, s.pending_after) for s in res.steps
    ]


def _fleet_churn():
    return churn_scenario(0, n_nodes=48, n_events=200, ops_per_step=20)


@pytest.mark.slow
def test_fleet_lane_fault_degrades_only_that_lane():
    """Per-lane chaos (KSIM_FLEET_FAULTS syntax): an injected dispatch
    fault on lane 2 degrades lane 2 alone — it diverges to the solo
    path, walks the device_error ladder, and still lands byte-identical
    counts; every other lane stays in the convergent cohort with zero
    degradation."""
    jax.config.update("jax_enable_x64", False)
    kw = dict(max_pods_per_pass=1024, pod_bucket_min=128, device_segment_steps=8)
    solo_r = ScenarioRunner(device_replay=True, **kw)
    solo = solo_r.run(_fleet_churn())
    fleet_r = ScenarioRunner(
        device_replay=True, fleet=4, fleet_faults="2:replay.dispatch=call:1", **kw
    )
    fleet_r.run(_fleet_churn())
    lanes = fleet_r.fleet_lanes
    for ln in lanes:
        assert _fleet_sig(ln.result) == _fleet_sig(solo), f"lane {ln.idx}"
    assert lanes[2].driver.device_errors == 1
    assert lanes[2].driver.unsupported.get("device_error") == 1
    assert not lanes[2].convergent
    assert lanes[2].driver.fallback_steps >= 1
    for ln in (lanes[0], lanes[1], lanes[3]):
        assert ln.driver.device_errors == 0
        assert ln.driver.fallback_steps == 0
        assert ln.convergent
    assert fleet_r.fleet_driver.stats()["divergences"] == 1


@pytest.mark.slow
def test_fleet_lane_reconcile_fault_rolls_back_only_that_lane():
    """A per-lane injected reconcile fault rolls back ONE lane's segment
    (its store byte-identical to the window start, the head step re-run
    per-pass) while the cohort commits; all lanes still converge on the
    solo counts."""
    jax.config.update("jax_enable_x64", False)
    kw = dict(max_pods_per_pass=1024, pod_bucket_min=128, device_segment_steps=8)
    solo_r = ScenarioRunner(device_replay=True, **kw)
    solo = solo_r.run(_fleet_churn())
    fleet_r = ScenarioRunner(
        device_replay=True, fleet=3, fleet_faults="1:replay.reconcile=call:1", **kw
    )
    fleet_r.run(_fleet_churn())
    lanes = fleet_r.fleet_lanes
    for ln in lanes:
        assert _fleet_sig(ln.result) == _fleet_sig(solo), f"lane {ln.idx}"
    assert lanes[1].driver.unsupported.get("reconcile_fault") == 1
    assert not lanes[1].convergent
    assert lanes[0].driver.unsupported.get("reconcile_fault") is None
    assert lanes[2].driver.unsupported.get("reconcile_fault") is None


@pytest.mark.slow
def test_fleet_leader_lane_lower_fault_degrades_leader_alone():
    """Review regression (round 12): a replay.lower fault armed on the
    COHORT LEADER's lane must fire exactly on its scheduled call and
    degrade the leader alone — not double-count through the shared
    lowering and not blast the whole cohort with lowering_fault."""
    jax.config.update("jax_enable_x64", False)
    kw = dict(max_pods_per_pass=1024, pod_bucket_min=128, device_segment_steps=8)
    solo_r = ScenarioRunner(device_replay=True, **kw)
    solo = solo_r.run(_fleet_churn())
    fleet_r = ScenarioRunner(
        device_replay=True, fleet=3, fleet_faults="0:replay.lower=call:1", **kw
    )
    fleet_r.run(_fleet_churn())
    lanes = fleet_r.fleet_lanes
    for ln in lanes:
        assert _fleet_sig(ln.result) == _fleet_sig(solo), f"lane {ln.idx}"
    assert lanes[0].driver.unsupported.get("lowering_fault") == 1
    assert lanes[0].driver.fallback_steps == 1
    assert not lanes[0].convergent
    for ln in lanes[1:]:
        assert "lowering_fault" not in ln.driver.unsupported, ln.driver.unsupported
        assert ln.driver.fallback_steps == 0
        assert ln.convergent
    # The lane plane fired exactly once (no gate+prepare double count).
    assert lanes[0].faults.fired("replay.lower") == 1
