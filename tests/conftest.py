"""Test env: CPU backend with 8 virtual devices (multi-chip sharding tests
run on a virtual mesh — real multi-chip hardware is validated separately by
the driver via __graft_entry__.dryrun_multichip), x64 enabled so the exact
int64 parity paths are active.

Note: this image's sitecustomize imports jax at interpreter start (axon TPU
plugin), so env vars are already baked into jax.config defaults — override
through jax.config.update, not os.environ.
"""

import os

_flags = os.environ.get("XLA_FLAGS", "")
if "host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (_flags + " --xla_force_host_platform_device_count=8").strip()

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_enable_x64", True)

assert jax.device_count() >= 8, "virtual device mesh not active"
