"""Test env: CPU backend with 8 virtual devices (multi-chip sharding tests
run on a virtual mesh — real multi-chip hardware is validated separately by
the driver via __graft_entry__.dryrun_multichip), x64 enabled so the exact
int64 parity paths are active.

Note: this image's sitecustomize imports jax at interpreter start (axon TPU
plugin), so env vars are already baked into jax.config defaults — override
through jax.config.update, not os.environ.
"""

import os

_flags = os.environ.get("XLA_FLAGS", "")
if "host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (_flags + " --xla_force_host_platform_device_count=8").strip()

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_enable_x64", True)

assert jax.device_count() >= 8, "virtual device mesh not active"

# Persistent compile cache (host-fingerprinted, ksim_tpu.util): the suite
# compiles many hundreds of XLA:CPU programs in one process, and this
# image's jaxlib segfaulted inside LLVM codegen late in two full-suite
# runs (reproducibly ~92% in, never in isolation).  A warm cache drops
# the per-process compile count to ~zero, which both sidesteps the crash
# and cuts suite wall-clock.  KSIM_COMPILE_CACHE=off disables.
import sys as _sys  # noqa: E402

_sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
from ksim_tpu.util import enable_compilation_cache  # noqa: E402

enable_compilation_cache()

# The real round-4 crash root cause: a full-suite process accumulates
# ~8k memory maps/min (every XLA:CPU executable mmaps code pages) and
# dies at the kernel's vm.max_map_count (65530 default) — SIGSEGV when
# it hits during LLVM codegen, SIGABRT during cache deserialization,
# always ~92% through the suite, never in half-suite runs (observed
# maps=62797 ten seconds before death).  Two best-effort guards: raise
# the limit (this image runs as root), and shed live executables when
# the map count nears the ceiling.


from ksim_tpu.util import raise_map_count_limit  # noqa: E402

raise_map_count_limit()

import pytest  # noqa: E402


@pytest.fixture(autouse=True, scope="module")
def _shed_executables_when_map_bound_nears():
    yield
    try:
        with open("/proc/self/maps") as f:
            n = sum(1 for _ in f)
    except OSError:
        return
    if n > 40_000:
        jax.clear_caches()
