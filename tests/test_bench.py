"""The driver bench must emit its one JSON line under any condition.

bench.py's parent process is stdlib-only and runs each rung in a
subprocess (see its module docstring for the round-1/round-2 failure
modes this guards against); these tests exercise the orchestrator
end-to-end on CPU and the guaranteed-emission paths.
"""

from __future__ import annotations

import json
import signal
import subprocess
import sys
import time

import pytest
from pathlib import Path

from tests.helpers import sanitized_cpu_env

REPO = Path(__file__).resolve().parent.parent


def _last_json_line(stdout: str) -> dict:
    lines = [ln for ln in stdout.strip().splitlines() if ln.startswith("{")]
    assert lines, f"no JSON line in stdout:\n{stdout[-2000:]}"
    return json.loads(lines[-1])


def test_bench_emits_json_on_cpu(tmp_path):
    proc = subprocess.run(
        [sys.executable, str(REPO / "bench.py"), "--only", "200x20", "--repeats", "1"],
        capture_output=True,
        text=True,
        timeout=420,
        cwd=REPO,
        env=sanitized_cpu_env(),
    )
    assert proc.returncode == 0, proc.stderr[-2000:]
    out = _last_json_line(proc.stdout)
    assert out["metric"] == "sched_pairs_per_sec"
    assert out["value"] > 0
    assert out["platform"] == "cpu"
    assert out["rungs"]["200x20"]["exact"] is True


def test_bench_emits_json_when_budget_exhausted():
    """With a near-zero budget every rung is skipped, but the line still
    prints with a non-null payload (the BENCH_r02 failure mode)."""
    env = sanitized_cpu_env({"BENCH_BUDGET_S": "1"})
    proc = subprocess.run(
        [sys.executable, str(REPO / "bench.py")],
        capture_output=True,
        text=True,
        timeout=120,
        cwd=REPO,
        env=env,
    )
    assert proc.returncode == 0, proc.stderr[-2000:]
    out = _last_json_line(proc.stdout)
    assert out["metric"] == "sched_pairs_per_sec"
    # Nothing ran: the payload must SAY why (top-level error, or every
    # attempted stage recorded as an error) — a bare value-0 line with no
    # explanation is the regression this test guards.
    stage_errors = [r for r in out["rungs"].values() if "error" in r]
    assert "error" in out or (out["rungs"] and len(stage_errors) == len(out["rungs"])), out


def test_bench_emits_json_on_sigterm():
    """An external watchdog's SIGTERM (the driver `timeout` kill) still
    yields the JSON line before exit."""
    proc = subprocess.Popen(
        [sys.executable, str(REPO / "bench.py"), "--only", "200x20", "--repeats", "1"],
        stdout=subprocess.PIPE,
        stderr=subprocess.DEVNULL,
        text=True,
        cwd=REPO,
        env=sanitized_cpu_env(),
    )
    # Let the orchestrator install its handlers and start the probe.
    time.sleep(5)
    proc.send_signal(signal.SIGTERM)
    try:
        stdout, _ = proc.communicate(timeout=60)
    except subprocess.TimeoutExpired:
        proc.kill()
        raise
    out = _last_json_line(stdout)
    assert out["metric"] == "sched_pairs_per_sec"
    assert out.get("interrupted") == "SIGTERM", out


def test_bench_churn_child_reports_breaker_under_permanent_dispatch_fault(tmp_path):
    """Round 8: a churn child whose device dispatch permanently fails
    (fault plane armed through the environment — the stdlib-only parent
    never imports anything) still writes its JSON record, with the
    degradation evidence: device_error fallbacks counted, breaker
    tripped, the whole stream carried by the per-pass path."""
    out = tmp_path / "churn.json"
    env = sanitized_cpu_env(
        {
            "KSIM_FAULTS": "replay.dispatch=always",
            "KSIM_REPLAY_BREAKER_N": "2",
        }
    )
    proc = subprocess.run(
        [
            sys.executable, str(REPO / "bench.py"),
            "--child", "churn", "--out", str(out),
            "--seed", "0", "--churn-events", "800", "--churn-nodes", "200",
            "--churn-device",
        ],
        capture_output=True,
        text=True,
        timeout=420,
        cwd=REPO,
        env=env,
    )
    assert proc.returncode == 0, proc.stderr[-2000:]
    rec = json.loads(out.read_text())
    assert rec["breaker_tripped"] is True
    assert rec["device_errors"] >= 2
    assert rec["unsupported"].get("device_error", 0) >= 2
    assert rec["unsupported"].get("breaker_open", 0) > 0
    assert rec["device_steps"] == 0
    assert rec["fallback_steps"] == rec["steps"]
    assert rec["pods_scheduled"] > 0  # the host path carried the stream


@pytest.mark.slow
def test_bench_churn_fleet_child_records_fleet_evidence(tmp_path):
    """Round 12: the churn_fleet child's JSON record carries the fleet
    evidence the acceptance contract names — trajectories/sec, the
    aggregate-speedup comparison vs solo, per-lane counts matching the
    solo run, the lanes-on-device fraction, and the cohort leader's
    lower_cache/prelower counters (the lowered-once guard)."""
    out = tmp_path / "fleet.json"
    proc = subprocess.run(
        [
            sys.executable, str(REPO / "bench.py"),
            "--child", "churn_fleet", "--out", str(out),
            "--seed", "0", "--churn-events", "300", "--churn-nodes", "64",
            "--fleet-lanes", "3",
        ],
        capture_output=True,
        text=True,
        timeout=420,
        cwd=REPO,
        env=sanitized_cpu_env(),
    )
    assert proc.returncode == 0, proc.stderr[-2000:]
    rec = json.loads(out.read_text())
    assert rec["lanes"] == 3
    assert rec["lanes_match_solo"] is True
    assert rec["trajectories_per_sec"] > 0
    assert rec["aggregate_speedup"] > 0
    assert rec["fleet"]["lanes_on_device"] == 1.0
    assert rec["fleet"]["group_dispatches"] >= 1
    # Lowered once per window: exactly one driver carries lowerings.
    lowerings = rec["fleet"]["lane_lowerings"]
    assert sum(lowerings) == max(lowerings) > 0
    assert "lower_cache" in rec and "prelower" in rec and "phases" in rec


@pytest.mark.slow
def test_bench_churn_jobs_child_records_job_evidence(tmp_path):
    """Round 13: the churn_jobs child's record carries the job-plane
    evidence — sustained jobs/min, per-job counts + jobs_match_solo,
    per-job latency quantiles from each job's PRIVATE plane, and the
    process-wide compile_cache counters proving same-rung tenants
    compiled once (shared_rungs >= 1)."""
    out = tmp_path / "jobs.json"
    proc = subprocess.run(
        [
            sys.executable, str(REPO / "bench.py"),
            "--child", "churn_jobs", "--out", str(out),
            "--seed", "0", "--churn-events", "300", "--churn-nodes", "64",
            "--jobs-count", "3", "--jobs-workers", "2",
        ],
        capture_output=True,
        text=True,
        timeout=420,
        cwd=REPO,
        env=sanitized_cpu_env(),
    )
    assert proc.returncode == 0, proc.stderr[-2000:]
    rec = json.loads(out.read_text())
    assert rec["jobs"] == 3 and rec["workers"] == 2
    assert rec["all_finished"] is True
    assert rec["jobs_match_solo"] is True
    assert rec["jobs_per_min"] > 0
    assert len(rec["per_job"]) == 3
    for pj in rec["per_job"]:
        assert pj["state"] == "succeeded"
        assert pj["counts"] == rec["solo_counts"]
        assert pj["dispatch_p50_s"] > 0  # the job's own histogram
    cc = rec["compile_cache"]
    assert cc["misses"] >= 1 and cc["hits"] >= 1
    assert cc["shared_rungs"] >= 1, cc
    assert cc["shared_single_compile_rungs"] >= 1, cc
    assert rec["queue"]["submitted"] == 3 and rec["queue"]["rejected"] == 0


def test_bench_churn_jobs_child_survives_dead_device(tmp_path):
    """One-JSON-line-under-any-hardware, job-plane edition: with every
    dispatch failing (the wedged-tunnel stand-in) all jobs degrade to
    the host path, finish, and still match the solo counts."""
    out = tmp_path / "jobs_dead.json"
    env = sanitized_cpu_env(
        {
            "KSIM_FAULTS": "replay.dispatch=always@device",
            "KSIM_REPLAY_BREAKER_N": "2",
        }
    )
    proc = subprocess.run(
        [
            sys.executable, str(REPO / "bench.py"),
            "--child", "churn_jobs", "--out", str(out),
            "--seed", "0", "--churn-events", "300", "--churn-nodes", "64",
            "--jobs-count", "2", "--jobs-workers", "2",
        ],
        capture_output=True,
        text=True,
        timeout=420,
        cwd=REPO,
        env=env,
    )
    assert proc.returncode == 0, proc.stderr[-2000:]
    rec = json.loads(out.read_text())
    assert rec["all_finished"] is True
    assert rec["jobs_match_solo"] is True
    for pj in rec["per_job"]:
        assert pj["state"] == "succeeded"


def test_bench_churn_fleet_child_survives_dead_device(tmp_path):
    """The one-JSON-line-under-any-hardware contract, fleet edition: a
    churn_fleet child whose every dispatch fails (the wedged-tunnel
    stand-in, armed through the environment) still writes its record —
    every lane carried by the per-pass host path, breakers tripped,
    counts intact."""
    out = tmp_path / "fleet_dead.json"
    env = sanitized_cpu_env(
        {
            "KSIM_FAULTS": "replay.dispatch=always@device",
            "KSIM_REPLAY_BREAKER_N": "2",
        }
    )
    proc = subprocess.run(
        [
            sys.executable, str(REPO / "bench.py"),
            "--child", "churn_fleet", "--out", str(out),
            "--seed", "0", "--churn-events", "300", "--churn-nodes", "64",
            "--fleet-lanes", "3",
        ],
        capture_output=True,
        text=True,
        timeout=420,
        cwd=REPO,
        env=env,
    )
    assert proc.returncode == 0, proc.stderr[-2000:]
    rec = json.loads(out.read_text())
    assert rec["lanes_match_solo"] is True  # the host path carried all lanes
    assert rec["fleet"]["lanes_on_device"] == 0.0
    assert all(s == 0 for s in rec["fleet"]["lane_device_steps"])


def test_bench_churn_trace_child_records_trace_evidence(tmp_path):
    """Round 14: the churn_trace child's record carries the trace-plane
    acceptance evidence — both paths' counts with counts_match (the
    bundled fixture's locked family), device_step_fraction 1.0 with 0
    fallbacks, and the phases split."""
    out = tmp_path / "trace.json"
    proc = subprocess.run(
        [
            sys.executable, str(REPO / "bench.py"),
            "--child", "churn_trace", "--out", str(out),
        ],
        capture_output=True,
        text=True,
        timeout=420,
        cwd=REPO,
        env=sanitized_cpu_env(),
    )
    assert proc.returncode == 0, proc.stderr[-2000:]
    rec = json.loads(out.read_text())
    assert rec["format"] == "borg" and rec["trace"] == "borg_mini.jsonl"
    # The locked trace family (tests/test_behavior_locks.py).
    assert rec["counts"] == [56, 19]
    assert rec["counts_match"] is True
    assert rec["device_step_fraction"] == 1.0
    assert rec["fallback_steps"] == 0 and rec["unsupported"] == {}
    assert "phases" in rec and "replay.dispatch" in rec["phases"]


def test_bench_churn_trace_child_survives_dead_device(tmp_path):
    """One-JSON-line-under-any-hardware, trace edition: with every
    dispatch failing, the whole trace stream degrades to the per-pass
    host path, the counts still match, and the record still exists."""
    out = tmp_path / "trace_dead.json"
    env = sanitized_cpu_env(
        {
            "KSIM_FAULTS": "replay.dispatch=always@device",
            "KSIM_REPLAY_BREAKER_N": "2",
        }
    )
    proc = subprocess.run(
        [
            sys.executable, str(REPO / "bench.py"),
            "--child", "churn_trace", "--out", str(out),
        ],
        capture_output=True,
        text=True,
        timeout=420,
        cwd=REPO,
        env=env,
    )
    assert proc.returncode == 0, proc.stderr[-2000:]
    rec = json.loads(out.read_text())
    assert rec["counts_match"] is True  # the host path carried the stream
    assert rec["counts"] == [56, 19]
    assert rec["device_step_fraction"] == 0.0
    assert rec["unsupported"].get("device_error", 0) >= 2


_STREAM_CHILD_ARGS = [
    "--stream-records", "400", "--stream-max-events", "120",
    "--stream-nodes", "8", "--stream-ops-per-step", "10",
    "--stream-window", "64", "--stream-queue", "2",
]


def test_bench_churn_stream_child_records_streaming_evidence(tmp_path):
    """Round 20: the churn_stream child's record carries the streaming
    acceptance evidence — the mid-run VmHWM snapshot (taken before the
    materialized comparison), the events/sec headline, the producer's
    window/queue stats with zero fallbacks, and streamed-vs-materialized
    counts_match."""
    out = tmp_path / "stream.json"
    proc = subprocess.run(
        [
            sys.executable, str(REPO / "bench.py"),
            "--child", "churn_stream", "--out", str(out),
            *_STREAM_CHILD_ARGS,
        ],
        capture_output=True,
        text=True,
        timeout=420,
        cwd=REPO,
        env=sanitized_cpu_env(),
    )
    assert proc.returncode == 0, proc.stderr[-2000:]
    rec = json.loads(out.read_text())
    assert rec["counts_match"] is True
    assert rec["counts"] == rec["materialized_counts"]
    assert rec["rss_after_stream_kb"] > 0
    assert rec["rss_after_stream_kb"] <= rec["rss_peak_kb"]
    assert rec["events_per_sec"] > 0
    assert rec["window_ops"] == 64 and rec["queue_windows"] == 2
    assert rec["windows"] >= 2  # ~128 ops over 64-op windows
    assert rec["ingest_fallback"] == 0
    assert rec["ingest_prefetches"] >= 1


def test_bench_churn_stream_child_survives_dead_device(tmp_path):
    """One-JSON-line-under-any-hardware, streaming edition: with every
    dispatch failing the streamed replay degrades to the per-step host
    path mid-pipeline, the streamed counts still match the materialized
    run, and the record still exists."""
    out = tmp_path / "stream_dead.json"
    env = sanitized_cpu_env(
        {
            "KSIM_FAULTS": "replay.dispatch=always@device",
            "KSIM_REPLAY_BREAKER_N": "2",
        }
    )
    proc = subprocess.run(
        [
            sys.executable, str(REPO / "bench.py"),
            "--child", "churn_stream", "--out", str(out),
            *_STREAM_CHILD_ARGS,
        ],
        capture_output=True,
        text=True,
        timeout=420,
        cwd=REPO,
        env=env,
    )
    assert proc.returncode == 0, proc.stderr[-2000:]
    rec = json.loads(out.read_text())
    assert rec["counts_match"] is True  # the host path carried the stream
    assert rec["counts"] == rec["materialized_counts"]
    assert rec["ingest_fallback"] == 0  # producer faults are a separate plane


@pytest.mark.slow
def test_bench_churn_restart_child_records_warm_restart_evidence(tmp_path):
    """Round 15: the churn_restart child's record carries the warm-restart
    acceptance evidence — time-to-first-scheduled-pod plus the on-disk AOT
    compile-cache counters. Two children over the SAME state dir: the cold
    run stores the serialized executable, the warm run loads it from disk
    without compiling, and both produce identical counts."""
    state = tmp_path / "state"
    env = sanitized_cpu_env(
        {
            "KSIM_AOT_CACHE": str(state / "aot"),
            "KSIM_COMPILE_CACHE": str(state / "xla"),
        }
    )
    recs = []
    for leg in ("cold", "warm"):
        out = tmp_path / f"restart_{leg}.json"
        proc = subprocess.run(
            [
                sys.executable, str(REPO / "bench.py"),
                "--child", "churn_restart", "--out", str(out),
                "--seed", "0", "--churn-events", "600", "--churn-nodes", "200",
            ],
            capture_output=True,
            text=True,
            timeout=420,
            cwd=REPO,
            env=env,
        )
        assert proc.returncode == 0, proc.stderr[-2000:]
        recs.append(json.loads(out.read_text()))
    cold, warm = recs
    for rec in recs:
        assert rec["wall_s"] > 0
        assert rec["first_scheduled_s"] is not None
        assert 0 < rec["first_scheduled_s"] <= rec["wall_s"] + 0.1
        assert rec["device_steps"] > 0 and rec["fallback_steps"] == 0
    # Identical streams -> identical counts, cold or warm.
    assert (warm["pods_scheduled"], warm["unschedulable_attempts"]) == (
        cold["pods_scheduled"], cold["unschedulable_attempts"])
    # The cold leg compiled and persisted; the warm leg restored from disk.
    assert cold["compile_cache"]["disk_stores"] >= 1
    assert cold["compile_cache"]["disk_hits"] == 0
    assert warm["compile_cache"]["disk_hits"] >= 1
    assert warm["compile_cache"]["disk_stores"] == 0


@pytest.mark.slow
def test_bench_churn_resume_children_record_resume_evidence(tmp_path):
    """Round 16: the churn_resume rung's three children over ONE shared
    jobs dir. The victim writes its evidence JSON the moment the first
    segment checkpoint is durable and then SIGKILLs itself (the JSON
    must land despite the -9 exit); the resume child restores that
    checkpoint and replays only the suffix; scratch is the control.
    Counts must match byte-identically across resume and scratch."""
    state = tmp_path / "state"
    state.mkdir()
    # 200 creates + 32 churn steps = two K=16 segments: the first
    # checkpoint lands with a full segment of work still ahead, so the
    # kill is mid-run, not a degenerate post-completion snapshot.
    shape = ["--seed", "0", "--churn-events", "3400", "--churn-nodes", "200"]
    recs = {}
    for phase in ("victim", "resume", "scratch"):
        out = tmp_path / f"resume_{phase}.json"
        proc = subprocess.run(
            [
                sys.executable, str(REPO / "bench.py"),
                "--child", "churn_resume", "--out", str(out),
                "--resume-phase", phase, "--state-dir", str(state),
                *shape,
            ],
            capture_output=True,
            text=True,
            timeout=420,
            cwd=REPO,
            env=sanitized_cpu_env(),
        )
        if phase == "victim":
            # The victim dies by its own SIGKILL — after the JSON.
            assert proc.returncode == -signal.SIGKILL, proc.stderr[-2000:]
        else:
            assert proc.returncode == 0, proc.stderr[-2000:]
        recs[phase] = json.loads(out.read_text())
    victim, resume, scratch = recs["victim"], recs["resume"], recs["scratch"]
    assert victim["state_at_kill"] == "running"
    assert victim["checkpoint_segment"] is not None
    assert resume["state"] == "succeeded" and scratch["state"] == "succeeded"
    # Crash-safe restore, byte-identical counts (wall excluded).
    assert resume["counts"] == scratch["counts"]
    assert resume["events"] == scratch["events"]
    assert resume["resumed_from"] == victim["checkpoint_segment"]
    assert 0 < resume["events_replayed"] < resume["events"]
    assert resume["resume"]["cursor"] > 0


@pytest.mark.slow
def test_bench_churn_resume_child_survives_dead_device(tmp_path):
    """One-JSON-line-under-any-hardware, resume edition: with every
    dispatch failing the job degrades to the per-pass host path, which
    never commits segments, so NO checkpoint ever lands — the victim's
    poll exits on job completion instead, and the resume child serves
    the journaled terminal result rather than replaying. The rung still
    writes valid JSON at every phase."""
    state = tmp_path / "state"
    state.mkdir()
    env = sanitized_cpu_env(
        {
            "KSIM_FAULTS": "replay.dispatch=always@device",
            "KSIM_REPLAY_BREAKER_N": "2",
        }
    )
    shape = ["--seed", "0", "--churn-events", "800", "--churn-nodes", "100"]
    recs = {}
    for phase in ("victim", "resume"):
        out = tmp_path / f"resume_dead_{phase}.json"
        proc = subprocess.run(
            [
                sys.executable, str(REPO / "bench.py"),
                "--child", "churn_resume", "--out", str(out),
                "--resume-phase", phase, "--state-dir", str(state),
                *shape,
            ],
            capture_output=True,
            text=True,
            timeout=420,
            cwd=REPO,
            env=env,
        )
        if phase == "victim":
            assert proc.returncode == -signal.SIGKILL, proc.stderr[-2000:]
        else:
            assert proc.returncode == 0, proc.stderr[-2000:]
        recs[phase] = json.loads(out.read_text())
    # Host path == no segment commits == no checkpoints: the victim ran
    # to completion before its kill, and resume folds the journaled
    # terminal state instead of restoring.
    assert recs["victim"]["checkpoint_segment"] is None
    assert recs["victim"]["state_at_kill"] == "succeeded"
    assert recs["resume"]["state"] == "succeeded"
    assert recs["resume"]["resumed_from"] is None
    assert recs["resume"]["counts"] is not None


@pytest.mark.slow
def test_bench_emits_json_when_probe_backend_is_dead():
    """A wedged/absent accelerator at PROBE time (the chip-tunnel
    failure mode the stdlib-only parent exists for): the probe child
    fails backend init, the orchestrator falls back to the sanitized
    CPU environment, and the one JSON line still appears."""
    env = sanitized_cpu_env({"BENCH_BUDGET_S": "360"})
    # Point the probe at a backend this host does not have: jax raises
    # inside the probe subprocess, which is exactly a dead-chip probe.
    env["JAX_PLATFORMS"] = "tpu"
    proc = subprocess.run(
        [sys.executable, str(REPO / "bench.py"), "--only", "200x20", "--repeats", "1"],
        capture_output=True,
        text=True,
        timeout=420,
        cwd=REPO,
        env=env,
    )
    assert proc.returncode == 0, proc.stderr[-2000:]
    out = _last_json_line(proc.stdout)
    assert out["metric"] == "sched_pairs_per_sec"
    assert out["value"] > 0
    assert out["platform"] == "cpu"  # the fallback environment ran it


def test_bench_churn_shard_child_records_shard_evidence(tmp_path):
    """Round 17: the churn_shard child runs the SAME stream at tp=1 and
    tp=8 in one process and its record carries the sharding acceptance
    evidence — counts_match/device_steps_match, every tp=8 segment
    lowered at width 8 with zero shard_mesh fallbacks, the per-shard
    full-record byte budget shrunk by the mesh width, and the per-chip
    memory watermark field next to the phases split (null on CPU, whose
    backend has no memory_stats)."""
    out = tmp_path / "shard.json"
    proc = subprocess.run(
        [
            sys.executable, str(REPO / "bench.py"),
            "--child", "churn_shard", "--out", str(out),
            "--seed", "0", "--churn-events", "800", "--churn-nodes", "200",
            "--shard-tp", "8",
        ],
        capture_output=True,
        text=True,
        timeout=420,
        cwd=REPO,
        env=sanitized_cpu_env(),
    )
    assert proc.returncode == 0, proc.stderr[-2000:]
    rec = json.loads(out.read_text())
    assert rec["counts_match"] is True
    assert rec["device_steps_match"] is True
    tp1, tp8 = rec["modes"]["tp1"], rec["modes"]["tp8"]
    assert tp1["lowered_tps"] == [1] and tp8["lowered_tps"] == [8]
    for mode in (tp1, tp8):
        assert "shard_mesh" not in mode["unsupported"], mode["unsupported"]
        assert mode["fallback_steps"] == 0
        assert mode["device_steps"] >= 1
        assert "phases" in mode and "replay.dispatch" in mode["phases"]
        assert "per_chip_peak_bytes" in mode
    # The round-17 memory claim in one line: the full-record budget is
    # per shard, so tp=8 carries 1/8th of tp=1's bytes per chip.
    assert (
        tp8["full_bytes_per_shard_max"] * 8 == tp1["full_bytes_per_shard_max"]
    )


def test_bench_churn_shard_child_survives_dead_device(tmp_path):
    """One-JSON-line-under-any-hardware, shard edition: with every
    dispatch failing, BOTH widths degrade to the per-pass host path,
    the counts still match between them, and the record still exists."""
    out = tmp_path / "shard_dead.json"
    env = sanitized_cpu_env(
        {
            "KSIM_FAULTS": "replay.dispatch=always@device",
            "KSIM_REPLAY_BREAKER_N": "2",
        }
    )
    proc = subprocess.run(
        [
            sys.executable, str(REPO / "bench.py"),
            "--child", "churn_shard", "--out", str(out),
            "--seed", "0", "--churn-events", "300", "--churn-nodes", "64",
            "--shard-tp", "8",
        ],
        capture_output=True,
        text=True,
        timeout=420,
        cwd=REPO,
        env=env,
    )
    assert proc.returncode == 0, proc.stderr[-2000:]
    rec = json.loads(out.read_text())
    assert rec["counts_match"] is True  # the host path carried both widths
    for mode in rec["modes"].values():
        assert mode["device_steps"] == 0
        assert mode["unsupported"].get("device_error", 0) >= 1


def test_bench_churn_fleet_shard_child_records_mesh_evidence(tmp_path):
    """Round 19: the churn_fleet_shard child runs the solo device
    replay and the 2-lane tp=4 fleet of the SAME stream in one process
    and its record carries the 2-D mesh acceptance evidence — per-lane
    counts matching solo, the (2, 4) grid actually built, every fleet
    segment lowered at the declared width, the per-shard byte budget,
    and the leader's dev_const counters with hits (the committed fleet
    layout was adopted and steady-state windows re-transferred
    nothing)."""
    out = tmp_path / "fleet_shard.json"
    proc = subprocess.run(
        [
            sys.executable, str(REPO / "bench.py"),
            "--child", "churn_fleet_shard", "--out", str(out),
            "--seed", "0", "--churn-events", "1200", "--churn-nodes", "64",
            "--fleet-lanes", "2", "--shard-tp", "4",
        ],
        capture_output=True,
        text=True,
        timeout=420,
        cwd=REPO,
        env=sanitized_cpu_env(),
    )
    assert proc.returncode == 0, proc.stderr[-2000:]
    rec = json.loads(out.read_text())
    assert rec["lanes"] == 2 and rec["tp"] == 4
    assert rec["counts_match"] is True
    assert rec["mesh_failed"] is False
    assert rec["mesh_grids"] == [[2, 4]]
    assert rec["lowered_tps"] == [4]
    assert rec["full_bytes_per_shard_max"] > 0
    assert rec["aggregate_speedup"] > 0
    assert rec["fleet"]["lanes_on_device"] == 1.0
    assert rec["fleet"]["group_dispatches"] >= 1
    # Zero-resharding engagement: at least one steady-state window hit
    # the id-keyed reuse map under the ("mesh", 2, 4) layout token.
    assert rec["dev_const"]["hits"] > 0, rec["dev_const"]


@pytest.mark.slow
def test_bench_churn_fleet_shard_child_survives_dead_device(tmp_path):
    """One-JSON-line-under-any-hardware, 2-D mesh edition: with every
    dispatch failing, both legs degrade to the per-pass host path, the
    lane counts still match solo, and the record still exists."""
    out = tmp_path / "fleet_shard_dead.json"
    env = sanitized_cpu_env(
        {
            "KSIM_FAULTS": "replay.dispatch=always@device",
            "KSIM_REPLAY_BREAKER_N": "2",
        }
    )
    proc = subprocess.run(
        [
            sys.executable, str(REPO / "bench.py"),
            "--child", "churn_fleet_shard", "--out", str(out),
            "--seed", "0", "--churn-events", "300", "--churn-nodes", "64",
            "--fleet-lanes", "2", "--shard-tp", "4",
        ],
        capture_output=True,
        text=True,
        timeout=420,
        cwd=REPO,
        env=env,
    )
    assert proc.returncode == 0, proc.stderr[-2000:]
    rec = json.loads(out.read_text())
    assert rec["counts_match"] is True  # the host path carried all lanes
    assert rec["fleet"]["lanes_on_device"] == 0.0
    assert all(s == 0 for s in rec["fleet"]["lane_device_steps"])


@pytest.mark.slow
def test_bench_churn_workers_child_records_fleet_scaleout_evidence(tmp_path):
    """Round 20: the churn_workers child's record carries the
    horizontal-scale-out evidence — a 1-worker leg and an N-worker
    subprocess fleet leg over the same multi-tenant storm, every job's
    counts byte-identical to the in-process solo baseline, lease
    counters showing the fleet actually spread the claims, and zero
    takeovers (nobody died, nobody was deposed)."""
    out = tmp_path / "workers.json"
    proc = subprocess.run(
        [
            sys.executable, str(REPO / "bench.py"),
            "--child", "churn_workers", "--out", str(out),
            "--seed", "0", "--churn-events", "300", "--churn-nodes", "64",
            "--jobs-count", "2", "--workers-fleet", "2",
        ],
        capture_output=True,
        text=True,
        timeout=420,
        cwd=REPO,
        env=sanitized_cpu_env(),
    )
    assert proc.returncode == 0, proc.stderr[-2000:]
    rec = json.loads(out.read_text())
    assert rec["jobs"] == 2 and rec["fleet"] == 2
    assert rec["jobs_match_solo"] is True
    legs = rec["legs"]
    assert legs["one_worker"]["workers"] == 1
    assert legs["fleet"]["workers"] == 2
    for leg in legs.values():
        assert leg["finished"] == 2
        assert leg["jobs_per_min"] > 0
        assert leg["takeovers"] == 0
        assert leg["step_p99_max_s"] > 0
        for pj in leg["per_job"]:
            assert pj["state"] == "succeeded"
            assert pj["counts"] == rec["solo_counts"]
    # The 1-worker leg funnels every claim through one worker; in the
    # fleet leg every claim is accounted to some worker and nothing
    # expired.  (Claim SPREAD is racy at this tiny shape — a fast
    # worker may legally adopt both jobs in one poll — so only the
    # conservation law is asserted.)
    solo_counters = legs["one_worker"]["lease_counters"]
    assert len(solo_counters) == 1
    assert sum(c["claims"] for c in solo_counters.values()) == 2
    fleet_counters = legs["fleet"]["lease_counters"]
    assert sum(c["claims"] for c in fleet_counters.values()) == 2
    assert all(c["expired"] == 0 for c in fleet_counters.values())
    # Round 21: each leg records a timed fleet-scope observability
    # scrape (workers publish at KSIM_OBS_PUBLISH_S=1; the leg merges
    # the snapshots and round-trips the Prometheus exposition).
    for leg in legs.values():
        scrape = leg["obs_scrape"]
        assert scrape["scrape_ms"] >= 0
        assert scrape["exposition_bytes"] > 0
        # Jobs run for multiple publish intervals, so every worker of
        # the leg has published at least one snapshot by scrape time.
        assert len(scrape["workers_published"]) >= leg["workers"]
        assert scrape["dispatch_p99_s"] is None or scrape["dispatch_p99_s"] > 0


def test_bench_churn_workers_child_survives_dead_device(tmp_path):
    """One-JSON-line-under-any-hardware, scale-out edition: the fault
    plane rides the environment into every fleet worker subprocess
    (sanitized_cpu_env copies the parent env), every dispatch fails,
    each worker degrades to the host path — and the counts still match
    the (equally degraded) in-child solo baseline."""
    out = tmp_path / "workers_dead.json"
    env = sanitized_cpu_env(
        {
            "KSIM_FAULTS": "replay.dispatch=always@device",
            "KSIM_REPLAY_BREAKER_N": "2",
        }
    )
    proc = subprocess.run(
        [
            sys.executable, str(REPO / "bench.py"),
            "--child", "churn_workers", "--out", str(out),
            "--seed", "0", "--churn-events", "200", "--churn-nodes", "64",
            "--jobs-count", "1", "--workers-fleet", "2",
        ],
        capture_output=True,
        text=True,
        timeout=420,
        cwd=REPO,
        env=env,
    )
    assert proc.returncode == 0, proc.stderr[-2000:]
    rec = json.loads(out.read_text())
    assert rec["jobs_match_solo"] is True
    for leg in rec["legs"].values():
        assert leg["finished"] == 1
        assert all(pj["state"] == "succeeded" for pj in leg["per_job"])
        # The fleet-scope scrape must survive the dead device too — the
        # observability plane is pure host-side I/O, so a wedged chip
        # can degrade the jobs but never the telemetry pull.
        assert leg["obs_scrape"]["scrape_ms"] >= 0
        assert leg["obs_scrape"]["exposition_bytes"] > 0
