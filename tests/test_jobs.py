"""Tenant job plane (ksim_tpu/jobs + /api/v1/jobs): lifecycle over
HTTP, bounded-queue backpressure, SSE progress streaming, cancel-mid-
segment rollback, the shared compile cache, and per-tenant fault
containment (slow-marked; `make jobs` / `make faults` run it)."""

from __future__ import annotations

import http.client
import json
import time

import pytest

from ksim_tpu.engine.compilecache import COMPILE_CACHE
from ksim_tpu.jobs import JobManager, JobQueueFull, parse_job_faults
from ksim_tpu.scenario import (
    churn_scenario,
    operations_from_spec,
    spec_from_operations,
)
from ksim_tpu.scenario.spec import ScenarioSpecError
from ksim_tpu.server import DIContainer, SimulatorServer
from tests.helpers import make_node, make_pod

# The locked 6k churn prefix (repo CLAUDE.md).
LOCK_6K = (2524, 471)


def tiny_spec(n_pods: int = 3, *, priority: int = 0) -> dict:
    ops = [
        {"step": 0, "createOperation": {"object": make_node(f"n{i}", cpu="4")}}
        for i in range(2)
    ]
    ops += [
        {"step": i + 1, "createOperation": {"object": make_pod(f"p{i}", cpu="100m")}}
        for i in range(n_pods)
    ]
    return {"spec": {"priority": priority, "scenario": {"operations": ops}}}


def device_spec(
    seed: int = 7, n_nodes: int = 30, n_events: int = 200, **sim_extra
) -> dict:
    """A small in-vocabulary churn stream as a device-replay job doc."""
    ops = list(
        churn_scenario(seed, n_nodes=n_nodes, n_events=n_events, ops_per_step=20)
    )
    sim = {"deviceReplay": True, "podBucketMin": 64, **sim_extra}
    return {"spec": {"simulator": sim, "scenario": spec_from_operations(ops)}}


# ---------------------------------------------------------------------------
# HTTP plumbing (the test_server.py idiom)
# ---------------------------------------------------------------------------


@pytest.fixture()
def server():
    di = DIContainer()
    srv = SimulatorServer(di, port=0).start()
    yield srv
    srv.shutdown_server()
    di.shutdown()


def _conn(srv):
    return http.client.HTTPConnection("127.0.0.1", srv.port, timeout=30)


def _req(srv, method, path, body=None):
    c = _conn(srv)
    c.request(
        method,
        path,
        json.dumps(body) if body is not None else None,
        {"Content-Type": "application/json"},
    )
    r = c.getresponse()
    data = r.read()
    c.close()
    return r.status, json.loads(data) if data else None


def _wait_state(srv, job_id, states, deadline_s=60.0):
    end = time.monotonic() + deadline_s
    while time.monotonic() < end:
        status, body = _req(srv, "GET", f"/api/v1/jobs/{job_id}")
        assert status == 200
        if body["state"] in states:
            return body
        time.sleep(0.05)
    raise AssertionError(f"job {job_id} never reached {states}")


# ---------------------------------------------------------------------------
# Lifecycle over HTTP
# ---------------------------------------------------------------------------


def test_job_lifecycle_over_http(server):
    """submit -> status -> result round-trip, plus the list and the
    per-job trace endpoint (every record job-tagged)."""
    status, job = _req(server, "POST", "/api/v1/jobs", tiny_spec())
    assert status == 202
    jid = job["id"]
    assert job["state"] in ("queued", "running")
    # Result before completion may 409 (depending on scheduling) — the
    # status endpoint always answers.
    final = _wait_state(server, jid, {"succeeded", "failed"})
    assert final["state"] == "succeeded", final
    assert final["progress"]["steps_done"] == final["progress"]["steps_total"] == 4
    # Fleet fields ride along even solo: no owner, no lease (the keys
    # are always present so clients need no feature detection).
    assert final["owner"] is None and final["lease"] is None

    status, res = _req(server, "GET", f"/api/v1/jobs/{jid}/result")
    assert status == 200
    assert res["result"]["podsScheduled"] == 3
    assert res["result"]["unschedulableAttempts"] == 0
    assert res["latency"]["runner.step"]["count"] == 4
    assert res["latency"]["runner.step"]["p99_seconds"] >= res["latency"][
        "runner.step"
    ]["p50_seconds"]

    status, listing = _req(server, "GET", "/api/v1/jobs")
    assert status == 200
    assert any(j["id"] == jid for j in listing["items"])

    # The JOB's private ring as Chrome trace JSON — isolation visible.
    status, doc = _req(server, "GET", f"/api/v1/jobs/{jid}/trace")
    assert status == 200
    spans = [e for e in doc["traceEvents"] if e["ph"] == "X"]
    assert {"jobs.run", "runner.step", "service.schedule"} <= {
        e["name"] for e in spans
    }
    for e in spans:
        assert e["args"]["job"] == jid

    # Unknown id: 404 everywhere.
    status, _ = _req(server, "GET", "/api/v1/jobs/nope")
    assert status == 404
    status, _ = _req(server, "DELETE", "/api/v1/jobs/nope")
    assert status == 404


def test_job_bad_specs_rejected(server):
    status, body = _req(server, "POST", "/api/v1/jobs", {"spec": {}})
    assert status == 400
    status, body = _req(
        server,
        "POST",
        "/api/v1/jobs",
        {"spec": {"scenario": {"operations": []}, "initialSnapshotPath": "/etc/x"}},
    )
    assert status == 400
    assert "not allowed" in body["message"]
    # File paths are refused in the simulator block too.
    status, body = _req(
        server,
        "POST",
        "/api/v1/jobs",
        {
            "spec": {
                "simulator": {"initialSnapshotPath": "/etc/passwd"},
                "scenario": {"operations": []},
            }
        },
    )
    assert status == 400


def test_job_queue_full_returns_429(monkeypatch):
    """A saturated bounded queue answers 429, and the queued job can be
    cancelled (immediately terminal) via DELETE."""
    monkeypatch.setenv("KSIM_JOBS_WORKERS", "0")  # accept, never run
    monkeypatch.setenv("KSIM_JOBS_QUEUE", "1")
    di = DIContainer()
    srv = SimulatorServer(di, port=0).start()
    try:
        status, first = _req(srv, "POST", "/api/v1/jobs", tiny_spec())
        assert status == 202 and first["state"] == "queued"
        status, body = _req(srv, "POST", "/api/v1/jobs", tiny_spec())
        assert status == 429
        assert "full" in body["message"]
        # Queue-full evidence in the merged metrics document.
        status, m = _req(srv, "GET", "/api/v1/metrics")
        assert m["jobs"]["queue"] == {
            "depth": 1, "capacity": 1, "submitted": 1, "rejected": 1,
            "bypass_pops": 0,
        }
        assert m["jobs"]["workers"] == {"pool": 0, "active": 0}
        # Cancel the queued job: immediate terminal state.
        status, out = _req(srv, "DELETE", f"/api/v1/jobs/{first['id']}")
        assert status == 200 and out["state"] == "cancelled"
        status, st = _req(srv, "GET", f"/api/v1/jobs/{first['id']}")
        assert st["state"] == "cancelled"
    finally:
        srv.shutdown_server()
        di.shutdown()


def test_metrics_jobs_section_shape(server):
    """GET /api/v1/metrics carries the jobs section without breaking
    the existing merged-document shape — empty before the job plane is
    ever used, populated after."""
    status, m = _req(server, "GET", "/api/v1/metrics")
    assert status == 200
    assert set(m) >= {"counters", "timings", "trace", "faults", "jobs", "process"}
    assert set(m["process"]) >= {"role", "worker_id", "pid", "started_at", "uptime_s"}
    assert m["jobs"]["workers"]["pool"] == 0 and m["jobs"]["jobs"] == {}
    status, job = _req(server, "POST", "/api/v1/jobs", tiny_spec())
    assert status == 202
    _wait_state(server, job["id"], {"succeeded", "failed"})
    status, m = _req(server, "GET", "/api/v1/metrics")
    assert m["jobs"]["workers"]["pool"] >= 1
    jm_entry = m["jobs"]["jobs"][job["id"]]
    assert jm_entry["state"] == "succeeded"
    # The per-job plane snapshot rides along: private histograms.
    assert jm_entry["trace"]["histograms"]["runner.step"]["count"] == 4
    # compile_cache is a first-class provider section (process-wide),
    # including the AOT prewarm counters (startup + speculative rescan).
    assert "compile_cache" in m
    assert set(m["compile_cache"]) >= {
        "hits", "misses", "shared_rungs", "disk_prewarmed",
        "disk_speculative",
    }
    # Solo manager: no fleet section (it appears only under a role).
    assert "fleet" not in m["jobs"]


def test_fleet_status_and_metrics_over_http(tmp_path, monkeypatch):
    """Satellite: /api/v1/jobs/<id> carries the owner worker id and the
    lease age, and /api/v1/metrics the per-worker fleet counters, when
    the server runs as the fleet's front door."""
    monkeypatch.setenv("KSIM_JOBS_DIR", str(tmp_path))
    monkeypatch.setenv("KSIM_WORKERS_ROLE", "frontdoor")
    monkeypatch.setenv("KSIM_WORKER_ID", "fd")
    monkeypatch.setenv("KSIM_WORKERS_POLL_S", "0.1")
    di = DIContainer()
    srv = SimulatorServer(di, port=0).start()
    wk = JobManager(
        workers=1, queue_limit=8, jobs_dir=str(tmp_path),
        role="worker", worker_id="w1", lease_s=3.0, poll_s=0.1,
    )
    try:
        status, job = _req(srv, "POST", "/api/v1/jobs", tiny_spec())
        assert status == 202
        final = _wait_state(srv, job["id"], {"succeeded", "failed"})
        assert final["state"] == "succeeded", final
        assert final["owner"] == "w1"
        assert set(final["lease"]) == {"epoch", "age"}
        assert final["lease"]["epoch"] == 1
        assert final["lease"]["age"] >= 0
        status, m = _req(srv, "GET", "/api/v1/metrics")
        assert status == 200
        fleet = m["jobs"]["fleet"]
        assert fleet["role"] == "frontdoor" and fleet["worker_id"] == "fd"
        assert set(fleet["workers"]["w1"]) == {
            "claims", "takeovers", "renews", "expired",
        }
        assert fleet["workers"]["w1"]["claims"] == 1
        assert fleet["workers"]["w1"]["takeovers"] == 0
    finally:
        wk.shutdown()
        srv.shutdown_server()
        di.shutdown()


# ---------------------------------------------------------------------------
# SSE stream
# ---------------------------------------------------------------------------


def _read_sse(srv, path, deadline_s=60.0):
    """Collect all SSE data frames until the server ends the stream."""
    c = _conn(srv)
    c.request("GET", path, headers={"Accept": "text/event-stream"})
    resp = c.getresponse()
    assert resp.status == 200
    assert resp.getheader("Content-Type") == "text/event-stream"
    events = []
    end = time.monotonic() + deadline_s
    while time.monotonic() < end:
        line = resp.readline()
        if not line:
            break  # stream closed by the server
        line = line.strip()
        if line.startswith(b"data: "):
            events.append(json.loads(line[len(b"data: "):]))
    c.close()
    return events


def test_sse_stream_carries_monotonic_progress(server):
    status, job = _req(server, "POST", "/api/v1/jobs", tiny_spec(n_pods=4))
    assert status == 202
    events = _read_sse(server, f"/api/v1/jobs/{job['id']}/events")
    assert events, "empty SSE stream"
    # Sequence numbers are the replayable event-log order.
    assert [e["seq"] for e in events] == sorted(e["seq"] for e in events)
    states = [e["state"] for e in events if e["event"] == "state"]
    assert states[0] == "queued" and states[-1] == "succeeded"
    progress = [e for e in events if e["event"] == "progress"]
    assert progress, "no progress events in the stream"
    done = [e["steps_done"] for e in progress]
    assert done == sorted(done), f"progress regressed: {done}"
    assert done[-1] == progress[-1]["steps_total"] == 5
    # Late joiner replays the full history (the log, not a live tap).
    again = _read_sse(server, f"/api/v1/jobs/{job['id']}/events")
    assert [e["seq"] for e in again] == [e["seq"] for e in events]


# ---------------------------------------------------------------------------
# Cancellation
# ---------------------------------------------------------------------------


def test_cancel_mid_segment_rolls_back_store():
    """Cancel landing INSIDE a device segment's reconcile aborts the
    store transaction: the job ends cancelled and its store is
    byte-identical to the segment's start (here: empty — the hang sits
    in the FIRST segment).  The reconcile hang (the job's private
    fault plane) pins the timing deterministically."""
    jm = JobManager(
        workers=1,
        queue_limit=4,
        fault_spec="0:replay.reconcile=hang:1.5:1",
    )
    try:
        job = jm.submit(device_spec(n_events=200))
        # The hang fires fault.fired on the JOB's plane before sleeping;
        # it is forwarded into the job's event log — our cue that the
        # reconcile transaction is open right now.
        end = time.monotonic() + 120
        idx, seen = 0, False
        while time.monotonic() < end and not seen:
            evs, idx, done = job.events_since(idx, timeout=0.5)
            seen = any(
                e.get("event") == "trace" and e.get("name") == "fault.fired"
                for e in evs
            )
            if done:
                break
        assert seen, "reconcile hang never fired — wrong fault wiring"
        assert jm.cancel(job.id) in ("running", "cancelled")
        assert job.wait_done(60)
        state, result, err = job.result_view()
        assert state == "cancelled", (state, err)
        # Store consistency: the rolled-back first segment left nothing.
        assert job.store is not None
        assert job.store.list("pods") == []
        assert job.store.list("nodes") == []
    finally:
        jm.shutdown(timeout=5)


def test_cancel_running_job_between_steps():
    """A per-pass (host path) job cancels at the next step boundary."""
    jm = JobManager(workers=1, queue_limit=4)
    try:
        # Enough steps that cancellation lands mid-run.
        job = jm.submit(tiny_spec(n_pods=40))
        assert job.wait_done(0.0) is False
        end = time.monotonic() + 60
        while time.monotonic() < end and job.status()["state"] == "queued":
            time.sleep(0.02)
        jm.cancel(job.id)
        assert job.wait_done(60)
        assert job.status()["state"] in ("cancelled", "succeeded")
    finally:
        jm.shutdown(timeout=5)


def test_cancel_running_fleet_job_lands_at_round_boundary():
    """Round 16: DELETE on a running FLEET job now cancels — the cohort
    checks the parent run's flag once per dispatch round, so the cancel
    lands at the next lane dispatch boundary with every lane's store at
    a committed segment (no torn transactions)."""
    jm = JobManager(workers=1, queue_limit=4)
    try:
        job = jm.submit(device_spec(n_events=830, fleet=2))
        # The first progress event = the first committed cohort round:
        # the fleet is mid-run with more rounds to go.
        end = time.monotonic() + 120
        idx, seen = 0, False
        while time.monotonic() < end and not seen:
            evs, idx, done = job.events_since(idx, timeout=0.5)
            seen = any(e.get("event") == "progress" for e in evs)
            if done:
                break
        assert seen, "fleet job never committed a round"
        assert jm.cancel(job.id) in ("running", "cancelled")
        assert job.wait_done(120)
        state = job.status()["state"]
        # "succeeded" only if the last round was already in flight when
        # the flag flipped — the boundary semantics are pinned
        # deterministically at the runner layer (test_replay_device).
        assert state in ("cancelled", "succeeded")
        if state == "cancelled" and job.runner.fleet_lanes:
            for ln in job.runner.fleet_lanes:
                assert ln.runner.store._txn is None
    finally:
        jm.shutdown(timeout=5)


# ---------------------------------------------------------------------------
# Shared compile cache
# ---------------------------------------------------------------------------


def test_two_same_rung_jobs_compile_once():
    """Two identical device-replay jobs share every shape rung: the
    process-wide compile cache shows rungs owned by BOTH jobs with
    exactly one compile each (misses bounded by distinct rungs, hits
    from the second tenant)."""
    COMPILE_CACHE.reset()
    jm = JobManager(workers=2, queue_limit=4)
    try:
        doc = device_spec(n_events=160)
        j1 = jm.submit(doc)
        j2 = jm.submit(doc)
        assert jm.join(timeout=300)
        for j in (j1, j2):
            state, result, err = j.result_view()
            assert state == "succeeded", (j.id, state, err)
            assert result["replay"]["device_round_trips"] >= 1, result["replay"]
        s1 = j1.result_view()[1]["result"]
        s2 = j2.result_view()[1]["result"]
        assert (s1["podsScheduled"], s1["unschedulableAttempts"]) == (
            s2["podsScheduled"],
            s2["unschedulableAttempts"],
        )
        snap = COMPILE_CACHE.snapshot()
        assert snap["misses"] >= 1 and snap["hits"] >= 1, snap
        # The tenancy claim: >= 1 rung served BOTH jobs off ONE compile.
        assert snap["shared_rungs"] >= 1, snap
        assert snap["shared_single_compile_rungs"] >= 1, snap
        assert snap["max_owners_per_rung"] == 2, snap
        assert snap["aborts"] == 0, snap
    finally:
        jm.shutdown(timeout=5)


# ---------------------------------------------------------------------------
# Spec plumbing & queue semantics (unit)
# ---------------------------------------------------------------------------


def test_spec_from_operations_roundtrip():
    ops = list(churn_scenario(3, n_nodes=15, n_events=60, ops_per_step=10))
    assert operations_from_spec(spec_from_operations(ops)) == ops


def test_parse_job_faults_refusals():
    with pytest.raises(ValueError, match="expected"):
        parse_job_faults("replay.dispatch=always")  # no ordinal
    with pytest.raises(ValueError, match="job-plane site"):
        parse_job_faults("0:service.schedule=always")
    planes = parse_job_faults("1:replay.dispatch=call:1;1:jobs.run=first:1")
    assert set(planes) == {1}


def test_queue_priority_then_fifo():
    from ksim_tpu.jobs import JobQueue

    q = JobQueue(limit=10)
    q.put("a", priority=0)
    q.put("b", priority=5)
    q.put("c", priority=0)
    assert [q.get(0.1) for _ in range(3)] == ["b", "a", "c"]
    q2 = JobQueue(limit=1)
    q2.put("x")
    with pytest.raises(JobQueueFull):
        q2.put("y")
    assert q2.stats()["rejected"] == 1


def test_rejected_submission_does_not_consume_fault_ordinal():
    """A queue-full refusal must not shift which job an armed
    KSIM_JOBS_FAULTS ordinal lands on (a silently-shifted schedule is
    a vacuously-green chaos run)."""
    jm = JobManager(
        workers=0,
        queue_limit=1,
        fault_spec="1:replay.dispatch=always@device",
    )
    try:
        first = jm.submit(tiny_spec())
        assert first.ordinal == 0 and first.faults is None
        with pytest.raises(JobQueueFull):
            jm.submit(tiny_spec())  # refused: ordinal 1 NOT consumed
        # Drain the slot (no workers) and resubmit: the retry — the
        # first job that can actually run next — gets ordinal 1 and
        # the armed plane with it.
        assert jm.queue.get(0.1) is first
        second = jm.submit(tiny_spec())
        assert second.ordinal == 1
        assert second.faults is not None
    finally:
        jm.shutdown(timeout=1)


# ---------------------------------------------------------------------------
# Per-tenant admission (round 16): quotas + rate limits
# ---------------------------------------------------------------------------


def test_tenant_quota_throttles_and_releases():
    """KSIM_JOBS_TENANT_MAX_ACTIVE bounds a tenant's NON-TERMINAL jobs;
    other tenants are unaffected, and a terminal job frees the slot."""
    from ksim_tpu.jobs import JobThrottled

    jm = JobManager(workers=0, queue_limit=8, tenant_max_active=1)
    try:
        first = jm.submit(tiny_spec(), tenant="acme")
        with pytest.raises(JobThrottled) as ei:
            jm.submit(tiny_spec(), tenant="acme")
        assert ei.value.retry_after > 0
        assert "KSIM_JOBS_TENANT_MAX_ACTIVE" in str(ei.value)
        jm.submit(tiny_spec(), tenant="umbrella")  # per-tenant, not global
        t = jm.snapshot()["tenants"]
        assert t["acme"]["admitted"] == 1 and t["acme"]["throttled"] == 1
        assert t["umbrella"]["admitted"] == 1 and t["umbrella"]["throttled"] == 0
        # A terminal job no longer counts against the quota.
        assert jm.cancel(first.id) == "cancelled"
        assert jm.submit(tiny_spec(), tenant="acme").status()["state"] == "queued"
    finally:
        jm.shutdown(timeout=1)


def test_tenant_rate_limit_token_bucket():
    """KSIM_JOBS_TENANT_RATE is a per-tenant token bucket (burst
    max(rate, 1)): a drained bucket throttles with retry_after = the
    time until the next token; buckets never bleed across tenants."""
    from ksim_tpu.jobs import JobThrottled

    jm = JobManager(workers=0, queue_limit=16, tenant_rate=0.001)
    try:
        jm.submit(tiny_spec(), tenant="acme")  # the burst token
        with pytest.raises(JobThrottled) as ei:
            jm.submit(tiny_spec(), tenant="acme")
        assert ei.value.retry_after > 1.0  # ~1000 s to the next token
        assert "KSIM_JOBS_TENANT_RATE" in str(ei.value)
        jm.submit(tiny_spec(), tenant="umbrella")
    finally:
        jm.shutdown(timeout=1)


def test_tenant_routing_header_wins_over_spec_then_default():
    """The HTTP layer's X-Ksim-Tenant (the ``tenant=`` kwarg) wins over
    ``spec.tenant``; absent both, jobs pool under ``default``."""
    jm = JobManager(workers=0, queue_limit=8)
    try:
        doc = tiny_spec()
        doc["spec"]["tenant"] = "spec-t"
        assert jm.submit(doc, tenant="header-t").tenant == "header-t"
        assert jm.submit(doc).tenant == "spec-t"
        assert jm.submit(tiny_spec()).tenant == "default"
        assert jm.submit(tiny_spec()).status()["tenant"] == "default"
    finally:
        jm.shutdown(timeout=1)


def test_throttled_submission_does_not_consume_fault_ordinal():
    """Same invariant as the queue-full refusal: a throttled tenant
    must not shift which job an armed KSIM_JOBS_FAULTS ordinal lands
    on."""
    from ksim_tpu.jobs import JobThrottled

    jm = JobManager(
        workers=0,
        queue_limit=8,
        tenant_max_active=1,
        fault_spec="1:replay.dispatch=always@device",
    )
    try:
        first = jm.submit(tiny_spec(), tenant="acme")
        assert first.ordinal == 0 and first.faults is None
        with pytest.raises(JobThrottled):
            jm.submit(tiny_spec(), tenant="acme")  # ordinal 1 NOT consumed
        second = jm.submit(tiny_spec(), tenant="umbrella")
        assert second.ordinal == 1
        assert second.faults is not None
    finally:
        jm.shutdown(timeout=1)


def test_tenant_throttle_http_429_with_retry_after(monkeypatch):
    """Over HTTP: a throttled tenant gets 429 + a whole-second
    Retry-After header, routed by X-Ksim-Tenant; the merged metrics
    document carries the per-tenant counters."""
    monkeypatch.setenv("KSIM_JOBS_WORKERS", "0")
    monkeypatch.setenv("KSIM_JOBS_TENANT_MAX_ACTIVE", "1")
    di = DIContainer()
    srv = SimulatorServer(di, port=0).start()
    try:
        def post(tenant=None):
            c = _conn(srv)
            headers = {"Content-Type": "application/json"}
            if tenant:
                headers["X-Ksim-Tenant"] = tenant
            c.request("POST", "/api/v1/jobs", json.dumps(tiny_spec()), headers)
            r = c.getresponse()
            body = json.loads(r.read())
            retry = r.getheader("Retry-After")
            c.close()
            return r.status, body, retry

        status, first, _ = post("acme")
        assert status == 202
        status, body, retry = post("acme")
        assert status == 429
        assert "KSIM_JOBS_TENANT_MAX_ACTIVE" in body["message"]
        assert retry is not None and int(retry) >= 1
        status, other, _ = post("umbrella")
        assert status == 202
        status, m = _req(srv, "GET", "/api/v1/metrics")
        t = m["jobs"]["tenants"]
        assert t["acme"]["admitted"] == 1 and t["acme"]["throttled"] == 1
        assert t["umbrella"]["throttled"] == 0
    finally:
        srv.shutdown_server()
        di.shutdown()


def test_fleet_job_with_armed_faults_or_config_refused():
    """The fleet runner cannot carry a private fault plane, a tenant
    schedulerConfig or an initialSnapshot — dropped-on-the-floor specs
    must refuse at submission, not succeed wrongly."""
    jm = JobManager(
        workers=0, queue_limit=4, fault_spec="0:replay.dispatch=always"
    )
    try:
        fleet_doc = {
            "spec": {
                "simulator": {"fleet": 2, "deviceReplay": True},
                "scenario": tiny_spec()["spec"]["scenario"],
            }
        }
        with pytest.raises(ScenarioSpecError, match="KSIM_JOBS_FAULTS"):
            jm.submit(fleet_doc)
        for field in ("schedulerConfig", "initialSnapshot"):
            doc = {
                "spec": {
                    "simulator": {"fleet": 2, field: {"x": 1}},
                    "scenario": tiny_spec()["spec"]["scenario"],
                }
            }
            with pytest.raises(ScenarioSpecError, match="not supported"):
                jm.submit(doc)
    finally:
        jm.shutdown(timeout=1)


def test_direct_submit_rejects_bad_documents():
    jm = JobManager(workers=0, queue_limit=4)
    try:
        with pytest.raises(ScenarioSpecError):
            jm.submit({"spec": {}})
        with pytest.raises(ScenarioSpecError):
            jm.submit("not a mapping")
        with pytest.raises(ScenarioSpecError):
            jm.submit({"operations": [], "scenarioResultFilePath": "/tmp/x"})
    finally:
        jm.shutdown(timeout=1)


# ---------------------------------------------------------------------------
# Per-tenant fault containment (the chaos matrix leg; slow)
# ---------------------------------------------------------------------------


@pytest.mark.slow
def test_job_fault_containment_6k_locked():
    """KSIM_JOBS_FAULTS arms ONE job's private plane: that job's device
    path degrades (breaker opens, per-pass fallback) while running
    CONCURRENTLY with a clean job — and BOTH land the locked 6k counts
    (2524/471).  The `make faults`/`make jobs` matrix runs this."""
    import jax

    prev_x64 = jax.config.jax_enable_x64
    jax.config.update("jax_enable_x64", False)
    jm = JobManager(
        workers=2,
        queue_limit=4,
        fault_spec="0:replay.dispatch=always@device",
    )
    try:
        doc = {
            "spec": {
                "simulator": {
                    "deviceReplay": True,
                    "preemption": True,
                    "maxPodsPerPass": 1024,
                    "podBucketMin": 128,
                },
                "scenario": spec_from_operations(
                    list(
                        churn_scenario(
                            0, n_nodes=2000, n_events=6000, ops_per_step=100
                        )
                    )
                ),
            }
        }
        chaos = jm.submit(doc)
        clean = jm.submit(doc)
        assert jm.join(timeout=900)
        for j, label in ((chaos, "chaos"), (clean, "clean")):
            state, result, err = j.result_view()
            assert state == "succeeded", (label, state, err)
            counts = (
                result["result"]["podsScheduled"],
                result["result"]["unschedulableAttempts"],
            )
            assert counts == LOCK_6K, (label, counts)
        chaos_replay = chaos.result_view()[1]["replay"]
        clean_replay = clean.result_view()[1]["replay"]
        # The armed job degraded ALONE: its private plane fired, its
        # breaker opened, and it fell back to the host path...
        assert chaos.faults is not None
        assert chaos.faults.fired("replay.dispatch") >= 1
        assert chaos_replay["device_errors"] >= 1
        assert chaos_replay["breaker_tripped"] is True
        assert chaos_replay["device_steps"] == 0
        # ...while the concurrent clean job stayed on the device path.
        assert clean.faults is None
        assert clean_replay["device_errors"] == 0
        assert clean_replay["breaker_tripped"] is False
        assert clean_replay["device_steps"] > 0
    finally:
        jm.shutdown(timeout=5)
        jax.config.update("jax_enable_x64", prev_x64)


# ---------------------------------------------------------------------------
# Round 14: cost-aware admission (SJF + starvation bound)
# ---------------------------------------------------------------------------


def test_queue_sjf_within_priority_band():
    from ksim_tpu.jobs import JobQueue

    q = JobQueue(limit=10)
    q.put("big", cost=1000)
    q.put("small", cost=5)
    q.put("mid", cost=50)
    q.put("prio", priority=5, cost=9999)  # a higher band beats any cost
    assert [q.get(0.1) for _ in range(4)] == ["prio", "small", "mid", "big"]
    # cost=0 ties keep FIFO (the pre-round-14 special case).
    q.put("a"); q.put("b")
    assert [q.get(0.1), q.get(0.1)] == ["a", "b"]


def test_queue_starvation_bound():
    """A long job is overtaken at most max_bypass times, then pops
    regardless of cost — the SJF starvation bound, deterministically."""
    from ksim_tpu.jobs import JobQueue

    q = JobQueue(limit=0, max_bypass=2)
    q.put("long", cost=1000)
    q.put("s1", cost=1)
    assert q.get(0.1) == "s1"      # bypass 1
    q.put("s2", cost=1)
    assert q.get(0.1) == "s2"      # bypass 2
    q.put("s3", cost=1)
    assert q.get(0.1) == "long"    # the bound fires: cost ignored
    assert q.get(0.1) == "s3"
    assert q.stats()["bypass_pops"] == 1


def test_manager_submit_costs_queue_by_event_count(monkeypatch):
    """With no workers, submissions queue up; the pop order proves the
    manager passed the spec's event count as the cost."""
    jm = JobManager(workers=0, queue_limit=8)
    try:
        big = jm.submit(tiny_spec(10))
        small = jm.submit(tiny_spec(1))
        assert jm.queue.get(0.1) is small
        assert jm.queue.get(0.1) is big
    finally:
        jm.shutdown(timeout=1)


# ---------------------------------------------------------------------------
# Round 14: per-job resource bounds -> HTTP 413
# ---------------------------------------------------------------------------


def test_job_limits_refuse_oversized_specs():
    from ksim_tpu.jobs import JobLimitExceeded

    jm = JobManager(workers=0, queue_limit=8, max_job_events=5, max_job_nodes=0)
    try:
        with pytest.raises(JobLimitExceeded, match="KSIM_JOBS_MAX_EVENTS"):
            jm.submit(tiny_spec(10))
        # A refused submission consumes no ordinal and queues nothing.
        assert jm.queue.depth() == 0
        ok = jm.submit(tiny_spec(1))
        assert ok.ordinal == 0
    finally:
        jm.shutdown(timeout=1)
    jm2 = JobManager(workers=0, queue_limit=8, max_job_nodes=1)
    try:
        with pytest.raises(JobLimitExceeded, match="KSIM_JOBS_MAX_NODES"):
            jm2.submit(tiny_spec(1))  # tiny_spec creates 2 nodes
    finally:
        jm2.shutdown(timeout=1)


def test_job_limit_returns_413_over_http(monkeypatch):
    monkeypatch.setenv("KSIM_JOBS_MAX_EVENTS", "5")
    monkeypatch.setenv("KSIM_JOBS_WORKERS", "0")
    di = DIContainer()
    srv = SimulatorServer(di, port=0).start()
    try:
        status, body = _req(srv, "POST", "/api/v1/jobs", tiny_spec(10))
        assert status == 413
        assert "KSIM_JOBS_MAX_EVENTS" in body["message"]
        status, _ = _req(srv, "POST", "/api/v1/jobs", tiny_spec(1))
        assert status == 202
    finally:
        srv.shutdown_server()
        di.shutdown()


def test_job_trace_bound_refused_during_streaming_ingest(monkeypatch):
    """A trace-sourced spec over KSIM_JOBS_MAX_EVENTS is refused DURING
    streaming ingest (TraceBoundExceeded -> JobLimitExceeded): the
    refusal message carries both the env-var name and the early-stop
    marker, and nothing is queued."""
    from ksim_tpu.jobs import JobLimitExceeded

    monkeypatch.setenv("KSIM_TRACES_DIR", "tests/fixtures/traces")
    jm = JobManager(workers=0, queue_limit=8, max_job_events=5)
    try:
        with pytest.raises(JobLimitExceeded, match="KSIM_JOBS_MAX_EVENTS"):
            jm.submit(
                _trace_job(
                    name="borg_mini.jsonl", format="borg", nodes=4, opsPerStep=8
                )
            )
        with pytest.raises(JobLimitExceeded, match="ingest stopped early"):
            jm.submit(
                _trace_job(
                    name="borg_mini.jsonl", format="borg", nodes=4, opsPerStep=8
                )
            )
        assert jm.queue.depth() == 0
    finally:
        jm.shutdown(timeout=1)


# ---------------------------------------------------------------------------
# Round 14: trace-by-name submission + spec-armed chaos
# ---------------------------------------------------------------------------


def _trace_job(**trace):
    return {"spec": {"scenario": {"source": {"trace": trace}}}}


def test_job_submits_registered_trace_by_name(server, monkeypatch):
    monkeypatch.setenv("KSIM_TRACES_DIR", "tests/fixtures/traces")
    status, names = _req(server, "GET", "/api/v1/traces")
    assert status == 200
    assert "alibaba_batch_mini.csv" in [e["name"] for e in names["items"]]
    status, job = _req(
        server,
        "POST",
        "/api/v1/jobs",
        _trace_job(name="alibaba_batch_mini.csv", format="alibaba", nodes=4,
                   opsPerStep=8),
    )
    assert status == 202, job
    body = _wait_state(server, job["id"], {"succeeded", "failed"})
    assert body["state"] == "succeeded"
    status, result = _req(server, "GET", f"/api/v1/jobs/{job['id']}/result")
    assert status == 200
    assert result["result"]["eventsApplied"] > 24  # nodes + creates + deletes


def test_job_refuses_trace_paths_and_unregistered_names(server, monkeypatch):
    monkeypatch.setenv("KSIM_TRACES_DIR", "tests/fixtures/traces")
    status, body = _req(
        server, "POST", "/api/v1/jobs",
        _trace_job(path="/etc/passwd", format="borg"),
    )
    assert status == 400
    assert "registered" in body["message"]
    status, body = _req(
        server, "POST", "/api/v1/jobs",
        _trace_job(name="../../../etc/passwd", format="borg"),
    )
    assert status == 400
    status, body = _req(
        server, "POST", "/api/v1/jobs",
        _trace_job(name="nope.jsonl", format="borg"),
    )
    assert status == 400
    assert "no registered trace" in body["message"]


def test_spec_armed_faults_degrade_the_submitting_job_alone(server):
    """The chaos-native spec: a job arming its own jobs.run fault fails
    by itself while a concurrently submitted clean job succeeds."""
    chaotic = dict(tiny_spec(2))
    chaotic["spec"] = dict(chaotic["spec"], faults={"jobs.run": "always"})
    status, bad = _req(server, "POST", "/api/v1/jobs", chaotic)
    assert status == 202
    status, good = _req(server, "POST", "/api/v1/jobs", tiny_spec(2))
    assert status == 202
    bad_body = _wait_state(server, bad["id"], {"failed", "succeeded", "cancelled"})
    good_body = _wait_state(server, good["id"], {"failed", "succeeded", "cancelled"})
    assert bad_body["state"] == "failed"
    assert "InjectedFault" in bad_body["error"]
    assert good_body["state"] == "succeeded"


def test_spec_faults_refuse_non_job_sites(server):
    doc = dict(tiny_spec(1))
    doc["spec"] = dict(doc["spec"], faults={"service.schedule": "always"})
    status, body = _req(server, "POST", "/api/v1/jobs", doc)
    assert status == 400
    assert "job-plane site" in body["message"]


def test_malformed_jobs_faults_schedule_fails_at_construction():
    """An operator typo in a KSIM_JOBS_FAULTS SCHEDULE raises at
    JobManager construction (fail-fast), never later as a tenant-blamed
    400 with the chaos silently unarmed."""
    with pytest.raises(ValueError):
        JobManager(workers=0, queue_limit=2, fault_spec="0:jobs.run=bogus")


def test_spec_faults_schedule_smuggling_refused_over_http(server):
    doc = dict(tiny_spec(1))
    doc["spec"] = dict(
        doc["spec"], faults={"replay.dispatch": "always;service.schedule=always"}
    )
    status, body = _req(server, "POST", "/api/v1/jobs", doc)
    assert status == 400
    assert "one schedule per site" in body["message"]
