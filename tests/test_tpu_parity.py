"""TPU test tier: spawn tpu_parity_main.py against the real chip (the
suite itself is pinned to the virtual-CPU backend by conftest.py, so the
check runs in a subprocess with the image's default platform)."""

from __future__ import annotations

import os
import subprocess
import sys
from pathlib import Path

import pytest

REPO = Path(__file__).resolve().parent.parent


def test_tpu_backend_parity():
    env = dict(os.environ)
    # Drop the virtual-CPU-mesh flag the suite injects; keep the image's
    # default platform (the axon TPU plugin).
    env["XLA_FLAGS"] = " ".join(
        f for f in env.get("XLA_FLAGS", "").split()
        if "host_platform_device_count" not in f
    )
    # Prepend (not replace): the image's PYTHONPATH carries the axon TPU
    # plugin's sitecustomize — dropping it would silently lose the chip.
    env["PYTHONPATH"] = os.pathsep.join(
        [str(REPO)] + ([env["PYTHONPATH"]] if env.get("PYTHONPATH") else [])
    )
    # Fast probe first: on a wedged chip, jax backend init BLOCKS (it does
    # not raise), so the full parity run would eat its whole timeout before
    # failing.  A 90s bounded probe turns that into a skip.
    try:
        probe = subprocess.run(
            [sys.executable, "-c", "import jax; print(jax.devices()[0].platform)"],
            capture_output=True,
            text=True,
            timeout=90,
            cwd=REPO,
            env=env,
        )
    except subprocess.TimeoutExpired:
        pytest.skip("TPU backend init timed out (wedged chip)")
    if probe.returncode != 0:
        pytest.skip(f"no TPU available: {probe.stderr.strip()[-200:]}")
    try:
        proc = subprocess.run(
            [sys.executable, str(REPO / "tests" / "tpu_parity_main.py")],
            capture_output=True,
            text=True,
            timeout=580,
            cwd=REPO,
            env=env,
        )
    except subprocess.TimeoutExpired:
        pytest.skip("TPU parity run timed out (chip wedged mid-run)")
    if proc.returncode == 42:
        pytest.skip(f"no TPU available: {proc.stderr.strip()[-200:]}")
    assert proc.returncode == 0, f"stdout:\n{proc.stdout}\nstderr:\n{proc.stderr[-2000:]}"
