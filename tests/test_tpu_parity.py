"""TPU test tier: spawn tpu_parity_main.py against the real chip (the
suite itself is pinned to the virtual-CPU backend by conftest.py, so the
check runs in a subprocess with the image's default platform)."""

from __future__ import annotations

import os
import subprocess
import sys
from pathlib import Path

import pytest

REPO = Path(__file__).resolve().parent.parent


def test_tpu_backend_parity():
    env = dict(os.environ)
    # Drop the virtual-CPU-mesh flag the suite injects; keep the image's
    # default platform (the axon TPU plugin).
    env["XLA_FLAGS"] = " ".join(
        f for f in env.get("XLA_FLAGS", "").split()
        if "host_platform_device_count" not in f
    )
    # Prepend (not replace): the image's PYTHONPATH carries the axon TPU
    # plugin's sitecustomize — dropping it would silently lose the chip.
    env["PYTHONPATH"] = os.pathsep.join(
        [str(REPO)] + ([env["PYTHONPATH"]] if env.get("PYTHONPATH") else [])
    )
    proc = subprocess.run(
        [sys.executable, str(REPO / "tests" / "tpu_parity_main.py")],
        capture_output=True,
        text=True,
        timeout=580,
        cwd=REPO,
        env=env,
    )
    if proc.returncode == 42:
        pytest.skip(f"no TPU available: {proc.stderr.strip()[-200:]}")
    assert proc.returncode == 0, f"stdout:\n{proc.stdout}\nstderr:\n{proc.stderr[-2000:]}"
