"""Shared fixture builders: k8s-shaped JSON objects."""

from __future__ import annotations

import random
from typing import Any

JSON = dict[str, Any]


def make_node(
    name: str,
    cpu: str = "4",
    memory: str = "16Gi",
    pods: int = 110,
    *,
    labels: dict[str, str] | None = None,
    taints: list[JSON] | None = None,
    unschedulable: bool = False,
    extra_alloc: dict[str, str] | None = None,
) -> JSON:
    alloc = {"cpu": cpu, "memory": memory, "pods": str(pods), "ephemeral-storage": "100Gi"}
    alloc.update(extra_alloc or {})
    spec: JSON = {}
    if taints:
        spec["taints"] = taints
    if unschedulable:
        spec["unschedulable"] = True
    return {
        "apiVersion": "v1",
        "kind": "Node",
        "metadata": {"name": name, "labels": labels or {}},
        "spec": spec,
        "status": {"allocatable": dict(alloc), "capacity": dict(alloc)},
    }


def make_pod(
    name: str,
    cpu: str | None = "100m",
    memory: str | None = "128Mi",
    *,
    namespace: str = "default",
    node_name: str = "",
    labels: dict[str, str] | None = None,
    phase: str = "",
    tolerations: list[JSON] | None = None,
    affinity: JSON | None = None,
    node_selector: dict[str, str] | None = None,
    topology_spread_constraints: list[JSON] | None = None,
    priority: int | None = None,
    extra_requests: dict[str, str] | None = None,
) -> JSON:
    requests: JSON = {}
    if cpu is not None:
        requests["cpu"] = cpu
    if memory is not None:
        requests["memory"] = memory
    requests.update(extra_requests or {})
    spec: JSON = {
        "containers": [
            {"name": "c", "image": "img", "resources": {"requests": requests} if requests else {}}
        ]
    }
    if node_name:
        spec["nodeName"] = node_name
    if tolerations:
        spec["tolerations"] = tolerations
    if affinity:
        spec["affinity"] = affinity
    if node_selector:
        spec["nodeSelector"] = node_selector
    if topology_spread_constraints:
        spec["topologySpreadConstraints"] = topology_spread_constraints
    if priority is not None:
        spec["priority"] = priority
    status: JSON = {"phase": phase} if phase else {}
    return {
        "apiVersion": "v1",
        "kind": "Pod",
        "metadata": {"name": name, "namespace": namespace, "labels": labels or {}},
        "spec": spec,
        "status": status,
    }


def random_cluster(
    seed: int,
    n_nodes: int,
    n_pods: int,
    *,
    bound_fraction: float = 0.3,
    unschedulable_fraction: float = 0.1,
) -> tuple[list[JSON], list[JSON]]:
    """Reproducible random cluster; quantities are Mi/milli multiples."""
    rng = random.Random(seed)
    nodes = []
    for i in range(n_nodes):
        nodes.append(
            make_node(
                f"node-{i}",
                cpu=f"{rng.choice([2, 4, 8, 16, 32])}",
                memory=f"{rng.choice([4, 8, 16, 32, 64])}Gi",
                pods=rng.choice([8, 16, 32, 110]),
                unschedulable=rng.random() < unschedulable_fraction,
            )
        )
    pods = []
    for i in range(n_pods):
        bound = rng.random() < bound_fraction
        tolerates = rng.random() < 0.15
        pods.append(
            make_pod(
                f"pod-{i}",
                cpu=rng.choice([None, "50m", "100m", "250m", "500m", "1", "2"]),
                memory=rng.choice([None, "64Mi", "128Mi", "512Mi", "1Gi", "4Gi"]),
                node_name=f"node-{rng.randrange(n_nodes)}" if bound else "",
                tolerations=[
                    {"key": "node.kubernetes.io/unschedulable", "operator": "Exists", "effect": "NoSchedule"}
                ]
                if tolerates
                else None,
            )
        )
    return nodes, pods
