"""Shared fixture builders: k8s-shaped JSON objects."""

from __future__ import annotations

import os
import random
from typing import Any

JSON = dict[str, Any]


def sanitized_cpu_env(extra: dict[str, str] | None = None) -> dict[str, str]:
    """Subprocess environment for CPU smoke tests.

    Drops the axon TPU sitecustomize from PYTHONPATH — on this image it
    blocks on a wedged chip during jax backend init even under
    ``JAX_PLATFORMS=cpu`` — and pins the CPU backend, so entrypoint
    subprocess tests stay hermetic under any hardware condition.  Only
    ``tests/test_tpu_parity.py`` deliberately keeps the axon path (it
    wants the real chip, behind its own watchdog)."""
    env = dict(os.environ)
    pp = [
        p
        for p in env.get("PYTHONPATH", "").split(os.pathsep)
        if p and "axon" not in os.path.basename(p.rstrip("/"))
    ]
    if pp:
        env["PYTHONPATH"] = os.pathsep.join(pp)
    else:
        env.pop("PYTHONPATH", None)
    env["JAX_PLATFORMS"] = "cpu"
    if extra:
        env.update(extra)
    return env


def make_node(
    name: str,
    cpu: str = "4",
    memory: str = "16Gi",
    pods: int = 110,
    *,
    labels: dict[str, str] | None = None,
    taints: list[JSON] | None = None,
    unschedulable: bool = False,
    extra_alloc: dict[str, str] | None = None,
) -> JSON:
    alloc = {"cpu": cpu, "memory": memory, "pods": str(pods), "ephemeral-storage": "100Gi"}
    alloc.update(extra_alloc or {})
    spec: JSON = {}
    if taints:
        spec["taints"] = taints
    if unschedulable:
        spec["unschedulable"] = True
    return {
        "apiVersion": "v1",
        "kind": "Node",
        "metadata": {"name": name, "labels": labels or {}},
        "spec": spec,
        "status": {"allocatable": dict(alloc), "capacity": dict(alloc)},
    }


def make_pod(
    name: str,
    cpu: str | None = "100m",
    memory: str | None = "128Mi",
    *,
    namespace: str = "default",
    node_name: str = "",
    labels: dict[str, str] | None = None,
    phase: str = "",
    tolerations: list[JSON] | None = None,
    affinity: JSON | None = None,
    node_selector: dict[str, str] | None = None,
    topology_spread_constraints: list[JSON] | None = None,
    priority: int | None = None,
    extra_requests: dict[str, str] | None = None,
) -> JSON:
    requests: JSON = {}
    if cpu is not None:
        requests["cpu"] = cpu
    if memory is not None:
        requests["memory"] = memory
    requests.update(extra_requests or {})
    spec: JSON = {
        "containers": [
            {"name": "c", "image": "img", "resources": {"requests": requests} if requests else {}}
        ]
    }
    if node_name:
        spec["nodeName"] = node_name
    if tolerations:
        spec["tolerations"] = tolerations
    if affinity:
        spec["affinity"] = affinity
    if node_selector:
        spec["nodeSelector"] = node_selector
    if topology_spread_constraints:
        spec["topologySpreadConstraints"] = topology_spread_constraints
    if priority is not None:
        spec["priority"] = priority
    status: JSON = {"phase": phase} if phase else {}
    return {
        "apiVersion": "v1",
        "kind": "Pod",
        "metadata": {"name": name, "namespace": namespace, "labels": labels or {}},
        "spec": spec,
        "status": status,
    }


def random_cluster(
    seed: int,
    n_nodes: int,
    n_pods: int,
    *,
    bound_fraction: float = 0.3,
    unschedulable_fraction: float = 0.1,
    pod_affinity_fraction: float = 0.15,
) -> tuple[list[JSON], list[JSON]]:
    """Reproducible random cluster; quantities are Mi/milli multiples."""
    rng = random.Random(seed)
    zones = ["zone-a", "zone-b", "zone-c"]
    disks = ["ssd", "hdd"]
    nodes = []
    for i in range(n_nodes):
        taints = []
        if rng.random() < 0.15:
            taints.append({"key": "dedicated", "value": rng.choice(["gpu", "db"]), "effect": "NoSchedule"})
        if rng.random() < 0.15:
            taints.append({"key": "maintenance", "value": "", "effect": "PreferNoSchedule"})
        nodes.append(
            make_node(
                f"node-{i}",
                cpu=f"{rng.choice([2, 4, 8, 16, 32])}",
                memory=f"{rng.choice([4, 8, 16, 32, 64])}Gi",
                pods=rng.choice([8, 16, 32, 110]),
                unschedulable=rng.random() < unschedulable_fraction,
                labels={
                    "topology.kubernetes.io/zone": rng.choice(zones),
                    "kubernetes.io/hostname": f"node-{i}",
                    "disktype": rng.choice(disks),
                },
                taints=taints or None,
            )
        )
    apps = ["web", "db", "cache", "batch"]
    pods = []
    for i in range(n_pods):
        bound = rng.random() < bound_fraction
        app = rng.choice(apps)
        spread = None
        if rng.random() < 0.3:
            spread = [{
                "maxSkew": rng.choice([1, 2]),
                "topologyKey": rng.choice(["topology.kubernetes.io/zone", "kubernetes.io/hostname"]),
                "whenUnsatisfiable": rng.choice(["DoNotSchedule", "ScheduleAnyway"]),
                "labelSelector": {"matchLabels": {"app": app}},
            }]
            if rng.random() < 0.3:
                spread.append({
                    "maxSkew": 3,
                    "topologyKey": "topology.kubernetes.io/zone",
                    "whenUnsatisfiable": "ScheduleAnyway",
                    "labelSelector": {"matchLabels": {"app": app}},
                })
        tolerations = []
        if rng.random() < 0.15:
            tolerations.append(
                {"key": "node.kubernetes.io/unschedulable", "operator": "Exists", "effect": "NoSchedule"}
            )
        if rng.random() < 0.25:
            tolerations.append(
                {"key": "dedicated", "operator": rng.choice(["Exists", "Equal"]), "value": "gpu", "effect": "NoSchedule"}
            )
        if rng.random() < 0.15:
            tolerations.append({"key": "maintenance", "operator": "Exists"})
        node_selector = {"disktype": rng.choice(disks)} if rng.random() < 0.2 else None
        affinity = None
        if rng.random() < 0.3:
            node_affinity = {}
            if rng.random() < 0.6:
                node_affinity["requiredDuringSchedulingIgnoredDuringExecution"] = {
                    "nodeSelectorTerms": [
                        {"matchExpressions": [
                            {"key": "topology.kubernetes.io/zone", "operator": "In",
                             "values": rng.sample(zones, rng.randint(1, 2))}
                        ]}
                    ]
                }
            if rng.random() < 0.7:
                node_affinity["preferredDuringSchedulingIgnoredDuringExecution"] = [
                    {"weight": rng.choice([1, 10, 50, 100]),
                     "preference": {"matchExpressions": [
                         {"key": "disktype", "operator": rng.choice(["In", "NotIn"]),
                          "values": [rng.choice(disks)]}
                     ]}}
                ]
            if node_affinity:
                affinity = {"nodeAffinity": node_affinity}
        if rng.random() < pod_affinity_fraction:
            tk = rng.choice(["topology.kubernetes.io/zone", "kubernetes.io/hostname"])
            term = {
                "labelSelector": {"matchLabels": {"app": rng.choice(apps)}},
                "topologyKey": tk,
            }
            kind = rng.random()
            pod_aff: JSON = {}
            if kind < 0.35:
                pod_aff["podAffinity"] = {
                    "requiredDuringSchedulingIgnoredDuringExecution": [term]
                }
            elif kind < 0.65:
                pod_aff["podAntiAffinity"] = {
                    "requiredDuringSchedulingIgnoredDuringExecution": [term]
                }
            else:
                pod_aff["podAffinity"] = {
                    "preferredDuringSchedulingIgnoredDuringExecution": [
                        {"weight": rng.choice([1, 25, 100]), "podAffinityTerm": term}
                    ]
                }
                if rng.random() < 0.5:
                    pod_aff["podAntiAffinity"] = {
                        "preferredDuringSchedulingIgnoredDuringExecution": [
                            {
                                "weight": rng.choice([1, 25, 100]),
                                "podAffinityTerm": {
                                    "labelSelector": {
                                        "matchLabels": {"app": rng.choice(apps)}
                                    },
                                    "topologyKey": "topology.kubernetes.io/zone",
                                },
                            }
                        ]
                    }
            affinity = {**(affinity or {}), **pod_aff}
        pods.append(
            make_pod(
                f"pod-{i}",
                cpu=rng.choice([None, "50m", "100m", "250m", "500m", "1", "2"]),
                memory=rng.choice([None, "64Mi", "128Mi", "512Mi", "1Gi", "4Gi"]),
                node_name=f"node-{rng.randrange(n_nodes)}" if bound else "",
                labels={"app": app},
                tolerations=tolerations or None,
                node_selector=node_selector,
                affinity=affinity,
                topology_spread_constraints=spread,
            )
        )
    return nodes, pods


def pods_by_node(pods: list[JSON]) -> dict[str, list[JSON]]:
    """Bound, non-terminal pods grouped by node (the spread-stats view)."""
    out: dict[str, list[JSON]] = {}
    for p in pods:
        if not p.get("spec", {}).get("nodeName"):
            continue
        if p.get("status", {}).get("phase") in ("Succeeded", "Failed"):
            continue
        out.setdefault(p["spec"]["nodeName"], []).append(p)
    return out
