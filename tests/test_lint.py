"""tools/ksimlint — the AST contract analyzer (docs/lint.md).

Three layers:

- per-rule fixture tests under tests/fixtures/lint/: one seeded-bad,
  one suppressed, one clean sample per rule, proving each checker
  actually FIRES and honors ``# ksimlint: disable=`` suppressions;
- the full-tree scan, in-process, asserting the real codebase carries
  zero unsuppressed findings (the same gate as ``make lint``);
- cross-checks pinning the analyzer's AST-side views (kernel registry,
  taxonomy registries) to the runtime objects the process imports.

The analyzer itself is stdlib-only, so everything here except the
runtime cross-check runs without touching jax.
"""

from __future__ import annotations

import os

import pytest

from tools.ksimlint.core import DEFAULT_TARGETS, Project, mark_suppressed, run
from tools.ksimlint.rules import (
    env_contract,
    exception_flow,
    import_boundary,
    kernel_purity,
    lock_discipline,
    lock_order,
    registry_literals,
    thread_role,
)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
FIXTURES = os.path.join(REPO, "tests", "fixtures", "lint")


def _project(*names: str) -> Project:
    return Project.load(FIXTURES, tuple(names))


def _run_rule(check, project: Project, **kw):
    """check() + suppression marking; returns (open, suppressed)."""
    findings = mark_suppressed(project, check(project, **kw))
    return (
        [f for f in findings if not f.suppressed],
        [f for f in findings if f.suppressed],
    )


# ---------------------------------------------------------------------------
# lock-discipline
# ---------------------------------------------------------------------------


def test_lock_discipline_fires_on_seeded_violations():
    open_, suppressed = _run_rule(lock_discipline.check, _project("lock_bad.py"))
    assert not suppressed
    lines = {f.line for f in open_}
    messages = "\n".join(f.message for f in open_)
    # Unlocked module-global, unlocked read, unlocked write, closure
    # escape, worker self-write — and nothing from the disciplined
    # methods.
    assert len(open_) == 5, messages
    assert "_registry" in messages
    assert "self._items" in messages
    assert "worker-thread function '_run' writes self.counter" in messages
    # Exactly the seeded lines fired — the with-block, lock-held and
    # main-thread-read accesses produced nothing.
    assert lines == {16, 33, 36, 41, 46}, sorted(lines)


def test_lock_discipline_suppression_and_clean():
    open_, suppressed = _run_rule(lock_discipline.check, _project("lock_suppressed.py"))
    assert not open_ and len(suppressed) == 1
    open_, suppressed = _run_rule(lock_discipline.check, _project("lock_clean.py"))
    assert not open_ and not suppressed


# ---------------------------------------------------------------------------
# kernel-purity
# ---------------------------------------------------------------------------


def test_kernel_purity_fires_on_seeded_violations():
    open_, suppressed = _run_rule(kernel_purity.check, _project("kernel_bad.py"))
    assert not suppressed
    messages = [f.message for f in open_]
    joined = "\n".join(messages)
    assert sum("Python branch on a traced value" in m for m in messages) == 2
    assert "print() inside a traced body" in joined
    assert "float() coerces a traced value" in joined
    assert "host numpy op np.sum" in joined
    assert "64-bit dtype literal 'float64'" in joined
    assert ".item() on a traced value" in joined
    # The static-arg branch (cfg.preempt) did NOT fire.
    assert len(open_) == 7, joined


def test_kernel_purity_suppression_and_clean():
    open_, suppressed = _run_rule(kernel_purity.check, _project("kernel_suppressed.py"))
    assert not open_ and len(suppressed) == 1
    open_, suppressed = _run_rule(kernel_purity.check, _project("kernel_clean.py"))
    assert not open_ and not suppressed


# ---------------------------------------------------------------------------
# import-boundary
# ---------------------------------------------------------------------------


def _boundary(target, scope):
    return (
        import_boundary.Boundary(
            target, frozenset({"jax", "jaxlib", "numpy"}), scope
        ),
    )


def test_import_boundary_fires_per_scope():
    project = _project("import_bad.py")
    # import-time: the module-scope numpy import (function bodies and
    # child payloads are invisible to this scope).
    open_, _ = _run_rule(
        import_boundary.check, project,
        boundaries=_boundary("import_bad.py", "import-time"),
    )
    assert len(open_) == 1 and "numpy" in open_[0].message
    # parent-child: module scope AND the non-child parent function; the
    # child payload stays sanctioned.
    open_, _ = _run_rule(
        import_boundary.check, project,
        boundaries=_boundary("import_bad.py", "parent-child"),
    )
    assert len(open_) == 2
    assert any("parent_helper" in f.message for f in open_)
    assert not any("child_payload" in f.message for f in open_)


def test_import_boundary_suppression_and_clean():
    open_, suppressed = _run_rule(
        import_boundary.check, _project("import_suppressed.py"),
        boundaries=_boundary("import_suppressed.py", "everywhere"),
    )
    assert not open_ and len(suppressed) == 1
    open_, suppressed = _run_rule(
        import_boundary.check, _project("import_clean.py"),
        boundaries=_boundary("import_clean.py", "import-time"),
    )
    assert not open_ and not suppressed  # lazy bridge + TYPE_CHECKING legal


# ---------------------------------------------------------------------------
# registry-literals
# ---------------------------------------------------------------------------


def _registry_cfg(replay: str) -> registry_literals.RegistryConfig:
    return registry_literals.RegistryConfig(
        faults_module="registry_regs.py",
        obs_module="registry_regs.py",
        replay_module=replay,
    )


def test_registry_literals_fires_on_seeded_violations():
    project = _project(
        "registry_regs.py", "registry_replay_bad.py", "registry_caller_bad.py"
    )
    open_, suppressed = _run_rule(
        registry_literals.check, project, cfg=_registry_cfg("registry_replay_bad.py")
    )
    assert not suppressed
    joined = "\n".join(f.message for f in open_)
    assert "'rogue.site' is not declared in SITES" in joined
    assert "SITES entry 'wired.site' has no FAULTS.check call site" in joined
    assert "'rogue.span' is not in obs.SPAN_NAMES" in joined
    assert "'rogue.event' is not in obs.EVENT_NAMES" in joined
    assert "non-literal name" in joined
    assert "'rogue_metric' is not in obs.METRIC_NAMES" in joined
    assert "non-literal family name" in joined
    assert "'rogue_reason' not in FALLBACK_REASONS" in joined
    assert "'host_hook:' not covered by FALLBACK_REASON_PREFIXES" in joined
    assert "'dead_entry' appears nowhere" in joined


def test_registry_literals_suppression_and_clean():
    project = _project(
        "registry_regs.py", "registry_replay_clean.py", "registry_caller_suppressed.py"
    )
    open_, suppressed = _run_rule(
        registry_literals.check, project, cfg=_registry_cfg("registry_replay_clean.py")
    )
    # The two rogue call sites are suppressed; the unwired-site finding
    # for wired.site remains structural (the suppressed calls don't
    # count as wiring) — assert exactly that split.
    assert len(suppressed) == 2
    assert len(open_) == 1 and "no FAULTS.check call site" in open_[0].message

    project = _project(
        "registry_regs.py", "registry_replay_clean.py", "registry_caller_clean.py"
    )
    open_, suppressed = _run_rule(
        registry_literals.check, project, cfg=_registry_cfg("registry_replay_clean.py")
    )
    assert not open_ and not suppressed


def test_registry_literals_dead_metric_entry_fires():
    """A METRIC_NAMES entry with no _expo_family declaration is a dead
    registry entry — a family dashboards would scrape for in vain."""
    project = _project(
        "registry_regs_deadmetric.py",
        "registry_replay_clean.py",
        "registry_caller_clean.py",
    )
    cfg = registry_literals.RegistryConfig(
        faults_module="registry_regs_deadmetric.py",
        obs_module="registry_regs_deadmetric.py",
        replay_module="registry_replay_clean.py",
    )
    open_, suppressed = _run_rule(registry_literals.check, project, cfg=cfg)
    assert not suppressed
    assert len(open_) == 1, [f.message for f in open_]
    assert "'ksim_dead_total'" in open_[0].message
    assert "dead registry entry" in open_[0].message


# ---------------------------------------------------------------------------
# env-contract
# ---------------------------------------------------------------------------


def test_env_contract_fires_both_directions():
    open_, _ = _run_rule(
        env_contract.check, _project("env_bad.py"),
        cfg=env_contract.EnvConfig(docs_rel="env_docs.md"),
    )
    joined = "\n".join(f"{f.path}: {f.message}" for f in open_)
    assert "env_bad.py: KSIM_LINTFIXTURE_UNDOCUMENTED" in joined
    assert "env_docs.md: documented variable KSIM_LINTFIXTURE_DEAD" in joined


def test_env_contract_suppression_and_clean():
    open_, suppressed = _run_rule(
        env_contract.check, _project("env_suppressed.py"),
        cfg=env_contract.EnvConfig(docs_rel="env_docs_clean.md"),
    )
    assert not open_ and len(suppressed) == 1
    open_, suppressed = _run_rule(
        env_contract.check, _project("env_clean.py"),
        cfg=env_contract.EnvConfig(docs_rel="env_docs_clean.md"),
    )
    assert not open_ and not suppressed


def test_env_contract_missing_docs_is_a_finding():
    open_, _ = _run_rule(
        env_contract.check, _project("env_bad.py"),
        cfg=env_contract.EnvConfig(docs_rel="no_such_docs.md"),
    )
    assert len(open_) == 1 and "missing" in open_[0].message


# ---------------------------------------------------------------------------
# lock-order (interprocedural — tools/ksimlint/callgraph.py)
# ---------------------------------------------------------------------------


def test_lock_order_seeded_deadlock_is_exactly_one_cycle():
    """The ABBA fixture declares BOTH orders, so the only finding is
    the cycle itself — visible only through the call graph (neither
    function nests two with-blocks lexically)."""
    open_, suppressed = _run_rule(lock_order.check, _project("lockorder_bad.py"))
    assert not suppressed
    assert len(open_) == 1, [f.message for f in open_]
    assert "cycle" in open_[0].message
    assert "Pair._a" in open_[0].message and "Pair._b" in open_[0].message


def test_lock_order_suppression_waives_and_clean():
    # Suppressing EVERY witness of an edge also waives it out of the
    # cycle graph — one suppressed finding, nothing open.
    open_, suppressed = _run_rule(
        lock_order.check, _project("lockorder_suppressed.py")
    )
    assert not open_ and len(suppressed) == 1
    assert "undeclared lock nesting" in suppressed[0].message
    # Declared acyclic nesting + RLock reentrancy: nothing at all.
    open_, suppressed = _run_rule(lock_order.check, _project("lockorder_clean.py"))
    assert not open_ and not suppressed


def test_lock_order_graph_covers_annotated_domains():
    """Every annotated lock domain in the tree is a node the analyzer
    can reason about — the coverage claim behind the zero-cycle gate."""
    graph = Project.load(REPO, DEFAULT_TARGETS).callgraph()
    required = {
        "ClusterStore._lock",
        "TracePlane._lock",
        "FaultPlane._lock",
        "JobQueue._cond",
        "Job._cond",
        "JobManager._lock",
        "JobJournal._lock",
        "CompileCache._lock",
        "replay._PREWARM_LOCK",
        "replay._TP_MESH_LOCK",
    }
    assert required <= set(graph.lock_kinds), sorted(graph.lock_kinds)
    # The documented compaction chain is OBSERVED, not just declared:
    # the qualified lock-held on JobManager._journal_records is what
    # makes the dynamic snapshot_fn callback visible.
    assert ("JobJournal._lock", "JobManager._lock") in graph.observed_edges()


# ---------------------------------------------------------------------------
# thread-role
# ---------------------------------------------------------------------------


def test_thread_role_seeded_worker_store_is_exactly_one_finding():
    """The store lives in a helper the round-8 lexical check cannot
    see; the interprocedural propagation reaches it."""
    open_, suppressed = _run_rule(thread_role.check, _project("role_bad.py"))
    assert not suppressed
    assert len(open_) == 1, [f.message for f in open_]
    assert "store to self.done" in open_[0].message
    assert "reachable from dispatch-worker root" in open_[0].message


def test_thread_role_suppression_and_clean():
    open_, suppressed = _run_rule(thread_role.check, _project("role_suppressed.py"))
    assert not open_ and len(suppressed) == 1
    open_, suppressed = _run_rule(thread_role.check, _project("role_clean.py"))
    assert not open_ and not suppressed


def test_thread_role_unknown_role_and_missing_role_fire():
    """A typo'd role would silently opt out of every propagated check;
    an unannotated resolved Thread target is the same hazard."""
    import textwrap

    from tools.ksimlint.core import SourceFile

    src = textwrap.dedent(
        """
        import threading


        class D:
            def start(self):
                threading.Thread(target=self._work).start()
                threading.Thread(target=self._other).start()

            def _work(self):  # ksimlint: thread-role(cowboy)
                pass

            def _other(self):
                pass
        """
    )
    sf = SourceFile("m.py", "m.py", src)
    findings = thread_role.check(Project("/tmp", {"m.py": sf}, ("m.py",)))
    joined = "\n".join(f.message for f in findings)
    assert "unknown thread-role 'cowboy'" in joined
    assert "has no role annotation" in joined


# ---------------------------------------------------------------------------
# exception-flow
# ---------------------------------------------------------------------------


def test_exception_flow_seeded_absorption_is_exactly_one_finding():
    """run_all's broad handler absorbs the RunCancelled its callee may
    raise — known only through the call graph."""
    open_, suppressed = _run_rule(exception_flow.check, _project("exc_bad.py"))
    assert not suppressed
    assert len(open_) == 1, [f.message for f in open_]
    assert "broad except absorbs RunCancelled" in open_[0].message
    assert "_step" in open_[0].message


def test_exception_flow_suppression_and_clean():
    open_, suppressed = _run_rule(exception_flow.check, _project("exc_suppressed.py"))
    assert not open_ and len(suppressed) == 1
    # Explicit RunCancelled arm, capture-box pattern, _reject-raised
    # ReplayFallback: all compliant shapes, zero findings.
    open_, suppressed = _run_rule(exception_flow.check, _project("exc_clean.py"))
    assert not open_ and not suppressed


def test_exception_flow_fault_and_fallback_channels():
    """except InjectedFault outside the containment scopes and a direct
    ReplayFallback raise outside _reject/_Unsupported both fire."""
    import textwrap

    from tools.ksimlint.core import SourceFile

    src = textwrap.dedent(
        """
        class InjectedFault(Exception):
            pass


        class ReplayFallback(Exception):
            pass


        def contain(op):
            try:
                return op()
            except InjectedFault:
                return None


        def bail(reason):
            raise ReplayFallback(reason)
        """
    )
    sf = SourceFile("m.py", "m.py", src)
    findings = exception_flow.check(Project("/tmp", {"m.py": sf}, ("m.py",)))
    joined = "\n".join(f.message for f in findings)
    assert "explicit `except InjectedFault` outside" in joined
    assert "direct `raise ReplayFallback(...)`" in joined


# ---------------------------------------------------------------------------
# The full tree (the same gate as `make lint`)
# ---------------------------------------------------------------------------


def test_full_tree_has_zero_unsuppressed_findings():
    """The tier-1 in-process equivalent of `make lint`: every rule over
    ksim_tpu/, bench.py and tools/ — zero unsuppressed findings.  The
    analyzer is stdlib-only, so this needs no jax and no subprocess."""
    findings = run(REPO, DEFAULT_TARGETS)
    open_ = [f for f in findings if not f.suppressed]
    assert not open_, "\n" + "\n".join(f.format() for f in open_)
    # The suppressions that exist are the documented, justified ones;
    # a new suppression should be a conscious reviewable event, so pin
    # the count: two round-11 lock-discipline snapshots, the fleet
    # driver's deliberate on-worker mesh-failure store (round 19's
    # _mesh_lock rework left one flagged write where round 18 had two),
    # and the waived construction-time JobManager._recover journal edge.
    assert len(findings) - len(open_) == 4, [f.format() for f in findings if f.suppressed]


def test_cli_human_and_json(tmp_path, capsys):
    """The CLI surface `make lint` drives: exit 0 + summary on the real
    tree, exit 1 on a tree with a finding, --json parses."""
    import json as json_mod

    from tools.ksimlint.__main__ import main

    assert main(["--root", REPO]) == 0
    capsys.readouterr()
    bad = tmp_path / "mod.py"
    bad.write_text(
        "import threading\n"
        "class C:\n"
        "    def __init__(self):\n"
        "        self._lock = threading.Lock()\n"
        "        self._d = {}  # guarded-by: _lock\n"
        "    def f(self):\n"
        "        return self._d\n"
    )
    assert main(["--root", str(tmp_path), "mod.py"]) == 1
    out = capsys.readouterr().out
    assert "mod.py:7" in out and "lock-discipline" in out
    assert main(["--root", str(tmp_path), "mod.py", "--json"]) == 1
    doc = json_mod.loads(capsys.readouterr().out)
    assert doc["unsuppressed"] == 1 and doc["findings"][0]["rule"] == "lock-discipline"


def test_cli_exits_1_on_seeded_concurrency_fixtures(capsys):
    """The gate the ISSUE pins: the analyzer run on either seeded
    fixture fails the build (exit 1) with exactly one finding."""
    from tools.ksimlint.__main__ import main

    assert main(["--root", REPO, "tests/fixtures/lint/lockorder_bad.py"]) == 1
    out = capsys.readouterr().out
    assert out.count("[lock-order]") == 1 and "cycle" in out
    assert main(["--root", REPO, "tests/fixtures/lint/role_bad.py"]) == 1
    out = capsys.readouterr().out
    assert out.count("[thread-role]") == 1


def test_trace_ingest_role_fixtures(capsys):
    """Round 20: ``trace-ingest`` is in the role vocabulary — the
    stream.py-shaped clean fixture passes, the seeded cross-thread
    write (producer storing to a main-thread-guarded attr through a
    helper) fails, and the real producer module itself is clean under
    the rule."""
    from tools.ksimlint.__main__ import main

    assert main(["--root", REPO, "tests/fixtures/lint/role_ingest_clean.py"]) == 0
    capsys.readouterr()
    assert main(["--root", REPO, "tests/fixtures/lint/role_ingest_bad.py"]) == 1
    out = capsys.readouterr().out
    assert out.count("[thread-role]") == 1 and "trace-ingest" in out
    assert (
        main(["--root", REPO, "--rule", "thread-role", "ksim_tpu/traces/stream.py"])
        == 0
    )


def test_cli_rule_flag_filters(capsys):
    """--rule is the repeatable single-rule spelling of --rules; an
    unknown rule is still a loud exit 2."""
    from tools.ksimlint.__main__ import main

    assert (
        main(
            [
                "--root", REPO, "--rule", "exception-flow",
                "tests/fixtures/lint/exc_bad.py",
            ]
        )
        == 1
    )
    assert "[exception-flow]" in capsys.readouterr().out
    assert main(["--root", REPO, "--rule", "lock-ordr"]) == 2


def test_cli_sarif_output(capsys):
    """--format sarif: schema-shaped SARIF 2.1.0 with rule metadata,
    physical locations, and in-source suppression objects."""
    import json as json_mod

    from tools.ksimlint.__main__ import main

    assert main(["--root", REPO, "--format", "sarif"]) == 0
    doc = json_mod.loads(capsys.readouterr().out)
    assert doc["version"] == "2.1.0" and doc["$schema"].endswith("sarif-2.1.0.json")
    run0 = doc["runs"][0]
    driver = run0["tool"]["driver"]
    assert driver["name"] == "ksimlint"
    rule_ids = [r["id"] for r in driver["rules"]]
    assert len(rule_ids) == 8 and "lock-order" in rule_ids
    assert all(r["shortDescription"]["text"] for r in driver["rules"])
    # The real tree's findings are all suppressed: each result carries
    # the in-source suppression object so an upload stays green.
    assert run0["results"], "expected the audited suppressions to appear"
    for res in run0["results"]:
        assert res["ruleId"] in rule_ids
        assert res["ruleIndex"] == rule_ids.index(res["ruleId"])
        loc = res["locations"][0]["physicalLocation"]
        assert loc["artifactLocation"]["uri"]
        assert loc["region"]["startLine"] >= 1
        assert res["suppressions"][0]["kind"] == "inSource"
    # An OPEN finding has no suppressions key (SARIF viewers would
    # otherwise hide it).
    assert main(
        ["--root", REPO, "--format", "sarif", "tests/fixtures/lint/exc_bad.py"]
    ) == 1
    doc = json_mod.loads(capsys.readouterr().out)
    (res,) = doc["runs"][0]["results"]
    assert "suppressions" not in res


def test_cli_partial_target_and_typo(capsys):
    """A single-file run must not mass-flag docs rows the slice doesn't
    mention (the dead-row direction needs the whole tree), and a typo'd
    target is a loud usage error (exit 2), never a vacuously green
    scan of nothing."""
    from tools.ksimlint.__main__ import main

    assert main(["--root", REPO, "ksim_tpu/obs.py"]) == 0
    capsys.readouterr()
    assert main(["--root", REPO, "ksim_tpu/no_such_file.py"]) == 2
    assert "not found" in capsys.readouterr().err
    # A typo'd rule name is the same vacuously-green hazard: exit 2.
    assert main(["--root", REPO, "--rules", "lock-disclipine"]) == 2
    assert "unknown rule" in capsys.readouterr().err


def test_import_boundary_relative_imports_resolve(tmp_path):
    """A relative import is just spelling — it must not bypass the
    boundary: `from .engine import replay` from pkg/obs.py reaches
    pkg/engine/replay.py, whose module-scope jax import breaks the
    import-time contract transitively."""
    pkg = tmp_path / "pkg"
    (pkg / "engine").mkdir(parents=True)
    (pkg / "__init__.py").write_text("")
    (pkg / "obs.py").write_text("from .engine import replay\n")
    (pkg / "engine" / "__init__.py").write_text("")
    (pkg / "engine" / "replay.py").write_text("import jax\n")
    project = Project.load(str(tmp_path), ("pkg",))
    findings = import_boundary.check(
        project,
        boundaries=(
            import_boundary.Boundary(
                "pkg/obs.py", frozenset({"jax"}), "import-time"
            ),
        ),
    )
    assert len(findings) == 1
    assert "pkg/engine/replay.py:1 imports jax" in findings[0].message


def test_lock_discipline_module_guards_cover_methods():
    """A class method touching a guarded module global without its lock
    is a finding too (the obs provider-registry shape)."""
    import textwrap

    from tools.ksimlint.core import SourceFile

    src = textwrap.dedent(
        """
        import threading

        _providers = {}  # guarded-by: _providers_lock
        _providers_lock = threading.Lock()


        class Plane:
            def sneaky(self):
                return dict(_providers)

            def polite(self):
                with _providers_lock:
                    return dict(_providers)
        """
    )
    sf = SourceFile("m.py", "m.py", src)
    findings = lock_discipline.check(Project("/tmp", {"m.py": sf}, ("m.py",)))
    assert len(findings) == 1 and findings[0].line == 10


def test_kernel_purity_scans_match_statements():
    """No statement type escapes the kernel scan: a match on a traced
    subject is host control flow, and case bodies are checked."""
    import textwrap

    from tools.ksimlint.core import SourceFile

    src = textwrap.dedent(
        """
        def device_kernel(fn=None, *, static=()):
            return fn if fn is not None else (lambda f: f)


        @device_kernel
        def k(x):
            match x:
                case 0:
                    print("zero")
                case _:
                    pass
            return x
        """
    )
    sf = SourceFile("m.py", "m.py", src)
    findings = kernel_purity.check(Project("/tmp", {"m.py": sf}, ("m.py",)))
    messages = "\n".join(f.message for f in findings)
    assert "Python branch on a traced value" in messages
    assert "print() inside a traced body" in messages


# ---------------------------------------------------------------------------
# Runtime cross-checks (these import the engine, hence jax)
# ---------------------------------------------------------------------------


def test_kernel_registry_matches_ast_scan():
    """The runtime KERNELS registry (decorator side) and the analyzer's
    AST scan (enforcement side) see the same kernels with the same
    static names — a kernel marked but unparsable, or scanned but
    unregistered, cannot drift silently."""
    import ksim_tpu.engine.core  # noqa: F401 - registers kernels on import
    import ksim_tpu.engine.replay  # noqa: F401
    from ksim_tpu.engine.kernelreg import KERNELS

    project = Project.load(
        REPO, ("ksim_tpu/engine/core.py", "ksim_tpu/engine/replay.py")
    )
    ast_view = {
        (fn.name, statics)
        for sf in project.files.values()
        for fn, statics in kernel_purity.scan_kernels(sf)
    }
    runtime_view = {(f.__name__, f.__ksim_kernel_static__) for f in KERNELS}
    assert runtime_view == ast_view
    assert ("_segment_fn", ("st", "prog")) in runtime_view
    assert ("_schedule_fn", ("self",)) in runtime_view


def test_device_kernel_decorator_is_identity():
    from ksim_tpu.engine.kernelreg import KERNELS, device_kernel

    before = len(KERNELS)

    @device_kernel
    def bare(x):
        return x

    @device_kernel(static=("cfg",))
    def with_args(cfg, x):
        return x

    try:
        assert bare(1) == 1 and with_args(None, 2) == 2
        assert bare.__ksim_kernel_static__ == ()
        assert with_args.__ksim_kernel_static__ == ("cfg",)
        assert KERNELS[-2:] == [bare, with_args]
    finally:
        del KERNELS[before:]
