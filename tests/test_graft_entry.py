"""The driver contract: entry() jits single-device; dryrun_multichip
compiles + executes the full sharded step on the virtual mesh.

Running these in-suite means a regression in either entry point is
caught by `make test` instead of first failing in the driver's own
compile-check at round end.
"""

from __future__ import annotations

import jax


def test_entry_jits_and_runs():
    import __graft_entry__ as g

    fn, ex = g.entry()
    out = jax.jit(fn)(*ex)
    jax.block_until_ready(out)
    # The batch step returns the per-pod output pytree; selection must
    # cover the padded pod axis.
    assert out["selected"].shape[0] == ex[1].valid.shape[0]


def test_dryrun_multichip_on_virtual_mesh():
    import __graft_entry__ as g

    # conftest.py pins the suite to the 8-device virtual CPU mesh, which
    # is exactly what dryrun_multichip builds from.
    g.dryrun_multichip(8)
