"""pod_requests: upstream resourcehelper.PodRequests semantics incl. sidecars."""

from ksim_tpu.state.resources import pod_requests


def _pod(containers=(), init_containers=(), overhead=None):
    spec = {"containers": list(containers)}
    if init_containers:
        spec["initContainers"] = list(init_containers)
    if overhead:
        spec["overhead"] = overhead
    return {"metadata": {"name": "p"}, "spec": spec}


def _c(cpu=None, memory=None, restart=None):
    c = {"name": "c", "resources": {"requests": {}}}
    if cpu:
        c["resources"]["requests"]["cpu"] = cpu
    if memory:
        c["resources"]["requests"]["memory"] = memory
    if restart:
        c["restartPolicy"] = restart
    return c


def test_sum_of_app_containers():
    p = _pod([_c(cpu="100m"), _c(cpu="200m", memory="1Gi")])
    assert pod_requests(p) == {"cpu": 300, "memory": 1024**3}


def test_init_container_max():
    p = _pod([_c(cpu="100m")], [_c(cpu="1"), _c(cpu="500m")])
    assert pod_requests(p)["cpu"] == 1000  # max(100, 1000, 500)


def test_sidecar_adds_to_total():
    # Sidecar (restartPolicy: Always) joins the running sum: 1 + 1 = 2 CPU.
    p = _pod([_c(cpu="1")], [_c(cpu="1", restart="Always")])
    assert pod_requests(p)["cpu"] == 2000


def test_non_restartable_init_includes_prior_sidecars():
    # Init container runs while earlier sidecars are up: its requirement is
    # own + sidecar sum; max'ed against app-sum + sidecars.
    p = _pod(
        [_c(cpu="500m")],
        [_c(cpu="1", restart="Always"), _c(cpu="2")],
    )
    # total = max(500m + 1, 2 + 1) = 3
    assert pod_requests(p)["cpu"] == 3000


def test_overhead_added():
    p = _pod([_c(cpu="100m")], overhead={"cpu": "50m"})
    assert pod_requests(p)["cpu"] == 150


def test_non_zero_defaults_apply_to_init_containers_too():
    p = _pod([], [_c(memory="1Gi")])
    nz = pod_requests(p, non_zero=True)
    assert nz["cpu"] == 100  # defaulted
    assert nz["memory"] == 1024**3
