"""HTTP server integration: the reference's /api/v1 surface end-to-end
(reference simulator/server/server.go:44-54) — config-change -> schedule ->
export cycle, reset, and the streaming listwatchresources endpoint."""

from __future__ import annotations

import http.client
import json
import time

import pytest

from ksim_tpu.server import DIContainer, SimulatorServer
from tests.helpers import make_node, make_pod


@pytest.fixture()
def server():
    di = DIContainer()
    srv = SimulatorServer(di, port=0).start()  # ephemeral port
    yield srv
    srv.shutdown_server()
    di.shutdown()


def _conn(srv):
    return http.client.HTTPConnection("127.0.0.1", srv.port, timeout=10)


def _req(srv, method, path, body=None):
    c = _conn(srv)
    c.request(
        method,
        path,
        json.dumps(body) if body is not None else None,
        {"Content-Type": "application/json"},
    )
    r = c.getresponse()
    data = r.read()
    c.close()
    return r.status, json.loads(data) if data else None


def _raw(srv, method, path, body=None, ctype="application/json"):
    c = _conn(srv)
    c.request(method, path, body, {"Content-Type": ctype})
    r = c.getresponse()
    data = r.read()
    c.close()
    return r.status, data.decode()


def test_full_cycle_over_http(server):
    di = server.di
    # Import a snapshot.
    snap = {
        "nodes": [make_node("n0", cpu="4", memory="8Gi")],
        "pods": [make_pod("p0", cpu="1", memory="1Gi")],
        "pvs": [], "pvcs": [], "storageClasses": [], "priorityClasses": [],
        "namespaces": [], "schedulerConfig": None,
    }
    status, _ = _req(server, "POST", "/api/v1/import", snap)
    assert status == 200

    # Apply a scheduler config (only profiles/extenders are taken).
    cfg = {
        "apiVersion": "kubescheduler.config.k8s.io/v1",
        "kind": "KubeSchedulerConfiguration",
        "profiles": [{"schedulerName": "my-scheduler"}],
    }
    status, _ = _req(server, "POST", "/api/v1/schedulerconfiguration", cfg)
    assert status == 202
    status, got = _req(server, "GET", "/api/v1/schedulerconfiguration")
    assert status == 200
    assert got["profiles"] == [{"schedulerName": "my-scheduler"}]

    # A bad config rolls back and returns 500.
    bad = {"profiles": [{"plugins": {"multiPoint": {"enabled": [{"name": "Nope"}]}}}]}
    status, _ = _req(server, "POST", "/api/v1/schedulerconfiguration", bad)
    assert status == 500
    _, got = _req(server, "GET", "/api/v1/schedulerconfiguration")
    assert got["profiles"] == [{"schedulerName": "my-scheduler"}]

    # Schedule the pending pod (profile renamed the scheduler, so address it).
    di.store.patch(
        "pods", "p0", "default",
        lambda o: o["spec"].__setitem__("schedulerName", "my-scheduler"),
    )
    placements = di.scheduler_service.schedule_pending()
    assert placements == {"default/p0": "n0"}

    # Export reflects the binding and the applied config.
    status, out = _req(server, "GET", "/api/v1/export")
    assert status == 200
    assert out["pods"][0]["spec"]["nodeName"] == "n0"
    assert out["schedulerConfig"]["profiles"] == [{"schedulerName": "my-scheduler"}]

    # Reset restores the boot-time (empty) cluster and default config.
    status, _ = _req(server, "PUT", "/api/v1/reset")
    assert status == 202
    status, out = _req(server, "GET", "/api/v1/export")
    assert out["nodes"] == [] and out["pods"] == []
    _, got = _req(server, "GET", "/api/v1/schedulerconfiguration")
    # Reset returns the scheme-defaulted document (reference
    # DefaultSchedulerConfig, scheduler/config/config.go:19-26).
    assert got["profiles"] == [{"schedulerName": "default-scheduler"}]
    assert got["kind"] == "KubeSchedulerConfiguration"


def test_extender_routes_present(server):
    status, body = _req(server, "POST", "/api/v1/extender/filter/0", {})
    assert status == 400  # no extenders configured
    status, _ = _req(server, "POST", "/api/v1/extender/nope/0", {})
    assert status == 404


def _read_events(resp, n, deadline=10.0):
    events = []
    end = time.monotonic() + deadline
    while len(events) < n and time.monotonic() < end:
        try:
            line = resp.readline()
        except TimeoutError:
            break
        if line.strip():
            events.append(json.loads(line))
    return events


def test_listwatch_stream(server):
    di = server.di
    di.store.create("nodes", make_node("n0"))
    c = _conn(server)
    c.request("GET", "/api/v1/listwatchresources")
    resp = c.getresponse()
    assert resp.status == 200
    # Initial LIST as ADDED.
    (ev,) = _read_events(resp, 1)
    assert ev["Kind"] == "nodes" and ev["EventType"] == "ADDED"
    assert ev["Obj"]["metadata"]["name"] == "n0"
    # Live event.
    di.store.create("pods", make_pod("p0"))
    (ev2,) = _read_events(resp, 1)
    assert ev2["Kind"] == "pods" and ev2["EventType"] == "ADDED"
    rv = int(ev2["Obj"]["metadata"]["resourceVersion"])
    c.close()

    # Resume from lastResourceVersion: only newer events arrive.
    di.store.create("pods", make_pod("p1"))
    c2 = _conn(server)
    c2.request(
        "GET",
        "/api/v1/listwatchresources?podsLastResourceVersion="
        f"{rv}&nodesLastResourceVersion={rv}",
    )
    resp2 = c2.getresponse()
    (ev3,) = _read_events(resp2, 1)
    assert ev3["Kind"] == "pods" and ev3["Obj"]["metadata"]["name"] == "p1"
    c2.close()


def test_watch_driven_scheduling_over_http(server):
    """The full product loop: watch stream sees the pod arrive and then
    get bound by the running scheduler."""
    di = server.di
    di.store.create("nodes", make_node("n0"))
    di.scheduler_service.start()
    try:
        c = _conn(server)
        c.request("GET", "/api/v1/listwatchresources")
        resp = c.getresponse()
        (ev,) = _read_events(resp, 1)  # node list
        di.store.create("pods", make_pod("p0", cpu="100m"))
        seen_bound = False
        end = time.monotonic() + 20
        while not seen_bound and time.monotonic() < end:
            for ev in _read_events(resp, 1, deadline=5.0):
                if (
                    ev["Kind"] == "pods"
                    and ev["EventType"] in ("ADDED", "MODIFIED")
                    and ev["Obj"]["spec"].get("nodeName") == "n0"
                ):
                    seen_bound = True
        assert seen_bound
        c.close()
    finally:
        di.scheduler_service.stop()


def test_metrics_and_ui(server):
    di = server.di
    di.store.create("nodes", make_node("n0"))
    di.store.create("pods", make_pod("p0"))
    di.scheduler_service.schedule_pending()
    status, m = _req(server, "GET", "/api/v1/metrics")
    assert status == 200
    assert m["counters"]["scheduling_passes"] >= 1
    assert m["counters"]["pods_scheduled"] >= 1
    assert m["timings"]["engine"]["count"] >= 1
    # Timers are histograms now: buckets + quantiles next to the legacy
    # total/count/mean keys.
    assert m["timings"]["engine"]["total_seconds"] > 0
    assert sum(c for _, c in m["timings"]["engine"]["buckets"]) == (
        m["timings"]["engine"]["count"]
    )
    assert m["timings"]["engine"]["p99_seconds"] >= m["timings"]["engine"]["p50_seconds"]
    # The built-in UI serves at / and references the watch endpoint.
    c = _conn(server)
    c.request("GET", "/")
    r = c.getresponse()
    body = r.read().decode()
    c.close()
    assert r.status == 200 and "listwatchresources" in body


def test_metrics_merges_faults_trace_and_replay_stats(server):
    """One GET shows the whole degradation-evidence surface (the former
    gap: fault counters and replay stats were bench-JSON-only)."""
    from ksim_tpu.faults import FAULTS, InjectedFault
    from ksim_tpu.obs import TRACE

    di = server.di
    di.store.create("nodes", make_node("n0"))
    di.store.create("pods", make_pod("p0"))
    prev_state = (TRACE._active, TRACE._ring_on, TRACE._user_disabled)
    TRACE.enable(ring=True)
    FAULTS.arm("service.schedule", "call:1")
    try:
        with pytest.raises(InjectedFault):
            di.scheduler_service.schedule_pending()
        di.scheduler_service.schedule_pending()  # a clean pass after
        status, m = _req(server, "GET", "/api/v1/metrics")
        assert status == 200
        # Fault-plane evidence, per site.
        assert m["faults"]["service.schedule"]["fired"] == 1
        assert m["faults"]["service.schedule"]["calls"] >= 2
        # Trace-plane evidence: the schedule span histogram + the
        # fault.fired event counter.
        assert m["trace"]["enabled"]
        assert m["trace"]["histograms"]["service.schedule"]["count"] >= 1
        assert m["trace"]["events"]["fault.fired"] >= 1
        # Replay stats appear once a driver exists in the process (other
        # tests in the suite may have created one); the KEY contract is
        # that the document is a single merged object.
        assert set(m) >= {"counters", "timings", "trace", "faults", "process"}
        if "replay" in m:
            # Live stats, a weakly-referenced driver already collected,
            # or a provider error — all are valid merged-doc shapes.
            assert any(
                k in m["replay"] for k in ("device_steps", "collected", "error")
            )
    finally:
        FAULTS.reset()
        TRACE._active, TRACE._ring_on, TRACE._user_disabled = prev_state


def test_metrics_identity_block_and_prometheus_exposition(server):
    """Solo scope carries the process-identity block unconditionally
    (the fleet aggregator keys on it), and GET /metrics renders the
    document as Prometheus text the in-repo stdlib parser accepts —
    for BOTH scopes, every family in the lint-enforced registry."""
    import os

    from ksim_tpu.obs import METRIC_NAMES, parse_prometheus

    status, m = _req(server, "GET", "/api/v1/metrics")
    assert status == 200
    ident = m["process"]
    assert set(ident) >= {"role", "worker_id", "pid", "started_at", "uptime_s"}
    assert ident["role"] == "solo" and ident["pid"] == os.getpid()
    assert ident["uptime_s"] >= 0
    for path in ("/metrics", "/metrics?scope=fleet"):
        status, text = _raw(server, "GET", path)
        assert status == 200, path
        families = parse_prometheus(text)
        assert set(families) <= set(METRIC_NAMES), path
        assert "ksim_up" in families, path
    # Fleet scope without a jobs dir still answers: the serving process
    # itself is the one (live, never-stale) worker.
    status, fm = _req(server, "GET", "/api/v1/metrics?scope=fleet")
    assert status == 200 and fm["scope"] == "fleet"
    (wid,) = fm["workers"]
    assert fm["workers"][wid]["stale"] is False


def test_trace_endpoint_serves_chrome_json(server):
    from ksim_tpu.obs import TRACE

    di = server.di
    di.store.create("nodes", make_node("n0"))
    di.store.create("pods", make_pod("p0"))
    prev_state = (TRACE._active, TRACE._ring_on, TRACE._user_disabled)
    TRACE.enable(ring=True)
    try:
        di.scheduler_service.schedule_pending()
        status, doc = _req(server, "GET", "/api/v1/trace")
        assert status == 200
        assert isinstance(doc["traceEvents"], list)
        names = {e["name"] for e in doc["traceEvents"]}
        assert "service.schedule" in names
        spans = [e for e in doc["traceEvents"] if e.get("ph") == "X"]
        assert all("ts" in e and "dur" in e for e in spans)
    finally:
        TRACE._active, TRACE._ring_on, TRACE._user_disabled = prev_state


def test_resource_crud_routes(server):
    """Per-resource CRUD under /api/v1/resources — the role the KWOK
    apiserver plays for the reference UI (web/api/v1/*.ts)."""
    node = make_node("crud-n1", cpu="2")
    status, created = _req(server, "POST", "/api/v1/resources/nodes", node)
    assert status == 201 and created["metadata"]["resourceVersion"]
    status, got = _req(server, "GET", "/api/v1/resources/nodes/crud-n1")
    assert status == 200 and got["metadata"]["name"] == "crud-n1"
    got["metadata"]["labels"] = {"zone": "a"}
    status, updated = _req(server, "PUT", "/api/v1/resources/nodes/crud-n1", got)
    assert status == 200 and updated["metadata"]["labels"] == {"zone": "a"}
    status, listing = _req(server, "GET", "/api/v1/resources/nodes")
    assert status == 200 and any(
        n["metadata"]["name"] == "crud-n1" for n in listing["items"]
    )
    # Namespaced kind: pods default to the "default" namespace.
    pod = make_pod("crud-p1", cpu="100m")
    status, _ = _req(server, "POST", "/api/v1/resources/pods", pod)
    assert status == 201
    status, got = _req(server, "GET", "/api/v1/resources/pods/default/crud-p1")
    assert status == 200
    status, _ = _req(server, "DELETE", "/api/v1/resources/pods/default/crud-p1")
    assert status == 200
    status, _ = _req(server, "GET", "/api/v1/resources/pods/default/crud-p1")
    assert status == 404
    status, _ = _req(server, "DELETE", "/api/v1/resources/nodes/crud-n1")
    assert status == 200
    # Unknown kind and double-create conflict.
    status, _ = _req(server, "GET", "/api/v1/resources/gadgets")
    assert status == 404
    status, _ = _req(server, "POST", "/api/v1/resources/nodes", make_node("c2"))
    assert status == 201
    status, _ = _req(server, "POST", "/api/v1/resources/nodes", make_node("c2"))
    assert status == 409
    _req(server, "DELETE", "/api/v1/resources/nodes/c2")


def test_ui_edit_workflow_reschedules_pod(server):
    """The UI's view/edit workflow (YamlEditor.vue analogue): GET a live
    unschedulable pod through the CRUD, shrink its requests, PUT it back
    — the watch-driven scheduler must retry it promptly (backoff cleared
    by the user's update, upstream Pod-update QueueingHints) and bind."""
    di = server.di
    di.store.create("nodes", make_node("edit-n1", cpu="2", memory="4Gi"))
    di.store.create(
        "pods", make_pod("edit-huge", cpu="32", memory="256Mi")
    )
    di.scheduler_service.start()
    try:
        deadline = time.time() + 60
        while time.time() < deadline:
            status, pod = _req(server, "GET", "/api/v1/resources/pods/default/edit-huge")
            if status == 200 and pod["metadata"].get("annotations"):
                break
            time.sleep(0.1)
        assert pod["spec"].get("nodeName") is None  # unschedulable as-is
        # Edit: make it fit (and tag it, proving arbitrary field edits).
        pod["spec"]["containers"][0]["resources"]["requests"]["cpu"] = "500m"
        pod["metadata"].setdefault("labels", {})["edited"] = "yes"
        status, _ = _req(server, "PUT", "/api/v1/resources/pods/default/edit-huge", pod)
        assert status == 200
        deadline = time.time() + 60
        bound = None
        while time.time() < deadline and not bound:
            _, pod = _req(server, "GET", "/api/v1/resources/pods/default/edit-huge")
            bound = pod["spec"].get("nodeName")
            time.sleep(0.1)
        assert bound == "edit-n1"
        assert pod["metadata"]["labels"]["edited"] == "yes"
        # Both attempts live in result-history — the data the UI's
        # attempt browser renders (storereflector.go:148-167).
        from ksim_tpu.engine.annotations import RESULT_HISTORY_KEY

        history = json.loads(pod["metadata"]["annotations"][RESULT_HISTORY_KEY])
        assert len(history) >= 2
        # The failed attempt has no selected-node; the final one does.
        sel = "kube-scheduler-simulator.sigs.k8s.io/selected-node"
        assert sel not in history[0]
        assert history[-1][sel] == "edit-n1"
    finally:
        di.scheduler_service.stop()


def test_ui_page_has_board_editor_and_history_panels(server):
    """The built-in page ships the three debuggability surfaces the
    reference UI has: pods-by-node board with an unscheduled bucket
    (web/store/pod.ts:12-16), live-resource editor (YamlEditor.vue), and
    the result-history attempt browser (SchedulingResults.vue)."""
    c = _conn(server)
    c.request("GET", "/")
    body = c.getresponse().read().decode()
    c.close()
    assert 'id="board"' in body and "unscheduled" in body
    assert 'id="editPanel"' in body and "doSave" in body
    assert "data-attempt" in body and "result-history" in body


def test_external_scheduler_over_http(server):
    """The reference's integrate-your-scheduler workflow: an EXTERNAL
    scheduler watches for its pods and binds them through the resource
    API, while the built-in scheduler ignores pods addressed elsewhere
    (upstream schedulers only touch pods naming one of their profiles)."""
    di = server.di
    di.store.create("nodes", make_node("ext-n1"))
    foreign = make_pod("ext-p1")
    foreign["spec"]["schedulerName"] = "my-external-scheduler"
    di.store.create("pods", foreign)
    di.scheduler_service.start()
    try:
        # The built-in scheduler must leave it alone.
        time.sleep(1.5)
        _, pod = _req(server, "GET", "/api/v1/resources/pods/default/ext-p1")
        assert "nodeName" not in pod["spec"]
        assert di.scheduler_service.pending_count() == 0  # not its pod

        # External scheduler: read, decide, bind via PUT.
        pod["spec"]["nodeName"] = "ext-n1"
        pod["status"] = {"phase": "Running"}
        status, bound = _req(
            server, "PUT", "/api/v1/resources/pods/default/ext-p1", pod
        )
        assert status == 200 and bound["spec"]["nodeName"] == "ext-n1"

        # The binding is visible on the watch stream and in exports.
        _, export = _req(server, "GET", "/api/v1/export")
        got = {p["metadata"]["name"]: p["spec"].get("nodeName") for p in export["pods"]}
        assert got["ext-p1"] == "ext-n1"
    finally:
        di.scheduler_service.stop(timeout=None)


def test_listwatch_410_on_foreign_resume_point(server):
    """A reconnect carrying a resourceVersion this server never issued
    (the signature of a server restart) answers 410 Gone so the client
    drops its cache and relists — the etcd-compaction contract."""
    status, body = _req(
        server, "GET", "/api/v1/listwatchresources?podsLastResourceVersion=999999"
    )
    assert status == 410
    assert "resourceVersion" in body["message"]


def test_yaml_resource_roundtrip(server):
    """YAML is a first-class wire format for the CRUD + config routes
    (the reference UI edits resources and config as YAML in Monaco,
    web/components/ResourceBar/YamlEditor.vue): GET ?format=yaml serves
    YAML, and YAML request bodies parse by Content-Type."""
    import yaml

    node_yaml = yaml.safe_dump(make_node("yaml-node"))
    c = _conn(server)
    c.request(
        "POST", "/api/v1/resources/nodes", node_yaml,
        {"Content-Type": "application/yaml"},
    )
    r = c.getresponse()
    assert r.status == 201
    r.read()
    c.close()

    status, raw = _raw(server, "GET", "/api/v1/resources/nodes/yaml-node?format=yaml")
    assert status == 200
    obj = yaml.safe_load(raw)
    assert obj["metadata"]["name"] == "yaml-node"

    # Edit workflow over YAML: mutate and PUT back as YAML.
    obj["spec"]["unschedulable"] = True
    c = _conn(server)
    c.request(
        "PUT", "/api/v1/resources/nodes/yaml-node", yaml.safe_dump(obj),
        {"Content-Type": "application/yaml"},
    )
    r = c.getresponse()
    assert r.status == 200
    r.read()
    c.close()
    _status, body = _req(server, "GET", "/api/v1/resources/nodes/yaml-node")
    assert body["spec"]["unschedulable"] is True

    # Scheduler config serves + applies as YAML too.
    status, raw = _raw(server, "GET", "/api/v1/schedulerconfiguration?format=yaml")
    assert status == 200
    cfg = yaml.safe_load(raw)
    assert cfg["kind"] == "KubeSchedulerConfiguration"
    cfg["profiles"] = [
        {"plugins": {"multiPoint": {"disabled": [{"name": "ImageLocality"}]}}}
    ]
    c = _conn(server)
    c.request(
        "POST", "/api/v1/schedulerconfiguration", yaml.safe_dump(cfg),
        {"Content-Type": "application/yaml"},
    )
    r = c.getresponse()
    assert r.status == 202, r.read()
    r.read()
    c.close()
    _status, got = _req(server, "GET", "/api/v1/schedulerconfiguration")
    assert got["profiles"][0]["plugins"]["multiPoint"]["disabled"] == [
        {"name": "ImageLocality"}
    ]


def test_traces_endpoint_lists_entries_with_metadata(server, tmp_path, monkeypatch):
    """GET /api/v1/traces pins the registry-entry shape — ``name`` /
    ``size_bytes`` / ``gzip`` / ``format`` — across a plain Borg JSONL,
    an Alibaba CSV, and a gzipped trace (detected format is advisory;
    job specs still name theirs explicitly)."""
    import gzip

    (tmp_path / "mini.jsonl").write_text('{"time": 0, "type": "SUBMIT"}\n')
    (tmp_path / "batch.csv").write_text("t1,task,j1,1,0,100,Terminated,0.5,1.0\n")
    with gzip.open(tmp_path / "mini2.jsonl.gz", "wt") as f:
        f.write('{"time": 1}\n')
    monkeypatch.setenv("KSIM_TRACES_DIR", str(tmp_path))
    status, body = _req(server, "GET", "/api/v1/traces")
    assert status == 200
    items = body["items"]
    assert [e["name"] for e in items] == [
        "batch.csv",
        "mini.jsonl",
        "mini2.jsonl.gz",
    ]
    for entry in items:
        assert set(entry) == {"name", "size_bytes", "gzip", "format"}
        assert entry["size_bytes"] > 0
    by_name = {e["name"]: e for e in items}
    assert by_name["mini.jsonl"]["format"] == "borg"
    assert by_name["mini.jsonl"]["gzip"] is False
    assert by_name["batch.csv"]["format"] == "alibaba"
    assert by_name["batch.csv"]["gzip"] is False
    assert by_name["mini2.jsonl.gz"]["format"] == "borg"
    assert by_name["mini2.jsonl.gz"]["gzip"] is True
