"""Sequential-commit scheduling loop vs an oracle greedy simulation."""

import numpy as np
import pytest

from ksim_tpu.engine import Engine, ScoredPlugin
from ksim_tpu.plugins import oracle
from ksim_tpu.plugins.noderesources import (
    NodeResourcesBalancedAllocation,
    NodeResourcesFit,
)
from ksim_tpu.state.featurizer import Featurizer
from tests.helpers import make_node, make_pod, random_cluster


def greedy_oracle(nodes, pods, queue):
    """Pure-Python replication of the full default-profile cycle: all five
    filters, raw scores, per-plugin normalization over feasible nodes,
    upstream weights, first-max selection, commit."""
    from tests.helpers import pods_by_node as group_pods

    infos = oracle.build_node_infos(nodes, pods)
    pods_by_node = group_pods(pods)
    out = []
    for pod in queue:
        spread_reasons = oracle.topology_spread_filter_all(pod, infos, pods_by_node)
        ipa_reasons = oracle.inter_pod_affinity_filter_all(pod, infos, pods_by_node)
        feasible_mask = [
            not (
                oracle.node_unschedulable_filter(pod, info)
                or oracle.fit_filter(pod, info)
                or oracle.taint_toleration_filter(pod, info)
                or oracle.node_affinity_filter(pod, info)
                or spread_reasons[ni]
                or ipa_reasons[ni]
            )
            for ni, info in enumerate(infos)
        ]
        feasible = [ni for ni, m in enumerate(feasible_mask) if m]
        _, spread_norm = oracle.topology_spread_score_all(
            pod, infos, pods_by_node, feasible_mask
        )
        _, ipa_norm = oracle.inter_pod_affinity_score_all(
            pod, infos, pods_by_node, feasible_mask
        )
        best, best_score = -1, None
        fit = [oracle.least_allocated_score(pod, infos[ni]) for ni in feasible]
        bal = [oracle.balanced_allocation_score(pod, infos[ni]) for ni in feasible]
        tnt = oracle.default_normalize_score(
            [oracle.taint_toleration_score(pod, infos[ni]) for ni in feasible],
            reverse=True,
        )
        aff = oracle.default_normalize_score(
            [oracle.node_affinity_score(pod, infos[ni]) for ni in feasible],
            reverse=False,
        )
        for k, ni in enumerate(feasible):
            total = (
                fit[k] * 1 + bal[k] * 1 + tnt[k] * 3 + aff[k] * 2
                + spread_norm[ni] * 2 + ipa_norm[ni] * 2
            )
            if best_score is None or total > best_score:
                best, best_score = ni, total
        if best >= 0:
            oracle.commit_pod(infos[best], pod)
            pods_by_node.setdefault(infos[best]["name"], []).append(pod)
        out.append(best)
    return out


from ksim_tpu.engine.profiles import default_plugins


def run_engine(nodes, pods, queue):
    feats = Featurizer().featurize(nodes, pods, queue_pods=queue)
    eng = Engine(feats, default_plugins(feats), record="full")
    res, state = eng.schedule()
    return feats, res, state


def test_cordoned_node_filtered_unless_tolerated():
    nodes = [make_node("up", cpu="4", memory="8Gi"),
             make_node("cordoned", cpu="32", memory="64Gi", unschedulable=True)]
    tol = [{"key": "node.kubernetes.io/unschedulable", "operator": "Exists", "effect": "NoSchedule"}]
    queue = [make_pod("plain", cpu="1", memory="1Gi"),
             make_pod("tolerant", cpu="1", memory="1Gi", tolerations=tol)]
    _, res, _ = run_engine(nodes, [], queue)
    # Plain pod can only land on "up"; tolerant pod prefers the big
    # cordoned node (more free resources -> higher least-allocated score).
    assert [int(x) for x in res.selected[:2]] == [0, 1]


def test_schedule_matches_oracle_greedy():
    for seed in (0, 7):
        nodes, pods = random_cluster(seed, n_nodes=9, n_pods=40, bound_fraction=0.2)
        queue = [p for p in pods if not p["spec"].get("nodeName")]
        feats, res, state = run_engine(nodes, pods, queue)
        want = greedy_oracle(nodes, pods, queue)
        got = [int(x) for x in res.selected[: len(queue)]]
        assert got == want


def test_capacity_fills_up():
    # One node fits exactly two of these pods; third must go unschedulable.
    nodes = [make_node("n1", cpu="1", memory="1Gi", pods=110)]
    queue = [make_pod(f"p{i}", cpu="500m", memory="256Mi") for i in range(3)]
    _, res, state = run_engine(nodes, [], queue)
    assert [int(x) for x in res.selected[:3]] == [0, 0, -1]
    assert bool(res.feasible[0]) and not bool(res.feasible[2])
    # Committed state reflects both placements.
    assert int(state.pod_count[0]) == 2


def test_spread_prefers_emptier_node():
    nodes = [make_node("a", cpu="2", memory="4Gi"), make_node("b", cpu="2", memory="4Gi")]
    queue = [make_pod(f"p{i}", cpu="500m", memory="1Gi") for i in range(4)]
    _, res, _ = run_engine(nodes, [], queue)
    sel = [int(x) for x in res.selected[:4]]
    # Least-allocated scoring alternates nodes.
    assert sel == [0, 1, 0, 1]


def test_padding_pods_not_scheduled():
    nodes = [make_node("n1")]
    queue = [make_pod("p0")]
    feats, res, _ = run_engine(nodes, [], queue)
    assert [int(x) for x in res.selected[1:]] == [-1] * (len(res.selected) - 1)


def test_chunked_schedule_and_batch_match_unchunked():
    """Chunk boundaries must be semantically invisible: the carries thread
    through the host loop unchanged (engine/core.py schedule chunking)."""
    nodes, pods = random_cluster(3, n_nodes=16, n_pods=60, bound_fraction=0.2)
    queue = [p for p in pods if not p["spec"].get("nodeName")]
    feats = Featurizer().featurize(nodes, pods, queue_pods=queue)
    eng = Engine(feats, default_plugins(feats), record="full")
    whole, state_whole = eng.schedule(chunk=int(feats.pods.valid.shape[0]))
    parts, state_parts = eng.schedule(chunk=17)
    for field in ("reason_bits", "scores", "final_scores", "total", "feasible", "selected"):
        a, b = getattr(whole, field), getattr(parts, field)
        assert np.array_equal(a, b), field
    assert np.array_equal(state_whole.requested, state_parts.requested)
    assert np.array_equal(state_whole.pod_count, state_parts.pod_count)

    bwhole = eng.evaluate_batch(chunk=int(feats.pods.valid.shape[0]))
    bparts = eng.evaluate_batch(chunk=13)
    for field in ("reason_bits", "scores", "final_scores", "total", "feasible", "selected"):
        assert np.array_equal(getattr(bwhole, field), getattr(bparts, field)), field


def test_engine_jit_cache_reused_across_instances():
    """Re-featurizing a same-shaped snapshot must NOT recompile: Engine
    hashes by (record, plugin static signatures) and shapes key the rest
    (engine/core.py _sig) — the watch-driven service builds a fresh Engine
    per pass and relies on this."""
    from ksim_tpu.engine.core import _Program

    nodes, pods = random_cluster(11, n_nodes=10, n_pods=30, bound_fraction=0.2)
    queue = [p for p in pods if not p["spec"].get("nodeName")]
    feats1 = Featurizer().featurize(nodes, pods, queue_pods=queue)
    eng1 = Engine(feats1, default_plugins(feats1), record="full")
    res1, _ = eng1.schedule()
    eng1.evaluate_batch()
    size_sched = _Program._schedule_fn._cache_size()
    size_batch = _Program._batch_fn._cache_size()

    # Mutate one pod's requests (same shapes/vocabs), re-featurize: the
    # compiled programs must be reused AND produce the new values.
    import copy

    queue2 = copy.deepcopy(queue)
    queue2[0]["spec"]["containers"][0]["resources"] = {"requests": {"cpu": "3"}}
    feats2 = Featurizer().featurize(nodes, pods, queue_pods=queue2)
    eng2 = Engine(feats2, default_plugins(feats2), record="full")
    assert eng2._prog == eng1._prog and hash(eng2._prog) == hash(eng1._prog)
    res2, _ = eng2.schedule()
    eng2.evaluate_batch()
    assert _Program._schedule_fn._cache_size() == size_sched
    assert _Program._batch_fn._cache_size() == size_batch
    assert not np.array_equal(res1.total, res2.total)  # new values flowed


def test_partitioned_batch_matches_unpartitioned():
    """partition=True classes pods host-side and runs light pods through
    a program that statically skips the heavy constraint plugins
    (engine/core.py evaluate_batch_chunks) — results must be
    bit-identical to the contiguous evaluation in original pod order,
    including the recorded result tensors.  random_cluster mixes
    constraint-carrying and constraint-less pods, so both classes and a
    ragged class tail (odd chunk) are exercised."""
    nodes, pods = random_cluster(5, n_nodes=16, n_pods=60, bound_fraction=0.2)
    queue = [p for p in pods if not p["spec"].get("nodeName")]
    feats = Featurizer().featurize(nodes, pods, queue_pods=queue)
    eng = Engine(feats, default_plugins(feats), record="full")
    light = eng._light_mask(eng._partition_assume())
    assert light is not None and light.any() and not light.all(), (
        "fixture must exercise both classes"
    )
    plain = eng.evaluate_batch(chunk=13)
    parted = eng.evaluate_batch(chunk=13, partition=True)
    for field in ("reason_bits", "scores", "final_scores", "total", "feasible", "selected"):
        assert np.array_equal(getattr(plain, field), getattr(parted, field)), field


def test_partitioned_batch_trivial_classes_fall_back():
    """All-light or all-heavy classifications take the contiguous path
    (no gather, no second program)."""
    nodes = [make_node(f"n{i}") for i in range(3)]
    pods = [make_pod(f"p{i}") for i in range(5)]
    feats = Featurizer().featurize(nodes, pods, queue_pods=pods)
    eng = Engine(feats, default_plugins(feats), record="full")
    keys = [k for k, _ in eng.evaluate_batch_chunks(chunk=4, partition=True)]
    assert all(isinstance(k, int) for k in keys), keys
    plain = eng.evaluate_batch(chunk=4)
    parted = eng.evaluate_batch(chunk=4, partition=True)
    assert np.array_equal(plain.selected, parted.selected)


def test_fused_batch_matches_chunked():
    """evaluate_batch_fused must equal the chunked evaluation in both
    bounded record modes, for block sizes that do and don't divide the
    padded pod count (the entry shrinks block until it divides)."""
    nodes, pods = random_cluster(7, n_nodes=12, n_pods=50, bound_fraction=0.2)
    queue = [p for p in pods if not p["spec"].get("nodeName")]
    feats = Featurizer().featurize(nodes, pods, queue_pods=queue)
    for record in ("selection", "final"):
        eng = Engine(feats, default_plugins(feats), record=record)
        plain = eng.evaluate_batch(chunk=13)
        for block in (8, 256):
            fused = eng.evaluate_batch_fused(block=block)
            for field in ("final_scores", "total", "feasible", "selected"):
                a, b = getattr(plain, field), getattr(fused, field)
                if a is None and b is None:
                    continue
                assert np.array_equal(a, b), (record, block, field)


def test_fused_batch_rejects_full_record():
    nodes, pods = random_cluster(7, n_nodes=4, n_pods=6, bound_fraction=0.0)
    feats = Featurizer().featurize(nodes, pods, queue_pods=pods)
    eng = Engine(feats, default_plugins(feats), record="full")
    with pytest.raises(ValueError):
        eng.evaluate_batch_fused()
