"""PodTopologySpread: kernel-vs-oracle parity and behavioral tests."""

import numpy as np

from ksim_tpu.engine import Engine
from ksim_tpu.engine.profiles import default_plugins
from ksim_tpu.plugins import oracle
from ksim_tpu.plugins.podtopologyspread import PodTopologySpread
from ksim_tpu.state.featurizer import Featurizer
from tests.helpers import make_node, make_pod, pods_by_node, random_cluster


def test_batch_parity_spread_random():
    for seed in (11, 12):
        nodes, pods = random_cluster(seed, n_nodes=11, n_pods=31, bound_fraction=0.4)
        queue = [p for p in pods if not p["spec"].get("nodeName")]
        feats = Featurizer().featurize(nodes, pods, queue_pods=queue)
        eng = Engine(feats, default_plugins(feats), record="full")
        res = eng.evaluate_batch()
        infos = oracle.build_node_infos(nodes, pods)
        by_node = pods_by_node(pods)
        sp = PodTopologySpread(feats.aux["spread"])
        sp_f = res.filter_plugin_names.index("PodTopologySpread")
        sp_s = res.plugin_names.index("PodTopologySpread")
        for pi, pod in enumerate(queue):
            want_rows = oracle.topology_spread_filter_all(pod, infos, by_node)
            for ni in range(len(infos)):
                got = sp.decode_reasons(int(res.reason_bits[pi, sp_f, ni]))
                assert got == want_rows[ni], (seed, pod["metadata"]["name"], ni)
            # Raw score parity over the engine's feasibility mask.
            feasible_mask = [
                bool(
                    np.all(res.reason_bits[pi, :, ni] == 0)
                ) for ni in range(len(infos))
            ]
            raw, _ = oracle.topology_spread_score_all(pod, infos, by_node, feasible_mask)
            for ni in range(len(infos)):
                assert int(res.scores[pi, sp_s, ni]) == raw[ni], (seed, pi, ni)


def test_do_not_schedule_skew_enforced():
    # Two zones; zone-a already has 2 matching pods, zone-b has 0.
    # maxSkew=1 forbids adding a third to zone-a (skew 3-0 > 1).
    nodes = [
        make_node("a1", labels={"topology.kubernetes.io/zone": "za"}),
        make_node("b1", labels={"topology.kubernetes.io/zone": "zb"}),
    ]
    bound = [
        make_pod("w1", labels={"app": "web"}, node_name="a1"),
        make_pod("w2", labels={"app": "web"}, node_name="a1"),
    ]
    con = [{
        "maxSkew": 1, "topologyKey": "topology.kubernetes.io/zone",
        "whenUnsatisfiable": "DoNotSchedule",
        "labelSelector": {"matchLabels": {"app": "web"}},
    }]
    q = make_pod("w3", labels={"app": "web"}, topology_spread_constraints=con)
    feats = Featurizer().featurize(nodes, bound, queue_pods=[q])
    eng = Engine(feats, default_plugins(feats), record="full")
    res = eng.evaluate_batch()
    assert feats.nodes.names[int(res.selected[0])] == "b1"
    sp_f = res.filter_plugin_names.index("PodTopologySpread")
    assert int(res.reason_bits[0, sp_f, 0]) != 0  # zone-a blocked
    assert int(res.reason_bits[0, sp_f, 1]) == 0


def test_missing_topology_key_fails_with_label_reason():
    nodes = [make_node("plain", labels={})]
    con = [{
        "maxSkew": 1, "topologyKey": "topology.kubernetes.io/zone",
        "whenUnsatisfiable": "DoNotSchedule",
        "labelSelector": {"matchLabels": {"app": "web"}},
    }]
    q = make_pod("w", labels={"app": "web"}, topology_spread_constraints=con)
    feats = Featurizer().featurize(nodes, [], queue_pods=[q])
    eng = Engine(feats, default_plugins(feats), record="full")
    res = eng.evaluate_batch()
    sp = PodTopologySpread(feats.aux["spread"])
    sp_f = res.filter_plugin_names.index("PodTopologySpread")
    assert sp.decode_reasons(int(res.reason_bits[0, sp_f, 0])) == [
        "node(s) didn't match pod topology spread constraints (missing required label)"
    ]


def test_schedule_anyway_spreads_across_zones():
    # 4 schedulable pods with a ScheduleAnyway zone constraint and equal
    # nodes: the scan should spread across zones, never stacking 3+ in one.
    nodes = [
        make_node(f"n{i}", labels={"topology.kubernetes.io/zone": f"z{i % 2}"})
        for i in range(4)
    ]
    con = [{
        "maxSkew": 1, "topologyKey": "topology.kubernetes.io/zone",
        "whenUnsatisfiable": "ScheduleAnyway",
        "labelSelector": {"matchLabels": {"app": "web"}},
    }]
    queue = [
        make_pod(f"w{i}", labels={"app": "web"}, topology_spread_constraints=con)
        for i in range(4)
    ]
    feats = Featurizer().featurize(nodes, [], queue_pods=queue)
    eng = Engine(feats, default_plugins(feats), record="selection")
    res, _ = eng.schedule()
    zones = [int(s) % 2 for s in res.selected[:4]]
    assert sorted(zones) == [0, 0, 1, 1]
