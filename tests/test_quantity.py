"""Quantity parsing parity with k8s resource.Quantity semantics."""

import pytest

from ksim_tpu.state.quantity import parse_quantity


@pytest.mark.parametrize(
    "s,milli,value",
    [
        ("100m", 100, 1),  # Value() rounds up
        ("1", 1000, 1),
        ("1.5", 1500, 2),
        ("2", 2000, 2),
        ("0", 0, 0),
        ("128Mi", 128 * 1024**2 * 1000, 128 * 1024**2),
        ("1Gi", 1024**3 * 1000, 1024**3),
        ("1.5Gi", 1536 * 1024**2 * 1000, 1536 * 1024**2),
        ("2k", 2000_000, 2000),
        ("1e3", 1_000_000, 1000),
        ("2E2", 200_000, 200),
        ("500u", 1, 1),  # micro rounds up at milli scale
        ("110", 110_000, 110),
    ],
)
def test_parse(s, milli, value):
    q = parse_quantity(s)
    assert q.milli_value == milli
    assert q.value == value


def test_negative_rounds_toward_larger_magnitude():
    q = parse_quantity("-1.5")
    assert q.value == -2  # away from zero, like Go


def test_add():
    assert (parse_quantity("100m") + parse_quantity("900m")).value == 1


def test_invalid():
    for bad in ["", "abc", "1.2.3", "12x", "Gi"]:
        with pytest.raises(ValueError):
            parse_quantity(bad)


def test_int_passthrough():
    assert parse_quantity(5).milli_value == 5000
