"""Featurizer: unit scaling exactness, accumulation, bucketing."""

import numpy as np

from ksim_tpu.state.featurizer import Featurizer, bucket_size
from tests.helpers import make_node, make_pod


def test_bucket_size():
    assert bucket_size(1) == 8
    assert bucket_size(8) == 8
    assert bucket_size(9) == 16
    assert bucket_size(1000) == 1024


def test_resource_axis_and_units():
    nodes = [make_node("n1", cpu="4", memory="16Gi")]
    pods = [make_pod("p1", cpu="100m", memory="128Mi")]
    f = Featurizer().featurize(nodes, pods)
    assert f.resources[:3] == ("cpu", "memory", "ephemeral-storage")
    assert f.exact
    ci, mi = f.resource_index("cpu"), f.resource_index("memory")
    # Ratios are preserved exactly: alloc/request == raw ratio.
    assert f.nodes.allocatable[0, ci] / f.pods.requests[0, ci] == 4000 / 100
    assert f.nodes.allocatable[0, mi] / f.pods.requests[0, mi] == (16 * 1024) / 128


def test_bound_pods_accumulate():
    nodes = [make_node("n1", cpu="4", memory="16Gi")]
    pods = [
        make_pod("p1", cpu="500m", memory="1Gi", node_name="n1"),
        make_pod("p2", cpu="250m", memory="1Gi", node_name="n1"),
        make_pod("p3", cpu="250m", memory="1Gi", node_name="n1", phase="Succeeded"),
        make_pod("q1", cpu="100m", memory="128Mi"),
    ]
    f = Featurizer().featurize(nodes, pods)
    ci = f.resource_index("cpu")
    unit = f.units["cpu"]
    assert f.nodes.requested[0, ci] * unit == 750  # terminal pod excluded
    assert f.nodes.pod_count[0] == 2
    assert f.pods.count == 1  # only the unbound pod is in the queue


def test_nonzero_requests_default():
    nodes = [make_node("n1")]
    pods = [make_pod("p1", cpu=None, memory=None)]
    f = Featurizer().featurize(nodes, pods)
    ci, mi = f.resource_index("cpu"), f.resource_index("memory")
    assert f.pods.requests[0, ci] == 0
    assert f.pods.nonzero_requests[0, ci] * f.units["cpu"] == 100  # 100m default
    assert f.pods.nonzero_requests[0, mi] * f.units["memory"] == 200 * 1024 * 1024


def test_extended_resources():
    nodes = [make_node("n1", extra_alloc={"example.com/gpu": "4"})]
    pods = [make_pod("p1", extra_requests={"example.com/gpu": "2"})]
    f = Featurizer().featurize(nodes, pods)
    gi = f.resource_index("example.com/gpu")
    assert f.nodes.allocatable[0, gi] * f.units["example.com/gpu"] == 4
    assert f.pods.requests[0, gi] * f.units["example.com/gpu"] == 2


def test_padding_masks():
    nodes = [make_node(f"n{i}") for i in range(3)]
    pods = [make_pod(f"p{i}") for i in range(5)]
    f = Featurizer().featurize(nodes, pods)
    assert f.nodes.padded == 8 and f.nodes.count == 3
    assert np.sum(f.nodes.valid) == 3
    assert np.sum(f.pods.valid) == 5


def test_bucket_size_three_quarter_step():
    """From the 8192 pow2 up the bucket ladder gains a 3/4 step (6144,
    12288, …): caps padding waste at 1/3 where the big-shape scans pay
    for it, every step divisible by 2048 for mesh sharding, and NO new
    recompile boundaries at churn-scale shapes (<= 4096)."""
    assert bucket_size(4097) == 6144
    assert bucket_size(5000) == 6144
    assert bucket_size(6144) == 6144
    assert bucket_size(6145) == 8192
    assert bucket_size(10000) == 12288
    assert bucket_size(12289) == 16384
    # Below the threshold the ladder is unchanged.
    assert bucket_size(2049) == 4096
    assert bucket_size(2048) == 2048
    assert bucket_size(4096) == 4096
    assert bucket_size(1000) == 1024


def test_featurize_with_bound_pods_param_matches_split():
    """featurize(bound_pods=...) — the indexed-store fast path — must
    produce the same tensors as the O(all pods) split it replaces,
    including the phase filter it still applies."""
    import numpy as np

    from tests.helpers import make_node, make_pod

    nodes = [make_node(f"n{i}") for i in range(4)]
    bound = [make_pod(f"b{i}", node_name=f"n{i % 4}") for i in range(6)]
    done = [make_pod("done", node_name="n0", phase="Succeeded")]
    queue = [make_pod(f"q{i}") for i in range(3)]
    pods = bound + done + queue

    f1 = Featurizer().featurize(nodes, pods, queue_pods=queue)
    f2 = Featurizer().featurize(
        nodes, (), queue_pods=queue, bound_pods=bound + done
    )
    np.testing.assert_array_equal(f1.nodes.requested, f2.nodes.requested)
    np.testing.assert_array_equal(f1.nodes.pod_count, f2.nodes.pod_count)
    np.testing.assert_array_equal(f1.pods.requests, f2.pods.requests)
