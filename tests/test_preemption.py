"""DefaultPreemption (PostFilter) tests — victim selection semantics and
the service-level eviction + annotation flow (reference records at
simulator/scheduler/plugin/wrappedplugin.go:550-577)."""

from __future__ import annotations

import json

from ksim_tpu.engine.annotations import POST_FILTER_RESULT_KEY, SELECTED_NODE_KEY
from ksim_tpu.scheduler.preemption import (
    find_preemption,
    render_postfilter_result,
)
from ksim_tpu.scheduler.service import SchedulerService
from ksim_tpu.state.cluster import ClusterStore
from tests.helpers import make_node, make_pod


def _bound(name, node, cpu, prio, ts="2024-01-01T00:00:00Z"):
    p = make_pod(name, cpu=cpu, memory=None, node_name=node, priority=prio)
    p["metadata"]["creationTimestamp"] = ts
    return p


def test_find_preemption_minimal_victims():
    # Node full: 4 x 1cpu victims (prio 1,2,3,4); preemptor needs 2cpu.
    nodes = [make_node("n0", cpu="4", memory="8Gi")]
    pods = [_bound(f"v{i}", "n0", "1", i + 1) for i in range(4)]
    preemptor = make_pod("big", cpu="2", memory=None, priority=10)
    d = find_preemption(preemptor, nodes, pods)
    assert d.nominated_node == "n0"
    # Reprieve order keeps the most important victims: prio 4 and 3 are
    # re-added (2cpu free suffices), prio 2 and 1 are evicted.
    assert sorted(v["metadata"]["name"] for v in d.victims) == ["v0", "v1"]


def test_find_preemption_respects_priority_and_policy():
    nodes = [make_node("n0", cpu="2", memory="8Gi")]
    pods = [_bound("equal", "n0", "2", 10)]
    # Same priority -> no potential victims.
    preemptor = make_pod("p", cpu="1", memory=None, priority=10)
    assert find_preemption(preemptor, nodes, pods).nominated_node is None
    # preemptionPolicy Never opts out entirely.
    lower = [_bound("low", "n0", "2", 1)]
    never = make_pod("p2", cpu="1", memory=None, priority=10)
    never["spec"]["preemptionPolicy"] = "Never"
    assert find_preemption(never, nodes, lower).nominated_node is None
    # Default policy preempts the lower-priority pod.
    ok = make_pod("p3", cpu="1", memory=None, priority=10)
    d = find_preemption(ok, nodes, lower)
    assert d.nominated_node == "n0"
    assert [v["metadata"]["name"] for v in d.victims] == ["low"]


def test_pick_node_prefers_lower_priority_victims():
    # Two candidate nodes; n1's victim has lower priority -> chosen.
    nodes = [make_node("n0", cpu="1", memory="8Gi"), make_node("n1", cpu="1", memory="8Gi")]
    pods = [_bound("hi", "n0", "1", 5), _bound("lo", "n1", "1", 2)]
    preemptor = make_pod("p", cpu="1", memory=None, priority=10)
    d = find_preemption(preemptor, nodes, pods)
    assert d.nominated_node == "n1"
    assert [v["metadata"]["name"] for v in d.victims] == ["lo"]


def test_render_postfilter_shape():
    out = render_postfilter_result(["a", "b"], "b")
    assert out == {"a": {}, "b": {"DefaultPreemption": "preemption victim"}}
    assert render_postfilter_result(["a"], None) == {"a": {}}


def test_service_preempts_and_reschedules():
    store = ClusterStore()
    store.create("nodes", make_node("n0", cpu="2", memory="8Gi"))
    for i in range(2):
        store.create("pods", _bound(f"low{i}", "n0", "1", 1))
    svc = SchedulerService(store)
    # High-priority pod cannot fit -> preemption evicts a victim.
    store.create("pods", make_pod("crit", cpu="1", memory=None, priority=100))
    placements = svc.schedule_pending()
    assert placements == {"default/crit": None}
    crit = store.get("pods", "crit")
    post = json.loads(crit["metadata"]["annotations"][POST_FILTER_RESULT_KEY])
    assert post == {"n0": {"DefaultPreemption": "preemption victim"}}
    assert crit["status"]["nominatedNodeName"] == "n0"
    # Exactly one victim evicted (minimal set).
    remaining = [p["metadata"]["name"] for p in store.list("pods")]
    assert len(remaining) == 2 and "crit" in remaining
    # Next pass binds the preemptor onto the freed capacity.
    placements = svc.schedule_pending()
    assert placements == {"default/crit": "n0"}
    crit = store.get("pods", "crit")
    assert crit["spec"]["nodeName"] == "n0"
    assert crit["metadata"]["annotations"][SELECTED_NODE_KEY] == "n0"


def test_service_no_preemption_when_unresolvable():
    # Unschedulable node: failure is UnschedulableAndUnresolvable -> no
    # candidates, postfilter records the failed node with no nomination.
    store = ClusterStore()
    store.create("nodes", make_node("n0", cpu="2", memory="8Gi", unschedulable=True))
    store.create("pods", _bound("low", "n0", "1", 1, ts="2024-01-01T00:00:01Z"))
    svc = SchedulerService(store)
    store.create("pods", make_pod("crit", cpu="1", memory=None, priority=100))
    placements = svc.schedule_pending()
    assert placements == {"default/crit": None}
    crit = store.get("pods", "crit")
    post = json.loads(crit["metadata"]["annotations"][POST_FILTER_RESULT_KEY])
    assert post == {"n0": {}}
    assert "nominatedNodeName" not in crit.get("status", {})
    assert len(store.list("pods")) == 2  # nothing evicted


def test_pick_node_latest_high_priority_victim_start():
    # Tie on priority/sum/count; upstream compares the earliest start of
    # the HIGHEST-priority victims and picks the latest such node.
    nodes = [make_node("a", cpu="2", memory="8Gi"), make_node("b", cpu="2", memory="8Gi")]
    pods = [
        _bound("a-hi", "a", "1", 5, ts="2024-01-05T00:00:00Z"),
        _bound("a-lo", "a", "1", 1, ts="2024-01-01T00:00:00Z"),
        _bound("b-hi", "b", "1", 5, ts="2024-01-03T00:00:00Z"),
        _bound("b-lo", "b", "1", 1, ts="2024-01-02T00:00:00Z"),
    ]
    preemptor = make_pod("p", cpu="2", memory=None, priority=10)
    d = find_preemption(preemptor, nodes, pods)
    assert d.nominated_node == "a"  # 01-05 > 01-03 among prio-5 victims


def test_service_preemption_without_full_record():
    # record="final" has no reason bits; preemption still runs with an
    # unrestricted candidate mask (no annotations in this mode).
    store = ClusterStore()
    store.create("nodes", make_node("n0", cpu="2", memory="8Gi"))
    store.create("pods", _bound("low", "n0", "2", 1))
    svc = SchedulerService(store, record="final")
    store.create("pods", make_pod("crit", cpu="1", memory=None, priority=100))
    assert svc.schedule_pending() == {"default/crit": None}
    crit = store.get("pods", "crit")
    assert crit["status"]["nominatedNodeName"] == "n0"
    assert [p["metadata"]["name"] for p in store.list("pods")] == ["crit"]
    assert svc.schedule_pending() == {"default/crit": "n0"}
    # Binding clears the nomination, like the apiserver does.
    assert "nominatedNodeName" not in store.get("pods", "crit")["status"]


def test_preemption_rechecks_port_conflicts():
    # Victim search must re-check NodePorts: the port is held by a
    # LOW-priority pod, so evicting it resolves the conflict.
    node = make_node("n0", cpu="8", memory="16Gi")
    low = _bound("low", "n0", "1", 1)
    low["spec"]["containers"][0]["ports"] = [{"hostPort": 8080}]
    preemptor = make_pod("p", cpu="1", memory=None, priority=10)
    preemptor["spec"]["containers"][0]["ports"] = [{"hostPort": 8080}]
    d = find_preemption(preemptor, [node], [low])
    assert d.nominated_node == "n0"
    assert [v["metadata"]["name"] for v in d.victims] == ["low"]
    # Held by a HIGHER-priority pod instead: no preemption can help.
    hi = _bound("hi", "n0", "1", 100)
    hi["spec"]["containers"][0]["ports"] = [{"hostPort": 8080}]
    d2 = find_preemption(preemptor, [node], [hi, _bound("low2", "n0", "1", 1)])
    assert d2.nominated_node is None


def test_priority_class_resolution():
    """Pods naming a PriorityClass (no spec.priority) resolve through the
    snapshot's priorityClasses for queue order AND preemption."""
    store = ClusterStore()
    store.create("priorityclasses", {
        "apiVersion": "scheduling.k8s.io/v1", "kind": "PriorityClass",
        "metadata": {"name": "critical"}, "value": 1000,
    })
    store.create("nodes", make_node("n0", cpu="2", memory="8Gi"))
    store.create("pods", _bound("low", "n0", "2", 1))
    crit = make_pod("crit", cpu="1", memory=None)
    crit["spec"]["priorityClassName"] = "critical"  # no spec.priority
    store.create("pods", crit)
    svc = SchedulerService(store)
    assert svc.schedule_pending() == {"default/crit": None}
    # Preemption saw the resolved priority 1000 > 1 and evicted the holder.
    assert store.get("pods", "crit")["status"]["nominatedNodeName"] == "n0"
    assert svc.schedule_pending() == {"default/crit": "n0"}
