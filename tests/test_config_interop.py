"""Interop: every valid v1.30 KubeSchedulerConfiguration is accepted.

The reference decodes any upstream config through the scheme codecs
(reference simulator/config/config.go:275-291); its tests exercise
``scoringStrategy: MostAllocated`` (config_test.go:30-56), and its own
exported default config carries the legacy volume-limit names
EBSLimits/GCEPDLimits/AzureDiskLimits in the filter set and
``defaultingType: System`` for PodTopologySpread
(snapshot_test.go:1415 — embedded verbatim as
tests/fixtures/reference_default_config.json, the interop contract).

Scoring-strategy and addedAffinity expected values are hand-derived in
tests/fixtures/upstream_v130.py (never by running oracle or kernels) and
asserted against BOTH the pure-Python oracle and the JAX kernels.
"""

from __future__ import annotations

import json
import pathlib

import pytest

from ksim_tpu.engine import Engine
from ksim_tpu.plugins import oracle
from ksim_tpu.scheduler import SchedulerService
from ksim_tpu.scheduler.profile import compile_configuration, compile_profile
from ksim_tpu.state.cluster import ClusterStore
from ksim_tpu.state.featurizer import Featurizer
from tests.fixtures import upstream_v130 as fx
from tests.helpers import make_node, make_pod

FIXTURE_DIR = pathlib.Path(__file__).parent / "fixtures"


def _reference_config() -> dict:
    doc = json.loads((FIXTURE_DIR / "reference_default_config.json").read_text())
    return doc["schedulerConfig"]


def _prof_engine(prof, nodes, bound, queue, **kw):
    feats = prof.featurizer().featurize(nodes, bound, queue_pods=queue, **kw)
    eng = Engine(feats, prof.plugins(feats), record="full")
    return feats, eng.evaluate_batch()


# -- the reference's own exported config must import ------------------------


def test_reference_default_config_compiles():
    profs = compile_configuration(_reference_config())
    assert len(profs) == 1
    prof = profs[0]
    assert prof.scheduler_name == "default-scheduler"
    enabled = dict(prof.enabled)
    # The legacy names resolve to kernels (not skips) and every
    # pluginConfig arg threads.
    for legacy in ("EBSLimits", "GCEPDLimits", "AzureDiskLimits"):
        assert legacy in enabled
    assert prof.skipped == ()
    assert prof.hard_pod_affinity_weight == 1


def test_reference_default_config_schedules_end_to_end():
    """The whole reference config drives the service: import -> compile ->
    schedule (the round-trip a reference-exported snapshot performs)."""
    store = ClusterStore()
    store.create("nodes", make_node("n1"))
    store.create("pods", make_pod("p1"))
    svc = SchedulerService(store, config=_reference_config())
    assert svc.schedule_pending() == {"default/p1": "n1"}


def test_most_allocated_config_accepted():
    """The reference config test's MostAllocated document
    (config_test.go:30-56) compiles into a profile."""
    prof = compile_profile(
        {
            "pluginConfig": [
                {
                    "name": "NodeResourcesFit",
                    "args": {
                        "scoringStrategy": {
                            "resources": [{"name": "cpu", "weight": 1}],
                            "type": "MostAllocated",
                        }
                    },
                }
            ]
        }
    )
    feats = Featurizer().featurize([make_node("n")], [], queue_pods=[make_pod("p")])
    assert any(sp.plugin.name == "NodeResourcesFit" for sp in prof.plugins(feats))


def test_unknown_scoring_strategy_still_rejected():
    prof = compile_profile(
        {
            "pluginConfig": [
                {
                    "name": "NodeResourcesFit",
                    "args": {"scoringStrategy": {"type": "Bogus"}},
                }
            ]
        }
    )
    feats = Featurizer().featurize([make_node("n")], [], queue_pods=[make_pod("p")])
    with pytest.raises(ValueError, match="scoring strategy"):
        prof.plugins(feats)


def test_rtcr_shape_validation():
    feats = Featurizer().featurize([make_node("n")], [], queue_pods=[make_pod("p")])
    no_shape = compile_profile(
        {
            "pluginConfig": [
                {
                    "name": "NodeResourcesFit",
                    "args": {"scoringStrategy": {"type": "RequestedToCapacityRatio"}},
                }
            ]
        }
    )
    with pytest.raises(ValueError, match="shape"):
        no_shape.plugins(feats)
    bad_order = compile_profile(
        {
            "pluginConfig": [
                {
                    "name": "NodeResourcesFit",
                    "args": {
                        "scoringStrategy": {
                            "type": "RequestedToCapacityRatio",
                            "requestedToCapacityRatio": {
                                "shape": [
                                    {"utilization": 50, "score": 5},
                                    {"utilization": 50, "score": 7},
                                ]
                            },
                        }
                    },
                }
            ]
        }
    )
    with pytest.raises(ValueError, match="increasing"):
        bad_order.plugins(feats)


# -- scoring-strategy fixtures (hand-derived) -------------------------------


def _strategy_cluster(case):
    node = make_node(
        "n0", cpu=f"{case['node_cpu_milli']}m", memory=str(case["node_mem"])
    )
    cpu = None if case["pod_cpu_milli"] is None else f"{case['pod_cpu_milli']}m"
    mem = None if case["pod_mem"] is None else str(case["pod_mem"])
    pod = make_pod("p0", cpu=cpu, memory=mem)
    return [node], pod


def _strategy_profile(case, stype):
    strategy = {
        "type": stype,
        "resources": [{"name": r, "weight": w} for r, w in case["weights"]],
    }
    if stype == "RequestedToCapacityRatio":
        strategy["requestedToCapacityRatio"] = {
            "shape": [
                {"utilization": u, "score": s} for u, s in case["shape"]
            ]
        }
    return compile_profile(
        {"pluginConfig": [{"name": "NodeResourcesFit", "args": {"scoringStrategy": strategy}}]}
    )


@pytest.mark.parametrize("case", fx.MOST_ALLOCATED_CASES, ids=lambda c: c["name"])
def test_most_allocated_fixture(case):
    nodes, pod = _strategy_cluster(case)
    infos = oracle.build_node_infos(nodes, [])
    assert (
        oracle.most_allocated_score(pod, infos[0], resources=case["weights"])
        == case["want"]
    )
    prof = _strategy_profile(case, "MostAllocated")
    _feats, res = _prof_engine(prof, nodes, [], [pod])
    si = res.plugin_names.index("NodeResourcesFit")
    assert int(res.scores[0, si, 0]) == case["want"]


@pytest.mark.parametrize("case", fx.RTCR_CASES, ids=lambda c: c["name"])
def test_requested_to_capacity_ratio_fixture(case):
    nodes, pod = _strategy_cluster(case)
    infos = oracle.build_node_infos(nodes, [])
    assert (
        oracle.requested_to_capacity_ratio_score(
            pod, infos[0], case["shape"], resources=case["weights"]
        )
        == case["want"]
    )
    prof = _strategy_profile(case, "RequestedToCapacityRatio")
    _feats, res = _prof_engine(prof, nodes, [], [pod])
    si = res.plugin_names.index("NodeResourcesFit")
    assert int(res.scores[0, si, 0]) == case["want"]


# -- NodeAffinityArgs.addedAffinity -----------------------------------------


def _added_nodes():
    return [
        make_node("n-a", labels={"zone": "a", "hw": "x"}),
        make_node("n-b", labels={"zone": "b", "hw": "x"}),
    ]


def _added_profile(added):
    return compile_profile(
        {"pluginConfig": [{"name": "NodeAffinity", "args": {"addedAffinity": added}}]}
    )


def test_added_affinity_filter_fixture():
    nodes = _added_nodes()
    pod = make_pod("plain")
    infos = oracle.build_node_infos(nodes, [])
    for info in infos:
        assert (
            oracle.node_affinity_filter(
                pod, info, added_affinity=fx.ADDED_AFFINITY_REQUIRED
            )
            == fx.ADDED_AFFINITY_FILTER_EXPECT[info["name"]]
        )
    prof = _added_profile(fx.ADDED_AFFINITY_REQUIRED)
    _feats, res = _prof_engine(prof, nodes, [], [pod])
    fi = res.filter_plugin_names.index("NodeAffinity")
    plugins = {sp.plugin.name: sp.plugin for sp in prof.plugins(_feats)}
    for ni, name in enumerate(("n-a", "n-b")):
        got = plugins["NodeAffinity"].decode_reasons(int(res.reason_bits[0, fi, ni]))
        assert got == fx.ADDED_AFFINITY_FILTER_EXPECT[name]


def test_added_affinity_cross_fixture():
    """Pod selector wants zone=b: the enforced check early-returns on n-b's
    complement while the pod reason surfaces where only the pod fails."""
    nodes = _added_nodes()
    pod = make_pod("wants-b", node_selector={"zone": "b"})
    infos = oracle.build_node_infos(nodes, [])
    for info in infos:
        assert (
            oracle.node_affinity_filter(
                pod, info, added_affinity=fx.ADDED_AFFINITY_REQUIRED
            )
            == fx.ADDED_AFFINITY_CROSS_EXPECT[info["name"]]
        )
    prof = _added_profile(fx.ADDED_AFFINITY_REQUIRED)
    _feats, res = _prof_engine(prof, nodes, [], [pod])
    fi = res.filter_plugin_names.index("NodeAffinity")
    plugins = {sp.plugin.name: sp.plugin for sp in prof.plugins(_feats)}
    for ni, name in enumerate(("n-a", "n-b")):
        got = plugins["NodeAffinity"].decode_reasons(int(res.reason_bits[0, fi, ni]))
        assert got == fx.ADDED_AFFINITY_CROSS_EXPECT[name]


def test_added_affinity_score_fixture():
    nodes = _added_nodes()
    pod = make_pod(
        "prefers-x",
        affinity={
            "nodeAffinity": {
                "preferredDuringSchedulingIgnoredDuringExecution": [
                    {
                        "weight": 5,
                        "preference": {
                            "matchExpressions": [
                                {"key": "hw", "operator": "In", "values": ["x"]}
                            ]
                        },
                    }
                ]
            }
        },
    )
    infos = oracle.build_node_infos(nodes, [])
    raw = [
        oracle.node_affinity_score(
            pod, info, added_affinity=fx.ADDED_AFFINITY_PREFERRED
        )
        for info in infos
    ]
    norm = oracle.default_normalize_score(raw, reverse=False)
    assert dict(zip(("n-a", "n-b"), norm)) == fx.ADDED_AFFINITY_SCORE_EXPECT
    prof = _added_profile(fx.ADDED_AFFINITY_PREFERRED)
    _feats, res = _prof_engine(prof, nodes, [], [pod])
    si = res.plugin_names.index("NodeAffinity")
    got = {
        name: int(res.final_scores[0, si, ni] // 2)  # default weight 2
        for ni, name in enumerate(("n-a", "n-b"))
    }
    assert got == fx.ADDED_AFFINITY_SCORE_EXPECT


# -- legacy non-CSI volume-limit plugins ------------------------------------


def _ebs_pod(name, vol_id):
    pod = make_pod(name)
    pod["spec"]["volumes"] = [
        {"name": "disk", "awsElasticBlockStore": {"volumeID": vol_id}}
    ]
    return pod


def test_legacy_ebs_limits_fixture():
    node = make_node("ebs-1", extra_alloc={"attachable-volumes-aws-ebs": "1"})
    holder = _ebs_pod("holder", "vol-1")
    holder["spec"]["nodeName"] = "ebs-1"
    newvol = _ebs_pod("newvol", "vol-2")
    sharer = _ebs_pod("sharer", "vol-1")

    # Oracle, pool-restricted like the EBSLimits plugin.
    assert oracle.node_volume_limits_filter(
        newvol, node, [holder], [], [], [], pools=("aws-ebs",)
    ) == [fx.EBS_LIMIT_REASON]
    assert (
        oracle.node_volume_limits_filter(
            sharer, node, [holder], [], [], [], pools=("aws-ebs",)
        )
        == []
    )
    # The GCE-PD plugin ignores the EBS pool entirely.
    assert (
        oracle.node_volume_limits_filter(
            newvol, node, [holder], [], [], [], pools=("gce-pd",)
        )
        == []
    )

    # Kernel through a profile enabling the legacy names at filter
    # (exactly how the reference default config carries them).
    prof = compile_profile(
        {
            "plugins": {
                "filter": {
                    "enabled": [{"name": "EBSLimits"}, {"name": "GCEPDLimits"}]
                }
            }
        }
    )
    _feats, res = _prof_engine(prof, [node], [holder], [newvol, sharer])
    ebs = res.filter_plugin_names.index("EBSLimits")
    gce = res.filter_plugin_names.index("GCEPDLimits")
    assert int(res.reason_bits[0, ebs, 0]) != 0  # newvol over the EBS limit
    assert int(res.reason_bits[1, ebs, 0]) == 0  # sharer dedups
    assert int(res.reason_bits[0, gce, 0]) == 0  # GCE plugin unaffected


def test_in_tree_pool_limit_applies_via_node_volume_limits():
    """Round-4 regression: the SOURCE_POOL names were full
    attachable-volumes-* keys while the pool vocabulary uses suffixes, so
    in-tree EBS/GCE/Azure volumes were never counted against their pools
    by ANY plugin (kernel and oracle agreed on the no-op, which is why
    only a hand-derived fixture catches it)."""
    node = make_node("ebs-1", extra_alloc={"attachable-volumes-aws-ebs": "1"})
    holder = _ebs_pod("holder", "vol-1")
    holder["spec"]["nodeName"] = "ebs-1"
    newvol = _ebs_pod("newvol", "vol-2")
    assert oracle.node_volume_limits_filter(
        newvol, node, [holder], [], [], []
    ) == [fx.EBS_LIMIT_REASON]
    prof = compile_profile({})
    _feats, res = _prof_engine(prof, [node], [holder], [newvol])
    fi = res.filter_plugin_names.index("NodeVolumeLimits")
    assert int(res.reason_bits[0, fi, 0]) != 0


# -- PodTopologySpreadArgs: defaultConstraints / defaultingType -------------


def test_spread_defaulting_type_validation():
    with pytest.raises(ValueError, match="defaultingType is System"):
        compile_profile(
            {
                "pluginConfig": [
                    {
                        "name": "PodTopologySpread",
                        "args": {
                            "defaultingType": "System",
                            "defaultConstraints": [
                                {"maxSkew": 1, "topologyKey": "zone",
                                 "whenUnsatisfiable": "DoNotSchedule"}
                            ],
                        },
                    }
                ]
            }
        )
    with pytest.raises(ValueError, match="defaultingType"):
        compile_profile(
            {
                "pluginConfig": [
                    {"name": "PodTopologySpread", "args": {"defaultingType": "Bogus"}}
                ]
            }
        )


def test_spread_default_constraints_inert_without_owner_kinds():
    """Explicit List defaultConstraints compile and schedule — and are
    inert, exactly like the reference: upstream buildDefaultConstraints
    (pod_topology_spread/common.go) drops the defaults when
    helper.DefaultSelector is empty, and the 7-kind snapshot model
    (reference simulator/snapshot/snapshot.go:33-42) carries no
    Services/ReplicaSets/StatefulSets to build that selector from."""
    cfg = {
        "pluginConfig": [
            {
                "name": "PodTopologySpread",
                "args": {
                    "defaultingType": "List",
                    "defaultConstraints": [
                        {
                            "maxSkew": 1,
                            "topologyKey": "topology.kubernetes.io/zone",
                            "whenUnsatisfiable": "DoNotSchedule",
                        }
                    ],
                },
            }
        ]
    }
    prof = compile_profile(cfg)
    assert prof.spread_defaults() == (
        {
            "maxSkew": 1,
            "topologyKey": "topology.kubernetes.io/zone",
            "whenUnsatisfiable": "DoNotSchedule",
        },
    )
    # Only one node carries the zone key: if the default constraint
    # applied, bare pods would be filtered off zoneless n-plain; the
    # empty DefaultSelector makes it a no-op instead.
    nodes = [
        make_node("n-zoned", labels={"topology.kubernetes.io/zone": "a"}),
        make_node("n-plain"),
    ]
    pod = make_pod("bare")
    prof_plain = compile_profile({})
    _f1, res_defaults = _prof_engine(prof, nodes, [], [pod])
    _f2, res_plain = _prof_engine(prof_plain, nodes, [], [pod])
    fi = res_defaults.filter_plugin_names.index("PodTopologySpread")
    assert int(res_defaults.reason_bits[0, fi, 1]) == 0  # n-plain unfiltered
    assert res_defaults.feasible[0] and res_plain.feasible[0]


def test_default_spread_selector_owner_kinds():
    """default_spread_selector mirrors upstream helper.DefaultSelector when
    the owner kinds DO exist (future-proofing; the snapshot model cannot
    produce them today)."""
    from ksim_tpu.state.encoding import default_spread_selector

    pod = make_pod("owned", labels={"app": "db"})
    pod["metadata"]["ownerReferences"] = [
        {"kind": "ReplicaSet", "name": "rs-1", "controller": True}
    ]
    assert default_spread_selector(pod) is None
    svc = {
        "metadata": {"name": "s", "namespace": "default"},
        "spec": {"selector": {"app": "db"}},
    }
    rs = {
        "metadata": {"name": "rs-1", "namespace": "default"},
        "spec": {"selector": {"matchLabels": {"tier": "data"}}},
    }
    sel = default_spread_selector(pod, services=[svc], replica_sets=[rs])
    assert sel == {"matchLabels": {"app": "db", "tier": "data"}}
    # A service whose selector does NOT select the pod contributes nothing.
    other = {
        "metadata": {"name": "o", "namespace": "default"},
        "spec": {"selector": {"app": "web"}},
    }
    assert default_spread_selector(pod, services=[other]) is None


@pytest.mark.parametrize(
    "case", fx.LEAST_ALLOCATED_WEIGHTED_CASES, ids=lambda c: c["name"]
)
def test_least_allocated_weighted_fixture(case):
    """LeastAllocated with CUSTOM per-resource weights through a real
    scoringStrategy profile — including a weight on a resource the node
    lacks, which upstream skips entirely (weight excluded from the
    sum).  Hand-derived expectations, oracle and kernel both checked."""
    nodes, pod = _strategy_cluster(case)
    if any(r not in ("cpu", "memory") for r, _w in case["weights"]):
        # Force the extended resource INTO the resource axis via a
        # second node that allocates it: the kernel's per-node
        # zero-allocatable weight exclusion (has = c > 0) is only a
        # real branch when the resource exists on the axis — without
        # this node the featurizer never tracks it and the kernel
        # check would be vacuously a cpu/memory case.
        nodes = nodes + [
            make_node("n-gpu", cpu="1", extra_alloc={"example.com/gpu": "8"})
        ]
    infos = oracle.build_node_infos(nodes, [])
    assert (
        oracle.least_allocated_score(pod, infos[0], resources=case["weights"])
        == case["want"]
    )
    prof = _strategy_profile(case, "LeastAllocated")
    _feats, res = _prof_engine(prof, nodes, [], [pod])
    si = res.plugin_names.index("NodeResourcesFit")
    assert int(res.scores[0, si, 0]) == case["want"]


def test_balanced_allocation_three_resource_fixture():
    """BalancedAllocationArgs.resources with an extended resource: the
    std-dev runs over THREE fractions (hand-derived float64 math), not
    the default cpu/memory pair."""
    case = fx.BALANCED_THREE_RESOURCE_CASE
    node = make_node(
        "n0",
        cpu=f"{case['node_cpu_milli']}m",
        memory=str(case["node_mem"]),
        extra_alloc={"example.com/gpu": str(case["node_gpu"])},
    )
    pod = make_pod(
        "p0",
        cpu=f"{case['pod_cpu_milli']}m",
        memory=str(case["pod_mem"]),
        extra_requests={"example.com/gpu": str(case["pod_gpu"])},
    )
    prof = compile_profile(
        {
            "pluginConfig": [
                {
                    "name": "NodeResourcesBalancedAllocation",
                    "args": {
                        "resources": [
                            {"name": r, "weight": 1} for r in case["resources"]
                        ]
                    },
                }
            ]
        }
    )
    infos = oracle.build_node_infos([node], [])
    assert (
        oracle.balanced_allocation_score(
            pod, infos[0], resources=case["resources"]
        )
        == case["want"]
    )
    _feats, res = _prof_engine(prof, [node], [], [pod])
    si = res.plugin_names.index("NodeResourcesBalancedAllocation")
    assert int(res.scores[0, si, 0]) == case["want"]
