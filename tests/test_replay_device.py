"""Device-resident churn replay (engine/replay.py) behavior locks.

The segment-scan path must reproduce the per-pass path's scheduling
outcomes BYTE-IDENTICALLY — counts are the contract (repo CLAUDE.md).
These tests pin:

- step-by-step equivalence against the per-pass path on a mixed churn
  stream (spread + affinity pods, node drain/replace, bound-pod
  completions) in both float modes;
- the flagship 6k-event locked prefix (seed 0, 2000 nodes -> 2524/471)
  THROUGH the device path, with proof the device path actually ran
  (a silent blanket fallback would pass the counts vacuously);
- fallback behavior: segments containing unsupported ops take the
  per-pass path and land on identical results.
"""

from __future__ import annotations

import jax
import pytest

from ksim_tpu.scenario import ScenarioRunner, churn_scenario
from ksim_tpu.scenario.runner import Operation
from tests.helpers import make_node, make_pod


def _steps_sig(res):
    return [
        (s.step, s.scheduled, s.unschedulable, s.pending_after) for s in res.steps
    ]


def _run_pair(stream_factory, *, x64: bool, k: int = 8, **runner_kw):
    prev = jax.config.jax_enable_x64
    jax.config.update("jax_enable_x64", x64)
    try:
        base = ScenarioRunner(**runner_kw).run(stream_factory())
        dev_runner = ScenarioRunner(
            device_replay=True, device_segment_steps=k, **runner_kw
        )
        dev = dev_runner.run(stream_factory())
    finally:
        jax.config.update("jax_enable_x64", prev)
    return base, dev, dev_runner.replay_driver


@pytest.mark.parametrize("x64", [False, True], ids=["f32-fast", "exact-x64"])
def test_device_replay_matches_per_pass_churn(x64):
    """Mixed-constraint churn: per-step (scheduled, unschedulable,
    pending) byte-identical through the device path, with real device
    coverage."""
    base, dev, driver = _run_pair(
        lambda: churn_scenario(0, n_nodes=200, n_events=800, ops_per_step=50),
        x64=x64,
        k=8,
        max_pods_per_pass=1024,
        pod_bucket_min=128,
    )
    assert _steps_sig(dev) == _steps_sig(base)
    assert (dev.pods_scheduled, dev.unschedulable_attempts) == (
        base.pods_scheduled,
        base.unschedulable_attempts,
    )
    assert driver.device_steps >= 8  # at least one real device segment


def test_device_replay_lock_6k_seed0_f32():
    """The flagship locked prefix through the device-resident path:
    seed 0, 2000 nodes, 6k events -> 2524/471 (repo CLAUDE.md), exactly
    as the bench runs it.  The driver must have covered the bulk of the
    steps on-device — a blanket fallback passing vacuously is a failure."""
    jax.config.update("jax_enable_x64", False)
    try:
        runner = ScenarioRunner(
            max_pods_per_pass=1024,
            pod_bucket_min=128,
            device_replay=True,
            device_segment_steps=16,
        )
        res = runner.run(
            churn_scenario(0, n_nodes=2000, n_events=6000, ops_per_step=100)
        )
    finally:
        jax.config.update("jax_enable_x64", True)
    assert res.events_applied == 6430
    assert (res.pods_scheduled, res.unschedulable_attempts) == (2524, 471)
    driver = runner.replay_driver
    assert driver.device_steps >= 32
    assert driver.device_steps + driver.fallback_steps == len(res.steps)


@pytest.mark.slow
def test_device_replay_lock_6k_seed0_exact():
    """Exact-mode (x64) variant of the device-path lock."""
    runner = ScenarioRunner(
        max_pods_per_pass=1024,
        pod_bucket_min=128,
        device_replay=True,
        device_segment_steps=16,
    )
    res = runner.run(
        churn_scenario(0, n_nodes=2000, n_events=6000, ops_per_step=100)
    )
    assert (res.pods_scheduled, res.unschedulable_attempts) == (2524, 471)
    assert runner.replay_driver.device_steps >= 32


def test_device_replay_falls_back_on_unsupported_ops():
    """A patch op poisons its segment (outside the tensor vocabulary):
    that segment runs per-pass, the rest still runs on-device, and the
    end state matches the pure per-pass path."""

    def stream():
        step = 0
        for i in range(8):
            yield Operation(
                step=0, op="create", kind="nodes",
                obj=make_node(f"n-{i}", cpu="8", memory="16Gi"),
            )
        for step in range(1, 9):
            yield Operation(
                step=step, op="create", kind="pods",
                obj=make_pod(f"p-{step}", cpu="500m", memory="512Mi"),
            )
            if step == 4:
                # RFC 7386 merge patch: outside the device vocabulary.
                yield Operation(
                    step=step, op="patch", kind="pods",
                    name=f"p-{step}", namespace="default",
                    obj={"metadata": {"labels": {"patched": "yes"}}},
                )

    base, dev, driver = _run_pair(stream, x64=False, k=4)
    assert _steps_sig(dev) == _steps_sig(base)
    # Fallback is per-STEP granular: the patch step runs per-pass and the
    # driver re-segments right after it, so only the poisoned step(s)
    # leave the device path.
    assert driver.fallback_steps >= 1
    assert driver.device_steps >= 8
    assert any(r.startswith("op:patch") for r in driver.unsupported)


def test_device_replay_pod_vocabulary_fallback():
    """Pods with host ports are outside the tensor vocabulary: the
    lowering rejects the segment and results still match per-pass."""

    def stream():
        for i in range(4):
            yield Operation(
                step=0, op="create", kind="nodes",
                obj=make_node(f"n-{i}", cpu="8", memory="16Gi"),
            )
        ported = make_pod("ported", cpu="500m", memory="512Mi")
        ported["spec"]["containers"][0]["ports"] = [{"hostPort": 8080}]
        yield Operation(step=1, op="create", kind="pods", obj=ported)
        yield Operation(
            step=2, op="create", kind="pods",
            obj=make_pod("plain", cpu="500m", memory="512Mi"),
        )

    base, dev, driver = _run_pair(stream, x64=False, k=3)
    assert _steps_sig(dev) == _steps_sig(base)
    assert driver.unsupported.get("host_ports", 0) >= 1


def test_device_replay_namespaceless_create_op():
    """A create op whose pod object omits metadata.namespace (the store
    defaults it to "default" on create) must flow through the device
    path under the same key the service uses — review finding: the two
    key schemes diverged and crashed the lowering."""

    def stream():
        for i in range(4):
            yield Operation(
                step=0, op="create", kind="nodes",
                obj=make_node(f"n-{i}", cpu="8", memory="16Gi"),
            )
        bare = make_pod("nsless", cpu="500m", memory="512Mi")
        del bare["metadata"]["namespace"]
        yield Operation(step=1, op="create", kind="pods", obj=bare)
        yield Operation(
            step=2, op="create", kind="pods",
            obj=make_pod("plain", cpu="500m", memory="512Mi"),
        )

    base, dev, driver = _run_pair(stream, x64=False, k=3)
    assert _steps_sig(dev) == _steps_sig(base)
    assert driver.device_steps == 3


def test_sampling_k_validated_against_real_node_count():
    """Library-direct regression (review satellite): sampling_k between
    the real node count and the padded axis must be rejected — padding
    rows never pass filters, so such a K silently under-samples."""
    from ksim_tpu.engine import Engine
    from ksim_tpu.engine.profiles import default_plugins
    from ksim_tpu.state.featurizer import Featurizer

    nodes = [make_node(f"n-{i}", cpu="4", memory="8Gi") for i in range(5)]
    pods = [make_pod("p-0", cpu="1", memory="1Gi")]
    feats = Featurizer().featurize(nodes, (), queue_pods=pods)
    assert feats.nodes.padded > feats.nodes.count  # padding exists
    Engine(feats, default_plugins(feats), record="selection", sampling_k=5)
    with pytest.raises(ValueError, match="real node count"):
        Engine(
            feats, default_plugins(feats), record="selection",
            sampling_k=feats.nodes.count + 1,
        )
