"""Device-resident churn replay (engine/replay.py) behavior locks.

The segment-scan path must reproduce the per-pass path's scheduling
outcomes BYTE-IDENTICALLY — counts are the contract (repo CLAUDE.md).
These tests pin:

- step-by-step equivalence against the per-pass path on a mixed churn
  stream (spread + affinity pods, node drain/replace, bound-pod
  completions) in both float modes;
- the flagship 6k-event locked prefix (seed 0, 2000 nodes -> 2524/471)
  THROUGH the device path, with proof the device path actually ran
  (a silent blanket fallback would pass the counts vacuously);
- fallback behavior: segments containing unsupported ops take the
  per-pass path and land on identical results.
"""

from __future__ import annotations

import jax
import pytest

from ksim_tpu.scenario import ScenarioRunner, churn_scenario
from ksim_tpu.scenario.runner import Operation
from tests.fixtures.preemption_victims import CASES as PREEMPTION_CASES
from tests.helpers import make_node, make_pod


def _steps_sig(res):
    return [
        (s.step, s.scheduled, s.unschedulable, s.pending_after) for s in res.steps
    ]


def _run_pair(stream_factory, *, x64: bool, k: int = 8, **runner_kw):
    prev = jax.config.jax_enable_x64
    jax.config.update("jax_enable_x64", x64)
    try:
        base = ScenarioRunner(**runner_kw).run(stream_factory())
        dev_runner = ScenarioRunner(
            device_replay=True, device_segment_steps=k, **runner_kw
        )
        dev = dev_runner.run(stream_factory())
    finally:
        jax.config.update("jax_enable_x64", prev)
    return base, dev, dev_runner.replay_driver


@pytest.mark.parametrize("x64", [False, True], ids=["f32-fast", "exact-x64"])
def test_device_replay_matches_per_pass_churn(x64):
    """Mixed-constraint churn: per-step (scheduled, unschedulable,
    pending) byte-identical through the device path, with real device
    coverage."""
    base, dev, driver = _run_pair(
        lambda: churn_scenario(0, n_nodes=200, n_events=800, ops_per_step=50),
        x64=x64,
        k=8,
        max_pods_per_pass=1024,
        pod_bucket_min=128,
    )
    assert _steps_sig(dev) == _steps_sig(base)
    assert (dev.pods_scheduled, dev.unschedulable_attempts) == (
        base.pods_scheduled,
        base.unschedulable_attempts,
    )
    assert driver.device_steps >= 8  # at least one real device segment


def test_device_replay_lock_6k_seed0_f32():
    """The flagship locked prefix through the device-resident path:
    seed 0, 2000 nodes, 6k events -> 2524/471 (repo CLAUDE.md), exactly
    as the bench runs it.  The driver must have covered the bulk of the
    steps on-device — a blanket fallback passing vacuously is a failure."""
    jax.config.update("jax_enable_x64", False)
    try:
        runner = ScenarioRunner(
            max_pods_per_pass=1024,
            pod_bucket_min=128,
            device_replay=True,
            device_segment_steps=16,
        )
        res = runner.run(
            churn_scenario(0, n_nodes=2000, n_events=6000, ops_per_step=100)
        )
    finally:
        jax.config.update("jax_enable_x64", True)
    assert res.events_applied == 6430
    assert (res.pods_scheduled, res.unschedulable_attempts) == (2524, 471)
    driver = runner.replay_driver
    assert driver.device_steps >= 32
    assert driver.device_steps + driver.fallback_steps == len(res.steps)


@pytest.mark.slow
def test_device_replay_lock_6k_seed0_exact():
    """Exact-mode (x64) variant of the device-path lock."""
    runner = ScenarioRunner(
        max_pods_per_pass=1024,
        pod_bucket_min=128,
        device_replay=True,
        device_segment_steps=16,
    )
    res = runner.run(
        churn_scenario(0, n_nodes=2000, n_events=6000, ops_per_step=100)
    )
    assert (res.pods_scheduled, res.unschedulable_attempts) == (2524, 471)
    assert runner.replay_driver.device_steps >= 32


def test_device_replay_falls_back_on_unsupported_ops():
    """A patch op poisons its segment (outside the tensor vocabulary):
    that segment runs per-pass, the rest still runs on-device, and the
    end state matches the pure per-pass path."""

    def stream():
        step = 0
        for i in range(8):
            yield Operation(
                step=0, op="create", kind="nodes",
                obj=make_node(f"n-{i}", cpu="8", memory="16Gi"),
            )
        for step in range(1, 9):
            yield Operation(
                step=step, op="create", kind="pods",
                obj=make_pod(f"p-{step}", cpu="500m", memory="512Mi"),
            )
            if step == 4:
                # RFC 7386 merge patch: outside the device vocabulary.
                yield Operation(
                    step=step, op="patch", kind="pods",
                    name=f"p-{step}", namespace="default",
                    obj={"metadata": {"labels": {"patched": "yes"}}},
                )

    base, dev, driver = _run_pair(stream, x64=False, k=4)
    assert _steps_sig(dev) == _steps_sig(base)
    # Fallback is per-STEP granular: the patch step runs per-pass and the
    # driver re-segments right after it, so only the poisoned step(s)
    # leave the device path.
    assert driver.fallback_steps >= 1
    assert driver.device_steps >= 8
    assert any(r.startswith("op:patch") for r in driver.unsupported)


def test_device_replay_pod_vocabulary_fallback():
    """Pods with host ports are outside the tensor vocabulary: the
    lowering rejects the segment and results still match per-pass."""

    def stream():
        for i in range(4):
            yield Operation(
                step=0, op="create", kind="nodes",
                obj=make_node(f"n-{i}", cpu="8", memory="16Gi"),
            )
        ported = make_pod("ported", cpu="500m", memory="512Mi")
        ported["spec"]["containers"][0]["ports"] = [{"hostPort": 8080}]
        yield Operation(step=1, op="create", kind="pods", obj=ported)
        yield Operation(
            step=2, op="create", kind="pods",
            obj=make_pod("plain", cpu="500m", memory="512Mi"),
        )

    base, dev, driver = _run_pair(stream, x64=False, k=3)
    assert _steps_sig(dev) == _steps_sig(base)
    assert driver.unsupported.get("host_ports", 0) >= 1


def test_device_replay_namespaceless_create_op():
    """A create op whose pod object omits metadata.namespace (the store
    defaults it to "default" on create) must flow through the device
    path under the same key the service uses — review finding: the two
    key schemes diverged and crashed the lowering."""

    def stream():
        for i in range(4):
            yield Operation(
                step=0, op="create", kind="nodes",
                obj=make_node(f"n-{i}", cpu="8", memory="16Gi"),
            )
        bare = make_pod("nsless", cpu="500m", memory="512Mi")
        del bare["metadata"]["namespace"]
        yield Operation(step=1, op="create", kind="pods", obj=bare)
        yield Operation(
            step=2, op="create", kind="pods",
            obj=make_pod("plain", cpu="500m", memory="512Mi"),
        )

    base, dev, driver = _run_pair(stream, x64=False, k=3)
    assert _steps_sig(dev) == _steps_sig(base)
    assert driver.device_steps == 3


# ---------------------------------------------------------------------------
# Round 7: on-device preemption victim search + record="full" streaming
# ---------------------------------------------------------------------------


def _collect_evictions(runner):
    order = []
    runner.service.add_eviction_listener(lambda ns, nm: order.append((ns, nm)))
    return order


@pytest.mark.parametrize(
    "case", PREEMPTION_CASES, ids=[c["name"] for c in PREEMPTION_CASES]
)
def test_device_preemption_matches_fixtures(case):
    """The ON-DEVICE victim search lands on the hand-derived nominated
    node and evicts the same victims in the same (reprieve) order as the
    host oracle — with proof the segment actually ran on-device."""
    from tests.test_preemption_fixtures import case_objects

    jax.config.update("jax_enable_x64", False)
    nodes, victims, pre = case_objects(case)
    from ksim_tpu.state.cluster import ClusterStore

    store = ClusterStore()
    for n in nodes:
        store.create("nodes", n)
    for v in victims:
        store.create("pods", v)
    runner = ScenarioRunner(
        store=store, preemption=True, device_replay=True, device_segment_steps=4
    )
    evicted = _collect_evictions(runner)
    runner.run(iter([Operation(step=1, op="create", kind="pods", obj=pre)]))
    driver = runner.replay_driver
    assert driver.device_steps >= 1, driver.unsupported
    got = store.get("pods", "preemptor")
    assert (
        got.get("status", {}).get("nominatedNodeName")
        == case["expected_nominated"]
    )
    assert [nm for _ns, nm in evicted] == case["expected_victims"]


def test_device_preemption_churn_matches_per_pass():
    """A churn stream with priority strata (so preemption really fires
    mid-segment): per-step counts and the final store byte-identical
    between the per-pass path and the device path with preemption ON."""

    def stream():
        # 3 nodes x 4 cpu saturate after 8 x 1.5cpu pods; later
        # higher-priority arrivals must preempt the prio-0 stratum.
        for i in range(3):
            yield Operation(
                step=0, op="create", kind="nodes",
                obj=make_node(f"n-{i}", cpu="4", memory="16Gi"),
            )
        for step in range(1, 17):
            prio = [0, 0, 5, 10][step % 4]
            pod = make_pod(
                f"p-{step}", cpu="1500m", memory="256Mi", priority=prio
            )
            pod["metadata"]["creationTimestamp"] = f"2026-01-{step:02d}T00:00:00Z"
            yield Operation(step=step, op="create", kind="pods", obj=pod)

    def run(device):
        runner = ScenarioRunner(
            preemption=True, device_replay=device, device_segment_steps=4
        )
        ev = _collect_evictions(runner)
        res = runner.run(stream())
        state = sorted(
            (
                p["metadata"]["name"],
                p.get("spec", {}).get("nodeName"),
                p.get("status", {}).get("nominatedNodeName"),
            )
            for p in runner.store.list("pods")
        )
        return runner, res, state, ev

    jax.config.update("jax_enable_x64", False)
    r_base, base, st_base, ev_base = run(False)
    r_dev, dev, st_dev, ev_dev = run(True)
    assert _steps_sig(dev) == _steps_sig(base)
    assert st_dev == st_base
    assert ev_dev == ev_base
    assert ev_base, "stream never triggered preemption — fixture is vacuous"
    assert r_dev.replay_driver.device_steps >= 8
    assert "preemption" not in r_dev.replay_driver.unsupported


def test_device_full_record_annotations_match():
    """record="full" streams result tensors out of the segment scan; the
    host decode must reproduce the per-pass annotations BYTE-identically
    (filter/score/finalscore maps, history, selected-node)."""

    def stream():
        return churn_scenario(0, n_nodes=24, n_events=160, ops_per_step=16)

    def annos(store):
        return {
            p["metadata"]["name"]: p["metadata"].get("annotations", {})
            for p in store.list("pods")
        }

    jax.config.update("jax_enable_x64", False)
    base_r = ScenarioRunner(record="full", max_pods_per_pass=64, pod_bucket_min=32)
    base = base_r.run(stream())
    dev_r = ScenarioRunner(
        record="full", max_pods_per_pass=64, pod_bucket_min=32,
        device_replay=True, device_segment_steps=8,
    )
    dev = dev_r.run(stream())
    assert _steps_sig(dev) == _steps_sig(base)
    assert dev_r.replay_driver.device_steps >= 4, dev_r.replay_driver.unsupported
    a_base, a_dev = annos(base_r.store), annos(dev_r.store)
    assert set(a_base) == set(a_dev)
    for name in a_base:
        assert a_base[name] == a_dev[name], f"annotations diverged for {name}"


def test_device_preemption_with_full_record():
    """Preemption + record="full" together: the resolvable-candidate
    mask is derived from the streamed reason bits on-device, and the
    postfilter-result annotation (every failed node, nominated entry)
    matches the per-pass render."""
    import json

    from ksim_tpu.engine.annotations import POST_FILTER_RESULT_KEY
    from ksim_tpu.state.cluster import ClusterStore

    def build(device):
        store = ClusterStore()
        store.create("nodes", make_node("n0", cpu="2", memory="8Gi"))
        low = make_pod("low0", cpu="1", memory=None, node_name="n0", priority=1)
        low["metadata"]["creationTimestamp"] = "2024-01-01T00:00:00Z"
        store.create("pods", low)
        low2 = make_pod("low1", cpu="1", memory=None, node_name="n0", priority=1)
        low2["metadata"]["creationTimestamp"] = "2024-01-01T00:00:01Z"
        store.create("pods", low2)
        runner = ScenarioRunner(
            store=store, record="full", preemption=True,
            device_replay=True if device else False, device_segment_steps=4,
        )
        crit = make_pod("crit", cpu="1", memory=None, priority=100)
        runner.run(iter([Operation(step=1, op="create", kind="pods", obj=crit)]))
        return runner

    jax.config.update("jax_enable_x64", False)
    base = build(False)
    dev = build(True)
    assert dev.replay_driver.device_steps >= 1, dev.replay_driver.unsupported
    pb = base.store.get("pods", "crit")
    pd = dev.store.get("pods", "crit")
    assert pd["status"].get("nominatedNodeName") == "n0"
    assert (
        pb["metadata"]["annotations"] == pd["metadata"]["annotations"]
    )
    post = json.loads(pd["metadata"]["annotations"][POST_FILTER_RESULT_KEY])
    assert post == {"n0": {"DefaultPreemption": "preemption victim"}}


def test_tail_segment_padding_keeps_short_streams_on_device():
    """Streams shorter than K no longer fall back: the tail is padded
    with inactive no-op steps on-device (ROADMAP open item)."""

    def stream():
        for i in range(4):
            yield Operation(
                step=0, op="create", kind="nodes",
                obj=make_node(f"n-{i}", cpu="8", memory="16Gi"),
            )
        for step in range(1, 6):
            yield Operation(
                step=step, op="create", kind="pods",
                obj=make_pod(f"p-{step}", cpu="500m", memory="512Mi"),
            )

    base, dev, driver = _run_pair(stream, x64=False, k=8)
    assert _steps_sig(dev) == _steps_sig(base)
    assert driver.fallback_steps == 0
    assert driver.device_steps == 6  # step 0 (bootstrap) + 5 pod steps


def test_sampling_k_validated_against_real_node_count():
    """Library-direct regression (review satellite): sampling_k between
    the real node count and the padded axis must be rejected — padding
    rows never pass filters, so such a K silently under-samples."""
    from ksim_tpu.engine import Engine
    from ksim_tpu.engine.profiles import default_plugins
    from ksim_tpu.state.featurizer import Featurizer

    nodes = [make_node(f"n-{i}", cpu="4", memory="8Gi") for i in range(5)]
    pods = [make_pod("p-0", cpu="1", memory="1Gi")]
    feats = Featurizer().featurize(nodes, (), queue_pods=pods)
    assert feats.nodes.padded > feats.nodes.count  # padding exists
    Engine(feats, default_plugins(feats), record="selection", sampling_k=5)
    with pytest.raises(ValueError, match="real node count"):
        Engine(
            feats, default_plugins(feats), record="selection",
            sampling_k=feats.nodes.count + 1,
        )


# ---------------------------------------------------------------------------
# Fleet replay (round 12, engine/fleet.py): S independent trajectories,
# one vmapped dispatch, per-lane parity with the solo device path.
# ---------------------------------------------------------------------------


def _small_churn():
    return churn_scenario(0, n_nodes=48, n_events=200, ops_per_step=20)


def test_fleet_lanes_byte_identical_to_solo_device():
    """The fleet parity lock's in-suite form: every lane of a 3-lane
    fleet lands per-step (scheduled, unschedulable, pending) triples and
    totals byte-identical to the SOLO device-replay run of the same
    stream — and the shared universe is lowered ONCE per window (only
    the cohort leader's driver ever lowers; the counter-based guard)."""
    jax.config.update("jax_enable_x64", False)
    kw = dict(max_pods_per_pass=1024, pod_bucket_min=128, device_segment_steps=8)
    solo_r = ScenarioRunner(device_replay=True, **kw)
    solo = solo_r.run(_small_churn())
    assert solo_r.replay_driver.device_steps >= 8
    fleet_r = ScenarioRunner(device_replay=True, fleet=3, **kw)
    agg = fleet_r.run(_small_churn())
    assert agg.lanes is not None and len(agg.lanes) == 3
    for ln in fleet_r.fleet_lanes:
        assert _steps_sig(ln.result) == _steps_sig(solo), f"lane {ln.idx}"
        assert (
            ln.result.pods_scheduled,
            ln.result.unschedulable_attempts,
        ) == (solo.pods_scheduled, solo.unschedulable_attempts)
        assert ln.driver.device_steps == solo_r.replay_driver.device_steps
    # Aggregate = sum of lanes.
    assert agg.pods_scheduled == 3 * solo.pods_scheduled
    # Lowered once, not per lane: every follower's driver did ZERO
    # lowerings and built no device featurizer.
    stats = fleet_r.fleet_driver.stats()
    lowerings = stats["lane_lowerings"]
    assert sum(lowerings) == lowerings[0] > 0, stats
    assert stats["lanes_on_device"] == 1.0
    assert stats["group_dispatches"] == len(solo_r.replay_driver.lower_log)
    for ln in fleet_r.fleet_lanes[1:]:
        assert ln.driver._featurizer is None


@pytest.mark.slow
def test_fleet_full_record_annotations_byte_identical():
    """record="full" through the fleet: every lane's decoded result
    annotations (filter/score/finalscore maps, history, selected node)
    must be byte-identical to the solo device run's store contents."""

    def stream():
        return churn_scenario(0, n_nodes=24, n_events=160, ops_per_step=16)

    def annos(store):
        return {
            p["metadata"]["name"]: p["metadata"].get("annotations", {})
            for p in store.list("pods")
        }

    jax.config.update("jax_enable_x64", False)
    kw = dict(record="full", max_pods_per_pass=64, pod_bucket_min=32,
              device_replay=True, device_segment_steps=8)
    solo_r = ScenarioRunner(**kw)
    solo = solo_r.run(stream())
    assert solo_r.replay_driver.device_steps >= 4
    fleet_r = ScenarioRunner(fleet=2, **kw)
    fleet_r.run(stream())
    a_solo = annos(solo_r.store)
    for ln in fleet_r.fleet_lanes:
        assert _steps_sig(ln.result) == _steps_sig(solo)
        a_lane = annos(ln.runner.store)
        assert set(a_lane) == set(a_solo)
        for name in a_solo:
            assert a_lane[name] == a_solo[name], (
                f"lane {ln.idx} annotations diverged for {name}"
            )


@pytest.mark.slow
def test_fleet_lane_ops_override_runs_divergent_lane():
    """A per-lane stream (run(..., lane_ops=...)) rides the solo device
    path outside the cohort and matches ITS OWN solo run; base lanes
    still share one lowering."""
    jax.config.update("jax_enable_x64", False)
    kw = dict(max_pods_per_pass=1024, pod_bucket_min=128, device_segment_steps=8)

    def other_stream():
        return churn_scenario(7, n_nodes=32, n_events=120, ops_per_step=20)

    solo_base = ScenarioRunner(device_replay=True, **kw).run(_small_churn())
    solo_other = ScenarioRunner(device_replay=True, **kw).run(other_stream())
    fleet_r = ScenarioRunner(device_replay=True, fleet=3, **kw)
    fleet_r.run(_small_churn(), lane_ops={1: other_stream()})
    lanes = fleet_r.fleet_lanes
    assert _steps_sig(lanes[0].result) == _steps_sig(solo_base)
    assert _steps_sig(lanes[1].result) == _steps_sig(solo_other)
    assert _steps_sig(lanes[2].result) == _steps_sig(solo_base)
    assert not lanes[1].convergent and not lanes[1].shared_stream
    # The divergent lane lowered for itself; the cohort shared one.
    assert len(lanes[1].driver.lower_log) > 0
    assert len(lanes[2].driver.lower_log) == 0


def test_fleet_cancel_lands_at_dispatch_boundary():
    """Round 16: a cancel raised mid-run inside a fleet cohort aborts
    at the NEXT lane dispatch boundary (the per-round check in
    FleetDriver.run), propagating RunCancelled through the group
    exception ladders — which deliberately do not catch it — with
    every lane's store left at a committed segment boundary."""
    from ksim_tpu.errors import RunCancelled

    class FlipAfter:
        """A cancel flag that trips after N polls — mid-run, not
        before the first round."""

        def __init__(self, n):
            self.n = n
            self.polls = 0

        def is_set(self):
            self.polls += 1
            return self.polls > self.n

    jax.config.update("jax_enable_x64", False)
    flag = FlipAfter(3)
    fleet_r = ScenarioRunner(
        device_replay=True, fleet=2, cancel=flag,
        max_pods_per_pass=1024, pod_bucket_min=128, device_segment_steps=8,
    )
    with pytest.raises(RunCancelled):
        fleet_r.run(_small_churn())
    assert flag.polls > 3  # the run made progress before the trip
    # Rollback invariant: no lane's store holds a torn segment — every
    # store transaction either committed whole or rolled back.
    for ln in fleet_r.fleet_lanes or ():
        assert ln.runner.store._txn is None


def test_fleet_rejects_bad_config():
    with pytest.raises(ValueError, match="device_replay"):
        ScenarioRunner(fleet=2)
    with pytest.raises(ValueError, match="at least 2"):
        ScenarioRunner(device_replay=True, fleet=1)
    from ksim_tpu.state.cluster import ClusterStore

    with pytest.raises(ValueError, match="own stores"):
        ScenarioRunner(store=ClusterStore(), device_replay=True, fleet=2)
    with pytest.raises(ValueError, match="lane_ops requires fleet"):
        ScenarioRunner().run(iter(()), lane_ops={0: iter(())})
    with pytest.raises(ValueError, match="lane 5 outside"):
        ScenarioRunner(
            device_replay=True, fleet=2, fleet_faults="5:replay.lower=always"
        ).run(iter(()))
    # Same refusal for lane_ops: a typoed index would silently replay
    # the base stream everywhere and the sweep would be vacuous.
    with pytest.raises(ValueError, match=r"lane_ops lanes \[4\] outside"):
        ScenarioRunner(device_replay=True, fleet=4).run(
            iter(()), lane_ops={4: iter(())}
        )
    # ...and for a lane fault spec with no fleet to arm it on.
    with pytest.raises(ValueError, match="fleet_faults requires fleet"):
        ScenarioRunner(device_replay=True, fleet_faults="0:replay.lower=always")


def _preempt_then_create_free_stream():
    """Step 1 schedules a low-priority pod; step 2's critical pod must
    PREEMPT it mid-segment (nominated, stays pending); step 3 is
    create-free, so the lowering predicts no featurize — but the
    nominated pod is still eligible, and the device run must discard."""
    yield Operation(
        step=0, op="create", kind="nodes",
        obj=make_node("n0", cpu="2", memory="8Gi"),
    )
    low = make_pod("low", cpu="1500m", memory=None, priority=1)
    low["metadata"]["creationTimestamp"] = "2024-01-01T00:00:00Z"
    yield Operation(step=1, op="create", kind="pods", obj=low)
    crit = make_pod("crit", cpu="1500m", memory=None, priority=100)
    crit["metadata"]["creationTimestamp"] = "2024-01-01T00:00:01Z"
    yield Operation(step=2, op="create", kind="pods", obj=crit)
    yield Operation(
        step=3, op="create", kind="nodes",
        obj=make_node("n1", cpu="2", memory="8Gi"),
    )


def test_residual_preemption_then_create_free_step_discards_segment():
    """Regression pin for the documented residual (ROADMAP "known
    residuals"): a mid-segment preemption followed by a create-free step
    breaks the featurize prediction and DISCARDS the segment — the
    stream falls back per-pass with identical outcomes.  This pins the
    behavior (fallback, not wrong counts) until a workload motivates
    lifting it."""
    base, dev, driver = _run_pair(
        _preempt_then_create_free_stream, x64=False, k=8, preemption=True
    )
    assert _steps_sig(dev) == _steps_sig(base)
    assert driver.unsupported.get("featurize_prediction", 0) >= 1
    # The per-pass path really preempted (the residual needs a real
    # mid-segment preemption to trigger).
    assert base.pods_scheduled >= 2


def test_residual_featurize_prediction_inherited_per_lane_in_fleet():
    """Fleet-mode twin of the residual pin: the discard is deterministic
    over identical lanes, so EVERY lane inherits the documented
    fallback (per lane, convergently) and lands the per-pass counts."""
    jax.config.update("jax_enable_x64", False)
    solo_r = ScenarioRunner(device_replay=True, device_segment_steps=8, preemption=True)
    solo = solo_r.run(_preempt_then_create_free_stream())
    fleet_r = ScenarioRunner(
        device_replay=True, device_segment_steps=8, preemption=True, fleet=2
    )
    fleet_r.run(_preempt_then_create_free_stream())
    for ln in fleet_r.fleet_lanes:
        assert _steps_sig(ln.result) == _steps_sig(solo), f"lane {ln.idx}"
        assert ln.driver.unsupported.get("featurize_prediction", 0) >= 1
        assert ln.convergent  # a shared discard degrades convergently


@pytest.mark.slow
def test_fleet_dp_mesh_lanes_match_single_device(monkeypatch):
    """KSIM_FLEET_DP lays the lane axis over a dp-device mesh (the
    conftest forces 8 virtual CPU devices): the sharded group dispatch
    must land byte-identical per-lane outcomes, and the mesh must
    actually have been built (not the silent single-device fallback)."""
    jax.config.update("jax_enable_x64", False)
    kw = dict(max_pods_per_pass=1024, pod_bucket_min=128, device_segment_steps=8)
    solo = ScenarioRunner(device_replay=True, **kw).run(_small_churn())
    monkeypatch.setenv("KSIM_FLEET_DP", "2")
    fleet_r = ScenarioRunner(device_replay=True, fleet=2, **kw)
    fleet_r.run(_small_churn())
    fd = fleet_r.fleet_driver
    assert fd.dp == 2
    with fd._mesh_lock:
        assert fd._mesh and not fd._mesh_failed  # (dp, tp)-keyed, round 19
    assert fd.stats()["lanes_on_device"] == 1.0
    for ln in fleet_r.fleet_lanes:
        assert _steps_sig(ln.result) == _steps_sig(solo), f"lane {ln.idx}"


def test_fleet_vmap_cohort_tiny_stream(monkeypatch):
    """KSIM_FLEET_VMAP=1 drives the cohort through the genuinely
    lane-stacked ``_fleet_segment_fn`` (vmapped carry) — tiny stream so
    the batched compile stays tier-1 cheap; the 6k x 8-lane vmapped leg
    lives in `make lock-check`.  Every lane must match the solo device
    run byte-identically."""

    def stream():
        for i in range(3):
            yield Operation(
                step=0, op="create", kind="nodes",
                obj=make_node(f"n-{i}", cpu="4", memory="8Gi"),
            )
        for step in range(1, 6):
            yield Operation(
                step=step, op="create", kind="pods",
                obj=make_pod(f"p-{step}", cpu="500m", memory="512Mi"),
            )

    jax.config.update("jax_enable_x64", False)
    solo_r = ScenarioRunner(device_replay=True, device_segment_steps=4)
    solo = solo_r.run(stream())
    assert solo_r.replay_driver.device_steps == 6
    monkeypatch.setenv("KSIM_FLEET_VMAP", "1")
    fleet_r = ScenarioRunner(device_replay=True, device_segment_steps=4, fleet=3)
    fleet_r.run(stream())
    assert fleet_r.fleet_driver.stats()["cohort_mode"] == "vmap"
    assert fleet_r.fleet_driver.stats()["lanes_on_device"] == 1.0
    for ln in fleet_r.fleet_lanes:
        assert _steps_sig(ln.result) == _steps_sig(solo), f"lane {ln.idx}"


# ---------------------------------------------------------------------------
# Round 19: 2-D (tp x dp) fleet mesh + donated scan carries
# ---------------------------------------------------------------------------


def test_fleet_tp_dp_mesh_lanes_match_single_device(monkeypatch):
    """KSIM_FLEET_DP=2 composed with KSIM_REPLAY_TP=4 over the
    conftest's 8 virtual devices (the round-19 2-D fleet): lanes lay
    over dp, every lane's [N]/[N, R] node tensors shard over tp, and
    each lane's outcome stays byte-identical to the solo unsharded
    single-device run.  16 nodes keeps every shard at the
    _MIN_SHARD_NODES floor (16 // 4 = 4) so the width is honored, not
    narrowed."""

    def stream():
        for i in range(16):
            yield Operation(
                step=0, op="create", kind="nodes",
                obj=make_node(f"n-{i}", cpu="4", memory="8Gi"),
            )
        for step in range(1, 9):
            yield Operation(
                step=step, op="create", kind="pods",
                obj=make_pod(f"p-{step}", cpu="500m", memory="512Mi"),
            )

    jax.config.update("jax_enable_x64", False)
    monkeypatch.delenv("KSIM_REPLAY_TP", raising=False)
    solo_r = ScenarioRunner(device_replay=True, device_segment_steps=4)
    solo = solo_r.run(stream())
    assert solo_r.replay_driver.device_steps == 9
    monkeypatch.setenv("KSIM_FLEET_DP", "2")
    monkeypatch.setenv("KSIM_REPLAY_TP", "4")
    fleet_r = ScenarioRunner(device_replay=True, device_segment_steps=4, fleet=2)
    fleet_r.run(stream())
    fd = fleet_r.fleet_driver
    assert fd.stats()["cohort_mode"] == "vmap"
    assert fd.stats()["lanes_on_device"] == 1.0
    with fd._mesh_lock:
        assert not fd._mesh_failed
        assert (2, 4) in fd._mesh, fd._mesh  # the (dp, tp) grid was built
    for ln in fleet_r.fleet_lanes:
        assert _steps_sig(ln.result) == _steps_sig(solo), f"lane {ln.idx}"
    # The cohort leader lowers each window once for every lane; all of
    # its segment programs must carry the declared tp=4 node width.
    tps = sorted({e["tp"] for ln in fleet_r.fleet_lanes for e in ln.driver.lower_log})
    assert tps == [4], tps


def test_replay_donation_engages_and_stays_byte_identical():
    """The segment programs donate the scan carry (KSIM_REPLAY_DONATE
    default-on, engine/replay.py _DONATE_ARGNUMS): a donated dispatch
    must raise no jax donation warnings on CPU — XLA either consumed
    the buffers or would warn "Some donated buffers were not usable" —
    and the donated path's per-step outcomes stay byte-identical to
    the per-pass oracle on a preemption-bearing churn stream (the 6k
    lock's in-suite prefix runs through this same donated program;
    tests/test_behavior_locks.py pins its counts)."""
    import warnings

    from ksim_tpu.engine import replay as rmod

    assert rmod._REPLAY_DONATE and rmod._DONATE_ARGNUMS == (4,)
    jax.config.update("jax_enable_x64", False)
    with warnings.catch_warnings():
        warnings.filterwarnings(
            "error", message=".*[Dd]onated buffers.*"
        )
        base = ScenarioRunner().run(
            churn_scenario(0, n_nodes=48, n_events=200, ops_per_step=20)
        )
        dev_r = ScenarioRunner(device_replay=True, device_segment_steps=8)
        dev = dev_r.run(
            churn_scenario(0, n_nodes=48, n_events=200, ops_per_step=20)
        )
    assert dev_r.replay_driver.device_steps > 0
    assert _steps_sig(dev) == _steps_sig(base)


def test_pull_tree_to_host_returns_owned_arrays():
    """Every leaf leaving _pull_tree_to_host must OWN its memory.
    np.asarray of a CPU-backend jax result is zero-copy where the
    layout allows (single-device outputs view the result buffer; a
    replicated multi-device output views shard 0), and with the carry
    donated (round 19) XLA recycles execution memory — a retained view
    decodes garbage once the buffer is reused.  The fleet tp*dp replay
    diverged nondeterministically through exactly this hole; this pins
    the _owned_host contract on both pull branches."""
    import numpy as np

    import jax.numpy as jnp
    from jax.sharding import NamedSharding, PartitionSpec as P

    from ksim_tpu.engine.core import _pull_tree_to_host
    from ksim_tpu.engine.sharding import make_mesh

    jax.config.update("jax_enable_x64", False)

    def owned(h):
        return isinstance(h, np.ndarray) and (
            h.flags["OWNDATA"] or isinstance(h.base, np.ndarray)
            and h.base.flags["OWNDATA"]
        )

    # Packed branch: >= 2 single-device array leaves.
    f = jax.jit(lambda t: jax.tree_util.tree_map(lambda x: x * 2, t))
    tree = f({"a": jnp.arange(64, dtype=jnp.float32),
              "b": jnp.ones((8, 8), jnp.int32)})
    out = _pull_tree_to_host(tree)
    for k, h in out.items():
        assert owned(h), f"packed-branch leaf {k} is a device view"
    # Fallback branch: multi-device leaves (replicated is the zero-copy
    # trap; sharded gathers).  Needs the 8 virtual CPU devices conftest
    # forces.
    mesh = make_mesh(4, dp=2)
    repl = jax.device_put(np.arange(16, dtype=np.float32),
                          NamedSharding(mesh, P()))
    shrd = jax.device_put(np.arange(16, dtype=np.float32),
                          NamedSharding(mesh, P("tp")))
    out2 = _pull_tree_to_host({"r": repl, "s": shrd})
    for k, h in out2.items():
        assert owned(h), f"fallback-branch leaf {k} is a device view"


# ---------------------------------------------------------------------------
# Round 17: tp-sharded device replay (KSIM_REPLAY_TP / service shard_mesh)
# ---------------------------------------------------------------------------


def _run_sharded_pair(stream_factory, tp, monkeypatch, *, k=8, **runner_kw):
    """The same stream through the solo device path and the
    KSIM_REPLAY_TP-sharded one (conftest forces 8 virtual CPU devices);
    returns both results and both drivers so callers can pin counts AND
    coverage evidence."""
    jax.config.update("jax_enable_x64", False)
    monkeypatch.delenv("KSIM_REPLAY_TP", raising=False)
    solo_r = ScenarioRunner(device_replay=True, device_segment_steps=k, **runner_kw)
    solo = solo_r.run(stream_factory())
    monkeypatch.setenv("KSIM_REPLAY_TP", str(tp))
    shard_r = ScenarioRunner(device_replay=True, device_segment_steps=k, **runner_kw)
    shard = shard_r.run(stream_factory())
    return solo, solo_r, shard, shard_r


def _lowered_tps(driver):
    return sorted({e["tp"] for e in driver.lower_log})


def test_device_sharded_small_churn_byte_parity(monkeypatch):
    """KSIM_REPLAY_TP=8 lays the node axis over a tp mesh: per-step
    triples, totals and device coverage must all be byte-identical to
    the solo device run, with proof the mesh was honored (every lowered
    segment at tp=8) and zero shard_mesh fallbacks."""
    solo, solo_r, shard, shard_r = _run_sharded_pair(
        lambda: churn_scenario(0, n_nodes=200, n_events=800, ops_per_step=50),
        8, monkeypatch, max_pods_per_pass=1024, pod_bucket_min=128,
    )
    assert _steps_sig(shard) == _steps_sig(solo)
    assert (shard.pods_scheduled, shard.unschedulable_attempts) == (
        solo.pods_scheduled, solo.unschedulable_attempts,
    )
    d = shard_r.replay_driver
    assert d.device_steps == solo_r.replay_driver.device_steps
    assert d.device_steps >= 8
    assert "shard_mesh" not in d.unsupported, d.unsupported
    assert _lowered_tps(d) == [8], d.lower_log
    assert _lowered_tps(solo_r.replay_driver) == [1]


def test_device_sharded_full_record_annotations_byte_parity(monkeypatch):
    """record="full" under the mesh: result tensors stream out of the
    sharded scan per shard, and the host decode must land every pod
    annotation (filter/score/finalscore maps, history, selected-node)
    byte-identical to the solo device run.  The per-shard byte budget is
    the point of round 17 — the lower log must carry it."""

    def annos(runner):
        return {
            p["metadata"]["name"]: p["metadata"].get("annotations", {})
            for p in runner.store.list("pods")
        }

    solo, solo_r, shard, shard_r = _run_sharded_pair(
        lambda: churn_scenario(0, n_nodes=24, n_events=160, ops_per_step=16),
        8, monkeypatch, record="full", max_pods_per_pass=64, pod_bucket_min=32,
    )
    assert _steps_sig(shard) == _steps_sig(solo)
    assert annos(shard_r) == annos(solo_r)
    d = shard_r.replay_driver
    assert d.device_steps == solo_r.replay_driver.device_steps
    assert _lowered_tps(d) == [8]
    for entry in d.lower_log:
        assert entry["full_bytes_per_shard"] > 0


def test_device_sharded_preemption_parity_narrows_tiny_universe(monkeypatch):
    """Preemption through the sharded scan on a universe SMALLER than
    the requested mesh: the width floor (_MIN_SHARD_NODES) narrows
    tp=8 to tp=2 at N=8 instead of trusting the partitioner below it
    (the sel/nom doubling hazard — see _lower), and the narrowed run
    still lands store, eviction order and counts byte-identical."""

    def stream():
        for i in range(3):
            yield Operation(
                step=0, op="create", kind="nodes",
                obj=make_node(f"n-{i}", cpu="4", memory="16Gi"),
            )
        for step in range(1, 17):
            prio = [0, 0, 5, 10][step % 4]
            pod = make_pod(
                f"p-{step}", cpu="1500m", memory="256Mi", priority=prio
            )
            pod["metadata"]["creationTimestamp"] = f"2026-01-{step:02d}T00:00:00Z"
            yield Operation(step=step, op="create", kind="pods", obj=pod)

    def pods_state(runner):
        return sorted(
            (
                p["metadata"]["name"],
                p.get("spec", {}).get("nodeName"),
                p.get("status", {}).get("nominatedNodeName"),
            )
            for p in runner.store.list("pods")
        )

    jax.config.update("jax_enable_x64", False)
    monkeypatch.delenv("KSIM_REPLAY_TP", raising=False)
    solo_r = ScenarioRunner(
        preemption=True, device_replay=True, device_segment_steps=4
    )
    ev_solo = _collect_evictions(solo_r)
    solo = solo_r.run(stream())
    monkeypatch.setenv("KSIM_REPLAY_TP", "8")
    shard_r = ScenarioRunner(
        preemption=True, device_replay=True, device_segment_steps=4
    )
    ev_shard = _collect_evictions(shard_r)
    shard = shard_r.run(stream())
    assert _steps_sig(shard) == _steps_sig(solo)
    assert pods_state(shard_r) == pods_state(solo_r)
    assert ev_shard == ev_solo
    assert ev_solo, "stream never triggered preemption — fixture is vacuous"
    d = shard_r.replay_driver
    assert d.device_steps == solo_r.replay_driver.device_steps
    assert _lowered_tps(d) == [2], d.lower_log  # the floor, not the request


def test_device_sharded_explicit_mesh_contract():
    """An explicit service shard_mesh is a layout contract: a dp=1 tp
    mesh is honored by the device path (every segment lowered at its
    width); any other shape falls back per-pass with the narrowed
    "shard_mesh" reason — and both land the same counts."""
    from ksim_tpu.engine.sharding import make_mesh
    from ksim_tpu.scheduler.service import SchedulerService
    from ksim_tpu.state.cluster import ClusterStore

    jax.config.update("jax_enable_x64", False)

    def run(mesh):
        store = ClusterStore()
        svc = SchedulerService(store, shard_mesh=mesh)
        runner = ScenarioRunner(
            store, svc, device_replay=True, device_segment_steps=8,
            max_pods_per_pass=1024, pod_bucket_min=128,
        )
        res = runner.run(
            churn_scenario(0, n_nodes=48, n_events=200, ops_per_step=20)
        )
        return res, runner.replay_driver

    base, base_d = run(None)
    tp_res, tp_d = run(make_mesh(8, dp=1))
    dp_res, dp_d = run(make_mesh(8, dp=2))
    for res in (tp_res, dp_res):
        assert (res.pods_scheduled, res.unschedulable_attempts) == (
            base.pods_scheduled, base.unschedulable_attempts,
        )
    assert [(s.step, s.scheduled) for s in tp_res.steps] == [
        (s.step, s.scheduled) for s in base.steps
    ]
    assert tp_d.device_steps == base_d.device_steps
    assert _lowered_tps(tp_d) == [8]
    assert "shard_mesh" not in tp_d.unsupported
    # dp=2: rejected up front, every segment per-pass, counts intact.
    assert dp_d.device_steps == 0
    assert dp_d.unsupported.get("shard_mesh", 0) >= 1
    assert not dp_d.lower_log


def test_device_sharded_dead_device_contained(monkeypatch):
    """A mesh wider than the host's devices is a DEVICE error, not a
    lowering bug: the ladder counts device_error, the breaker opens
    after the threshold, and the whole stream still lands the per-pass
    counts (containment, repo invariant since round 4)."""
    jax.config.update("jax_enable_x64", False)
    monkeypatch.delenv("KSIM_REPLAY_TP", raising=False)
    solo = ScenarioRunner(device_replay=True, device_segment_steps=8).run(
        churn_scenario(0, n_nodes=48, n_events=200, ops_per_step=20)
    )
    # N=64 -> gcd(64, 64)=64, width-floor-narrowed to 16 — still wider
    # than the 8 virtual devices, so every dispatch attempt dies in
    # _tp_mesh before touching a buffer.
    monkeypatch.setenv("KSIM_REPLAY_TP", "64")
    shard_r = ScenarioRunner(device_replay=True, device_segment_steps=8)
    shard = shard_r.run(
        churn_scenario(0, n_nodes=48, n_events=200, ops_per_step=20)
    )
    assert _steps_sig(shard) == _steps_sig(solo)
    d = shard_r.replay_driver
    assert d.device_steps == 0
    assert d.unsupported.get("device_error", 0) >= 1, d.unsupported
    assert d.breaker_tripped
