"""NodeName, NodePorts, ImageLocality kernels vs the oracle, plus
SchedulingGates enforcement and the skipped-plugin surface."""

from __future__ import annotations

import numpy as np

from ksim_tpu.engine import Engine
from ksim_tpu.engine.profiles import default_plugins
from ksim_tpu.plugins import oracle
from ksim_tpu.scheduler.profile import compile_profile
from ksim_tpu.scheduler.service import SchedulerService
from ksim_tpu.state.cluster import ClusterStore
from ksim_tpu.state.featurizer import Featurizer
from tests.helpers import make_node, make_pod, random_cluster


def _with_ports(pod, ports):
    pod["spec"]["containers"][0]["ports"] = ports
    return pod


def _with_images(node, images):
    node["status"]["images"] = images
    return node


def _run(nodes, pods, queue):
    feats = Featurizer().featurize(nodes, pods, queue_pods=queue)
    eng = Engine(feats, default_plugins(feats), record="full")
    res, _ = eng.schedule()
    return feats, res


def _plugin_col(res, name, kind="filter"):
    names = res.filter_plugin_names if kind == "filter" else res.plugin_names
    return names.index(name)


def test_node_name_filter():
    nodes = [make_node("a"), make_node("b")]
    q1 = make_pod("wants-b")
    q1["spec"]["nodeName"] = ""  # no request
    q2 = make_pod("explicit")
    feats = Featurizer().featurize(nodes, [], queue_pods=[q1, q2])
    # Simulate a queue pod carrying a node request through aux encoding.
    q3 = make_pod("ghost")
    q3["spec"]["nodeName"] = "missing"
    feats2 = Featurizer().featurize(nodes, [], queue_pods=[q3])
    assert feats.aux["nodename"].pod_req_node[0] == -1
    assert feats2.aux["nodename"].pod_req_node[0] == -2
    eng = Engine(feats2, default_plugins(feats2), record="full")
    res = eng.evaluate_batch()
    fi = _plugin_col(res, "NodeName")
    assert (res.reason_bits[0, fi, :2] != 0).all()  # fails everywhere
    assert int(res.selected[0]) == -1


def test_node_ports_conflict_and_commit():
    nodes = [make_node("a"), make_node("b")]
    bound = _with_ports(
        make_pod("existing", node_name="a"), [{"hostPort": 8080, "protocol": "TCP"}]
    )
    q1 = _with_ports(make_pod("q1"), [{"hostPort": 8080}])  # TCP default
    q2 = _with_ports(make_pod("q2"), [{"hostPort": 8080}])
    feats, res = _run(nodes, [bound], [q1, q2])
    # q1 conflicts on a (existing pod), lands on b; q2 then conflicts on
    # BOTH (scan carry commit) -> unschedulable.
    assert feats.nodes.names[int(res.selected[0])] == "b"
    assert int(res.selected[1]) == -1
    fi = _plugin_col(res, "NodePorts")
    assert int(res.reason_bits[1, fi, 0]) != 0 and int(res.reason_bits[1, fi, 1]) != 0
    # Oracle agreement.
    from ksim_tpu.plugins.nodeports import ERR_REASON

    assert oracle.node_ports_filter(q1, [bound]) == [ERR_REASON]
    assert oracle.node_ports_filter(q1, []) == []


def test_node_ports_wildcard_ip_semantics():
    a = {"hostPort": 80, "protocol": "TCP", "hostIP": "10.0.0.1"}
    b = {"hostPort": 80, "protocol": "TCP", "hostIP": "10.0.0.2"}
    wild = {"hostPort": 80, "protocol": "TCP"}
    udp = {"hostPort": 80, "protocol": "UDP"}
    p_a = _with_ports(make_pod("pa", node_name="n"), [a])
    # Different specific IPs do not conflict; wildcard conflicts with any.
    assert oracle.node_ports_filter(_with_ports(make_pod("x"), [b]), [p_a]) == []
    assert oracle.node_ports_filter(_with_ports(make_pod("y"), [wild]), [p_a]) != []
    assert oracle.node_ports_filter(_with_ports(make_pod("z"), [udp]), [p_a]) == []


def test_image_locality_score_parity():
    mb = 1024 * 1024
    img_big = {"names": ["repo/app:v1"], "sizeBytes": 500 * mb}
    img_small = {"names": ["repo/side"], "sizeBytes": 100 * mb}  # :latest normalized
    nodes = [
        _with_images(make_node("a"), [img_big, img_small]),
        _with_images(make_node("b"), [img_big]),
        make_node("c"),
    ]
    q = make_pod("p")
    q["spec"]["containers"] = [
        {"name": "c1", "image": "repo/app:v1", "resources": {}},
        {"name": "c2", "image": "repo/side:latest", "resources": {}},
    ]
    feats, res = _run(nodes, [], [q])
    si = _plugin_col(res, "ImageLocality", kind="score")
    states = oracle.build_image_states(nodes)
    for ni, node in enumerate(nodes):
        want = oracle.image_locality_score(q, node, states, total_nodes=3)
        assert int(res.scores[0, si, ni]) == want, node["metadata"]["name"]
    # Node a has both images -> strictly best score.
    assert int(res.scores[0, si, 0]) > int(res.scores[0, si, 1]) > 0
    assert int(res.scores[0, si, 2]) == 0


def test_scheduling_gates_enforced():
    store = ClusterStore()
    store.create("nodes", make_node("n0"))
    gated = make_pod("gated")
    gated["spec"]["schedulingGates"] = [{"name": "example.com/gate"}]
    store.create("pods", gated)
    svc = SchedulerService(store)
    assert svc.schedule_pending() == {}  # gated pod never enters the queue
    assert store.get("pods", "gated")["spec"].get("nodeName") is None
    # Removing the gates makes it schedulable.
    store.patch("pods", "gated", "default", lambda o: o["spec"].pop("schedulingGates"))
    assert svc.schedule_pending() == {"default/gated": "n0"}


def test_no_default_plugins_skipped():
    # Every upstream default-profile plugin has a kernel now; truly
    # unknown plugins still raise (profile.py compile_profile).
    prof = compile_profile({})
    assert prof.skipped == ()


def test_new_plugins_neutral_on_plain_clusters():
    # Pods without ports/images/node requests: new plugins must not
    # change selections vs the six-plugin oracle in test_engine_schedule.
    nodes, pods = random_cluster(5, n_nodes=8, n_pods=30, bound_fraction=0.2)
    queue = [p for p in pods if not p["spec"].get("nodeName")]
    feats, res = _run(nodes, pods, queue)
    si = _plugin_col(res, "ImageLocality", kind="score")
    assert (res.scores[: len(queue), si, :8] == 0).all()
    fi = _plugin_col(res, "NodePorts")
    assert (res.reason_bits[: len(queue), fi, :8] == 0).all()
