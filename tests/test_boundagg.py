"""Incremental bound-pod aggregation (state/boundagg.py): a persistent
Featurizer replaying cluster mutations must be engine-equivalent to a
fresh featurization of the same snapshot.

The persistent path orders nodes by stable slot (first-seen, swap-remove)
while a fresh featurizer uses the caller's order, so outputs are compared
per NODE NAME.  ``selected`` is excluded: selection breaks score ties by
node index, which legitimately differs between orderings."""

from __future__ import annotations

import copy
import random

import numpy as np
import pytest

from ksim_tpu.engine import Engine
from ksim_tpu.engine.profiles import default_plugins
from ksim_tpu.state.boundagg import NodeSlots
from ksim_tpu.state.featurizer import Featurizer
from tests.helpers import make_node, make_pod


def test_node_slots_swap_remove():
    slots = NodeSlots()
    a, b, c = make_node("a"), make_node("b"), make_node("c")
    ordered, changed = slots.sync([a, b, c])
    assert [n["metadata"]["name"] for n in ordered] == ["a", "b", "c"]
    assert changed == {0, 1, 2}
    # Deleting "a" moves "c" (last) into slot 0.
    ordered, changed = slots.sync([b, c])
    assert [n["metadata"]["name"] for n in ordered] == ["c", "b"]
    assert 0 in changed and 2 in changed  # slot 0 re-occupied, slot 2 gone
    # Same set, same objects: nothing changes.
    ordered, changed = slots.sync([c, b])
    assert [n["metadata"]["name"] for n in ordered] == ["c", "b"]
    assert changed == set()
    # Replacing an object (same name) flags its slot.
    b2 = copy.deepcopy(b)
    ordered, changed = slots.sync([b2, c])
    assert changed == {1}


def _rand_pod(rng: random.Random, seq: int) -> dict:
    pod = make_pod(
        f"p{seq}",
        cpu=f"{rng.choice([100, 250, 500])}m",
        memory=f"{rng.choice([128, 256])}Mi",
    )
    labels = {"app": rng.choice(["web", "db", "cache"])}
    pod["metadata"]["labels"] = labels
    spec = pod["spec"]
    if rng.random() < 0.5:
        spec["topologySpreadConstraints"] = [{
            "maxSkew": 1,
            "topologyKey": "zone",
            "whenUnsatisfiable": rng.choice(["DoNotSchedule", "ScheduleAnyway"]),
            "labelSelector": {"matchLabels": {"app": labels["app"]}},
        }]
    if rng.random() < 0.5:
        term = {
            "topologyKey": rng.choice(["zone", "kubernetes.io/hostname"]),
            "labelSelector": {"matchLabels": {"app": rng.choice(["web", "db"])}},
        }
        aff = spec.setdefault("affinity", {})
        if rng.random() < 0.5:
            aff["podAntiAffinity"] = {
                "preferredDuringSchedulingIgnoredDuringExecution": [
                    {"weight": rng.randint(1, 50), "podAffinityTerm": term}
                ]
            }
        else:
            aff["podAffinity"] = {
                "requiredDuringSchedulingIgnoredDuringExecution": [term]
            }
    return pod


def _rand_node(rng: random.Random, seq: int) -> dict:
    node = make_node(f"n{seq}", cpu="4", memory="8Gi")
    node["metadata"]["labels"] = {
        "zone": rng.choice(["az-1", "az-2", "az-3"]),
        "kubernetes.io/hostname": f"n{seq}",
    }
    return node


def _engine_view(feats):
    eng = Engine(feats, default_plugins(feats), record="full")
    res = eng.evaluate_batch()
    return eng, res


def test_persistent_featurizer_matches_fresh_replay():
    rng = random.Random(7)
    persistent = Featurizer()
    nodes = [_rand_node(rng, i) for i in range(6)]
    pods: list[dict] = []
    node_seq, pod_seq = 6, 0

    for step in range(14):
        # Mutate like the store would: objects are replaced, not edited.
        for _ in range(rng.randint(1, 6)):
            r = rng.random()
            if r < 0.45 or not pods:
                pods.append(_rand_pod(rng, pod_seq))
                pod_seq += 1
            elif r < 0.65 and any(p["spec"].get("nodeName") for p in pods):
                bound = [i for i, p in enumerate(pods) if p["spec"].get("nodeName")]
                pods.pop(rng.choice(bound))
            elif r < 0.8:
                # Bind a pending pod (new object, like the store's patch).
                pending = [i for i, p in enumerate(pods) if not p["spec"].get("nodeName")]
                if pending:
                    i = rng.choice(pending)
                    p = copy.deepcopy(pods[i])
                    p["spec"]["nodeName"] = rng.choice(nodes)["metadata"]["name"]
                    pods[i] = p
            elif r < 0.93 and len(nodes) > 3:
                # Drain/replace a node; its pods go pending (new objects).
                gone = nodes.pop(rng.randrange(len(nodes)))
                gname = gone["metadata"]["name"]
                for i, p in enumerate(pods):
                    if p["spec"].get("nodeName") == gname:
                        p2 = copy.deepcopy(p)
                        p2["spec"].pop("nodeName", None)
                        pods[i] = p2
                nodes.append(_rand_node(rng, node_seq))
                node_seq += 1
            else:
                # Relabel a node in place on the axis (new object).
                i = rng.randrange(len(nodes))
                n2 = copy.deepcopy(nodes[i])
                n2["metadata"]["labels"]["zone"] = rng.choice(["az-1", "az-2", "az-3"])
                nodes[i] = n2

        queue = [p for p in pods if not p["spec"].get("nodeName")]
        if not queue:
            continue
        feats_p = persistent.featurize(list(nodes), list(pods), queue_pods=queue)
        feats_f = Featurizer().featurize(list(nodes), list(pods), queue_pods=queue)

        # Node-name alignment: permutation from fresh order to persistent.
        names_p = feats_p.nodes.names
        names_f = feats_f.nodes.names
        assert sorted(names_p) == sorted(names_f)
        perm = [names_p.index(nm) for nm in names_f]

        np.testing.assert_array_equal(
            feats_p.nodes.requested[perm], feats_f.nodes.requested[: len(perm)],
            err_msg=f"step {step}: requested diverged",
        )
        np.testing.assert_array_equal(
            feats_p.nodes.pod_count[perm], feats_f.nodes.pod_count[: len(perm)]
        )

        _, res_p = _engine_view(feats_p)
        _, res_f = _engine_view(feats_f)
        P = len(queue)
        np.testing.assert_array_equal(
            res_p.feasible[:P], res_f.feasible[:P],
            err_msg=f"step {step}: feasibility diverged",
        )
        np.testing.assert_array_equal(
            (res_p.reason_bits[:P][:, :, perm] != 0),
            (res_f.reason_bits[:P][:, :, : len(perm)] != 0),
            err_msg=f"step {step}: filter masks diverged",
        )
        np.testing.assert_array_equal(
            res_p.total[:P][:, perm], res_f.total[:P][:, : len(perm)],
            err_msg=f"step {step}: total scores diverged",
        )
