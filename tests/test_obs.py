"""Trace plane (ksim_tpu/obs.py): spans, histograms, ring, export,
and the registry-sync guards that keep the fault-site / fallback-reason
taxonomies and the trace event names from drifting apart.

The plane is process-global in production; these tests construct
private ``TracePlane`` instances wherever possible and restore the
global one when they must touch it."""

from __future__ import annotations

import functools
import json
import threading

import pytest

from ksim_tpu import obs
from ksim_tpu.obs import (
    EVENT_NAMES,
    SPAN_NAMES,
    LatencyHistogram,
    TracePlane,
)


@pytest.fixture
def plane() -> TracePlane:
    p = TracePlane()
    p.enable(ring=True)
    return p


# ---------------------------------------------------------------------------
# Spans
# ---------------------------------------------------------------------------


def test_span_nesting_depth_and_order(plane):
    with plane.span("runner.step", step=1):
        with plane.span("service.schedule", pass_num=1):
            pass
        with plane.span("service.schedule", pass_num=2):
            pass
    recs = plane.ring_records()
    # Spans record at EXIT: inner spans land before their parent.
    assert [r["name"] for r in recs] == [
        "service.schedule",
        "service.schedule",
        "runner.step",
    ]
    assert [r["depth"] for r in recs] == [1, 1, 0]
    outer = recs[2]
    for inner in recs[:2]:
        # Interval containment (what makes Chrome/Perfetto nest them).
        assert outer["t"] <= inner["t"]
        assert inner["t"] + inner["d"] <= outer["t"] + outer["d"]
    assert outer["args"] == {"step": 1}


def test_span_records_error_and_propagates(plane):
    with pytest.raises(ValueError):
        with plane.span("replay.lower", segment=1):
            raise ValueError("boom")
    (rec,) = plane.ring_records()
    assert rec["args"]["error"] == "ValueError"
    # Histogram observed the failed span too (time was still spent).
    assert plane.phase_totals()["replay.lower"][1] == 1


def test_span_histograms_accumulate(plane):
    for _ in range(5):
        with plane.span("kubeapi.request"):
            pass
    total, count = plane.phase_totals()["kubeapi.request"]
    assert count == 5
    assert total > 0.0
    snap = plane.snapshot()["histograms"]["kubeapi.request"]
    assert snap["count"] == 5
    assert sum(c for _, c in snap["buckets"]) == 5


# ---------------------------------------------------------------------------
# Histogram buckets
# ---------------------------------------------------------------------------


def test_histogram_edges_are_fixed_log_spaced():
    edges = LatencyHistogram.EDGES
    assert len(edges) == 33
    assert edges[0] == pytest.approx(1e-6)
    assert edges[-1] == pytest.approx(100.0)
    # 4 per decade: every 4th edge is a decade step.
    assert edges[4] == pytest.approx(1e-5)
    assert edges[32] == pytest.approx(1e-6 * 10**8)


def test_histogram_bucket_edge_assignment():
    h = LatencyHistogram()
    # An observation exactly ON an edge belongs to the bucket it is the
    # upper edge of (le semantics).
    h.observe(1e-6)
    assert h.counts[0] == 1
    # Just above the first edge -> second bucket.
    h.observe(1.0000001e-6)
    assert h.counts[1] == 1
    # Overflow bucket catches everything past 100 s.
    h.observe(1e9)
    assert h.counts[-1] == 1
    # Sub-first-edge lands in the first bucket.
    h.observe(1e-9)
    assert h.counts[0] == 2
    assert h.count == 4
    snap = h.snapshot()
    assert snap["count"] == 4
    # The overflow bucket serializes with a null upper edge.
    assert [edge for edge, _ in snap["buckets"]][-1] is None


def test_histogram_quantiles_clamped_to_observed_max():
    h = LatencyHistogram()
    h.observe(0.01)
    h.observe(5.0)
    # The 5.0 bucket's upper edge is ~5.62; estimates must not exceed
    # anything actually observed.
    assert h.quantile(0.99) == pytest.approx(5.0)
    assert h.quantile(0.5) == pytest.approx(0.01)
    assert h.snapshot()["max_seconds"] == pytest.approx(5.0)


# ---------------------------------------------------------------------------
# Ring
# ---------------------------------------------------------------------------


def test_ring_eviction_under_concurrent_writers():
    p = TracePlane()
    p.configure_from_env({"KSIM_TRACE_RING": "64", "KSIM_TRACE": "1"})
    n_threads, per_thread = 8, 200

    def hammer(i: int) -> None:
        for j in range(per_thread):
            p.event("replay.fallback", reason=f"t{i}", n=j)
            with p.span("runner.step", thread=i):
                pass

    threads = [threading.Thread(target=hammer, args=(i,)) for i in range(n_threads)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    snap = p.snapshot()
    assert snap["ring"]["capacity"] == 64
    assert snap["ring"]["size"] == 64
    appended = n_threads * per_thread * 2
    assert snap["ring"]["appended"] == appended
    assert snap["ring"]["evicted"] == appended - 64
    # Nothing was lost from the aggregate layers despite eviction.
    assert snap["events"]["replay.fallback"] == n_threads * per_thread
    assert snap["histograms"]["runner.step"]["count"] == n_threads * per_thread
    # Every surviving record is well-formed.
    for r in p.ring_records():
        assert r["ph"] in ("X", "i")
        assert isinstance(r["t"], int) and isinstance(r["args"], dict)


# ---------------------------------------------------------------------------
# Disabled path
# ---------------------------------------------------------------------------


def test_disabled_plane_is_noop():
    p = TracePlane()
    assert not p.active
    s1 = p.span("runner.step", step=1)
    s2 = p.span("service.schedule")
    # The disabled path hands out ONE shared no-op object — no
    # allocation, no clock read.
    assert s1 is s2 is obs._NOOP
    with s1:
        pass
    p.event("fault.fired", site="replay.dispatch")
    assert p.ring_records() == []
    assert p.phase_totals() == {}
    assert p.snapshot()["events"] == {}


def test_disable_reenable_cycle(plane):
    with plane.span("runner.step"):
        pass
    plane.disable()
    with plane.span("runner.step"):
        pass
    assert plane.phase_totals()["runner.step"][1] == 1
    plane.enable(ring=True)
    with plane.span("runner.step"):
        pass
    assert plane.phase_totals()["runner.step"][1] == 2


def test_ensure_timing_keeps_ring_off():
    p = TracePlane()
    p.ensure_timing()
    assert p.active
    with p.span("runner.step"):
        pass
    assert p.phase_totals()["runner.step"][1] == 1
    assert p.ring_records() == []  # timing-only: histograms, no ring


def test_ensure_timing_respects_explicit_disable():
    """Convenience activation (ScenarioRunner.run) must never override
    an operator's stated opt-out — disable()/KSIM_TRACE=off is sticky
    against it; only an explicit enable() turns the plane back on."""
    p = TracePlane()
    p.disable()
    p.ensure_timing()
    assert not p.active
    p2 = TracePlane()
    p2.configure_from_env({"KSIM_TRACE": "off"})
    p2.ensure_timing()
    assert not p2.active
    p2.enable(ring=False)
    assert p2.active


# ---------------------------------------------------------------------------
# Export
# ---------------------------------------------------------------------------


def test_chrome_export_roundtrip(plane, tmp_path):
    with plane.span("replay.lower", segment=1, steps=16):
        with plane.span("replay.dispatch", segment=1, steps=16):
            pass
    plane.event("store.txn_commit", writes=3, events=3)
    out = tmp_path / "trace.json"
    doc = plane.export_chrome(str(out))
    on_disk = json.loads(out.read_text())
    assert on_disk == doc
    evs = doc["traceEvents"]
    phases = {e["ph"] for e in evs}
    assert phases == {"M", "X", "i"}
    xs = [e for e in evs if e["ph"] == "X"]
    assert {e["name"] for e in xs} == {"replay.lower", "replay.dispatch"}
    for e in xs:
        assert e["ts"] >= 0 and e["dur"] >= 0
        assert e["cat"] == "replay"
    (instant,) = [e for e in evs if e["ph"] == "i"]
    assert instant["s"] == "t" and instant["args"]["writes"] == 3
    # Thread metadata names the recording thread.
    (meta,) = [e for e in evs if e["ph"] == "M"]
    assert meta["name"] == "thread_name"


def test_env_configuration(tmp_path):
    p = TracePlane()
    out = tmp_path / "t.json"
    p.configure_from_env({"KSIM_TRACE_OUT": str(out)})
    assert p.active and p.out_path == str(out)
    p2 = TracePlane()
    p2.configure_from_env({"KSIM_TRACE": "timing"})
    assert p2.active
    with p2.span("runner.step"):
        pass
    assert p2.ring_records() == []
    p3 = TracePlane()
    p3.configure_from_env({"KSIM_TRACE": "off"})
    assert not p3.active
    # The operator's opt-out beats a wrapper-exported KSIM_TRACE_OUT.
    p4 = TracePlane()
    p4.configure_from_env({"KSIM_TRACE": "off", "KSIM_TRACE_OUT": "/tmp/x.json"})
    assert not p4.active and p4.out_path is None


# ---------------------------------------------------------------------------
# Registry sync: fault sites <-> spans, fallback reasons <-> events
# ---------------------------------------------------------------------------


def _repo_root():
    import os

    return os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@functools.lru_cache(maxsize=1)
def _lint_project():
    """The analyzer's view of the tree (tools/ksimlint, docs/lint.md).
    These tests are RE-BACKED by the analyzer's call-site scans — the
    same AST pass `make lint` runs — so the in-suite registry checks
    can never drift from what the lint rule actually sees (the old
    inline grep/ast logic lived here and could).  Cached: the tree is
    immutable while tests run, and three tests share the parse."""
    from tools.ksimlint.core import Project

    return Project.load(_repo_root())


def test_fault_sites_match_source_and_span_taxonomy():
    """Every FAULTS.check("...") literal in the codebase is a declared
    site, every declared site is wired somewhere, and every site has a
    same-named span enclosing it on the timeline — the taxonomies
    cannot drift apart silently.  Also pins the analyzer's AST-read
    registries to the imported runtime values: the lint rule checks
    call sites against what it PARSES, this asserts what it parses is
    what the process actually runs."""
    from ksim_tpu.faults import SITES
    from tools.ksimlint.rules import registry_literals as rl

    project = _lint_project()
    regs = rl.load_registries(project)
    assert regs.sites == SITES
    assert regs.span_names == SPAN_NAMES
    assert regs.event_names == EVENT_NAMES

    scan = rl.scan_fault_sites(project)
    assert not scan.dynamic, f"non-literal FAULTS.check sites: {scan.dynamic}"
    assert set(scan.literals) == set(SITES)
    assert set(SITES) <= set(SPAN_NAMES)
    assert "fault.fired" in EVENT_NAMES


def test_trace_literals_match_taxonomy():
    """Every TRACE.span / TRACE.event name spelled at a call site is in
    the registry (the analyzer's scan, asserted in-suite)."""
    from tools.ksimlint.rules import registry_literals as rl

    spans, events = rl.scan_trace_literals(_lint_project())
    assert not spans.dynamic and not events.dynamic
    assert set(spans.literals) <= set(SPAN_NAMES), (
        set(spans.literals) - set(SPAN_NAMES)
    )
    assert set(events.literals) <= set(EVENT_NAMES), (
        set(events.literals) - set(EVENT_NAMES)
    )


def test_metric_names_match_exposition_literals():
    """The Prometheus exposition surface is machine-checked the same
    way SITES/SPAN_NAMES are: the analyzer's AST view of METRIC_NAMES
    equals the imported runtime tuple, and every `_expo_family("...")`
    declaration resolves into the registry with no dead entries."""
    from tools.ksimlint.rules import registry_literals as rl

    project = _lint_project()
    regs = rl.load_registries(project)
    assert regs.metric_names == obs.METRIC_NAMES

    scan = rl.scan_metric_literals(project)
    assert not scan.dynamic, f"non-literal exposition families: {scan.dynamic}"
    assert set(scan.literals) == set(obs.METRIC_NAMES)
    # The runtime family table renders exactly the registry, in order.
    assert tuple(f["name"] for f in obs._EXPO_FAMILIES) == obs.METRIC_NAMES


def test_fallback_reasons_match_replay_source():
    """Every statically spelled fallback reason in engine/replay.py is
    registered in FALLBACK_REASONS (so it reaches the trace taxonomy),
    and the registry carries no dead entries — via the analyzer's scan
    (it replaced the inline ast walk this test used to carry)."""
    from ksim_tpu.engine.replay import (
        FALLBACK_REASON_PREFIXES,
        FALLBACK_REASONS,
    )
    from tools.ksimlint.rules import registry_literals as rl

    project = _lint_project()
    regs = rl.load_registries(project)
    assert regs.fallback_reasons == FALLBACK_REASONS
    assert regs.fallback_prefixes == FALLBACK_REASON_PREFIXES

    fb = rl.scan_fallback_reasons(project)
    unregistered = set(fb.call_reasons) - FALLBACK_REASONS
    assert not unregistered, (
        f"fallback reasons missing from FALLBACK_REASONS: {sorted(unregistered)}"
    )
    # The post-dispatch validation discards return their reason as a
    # string (featurize_prediction / preemption_overflow): registry
    # entries must exist SOMEWHERE in the source.
    dead = FALLBACK_REASONS - set(fb.call_reasons) - fb.return_strings
    assert not dead, f"FALLBACK_REASONS entries not found in source: {sorted(dead)}"
    for prefix in fb.fstring_prefixes:
        assert any(prefix.startswith(p) for p in FALLBACK_REASON_PREFIXES), (
            f"dynamic fallback reason family {prefix!r} not in "
            f"FALLBACK_REASON_PREFIXES"
        )
    assert "replay.fallback" in EVENT_NAMES


def test_fault_fire_emits_trace_event():
    """The fault plane lands fault.fired on the global plane; exercised
    through a private enable/restore cycle of the global TRACE."""
    from ksim_tpu.faults import FaultPlane, InjectedFault
    from ksim_tpu.obs import TRACE

    prev_state = (TRACE._active, TRACE._ring_on, TRACE._user_disabled)
    TRACE.enable(ring=True)
    try:
        before = TRACE.snapshot()["events"].get("fault.fired", 0)
        plane = FaultPlane()
        plane.arm("replay.dispatch", "call:1")
        with pytest.raises(InjectedFault):
            plane.check("replay.dispatch")
        events = [
            r for r in TRACE.ring_records() if r["name"] == "fault.fired"
        ]
        assert events and events[-1]["args"]["site"] == "replay.dispatch"
        assert TRACE.snapshot()["events"]["fault.fired"] == before + 1
    finally:
        # Exact flag restore (not disable(): its sticky opt-out would
        # leak into later tests' ensure_timing).
        TRACE._active, TRACE._ring_on, TRACE._user_disabled = prev_state


def test_provider_registry_rejects_reserved_names():
    for name in obs.RESERVED_PROVIDER_NAMES:
        with pytest.raises(ValueError):
            obs.register_provider(name, dict)


def test_provider_registry():
    obs.register_provider("_test_ok", lambda: {"x": 1})
    obs.register_provider("_test_boom", lambda: 1 / 0)
    try:
        snaps = obs.provider_snapshots()
        assert snaps["_test_ok"] == {"x": 1}
        assert "ZeroDivisionError" in snaps["_test_boom"]["error"]
    finally:
        with obs._providers_lock:
            obs._providers.pop("_test_ok", None)
            obs._providers.pop("_test_boom", None)
