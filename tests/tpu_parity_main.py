"""TPU-backend parity check (run as a subprocess by test_tpu_parity.py).

Runs the kernels on the REAL TPU with x64 enabled (XLA emulates s64/f64
on TPU) and asserts bit-for-bit agreement with the float64/int64 oracle —
the SURVEY section-4 "CPU-vs-TPU numerical-equality" tier.  Exit codes:
0 parity holds, 42 no TPU available, 1 mismatch.
"""

from __future__ import annotations

import sys
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))


def main() -> int:
    import jax

    jax.config.update("jax_enable_x64", True)
    try:
        platform = jax.devices()[0].platform
    except Exception as e:  # no backend at all
        print(f"no TPU backend: {e}", file=sys.stderr)
        return 42
    if platform != "tpu":
        print(f"default platform is {platform!r}, not tpu", file=sys.stderr)
        return 42

    import numpy as np

    from ksim_tpu.engine import Engine
    from ksim_tpu.engine.profiles import default_plugins
    from ksim_tpu.plugins import oracle
    from ksim_tpu.state.featurizer import Featurizer
    from tests.helpers import random_cluster
    from tests.test_engine_schedule import greedy_oracle

    failures = 0
    for seed in (0, 1, 2):
        nodes, pods = random_cluster(seed, n_nodes=11, n_pods=47, bound_fraction=0.25)
        queue = [p for p in pods if not p["spec"].get("nodeName")]
        feats = Featurizer().featurize(nodes, pods, queue_pods=queue)
        eng = Engine(feats, default_plugins(feats), record="full")

        # Sequential selections must match the pure-Python greedy oracle
        # (exercises every filter, score, normalize, and carry commit).
        res, _ = eng.schedule()
        want = greedy_oracle(nodes, pods, queue)
        got = [int(x) for x in res.selected[: len(queue)]]
        if got != want:
            print(f"seed {seed}: selections differ\n got {got}\nwant {want}")
            failures += 1

        # Batch raw scores vs the oracle, per plugin per node.
        bres = eng.evaluate_batch()
        infos = oracle.build_node_infos(nodes, pods)
        checks = {
            "NodeResourcesFit": oracle.least_allocated_score,
            "NodeResourcesBalancedAllocation": oracle.balanced_allocation_score,
            "TaintToleration": oracle.taint_toleration_score,
            "NodeAffinity": oracle.node_affinity_score,
        }
        for name, fn in checks.items():
            si = bres.plugin_names.index(name)
            for pi, pod in enumerate(queue):
                for ni, info in enumerate(infos):
                    w = fn(pod, info)
                    g = int(bres.scores[pi, si, ni])
                    if g != w:
                        print(
                            f"seed {seed}: {name} score mismatch pod {pi} "
                            f"node {ni}: got {g} want {w}"
                        )
                        failures += 1
        print(f"seed {seed}: ok ({len(queue)} pods x {len(nodes)} nodes)")
    if failures:
        print(f"{failures} mismatches", file=sys.stderr)
        return 1
    print("tpu parity: all checks passed (platform=tpu, x64 on)")
    return 0


if __name__ == "__main__":
    sys.exit(main())
